"""Wire format + transports for the fault-tolerant job gateway.

This module owns everything a transport needs and nothing the
scheduler does: the typed error taxonomy (one structured code per
rejection class, mapped onto the PR 13 admission/breaker exceptions),
bounded JSON body parsing (clients lie about payload sizes), dedupe
payload digests, deadline and cursor token parsing, and the
:class:`Transport` protocol with the stdlib :class:`HttpTransport`
implementation (``http.server`` threading core, so the whole stack
runs in CI with no dependencies).

The handler logic itself lives in :mod:`.gateway` —
:class:`~.gateway.Gateway` is transport-agnostic: it consumes
:class:`WireRequest` and produces :class:`WireResponse`, and any
transport that can build the former and write the latter (HTTP here; a
unix socket or gRPC shim elsewhere) gets every robustness contract —
idempotent submission, deadline propagation, resumable cursors,
graceful drain — for free.

Error-code taxonomy (``docs/SERVING.md`` carries the full table):

=================== ====== ==============================================
code                status meaning / mapped exception
=================== ====== ==============================================
``BAD_REQUEST``     400    malformed JSON, unknown route, bad field
``DEADLINE_INVALID`` 400   unparseable / non-positive deadline
``CURSOR_INVALID``  400    cursor token not a row index in ``[0, niter]``
``NOT_FOUND``       404    unknown job id
``DEDUPE_MISMATCH`` 409    dedupe key replayed with a DIFFERENT payload
``STREAM_CROSSING`` 409    reattach credentials do not match the journal
``PAYLOAD_TOO_LARGE`` 413  body over the gateway's upload bound
``BUCKET_OVERFLOW`` 422    dataset no bucket covers (typed, with nearest)
``QUEUE_FULL``      429    admission backpressure (``AdmissionController``)
``CIRCUIT_OPEN``    429    the tenant's circuit breaker is open
``INTERNAL``        500    anything unclassified (the body still carries
                           the exception repr for the operator)
``DRAINING``        503    gateway is draining/stopped: no new work
``STREAM_SHED``     503    this stream fell too far behind and was shed
=================== ====== ==============================================
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Iterator, Protocol

#: default upload bound (bytes) — submissions are small par/tim-shaped
#: specs, not sample data; anything bigger is hostile or misrouted
MAX_BODY_BYTES = 1 << 20

#: code -> HTTP status (the taxonomy table in the module docstring)
ERROR_STATUS = {
    "BAD_REQUEST": 400,
    "DEADLINE_INVALID": 400,
    "CURSOR_INVALID": 400,
    "NOT_FOUND": 404,
    "DEDUPE_MISMATCH": 409,
    "STREAM_CROSSING": 409,
    "SUPERSEDED": 409,
    "LINEAGE_UNRESOLVED": 409,
    "PAYLOAD_TOO_LARGE": 413,
    "BUCKET_OVERFLOW": 422,
    "QUEUE_FULL": 429,
    "CIRCUIT_OPEN": 429,
    "INTERNAL": 500,
    "DRAINING": 503,
    "STREAM_SHED": 503,
}

#: job ids / dedupe keys / tenant names arriving over the network are
#: used as filesystem path components and Prometheus label values —
#: constrain them at the wire instead of trusting every layer below
NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

#: deadline header (milliseconds, relative to receipt); the JSON body
#: field ``deadline_ms`` is equivalent and wins when both are present
DEADLINE_HEADER = "x-ptgibbs-deadline-ms"
#: reattach credential header: the submission's dedupe key (stream
#: requests present it so a restarted gateway can refuse crossings)
DEDUPE_HEADER = "x-ptgibbs-dedupe-key"


class WireError(Exception):
    """A typed, wire-mappable rejection.  ``code`` is one of
    :data:`ERROR_STATUS`; ``retry_after_s`` (optional) surfaces breaker
    cooldowns / backpressure hints to well-behaved clients."""

    def __init__(self, code, message, retry_after_s=None):
        if code not in ERROR_STATUS:
            raise ValueError(f"unknown wire error code {code!r}")
        super().__init__(message)
        self.code = code
        self.status = ERROR_STATUS[code]
        self.retry_after_s = retry_after_s

    def body(self) -> dict:
        out = {"error": self.code, "message": str(self)}
        if self.retry_after_s is not None:
            out["retry_after_s"] = round(float(self.retry_after_s), 3)
        return out


def classify_exception(exc) -> WireError:
    """Map a service-layer exception onto the wire taxonomy.

    The PR 13 admission/breaker machinery raises one exception type
    (``CircuitOpen``) for two distinct client remedies — resubmit after
    the queue drains vs. wait out THIS tenant's cooldown — so the code
    split here keys on the attached breaker, which backpressure
    rejections do not carry."""
    from ..runtime.lineage import LineageError
    from ..runtime.supervisor import CircuitOpen
    from .buckets import BucketOverflow

    if isinstance(exc, WireError):
        return exc
    if isinstance(exc, BucketOverflow):
        return WireError("BUCKET_OVERFLOW", str(exc))
    if isinstance(exc, LineageError):
        # an append whose parent has NO verified generation: the client
        # cannot fix it by retrying the same request — the parent needs
        # rows on disk (or an operator) first
        return WireError("LINEAGE_UNRESOLVED", str(exc))
    if isinstance(exc, CircuitOpen):
        if getattr(exc, "breaker", None) is None:
            return WireError("QUEUE_FULL", str(exc))
        br = exc.breaker
        wait = None
        if getattr(br, "opened_at", None) is not None:
            wait = max(0.0, br.cooldown_s - (br.clock() - br.opened_at))
        return WireError("CIRCUIT_OPEN", str(exc), retry_after_s=wait)
    return WireError("INTERNAL", repr(exc))


# -- bounded body / payload helpers ---------------------------------------

def parse_body(raw: bytes, limit: int = MAX_BODY_BYTES) -> dict:
    """Bounded JSON object parse.  ``raw`` longer than ``limit`` is a
    typed ``PAYLOAD_TOO_LARGE`` (the transport already refused to READ
    past ``limit + 1`` — this re-check makes the bound transport-
    independent); anything that is not a JSON object is a
    ``BAD_REQUEST``."""
    if len(raw) > limit:
        raise WireError(
            "PAYLOAD_TOO_LARGE",
            f"request body {len(raw)} B exceeds the gateway's "
            f"{limit} B upload bound")
    try:
        body = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise WireError("BAD_REQUEST",
                        f"request body is not valid JSON: {exc}") from None
    if not isinstance(body, dict):
        raise WireError("BAD_REQUEST", "request body must be a JSON object")
    return body


def payload_digest(payload: dict) -> str:
    """Canonical sha256 of a submission payload — the identity a dedupe
    key is bound to.  Two submissions with one dedupe key and different
    digests are a client bug (or an attack) and refuse with
    ``DEDUPE_MISMATCH``; equal digests are the same upload retried."""
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def require_name(value, field) -> str:
    """Validate a network-supplied identifier (dedupe key, job id,
    tenant name) against :data:`NAME_RE` — these become path components
    and metric label values downstream."""
    if not isinstance(value, str) or not NAME_RE.match(value):
        raise WireError(
            "BAD_REQUEST",
            f"{field} must match {NAME_RE.pattern} (got {value!r})")
    return value


def parse_deadline_ms(headers: dict, body: dict | None = None):
    """Relative deadline in seconds (float) or None when unset.  The
    body field ``deadline_ms`` wins over the header; non-numeric or
    non-positive values are a typed ``DEADLINE_INVALID``."""
    raw = None
    if body is not None and "deadline_ms" in body:
        raw = body["deadline_ms"]
    elif headers:
        raw = {k.lower(): v for k, v in headers.items()}.get(
            DEADLINE_HEADER)
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        raise WireError("DEADLINE_INVALID",
                        f"deadline {raw!r} is not a number (ms)") from None
    if not ms > 0:
        raise WireError("DEADLINE_INVALID",
                        f"deadline must be positive, got {ms} ms")
    return ms / 1e3


def parse_cursor(raw, niter=None) -> int:
    """Cursor token -> recorded-row index.  Cursors are MONOTONIC row
    counts into the job's recorded chain, so a reattaching client
    resumes exactly where it left off (the rows below the cursor were
    already delivered and acknowledged by advancing it)."""
    try:
        cur = int(raw)
    except (TypeError, ValueError):
        raise WireError("CURSOR_INVALID",
                        f"cursor {raw!r} is not a row index") from None
    if cur < 0 or (niter is not None and cur > int(niter)):
        raise WireError(
            "CURSOR_INVALID",
            f"cursor {cur} outside [0, {niter if niter is not None else '∞'}]")
    return cur


# -- transport-agnostic request/response ----------------------------------

@dataclasses.dataclass
class WireRequest:
    """One request as the gateway core sees it, transport stripped."""

    method: str
    path: str
    query: dict
    headers: dict
    body: bytes = b""


@dataclasses.dataclass
class WireResponse:
    """Either a one-shot JSON body or a stream of NDJSON lines.

    ``stream`` (an iterator of ``bytes`` lines, each a complete JSON
    document ending in ``\\n``) wins over ``body`` when set; transports
    write it incrementally (chunked transfer on HTTP) and must tolerate
    the client vanishing mid-iteration — the iterator owns its own
    cleanup via ``close()``."""

    status: int = 200
    body: dict | None = None
    stream: Iterator[bytes] | None = None
    headers: dict = dataclasses.field(default_factory=dict)
    #: pre-encoded non-JSON payload (Prometheus exposition text);
    #: wins over ``body``, loses to ``stream``
    raw: bytes | None = None

    @classmethod
    def error(cls, err: WireError) -> "WireResponse":
        hdr = {}
        if err.retry_after_s is not None:
            hdr["Retry-After"] = str(max(0, int(err.retry_after_s + 0.5)))
        return cls(status=err.status, body=err.body(), headers=hdr)


class Transport(Protocol):
    """What the gateway needs from a transport: start accepting,
    stop accepting, and say where it listens.  The transport builds a
    :class:`WireRequest` per native request, calls
    ``core.handle(request)`` and writes the :class:`WireResponse` back
    (honoring ``stream``); it never interprets routes or bodies."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    @property
    def address(self) -> tuple: ...


class ConnDropped(Exception):
    """Injected transport fault: the client connection vanished (the
    ``conn_drop`` chaos kind).  Transports abort the response without
    writing anything — exactly what a dead TCP peer looks like."""


class HttpTransport:
    """Threading ``http.server`` front for a :class:`~.gateway.Gateway`.

    One handler thread per connection (stdlib ``ThreadingHTTPServer``),
    so every gateway entry point is concurrent by construction — the
    core's locking, the breaker's probe accounting and the stream
    shedding rules are all exercised exactly as a real deployment
    would.  ``port=0`` binds an ephemeral port (tests)."""

    def __init__(self, core, host="127.0.0.1", port=0):
        import http.server
        import threading

        transport = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # quiet: the gateway has spans/metrics; stderr noise is not
            # an observability channel
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _read_body(self) -> bytes:
                """Bounded read: trust Content-Length only up to the
                upload bound + 1 so a lying client cannot make the
                handler buffer an arbitrary body (the +1 byte makes the
                over-limit case detectable as TOO_LARGE, not silently
                truncated-and-accepted).

                Reading LESS than Content-Length desyncs HTTP/1.1
                keep-alive framing — the unread remainder would parse
                as the start of the next request.  Rather than drain an
                attacker-chosen number of bytes, the connection closes
                after the response whenever the declared length exceeds
                the cap; a malformed Content-Length closes too (the
                bytes that follow have no trustworthy framing)."""
                raw = self.headers.get("Content-Length", 0)
                try:
                    n = int(raw)
                except ValueError:
                    self.close_connection = True
                    return b""
                n = max(0, n)
                cap = int(core.max_body) + 1
                if n > cap:
                    self.close_connection = True
                return self.rfile.read(min(n, cap))

            def _serve(self, method):
                from urllib.parse import parse_qsl, urlsplit

                parts = urlsplit(self.path)
                req = WireRequest(
                    method=method, path=parts.path,
                    query=dict(parse_qsl(parts.query)),
                    headers={k.lower(): v for k, v in self.headers.items()},
                    body=self._read_body() if method == "POST" else b"")
                try:
                    resp = core.handle(req)
                except ConnDropped:
                    self.close_connection = True
                    return
                try:
                    self._write(resp)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    self.close_connection = True

            def _write(self, resp: WireResponse):
                if resp.stream is not None:
                    self.send_response(resp.status)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    try:
                        for line in resp.stream:
                            self.wfile.write(
                                b"%x\r\n%s\r\n" % (len(line), line))
                        self.wfile.write(b"0\r\n\r\n")
                    except ConnDropped:
                        # injected client vanish: abort mid-stream
                        self.close_connection = True
                    finally:
                        close = getattr(resp.stream, "close", None)
                        if close is not None:
                            close()
                    return
                if resp.raw is not None:
                    blob = resp.raw
                else:
                    blob = json.dumps(
                        resp.body if resp.body is not None else {},
                        sort_keys=True).encode("utf-8")
                self.send_response(resp.status)
                hdrs = dict(resp.headers)
                ctype = hdrs.pop("Content-Type", "application/json")
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(blob)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            # drained gateways must release the port promptly on CI
            allow_reuse_address = True

        self._server_cls = _Server
        self._handler_cls = _Handler
        self._host, self._port = host, int(port)
        self._httpd = None
        self._thread = None
        self._threading = threading

    def start(self) -> None:
        self._httpd = self._server_cls((self._host, self._port),
                                       self._handler_cls)
        self._thread = self._threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            name="ptgibbs-gateway-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def address(self) -> tuple:
        if self._httpd is None:
            raise RuntimeError("transport not started")
        return self._httpd.server_address
