"""Per-request state: lifecycle, PRNG identity, per-job checkpoints.

A job's randomness is fully determined by ``(service_seed,
tenant_id)``: the tenant base key is ``fold_in(key(service_seed),
tenant_id)`` and every sweep folds the absolute iteration in-trace —
so a job resumed after eviction, crash, or in a fresh process replays
bit-identically, and two jobs never share a stream.

Each job owns a checkpoint directory with the standard verified set
(``ChainStore``: chain.npy / bchain.npy / adapt.npz + manifest.json +
rotating ``.bak``), so the whole integrity / rollback / reshard
machinery of ``runtime/`` applies per request.  ``adapt.npz`` carries
the device carries ``(x, b)`` and the iteration count; the manifest's
``serve`` section records the identity needed to readmit the job
anywhere (:func:`Job.manifest_extra`).

States (mapped onto the supervisor failure taxonomy by the service):

- ``queued``      waiting for a batch-row slot
- ``warming``     bucket routing / compile / graft / b-init in progress
- ``sampling``    resident: riding the vmap axis of the compiled sweep
- ``draining``    preemption drain: checkpointing to a verified set
- ``quarantined`` row-health breach: reverted to its verified
  checkpoint, waiting out its circuit breaker (re-admitted with the
  quarantine budget) or — budget exhausted — parked terminally with
  the marker in its manifest (``integrity.load_resume`` refuses the
  directory without ``force_requeue``)
- ``done``        niter recorded rows checkpointed
- ``failed``      terminal failure (``Job.failure`` carries the class)
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

JOB_STATES = ("queued", "warming", "sampling", "draining", "quarantined",
              "done", "failed")


@dataclasses.dataclass
class Job:
    """One analysis request and its runtime state."""

    job_id: str
    pta: object
    niter: int
    tenant_id: int
    outdir: str
    state: str = "queued"
    failure: str | None = None

    # routing / compiled artifacts (populated at admission)
    bucket: object = None
    cm: object = None            # grafted CompiledPTA
    store: object = None         # ChainStore over outdir

    # progress
    it: int = 0                  # recorded rows so far
    chain: np.ndarray | None = None    # (niter, nx) float64
    bchain: np.ndarray | None = None   # (niter, P*Bmax) float64
    x: np.ndarray | None = None        # (nx,) current state
    b: np.ndarray | None = None        # (P, Bmax) current coefficients
    retries: int = 0
    chunks_resident: int = 0     # chunks since last admission (fair share)
    quarantines: int = 0         # row-health breaches (capped budget)

    # SLO bookkeeping
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    first_sample_at: float | None = None
    admitted_at: float | None = None

    def set_state(self, state: str):
        if state not in JOB_STATES:
            raise ValueError(f"unknown job state {state!r}")
        self.state = state

    @property
    def done(self) -> bool:
        return self.it >= self.niter

    def time_to_first_sample_ms(self) -> float | None:
        if self.first_sample_at is None:
            return None
        return 1e3 * (self.first_sample_at - self.submitted_at)

    # -- checkpointing ------------------------------------------------------

    def open_store(self):
        """Create the per-job ChainStore (writes the pars sidecars that
        ``integrity.load_resume`` reconstructs the store from)."""
        from ..sampler.chains import ChainStore

        cm = self.cm
        bnames = [f"b_p{p}_c{j}" for p in range(cm.P)
                  for j in range(cm.Bmax)]
        self.store = ChainStore(self.outdir, list(self.pta.param_names),
                                bnames)
        return self.store

    def manifest_extra(self) -> dict:
        """Identity the next incarnation needs to readmit this job with
        the same PRNG stream and progress accounting."""
        return {"serve": {
            "job_id": self.job_id,
            "tenant_id": int(self.tenant_id),
            "niter": int(self.niter),
            "bucket": list(self.bucket.as_tuple()),
            "state": self.state,
        }}

    def adapt_state(self) -> dict:
        # ChainStore.save stamps ``iter`` itself (from ``upto``)
        return {
            "x": np.asarray(self.x, np.float64),
            "b": np.asarray(self.b, np.float64),
            "tenant_id": np.asarray(self.tenant_id, np.int64),
        }

    def checkpoint(self):
        """Persist rows [0, it) + carries through the verified-save
        protocol (tmp+replace per file, manifest last, ``.bak``
        rotation)."""
        self.store.save(self.chain[:self.it], self.bchain[:self.it],
                        self.it, adapt_state=self.adapt_state(),
                        extra=self.manifest_extra())

    def try_resume(self, force_requeue=False) -> bool:
        """Load a verified checkpoint from ``outdir`` if one exists
        (``integrity.load_resume`` semantics: manifest verification,
        ``.bak`` rollback, ``CheckpointError`` when unrecoverable —
        including the refusal of a quarantine-marked directory unless
        ``force_requeue``).  Returns True when progress was restored."""
        from ..runtime import integrity

        got = integrity.load_resume(self.outdir,
                                    force_requeue=force_requeue)
        if got is None:
            return False
        chain, bchain, upto, adapt = got
        if int(adapt["tenant_id"]) != int(self.tenant_id):
            raise RuntimeError(
                f"checkpoint in {self.outdir} belongs to tenant "
                f"{int(adapt['tenant_id'])}, not {self.tenant_id} — "
                "refusing a stream-crossing resume")
        self.it = int(upto)
        self.chain[:self.it] = chain[:self.it]
        self.bchain[:self.it] = bchain[:self.it]
        self.x = np.asarray(adapt["x"], np.float64)
        self.b = np.asarray(adapt["b"], np.float64)
        return True

    def alloc(self, nx: int, nb: int):
        """Host record buffers (f64, like the facade's)."""
        self.chain = np.zeros((self.niter, nx), np.float64)
        self.bchain = np.zeros((self.niter, nb), np.float64)
