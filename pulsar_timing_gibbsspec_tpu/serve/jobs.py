"""Per-request state: lifecycle, PRNG identity, per-job checkpoints.

A job's randomness is fully determined by ``(service_seed,
tenant_id, generation)``: the tenant base key is
``fold_in(key(service_seed), tenant_id)`` — with the generation
counter folded on top for forked standing-model generations — and
every sweep folds the absolute iteration in-trace, so a job resumed
after eviction, crash, or in a fresh process replays bit-identically,
and two jobs never share a stream (not even a child generation with
its own parent: past the retained prefix their streams diverge by the
generation fold).

Each job owns a checkpoint directory with the standard verified set
(``ChainStore``: chain.npy / bchain.npy / adapt.npz + manifest.json +
rotating ``.bak``), so the whole integrity / rollback / reshard
machinery of ``runtime/`` applies per request.  ``adapt.npz`` carries
the device carries ``(x, b)`` and the iteration count; the manifest's
``serve`` section records the identity needed to readmit the job
anywhere (:func:`Job.manifest_extra`).

States (mapped onto the supervisor failure taxonomy by the service):

- ``queued``      waiting for a batch-row slot
- ``warming``     bucket routing / compile / graft / b-init in progress
- ``sampling``    resident: riding the vmap axis of the compiled sweep
- ``draining``    preemption drain: checkpointing to a verified set
- ``quarantined`` row-health breach: reverted to its verified
  checkpoint, waiting out its circuit breaker (re-admitted with the
  quarantine budget) or — budget exhausted — parked terminally with
  the marker in its manifest (``integrity.load_resume`` refuses the
  directory without ``force_requeue``)
- ``done``        niter recorded rows checkpointed
- ``failed``      terminal failure (``Job.failure`` carries the class)
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

JOB_STATES = ("queued", "warming", "sampling", "draining", "quarantined",
              "done", "failed")


@dataclasses.dataclass
class Job:
    """One analysis request and its runtime state."""

    job_id: str
    pta: object
    niter: int
    tenant_id: int
    outdir: str
    state: str = "queued"
    failure: str | None = None

    # standing-model lifecycle: 0 = root; a forked child generation
    # carries its lineage section (runtime/lineage.py) for the manifest
    generation: int = 0
    lineage: dict | None = None

    # routing / compiled artifacts (populated at admission)
    bucket: object = None
    cm: object = None            # grafted CompiledPTA
    store: object = None         # ChainStore over outdir
    slice_id: int | None = None  # fault domain of the last residency

    # progress
    it: int = 0                  # recorded rows so far
    chain: np.ndarray | None = None    # (niter, nx) float64
    bchain: np.ndarray | None = None   # (niter, P*Bmax) float64
    x: np.ndarray | None = None        # (nx,) current state
    b: np.ndarray | None = None        # (P, Bmax) current coefficients
    retries: int = 0
    chunks_resident: int = 0     # chunks since last admission (fair share)
    quarantines: int = 0         # row-health breaches (capped budget)

    # SLO bookkeeping
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    first_sample_at: float | None = None
    admitted_at: float | None = None

    def set_state(self, state: str):
        if state not in JOB_STATES:
            raise ValueError(f"unknown job state {state!r}")
        self.state = state

    @property
    def done(self) -> bool:
        return self.it >= self.niter

    def time_to_first_sample_ms(self) -> float | None:
        if self.first_sample_at is None:
            return None
        return 1e3 * (self.first_sample_at - self.submitted_at)

    # -- checkpointing ------------------------------------------------------

    def open_store(self):
        """Create the per-job ChainStore (writes the pars sidecars that
        ``integrity.load_resume`` reconstructs the store from)."""
        from ..sampler.chains import ChainStore

        cm = self.cm
        bnames = [f"b_p{p}_c{j}" for p in range(cm.P)
                  for j in range(cm.Bmax)]
        self.store = ChainStore(self.outdir, list(self.pta.param_names),
                                bnames)
        return self.store

    def manifest_extra(self) -> dict:
        """Identity the next incarnation needs to readmit this job with
        the same PRNG stream and progress accounting."""
        extra = {"serve": {
            "job_id": self.job_id,
            "tenant_id": int(self.tenant_id),
            "niter": int(self.niter),
            "bucket": list(self.bucket.as_tuple()),
            "state": self.state,
            "generation": int(self.generation),
            "pulsars": [str(p) for p in self.pta.pulsars],
        }}
        if self.slice_id is not None:
            # the fault domain the checkpoint was cut in: forensic only
            # (readmission re-routes by group key, never by old slice)
            extra["serve"]["slice"] = int(self.slice_id)
        if self.lineage is not None:
            extra["lineage"] = dict(self.lineage)
        return extra

    def adapt_state(self) -> dict:
        # ChainStore.save stamps ``iter`` itself (from ``upto``)
        return {
            "x": np.asarray(self.x, np.float64),
            "b": np.asarray(self.b, np.float64),
            "tenant_id": np.asarray(self.tenant_id, np.int64),
            "generation": np.asarray(self.generation, np.int64),
        }

    def checkpoint(self):
        """Persist rows [0, it) + carries through the verified-save
        protocol (tmp+replace per file, manifest last, ``.bak``
        rotation)."""
        self.store.save(self.chain[:self.it], self.bchain[:self.it],
                        self.it, adapt_state=self.adapt_state(),
                        extra=self.manifest_extra())

    def try_resume(self, force_requeue=False) -> bool:
        """Load a verified checkpoint from ``outdir`` if one exists
        (``integrity.load_resume`` semantics: manifest verification,
        ``.bak`` rollback, ``CheckpointError`` when unrecoverable —
        including the refusal of a quarantine-marked directory unless
        ``force_requeue``).  Returns True when progress was restored."""
        from ..runtime import integrity

        got = integrity.load_resume(self.outdir,
                                    force_requeue=force_requeue,
                                    pta=self.pta)
        if got is None:
            return False
        chain, bchain, upto, adapt = got
        if int(adapt["tenant_id"]) != int(self.tenant_id):
            raise RuntimeError(
                f"checkpoint in {self.outdir} belongs to tenant "
                f"{int(adapt['tenant_id'])}, not {self.tenant_id} — "
                "refusing a stream-crossing resume")
        ck_gen = int(adapt["generation"]) if "generation" in adapt else 0
        if ck_gen != int(self.generation):
            raise RuntimeError(
                f"checkpoint in {self.outdir} is generation {ck_gen}, "
                f"not {self.generation} — refusing a generation-"
                "crossing resume (streams are re-keyed per generation)")
        if self.lineage is None:
            man = integrity.read_manifest(self.outdir)
            if isinstance(man, dict) and not man.get("corrupt") \
                    and isinstance(man.get("lineage"), dict):
                self.lineage = dict(man["lineage"])
        self.it = int(upto)
        self.chain[:self.it] = chain[:self.it]
        self.bchain[:self.it] = bchain[:self.it]
        self.x = np.asarray(adapt["x"], np.float64)
        self.b = np.asarray(adapt["b"], np.float64)
        return True

    def alloc(self, nx: int, nb: int):
        """Host record buffers (f64, like the facade's)."""
        self.chain = np.zeros((self.niter, nx), np.float64)
        self.bchain = np.zeros((self.niter, nb), np.float64)


# -- standing-model migration ------------------------------------------------

#: the migration state machine (audited by racecheck M1–M3; declared in
#: contracts/racecheck.json).  ``planned → journaled`` happens at the
#: gateway (the forking intent is durable before any checkpoint work);
#: a service-level append with no journal goes ``planned → forked``
#: directly.  ``aborted`` is reachable from every non-final state — a
#: kill mid-migration leaves either the parent (nothing promoted) or
#: the child (fork idempotent, readmit replayable), never a hybrid.
MIGRATION_STATES = ("planned", "journaled", "forked", "readmitted",
                    "aborted")


class MigrationTicket:
    """Tracks one append → fork → readmit migration through its
    audited state machine (see :data:`MIGRATION_STATES`)."""

    def __init__(self, job_id, plan=None):
        self.job_id = str(job_id)
        self.plan = plan
        self.state = "planned"

    def journaled(self):
        if self.state == "planned":
            self.state = "journaled"

    def forked(self):
        if self.state == "planned":
            self.state = "forked"
            return
        if self.state == "journaled":
            self.state = "forked"

    def readmitted(self):
        if self.state == "forked":
            self.state = "readmitted"

    def abort(self):
        if self.state == "planned":
            self.state = "aborted"
            return
        if self.state == "journaled":
            self.state = "aborted"
            return
        if self.state == "forked":
            self.state = "aborted"


def repad_checkpoint(stage_dir, p_old, b_old, p_new, b_new):
    """Re-embed a staged checkpoint's padded-basis axes from the parent
    bucket's ``(P_old, Bmax_old)`` geometry into the child bucket's
    ``(P_new, Bmax_new)``.

    Pad slots are EXACT zeros by the compiled-sweep conventions
    (``serve/buckets.py`` docstring), so zero-embedding the recorded
    ``bchain`` rows and the ``b`` carry reproduces bit-for-bit what the
    child bucket's program would have recorded for the same draws — the
    retained-row prefix survives a cross-bucket migration bitwise.
    ``chain.npy`` and ``x`` are untouched: the parameter vector depends
    on the dataset, not the padding.  Runs against a non-live staging
    dir (``lineage.fork_generation``'s transform hook), so plain
    writes are fine.
    """
    import os
    from pathlib import Path

    if (p_new, b_new) == (p_old, b_old):
        return
    if p_new < p_old or b_new < b_old:
        raise ValueError(
            f"re-pad cannot shrink the padded geometry "
            f"(({p_old}, {b_old}) -> ({p_new}, {b_new}))")
    stage = Path(stage_dir)
    bpath = stage / "bchain.npy"
    if bpath.exists():
        arr = np.load(bpath)
        rows = arr.shape[0]
        out = np.zeros((rows, p_new, b_new), arr.dtype)
        out[:, :p_old, :b_old] = arr.reshape(rows, p_old, b_old)
        np.save(stage / "bchain.npy.tmp.npy", out.reshape(rows, -1))
        os.replace(stage / "bchain.npy.tmp.npy", bpath)
    apath = stage / "adapt.npz"
    if apath.exists():
        with np.load(apath) as z:
            d = {k: z[k] for k in z.files}
        if "b" in d:
            b = np.asarray(d["b"])
            nb = np.zeros((p_new, b_new), b.dtype)
            nb[:p_old, :b_old] = b.reshape(p_old, b_old)
            d["b"] = nb
        np.savez(stage / "adapt.npz.tmp.npz", **d)
        os.replace(stage / "adapt.npz.tmp.npz", apath)
    bnames = [f"b_p{p}_c{j}" for p in range(p_new) for j in range(b_new)]
    (stage / "pars_bchain.txt").write_text("\n".join(bnames) + "\n")
