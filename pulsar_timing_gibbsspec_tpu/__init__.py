"""TPU-native blocked Gibbs sampler for pulsar-timing-array free-spectrum analysis.

A ground-up JAX/XLA re-design of the capabilities of
``astrolamb/pulsar_timing_gibbsspec`` (blocked Gibbs periodogram sampler after
van Haasteren & Vallisneri 2014, arXiv:1407.1838).  The compute path is
jit-compiled JAX — conditional-draw kernels composed in ``lax.scan`` sweeps,
``vmap`` over pulsars/chains, ``shard_map`` over a device mesh for the
45-pulsar array — while host-side ingestion (par/tim parsing, design matrices,
priors, chain I/O) stays NumPy/C++.

Layout
------
``data/``      host ingestion: par/tim readers, timing design matrix, Fourier
               GP basis, injection simulator
``models/``    priors, PSDs, ORFs, signal model + PTA container,
               ``model_general`` factory (kwarg surface of the reference's
               ``model_definition.py``)
``ops/``       JAX device kernels: preconditioned solves, conditional draws,
               MH scans, autocorrelation
``sampler/``   Gibbs sampler backends (``numpy`` oracle, ``jax`` device path)
               and the user-facing facade
``parallel/``  meshes, collectives (psum common-spectrum reduction),
               shard_map'd sweeps
``native/``    C++ host components (acor-style ACT, chain writer)
``utils/``     profiling, logging, config
"""

from .config import settings

__version__ = "0.1.0"


def __getattr__(name):
    # lazy so that importing the package never pulls in jax before the
    # caller has had a chance to set platform/precision env vars
    if name == "model_general":
        from .models.factory import model_general

        return model_general
    if name in ("PulsarBlockGibbs", "PTABlockGibbs"):
        from .sampler import gibbs

        return getattr(gibbs, name)
    raise AttributeError(name)

__all__ = [
    "settings",
    "model_general",
    "PulsarBlockGibbs",
    "PTABlockGibbs",
    "__version__",
]
