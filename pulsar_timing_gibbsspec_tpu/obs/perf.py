"""The performance observatory: streaming stage telemetry, anomaly
capture, and the durable perf ledger.

Three cooperating pieces, all riding the PR 7 span seams instead of
adding new instrumentation to the hot loop:

- :class:`StageAggregator` — a trace *observer* that folds every
  per-chunk pipeline-stage span (``chunk.host_prep`` /
  ``chunk.dispatch`` / ``chunk.d2h`` / ``chunk.writeback`` and their
  ``serve.*`` twins) into bounded ring-buffer time series and exports
  EMA/percentile gauges through :mod:`runtime.telemetry` labels —
  ``dispatch_ms{stage="device",stat="p90"[,job=...]}`` — so
  ``SamplerService.prometheus()`` scrapes the live dispatch breakdown
  without any one-shot probe.  Observers run outside the traced
  program: sampling outputs stay bitwise identical (the PR 7 proof in
  tests/test_obs.py extends over this layer), and with no observer
  installed the span seams remain the shared nullcontext — zero cost.

- :class:`FlightRecorder` — anomaly-triggered capture.  When the
  dispatch-EMA watchdog soft-warns (``watchdog.soft`` instant) or a
  stage gauge breaches its band (``perf.band_breach`` from the
  aggregator), it opens a bounded ``jax.profiler`` trace window and,
  after the next few chunks, merges the XLA trace with the obs span
  timeline into one Perfetto file — the stall arrives with the
  device-level evidence attached.

- the **perf ledger** — an append-only ``PERF_LEDGER.jsonl`` of bench
  headline records (rates, ess/s, dispatch percentiles, per-block
  roofline, device/backend/mesh, contract hashes, git sha) written by
  ``bench.py`` and checked by ``tools/perfwatch.py --check`` under
  explicit noise bands (:func:`check_ledger`), so the perf trajectory
  is machine-gated like the jaxlint/jaxprcheck ratchets.

Schema/glossary: docs/OBSERVABILITY.md; reading the roofline:
docs/PERFORMANCE.md.
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
import subprocess
import time
from pathlib import Path

import numpy as np

from ..runtime import telemetry
from . import trace as otrace

_REPO_ROOT = Path(__file__).resolve().parents[2]

# ---------------------------------------------------------------------------
# streaming stage telemetry


class RingSeries:
    """A bounded numeric time series: O(1) append into a fixed ring,
    EMA maintained online, percentiles over the retained window."""

    __slots__ = ("_buf", "_n", "_i", "ema", "_alpha", "count")

    def __init__(self, cap: int = 512, ema_alpha: float = 0.3):
        self._buf = np.empty(int(cap), np.float64)
        self._n = 0          # filled entries (<= cap)
        self._i = 0          # next write slot
        self.ema = None
        self._alpha = float(ema_alpha)
        self.count = 0       # total ever appended

    def append(self, v: float) -> None:
        v = float(v)
        self._buf[self._i] = v
        self._i = (self._i + 1) % len(self._buf)
        self._n = min(self._n + 1, len(self._buf))
        self.ema = v if self.ema is None else (
            self._alpha * v + (1.0 - self._alpha) * self.ema)
        self.count += 1

    def last(self) -> float | None:
        if not self._n:
            return None
        return float(self._buf[(self._i - 1) % len(self._buf)])

    def values(self) -> np.ndarray:
        return self._buf[: self._n].copy()

    def percentile(self, q) -> float:
        return float(np.percentile(self._buf[: self._n], q))

    def __len__(self) -> int:
        return self._n


#: span name -> pipeline stage.  ``chunk.dispatch`` is the *enqueue*
#: (async backends return once the program is in flight), ``chunk.d2h``
#: the wait for device results — the same reading as
#: ``profiling.dispatch_breakdown``.  ``chunk.compile_dispatch`` is
#: deliberately absent: a compile wall is not a steady-state stage.
#: ``chunk.carry_sync`` (the mega-chunk loop's host snapshot of the
#: donated carry) is also unmapped — it is a sync point inside the
#: dispatch pipeline, visible in the Perfetto timeline, not a stage of
#: its own.  A synthetic ``dispatch_amortized`` stage (enqueue ms /
#: sweeps-per-dispatch, from the span's ``n=`` attr) is derived in
#: :meth:`StageAggregator._on_event`.
SPAN_STAGES = {
    "chunk.host_prep": "host_prep",
    "chunk.dispatch": "enqueue",
    "chunk.d2h": "device",
    "chunk.writeback": "writeback",
    "serve.prepare": "host_prep",
    "serve.dispatch": "enqueue",
    "serve.d2h": "device",
    "serve.writeback": "writeback",
}

#: gauge stats exported per stage
_STATS = ("last", "ema", "p50", "p90", "p99")


class StageAggregator:
    """Trace observer folding pipeline-stage spans into per-stage
    :class:`RingSeries` and ``dispatch_ms{stage=...,stat=...}`` gauges.

    ``band_k``, when set, arms the breach detector: a stage sample
    exceeding ``band_k``x its prior EMA (after ``warm_n`` samples)
    emits a ``perf.band_breach`` instant, bumps the
    ``stage_band_breaches`` counter, and pokes ``recorder.trigger()``
    when a :class:`FlightRecorder` is attached.
    """

    def __init__(self, cap: int = 512, job: str | None = None,
                 ema_alpha: float = 0.3, band_k: float | None = None,
                 warm_n: int = 8, recorder=None):
        self.job = job
        self.band_k = band_k
        self.warm_n = int(warm_n)
        self.recorder = recorder
        self._series: dict[str, RingSeries] = {}
        self._cap = int(cap)
        self._alpha = float(ema_alpha)
        self._labels = {"job": job} if job is not None else {}

    # -- observer plumbing

    def install(self) -> "StageAggregator":
        otrace.add_observer(self._on_event)
        if self.recorder is not None:
            self.recorder.install()
        return self

    def uninstall(self) -> None:
        otrace.remove_observer(self._on_event)
        if self.recorder is not None:
            self.recorder.uninstall()

    def _on_event(self, ev: dict) -> None:
        if ev.get("ph") != "X":
            return
        stage = SPAN_STAGES.get(ev.get("name"))
        if stage is None:
            return
        ms = ev["dur"] / 1e3
        self.observe(stage, ms)
        if stage == "enqueue":
            # the dispatch span carries the sweeps it covers (run() tags
            # ``n=``): fold the AMORTIZED per-sweep dispatch cost as its
            # own stage so ``dispatch_ms{stage="dispatch_amortized"}``
            # streams live next to the raw stage walls — the metric the
            # mega-chunk loop exists to drive under 1 ms/sweep
            n = (ev.get("args") or {}).get("n")
            if n:
                self.observe("dispatch_amortized", ms / int(n))

    # -- the fold

    def observe(self, stage: str, ms: float) -> None:
        s = self._series.get(stage)
        if s is None:
            s = self._series[stage] = RingSeries(self._cap, self._alpha)
        prior_ema, prior_n = s.ema, s.count
        s.append(ms)
        g = telemetry.gauge
        g("dispatch_ms", ms, stage=stage, stat="last", **self._labels)
        g("dispatch_ms", s.ema, stage=stage, stat="ema", **self._labels)
        for q, stat in ((50, "p50"), (90, "p90"), (99, "p99")):
            g("dispatch_ms", s.percentile(q), stage=stage, stat=stat,
              **self._labels)
        if (self.band_k is not None and prior_ema is not None
                and prior_n >= self.warm_n and ms > self.band_k * prior_ema):
            telemetry.incr("stage_band_breaches", stage=stage,
                           **self._labels)
            otrace.instant("perf.band_breach", stage=stage,
                           ms=round(ms, 3), ema=round(prior_ema, 3),
                           k=self.band_k)
            if self.recorder is not None:
                self.recorder.trigger(f"band_breach:{stage}")

    # -- export

    def summary(self) -> dict:
        """``{stage: {n, last, ema, p50, p90, p99}}`` for reports."""
        out = {}
        for stage, s in self._series.items():
            if not len(s):
                continue
            out[stage] = {"n": s.count, "last": s.last(), "ema": s.ema,
                          "p50": s.percentile(50), "p90": s.percentile(90),
                          "p99": s.percentile(99)}
        return out


# ---------------------------------------------------------------------------
# anomaly-triggered capture


class FlightRecorder:
    """Bounded anomaly capture: on a trigger (``watchdog.soft`` instant
    by default, or an explicit :meth:`trigger` from the aggregator's
    band detector), start a ``jax.profiler`` trace and stop it after
    the next ``window_chunks`` dispatch spans (or ``max_s`` seconds),
    merging the XLA trace with the obs span timeline into one Perfetto
    file under ``outdir``.  At most ``max_captures`` windows per
    process — a flapping anomaly cannot fill the disk.
    """

    #: spans that advance the capture window (one per chunk dispatch)
    _WINDOW_SPANS = ("chunk.dispatch", "chunk.compile_dispatch",
                     "serve.dispatch", "serve.compile_dispatch")

    def __init__(self, outdir, window_chunks: int = 4,
                 max_captures: int = 2, max_s: float = 60.0,
                 profiler: bool = True,
                 triggers=("watchdog.soft",)):
        self.outdir = Path(outdir)
        self.window_chunks = int(window_chunks)
        self.max_captures = int(max_captures)
        self.max_s = float(max_s)
        self.profiler = profiler
        self.triggers = tuple(triggers)
        self.captures: list = []     # merged-file paths, one per capture
        self._armed = False
        self._left = 0
        self._t0 = 0.0
        self._reason = None
        self._profiling = False
        self._window_events: list = []

    def install(self) -> "FlightRecorder":
        otrace.add_observer(self._on_event)
        return self

    def uninstall(self) -> None:
        otrace.remove_observer(self._on_event)
        if self._armed:
            self._finish()

    def _on_event(self, ev: dict) -> None:
        if self._armed:
            if len(self._window_events) < 10_000:
                self._window_events.append(ev)
            if (ev.get("ph") == "X"
                    and ev.get("name") in self._WINDOW_SPANS):
                self._left -= 1
            if self._left <= 0 or time.monotonic() - self._t0 > self.max_s:
                self._finish()
            return
        if ev.get("ph") == "i" and ev.get("name") in self.triggers:
            self.trigger(ev["name"])

    def trigger(self, reason: str) -> bool:
        """Arm a capture window.  Returns False when already armed or
        out of capture budget."""
        if self._armed or len(self.captures) >= self.max_captures:
            return False
        self._armed = True
        self._left = self.window_chunks
        self._t0 = time.monotonic()
        self._reason = reason
        self._window_events = []
        self.outdir.mkdir(parents=True, exist_ok=True)
        if self.profiler:
            try:
                import jax

                jax.profiler.start_trace(str(self._profile_dir()))
                self._profiling = True
            except Exception:
                self._profiling = False
        telemetry.incr("anomaly_captures")
        otrace.instant("perf.capture_start", reason=reason)
        return True

    def _profile_dir(self) -> Path:
        return self.outdir / f"xla_{len(self.captures)}"

    def _finish(self) -> None:
        if self._profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._profiling = False
        out = self.outdir / f"anomaly_{len(self.captures)}.trace.json"
        # the full buffered timeline when the trace layer records;
        # otherwise the window this observer buffered itself
        spans = (otrace.events() if otrace.is_enabled()
                 else self._window_events)
        try:
            merge_perfetto(self._profile_dir(), out,
                           extra_events=spans,
                           meta={"reason": self._reason})
            self.captures.append(str(out))
        except Exception:
            self.captures.append(None)
        self._armed = False
        otrace.instant("perf.capture_done", path=str(out))


def merge_perfetto(profile_dir, out_path, extra_events=None,
                   meta=None) -> str:
    """Merge every ``*.trace.json[.gz]`` under ``profile_dir`` (the
    ``jax.profiler`` output layout) with ``extra_events`` (obs span
    dicts) into one Chrome/Perfetto trace file.  Tolerates a missing or
    empty profiler dir — the span timeline alone still lands."""
    events: list = []
    profile_dir = os.fspath(profile_dir)
    paths = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(profile_dir, "**", "*.trace.json"),
                    recursive=True))
    for p in paths:
        try:
            op = gzip.open if p.endswith(".gz") else open
            with op(p, "rt") as fh:
                doc = json.load(fh)
            events.extend(doc.get("traceEvents", []))
        except Exception:
            continue
    if extra_events:
        events.extend(extra_events)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        doc["metadata"] = dict(meta)
    out_path = os.fspath(out_path)
    with open(out_path, "w") as fh:
        json.dump(doc, fh)
    return out_path


# ---------------------------------------------------------------------------
# the durable perf ledger

#: bumped when a record's field meaning changes (docs/OBSERVABILITY.md)
LEDGER_SCHEMA = 1

#: headline fields copied verbatim into a ledger record when present
_HEADLINE_FIELDS = (
    "metric", "value", "unit", "vs_baseline", "device_kind", "backend",
    "sweeps_per_sec", "nchains", "mfu", "ess_per_sec",
    "ess_per_sec_device", "rho_act_median", "mesh_axes", "n_retraces",
    "dispatch_amortized_ms_per_sweep",
    "dispatch_breakdown_ms", "stage_summary",
)


def ledger_path(root=None) -> Path:
    return Path(root or _REPO_ROOT) / "PERF_LEDGER.jsonl"


def git_sha() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except Exception:
        return None


def make_ledger_record(headline: dict, *, source: str, kind: str = "bench",
                       run: str | None = None, ts: float | None = None,
                       note: str | None = None) -> dict:
    """One append-only ledger line from a bench headline dict.  Heavy
    sub-objects are condensed: the roofline keeps per-block MFU/bound
    only, contract hashes come from the resilience block."""
    rec = {"schema": LEDGER_SCHEMA, "kind": kind, "source": source,
           "ts": time.time() if ts is None else ts}
    if rec["ts"] is not None:
        rec["ts_iso"] = _iso_ts(rec["ts"])
    if run:
        rec["run"] = run
    if note:
        rec["note"] = note
    for k in _HEADLINE_FIELDS:
        if headline.get(k) is not None:
            rec[k] = headline[k]
    roof = headline.get("roofline")
    if roof:
        rec["roofline"] = {
            name: {kk: r[kk] for kk in ("mfu", "intensity", "bound")
                   if kk in r}
            for name, r in roof.get("blocks", {}).items()}
    contracts = (headline.get("resilience") or {}).get(
        "jaxprcheck", {}).get("contracts")
    if contracts:
        rec["contract_hashes"] = contracts
    sha = git_sha()
    if sha:
        rec["git_sha"] = sha
    return rec


def _iso_ts(ts: float) -> str:
    """Host-side ISO-8601 UTC stamp for a ledger epoch ``ts``."""
    import datetime

    return datetime.datetime.fromtimestamp(
        float(ts), tz=datetime.timezone.utc
    ).isoformat(timespec="seconds").replace("+00:00", "Z")


def ledger_append(rec: dict, path=None) -> str:
    """Append one record, stamping the append time when the producer
    left ``ts`` null/absent (the MULTICHIP snapshot parser used to —
    a record must always carry a real host-side timestamp)."""
    if rec.get("ts") is None:
        rec = dict(rec, ts=time.time())
    if not rec.get("ts_iso"):
        rec = dict(rec, ts_iso=_iso_ts(rec["ts"]))
    path = os.fspath(path or ledger_path())
    with open(path, "a") as fh:
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return path


def ledger_read(path=None) -> list[dict]:
    """All well-formed records, in file order.  Corrupt lines (torn
    appends) are skipped, counted in each run's ``check_ledger``."""
    path = os.fspath(path or ledger_path())
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except Exception:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


# -- the regression gate

#: gated metrics with their default noise bands.  For rate fields
#: (bigger is better) the band is the allowed fractional DROP of HEAD
#: vs the best (highest) prior record in the same group; for the cost
#: fields in :data:`LOWER_IS_BETTER` it is the allowed fractional
#: GROWTH over the best (lowest) prior.  Wide on purpose: bench numbers
#: span hosts and load; the gate exists to catch step regressions, not
#: jitter.
DEFAULT_BANDS = {
    "value": 0.35,
    "sweeps_per_sec": 0.35,
    "ess_per_sec": 0.40,
    "ess_per_sec_device": 0.40,
    "dispatch_amortized_ms_per_sweep": 0.50,
}

#: fields where SMALLER is better — the dispatch-tax headline the
#: mega-chunk loop drives down; the gate bounds growth above the best
#: prior instead of a drop below it (a ``--band`` override changes the
#: width only, never the direction)
LOWER_IS_BETTER = frozenset({"dispatch_amortized_ms_per_sweep"})


def _group_key(rec: dict) -> tuple:
    """Records compare only within (kind, metric, device, backend) —
    a CPU smoke run must never gate against the TPU trajectory."""
    return (rec.get("kind", "bench"), rec.get("metric"),
            rec.get("device_kind"), rec.get("backend"))


def check_ledger(records: list[dict], bands: dict | None = None) -> list:
    """Noise-banded regression check over a ledger.

    Within each (kind, metric, device_kind, backend) group the newest
    record's rate fields must not fall more than the band fraction
    below the best prior value; :data:`LOWER_IS_BETTER` fields
    (dispatch tax) must not GROW more than the band above the best
    (lowest) prior.  New metrics/groups/fields (no prior) pass;
    ``multichip`` records must carry ``ok: true``.  Returns a list of
    problem strings — empty means the gate passes."""
    bands = {**DEFAULT_BANDS, **(bands or {})}
    problems: list = []
    groups: dict = {}
    multichip: list = []
    for rec in records:
        if rec.get("schema") is None:
            problems.append(f"record missing schema: {rec.get('run') or rec}")
            continue
        if rec.get("kind") == "multichip":
            multichip.append(rec)
            continue
        if rec.get("metric") is None:
            continue
        groups.setdefault(_group_key(rec), []).append(rec)
    # early failed multichip runs are history, not a regression; only
    # the trajectory's newest scaling record must be healthy
    if multichip and multichip[-1].get("ok") is False:
        problems.append(
            f"newest multichip run {multichip[-1].get('run')} recorded "
            "ok=false")
    for key, recs in groups.items():
        if len(recs) < 2:
            continue                      # new group: tolerated
        newest, prior = recs[-1], recs[:-1]
        for field, band in bands.items():
            new_v = newest.get(field)
            if new_v is None or not isinstance(new_v, (int, float)):
                continue
            prev = [r[field] for r in prior
                    if isinstance(r.get(field), (int, float))
                    and math.isfinite(r[field])]
            if not prev:
                continue                  # new field: tolerated
            if field in LOWER_IS_BETTER:
                best = min(prev)
                ceiling = (1.0 + band) * best
                if new_v > ceiling:
                    problems.append(
                        f"{key[1]} [{key[2]}/{key[3]}] {field}: newest "
                        f"{new_v:.4g} grew past noise band "
                        f"(best prior {best:.4g}, ceiling "
                        f"{ceiling:.4g}, band {band:.0%})")
                continue
            best = max(prev)
            floor = (1.0 - band) * best
            if new_v < floor:
                problems.append(
                    f"{key[1]} [{key[2]}/{key[3]}] {field}: newest "
                    f"{new_v:.4g} fell below noise band "
                    f"(best prior {best:.4g}, floor {floor:.4g}, "
                    f"band {band:.0%})")
    return problems
