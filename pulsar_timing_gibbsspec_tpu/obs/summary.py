"""Host-side finalizers for the on-device diagnostic sketches.

Everything here is NumPy on the tiny summary slab (``obs/sketch.py``
state brought to host once, plus the per-writeback cumulative moment
snapshots the driver keeps) — no chain-sized arrays, no device work.
"""

from __future__ import annotations

import time

import numpy as np

from ..ops.acf import act_from_rho, integrated_act
from .sketch import SketchSpec


def finalize(spec: SketchSpec, state, c: float = 5.0) -> dict:
    """Turn a host copy of the sketch state into diagnostics.

    Returns per-chain/channel arrays: ``mean``/``var`` ``(C, D)``,
    ``rho`` ``(C, D, L)``, ``act``/``ess`` ``(C, D)`` (ACT in SWEEP
    units — the sketch streams every sweep, before record thinning),
    ``cross_cov`` ``(C, Kc, Kc)``, ``move_rate`` per block group, and
    scalar roll-ups (``act_rho_med``, ``ess_total``).
    """
    n = float(np.asarray(state["n"]))
    C, D, L = state["mean"].shape[0], spec.D, spec.lags
    mean = np.asarray(state["mean"], np.float64)
    m2 = np.asarray(state["m2"], np.float64)
    out = {"n": n, "channels": list(spec.names),
           "groups": [nm for nm, _ in spec.groups]}
    if n < 4:
        out.update(mean=mean, var=np.zeros_like(mean),
                   act=np.ones((C, D)), ess=np.zeros((C, D)),
                   rho=np.zeros((C, D, L)), cross_cov=None,
                   move_rate={}, act_rho_med=1.0, ess_total=0.0,
                   window_saturated=False)
        return out
    var = m2 / max(n - 1.0, 1.0)
    # plug-in-mean autocovariance from the raw lagged-product sums;
    # gamma_0 reduces exactly to the biased m2/n the FFT estimator uses
    counts = np.maximum(n - np.arange(L, dtype=np.float64), 1.0)
    gamma = np.asarray(state["lag"], np.float64) / counts - mean[..., None] ** 2
    g0 = gamma[..., :1]
    dead = g0[..., 0] <= 0                    # constant channels
    rho = np.where(dead[..., None], 0.0, gamma / np.where(g0 <= 0, 1.0, g0))
    rho[..., 0] = np.where(dead, 1.0, rho[..., 0])
    act = act_from_rho(rho, c=c)
    act = np.where(dead, 1.0, act)
    # a window that never qualified means L was too short for this
    # channel's tau — surface it instead of silently under-reporting
    tau = 2.0 * np.cumsum(rho, axis=-1) - 1.0
    saturated = ~np.any(np.arange(L) >= c * tau, axis=-1) & ~dead
    ess = np.where(dead, 0.0, n / act)
    out.update(mean=mean, var=var, rho=rho, act=act, ess=ess,
               cross_cov=(np.asarray(state["cross"], np.float64)
                          / max(n - 1.0, 1.0)) if spec.cross_k else None,
               window_saturated=bool(saturated.any()))
    moven = float(np.asarray(state["moven"]))
    move = np.asarray(state["move"], np.float64)
    out["move_rate"] = {
        nm: move[:, g] / max(moven, 1.0)
        for g, (nm, _) in enumerate(spec.groups)}
    # roll-ups the bench/serve gauges report: the rho block is the slow
    # direction, so its median ACT is the honest mixing scalar
    nrho = sum(1 for nm in spec.names if "rho" in nm and "gw" in nm)
    sl = slice(0, nrho) if nrho else slice(0, D)
    out["act_rho_med"] = float(np.median(act[:, sl]))
    out["ess_total"] = float(ess.sum())
    return out


def moment_split_rhat(snaps, final) -> np.ndarray | None:
    """Split-R-hat per channel from cumulative moment snapshots.

    ``snaps`` is the driver's per-writeback list of cumulative
    ``(n, mean, m2)`` host tuples; ``final`` the end-of-run host state.
    The snapshot nearest n/2 gives the first-half moments; the second
    half follows by Chan SUBTRACTION of the cumulative pair — so each
    chain contributes two groups (its halves) to the classic Gelman-
    Rubin between/within ratio, all from the summary slab, never from
    chains.  Returns ``(D,)`` R-hat per channel, or None when the run
    is too short to split.
    """
    nT = float(np.asarray(final["n"]))
    if not snaps or nT < 8:
        return None
    ns = np.asarray([s[0] for s in snaps])
    k = int(np.argmin(np.abs(ns - nT / 2.0)))
    n1, mean1, m21 = snaps[k]
    n1 = float(n1)
    n2 = nT - n1
    if n1 < 4 or n2 < 4:
        return None
    meanT = np.asarray(final["mean"], np.float64)
    m2T = np.asarray(final["m2"], np.float64)
    mean2 = (nT * meanT - n1 * mean1) / n2
    m22 = m2T - m21 - (mean2 - mean1) ** 2 * (n1 * n2 / nT)
    # 2C groups: per-chain halves.  Group sizes differ by at most one
    # snapshot granule; use their mean as the formula's n.
    means = np.concatenate([mean1, mean2], axis=0)      # (2C, D)
    vars_ = np.concatenate([m21 / max(n1 - 1.0, 1.0),
                            np.maximum(m22, 0.0) / max(n2 - 1.0, 1.0)],
                           axis=0)
    nbar = (n1 + n2) / 2.0
    W = vars_.mean(axis=0)
    B = nbar * means.var(axis=0, ddof=1)
    W = np.where(W <= 0, np.finfo(np.float64).tiny, W)
    var_plus = (nbar - 1.0) / nbar * W + B / nbar
    return np.sqrt(var_plus / W)


class RollingDiag:
    """Bounded live diagnostics for one resident serve job (host-side).

    The serve writeback feeds it thinned recorded rows of the job's
    diagnostic channels; it keeps only the last ``cap`` rows and
    answers the three per-job SLO gauges: ``ess_per_sec`` (Sokal ACT
    over the window / observed row rate), ``rhat_max`` (rank-normalized
    split-R-hat of the window halves, :mod:`.convergence`), and
    ``accept_rate`` (consecutive-row movement fraction).
    """

    def __init__(self, cap: int = 512):
        self.cap = int(cap)
        self._rows: list = []
        self.n = 0
        self.t0 = None

    def observe(self, rows: np.ndarray, now: float | None = None) -> None:
        """``rows`` is ``(m, d)`` — recorded sweeps x diagnostic
        channels for one job."""
        now = time.monotonic() if now is None else now
        if self.t0 is None:
            self.t0 = now
        self._t = now
        rows = np.asarray(rows, np.float64)
        self.n += rows.shape[0]
        self._rows.extend(rows)
        del self._rows[: max(0, len(self._rows) - self.cap)]

    def _window(self) -> np.ndarray:
        return np.asarray(self._rows, np.float64)

    def row_rate(self) -> float:
        dt = (self._t - self.t0) if self.t0 is not None else 0.0
        return self.n / dt if dt > 0 else 0.0

    def act(self) -> float:
        w = self._window()
        if w.shape[0] < 8:
            return 1.0
        return float(np.median([integrated_act(w[:, j])
                                for j in range(w.shape[1])]))

    def ess_per_sec(self) -> float:
        return self.row_rate() / max(self.act(), 1.0)

    def rhat_max(self) -> float:
        w = self._window()
        if w.shape[0] < 16:
            return 1.0
        from .convergence import rank_normalized_split_rhat

        vals = [rank_normalized_split_rhat(w[None, :, j])
                for j in range(w.shape[1])]
        return float(np.max(vals))

    def accept_rate(self) -> float:
        w = self._window()
        if w.shape[0] < 2:
            return 0.0
        return float(np.mean(np.any(w[1:] != w[:-1], axis=-1)))
