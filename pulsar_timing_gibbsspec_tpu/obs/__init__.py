"""On-device streaming diagnostics + host-side trace/metrics export.

Two halves (docs/OBSERVABILITY.md):

- **Device half** (:mod:`.sketch`, finalized by :mod:`.summary`):
  streaming Welford/cross-covariance moments, a one-pass batched
  lagged-product ACF accumulator (the vmapped generalization of
  ``ops/acf.py``), and per-block move-rate sums, carried through the
  scanned chunk so ESS/ACT/R-hat ship as a tiny summary slab instead of
  raw chains.
- **Host half** (:mod:`.trace`, :mod:`.metrics`, :mod:`.convergence`,
  :mod:`.perf`):
  nested monotonic trace spans around the dispatch pipeline (Perfetto/
  Chrome ``trace.json`` + ``metrics.jsonl`` events), a dependency-free
  Prometheus text exposition writer over the labeled telemetry
  registry, exact rank-normalized split-R-hat for host-side record
  slabs, and the performance observatory (streaming stage gauges,
  anomaly-triggered profiler capture, the append-only perf ledger).

This ``__init__`` stays import-light: :mod:`.trace` is stdlib-only and
eagerly available (the driver hot path touches it every chunk); the
jax/numpy halves load on first attribute access.
"""

from . import trace  # noqa: F401  (stdlib-only; hot-path no-op when disabled)

_LAZY = {
    "sketch": ".sketch",
    "summary": ".summary",
    "metrics": ".metrics",
    "convergence": ".convergence",
    "perf": ".perf",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
