"""Prometheus text-format exposition over the telemetry registry.

Dependency-free writer for the 0.0.4 text format: counters and gauges
from :mod:`runtime.telemetry` (including its labeled composite keys,
which already use the Prometheus ``name{k="v"}`` syntax) render into
one scrape body.  ``SamplerService.prometheus()`` is the intended
caller; ``tools/obs_probe.py`` writes the same body to disk.
"""

from __future__ import annotations

import math
import re

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_KEY_RE = re.compile(r"^([^{]+)(?:\{(.*)\})?$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def sanitize(name: str) -> str:
    name = _NAME_OK.sub("_", name)
    return "_" + name if name[:1].isdigit() else name


_UNESC = re.compile(r"\\(.)")
_UNESC_MAP = {"n": "\n", "r": "\r"}


def _unescape(v: str) -> str:
    return _UNESC.sub(
        lambda m: _UNESC_MAP.get(m.group(1), m.group(1)), v)


def split_key(key: str):
    """``'name{a="b"}'`` -> ``('name', {'a': 'b'})``; plain names pass
    through with empty labels.  Inverse of ``telemetry.labeled``: label
    values are unescaped here (the renderer re-escapes on the way out)."""
    m = _KEY_RE.match(key)
    if not m:
        return key, {}
    labels = ({k: _unescape(v) for k, v in _LABEL_RE.findall(m.group(2))}
              if m.group(2) else {})
    return m.group(1), labels


def _fmt(v: float) -> str:
    """Prometheus 0.0.4 sample-value spelling: non-finite floats must be
    ``NaN``/``+Inf``/``-Inf`` (Python's ``nan``/``inf`` are invalid)."""
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def _escape(v: str) -> str:
    """Label-value escaping for the exposition body.  Beyond the spec's
    ``\\``/``"``/``\\n`` set, a bare ``\\r`` is escaped as well: label
    values here can arrive from the network path (tenant/job names via
    the gateway), and an unescaped carriage return would let a hostile
    name split a sample line and forge metrics on line-oriented
    scrapers."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n").replace("\r", "\\r")


def _render_family(out, seen, name, labels, value, kind, prefix):
    metric = sanitize(f"{prefix}_{name}" if prefix else name)
    if metric not in seen:
        out.append(f"# TYPE {metric} {kind}")
        seen.add(metric)
    if labels:
        lab = ",".join(f'{sanitize(k)}="{_escape(v)}"'
                       for k, v in sorted(labels.items()))
        out.append(f"{metric}{{{lab}}} {value}")
    else:
        out.append(f"{metric} {value}")


def render(counts=None, gauges=None, prefix: str = "ptgibbs") -> str:
    """Render counter/gauge dicts (telemetry ``snapshot()``/``gauges()``
    shapes — possibly with labeled composite keys) as a Prometheus
    scrape body."""
    out: list = []
    seen: set = set()
    for key, v in sorted((counts or {}).items()):
        name, labels = split_key(key)
        _render_family(out, seen, name + "_total", labels, int(v),
                       "counter", prefix)
    for key, v in sorted((gauges or {}).items()):
        name, labels = split_key(key)
        _render_family(out, seen, name, labels, _fmt(v), "gauge", prefix)
    return "\n".join(out) + ("\n" if out else "")


def render_telemetry(prefix: str = "ptgibbs") -> str:
    """One-call scrape body of the live process-wide registry."""
    from ..runtime import telemetry

    return render(telemetry.snapshot(), telemetry.gauges(), prefix=prefix)
