"""Rank-normalized split-R-hat (Vehtari et al. 2021) on host arrays.

The moment sketch gives a streaming split-R-hat from the summary slab
(``summary.moment_split_rhat``); this module is the EXACT rank-based
estimator for when a (thinned) record slab is on host anyway — tests,
``bench.py`` parity, and the per-job serve windows.  Dependency-free:
the normal quantile function comes from ``jax.scipy.special.ndtri``
evaluated on host-sized arrays.
"""

from __future__ import annotations

import numpy as np


def _ndtri(p: np.ndarray) -> np.ndarray:
    from jax.scipy.special import ndtri

    return np.asarray(ndtri(np.asarray(p, np.float64)))


def _avg_ranks(a: np.ndarray) -> np.ndarray:
    """Average ranks (1-based, ties averaged) of the pooled flat array,
    returned in ``a``'s shape."""
    flat = a.ravel()
    order = np.argsort(flat, kind="stable")
    ranks = np.empty_like(flat)
    sv = flat[order]
    # tie groups share the mean of their would-be ranks
    boundaries = np.flatnonzero(np.r_[True, sv[1:] != sv[:-1], True])
    for lo, hi in zip(boundaries[:-1], boundaries[1:]):
        ranks[order[lo:hi]] = 0.5 * (lo + hi - 1) + 1.0
    return ranks.reshape(a.shape)


def rank_normalize(chains: np.ndarray) -> np.ndarray:
    """Pooled rank-normalization: ranks across ALL chains and draws,
    mapped through the normal quantile with the (r - 3/8)/(S + 1/4)
    blom offset (Vehtari et al. 2021, eq. 14)."""
    chains = np.asarray(chains, np.float64)
    S = chains.size
    return _ndtri((_avg_ranks(chains) - 0.375) / (S + 0.25))


def split_rhat(chains: np.ndarray) -> float:
    """Classic potential scale reduction on split chains.

    ``chains`` is ``(C, n)``; each chain is split into halves (2C
    groups of n//2 draws) before the between/within ratio, so a single
    drifting chain is detected even at C == 1.
    """
    chains = np.asarray(chains, np.float64)
    if chains.ndim != 2:
        raise ValueError("split_rhat expects (chains, draws)")
    n = chains.shape[1] // 2
    if n < 2:
        return 1.0
    halves = np.concatenate([chains[:, :n], chains[:, n:2 * n]], axis=0)
    W = halves.var(axis=1, ddof=1).mean()
    if W <= 0:
        return 1.0
    B = n * halves.mean(axis=1).var(ddof=1)
    var_plus = (n - 1.0) / n * W + B / n
    return float(np.sqrt(var_plus / W))


def rank_normalized_split_rhat(chains: np.ndarray) -> float:
    """max(bulk, tail) rank-based split-R-hat: the bulk statistic on
    rank-normalized draws, the tail statistic on the rank-normalized
    folded draws ``|x - median|``."""
    chains = np.asarray(chains, np.float64)
    bulk = split_rhat(rank_normalize(chains))
    folded = np.abs(chains - np.median(chains))
    tail = split_rhat(rank_normalize(folded))
    return max(bulk, tail)


def ensemble_rhat(chains: np.ndarray) -> np.ndarray:
    """Per-parameter rank-based split-R-hat over a ``(C, n, d)`` record
    slab (the 64-chain ensemble view the driver's thinned record
    provides)."""
    chains = np.asarray(chains, np.float64)
    if chains.ndim != 3:
        raise ValueError("ensemble_rhat expects (chains, draws, params)")
    return np.asarray([rank_normalized_split_rhat(chains[:, :, j])
                       for j in range(chains.shape[2])])
