"""Structured pipeline trace spans (stdlib-only, zero-cost when off).

A process-wide recorder of nested spans with monotonic timestamps,
wired into the dispatch pipeline seams (``JaxGibbsDriver.run``,
``DispatchWatchdog``, ``serve.SamplerService``).  Disabled, every call
is a shared ``nullcontext`` / early return — the hot loop pays one
attribute load per span, no allocation, no lock.

Enabled, finished spans/instants land in an in-memory buffer that
exports to Perfetto/Chrome trace-event JSON (:func:`to_chrome`,
``chrome://tracing`` / https://ui.perfetto.dev), and optionally stream
to a ``sink`` callable — the hook ``tools/obs_probe.py`` and the serve
layer use to append ``metrics.jsonl`` span events next to the
supervisor's (span taxonomy: docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

_lock = threading.Lock()
_enabled = False
_events: list = []
_t0 = 0.0
_sink = None
_tids: dict = {}
_NULL = contextlib.nullcontext()
#: cap so a forgotten enable() cannot grow without bound (~100 bytes/ev)
MAX_EVENTS = 200_000


def enable(sink=None) -> None:
    """Start recording (clears the buffer).  ``sink``, if given, is
    called with a dict per finished span/instant — exceptions from it
    are swallowed (observability must not kill the run)."""
    global _enabled, _t0, _sink
    with _lock:
        _events.clear()
        _tids.clear()
        _t0 = time.monotonic()
        _sink = sink
        _enabled = True


def disable() -> None:
    global _enabled, _sink
    with _lock:
        _enabled = False
        _sink = None


def is_enabled() -> bool:
    return _enabled


def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        t = _tids[ident] = len(_tids) + 1
    return t


def _emit(ev: dict) -> None:
    sink = _sink
    with _lock:
        if len(_events) < MAX_EVENTS:
            _events.append(ev)
    if sink is not None:
        try:
            sink(ev)
        except Exception:
            pass


class _Span:
    __slots__ = ("name", "args", "_start")

    def __init__(self, name, args):
        self.name = name
        self.args = args

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        if not _enabled:        # disabled mid-span: drop it
            return False
        end = time.monotonic()
        _emit({"ph": "X", "name": self.name,
               "ts": (self._start - _t0) * 1e6,
               "dur": (end - self._start) * 1e6,
               "pid": os.getpid(), "tid": _tid(),
               "args": self.args})
        return False


def span(name: str, **args):
    """Context manager timing a pipeline stage.  Nesting is expressed
    by containment of the ``ts``/``dur`` intervals (Chrome 'X' complete
    events), so concurrently open spans on one thread render stacked."""
    if not _enabled:
        return _NULL
    return _Span(name, args)


def instant(name: str, **args) -> None:
    """A zero-duration marker (watchdog soft/stall events etc.)."""
    if not _enabled:
        return
    _emit({"ph": "i", "name": name, "ts": (time.monotonic() - _t0) * 1e6,
           "pid": os.getpid(), "tid": _tid(), "s": "t", "args": args})


def events() -> list:
    with _lock:
        return list(_events)


def to_chrome() -> dict:
    """The Chrome/Perfetto trace-event JSON object."""
    return {"traceEvents": events(), "displayTimeUnit": "ms"}


def write_chrome(path) -> str:
    path = os.fspath(path)
    with open(path, "w") as fh:
        json.dump(to_chrome(), fh)
    return path


def jsonl_sink(path):
    """A ``sink`` that appends one metrics.jsonl line per event, in the
    supervisor's record shape (``runtime.supervisor._log_event``)."""
    path = os.fspath(path)

    def _sink(ev):
        rec = {"ts": round(time.time(), 3), "event": "trace_span"
               if ev.get("ph") == "X" else "trace_instant",
               "name": ev["name"], **ev.get("args", {})}
        if ev.get("ph") == "X":
            rec["ms"] = round(ev["dur"] / 1e3, 3)
        with open(path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")

    return _sink
