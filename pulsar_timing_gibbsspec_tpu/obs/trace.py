"""Structured pipeline trace spans (stdlib-only, zero-cost when off).

A process-wide recorder of nested spans with monotonic timestamps,
wired into the dispatch pipeline seams (``JaxGibbsDriver.run``,
``DispatchWatchdog``, ``serve.SamplerService``).  Disabled, every call
is a shared ``nullcontext`` / early return — the hot loop pays one
attribute load per span, no allocation, no lock.

Enabled, finished spans/instants land in a bounded in-memory ring
buffer (oldest events drop first; :func:`dropped` counts the loss)
that exports to Perfetto/Chrome trace-event JSON (:func:`to_chrome`,
``chrome://tracing`` / https://ui.perfetto.dev), and optionally stream
to a ``sink`` callable — the hook ``tools/obs_probe.py`` and the serve
layer use to append ``metrics.jsonl`` span events next to the
supervisor's (span taxonomy: docs/OBSERVABILITY.md).

Separate from the buffer, *observers* (:func:`add_observer`) receive
every finished event live without buffering — the seam
``obs.perf.StageAggregator`` and ``obs.perf.FlightRecorder`` hang off.
An installed observer activates the span seams even while the buffer
is disabled, so streaming telemetry does not require (or pay for)
whole-run event retention.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

_lock = threading.Lock()
_enabled = False
#: cap so a forgotten enable() cannot grow without bound (~100 bytes/ev)
MAX_EVENTS = 200_000
_events: collections.deque = collections.deque(maxlen=MAX_EVENTS)
_dropped = 0
_t0 = 0.0
_sink = None
_observers: list = []
_tids: dict = {}
_NULL = contextlib.nullcontext()


def enable(sink=None) -> None:
    """Start recording (clears the buffer).  ``sink``, if given, is
    called with a dict per finished span/instant — exceptions from it
    are swallowed (observability must not kill the run)."""
    global _enabled, _t0, _sink, _events, _dropped
    with _lock:
        # recreate so a monkeypatched MAX_EVENTS takes effect per-enable
        _events = collections.deque(maxlen=MAX_EVENTS)
        _dropped = 0
        _tids.clear()
        _t0 = time.monotonic()
        _sink = sink
        _enabled = True


def disable() -> None:
    """Stop recording.  The buffer is kept for late export; the sink,
    if it exposes ``flush``/``close`` (``jsonl_sink`` does), is flushed
    and closed.  Observers are managed independently and stay put."""
    global _enabled, _sink
    with _lock:
        _enabled = False
        sink, _sink = _sink, None
    for meth in ("flush", "close"):
        fn = getattr(sink, meth, None)
        if fn is not None:
            try:
                fn()
            except Exception:
                pass


def is_enabled() -> bool:
    return _enabled


def add_observer(fn) -> None:
    """Register a live event observer (called with each finished
    span/instant dict, outside the buffer lock; exceptions swallowed).
    Observers keep the span seams active even when buffering is off."""
    global _t0
    with _lock:
        if not _enabled and not _observers:
            _t0 = time.monotonic()   # give observer-only events a base
        if fn not in _observers:
            _observers.append(fn)


def remove_observer(fn) -> None:
    with _lock:
        if fn in _observers:
            _observers.remove(fn)


def dropped() -> int:
    """Events lost to the ring-buffer cap since the last ``enable()``."""
    return _dropped


def _tid() -> int:
    # spans finish on the watchdog worker thread as well as the main
    # thread (the dispatch closure runs inside DispatchWatchdog.call),
    # so the id registry needs the same lock as the ring buffer
    ident = threading.get_ident()
    with _lock:
        t = _tids.get(ident)
        if t is None:
            t = _tids[ident] = len(_tids) + 1
    return t


def _emit(ev: dict) -> None:
    global _dropped
    sink = _sink
    with _lock:
        if _enabled:
            if len(_events) == _events.maxlen:
                _dropped += 1           # deque evicts the oldest event
            _events.append(ev)
        observers = list(_observers) if _observers else None
    if sink is not None:
        try:
            sink(ev)
        except Exception:
            pass
    if observers:
        for fn in observers:
            try:
                fn(ev)
            except Exception:
                pass


class _Span:
    __slots__ = ("name", "args", "_start")

    def __init__(self, name, args):
        self.name = name
        self.args = args

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        if not (_enabled or _observers):    # disabled mid-span: drop it
            return False
        end = time.monotonic()
        _emit({"ph": "X", "name": self.name,
               "ts": (self._start - _t0) * 1e6,
               "dur": (end - self._start) * 1e6,
               "pid": os.getpid(), "tid": _tid(),
               "args": self.args})
        return False


def span(name: str, **args):
    """Context manager timing a pipeline stage.  Nesting is expressed
    by containment of the ``ts``/``dur`` intervals (Chrome 'X' complete
    events), so concurrently open spans on one thread render stacked."""
    if not (_enabled or _observers):
        return _NULL
    return _Span(name, args)


def instant(name: str, **args) -> None:
    """A zero-duration marker (watchdog soft/stall events etc.)."""
    if not (_enabled or _observers):
        return
    _emit({"ph": "i", "name": name, "ts": (time.monotonic() - _t0) * 1e6,
           "pid": os.getpid(), "tid": _tid(), "s": "t", "args": args})


def events() -> list:
    with _lock:
        return list(_events)

def to_chrome() -> dict:
    """The Chrome/Perfetto trace-event JSON object.  When the ring
    buffer overflowed, a leading instant records how many events the
    timeline is missing."""
    evs = events()
    if _dropped:
        evs.insert(0, {"ph": "i", "name": "trace.ring_dropped",
                       "ts": 0.0, "pid": os.getpid(), "tid": 0, "s": "g",
                       "args": {"dropped": _dropped,
                                "cap": MAX_EVENTS}})
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def write_chrome(path) -> str:
    path = os.fspath(path)
    with open(path, "w") as fh:
        json.dump(to_chrome(), fh)
    return path


def jsonl_sink(path):
    """A ``sink`` that appends one metrics.jsonl line per event, in the
    supervisor's record shape (``runtime.supervisor._log_event``).
    Keeps one file handle open (line-buffered); ``disable()`` calls the
    attached ``flush``/``close``."""
    path = os.fspath(path)
    fh = open(path, "a", buffering=1)

    def _sink(ev):
        rec = {"ts": round(time.time(), 3), "event": "trace_span"
               if ev.get("ph") == "X" else "trace_instant",
               "name": ev["name"], **ev.get("args", {})}
        if ev.get("ph") == "X":
            rec["ms"] = round(ev["dur"] / 1e3, 3)
        fh.write(json.dumps(rec) + "\n")

    _sink.flush = fh.flush
    _sink.close = fh.close
    return _sink
