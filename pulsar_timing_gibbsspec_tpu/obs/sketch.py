"""Streaming diagnostic sketches computed inside the compiled chunk.

The driver's record transfer is the sweep-wall bottleneck (ROADMAP
item 4), so convergence diagnostics must not depend on shipping raw
chains: this module accumulates, ON DEVICE, everything the host needs
to finalize mean/variance, small-k cross-covariance, a Sokal-windowed
ACT/ESS per chain and channel, per-block move rates, and a
moment-based split-R-hat — as a state pytree of fixed, tiny shapes
carried from chunk to chunk.

The sketch reads only the chunk's full-precision pre-thinning state
stack ``xs`` (and the chunk-entry state, for the first transition), it
consumes no PRNG keys, and it writes nothing back into the sweep carry
— so an instrumented chunk is **bitwise identical in its sampling
outputs** to an uninstrumented one
(tests/test_obs.py::test_instrumented_chunk_bitwise_identical).

Estimators (exact streaming identities, not approximations, except
where noted):

- moments: Chan et al. pairwise update of ``(n, mean, M2)`` per
  (chain, channel), plus the matching co-moment update for the first
  ``cross_k`` channels;
- ACF: raw lagged-product sums ``S_l = sum_{t=l}^{n-1} x_t x_{t-l}``
  via an ``L``-sample tail window concatenated onto each chunk (the
  zero-initialized pre-stream tail contributes exactly 0 to every
  product, so ``S_l`` is exact with pair count ``n - l``).  The host
  turns these into autocovariances with the plug-in mean,
  ``gamma_l = S_l/(n-l) - mean^2`` — the one place a full two-pass
  estimator is not reproduced exactly (the plug-in mean is the
  full-stream mean rather than per-lag window means; the difference is
  O(tau/n), far inside the 10% parity budget the acceptance pins);
- move rates: per transition and block group, the mean over the
  group's parameters of a changed-value indicator — the same movement
  proxy ``runtime.sentinels.chunk_health`` uses, summed per group so
  the host can report per-block acceptance-style rates (exact MH
  accept indicators are discarded inside the fused sweep bodies;
  movement is the observable proxy, and for the MH blocks a proposal
  that moves IS an acceptance).
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: channel cap: diagnostics follow the science-critical blocks first
#: (common rho, then hypers); a cap keeps the sketch state and the
#: per-chunk update cost O(C * channels * lags), independent of nx.
DEFAULT_CHANNELS = 32
DEFAULT_CROSS = 8
DEFAULT_LAGS = 64


@dataclasses.dataclass(frozen=True)
class SketchSpec:
    """Static description of what the device sketch tracks.

    ``channels`` are positions into the flat state vector ``x``;
    ``groups`` are the Gibbs block index arrays the move-rate sums are
    computed over (only non-empty blocks appear).
    """

    channels: np.ndarray        # (D,) int32 -> x
    names: tuple                # (D,) parameter names of the channels
    cross_k: int                # leading channels with full cross-cov
    lags: int                   # L, ACF window length
    groups: tuple               # ((name, (g,) int32 -> x), ...)

    @property
    def D(self) -> int:
        return int(self.channels.shape[0])

    @property
    def G(self) -> int:
        return len(self.groups)


def make_sketch_spec(cm, channels: int = DEFAULT_CHANNELS,
                     cross: int = DEFAULT_CROSS,
                     lags: int = DEFAULT_LAGS) -> SketchSpec:
    """Build the diagnostic channel selection from a CompiledPTA.

    Channel priority mirrors what the bench reports on: the common
    free-spectrum rho block first (the slow direction — ACT ~45 vs b's
    ~2, docs/ACT_TABLE.md), then red/ORF hypers, then white/ECORR,
    truncated at ``channels``.
    """
    idx = cm.idx
    order, seen = [], set()
    for block in (idx.rho, idx.red, idx.orf, idx.red_rho, idx.white,
                  idx.ecorr):
        for i in np.asarray(block).ravel():
            i = int(i)
            if i not in seen:
                seen.add(i)
                order.append(i)
    if not order:
        # a model with no recognized block still gets *some* channels
        order = list(range(min(int(channels), int(cm.nx))))
    ch = np.asarray(order[: int(channels)], dtype=np.int32)
    names = tuple(cm.param_names[i] for i in ch)
    groups = tuple(
        (nm, np.asarray(g, dtype=np.int32))
        for nm, g in (("rho", idx.rho), ("red", idx.red),
                      ("red_rho", idx.red_rho), ("white", idx.white),
                      ("ecorr", idx.ecorr), ("orf", idx.orf))
        if len(np.asarray(g)))
    return SketchSpec(channels=ch, names=names,
                      cross_k=min(int(cross), len(ch)), lags=int(lags),
                      groups=groups)


def init_state(spec: SketchSpec, nchains: int):
    """Zero sketch state (a dict pytree of f64 device arrays).

    The zero tail window is load-bearing: lagged products against the
    pre-stream zeros vanish, so ``S_l`` needs no special-casing at the
    stream head.
    """
    import jax.numpy as jnp

    C, D, L, Kc, G = (int(nchains), spec.D, spec.lags, spec.cross_k,
                      spec.G)
    f8 = jnp.float64
    return {
        "n": jnp.zeros((), f8),
        "mean": jnp.zeros((C, D), f8),
        "m2": jnp.zeros((C, D), f8),
        "cross": jnp.zeros((C, Kc, Kc), f8),
        "lag": jnp.zeros((C, D, L), f8),
        "tail": jnp.zeros((C, D, L), f8),
        "move": jnp.zeros((C, G), f8),
        "moven": jnp.zeros((), f8),
    }


def state_bytes(spec: SketchSpec, nchains: int) -> int:
    """Size of the summary slab — the ONLY extra device output an
    instrumented chunk produces (pinned by contracts/obs_quick.json)."""
    C, D, L, Kc, G = (int(nchains), spec.D, spec.lags, spec.cross_k,
                      spec.G)
    return 8 * (1 + C * D * 2 + C * Kc * Kc + C * D * L * 2 + C * G + 1)


def update(spec: SketchSpec, state, x0, xs):
    """Fold one chunk's state stack into the sketch (traced, jit-safe).

    ``x0`` is the chunk-entry state ``(C, nx)`` (first move transition),
    ``xs`` the full pre-thinning per-sweep stack ``(n, C, nx)`` in the
    compute dtype.  Returns the updated state pytree; everything is
    O(C * D * (n + L)) — no term scales with nx beyond the two gathers.
    """
    import jax
    import jax.numpy as jnp

    nc = int(xs.shape[0])
    ch = jnp.asarray(spec.channels, jnp.int32)
    z = jnp.moveaxis(xs[:, :, ch].astype(jnp.float64), 0, -1)  # (C, D, n)

    na = state["n"]
    nb = jnp.asarray(float(nc), jnp.float64)
    tot = na + nb

    # Chan pairwise merge of (n, mean, M2); exact for na == 0 too
    cmean = jnp.mean(z, axis=-1)                               # (C, D)
    cm2 = jnp.sum((z - cmean[..., None]) ** 2, axis=-1)
    delta = cmean - state["mean"]
    mean = state["mean"] + delta * (nb / tot)
    m2 = state["m2"] + cm2 + delta**2 * (na * nb / tot)

    # co-moment merge over the leading cross_k channels
    Kc = spec.cross_k
    zk = z[:, :Kc]
    ckm = cmean[:, :Kc]
    zc = zk - ckm[..., None]
    ccov = jnp.einsum("cin,cjn->cij", zc, zc)
    dk = ckm - state["mean"][:, :Kc]
    cross = (state["cross"] + ccov
             + dk[:, :, None] * dk[:, None, :] * (na * nb / tot))

    # one-pass lagged-product sums across the chunk boundary: the tail
    # window makes every cross-boundary pair available exactly once
    L = spec.lags
    ext = jnp.concatenate([state["tail"], z], axis=-1)         # (C, D, L+n)
    cur = ext[..., L:]

    def lag_body(_, lag):
        seg = jax.lax.dynamic_slice_in_dim(ext, L - lag, nc, axis=-1)
        return None, jnp.sum(cur * seg, axis=-1)

    _, lsum = jax.lax.scan(lag_body, None, jnp.arange(L))      # (L, C, D)
    lag = state["lag"] + jnp.moveaxis(lsum, 0, -1)
    tail = ext[..., -L:]

    # per-block move fractions over the chunk's n transitions
    full = jnp.concatenate([x0[None], xs], axis=0)             # (n+1, C, nx)
    changed = full[1:] != full[:-1]                            # (n, C, nx)
    gmoves = [
        jnp.sum(jnp.mean(
            changed[:, :, jnp.asarray(gi, jnp.int32)].astype(jnp.float64),
            axis=-1), axis=0)
        for _, gi in spec.groups]                              # each (C,)
    move = state["move"] + (jnp.stack(gmoves, axis=-1) if gmoves
                            else jnp.zeros_like(state["move"]))

    return {"n": tot, "mean": mean, "m2": m2, "cross": cross,
            "lag": lag, "tail": tail, "move": move,
            "moven": state["moven"] + nb}
