"""Device-mesh parallelism for the multi-pulsar sweep.

The scaling axis of this problem is **pulsars** (SURVEY §2.3): the 45-pulsar
array is embarrassingly parallel except for one collective — the common
free-spectrum conditional, where per-pulsar log-PDF grids are summed across
the array (the reference's serial PDF product at ``pta_gibbs.py:205``).
Sharding the pulsar axis of the compiled model over a ``jax.sharding.Mesh``
turns every cross-pulsar ``jnp.sum`` in the sweep into an XLA all-reduce
over ICI; no other communication exists in the algorithm.
"""

from .sharding import (make_mesh, pulsar_sharding, replicated_sharding,
                       shard_compiled)

__all__ = ["make_mesh", "pulsar_sharding", "replicated_sharding",
           "shard_compiled"]
