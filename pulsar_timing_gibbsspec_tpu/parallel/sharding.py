"""Pulsar-axis sharding of the compiled model over a device mesh.

Design (the "How to Scale Your Model" recipe): pick a mesh, annotate the
shardings of the *data*, and let XLA insert the collectives.  Every array in
:class:`~..sampler.compiled.CompiledPTA` with a leading pulsar axis is
placed with ``NamedSharding(mesh, P('pulsar', ...))``; everything else
(the parameter vector, priors, constant pool) is replicated.  The jitted
sweep kernels in ``sampler/jax_backend.py`` close over these arrays, so
GSPMD propagates the sharding through the whole sweep:

- per-pulsar work (Nvec, phi, TNT/d einsums, batched Cholesky b-draw) runs
  fully local to each device's pulsar shard,
- the cross-pulsar reductions (`jnp.sum` over the pulsar axis in the white
  likelihood and in the common-rho log-PDF grid, reference
  ``pta_gibbs.py:205``) lower to a single all-reduce each over ICI,
- parameter updates stay replicated (x is tiny).

``compile_pta(pad_pulsars=...)`` provides inert dummy pulsars so 45 divides
the mesh; see the padding conventions in ``sampler/compiled.py``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..sampler.compiled import CompiledPTA, GPComponent

#: CompiledPTA array fields whose leading axis is the pulsar axis
_PULSAR_FIELDS = (
    "y", "T", "toa_mask", "basis_mask", "psr_mask", "sigma2",
    "efac_ix", "equad_ix", "gequad_ix", "phi_base", "gp_mask",
    "gw_sin_ix", "gw_cos_ix", "gw_f", "gw_df", "gw_hyp_ix", "gw_rho_ix",
    "red_valid", "red_hyp_ix", "red_rho_ix", "red_rho_ix_x",
    "red_sin_ix", "red_cos_ix",
    "ec_cols", "ec_ix", "ke_eid", "ke_par_ix",
    "white_par_ix", "white_nper", "ecorr_par_ix", "ecorr_nper",
)
#: replicated small arrays
_REPLICATED_FIELDS = ("const_pool", "pkind", "pa", "pb", "prop_scale",
                      "rho_ix_x")


def make_mesh(n_devices: int | None = None, axis: str = "pulsar"):
    """A 1-d device mesh over the first ``n_devices`` devices (all by
    default).  Raises if fewer than ``n_devices`` devices exist — an
    under-provisioned mesh would silently drop the sharding it is supposed
    to exercise.  Multi-host extension: pass the global device list order so
    the pulsar axis rides ICI within each slice before spanning DCN."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"make_mesh({n_devices}) but only {len(devs)} "
                f"{devs[0].platform if devs else '?'} device(s) are "
                "available; refusing to build a truncated mesh. For a "
                "hardware-free run force the CPU backend with "
                "jax.config.update('jax_platforms', 'cpu') and "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "before backend init.")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def mesh_layout(mesh):
    """JSON-serializable description of a mesh placement.

    Recorded in the checkpoint manifest's ``shard_map`` section — the
    PHYSICAL half of the layout split: logical layout (chain/pulsar
    order, padded pulsar width, per-chain key folding) lives in the
    manifest's ``layout`` section and pins the sampled process, while
    this record is advisory — ``integrity.reshard_restore`` may rebuild
    the mesh with any device count that divides the padded width."""
    if mesh is None:
        return None
    devs = mesh.devices.ravel()
    return {"devices": int(devs.size),
            "axis": str(mesh.axis_names[0]),
            "platform": str(devs[0].platform) if devs.size else "?"}


def pulsar_sharding(mesh, ndim: int):
    """NamedSharding that splits axis 0 over the mesh's pulsar axis and
    replicates the rest."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_compiled(cm: CompiledPTA, mesh) -> CompiledPTA:
    """Place every CompiledPTA array on the mesh: pulsar-axis arrays split,
    the rest replicated.  Returns a new CompiledPTA whose arrays are
    committed ``jax.Array``s; jitted kernels closing over them inherit the
    placement."""
    import jax

    n = mesh.devices.size
    if cm.P % n:
        raise ValueError(
            f"pulsar axis ({cm.P}) does not divide the mesh ({n} devices); "
            f"compile with pad_pulsars={-(-cm.P // n) * n}")
    repl = replicated_sharding(mesh)
    updates = {}
    for name in _PULSAR_FIELDS:
        arr = getattr(cm, name)
        if arr is None:          # mode-gated fields (e.g. kernel ECORR off)
            continue
        arr = np.asarray(arr)
        updates[name] = jax.device_put(arr, pulsar_sharding(mesh, arr.ndim))
    for name in _REPLICATED_FIELDS:
        updates[name] = jax.device_put(np.asarray(getattr(cm, name)), repl)
    comps = []
    for c in cm.components:
        comps.append(GPComponent(
            kind=c.kind,
            cols=jax.device_put(np.asarray(c.cols), pulsar_sharding(mesh, 2)),
            f=jax.device_put(np.asarray(c.f), pulsar_sharding(mesh, 2)),
            df=jax.device_put(np.asarray(c.df), pulsar_sharding(mesh, 2)),
            hyp_ix=jax.device_put(np.asarray(c.hyp_ix),
                                  pulsar_sharding(mesh, 2)),
            rho_ix=jax.device_put(np.asarray(c.rho_ix),
                                  pulsar_sharding(mesh, 2)),
        ))
    updates["components"] = comps
    return dataclasses.replace(cm, **updates)


def collective_report(fn, *example_args, max_gather_elems=None):
    """Count the cross-device collectives XLA inserted into ``fn``'s
    optimized HLO — the regression instrument behind the MULTICHIP
    collective budget (``__graft_entry__`` asserts the sweep holds
    {all-reduce, all-gather} constant and that no gather moves a
    basis-sized operand).

    Returns ``{"all-reduce": n, "all-gather": n, "gather_elems": [...]}``
    where ``gather_elems`` lists each all-gather's operand element count
    (shape product).  ``max_gather_elems`` raises if any gather exceeds
    it — the guard that keeps "shard the pulsar axis, replicate x" honest:
    per-pulsar work must never round-trip a basis-sized array.

    Note on the structured correlated-ORF joint b-draw
    (``sampler/jax_backend.draw_b_joint_structured``): its Schur stage
    contracts the per-pulsar (2K, B) panels into (2K, 2K) grids of (P, P)
    blocks, so under pulsar-axis sharding the only new cross-device
    movement is the gather of those P-by-P Schur blocks — P*(2K)^2
    elements, the same order as the existing rho-grid reductions and far
    below any basis-sized operand — and the per-pulsar stage stays fully
    local.  The MULTICHIP budget ({'all-reduce': 5, 'all-gather': 3} at
    r05) is measured on the CRN sweep, which never enters the joint draw.
    """
    # counting core absorbed into analysis.jaxprcheck.collectives (the
    # C2 census contract): one set of regexes serves both this ad-hoc
    # probe and the committed-contract gate
    from ..analysis.jaxprcheck.collectives import census

    return census(fn, *example_args, max_gather_elems=max_gather_elems)
