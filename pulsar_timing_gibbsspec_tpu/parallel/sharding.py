"""Pulsar-axis sharding of the compiled model over a device mesh.

Design (the "How to Scale Your Model" recipe): pick a mesh, annotate the
shardings of the *data*, and let XLA insert the collectives.  Every array in
:class:`~..sampler.compiled.CompiledPTA` with a leading pulsar axis is
placed with ``NamedSharding(mesh, P('pulsar', ...))``; everything else
(the parameter vector, priors, constant pool) is replicated.  The jitted
sweep kernels in ``sampler/jax_backend.py`` close over these arrays, so
GSPMD propagates the sharding through the whole sweep:

- per-pulsar work (Nvec, phi, TNT/d einsums, batched Cholesky b-draw) runs
  fully local to each device's pulsar shard,
- the cross-pulsar reductions (`jnp.sum` over the pulsar axis in the white
  likelihood and in the common-rho log-PDF grid, reference
  ``pta_gibbs.py:205``) lower to a single all-reduce each over ICI,
- parameter updates stay replicated (x is tiny).

``compile_pta(pad_pulsars=...)`` provides inert dummy pulsars so 45 divides
the mesh; see the padding conventions in ``sampler/compiled.py``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..sampler.compiled import CompiledPTA, GPComponent

#: CompiledPTA array fields whose leading axis is the pulsar axis
_PULSAR_FIELDS = (
    "y", "T", "toa_mask", "basis_mask", "psr_mask", "sigma2",
    "efac_ix", "equad_ix", "gequad_ix", "phi_base", "gp_mask",
    "gw_sin_ix", "gw_cos_ix", "gw_f", "gw_df", "gw_hyp_ix", "gw_rho_ix",
    "red_valid", "red_hyp_ix", "red_rho_ix", "red_rho_ix_x",
    "red_sin_ix", "red_cos_ix",
    "ec_cols", "ec_ix", "ke_eid", "ke_par_ix",
    "white_par_ix", "white_nper", "ecorr_par_ix", "ecorr_nper",
)
#: replicated small arrays
_REPLICATED_FIELDS = ("const_pool", "pkind", "pa", "pb", "prop_scale",
                      "rho_ix_x")


def make_mesh(n_devices=None, axis: str = "pulsar"):
    """A device mesh: 1-d over the pulsar axis, or 2-d ``(chain, pulsar)``.

    ``n_devices`` is an int (or None = all devices) for the classic 1-d
    pulsar mesh, or a 2-tuple ``(n_chain_devs, n_pulsar_devs)`` for the
    2-d mesh — chains are embarrassingly parallel (independent Gibbs
    processes, per-chain fold_in key streams), so the chain axis carries
    ZERO collectives by construction and the one common-rho all-reduce
    stays the only pulsar-axis traffic.  Raises if fewer devices exist
    than the mesh needs — an under-provisioned mesh would silently drop
    the sharding it is supposed to exercise.  Multi-host extension: pass
    the global device list order so the pulsar axis rides ICI within
    each slice before spanning DCN (the chain axis, collective-free,
    tolerates DCN)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()

    def _need(n):
        if len(devs) < n:
            raise RuntimeError(
                f"make_mesh({n_devices}) but only {len(devs)} "
                f"{devs[0].platform if devs else '?'} device(s) are "
                "available; refusing to build a truncated mesh. For a "
                "hardware-free run force the CPU backend with "
                "jax.config.update('jax_platforms', 'cpu') and "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "before backend init.")

    if isinstance(n_devices, (tuple, list, np.ndarray)):
        shape = tuple(int(s) for s in n_devices)
        if len(shape) != 2 or any(s < 1 for s in shape):
            raise ValueError(
                f"make_mesh expects (n_chain_devs, n_pulsar_devs), "
                f"got {n_devices!r}")
        _need(shape[0] * shape[1])
        grid = np.asarray(devs[:shape[0] * shape[1]]).reshape(shape)
        return Mesh(grid, ("chain", axis))
    if n_devices is not None:
        _need(int(n_devices))
        devs = devs[:int(n_devices)]
    return Mesh(np.asarray(devs), (axis,))


def pulsar_submesh_size(mesh) -> int:
    """Devices along the mesh's pulsar axis (the LAST axis: the whole
    mesh for the 1-d layout, ``shape[1]`` for ``(chain, pulsar)``)."""
    return int(mesh.devices.shape[-1])


def chain_submesh_size(mesh) -> int:
    """Devices along the mesh's chain axis; 1 when the mesh has none
    (the 1-d pulsar layout replicates the chain axis)."""
    if mesh is None or "chain" not in mesh.axis_names:
        return 1
    return int(mesh.devices.shape[list(mesh.axis_names).index("chain")])


def chain_slice(mesh, lo: int, hi: int):
    """Carve chain-axis rows ``[lo, hi)`` of a 2-d ``(chain, pulsar)``
    mesh into a standalone submesh — the slice-carving primitive of the
    serving placement engine.  The carved mesh keeps the parent's axis
    names and pulsar width, so every sharding helper above applies
    unchanged; chains are collective-free by construction (measured,
    ``crn_2d_mesh``), so programs on disjoint slices share no devices
    and no collectives: each slice is an isolated fault domain."""
    from jax.sharding import Mesh

    if mesh is None:
        return None
    if "chain" not in mesh.axis_names:
        raise ValueError(
            "chain_slice needs a 2-d (chain, pulsar) mesh; got axes "
            f"{tuple(mesh.axis_names)} — build one with "
            "make_mesh((n_chain, n_pulsar))")
    nc = chain_submesh_size(mesh)
    lo, hi = int(lo), int(hi)
    if not 0 <= lo < hi <= nc:
        raise ValueError(
            f"chain_slice rows [{lo}, {hi}) fall outside the mesh's "
            f"chain axis ({nc} rows, mesh {tuple(mesh.devices.shape)})")
    return Mesh(mesh.devices[lo:hi], mesh.axis_names)


def carve_chain_slices(mesh, spans):
    """Carve consecutive chain-row spans (an iterable of row counts)
    into disjoint submeshes, in order from row 0.  Raises when the
    spans overrun the chain axis; leftover rows stay uncarved (spare
    capacity for rebalancing)."""
    out = []
    lo = 0
    nc = chain_submesh_size(mesh)
    for c in spans:
        c = int(c)
        if lo + c > nc:
            raise ValueError(
                f"carve_chain_slices: spans {list(spans)} need "
                f"{lo + c} chain rows but the mesh has {nc}")
        out.append(chain_slice(mesh, lo, lo + c))
        lo += c
    return out


def mesh_layout(mesh):
    """JSON-serializable description of a mesh placement.

    Recorded in the checkpoint manifest's ``shard_map`` section — the
    PHYSICAL half of the layout split: logical layout (chain/pulsar
    order, padded pulsar width, per-chain key folding) lives in the
    manifest's ``layout`` section and pins the sampled process, while
    this record is advisory — ``integrity.reshard_restore`` may rebuild
    the mesh with any axis shape whose pulsar size divides the padded
    width and whose chain size divides the chain count.  ``axes`` lists
    ``[name, size]`` per mesh axis in order (the 2-d record); ``axis``
    stays the pulsar axis name for back-compat readers."""
    if mesh is None:
        return None
    devs = mesh.devices.ravel()
    return {"devices": int(devs.size),
            "axis": str(mesh.axis_names[-1]),
            "axes": [[str(n), int(s)]
                     for n, s in zip(mesh.axis_names, mesh.devices.shape)],
            "platform": str(devs[0].platform) if devs.size else "?"}


def pulsar_sharding(mesh, ndim: int):
    """NamedSharding that splits axis 0 over the mesh's pulsar axis
    (always the LAST mesh axis) and replicates the rest — including,
    on a 2-d mesh, replication across the chain axis (every chain
    submesh row holds the full pulsar shard set)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[-1]
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def chain_sharding(mesh, ndim: int):
    """NamedSharding that splits axis 0 over the mesh's chain axis and
    replicates the rest (pulsar axis included: the sweep carry is tiny
    per chain, and per-pulsar kernels reslice it locally).  On a mesh
    without a chain axis this degrades to full replication, so callers
    can apply it unconditionally."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if "chain" not in mesh.axis_names:
        return replicated_sharding(mesh)
    return NamedSharding(mesh, P("chain", *([None] * (ndim - 1))))


def validate_chains(mesh, nchains: int):
    """Raise unless ``nchains`` splits evenly over the mesh's chain
    axis — an uneven split would give GSPMD a ragged chain shard and
    every ``(C, ...)`` carry a padded ghost chain whose rows never
    reach the chain files.  Actionable by construction: says which
    knob to turn."""
    nc = chain_submesh_size(mesh)
    if nc > 1 and int(nchains) % nc:
        raise ValueError(
            f"nchains={int(nchains)} does not divide over the mesh's "
            f"chain axis ({nc} devices, mesh "
            f"{tuple(mesh.devices.shape)}); pass nchains as a multiple "
            f"of {nc} (e.g. nchains={-(-int(nchains) // nc) * nc}) or "
            f"shrink the chain axis with make_mesh((n_chain, n_pulsar))")


def shard_carry(mesh, tree, nchains: int):
    """Place a sweep-carry pytree on the mesh's chain axis.

    Every array leaf whose leading axis equals ``nchains`` (the chain
    carries: x, b, record slabs, adaptation state, obs sketch) is
    committed with :func:`chain_sharding`; other array leaves are
    replicated.  A None mesh or a mesh without a chain axis returns the
    tree untouched — the 1-d pulsar layout keeps its existing placement
    (carries replicated, GSPMD decides)."""
    if mesh is None or "chain" not in mesh.axis_names:
        return tree
    import jax

    repl = replicated_sharding(mesh)

    def _place(leaf):
        nd = getattr(leaf, "ndim", None)
        if nd is None:
            return leaf
        if nd >= 1 and leaf.shape[0] == int(nchains):
            return jax.device_put(leaf, chain_sharding(mesh, nd))
        return jax.device_put(leaf, repl)

    return jax.tree_util.tree_map(_place, tree)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def shard_compiled(cm: CompiledPTA, mesh) -> CompiledPTA:
    """Place every CompiledPTA array on the mesh: pulsar-axis arrays split,
    the rest replicated.  Returns a new CompiledPTA whose arrays are
    committed ``jax.Array``s; jitted kernels closing over them inherit the
    placement."""
    import jax

    n = pulsar_submesh_size(mesh)
    if cm.P % n:
        # suggest padding for the PULSAR submesh, not the total device
        # count: on a (chain, pulsar) mesh only the last axis splits
        # the pulsar arrays
        total = int(mesh.devices.size)
        where = (f"the pulsar submesh ({n} of {total} devices, mesh "
                 f"{tuple(mesh.devices.shape)})" if total != n
                 else f"the mesh ({n} devices)")
        raise ValueError(
            f"pulsar axis ({cm.P}) does not divide {where}; "
            f"compile with pad_pulsars={-(-cm.P // n) * n}")
    repl = replicated_sharding(mesh)
    updates = {}
    for name in _PULSAR_FIELDS:
        arr = getattr(cm, name)
        if arr is None:          # mode-gated fields (e.g. kernel ECORR off)
            continue
        arr = np.asarray(arr)
        updates[name] = jax.device_put(arr, pulsar_sharding(mesh, arr.ndim))
    for name in _REPLICATED_FIELDS:
        updates[name] = jax.device_put(np.asarray(getattr(cm, name)), repl)
    comps = []
    for c in cm.components:
        comps.append(GPComponent(
            kind=c.kind,
            cols=jax.device_put(np.asarray(c.cols), pulsar_sharding(mesh, 2)),
            f=jax.device_put(np.asarray(c.f), pulsar_sharding(mesh, 2)),
            df=jax.device_put(np.asarray(c.df), pulsar_sharding(mesh, 2)),
            hyp_ix=jax.device_put(np.asarray(c.hyp_ix),
                                  pulsar_sharding(mesh, 2)),
            rho_ix=jax.device_put(np.asarray(c.rho_ix),
                                  pulsar_sharding(mesh, 2)),
        ))
    updates["components"] = comps
    return dataclasses.replace(cm, **updates)


def collective_report(fn, *example_args, max_gather_elems=None):
    """Count the cross-device collectives XLA inserted into ``fn``'s
    optimized HLO — the regression instrument behind the MULTICHIP
    collective budget (``__graft_entry__`` asserts the sweep holds
    {all-reduce, all-gather} constant and that no gather moves a
    basis-sized operand).

    Returns ``{"all-reduce": n, "all-gather": n, "gather_elems": [...]}``
    where ``gather_elems`` lists each all-gather's operand element count
    (shape product).  ``max_gather_elems`` raises if any gather exceeds
    it — the guard that keeps "shard the pulsar axis, replicate x" honest:
    per-pulsar work must never round-trip a basis-sized array.

    Note on the structured correlated-ORF joint b-draw
    (``sampler/jax_backend.draw_b_joint_structured``): its Schur stage
    contracts the per-pulsar (2K, B) panels into (2K, 2K) grids of (P, P)
    blocks, so under pulsar-axis sharding the only new cross-device
    movement is the gather of those P-by-P Schur blocks — P*(2K)^2
    elements, the same order as the existing rho-grid reductions and far
    below any basis-sized operand — and the per-pulsar stage stays fully
    local.  The MULTICHIP budget ({'all-reduce': 5, 'all-gather': 3} at
    r05) is measured on the CRN sweep, which never enters the joint draw.
    """
    # counting core absorbed into analysis.jaxprcheck.collectives (the
    # C2 census contract): one set of regexes serves both this ad-hoc
    # probe and the committed-contract gate
    from ..analysis.jaxprcheck.collectives import census

    return census(fn, *example_args, max_gather_elems=max_gather_elems)
