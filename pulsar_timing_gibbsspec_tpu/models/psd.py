"""Power spectral density models for rank-reduced Fourier GPs.

Each function maps per-column frequencies ``f`` (each frequency repeated for
its sin/cos pair, see ``data/fourier.py``) plus hyperparameters to the
per-coefficient prior variance ``phi`` [s^2].  These cover the PSD menu of
the reference's ``model_general`` (``model_definition.py:63-65``:
'powerlaw', 'spectrum', 'turnover', 'turnover_knee', 'broken_powerlaw', and
'infinitepower' for marginalization).  Conventions follow the standard PTA
definitions (as in enterprise ``utils``): amplitudes at ``f_yr = 1/yr``,
``phi(f) = hc(f)^2 / (12 pi^2 f^3) * df``.

All functions are plain ``numpy``-style expressions valid under ``jax.numpy``
tracing — the device backend calls them with ``jnp`` arrays inside jit.
"""

from __future__ import annotations

import numpy as np

DAY = 86400.0
YEAR = 365.25 * DAY
FYR = 1.0 / YEAR


def powerlaw(f, df, log10_A, gamma):
    A = 10.0 ** log10_A
    return (A**2 / (12.0 * np.pi**2)) * FYR ** (gamma - 3.0) * f ** (-gamma) * df


def free_spectrum(f, df, log10_rho):
    """phi_j = rho_j^2 directly per frequency; ``log10_rho`` has one entry
    per frequency and is repeated over the sin/cos pair (enterprise
    ``free_spectrum``; the Gibbs rho draw writes ``0.5*log10(rho_var)`` back
    into these parameters, reference ``pulsar_gibbs.py:236``)."""
    xp = np
    if not isinstance(log10_rho, np.ndarray):
        import jax.numpy as xp  # noqa: F811 — traced path
    return xp.repeat(10.0 ** (2.0 * xp.asarray(log10_rho)), 2)


def turnover(f, df, log10_A, gamma, lf0=-8.5, kappa=10.0 / 3.0, beta=0.5):
    A = 10.0 ** log10_A
    hcf = A * (f / FYR) ** ((3.0 - gamma) / 2.0) / (1.0 + (10.0**lf0 / f) ** kappa) ** beta
    return hcf**2 / (12.0 * np.pi**2) / f**3 * df


def broken_powerlaw(f, df, log10_A, gamma, delta=0.0, log10_fb=-8.5, kappa=0.1):
    A = 10.0 ** log10_A
    fb = 10.0 ** log10_fb
    hcf = (A * (f / FYR) ** ((3.0 - gamma) / 2.0)
           * (1.0 + (f / fb) ** (1.0 / kappa)) ** (kappa * (gamma - delta) / 2.0))
    return hcf**2 / (12.0 * np.pi**2) / f**3 * df


def turnover_knee(f, df, log10_A, gamma, lfb=-8.5, lfk=-8.0, kappa=10.0 / 3.0, delta=0.1):
    A = 10.0 ** log10_A
    hcf = (A * (f / FYR) ** ((3.0 - gamma) / 2.0)
           * (1.0 + (f / 10.0**lfk) ** delta)
           / (1.0 + (10.0**lfb / f) ** kappa) ** 0.5)
    return hcf**2 / (12.0 * np.pi**2) / f**3 * df


def powerlaw_breakflat(f, df, log10_A, gamma, log10_fb):
    """Powerlaw whose PSD flattens (P(f) = P(fb)) above the break frequency
    ``fb`` — the reference ``model_general`` kwargs ``red_breakflat`` /
    ``red_breakflat_fq`` (``model_definition.py:115-118``)."""
    fb = 10.0 ** log10_fb
    feff = np.minimum(f, fb)
    A = 10.0 ** log10_A
    return (A**2 / (12.0 * np.pi**2)) * FYR ** (gamma - 3.0) * feff ** (-gamma) * df


def infinitepower(f, df):
    """Effectively-unconstrained prior variance for marginalized bases
    (timing model); kept in log space device-side to stay f32-safe."""
    return np.full_like(np.asarray(f, dtype=np.float64), 1e40)


def tprocess(f, df, log10_A, gamma, alphas):
    """t-process: powerlaw scaled per frequency by inverse-gamma-distributed
    ``alphas`` (enterprise_extensions ``t_process``; the reference advertises
    it in the ``red_psd`` menu, ``model_definition.py:103-105``).  Each
    frequency's marginal coefficient prior becomes Student-t, robustifying
    the powerlaw against single-bin outliers.  ``alphas`` has one entry per
    frequency, repeated over the sin/cos pair."""
    xp = np
    if not isinstance(alphas, np.ndarray):
        import jax.numpy as xp  # noqa: F811 — traced path
    return powerlaw(f, df, log10_A, gamma) * xp.repeat(xp.asarray(alphas), 2)
