"""Kwarg-driven PTA model factory.

Mirrors the configuration surface of the reference's ``model_general``
(``model_definition.py:18-236``) — the de-facto config schema of the whole
stack (SURVEY §5) — with the reference's exact kwarg names.  Supported
natively:

- linear timing model with ``tm_svd`` / ``tm_norm`` / ``tm_marg``
- common red-noise block(s): ``common_psd`` in {powerlaw, spectrum,
  turnover, turnover_knee, broken_powerlaw}, multiple comma-separated ORFs
  (``orf``/``orf_names``), fixed or varied amplitude/index, custom rho
  bounds (``common_logmin/logmax``), ``common_components``, random phase
  shifts (``pshift``/``pseed``), custom bin weights (``wgts``)
- per-pulsar intrinsic red noise: ``red_var``, ``red_psd`` (powerlaw,
  spectrum, or infinitepower), ``red_components``, band/backend-split red
  processes (``red_select``), flattened high-frequency spectrum
  (``red_breakflat``/``red_breakflat_fq``) — note the reference's committed
  ``model_general`` accepts these kwargs but never adds the block (its
  notebooks hand-build it); here the advertised behavior is implemented
- white noise: ``white_vary``, per-backend EFAC/EQUAD via
  ``select='backend'``, fixed values via ``noisedict``, global EQUAD via
  ``gequad``; ``is_wideband`` excludes ECORR exactly as the reference does
- chromatic GPs: ``dm_var`` (nu^-2 dispersion-measure GP) and ``dm_chrom``
  (nu^-dmchrom_idx scattering GP), powerlaw PSDs, own basis columns;
  ``dm_annual`` as a *marginalized* linearized annual DM sinusoid (two
  nu^-2 sin/cos columns with improper prior — the same 2-d subspace the
  reference's sampled amplitude/phase parameterizes, with no extra
  sampling block)
- ``bayesephem``/``be_type``: physical solar-system-ephemeris error model
  as a marginalized 11-column basis (see ``models/ephem.py`` for the
  documented approximations vs enterprise's file-based partials)
- ECORR (basis) for pulsars carrying a NANOGrav pta flag, as in
  ``model_definition.py:221-223``
- ``Tspan``/``modes``/``logfreq`` frequency-grid control, upper-limit
  (LinearExp) amplitude priors per process class (``upper_limit``,
  ``upper_limit_common/red/dm``)

``coefficients`` and ``dense_like`` are accepted: the Gibbs scheme always
samples the latent coefficients explicitly (``bchain``) while conditionals
use marginalized forms, and all device factorizations are dense Cholesky —
the flags select between representations this framework already provides
simultaneously.  ``red_psd='tprocess'`` builds the t-process (powerlaw
scaled by per-frequency InvGamma alphas, sampled by their exact conjugate
conditional).  ``tm_var``/``tm_linear`` raise ``NotImplementedError``
loudly (the reference's committed body leaves its signal model undefined
when ``tm_var=True`` — ``model_definition.py:185-190`` only assigns ``s``
in the ``not tm_var`` branch — so no working reference behavior exists to
match); so do ``use_dmdata`` (requires wideband DM data this ingestion
layer does not model) and ``tprocess_adapt``.
"""

from __future__ import annotations

import numpy as np

from ..data.dataset import get_tspan
from .ephem import BayesEphemSignal
from .priors import Constant, InvGamma, LinearExp, Uniform
from .selections import SELECTIONS
from .pta import PTA, SignalModel
from .signals import (DMAnnualSignal, EcorrBasisSignal, FourierGPSignal,
                      TimingModelSignal, WhiteNoiseSignal)

_PSD_HYPERS = {
    "powerlaw": ("log10_A", "gamma"),
    "turnover": ("log10_A", "gamma", "lf0", "kappa"),
    "turnover_knee": ("log10_A", "gamma", "lfb", "lfk", "kappa", "delta"),
    "broken_powerlaw": ("log10_A", "gamma", "delta", "log10_fb", "kappa"),
}

#: fixed values for the shape hypers beyond (log10_A, gamma) — per PSD,
#: matching models/psd.py's own function defaults (varied only in
#: specialised analyses, as in the reference's enterprise blocks)
_PSD_SHAPE_DEFAULTS = {
    "turnover": {"lf0": -8.5, "kappa": 10.0 / 3.0},
    "turnover_knee": {"lfb": -8.5, "lfk": -8.0, "kappa": 10.0 / 3.0,
                      "delta": 0.1},
    "broken_powerlaw": {"delta": 0.0, "log10_fb": -8.5, "kappa": 0.1},
}

#: red_select band edges [MHz].  The reference delegates to enterprise
#: selections keyed on observing-system flags; the simulated datasets carry
#: none, so bands are cut on radio frequency — the physical quantity the
#: flag encodes ('band': below/above 1 GHz; 'band+': adds an L/S split).
_BANDS = {
    "band": (("low", 0.0, 1000.0), ("high", 1000.0, np.inf)),
    "band+": (("low", 0.0, 1000.0), ("mid", 1000.0, 2000.0),
              ("high", 2000.0, np.inf)),
}


def _log_grid(nmodes_lin, nmodes_log, Tspan):
    """'logfreq' grid: nmodes_log log-spaced bins below 1/T joined to the
    linear grid (reference model_definition.py 'logfreq'/'nmodes_log')."""
    flin = np.arange(1, nmodes_lin + 1) / Tspan
    flog = np.logspace(np.log10(flin[0] / 100.0), np.log10(flin[0]), nmodes_log,
                       endpoint=False)
    return np.concatenate([flog, flin])


def model_general(psrs, tm_var=False, tm_linear=False, tmparam_list=None,
                  tm_svd=False, tm_norm=True, noisedict=None,
                  white_vary=False, Tspan=None, modes=None, wgts=None,
                  logfreq=False, nmodes_log=10,
                  common_psd="powerlaw", common_components=30,
                  log10_A_common=None, gamma_common=None,
                  common_logmin=None, common_logmax=None,
                  orf="crn", orf_names=None, orf_ifreq=0, leg_lmax=5,
                  upper_limit_common=None, upper_limit=False,
                  red_var=True, red_psd="powerlaw", red_components=30,
                  upper_limit_red=None, red_select=None,
                  red_breakflat=False, red_breakflat_fq=None,
                  bayesephem=False, be_type="setIII_1980",
                  is_wideband=False, use_dmdata=False,
                  dm_var=False, dm_type="gp", dm_psd="powerlaw",
                  dm_components=30, upper_limit_dm=None,
                  dm_annual=False,
                  dm_chrom=False, dmchrom_psd="powerlaw", dmchrom_idx=4,
                  gequad=False, coefficients=False, pshift=False, pseed=None,
                  select="backend", tm_marg=False, dense_like=False,
                  **extra) -> PTA:
    """Build a PTA model over ``data.Pulsar`` objects.  See module docstring
    for the supported surface; returns a :class:`~..models.pta.PTA`."""
    if extra:
        raise TypeError(f"unknown model_general option(s): {sorted(extra)}")
    if tm_var or tm_linear or tmparam_list is not None:
        raise NotImplementedError(
            "tm_var/tm_linear: the reference's committed model_general "
            "never assigns a timing-model signal when tm_var=True "
            "(model_definition.py:185-190, NameError at PTA assembly), so "
            "there is no working behavior to match; the linear timing "
            "model here is always marginalized exactly in the b-draw")
    if use_dmdata:
        raise NotImplementedError(
            "use_dmdata requires wideband DM measurements "
            "(WidebandTimingModel); the par/tim ingestion layer models "
            "narrowband TOAs only")
    if dm_type != "gp":
        raise NotImplementedError(
            f"dm_type={dm_type!r}: only the Gaussian-process DM model is "
            "implemented (the reference's other choices route through "
            "additional enterprise options it never exercises)")
    if red_psd == "tprocess_adapt":
        raise NotImplementedError(
            "red_psd='tprocess_adapt' (single adaptively-located alpha) is "
            "not implemented; red_psd='tprocess' gives the full "
            "per-frequency t-process with exact conjugate alpha draws")
    if red_breakflat and red_breakflat_fq is None:
        raise ValueError("red_breakflat=True requires red_breakflat_fq [Hz]")
    # coefficients / dense_like / tm_marg: accepted — see module docstring
    # (the Gibbs sampler explicitly samples coefficients AND uses dense
    # Cholesky factorizations regardless; the timing model is always
    # analytically marginalized, which is what tm_marg selects)
    del coefficients, dense_like, tm_marg

    psrs = list(psrs)
    if Tspan is None:
        Tspan = get_tspan(psrs)

    # reference semantics (model_definition.py:173-181): with no per-class
    # flag set every class follows the global upper_limit; once ANY
    # per-class flag is given, each class is uniform only under its own
    # flag and log-uniform otherwise
    amp_prior = "uniform" if upper_limit else "log-uniform"
    if all(v is None for v in (upper_limit_red, upper_limit_dm,
                               upper_limit_common)):
        amp_prior_red = amp_prior_dm = amp_prior_common = amp_prior
    else:
        amp_prior_common = "uniform" if upper_limit_common else "log-uniform"
        amp_prior_red = "uniform" if upper_limit_red else "log-uniform"
        amp_prior_dm = "uniform" if upper_limit_dm else "log-uniform"

    # ---- common process hyperparameters (shared across pulsars) ----------
    orf_list = orf.split(",")
    orf_name_list = (orf_names or orf).split(",")
    common_param_sets = []
    orf_param_sets = []
    for orf_nm, orf_el in zip(orf_name_list, orf_list):
        gname = f"gw_{orf_nm}"
        # parameterized ORFs (bin_orf / legendre_orf): the inter-pulsar
        # correlation weights are sampled, one global set per process.
        # G(theta) = I + sum_j theta_j B_j must stay positive definite;
        # their ``init=0`` pins initial_sample at G = I (a prior draw of
        # the weights is non-PD with high probability, and the sampler
        # rejects non-PD proposals but cannot start from a non-PD state)
        def orf_weight(nm):
            p = Uniform(-1.0, 1.0, name=nm)
            p.init = 0.0
            return p

        # zero_diag_* variants carry the same sampled weight set as their
        # full counterparts (the zero diagonal only changes G(theta))
        orf_base = (orf_el[len("zero_diag_"):]
                    if orf_el.startswith("zero_diag_") else orf_el)
        if orf_base == "bin_orf":
            from .orf import BIN_ORF_EDGES

            orf_param_sets.append([
                orf_weight(f"{gname}_orfw_bin_{j}")
                for j in range(len(BIN_ORF_EDGES) - 1)])
        elif orf_base == "legendre_orf":
            orf_param_sets.append([
                orf_weight(f"{gname}_orfw_leg_{l}")
                for l in range(leg_lmax + 1)])
        else:
            orf_param_sets.append([])
    for orf_nm in orf_name_list:
        gname = f"gw_{orf_nm}"
        if common_psd == "spectrum":
            lo = -10.0 if common_logmin is None else common_logmin
            hi = -4.0 if common_logmax is None else common_logmax
            common_param_sets.append([
                Uniform(lo, hi, name=f"{gname}_log10_rho", size=common_components)])
        elif common_psd in _PSD_HYPERS:
            lo = -18.0 if common_logmin is None else common_logmin
            hi = -11.0 if common_logmax is None else common_logmax
            amp_cls = LinearExp if amp_prior_common == "uniform" else Uniform
            amp = (Constant(log10_A_common, name=f"{gname}_log10_A")
                   if log10_A_common is not None
                   else amp_cls(lo, hi, name=f"{gname}_log10_A"))
            gam = (Constant(gamma_common, name=f"{gname}_gamma")
                   if gamma_common is not None
                   else Uniform(0.0, 7.0, name=f"{gname}_gamma"))
            ps = [amp, gam]
            for hyper in _PSD_HYPERS[common_psd][2:]:
                ps.append(Constant(_PSD_SHAPE_DEFAULTS[common_psd][hyper],
                                   name=f"{gname}_{hyper}"))
            common_param_sets.append(ps)
        else:
            raise NotImplementedError(f"common_psd='{common_psd}'")

    grid = _log_grid(common_components, nmodes_log, Tspan) if logfreq else modes

    models = []
    for psr in psrs:
        sigs = [TimingModelSignal(psr.Mmat, use_svd=tm_svd, normed=tm_norm)]

        # pshift: deterministic per-pulsar random phases on the shared
        # Fourier grid (sky-scramble / false-alarm studies, the reference's
        # pshift/pseed kwargs).  One seed per PULSAR, applied to every
        # shared-grid signal alike: GW and intrinsic red share basis
        # columns (the reference sampler's own convention,
        # pulsar_gibbs.py:101-102), so a GW-only shift would be silently
        # discarded whenever the red process donates the wider basis.
        # Cross-pulsar decorrelation of the common process — the point of
        # the scramble — is preserved.  crc32 (not hash()) so phases are
        # stable across interpreter runs.
        shift_seed = None
        if pshift:
            import zlib

            shift_seed = zlib.crc32(repr((pseed or 0, psr.name)).encode())

        for orf_nm, orf_el, ps, ops in zip(orf_name_list, orf_list,
                                           common_param_sets, orf_param_sets):
            # correlated processes keep their own basis columns (disjoint
            # from intrinsic red) so the cross-pulsar prior on them is
            # purely rho_k G — exact HD + red sampling; CRN processes
            # share the red grid, the reference sampler's own convention
            sigs.append(FourierGPSignal(
                psr.toas / 86400.0, common_components, Tspan,
                psd_name=common_psd, psd_params=ps, name=f"gw_{orf_nm}",
                modes=grid, orf_name=orf_el, orf_ifreq=orf_ifreq,
                leg_lmax=leg_lmax, pshift_seed=shift_seed, wgts=wgts,
                share_group=("fourier" if orf_el == "crn"
                             else f"gw_{orf_nm}"),
                orf_params=ops))

        if red_var:
            red_name_psd = red_psd
            red_extra_hypers = []
            if red_breakflat:
                if red_psd != "powerlaw":
                    raise NotImplementedError(
                        "red_breakflat applies to red_psd='powerlaw'")
                red_name_psd = "powerlaw_breakflat"
            if red_select is not None and red_psd not in _PSD_HYPERS:
                raise NotImplementedError(
                    "red_select requires a powerlaw-family red_psd (split "
                    "free-spectrum blocks have no conditional sampler)")

            def red_params(rname):
                if red_psd == "spectrum":
                    return [Uniform(-10.0, -4.0, name=f"{rname}_log10_rho",
                                    size=red_components)]
                if red_psd == "infinitepower":
                    return []
                if red_psd == "tprocess":
                    # per-frequency InvGamma(df/2, df/2) scale factors,
                    # df=2 (enterprise_extensions t_process defaults);
                    # sampled by their exact conjugate conditional
                    amp_cls = (LinearExp if amp_prior_red == "uniform"
                               else Uniform)
                    return [amp_cls(-20.0, -11.0, name=f"{rname}_log10_A"),
                            Uniform(0.0, 7.0, name=f"{rname}_gamma"),
                            InvGamma(1.0, 1.0, name=f"{rname}_alphas",
                                     size=red_components)]
                if red_psd in _PSD_HYPERS:
                    amp_cls = (LinearExp if amp_prior_red == "uniform"
                               else Uniform)
                    rps = [amp_cls(-20.0, -11.0, name=f"{rname}_log10_A"),
                           Uniform(0.0, 7.0, name=f"{rname}_gamma")]
                    if _PSD_HYPERS[red_psd][2:]:
                        raise NotImplementedError(f"red_psd='{red_psd}'")
                    if red_breakflat:
                        rps.append(Constant(np.log10(red_breakflat_fq),
                                            name=f"{rname}_log10_fb"))
                    return rps
                raise NotImplementedError(f"red_psd='{red_psd}'")

            if red_select is None:
                rname = f"{psr.name}_red_noise"
                # same per-pulsar phase shift as the common process: the
                # two share basis columns, so their shifts must agree
                sigs.append(FourierGPSignal(
                    psr.toas / 86400.0, red_components, Tspan,
                    psd_name=red_name_psd, psd_params=red_params(rname),
                    name=rname, modes=grid, wgts=wgts,
                    pshift_seed=shift_seed))
            else:
                # split intrinsic red process, one GP per selection group
                # (reference red_select: 'backend' | 'band' | 'band+');
                # masked rows force own basis columns per group
                if red_select in _BANDS:
                    groups = {lab: (psr.freqs > lo) & (psr.freqs <= hi)
                              for lab, lo, hi in _BANDS[red_select]}
                elif red_select == "backend":
                    groups = SELECTIONS["backend"](psr.backend_flags)
                else:
                    raise NotImplementedError(f"red_select={red_select!r}")
                for lab in sorted(groups):
                    mask = np.asarray(groups[lab], dtype=bool)
                    if not mask.any():
                        continue
                    rname = f"{psr.name}_red_noise_{lab}"
                    sigs.append(FourierGPSignal(
                        psr.toas / 86400.0, red_components, Tspan,
                        psd_name=red_name_psd, psd_params=red_params(rname),
                        name=rname, modes=grid, row_mask=mask, wgts=wgts))

        # chromatic GPs (reference model_definition.py:19-31 via
        # enterprise's dm/chrom noise blocks; amplitudes referenced to
        # 1400 MHz): dm_var = nu^-2 dispersion measure, dm_chrom =
        # nu^-dmchrom_idx scattering.  Own basis columns each.
        def chrom_gp(suffix, psd, components, index, prior):
            if psd not in _PSD_HYPERS:
                raise NotImplementedError(
                    f"{suffix} psd='{psd}': chromatic GPs support the "
                    "powerlaw-family PSDs (their amplitude/index hypers "
                    "join the adaptive MH block; a free-spectrum chromatic "
                    "block has no conditional sampler)")
            cname = f"{psr.name}_{suffix}"
            amp_cls = LinearExp if prior == "uniform" else Uniform
            ps = [amp_cls(-20.0, -11.0, name=f"{cname}_log10_A"),
                  Uniform(0.0, 7.0, name=f"{cname}_gamma")]
            for hyper in _PSD_HYPERS[psd][2:]:
                ps.append(Constant(_PSD_SHAPE_DEFAULTS[psd][hyper],
                                   name=f"{cname}_{hyper}"))
            return FourierGPSignal(
                psr.toas / 86400.0, components, Tspan, psd_name=psd,
                psd_params=ps, name=cname, modes=grid,
                radio_freqs=psr.freqs, chrom_index=float(index))

        if dm_var:
            sigs.append(chrom_gp("dm_gp", dm_psd, dm_components, 2.0,
                                 amp_prior_dm))
        if dm_chrom:
            sigs.append(chrom_gp("chrom_gp", dmchrom_psd, dm_components,
                                 dmchrom_idx, amp_prior))
        if dm_annual:
            sigs.append(DMAnnualSignal(psr.toas, psr.freqs))
        if bayesephem:
            sigs.append(BayesEphemSignal(psr.toas, psr.pos, be_type=be_type))

        # ---- white noise -------------------------------------------------
        masks = SELECTIONS[select](psr.backend_flags)
        efacs, equads, ecorrs = {}, {}, {}
        for lab in sorted(masks):
            stem = f"{psr.name}_{lab}" if lab else psr.name
            if white_vary:
                efacs[lab] = Uniform(0.01, 10.0, name=f"{stem}_efac")
                equads[lab] = Uniform(-8.5, -5.0, name=f"{stem}_log10_tnequad")
                ecorrs[lab] = Uniform(-8.5, -5.0, name=f"{stem}_log10_ecorr")
            else:
                nd = noisedict or {}
                efacs[lab] = Constant(nd.get(f"{stem}_efac", 1.0),
                                      name=f"{stem}_efac")
                equads[lab] = Constant(nd.get(f"{stem}_log10_tnequad", -40.0),
                                       name=f"{stem}_log10_tnequad")
                ecorrs[lab] = Constant(nd.get(f"{stem}_log10_ecorr", -40.0),
                                       name=f"{stem}_log10_ecorr")
        geq = None
        if gequad:
            gname = f"{psr.name}_log10_gequad"
            if white_vary:
                geq = Uniform(-8.5, -5.0, name=gname)
            else:
                geq = Constant((noisedict or {}).get(gname, -40.0),
                               name=gname)
        white = WhiteNoiseSignal(psr.toaerrs, masks, efacs, equads,
                                 gequad=geq)

        # basis ECORR only for NANOGrav-flagged non-wideband pulsars, as
        # the reference gates it (model_definition.py:221-228)
        if "NANOGrav" in psr.flags.get("pta", "") and not is_wideband:
            sigs.append(EcorrBasisSignal(psr.toas, masks, ecorrs))

        m = SignalModel(psr, sigs, white)
        models.append(m)

    return PTA(models)
