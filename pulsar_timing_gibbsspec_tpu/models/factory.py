"""Kwarg-driven PTA model factory.

Mirrors the configuration surface of the reference's ``model_general``
(``model_definition.py:18-236``) — the de-facto config schema of the whole
stack (SURVEY §5).  Supported here natively:

- linear timing model with ``tm_svd`` / ``tm_norm``
- common red-noise block(s): ``common_psd`` in {powerlaw, spectrum,
  turnover, turnover_knee, broken_powerlaw}, multiple comma-separated ORFs
  (``orf``/``orf_names``), fixed or varied amplitude/index, custom rho
  bounds (``common_logmin/logmax``), ``common_components``
- per-pulsar intrinsic red noise: ``red_var``, ``red_psd`` (powerlaw or
  spectrum), ``red_components`` — note the reference's committed
  ``model_general`` accepts these kwargs but never adds the block (its
  notebooks hand-build it); here the advertised behavior is implemented
- white noise: ``white_vary``, per-backend EFAC/EQUAD via
  ``select='backend'``, fixed values via ``noisedict``, global EQUAD via
  ``gequad``
- chromatic GPs: ``dm_var`` (nu^-2 dispersion-measure GP) and ``dm_chrom``
  (nu^-chrom_idx scattering GP), powerlaw PSDs, own basis columns;
  ``dm_annual`` as a *marginalized* linearized annual DM sinusoid (two
  nu^-2 sin/cos columns with improper prior — the same 2-d subspace the
  reference's sampled amplitude/phase parameterizes, with no extra
  sampling block)
- ECORR (basis) for pulsars carrying a NANOGrav pta flag, as in
  ``model_definition.py:221-223``
- ``Tspan``/``modes``/``logfreq`` frequency-grid control, upper-limit
  (LinearExp) amplitude priors

Unsupported reference kwargs (BayesEphem, wideband, t-process PSDs, band
selections) raise ``NotImplementedError`` loudly rather than silently
no-op.
"""

from __future__ import annotations

import numpy as np

from ..data.dataset import get_tspan
from .priors import Constant, LinearExp, Uniform
from .selections import SELECTIONS
from .pta import PTA, SignalModel
from .signals import (DMAnnualSignal, EcorrBasisSignal, FourierGPSignal,
                      TimingModelSignal, WhiteNoiseSignal)

_PSD_HYPERS = {
    "powerlaw": ("log10_A", "gamma"),
    "turnover": ("log10_A", "gamma", "lf0", "kappa"),
    "turnover_knee": ("log10_A", "gamma", "lfb", "lfk", "kappa", "delta"),
    "broken_powerlaw": ("log10_A", "gamma", "delta", "log10_fb", "kappa"),
}


def _reject_unsupported(kw: dict):
    unsupported = {
        "tm_var": False, "tm_linear": False, "tmparam_list": None,
        "bayesephem": False, "is_wideband": False, "use_dmdata": False,
        "coefficients": False, "red_select": None,
        "red_breakflat": False, "pshift": False,
    }
    for key, default in unsupported.items():
        if kw.pop(key, default) not in (default, None):
            raise NotImplementedError(
                f"model_general option '{key}' is not implemented in the TPU "
                f"framework yet (reference model_definition.py accepts it)")


def _log_grid(nmodes_lin, nmodes_log, Tspan):
    """'logfreq' grid: nmodes_log log-spaced bins below 1/T joined to the
    linear grid (reference model_definition.py 'logfreq'/'nmodes_log')."""
    flin = np.arange(1, nmodes_lin + 1) / Tspan
    flog = np.logspace(np.log10(flin[0] / 100.0), np.log10(flin[0]), nmodes_log,
                       endpoint=False)
    return np.concatenate([flog, flin])


def model_general(psrs, tm_svd=False, tm_norm=True, noisedict=None,
                  white_vary=False, Tspan=None, modes=None, logfreq=False,
                  nmodes_log=10,
                  common_psd="powerlaw", common_components=30,
                  log10_A_common=None, gamma_common=None,
                  common_logmin=None, common_logmax=None,
                  orf="crn", orf_names=None,
                  upper_limit_common=None, upper_limit=False,
                  red_var=True, red_psd="powerlaw", red_components=30,
                  upper_limit_red=None,
                  dm_var=False, dm_psd="powerlaw", dm_components=30,
                  dm_annual=False,
                  dm_chrom=False, chrom_psd="powerlaw", chrom_components=30,
                  chrom_idx=4.0, gequad=False,
                  select="backend", **extra) -> PTA:
    """Build a PTA model over ``data.Pulsar`` objects.  See module docstring
    for the supported subset; returns a :class:`~..models.pta.PTA`."""
    _reject_unsupported(extra)
    if extra:
        raise TypeError(f"unknown model_general option(s): {sorted(extra)}")

    psrs = list(psrs)
    if Tspan is None:
        Tspan = get_tspan(psrs)

    amp_prior = "uniform" if upper_limit else "log-uniform"
    amp_prior_common = "uniform" if upper_limit_common else amp_prior
    amp_prior_red = "uniform" if upper_limit_red else amp_prior

    # ---- common process hyperparameters (shared across pulsars) ----------
    orf_list = orf.split(",")
    orf_name_list = (orf_names or orf).split(",")
    common_param_sets = []
    for orf_nm in orf_name_list:
        gname = f"gw_{orf_nm}"
        if common_psd == "spectrum":
            lo = -10.0 if common_logmin is None else common_logmin
            hi = -4.0 if common_logmax is None else common_logmax
            common_param_sets.append([
                Uniform(lo, hi, name=f"{gname}_log10_rho", size=common_components)])
        elif common_psd in _PSD_HYPERS:
            lo = -18.0 if common_logmin is None else common_logmin
            hi = -11.0 if common_logmax is None else common_logmax
            amp_cls = LinearExp if amp_prior_common == "uniform" else Uniform
            amp = (Constant(log10_A_common, name=f"{gname}_log10_A")
                   if log10_A_common is not None
                   else amp_cls(lo, hi, name=f"{gname}_log10_A"))
            gam = (Constant(gamma_common, name=f"{gname}_gamma")
                   if gamma_common is not None
                   else Uniform(0.0, 7.0, name=f"{gname}_gamma"))
            ps = [amp, gam]
            for hyper in _PSD_HYPERS[common_psd][2:]:
                # fixed shape defaults, varied only in specialised analyses
                ps.append(Constant({"lf0": -8.5, "kappa": 10 / 3, "lfb": -8.5,
                                    "lfk": -8.0, "delta": 0.0, "log10_fb": -8.5,
                                    }[hyper], name=f"{gname}_{hyper}"))
            common_param_sets.append(ps)
        else:
            raise NotImplementedError(f"common_psd='{common_psd}'")

    grid = _log_grid(common_components, nmodes_log, Tspan) if logfreq else modes

    models = []
    for psr in psrs:
        sigs = [TimingModelSignal(psr.Mmat, use_svd=tm_svd, normed=tm_norm)]

        for orf_nm, orf_el, ps in zip(orf_name_list, orf_list, common_param_sets):
            sigs.append(FourierGPSignal(
                psr.toas / 86400.0, common_components, Tspan,
                psd_name=common_psd, psd_params=ps, name=f"gw_{orf_nm}",
                modes=grid, orf_name=orf_el))

        if red_var:
            rname = f"{psr.name}_red_noise"
            if red_psd == "spectrum":
                rps = [Uniform(-10.0, -4.0, name=f"{rname}_log10_rho",
                               size=red_components)]
            elif red_psd in _PSD_HYPERS:
                amp_cls = LinearExp if amp_prior_red == "uniform" else Uniform
                rps = [amp_cls(-20.0, -11.0, name=f"{rname}_log10_A"),
                       Uniform(0.0, 7.0, name=f"{rname}_gamma")]
                for hyper in _PSD_HYPERS[red_psd][2:]:
                    raise NotImplementedError(f"red_psd='{red_psd}'")
            else:
                raise NotImplementedError(f"red_psd='{red_psd}'")
            sigs.append(FourierGPSignal(
                psr.toas / 86400.0, red_components, Tspan,
                psd_name=red_psd, psd_params=rps, name=rname, modes=grid))

        # chromatic GPs (reference model_definition.py:19-31 via
        # enterprise's dm/chrom noise blocks; amplitudes referenced to
        # 1400 MHz): dm_var = nu^-2 dispersion measure, dm_chrom =
        # nu^-chrom_idx scattering.  Own basis columns each.
        def chrom_gp(suffix, psd, components, index):
            if psd != "powerlaw":
                raise NotImplementedError(
                    f"{suffix} psd='{psd}': chromatic GPs currently "
                    "support the powerlaw PSD (their hypers join the "
                    "adaptive MH block)")
            cname = f"{psr.name}_{suffix}"
            amp_cls = LinearExp if amp_prior == "uniform" else Uniform
            ps = [amp_cls(-20.0, -11.0, name=f"{cname}_log10_A"),
                  Uniform(0.0, 7.0, name=f"{cname}_gamma")]
            return FourierGPSignal(
                psr.toas / 86400.0, components, Tspan, psd_name=psd,
                psd_params=ps, name=cname, modes=grid,
                radio_freqs=psr.freqs, chrom_index=float(index))

        if dm_var:
            sigs.append(chrom_gp("dm_gp", dm_psd, dm_components, 2.0))
        if dm_chrom:
            sigs.append(chrom_gp("chrom_gp", chrom_psd, chrom_components,
                                 chrom_idx))
        if dm_annual:
            sigs.append(DMAnnualSignal(psr.toas, psr.freqs))

        # ---- white noise -------------------------------------------------
        masks = SELECTIONS[select](psr.backend_flags)
        efacs, equads, ecorrs = {}, {}, {}
        for lab in sorted(masks):
            stem = f"{psr.name}_{lab}" if lab else psr.name
            if white_vary:
                efacs[lab] = Uniform(0.01, 10.0, name=f"{stem}_efac")
                equads[lab] = Uniform(-8.5, -5.0, name=f"{stem}_log10_tnequad")
                ecorrs[lab] = Uniform(-8.5, -5.0, name=f"{stem}_log10_ecorr")
            else:
                nd = noisedict or {}
                efacs[lab] = Constant(nd.get(f"{stem}_efac", 1.0),
                                      name=f"{stem}_efac")
                equads[lab] = Constant(nd.get(f"{stem}_log10_tnequad", -40.0),
                                       name=f"{stem}_log10_tnequad")
                ecorrs[lab] = Constant(nd.get(f"{stem}_log10_ecorr", -40.0),
                                       name=f"{stem}_log10_ecorr")
        geq = None
        if gequad:
            gname = f"{psr.name}_log10_gequad"
            if white_vary:
                geq = Uniform(-8.5, -5.0, name=gname)
            else:
                geq = Constant((noisedict or {}).get(gname, -40.0),
                               name=gname)
        white = WhiteNoiseSignal(psr.toaerrs, masks, efacs, equads,
                                 gequad=geq)

        # basis ECORR only for NANOGrav-flagged pulsars, as the reference
        # gates it (model_definition.py:221-223)
        if "NANOGrav" in psr.flags.get("pta", ""):
            sigs.append(EcorrBasisSignal(psr.toas, masks, ecorrs))

        m = SignalModel(psr, sigs, white)
        models.append(m)

    return PTA(models)
