"""Overlap reduction functions (inter-pulsar correlation of common signals).

The reference's ``model_general`` can build common processes with any of
these ORFs (``model_definition.py:198-216``), though its experimental PTA
sampler only ever exploits the block-diagonal CRN case (SURVEY §3.6).  Here
the ORFs are first-class so the PTA phi matrix can be dense when a correlated
common process is requested.
"""

from __future__ import annotations

import numpy as np


def crn(pos_a, pos_b):
    """Common-spectrum uncorrelated process: identity correlation."""
    return 1.0 if pos_a is pos_b or np.allclose(pos_a, pos_b) else 0.0


def hd(pos_a, pos_b):
    """Hellings-Downs quadrupolar correlation."""
    if pos_a is pos_b or np.allclose(pos_a, pos_b):
        return 1.0
    x = (1.0 - np.dot(pos_a, pos_b)) / 2.0
    x = np.clip(x, 1e-15, None)
    return 1.5 * x * np.log(x) - 0.25 * x + 0.5


def dipole(pos_a, pos_b):
    if pos_a is pos_b or np.allclose(pos_a, pos_b):
        return 1.0
    return float(np.dot(pos_a, pos_b))


def monopole(pos_a, pos_b):
    return 1.0


ORFS = {"crn": crn, "hd": hd, "dipole": dipole, "monopole": monopole}


def orf_matrix(name: str, positions) -> np.ndarray:
    """(P, P) correlation matrix over pulsars for the named ORF."""
    fn = ORFS[name]
    P = len(positions)
    for ii, p in enumerate(positions):
        if not np.isfinite(p).all() or np.linalg.norm(p) < 0.5:
            raise ValueError(
                f"pulsar {ii} has no usable sky position (par file lacked "
                f"ELONG/ELAT and RAJ/DECJ); cannot evaluate a correlated ORF")
    G = np.eye(P)
    for a in range(P):
        for b in range(a + 1, P):
            G[a, b] = G[b, a] = fn(positions[a], positions[b])
    return G
