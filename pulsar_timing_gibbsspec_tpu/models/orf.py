"""Overlap reduction functions (inter-pulsar correlation of common signals).

The reference's ``model_general`` can build common processes with any of
the enterprise_extensions ORFs (``model_definition.py:198-216``), though its
experimental PTA sampler only ever exploits the block-diagonal CRN case
(SURVEY §3.6).  Here the ORFs are first-class so the PTA phi matrix can be
dense when a correlated common process is requested — and, unlike the
reference, the dense-phi Gibbs path actually samples them (positive-definite
fixed ORFs; see ``sampler/compiled.py``).

Menu parity with ``blocks.common_red_noise_block``:

- fixed two-point ORFs: ``crn``, ``hd``, ``dipole``, ``monopole``,
  ``gw_monopole``, ``gw_dipole``, ``st`` (scalar transverse), and their
  ``zero_diag_*`` variants.  All are buildable; only positive-definite
  ones are *samplable* (hd, freq_hd, st, gw_monopole, gw_dipole).
  ``monopole``/``dipole`` (exactly rank-1 / rank-<=3) and the zero-diag
  detection variants yield degenerate priors and are rejected with a
  precise error — the reference's sampler handles no ORF at all
- ``bin_orf``, ``legendre_orf``: ORFs with *sampled* correlation weights
  ``G(theta) = I + sum_j theta_j B_j`` (:func:`orf_param_basis`), drawn by
  an MH block on the coefficient-conditional correlated likelihood —
  working here, unreachable in the reference (its sampler handles no
  correlated model).  ``param_hd``/``param_multiple`` (nonlinearly
  parameterized shapes) still reject loudly.
- ``freq_hd``: HD correlation applied only from frequency bin
  ``orf_ifreq`` upward (CRN below) — per-frequency ORF matrices
"""

from __future__ import annotations

import numpy as np


def _same(pos_a, pos_b):
    return pos_a is pos_b or np.allclose(pos_a, pos_b)


def crn(pos_a, pos_b):
    """Common-spectrum uncorrelated process: identity correlation."""
    return 1.0 if _same(pos_a, pos_b) else 0.0


def hd(pos_a, pos_b):
    """Hellings-Downs quadrupolar correlation."""
    if _same(pos_a, pos_b):
        return 1.0
    x = (1.0 - np.dot(pos_a, pos_b)) / 2.0
    x = np.clip(x, 1e-15, None)
    return 1.5 * x * np.log(x) - 0.25 * x + 0.5


def dipole(pos_a, pos_b):
    if _same(pos_a, pos_b):
        return 1.0
    return float(np.dot(pos_a, pos_b))


def monopole(pos_a, pos_b):
    return 1.0


def gw_monopole(pos_a, pos_b):
    """Breathing-mode (monopolar GW) correlation: 1/2 off-diagonal
    (enterprise_extensions ``gw_monopole_orf``)."""
    return 1.0 if _same(pos_a, pos_b) else 0.5


def gw_dipole(pos_a, pos_b):
    """Dipolar-GW correlation: cos(zeta)/2 off-diagonal
    (enterprise_extensions ``gw_dipole_orf``)."""
    if _same(pos_a, pos_b):
        return 1.0
    return 0.5 * float(np.dot(pos_a, pos_b))


def st(pos_a, pos_b):
    """Scalar-transverse correlation: 1/8 (3 + cos zeta) off-diagonal,
    3/8 normalization on the diagonal relative convention of
    enterprise_extensions ``st_orf`` (unit diagonal here)."""
    if _same(pos_a, pos_b):
        return 1.0
    return (3.0 + float(np.dot(pos_a, pos_b))) / 8.0


ORFS = {"crn": crn, "hd": hd, "dipole": dipole, "monopole": monopole,
        "gw_monopole": gw_monopole, "gw_dipole": gw_dipole, "st": st}

#: ORFs whose shape is itself sampled.  bin_orf/legendre_orf are handled
#: by :func:`orf_param_basis` (linear weight bases); the rest — and their
#: zero-diag detection variants — fail with a precise message when asked
#: for a fixed matrix
PARAMETERIZED_ORFS = ("param_hd", "param_multiple", "bin_orf", "legendre_orf",
                      "zero_diag_bin_orf", "zero_diag_legendre_orf")


def orf_matrix(name: str, positions) -> np.ndarray:
    """(P, P) correlation matrix over pulsars for the named ORF.

    ``zero_diag_<orf>`` zeroes the diagonal (cross-correlation-only
    detection statistic variants); the result is then not positive
    definite and cannot serve as a sampling prior — callers that need a
    PD phi must reject it.
    """
    zero_diag = False
    if name.startswith("zero_diag_"):
        zero_diag = True
        name = name[len("zero_diag_"):]
    if name in PARAMETERIZED_ORFS:
        raise NotImplementedError(
            f"orf='{name}' has sampled shape parameters; sampling "
            "parameterized ORFs is not implemented (the reference's Gibbs "
            "sampler supports no correlated ORF at all)")
    fn = ORFS[name]
    P = len(positions)
    for ii, p in enumerate(positions):
        if not np.isfinite(p).all() or np.linalg.norm(p) < 0.5:
            raise ValueError(
                f"pulsar {ii} has no usable sky position (par file lacked "
                f"ELONG/ELAT and RAJ/DECJ); cannot evaluate a correlated ORF")
    G = np.eye(P)
    for a in range(P):
        for b in range(a + 1, P):
            G[a, b] = G[b, a] = fn(positions[a], positions[b])
    if zero_diag:
        G = G - np.eye(P)
    return G


def orf_matrix_per_freq(name: str, positions, K: int,
                        orf_ifreq: int = 0) -> np.ndarray:
    """(K, P, P) per-frequency ORF stack.

    ``freq_hd`` (reference ``orf_ifreq`` kwarg): CRN below frequency bin
    ``orf_ifreq``, Hellings-Downs from that bin upward.  Any fixed ORF
    name yields a constant stack.
    """
    if name == "freq_hd":
        low = orf_matrix("crn", positions)
        high = orf_matrix("hd", positions)
        return np.stack([high if k >= orf_ifreq else low for k in range(K)])
    G = orf_matrix(name, positions)
    return np.broadcast_to(G, (K,) + G.shape).copy()


#: angular-separation bin edges [deg] for the binned ORF (the standard
#: 7-bin layout enterprise_extensions' bin_orf uses)
BIN_ORF_EDGES = (0.0, 30.0, 50.0, 80.0, 100.0, 120.0, 150.0, 180.0)


def orf_param_basis(name: str, positions, leg_lmax: int = 5):
    """Basis stack for a *parameterized* ORF: ``G(theta) = I + sum_j
    theta_j B_j`` with the diagonal pinned at 1 (the process variance is
    carried by rho_k; the sampled parameters are the inter-pulsar
    correlations).

    - ``bin_orf``: one parameter per angular-separation bin
      (``BIN_ORF_EDGES``); ``B_j`` masks the pairs in bin ``j``
    - ``legendre_orf``: parameters are Legendre coefficients ``c_l``,
      ``l = 0..leg_lmax``; ``B_l[a,b] = P_l(cos zeta_ab)`` off-diagonal

    Returns ``(B, labels)`` with ``B`` of shape (J, P, P), zero diagonal.

    ``zero_diag_bin_orf`` / ``zero_diag_legendre_orf`` (the reference's
    fixed-common-amplitude detection-statistic variants,
    ``model_definition.py:202-205``) carry the same weight basis — the
    difference is only that ``G(theta)`` omits the identity, which makes
    the prior non-PD; the sampler gate in ``sampler/compiled.py`` rejects
    sampling them, but the model *builds*.
    """
    if name.startswith("zero_diag_"):
        name = name[len("zero_diag_"):]
    P = len(positions)
    cosz = np.eye(P)
    for a in range(P):
        for b in range(a + 1, P):
            cosz[a, b] = cosz[b, a] = float(
                np.clip(np.dot(positions[a], positions[b]), -1.0, 1.0))
    off = 1.0 - np.eye(P)
    if name == "bin_orf":
        zeta = np.degrees(np.arccos(np.clip(cosz, -1.0, 1.0)))
        Bs, labels = [], []
        for j in range(len(BIN_ORF_EDGES) - 1):
            lo, hi = BIN_ORF_EDGES[j], BIN_ORF_EDGES[j + 1]
            mask = ((zeta > lo) if j else (zeta >= lo)) & (zeta <= hi)
            Bs.append(mask.astype(float) * off)
            labels.append(f"bin_{j}")
        return np.stack(Bs), labels
    if name == "legendre_orf":
        from scipy.special import eval_legendre

        Bs = [eval_legendre(l, cosz) * off for l in range(leg_lmax + 1)]
        return np.stack(Bs), [f"leg_{l}" for l in range(leg_lmax + 1)]
    raise NotImplementedError(f"parameterized orf '{name}'")


def orf_ginv_stack(name: str, positions, K: int,
                   orf_ifreq: int = 0) -> np.ndarray:
    """(K, P, P) inverse ORF stack for the correlated-phi samplers.

    Verifies positive definiteness first: the ``zero_diag_*`` variants are
    cross-correlation-only detection statistics, not valid sampling priors,
    and fail here with a precise message.
    """
    Gk = orf_matrix_per_freq(name, positions, K, orf_ifreq=orf_ifreq)
    wmin = float(np.linalg.eigvalsh(Gk).min())
    if wmin <= 1e-10:
        reason = (
            "zero-diag/cross-correlation-only ORFs are detection-statistic "
            "constructions" if name.startswith("zero_diag_") else
            "this correlation matrix is rank-deficient (monopole is rank 1, "
            "dipole rank <= 3: the common process collapses onto a "
            "lower-dimensional subspace), so the coefficient prior is "
            "degenerate")
        raise NotImplementedError(
            f"orf='{name}' cannot serve as a Gibbs sampling prior: {reason} "
            f"(min eigenvalue {wmin:.2e}).  The reference cannot sample any "
            "correlated ORF either; positive-definite choices here: hd, "
            "freq_hd, st, gw_monopole, gw_dipole")
    return np.linalg.inv(Gk)
