"""Per-pulsar signal model and PTA container.

Provides the exact accessor surface the reference samplers consume from an
enterprise PTA: ``pulsars``, ``params``, ``param_names``, ``map_params``,
``get_residuals``, ``get_basis``, ``get_ndiag``, ``get_phi``,
``get_phiinv(logdet=...)`` and a ``signals`` mapping (reference
``pulsar_gibbs.py:59-136,489-520``; ``pta_gibbs.py:512-548``).  Everything is
a plain NumPy array on the host; the JAX backend compiles this model into a
static device pytree (``sampler/jax_backend.py``).
"""

from __future__ import annotations

import numpy as np

from .priors import Constant
from .signals import BasisSignal, FourierGPSignal, WhiteNoiseSignal


class SignalModel:
    """One pulsar: ordered basis signals + white noise over its TOAs.

    Basis layout: ``[timing-model block | shared Fourier block | chromatic
    blocks | ECORR block]``.  Achromatic Fourier signals (red, common GW)
    share leading columns of the Fourier block — the reference's "red + GW
    share a basis" convention (``pulsar_gibbs.py:101-102``); the block is
    as wide as the largest requested mode count.  Chromatic GPs (DM,
    scattering) have radio-frequency-scaled bases, so each keeps its own
    columns.
    """

    def __init__(self, pulsar, basis_signals: list, white: WhiteNoiseSignal | None):
        self.pulsar = pulsar
        self.white = white

        # classification: Fourier GPs either share the common grid columns
        # (_fourier) or keep their own (_chrom: chromatic / row-masked /
        # band-split processes — any GP whose phi depends on sampled
        # hypers); remaining basis signals are static marginalized blocks
        # (_timing: timing model, dm_annual, BayesEphem — constant phi)
        self._fourier = [s for s in basis_signals
                         if getattr(s, "shares_fourier", False)]
        self._chrom = [s for s in basis_signals
                       if isinstance(s, FourierGPSignal)
                       and not getattr(s, "shares_fourier", False)]
        self._ecorr = [s for s in basis_signals if s.name == "basis_ecorr"]
        taken = set(map(id, self._fourier + self._chrom + self._ecorr))
        self._timing = [s for s in basis_signals if id(s) not in taken]
        self.signals = self._timing + self._fourier + self._chrom + self._ecorr

        blocks, self._slices = [], {}
        off = 0
        for s in self._timing:
            B = s.get_basis()
            blocks.append(B)
            self._slices[s.name] = slice(off, off + B.shape[1])
            off += B.shape[1]
        # shared-grid Fourier signals share columns within their
        # share_group (one block per group, donor = widest member); a
        # correlated common process carries its own group so its columns
        # stay disjoint from intrinsic red (see FourierGPSignal)
        groups: dict = {}
        for s in self._fourier:
            groups.setdefault(getattr(s, "share_group", "fourier"),
                              []).append(s)
        for members in groups.values():
            widths = [s.get_basis().shape[1] for s in members]
            wmax = max(widths)
            donor = members[int(np.argmax(widths))]
            blocks.append(donor.get_basis())
            for s in members:
                self._slices[s.name] = slice(off, off + s.get_basis().shape[1])
            off += wmax
        for s in self._chrom + self._ecorr:
            B = s.get_basis()
            blocks.append(B)
            self._slices[s.name] = slice(off, off + B.shape[1])
            off += B.shape[1]

        self._T = np.hstack(blocks) if blocks else np.zeros((pulsar.ntoa, 0))
        self._nbasis = off

    @property
    def params(self):
        seen, out = set(), []
        for s in self.signals + ([self.white] if self.white else []):
            for p in s.params:
                if not isinstance(p, Constant) and id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
        return out

    def basis_slice(self, name_frag: str):
        """Column slice of the first signal whose name contains the
        fragment (e.g. 'gw' -> GW coefficients; used for the tau fold)."""
        for s in self.signals:
            if name_frag in s.name:
                return self._slices[s.name]
        return None

    def get_basis(self):
        return self._T

    def get_phi(self, params: dict):
        phi = np.zeros(self._nbasis)
        for s in self.signals:
            phi[self._slices[s.name]] += s.get_phi(params)
        return phi

    def get_ndiag(self, params: dict):
        if self.white is None:
            return np.array(self.pulsar.toaerrs**2)
        return self.white.get_ndiag(params)


class PTA:
    """Container over per-pulsar SignalModels with enterprise-like accessors."""

    def __init__(self, models: list):
        self._models = list(models)
        self.pulsars = [m.pulsar.name for m in self._models]

        # signals mapping keyed '<pulsar>_<signalname>' in model order —
        # the reference iterates this to locate GW/red/ecorr bases
        # (pulsar_gibbs.py:94-105, pta_gibbs.py:100-109)
        self.signals = {}
        for m in self._models:
            for s in m.signals:
                self.signals[f"{m.pulsar.name}_{s.name}"] = s

    @property
    def params(self):
        """Deduped (by name) free parameters, sorted by name — enterprise
        PTA ordering, which fixes the chain-column layout."""
        seen, out = {}, []
        for m in self._models:
            for p in m.params:
                if p.name not in seen:
                    seen[p.name] = p
        return sorted(seen.values(), key=lambda p: p.name)

    @property
    def param_names(self):
        out = []
        for p in self.params:
            if p.size:
                out += [f"{p.name}_{ii}" for ii in range(p.size)]
            else:
                out.append(p.name)
        return out

    def map_params(self, xs):
        ret, ct = {}, 0
        for p in self.params:
            n = p.size if p.size else 1
            ret[p.name] = np.asarray(xs[ct:ct + n]) if n > 1 else float(xs[ct])
            ct += n
        return ret

    def initial_sample(self, rng=None):
        """Prior draw of every free parameter — except parameters carrying
        an explicit ``init`` attribute, which start there instead (the
        factory pins sampled ORF weights at 0 = identity correlation: a
        prior draw is non-positive-definite with high probability and no
        sampler could start from it)."""
        rng = np.random.default_rng() if rng is None else rng
        out = []
        for p in self.params:
            init = getattr(p, "init", None)
            if init is not None:
                out.append(np.full(p.size or 1, float(init)))
            else:
                out.append(np.atleast_1d(p.sample(rng)))
        return np.concatenate(out)

    # -- per-pulsar accessors (lists, one entry per pulsar) ------------------

    def get_residuals(self):
        return [m.pulsar.residuals for m in self._models]

    def get_basis(self, params=None):
        return [m.get_basis() for m in self._models]

    def get_ndiag(self, params):
        params = params if isinstance(params, dict) else self.map_params(params)
        return [m.get_ndiag(params) for m in self._models]

    def get_phi(self, params):
        params = params if isinstance(params, dict) else self.map_params(params)
        return [m.get_phi(params) for m in self._models]

    def get_phiinv(self, params, logdet: bool = False):
        out = []
        for phi in self.get_phi(params):
            if logdet:
                out.append((1.0 / phi, float(np.sum(np.log(phi)))))
            else:
                out.append(1.0 / phi)
        return out

    def get_lnprior(self, xs):
        params = xs if isinstance(xs, dict) else self.map_params(xs)
        return float(sum(p.get_logpdf(params=params) for p in self.params))

    def model(self, ii_or_name):
        if isinstance(ii_or_name, str):
            return self._models[self.pulsars.index(ii_or_name)]
        return self._models[ii_or_name]
