"""BayesEphem: solar-system-ephemeris error model as a marginalized basis.

The reference's ``model_general(bayesephem=True, be_type=...)`` attaches
enterprise's physical ephemeris model (``model_definition.py`` kwargs
``bayesephem``/``be_type``): 11 sampled global parameters — a frame drift
rate about the ecliptic pole, four outer-planet mass corrections, and six
Jupiter orbital-element perturbations — whose induced Roemer-delay
signatures are computed from JPL ephemeris partials shipped as data files.

This framework re-derives the same delay subspace analytically from
first-order celestial mechanics (no ephemeris files, which the build
environment cannot fetch), and — instead of sampling the 11 amplitudes —
**marginalizes** them as Gaussian basis coefficients in the b-draw, with
prior scales matched to enterprise's priors (IAU mass uncertainties;
uniform priors moment-matched to Gaussians of equal variance).

Numerical form: every column is stored *sigma-scaled* — the delay partial
multiplied by its prior standard deviation, so each marginalized
coefficient has unit prior variance.  This is a pure reparameterization
(the marginal covariance contribution ``T' T'^T = sum_k sigma_k^2 t_k
t_k^T`` is identical) that keeps the b-draw's preconditioned system
O(1)-conditioned: the raw parameterization spans ~22 decades between
column norms and prior precisions, pushing the smallest preconditioned
eigenvalue below float32 entry-rounding noise.

Approximations, stated plainly:

- Planet orbits are circular and coplanar (J2000 mean elements).  The
  neglected eccentricities are <= 0.056 (Saturn); the induced basis-shape
  error is at the few-percent level, far inside the prior width.
- Jupiter orbital-element perturbations are represented by the six
  first-order Keplerian patterns (radial offset, along-track offset,
  along-track drift, two cross-track sinusoids, and the eccentricity
  doublet) instead of the reference's numerically-tabulated setIII
  partials; both span the same physical delay subspace.  Enterprise's
  element parameters are expressed in the units of its partials tables
  (prior +-0.05); here each pattern's prior is set to the ~100 ns induced
  Roemer-delay scale — the DE421-vs-DE43x disagreement BayesEphem was
  designed to span (Arzoumanian et al. 2018, arXiv:1801.02617 §4).
- The 11 amplitudes are marginalized per pulsar rather than shared
  across the array.  For single-pulsar analyses this is exact (and
  Rao-Blackwellized vs the reference's sampling).  For multi-pulsar
  models it is conservative — each pulsar may absorb its own ephemeris
  error, an upper bound on the freedom the shared model allows.

Delay sign convention: a solar-system-barycenter position error
``dr`` displaces the Earth-to-SSB vector, changing the Roemer delay by
``-(dr . n) / c`` with ``n`` the pulsar direction.
"""

from __future__ import annotations

import numpy as np

from .signals import BasisSignal

AU_SEC = 499.00478384  # 1 AU light-travel time [s]
DAY = 86400.0
YEAR = 365.25 * DAY
MJD_J2000 = 51544.5
OBLIQUITY = np.deg2rad(23.439291111)

#: circular-orbit J2000 mean elements: semi-major axis [AU], sidereal
#: period [days], mean longitude at J2000 [deg]  (JPL approximate elements)
PLANETS = {
    "jupiter": (5.20288700, 4332.589, 34.39644),
    "saturn": (9.53667594, 10759.22, 49.95424),
    "uranus": (19.18916464, 30685.4, 313.23810),
    "neptune": (30.06992276, 60189.0, -55.12003),
}
EARTH = (1.00000261, 365.256, 100.46457)

#: IAU mass-parameter uncertainties [solar masses] — the Normal prior
#: sigmas enterprise's physical ephemeris model uses for d_*_mass
MASS_SIGMA = {
    "jupiter": 1.54976690e-11,
    "saturn": 8.17306184e-12,
    "uranus": 5.71923361e-11,
    "neptune": 7.96103855e-11,
}

#: enterprise frame_drift_rate prior half-width [rad/yr], moment-matched
#: to a Gaussian of variance w^2/3
FRAME_DRIFT_HALFWIDTH = 1e-9

#: 1-sigma induced Roemer delay per Jupiter orbital-element pattern [s]
#: (inter-ephemeris disagreement scale, see module docstring)
ORB_ELEMENT_DELAY_SIGMA = 1e-7

BE_TYPES = ("orbel", "orbel-v2", "setIII", "setIII_1980")


def _ecl_to_eq(v):
    """Rotate ecliptic-frame vectors (..., 3) to the equatorial frame."""
    ce, se = np.cos(OBLIQUITY), np.sin(OBLIQUITY)
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    return np.stack([x, ce * y - se * z, se * y + ce * z], axis=-1)


def _orbit(toas_sec, elements):
    """Circular-orbit position [AU, equatorial] and mean longitude vs time."""
    a, period_d, L0_deg = elements
    t_days = toas_sec / DAY - MJD_J2000
    L = np.deg2rad(L0_deg) + 2.0 * np.pi * t_days / period_d
    r_ecl = np.stack([a * np.cos(L), a * np.sin(L), np.zeros_like(L)], axis=-1)
    return _ecl_to_eq(r_ecl), L


class BayesEphemSignal(BasisSignal):
    """Marginalized physical ephemeris-error basis (11 columns).

    Columns are sigma-scaled Roemer-delay partials [s] (unit prior
    variance per coefficient); ``get_phi`` returns ones.  See the module
    docstring for the scaling rationale and approximations.
    """

    name = "bayesephem"

    def __init__(self, toas_sec: np.ndarray, pos: np.ndarray,
                 be_type: str = "setIII_1980"):
        if be_type not in BE_TYPES:
            raise ValueError(f"be_type={be_type!r}; known: {BE_TYPES}")
        if not np.isfinite(pos).all() or np.linalg.norm(pos) < 0.5:
            raise ValueError(
                "bayesephem requires a usable pulsar sky position (par file "
                "lacked ELONG/ELAT and RAJ/DECJ)")
        self.be_type = be_type
        self.params = []
        n = np.asarray(pos, dtype=np.float64)
        t_yr = (toas_sec / DAY - MJD_J2000) * DAY / YEAR

        cols = []

        # frame drift: rotation of the frame about the ecliptic pole at
        # rate w [rad/yr]; Earth position error w*t * (z_ecl x r_E)
        r_earth, _ = _orbit(toas_sec, EARTH)
        z_ecl = _ecl_to_eq(np.array([0.0, 0.0, 1.0]))
        zxr = np.cross(np.broadcast_to(z_ecl, r_earth.shape), r_earth)
        frame_sigma = FRAME_DRIFT_HALFWIDTH / np.sqrt(3.0)
        cols.append(-(zxr @ n) * t_yr * AU_SEC * frame_sigma)

        # outer-planet mass errors: dm shifts the SSB by dm * r_p, so the
        # Earth-to-SSB vector changes by -dm * r_p
        for planet in ("jupiter", "saturn", "uranus", "neptune"):
            r_p, _ = _orbit(toas_sec, PLANETS[planet])
            cols.append((r_p @ n) * AU_SEC * MASS_SIGMA[planet])

        # Jupiter orbital elements (all four be_type flavors carry them):
        # first-order Keplerian perturbation patterns, each normalized to
        # the ORB_ELEMENT_DELAY_SIGMA prior scale
        a_J, period_d, _ = PLANETS["jupiter"]
        r_J, L = _orbit(toas_sec, PLANETS["jupiter"])
        rhat = r_J / a_J
        # along-track unit vector: dr/dL normalized (equatorial)
        that = _ecl_to_eq(np.stack([-np.sin(L), np.cos(L),
                                    np.zeros_like(L)], axis=-1))
        zhat = np.broadcast_to(_ecl_to_eq(np.array([0.0, 0.0, 1.0])),
                               r_J.shape)
        nt = 2.0 * np.pi * (toas_sec / DAY - MJD_J2000) / period_d
        nt = nt - nt.mean()           # center the secular drift pattern
        patterns = [
            rhat,                                  # da: radial offset
            that,                                  # dM0/domega: along
            that * nt[:, None],                    # da: secular drift
            zhat * np.sin(L)[:, None],             # di
            zhat * np.cos(L)[:, None],             # dOmega (cross part)
            (-rhat * np.cos(L)[:, None]
             + 2.0 * that * np.sin(L)[:, None]),   # de doublet
        ]
        for pat in patterns:
            cols.append((pat @ n) * ORB_ELEMENT_DELAY_SIGMA)

        self._T = np.column_stack(cols)

    def get_basis(self):
        return self._T

    def get_phi(self, params):
        return np.ones(self._T.shape[1])
