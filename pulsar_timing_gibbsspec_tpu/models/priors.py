"""Prior / parameter objects.

A deliberately small, explicit replacement for the slice of enterprise's
parameter system the reference exercises: sampling initial points
(``[p.sample() for p in pta.params]``, reference ``pulsar_gibbs.py:74``),
prior log-pdfs inside MH blocks (``p.get_logpdf``, reference ``:617``), and
bound extraction for the conditional rho draws.  The reference recovers
bounds by parsing ``repr(param)`` strings (``pulsar_gibbs.py:82-87`` — noted
fragile in SURVEY §3.1); here bounds are first-class attributes
(``param.pmin``/``param.pmax``) while the repr still prints them for
familiarity.
"""

from __future__ import annotations

import numpy as np


class Parameter:
    """Base class: a named scalar or vector random variable."""

    def __init__(self, name: str, size: int | None = None):
        self.name = name
        self.size = size

    # subclasses: _sample1(rng, shape), _logpdf(value)

    def sample(self, rng=None):
        rng = np.random.default_rng() if rng is None else rng
        shape = () if self.size is None else (self.size,)
        return self._sample1(rng, shape)

    def get_logpdf(self, value=None, params: dict | None = None):
        if value is None and params is not None:
            value = params.get(self.name)
        return float(np.sum(self._logpdf(np.asarray(value, dtype=np.float64))))

    @property
    def params(self):
        """Scalar sub-parameters of a vector parameter (enterprise exposes
        the same; the reference reads bounds off element 0 at
        ``pulsar_gibbs.py:84``)."""
        if self.size is None:
            return [self]
        return [self._scalar(f"{self.name}_{ii}") for ii in range(self.size)]


class Uniform(Parameter):
    def __init__(self, pmin: float, pmax: float, name: str = "", size: int | None = None):
        super().__init__(name, size)
        self.pmin, self.pmax = float(pmin), float(pmax)

    def _sample1(self, rng, shape):
        return rng.uniform(self.pmin, self.pmax, size=shape)

    def _logpdf(self, x):
        inside = (x >= self.pmin) & (x <= self.pmax)
        return np.where(inside, -np.log(self.pmax - self.pmin), -np.inf)

    def _scalar(self, name):
        return Uniform(self.pmin, self.pmax, name=name)

    def __repr__(self):
        return f"{self.name}:Uniform(pmin={self.pmin}, pmax={self.pmax})"


class Normal(Parameter):
    def __init__(self, mu: float = 0.0, sigma: float = 1.0, name: str = "", size: int | None = None):
        super().__init__(name, size)
        self.mu, self.sigma = float(mu), float(sigma)

    def _sample1(self, rng, shape):
        return rng.normal(self.mu, self.sigma, size=shape)

    def _logpdf(self, x):
        return -0.5 * ((x - self.mu) / self.sigma) ** 2 - np.log(self.sigma * np.sqrt(2 * np.pi))

    def _scalar(self, name):
        return Normal(self.mu, self.sigma, name=name)

    def __repr__(self):
        return f"{self.name}:Normal(mu={self.mu}, sigma={self.sigma})"


class LinearExp(Parameter):
    """Uniform in the linear quantity for a log10-parameterized variable
    (enterprise's ``LinearExp`` — the 'uniform' amplitude prior used for
    upper-limit runs, reference ``model_definition.py:172``)."""

    def __init__(self, pmin: float, pmax: float, name: str = "", size: int | None = None):
        super().__init__(name, size)
        self.pmin, self.pmax = float(pmin), float(pmax)

    def _sample1(self, rng, shape):
        u = rng.uniform(size=shape)
        return np.log10(10**self.pmin + u * (10**self.pmax - 10**self.pmin))

    def _logpdf(self, x):
        inside = (x >= self.pmin) & (x <= self.pmax)
        dens = np.log(10.0) * 10**x / (10**self.pmax - 10**self.pmin)
        with np.errstate(divide="ignore"):
            return np.where(inside, np.log(dens), -np.inf)

    def _scalar(self, name):
        return LinearExp(self.pmin, self.pmax, name=name)

    def __repr__(self):
        return f"{self.name}:LinearExp(pmin={self.pmin}, pmax={self.pmax})"


class InvGamma(Parameter):
    """Inverse-gamma prior, ``x ~ InvGamma(shape, rate)``: density
    ``rate^shape / Gamma(shape) x^-(shape+1) exp(-rate/x)``.

    Used for the per-frequency scale factors of the t-process red PSD
    (enterprise_extensions ``t_process`` draws ``alphas ~ InvGamma(df/2,
    df/2)``, default df=2); conjugate to the Gaussian coefficient
    likelihood, so the Gibbs alpha-block is an exact draw."""

    def __init__(self, shape: float = 1.0, rate: float = 1.0, name: str = "",
                 size: int | None = None):
        super().__init__(name, size)
        self.shape, self.rate = float(shape), float(rate)

    def _sample1(self, rng, shape):
        return self.rate / rng.gamma(self.shape, size=shape)

    def _logpdf(self, x):
        from scipy.special import gammaln

        with np.errstate(divide="ignore", invalid="ignore"):
            lp = (self.shape * np.log(self.rate) - gammaln(self.shape)
                  - (self.shape + 1.0) * np.log(x) - self.rate / x)
        return np.where(x > 0, lp, -np.inf)

    def _scalar(self, name):
        return InvGamma(self.shape, self.rate, name=name)

    def __repr__(self):
        return f"{self.name}:InvGamma(shape={self.shape}, rate={self.rate})"


class Constant(Parameter):
    """Fixed value; excluded from ``PTA.params`` (and hence the chain)."""

    def __init__(self, value: float, name: str = ""):
        super().__init__(name, None)
        self.value = float(value)

    def _sample1(self, rng, shape):
        return self.value

    def _logpdf(self, x):
        return 0.0

    def __repr__(self):
        return f"{self.name}:Constant({self.value})"
