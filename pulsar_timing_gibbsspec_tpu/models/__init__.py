from .priors import Uniform, Normal, LinearExp, InvGamma, Constant
from .pta import PTA, SignalModel
from .factory import model_general

__all__ = [
    "Uniform", "Normal", "LinearExp", "InvGamma", "Constant",
    "PTA", "SignalModel", "model_general",
]
