from .priors import Uniform, Normal, LinearExp, Constant
from .pta import PTA, SignalModel
from .factory import model_general

__all__ = [
    "Uniform", "Normal", "LinearExp", "Constant",
    "PTA", "SignalModel", "model_general",
]
