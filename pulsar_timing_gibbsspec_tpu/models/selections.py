"""TOA-subset selections for white-noise parameters.

Equivalent of enterprise ``selections`` as used by the reference: one
EFAC/EQUAD (/ECORR) per backend via the per-TOA backend flag
(``selections.by_backend``, reference ``pulsar_gibbs.py:123`` and
``model_definition.py:219-228`` with ``select='backend'``).
"""

from __future__ import annotations

import numpy as np


def by_backend(backend_flags: np.ndarray) -> dict:
    """Label -> boolean TOA mask, one entry per distinct backend."""
    return {lab: backend_flags == lab
            for lab in sorted(set(backend_flags.tolist()))}


def no_selection(backend_flags: np.ndarray) -> dict:
    return {"": np.ones(len(backend_flags), dtype=bool)}


SELECTIONS = {"backend": by_backend, None: no_selection, "none": no_selection}
