"""Signal components composing a per-pulsar noise model.

Slim, array-first equivalents of the enterprise signal classes the reference
consumes through ``pta.get_basis/get_ndiag/get_phi`` (reference
``pulsar_gibbs.py:495-499``).  A signal either contributes basis columns with
a per-column prior variance ``phi`` (timing model, Fourier GPs, basis-ECORR)
or a diagonal measurement covariance (EFAC/EQUAD).  Basis signals built on
the same Fourier grid share columns — the "red + GW share a basis"
convention the reference hard-codes (``pulsar_gibbs.py:101-102``).
"""

from __future__ import annotations

import numpy as np

from ..data.fourier import fourier_basis
from . import psd as psdmod
from .priors import Constant, Parameter, Uniform

DAY = 86400.0


class BasisSignal:
    """Interface: named basis block + per-column prior variance."""

    name: str
    params: list
    shares_fourier = False

    def get_basis(self):
        raise NotImplementedError

    def get_phi(self, params: dict):
        raise NotImplementedError

    def _mapped(self, params: dict):
        """Pull this signal's hyperparameter values out of a name->value dict
        (Constants supply their fixed value)."""
        out = {}
        for p in self.params:
            out[p.name] = p.value if isinstance(p, Constant) else params[p.name]
        return out


class TimingModelSignal(BasisSignal):
    """Analytically marginalized linear timing model.

    ``tm_svd`` orthonormalizes the design matrix columns, ``tm_norm`` scales
    them to unit norm (reference ``model_definition.py:42-46`` /
    ``gp_signals.TimingModel(use_svd, normed)``); prior variance is the
    'infinite' 1e40 of enterprise's marginalization.
    """

    def __init__(self, Mmat: np.ndarray, use_svd: bool = False, normed: bool = True,
                 name: str = "linear_timing_model"):
        self.name = name
        self.params = []
        if use_svd:
            U, _, _ = np.linalg.svd(Mmat / np.linalg.norm(Mmat, axis=0),
                                    full_matrices=False)
            self._T = U
        elif normed:
            self._T = Mmat / np.linalg.norm(Mmat, axis=0)
        else:
            self._T = Mmat.copy()

    def get_basis(self):
        return self._T

    def get_phi(self, params):
        return np.full(self._T.shape[1], 1e40)


class FourierGPSignal(BasisSignal):
    """Rank-reduced Fourier-basis GP (red noise / common GW process / DM).

    ``psd_name`` selects from ``models/psd.py``; ``psd_params`` is the
    ordered list of hyperparameter objects matching the psd function
    signature after ``(f, df)``.  ``orf_name`` tags common processes with
    their inter-pulsar correlation (consumed by the PTA container; the
    per-pulsar phi is ORF-independent).

    ``chrom_index`` (with per-TOA ``radio_freqs`` in MHz) makes the
    process chromatic: each basis row is scaled by ``(1400/nu)^index``
    (index 2 = dispersion-measure variations, 4 = chromatic scattering;
    the reference gets these from enterprise's dm/chrom noise blocks,
    ``model_definition.py:19-31``).  Amplitudes are thus referenced to
    1400 MHz.  Chromatic signals keep their own basis columns — they
    cannot share the achromatic Fourier block.

    ``row_mask`` restricts the process to a subset of TOAs (rows outside
    the mask are zeroed) — the mechanism behind ``red_select``
    band/backend-split intrinsic red noise (reference ``model_general``
    kwarg ``red_select``).  Masked signals keep their own columns too.

    ``pshift_seed`` adds a deterministic random phase to every Fourier
    mode (``model_general(pshift=True)``, used for false-alarm studies);
    ``wgts`` overrides the per-bin summation weights (``sqrt(df)``), the
    ``wgts`` kwarg of ``model_general``.
    """

    def __init__(self, toas_mjd, nmodes: int, Tspan: float, psd_name: str,
                 psd_params: list, name: str, modes=None, orf_name: str = "crn",
                 radio_freqs=None, chrom_index: float | None = None,
                 row_mask=None, pshift_seed=None, wgts=None,
                 orf_ifreq: int = 0, leg_lmax: int = 5,
                 share_group: str = "fourier", orf_params: list = ()):
        self.name = name
        #: sampled ORF shape weights (bin_orf/legendre_orf) ride along for
        #: parameter collection but are not PSD arguments
        self.psd_params = list(psd_params)
        self.orf_params = list(orf_params)
        self.params = self.psd_params + self.orf_params
        self.psd_name = psd_name
        self.orf_name = orf_name
        # ORF-shape options (consumed by models/orf.py for the freq_hd and
        # legendre_orf families; inert for other ORFs, as in the reference)
        self.orf_ifreq = int(orf_ifreq)
        self.leg_lmax = int(leg_lmax)
        #: achromatic signals in the same share_group share basis columns
        #: (phi adds there — marginally identical to separate columns with
        #: separate phis).  A correlated common process gets its own group
        #: so its columns stay disjoint from intrinsic red: the joint
        #: cross-pulsar prior is then purely rho_k G on those columns
        #: while red keeps a per-pulsar diagonal — what makes HD + red
        #: sampling exact with the existing machinery.
        self.share_group = share_group
        self.nmodes = nmodes
        self.Tspan = Tspan
        self.chromatic = chrom_index is not None
        self.shares_fourier = not self.chromatic and row_mask is None
        phases = None
        if pshift_seed is not None:
            nm = nmodes if modes is None else len(modes)
            phases = np.random.default_rng(pshift_seed).uniform(
                0.0, 2.0 * np.pi, nm)
        self._F, self._f = fourier_basis(toas_mjd, nmodes, Tspan, modes=modes,
                                         pshift_phases=phases)
        if self.chromatic:
            scale = (1400.0 / np.asarray(radio_freqs)) ** float(chrom_index)
            self._F = self._F * scale[:, None]
        if row_mask is not None:
            self._F = self._F * np.asarray(row_mask, dtype=float)[:, None]
        # per-column bin width: spacing between consecutive unique
        # frequencies, first bin measured from 0 (uniform 1/Tspan on the
        # default grid; essential for logfreq/custom grids)
        funique = np.unique(self._f)
        self._df = np.repeat(np.diff(np.concatenate([[0.0], funique])), 2)
        if wgts is not None:
            self._df = np.repeat(np.asarray(wgts, dtype=np.float64) ** 2, 2)
        if psd_name == "spectrum":            # model_general's name for it
            psd_name = "free_spectrum"
            self.psd_name = psd_name
        self._psd_fn = getattr(psdmod, psd_name)

    def get_basis(self):
        return self._F

    @property
    def freqs(self):
        """Per-column frequencies (each repeated for sin/cos)."""
        return self._f

    def get_phi(self, params: dict):
        vals = self._mapped(params)
        args = [vals[p.name] for p in self.psd_params]
        if self.psd_name == "free_spectrum":
            return psdmod.free_spectrum(self._f, self._df, *args)
        return self._psd_fn(self._f, self._df, *args)


class DMAnnualSignal(BasisSignal):
    """Linearized annual dispersion-measure variation.

    Two nu^-2-scaled columns, ``sin(2 pi t / yr)`` and ``cos(2 pi t /
    yr)``, marginalized like timing-model columns (improper prior).  The
    reference's ``dm_annual`` is a deterministic sinusoid with sampled
    amplitude and phase (enterprise ``dm_annual``,
    ``model_definition.py:19-31``); amplitude x phase parameterizes
    exactly the 2-d linear subspace these columns span, so marginalizing
    the linear coefficients covers the same component without a nonlinear
    sampling block.
    """

    name = "dm_annual"
    YEAR = 365.25 * 86400.0

    def __init__(self, toas_sec: np.ndarray, radio_freqs: np.ndarray):
        w = 2.0 * np.pi / self.YEAR
        scale = (1400.0 / np.asarray(radio_freqs)) ** 2
        self._T = np.column_stack([np.sin(w * toas_sec),
                                   np.cos(w * toas_sec)]) * scale[:, None]
        self.params = []

    def get_basis(self):
        return self._T

    def get_phi(self, params):
        return np.full(2, 1e40)


class EcorrBasisSignal(BasisSignal):
    """Epoch-correlated white noise as a basis GP ('basis_ecorr').

    One basis column per observing epoch per backend (TOAs quantized into
    ``dt``-wide epochs), with variance 10^(2 log10_ecorr_backend).  The
    reference requires basis (not kernel) ECORR (``pulsar_gibbs.py:65-68``)
    but its ECORR Gibbs update is disabled; here the basis machinery is
    complete so the ECORR block can be sampled like any other.
    """

    def __init__(self, toas: np.ndarray, masks: dict,
                 params_by_backend: dict, dt_days: float = 10.0,
                 name: str = "basis_ecorr"):
        self.name = name
        cols, owners = [], []
        labels = sorted(params_by_backend)
        for lab in labels:
            mask = masks[lab]
            epochs = _quantize(toas[mask], dt_days * DAY)
            for ep in epochs:
                col = np.zeros(len(toas))
                idx = np.where(mask)[0][ep]
                col[idx] = 1.0
                cols.append(col)
                owners.append(lab)
        self._U = np.column_stack(cols) if cols else np.zeros((len(toas), 0))
        self._owners = owners
        self._by_backend = dict(params_by_backend)
        self.params = [params_by_backend[lab] for lab in labels]

    def get_basis(self):
        return self._U

    def get_phi(self, params: dict):
        vals = self._mapped(params)
        out = np.empty(len(self._owners))
        for jj, lab in enumerate(self._owners):
            out[jj] = 10.0 ** (2.0 * vals[self._by_backend[lab].name])
        return out


def _quantize(toas: np.ndarray, dt_sec: float):
    """Group sorted TOAs into epochs no wider than ``dt_sec`` [s]... input in
    seconds; returns list of index arrays relative to the input."""
    if len(toas) == 0:
        return []
    order = np.argsort(toas)
    groups, cur = [], [order[0]]
    for idx in order[1:]:
        if toas[idx] - toas[cur[0]] <= dt_sec:
            cur.append(idx)
        else:
            groups.append(np.array(cur))
            cur = [idx]
    groups.append(np.array(cur))
    return groups


class WhiteNoiseSignal:
    """Diagonal measurement covariance: per-backend EFAC and EQUAD, plus
    an optional global EQUAD.

    ``N_i = efac_b(i)^2 sigma_i^2 + 10^(2 log10_tnequad_b(i))
    [+ 10^(2 log10_gequad)]`` (the tnequad convention; ``gequad`` is the
    reference's backend-independent extra white term,
    ``model_definition.py`` kwarg ``gequad``).  With ``vary=False`` the
    parameters are Constants (efac 1, equad off) or come from a noise
    dictionary — mirroring ``white_noise_block(vary, select)`` usage at
    reference ``model_definition.py:219-228``.
    """

    name = "measurement_noise"

    def __init__(self, toaerrs: np.ndarray, masks: dict,
                 efac_by_backend: dict, equad_by_backend: dict | None,
                 gequad=None):
        self._sigma2 = toaerrs**2
        labels = sorted(efac_by_backend)
        self._masks = {lab: np.asarray(masks[lab], dtype=bool) for lab in labels}
        self._efac = dict(efac_by_backend)
        self._equad = dict(equad_by_backend) if equad_by_backend else None
        self._gequad = gequad
        self.params = [efac_by_backend[lab] for lab in labels]
        if self._equad:
            self.params += [self._equad[lab] for lab in labels]
        if gequad is not None:
            self.params.append(gequad)

    def get_basis(self):
        return None

    def get_ndiag(self, params: dict):
        vals = {}
        for p in self.params:
            vals[p.name] = p.value if isinstance(p, Constant) else params[p.name]
        N = np.array(self._sigma2)
        for lab, mask in self._masks.items():
            efac = vals[self._efac[lab].name]
            N[mask] = efac**2 * self._sigma2[mask]
            if self._equad:
                N[mask] += 10.0 ** (2.0 * vals[self._equad[lab].name])
        if self._gequad is not None:
            N += 10.0 ** (2.0 * vals[self._gequad.name])
        return N
