"""Profiling subsystem: per-block sweep timers, FLOP/MFU accounting, and
``jax.profiler`` trace capture.

The reference's only instrumentation is a wall-clock print every 100
iterations (``pta_gibbs.py:663,707-711``) and a tqdm bar
(``pulsar_gibbs.py:8,656``).  Here every Gibbs block can be timed as its
own compiled kernel (so the per-sweep cost budget is attributable), the
dominant FLOP terms are counted analytically, and a full XLA trace can be
dumped for tensorboard/xprof.

Typical use::

    drv = JaxGibbsDriver(pta, ...)
    ...run a few sweeps so adaptation state exists...
    report = profile_blocks(drv, x)
    # {"per_block_ms": {block: ms}, "in_sweep": {block: bool},
    #  "sum_blocks_ms": ..., "full_sweep_ms": ..., "dispatch_ms": ...}
    print(format_report(report, flops=sweep_flops(drv.cm)))
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

#: advertised peak dense-matmul throughput per chip, FLOP/s.  Keyed by a
#: substring of ``jax.devices()[0].device_kind``; used only to report MFU,
#: never to gate anything.  f32 rate (the TNT einsums run f32 inputs with
#: wider accumulation).
_PEAK_FLOPS = {
    "v5 lite": 197e12 / 2,   # bf16 197 TFLOP/s, f32 ~ half
    "v5e": 197e12 / 2,
    "v4": 275e12 / 2,
    "cpu": 5e10,
}


#: advertised HBM (or DRAM) bandwidth per chip, bytes/s — the roofline
#: denominator.  Same keying/caveats as ``_PEAK_FLOPS``.
_PEAK_BW = {
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v4": 1228e9,
    "cpu": 5e10,
}


def device_peak_flops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for frag, peak in _PEAK_FLOPS.items():
        if frag in kind:
            return peak
    return _PEAK_FLOPS["cpu"]


def device_peak_bw() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for frag, bw in _PEAK_BW.items():
        if frag in kind:
            return bw
    return _PEAK_BW["cpu"]


def _sync(out):
    """Force completion by a device-to-host copy of one small leaf.

    ``jax.block_until_ready`` does not reliably wait on remote/tunneled
    platforms (observed on the "axon" TPU tunnel: it returns while the
    computation is still in flight); a D2H transfer is an honest barrier.
    """
    import jax

    np.asarray(jax.tree_util.tree_leaves(out)[0])


def _timeit(fn, args, repeats=10):
    """Median wall time of a compiled callable, D2H-synced; compile
    excluded by a warmup call."""
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _scan_time(body2, x, b, inner, repeats):
    """Per-iteration device time of ``body2(x, b, key) -> (x, b)`` measured
    inside a ``lax.scan`` of ``inner`` iterations, with the per-dispatch
    overhead measured separately (a length-1 scan) and subtracted.  On a
    tunneled/remote device a single dispatch costs ~100 ms, so timing one
    kernel call measures the network, not the kernel."""
    import jax
    import jax.random as jr

    def scanned(n):
        def run(x, b, key):
            def step(carry, k):
                x, b = carry
                return body2(x, b, k), None

            (x, b), _ = jax.lax.scan(step, (x, b), jr.split(key, n))
            return x, b

        return jax.jit(run)

    key = jr.key(0)
    t_inner = _timeit(scanned(inner), (x, b, key), repeats)
    t_one = _timeit(scanned(1), (x, b, key), repeats)
    return max(t_inner - t_one, 1e-9) / (inner - 1)


def _block_state(driver, x):
    """The (C, ...) device state tuple ``(x, b)`` the block bodies run
    on, from a host x of either (nx,) or (C, nx) shape."""
    import jax.numpy as jnp

    cm = driver.cm
    x = np.asarray(x, np.float64)
    if x.ndim == 1:
        x = np.tile(x, (driver.C, 1))
    return jnp.asarray(x, cm.cdtype), jnp.asarray(driver.b)


def _block_bodies(driver, x, b):
    """The named per-block bodies of one post-adaptation Gibbs sweep,
    each a ``body(x, b, key) -> (x, b)`` at the driver's actual
    ``nchains`` width (vmapped over the chains axis exactly as the
    production sweep runs it).  Returns ``(bodies, full, in_sweep)``
    where ``full`` is the composed production sweep body and
    ``in_sweep[name]`` says whether that block runs in the every-sweep
    budget of THIS config (refresh slots and kernel cores are measured
    for attribution only).  Shared by the timing path
    (:func:`profile_blocks`) and the static cost path
    (:func:`block_cost_model`), so measured milliseconds and counted
    FLOPs always describe the same program.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from .sampler import jax_backend as jb

    cm = driver.cm
    C = driver.C
    out = {}

    def vm(single):
        """Lift a per-chain body (x1, b1, k1) -> (x1, b1) to the (C, ...)
        state with per-chain keys — the production sweep's layout."""
        def body(x, b, k):
            return jax.vmap(single)(x, b, jr.split(k, C))

        return body

    if len(cm.idx.white) and driver.aclength_white:
        nw = driver.aclength_white
        aux_w = tuple(jnp.asarray(a, cm.dtype) for a in (
            driver.chol_white, driver.mode_white, driver.asqrt_white))

        def white1(x, b, k, chol, mw, aw):
            r = jnp.asarray(cm.y, cm.dtype) - jb.b_matvec(cm, b)
            xn, _ = jb.parallel_cov_mh_scan(
                cm, x, k, jb.white_block_ll(cm, x, r, r * r),
                cm.white_par_ix,
                cm.white_nper, chol, nw, record=False, mode=mw, asqrt=aw)
            return xn, b

        def white(x, b, k):
            return jax.vmap(white1)(x, b, jr.split(k, C), *aux_w)

        out[f"white_mh[{nw}]"] = white

    if len(cm.idx.ecorr) and driver.aclength_ecorr and (cm.ec_cols.shape[1]
                                                        or cm.has_ke):
        ne = driver.aclength_ecorr
        aux_e = tuple(jnp.asarray(a, cm.dtype) for a in (
            driver.chol_ecorr, driver.mode_ecorr, driver.asqrt_ecorr))

        def ecorr1(x, b, k, chol, me, ae):
            r = jnp.asarray(cm.y, cm.dtype) - jb.b_matvec(cm, b)
            xn, _ = jb.parallel_cov_mh_scan(
                cm, x, k, jb.ecorr_block_ll(cm, x, b, r), cm.ecorr_par_ix,
                cm.ecorr_nper, chol, ne, record=False, mode=me, asqrt=ae)
            return xn, b

        def ecorr(x, b, k):
            return jax.vmap(ecorr1)(x, b, jr.split(k, C), *aux_e)

        out[f"ecorr_mh[{ne}]"] = ecorr

    if driver.do_red_conditional:
        out["red_conditional"] = vm(
            lambda x, b, k: (jb.red_conditional_update(cm, x, b, k), b))

    if driver.do_red_mh:
        ns = driver.red_steps
        U = jnp.asarray(driver.red_U)
        S = jnp.asarray(driver.red_S)
        # time the production kernel incl. the DE history gather
        hist = (None if driver.red_hist is None
                else jnp.asarray(driver.red_hist, cm.cdtype))

        def red1(x, b, k, U, S, h):
            return jb.red_mh_block(cm, x, b, k, U, S, ns, hist=h), b

        def redmh(x, b, k):
            return jax.vmap(red1)(x, b, jr.split(k, C), U, S, hist)

        out[f"red_mh[{ns}]"] = redmh

    if cm.K and len(cm.rho_ix_x):
        out["rho_gumbel"] = vm(
            lambda x, b, k: (jb.rho_update(cm, x, b, k), b))

    # the steady-sweep b-draw as the production body runs it: mixed /
    # two-float kernels for the structured joint (non-CRN) path, the f64
    # exact CRN draw otherwise (CRN steady sweeps run b_mh below — its
    # in_sweep flag says so)
    out["b_draw"] = vm(lambda x, b, k: (x, jb.draw_b_fn(cm, x, k, b)))
    if cm.orf_name != "crn":
        # the periodic exact_every refresh slot: the f64 factorization of
        # the same joint system (never in the every-sweep budget)
        out["b_draw_exact"] = vm(
            lambda x, b, k: (x, jb.draw_b_fn(cm, x, k, b, exact=True)))
    if cm.orf_name == "crn" and not cm.has_ke:
        # the production refresh slot (exact_every): Metropolised
        # segmented-Gram draw, cheaper than the f64 exact draw above
        def refresh1(x1, b1, k1):
            u1 = jb.b_matvec(cm, b1)
            bn, _, _ = jb.draw_b_refresh(cm, x1, b1, u1, k1)
            return x1, bn

        out["b_refresh"] = vm(refresh1)

        # the every-sweep Metropolised draw and its N-axis-heavy core (the
        # f32 Gram einsum): how much of full_sweep rides the padded TOA
        # axis decides whether TOA-bucketing the hot einsums pays
        def bmh1(x1, b1, k1):
            u1 = jb.b_matvec(cm, b1)
            bn, _, _ = jb.draw_b_mh(cm, x1, b1, u1, k1)
            return x1, bn

        out["b_mh"] = vm(bmh1)

        def gram1(x1, b1, k1):
            N = cm.ndiag_fast(x1)
            TNT, _d = jb.tnt_d_seg32(cm, N)
            return x1, b1 + 0.0 * TNT[:, : b1.shape[1], 0]

        out["gram32"] = vm(gram1)

        def rsq1(x1, b1, k1):
            r2 = jb.residual_sq(cm, b1)
            return x1 + 0.0 * r2[0, 0], b1

        out["residual_sq"] = vm(rsq1)

    # the composed sweep (this is what the chunked driver actually runs;
    # t=1 exercises the Metropolised-b-draw branch)
    body = driver._sweep_body()
    aux = driver._aux()

    def full(x, b, k):
        def one(x1, b1, k1, a):
            u1 = jb.b_matvec(cm, b1)
            (x1, b1, _), _ = body((x1, b1, u1), k1, a, 1)
            return x1, b1

        xn, bn = jax.vmap(one, in_axes=(0, 0, 0, 0))(x, b,
                                                     jr.split(k, C), aux)
        return xn, bn

    # reconciliation layer: per_block_ms entries are only comparable to
    # full_sweep_ms when the block actually runs in the every-sweep body
    # of THIS config — b_draw=404 ms sitting next to full_sweep=10.8 ms
    # with no flag is how BENCH_r05's numbers got misread.  in_sweep=False
    # blocks are measured for attribution (periodic refresh slots, kernel
    # cores) and are excluded from sum_blocks_ms.
    in_sweep = {}
    for name in out:
        if name == "b_draw":
            in_sweep[name] = cm.orf_name != "crn" or cm.has_ke
        elif name == "b_mh":
            in_sweep[name] = True          # the CRN steady draw
        elif name in ("b_refresh", "b_draw_exact", "gram32", "residual_sq"):
            in_sweep[name] = False
        else:
            in_sweep[name] = True          # white/ecorr/red/rho blocks
    return out, full, in_sweep


def profile_blocks(driver, x, repeats=5, inner=50):
    """Per-block device times (seconds per sweep) of one post-adaptation
    Gibbs sweep, at the driver's actual ``nchains`` width (each block is
    vmapped over the chains axis exactly as the production sweep runs it,
    so the breakdown sums to the real sweep and matches the MFU line).
    Each block is timed inside its own ``lax.scan`` of ``inner``
    iterations so per-dispatch overhead (dominant on remote devices)
    cancels; ``dispatch`` reports that overhead per call.  Requires the
    driver to have completed adaptation (``_first_sweep``).

    The report also carries the static cost model's per-block FLOP/byte
    counts joined with these times as a roofline attribution table
    (``"roofline"`` key, best-effort: ``None`` when tracing fails).
    """
    import jax
    import jax.numpy as jnp

    x_host = x
    x, b = _block_state(driver, x)
    bodies, full, in_sweep = _block_bodies(driver, x, b)
    out = {name: _scan_time(body, x, b, inner, repeats)
           for name, body in bodies.items()}
    full_sweep = _scan_time(full, x, b, inner, repeats)
    dispatch = _timeit(
        jax.jit(lambda x: x + 1.0), (jnp.zeros(()),), repeats)

    per_block_ms = {k: v * 1e3 for k, v in out.items()}
    per_block_ms["full_sweep"] = full_sweep * 1e3
    costs = roof = None
    try:
        costs = block_cost_model(driver, x_host)
        roof = roofline(costs, per_block_ms)
    except Exception:     # noqa: BLE001 — attribution is best-effort
        pass
    per_block_ms.pop("full_sweep")
    try:
        breakdown = dispatch_breakdown(driver, x_host)
    except Exception:     # noqa: BLE001 — the breakdown is best-effort
        breakdown = None
    return {
        "per_block_ms": per_block_ms,
        "in_sweep": in_sweep,
        "sum_blocks_ms": sum(v for k, v in per_block_ms.items()
                             if in_sweep[k]),
        "full_sweep_ms": full_sweep * 1e3,
        "dispatch_ms": dispatch * 1e3,
        "dispatch_breakdown_ms": breakdown,
        "block_costs": costs,
        "roofline": roof,
    }


def block_cost_model(driver, x):
    """Static per-block FLOP + HBM-byte counts of the same bodies
    :func:`profile_blocks` times, via the jaxprcheck C6 cost walker
    (host-side tracing only — nothing executes).  Returns
    ``{block: {"flops", "dot_flops", "hbm_bytes", "intensity"}}``
    including the composed ``full_sweep``."""
    import jax.random as jr

    from .analysis.jaxprcheck.cost import jaxpr_cost
    from .analysis.jaxprcheck.walk import trace_jaxpr

    x, b = _block_state(driver, x)
    bodies, full, _ = _block_bodies(driver, x, b)
    key = jr.key(0)
    costs = {}
    for name, body in {**bodies, "full_sweep": full}.items():
        costs[name] = jaxpr_cost(trace_jaxpr(body, (x, b, key))).as_dict()
    return costs


def roofline(costs, per_block_ms=None, peak_flops=None, peak_bw=None):
    """Join static per-block costs with measured per-block times into a
    roofline attribution table: arithmetic intensity (FLOP/byte) against
    the device ridge point classifies each block compute- vs
    bandwidth-bound; measured times add per-block MFU and
    bandwidth-utilization fractions.  ``per_block_ms`` is optional —
    without it the classification is purely static."""
    peak = peak_flops if peak_flops is not None else device_peak_flops()
    bw = peak_bw if peak_bw is not None else device_peak_bw()
    ridge = peak / bw
    blocks = {}
    for name, c in costs.items():
        ai = c["flops"] / c["hbm_bytes"] if c["hbm_bytes"] else 0.0
        row = {
            "gflops": c["flops"] / 1e9,
            "hbm_mib": c["hbm_bytes"] / 2 ** 20,
            "intensity": ai,
            "bound": "compute" if ai >= ridge else "bandwidth",
        }
        ms = (per_block_ms or {}).get(name)
        if ms and ms > 0:
            t = ms / 1e3
            row["ms"] = ms
            row["mfu"] = c["flops"] / t / peak
            row["bw_frac"] = c["hbm_bytes"] / t / bw
        blocks[name] = row
    return {"peak_flops": peak, "peak_bytes_per_sec": bw,
            "ridge_flop_per_byte": ridge, "blocks": blocks}


def dispatch_breakdown(driver, x):
    """Stage decomposition of ONE steady chunk dispatch, staged exactly
    the way ``JaxGibbsDriver.run()`` stages it — the per-chunk analogue
    of the span taxonomy in docs/OBSERVABILITY.md:

    - ``host_prep``  argument staging (explicit ``device_put`` of the
      host scalars, aux assembly) before the dispatch;
    - ``enqueue``    the jitted chunk call returning — on an async
      backend this is the host-side cost of getting the compiled
      program in flight, NOT the compute;
    - ``device``     the remaining wait for the chunk's results
      (``block_until_ready`` beyond the enqueue return);
    - ``writeback``  the device->host conversion of the recorded x/b
      stacks (on a tunneled device this is the transfer).

    ``dispatch_ms`` in the :func:`profile_blocks` report remains the
    bare per-call jit overhead (a scalar no-op); this says where a real
    chunk's wall actually goes.  The stages also emit ``profile.*``
    trace spans when the obs trace layer is enabled.

    A ``megachunk > 1`` driver is probed through its mega dispatch
    (``_mega_fn``) so the stages describe the program production runs;
    ``sweeps_per_dispatch`` and ``dispatch_amortized_per_sweep``
    ((host_prep + enqueue + writeback) / sweeps) report how far the
    dispatch tax is amortized — the bench headline
    ``dispatch_amortized_ms_per_sweep`` is read from here.
    """
    import jax
    import jax.numpy as jnp

    from .obs import trace as otrace

    cm = driver.cm
    x = np.asarray(x, np.float64)
    if x.ndim == 1:
        x = np.tile(x, (driver.C, 1))
    n = driver.chunk_size
    n_sub = max(1, int(getattr(driver, "megachunk", 1)))
    M = n * n_sub
    if n_sub > 1:
        fn = driver._mega_fn(n, n_sub, 0)
    else:
        fn = driver._chunk_fn(n, 0)
    obs_on = driver.obs is not None

    def staged():
        # fresh carry copies per call: the mega dispatch DONATES its
        # carries, so a reused buffer would be dead on the second probe
        xd = jnp.asarray(x, cm.cdtype)
        # copy=True even when driver.b already lives on device: asarray
        # would alias the driver's live buffer and the donation above
        # would delete it out from under the run (and the next repeat)
        bd = jnp.array(driver.b, copy=True)
        jax.block_until_ready((xd, bd))
        t0 = time.perf_counter()
        with otrace.span("profile.host_prep"):
            aux = (driver._aux_mega(None, 0, n_sub) if n_sub > 1
                   else driver._aux())
            args = (xd, bd, driver.key, jax.device_put(np.int32(0)),
                    aux, jax.device_put(np.int32(M)))
            if obs_on:
                args = args + (driver._obs_state,)
        t1 = time.perf_counter()
        with otrace.span("profile.enqueue"):
            outs = fn(*args)
        t2 = time.perf_counter()
        with otrace.span("profile.device"):
            jax.block_until_ready(outs[:5])
        t3 = time.perf_counter()
        with otrace.span("profile.writeback"):
            np.asarray(outs[2])
            np.asarray(outs[3])
        t4 = time.perf_counter()
        return t1 - t0, t2 - t1, t3 - t2, t4 - t3

    staged()              # warm: the chunk fn may still need compiling
    hp, eq, dv, wb = staged()
    out = {"host_prep": hp * 1e3, "enqueue": eq * 1e3,
           "device": dv * 1e3, "writeback": wb * 1e3,
           "sweeps_per_dispatch": float(M),
           # the headline this probe exists for: every ms the host spends
           # around the device wait, amortized over the sweeps one
           # dispatch covers
           "dispatch_amortized_per_sweep": (hp + eq + wb) * 1e3 / M}
    # the one-shot probe publishes the same dispatch_ms family the
    # streaming StageAggregator feeds, tagged stat="probe" so the scrape
    # distinguishes a staged measurement from live EMA/percentiles
    from .runtime import telemetry

    for stage in ("host_prep", "enqueue", "device", "writeback"):
        telemetry.gauge("dispatch_ms", out[stage], stage=stage,
                        stat="probe")
    telemetry.gauge("dispatch_ms", out["dispatch_amortized_per_sweep"],
                    stage="dispatch_amortized", stat="probe")
    return out


def flop_counts(cm, nchains=1):
    """Analytic per-term FLOP counts of the dominant per-sweep kernels —
    the ground truth the static cost model (C6) is validated against.

    Only the terms that can matter on a TPU are counted, each under its
    own key so the jaxpr-derived ``dot_general`` counts can be compared
    term-by-term: the TNT Gram einsum (2 P N B^2), the T b basis matvec
    (2 P N B), the batched Cholesky (P B^3 / 3) and triangular solves
    (3 P B^2).  Elementwise work (grids, MH deltas) is bandwidth- not
    FLOP-bound and is excluded.
    """
    P, N, B = cm.P, cm.Nmax, cm.Bmax
    return {
        "gram_einsum": 2.0 * P * N * B * B * nchains,
        "basis_matvec": 2.0 * P * N * B * nchains,
        "cholesky": P * (B ** 3) / 3.0 * nchains,
        "tri_solves": 3.0 * P * B * B * nchains,
    }


def sweep_flops(cm, nchains=1):
    """The :func:`flop_counts` terms folded into the historical bench
    shape (``tnt_einsum`` = Gram + matvec, ``cholesky`` = factor +
    solves, plus ``total``)."""
    fc = flop_counts(cm, nchains)
    ein = fc["gram_einsum"] + fc["basis_matvec"]
    chol = fc["cholesky"] + fc["tri_solves"]
    return {"tnt_einsum": ein, "cholesky": chol, "total": ein + chol}


def format_report(report: dict, flops: dict | None = None,
                  sweeps_per_sec: float | None = None) -> str:
    """Human-readable per-block breakdown of a :func:`profile_blocks`
    report, optionally with achieved FLOP/s and MFU when the sweep rate
    is known.  Blocks outside the every-sweep body are tagged
    ``[off-sweep]`` and the in-sweep subtotal is printed next to the
    composed ``full_sweep`` so the two visibly reconcile."""
    lines = ["per-block sweep profile:"]
    per_block = report["per_block_ms"]
    in_sweep = report["in_sweep"]
    for k, v in sorted(per_block.items(), key=lambda kv: -kv[1]):
        tag = "" if in_sweep.get(k, True) else "   [off-sweep]"
        lines.append(f"  {k:<20s} {v:8.2f} ms{tag}")
    lines.append(f"  {'sum(in-sweep)':<20s} {report['sum_blocks_ms']:8.2f} "
                 "ms")
    lines.append(f"  {'full_sweep':<20s} {report['full_sweep_ms']:8.2f} ms")
    lines.append(f"  {'dispatch':<20s} {report['dispatch_ms']:8.2f} ms")
    bd = report.get("dispatch_breakdown_ms")
    if bd:
        parts = " + ".join(
            f"{k} {bd[k]:.1f}"
            for k in ("host_prep", "enqueue", "device", "writeback")
            if k in bd)
        lines.append(f"  chunk stages: {parts} ms")
        if "dispatch_amortized_per_sweep" in bd:
            lines.append(
                f"  {'dispatch/sweep':<20s} "
                f"{bd['dispatch_amortized_per_sweep']:8.3f} ms  "
                f"({bd.get('sweeps_per_dispatch', 1):.0f} sweeps/dispatch)")
    roof = report.get("roofline")
    if roof:
        lines.append(
            f"roofline attribution (peak {roof['peak_flops']:.3g} FLOP/s, "
            f"{roof['peak_bytes_per_sec']:.3g} B/s, ridge "
            f"{roof['ridge_flop_per_byte']:.0f} FLOP/B):")
        lines.append(f"  {'block':<20s} {'GFLOP':>9s} {'MiB':>9s} "
                     f"{'AI':>7s} {'MFU%':>7s} {'BW%':>6s}  bound")
        rows = sorted(roof["blocks"].items(),
                      key=lambda kv: -kv[1].get("ms", 0.0))
        for name, r in rows:
            mfu = (f"{100 * r['mfu']:7.3f}" if "mfu" in r
                   else f"{'-':>7s}")
            bwf = (f"{100 * r['bw_frac']:6.2f}" if "bw_frac" in r
                   else f"{'-':>6s}")
            lines.append(
                f"  {name:<20s} {r['gflops']:9.3f} {r['hbm_mib']:9.2f} "
                f"{r['intensity']:7.1f} {mfu} {bwf}  {r['bound']}")
    if flops and sweeps_per_sec:
        achieved = flops["total"] * sweeps_per_sec
        peak = device_peak_flops()
        lines.append(f"  counted FLOPs/sweep   {flops['total']:.3g}")
        lines.append(f"  achieved FLOP/s       {achieved:.3g} "
                     f"(MFU {100.0 * achieved / peak:.2f}% of {peak:.3g})")
    return "\n".join(lines)


@contextlib.contextmanager
def trace(outdir: str):
    """Dump a full XLA profiler trace (view with tensorboard/xprof)."""
    import jax

    jax.profiler.start_trace(outdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def recompile_counter():
    """Attached :class:`~.analysis.guards.RecompileCounter` context manager.

    Counts XLA backend compiles (via ``jax.monitoring``) inside the
    block; after warmup a steady sweep loop must report zero.  Compiles
    are attributed to named phases (``rc.phase("warmup")`` /
    ``rc.phase("steady")``), and compiles the driver knowingly performs
    (cache-miss chunk dispatches, bracketed with
    ``analysis.guards.planned_compile``) are tracked separately, so
    ``rc.unplanned("steady")`` is the honest retrace count — warmup
    compiles cannot pollute it.  Re-exported here so benchmarking code
    (``bench.py``) gets the retrace counter from the same module as the
    timers::

        with recompile_counter() as rc:
            rc.phase("warmup"); warmup()
            rc.phase("steady"); run_steady_loop()
        assert rc.unplanned("steady") == 0
    """
    from .analysis.guards import count_recompiles

    return count_recompiles()
