from .dataset import (Pulsar, load_pulsar, load_directory, get_tspan,
                      from_enterprise, load_enterprise_snapshot)
from .partim import parse_par, parse_tim
from .fourier import fourier_basis
from .design import design_matrix

__all__ = [
    "Pulsar",
    "load_pulsar",
    "load_directory",
    "get_tspan",
    "from_enterprise",
    "load_enterprise_snapshot",
    "parse_par",
    "parse_tim",
    "fourier_basis",
    "design_matrix",
]
