from .dataset import (Pulsar, load_pulsar, load_directory, get_tspan,
                      from_enterprise, load_enterprise_snapshot)
from .append import append_polynomial_toas, append_toas, dataset_digest
from .partim import parse_par, parse_tim
from .fourier import fourier_basis
from .design import design_matrix

__all__ = [
    "Pulsar",
    "load_pulsar",
    "load_directory",
    "get_tspan",
    "from_enterprise",
    "load_enterprise_snapshot",
    "append_toas",
    "append_polynomial_toas",
    "dataset_digest",
    "parse_par",
    "parse_tim",
    "fourier_basis",
    "design_matrix",
]
