"""Fourier (rank-reduced GP) basis.

Sin/cos pairs at ``f_j = j / Tspan`` — the basis every red-noise and GW
signal in the reference rides on (enterprise's
``createfourierdesignmatrix_red``; consumed at ``pulsar_gibbs.py:95-105``
where GW basis indices are located, and at ``:208-209`` where sin/cos pairs
are folded into ``tau``).  Columns are interleaved ``[sin f_1, cos f_1,
sin f_2, ...]`` so that the sampler's pairwise reduction over ``[::2]`` /
``[1::2]`` strides matches reference semantics.
"""

from __future__ import annotations

import numpy as np

DAY = 86400.0


def fourier_basis(toas_mjd: np.ndarray, nmodes: int, Tspan: float,
                  modes: np.ndarray | None = None,
                  pshift_phases: np.ndarray | None = None):
    """Return ``(F, f)``: basis (n, 2*nmodes) and per-column frequencies.

    Parameters
    ----------
    toas_mjd : TOA epochs in MJD
    nmodes : number of frequencies
    Tspan : span in seconds defining the fundamental ``1/Tspan``
    modes : optional explicit frequency list [Hz], overrides the linear grid
    pshift_phases : optional per-frequency phase offsets [rad] added inside
        the sin/cos arguments — the ``pshift`` random-phase-shift option of
        the reference's ``model_general`` (``model_definition.py`` kwarg
        ``pshift``, enterprise ``createfourierdesignmatrix_red``) used for
        false-alarm / sky-scramble studies
    """
    t = toas_mjd * DAY
    if modes is None:
        f = np.arange(1, nmodes + 1) / Tspan
    else:
        f = np.asarray(modes, dtype=np.float64)
        nmodes = len(f)
    F = np.zeros((len(t), 2 * nmodes))
    arg = 2.0 * np.pi * t[:, None] * f[None, :]
    if pshift_phases is not None:
        arg = arg + np.asarray(pshift_phases, dtype=np.float64)[None, :]
    F[:, ::2] = np.sin(arg)
    F[:, 1::2] = np.cos(arg)
    return F, np.repeat(f, 2)
