"""Linear timing-model design matrix.

In the reference, the design matrix ``M`` comes out of tempo2/PINT via
``enterprise``'s ``Pulsar`` object and enters the sampler only as the
timing-model block of the combined basis ``T`` (reference
``pulsar_gibbs.py:499`` pulls it through ``pta.get_basis``); its columns are
then analytically marginalized with an (effectively) infinite prior variance.
Because only the *column space* of ``M`` matters for that marginalization,
this module builds an equivalent linear basis directly from the fitted
parameters listed in the par file, using the standard leading-order timing
partials:

- phase offset, spin frequency and derivatives  -> ``1, t, t^2 (, t^3)``
- sky position                                  -> annual sin/cos
- proper motion                                 -> ``t *`` annual sin/cos
- parallax                                      -> semi-annual sin/cos
- DM and derivatives                            -> ``1/nu^2 (, t/nu^2)``
- Keplerian binary parameters                   -> orbital-phase harmonics
  (2 harmonics; +2 more when Shapiro-sensitive params M2/SINI are fitted,
  since the Shapiro delay is sharply peaked at conjunction)

The matrix is full column rank over the shipped ``simulated_data/`` corpus
and is consumed after SVD orthonormalization or column normalization (see
``models/signals.py``, mirroring the reference's ``tm_svd``/``tm_norm``
options at ``model_definition.py:42-46``).
"""

from __future__ import annotations

import numpy as np

from .partim import ParFile, TimFile

DAY = 86400.0
YEAR = 365.25 * DAY


def design_matrix(par: ParFile, tim: TimFile) -> np.ndarray:
    """Build the (n_toa, n_col) timing design matrix for the fitted params."""
    t = (tim.mjds - tim.mjds.mean()) * DAY            # seconds, centered
    tyr = 2.0 * np.pi * t / YEAR                      # annual phase
    cols = [np.ones_like(t)]                          # overall phase offset

    fitted = set(par.fitted)

    # spin frequency and derivatives
    if "F0" in fitted:
        cols.append(t)
    if "F1" in fitted:
        cols.append(t**2)
    if "F2" in fitted:
        cols.append(t**3)

    # astrometry: position -> annual; proper motion -> t * annual;
    # parallax -> semi-annual
    if fitted & {"RAJ", "DECJ", "ELONG", "ELAT", "LAMBDA", "BETA"}:
        cols += [np.sin(tyr), np.cos(tyr)]
    if fitted & {"PMRA", "PMDEC", "PMELONG", "PMELAT", "PMLAMBDA", "PMBETA"}:
        cols += [t * np.sin(tyr), t * np.cos(tyr)]
    if "PX" in fitted:
        cols += [np.sin(2 * tyr), np.cos(2 * tyr)]

    # dispersion measure
    nu2 = (tim.freqs / 1400.0) ** 2
    nu2 = np.where(nu2 > 0, nu2, 1.0)
    if "DM" in fitted and np.ptp(tim.freqs) > 0:
        cols.append(1.0 / nu2)
    if "DM1" in fitted and np.ptp(tim.freqs) > 0:
        cols.append(t / nu2)

    # binary: harmonics of the orbital phase
    kepler = {"PB", "T0", "TASC", "A1", "OM", "ECC", "EPS1", "EPS2",
              "PBDOT", "XDOT", "OMDOT", "M2", "SINI", "KIN", "KOM", "GAMMA"}
    fitted_binary = fitted & kepler
    pb = par.get("PB")
    if fitted_binary and pb:
        t0 = par.get("T0", par.get("TASC", tim.mjds.mean()))
        phase = 2.0 * np.pi * ((tim.mjds - t0) / pb)
        n_harm = 2
        if fitted_binary & {"M2", "SINI", "KIN"}:
            n_harm = 4
        for k in range(1, n_harm + 1):
            cols += [np.sin(k * phase), np.cos(k * phase)]

    M = np.column_stack(cols)
    return _drop_degenerate(M)


def _drop_degenerate(M: np.ndarray, rtol: float = 1e-10) -> np.ndarray:
    """Drop columns that are numerically inside the span of earlier ones.

    The rank test runs on unit-normalized columns; raw timing partials span
    ~18 orders of magnitude (t^2 in s^2 vs the ones column) and would
    otherwise defeat a scale-blind singular-value threshold.
    """
    norms = np.linalg.norm(M, axis=0)
    Mn = M / np.where(norms > 0, norms, 1.0)
    keep = []
    for j in range(Mn.shape[1]):
        if norms[j] == 0:
            continue
        s = np.linalg.svd(Mn[:, keep + [j]], compute_uv=False)
        if s[-1] > rtol * s[0]:
            keep.append(j)
    return M[:, keep]
