"""Linear timing-model design matrix.

In the reference, the design matrix ``M`` comes out of tempo2/PINT via
``enterprise``'s ``Pulsar`` object and enters the sampler only as the
timing-model block of the combined basis ``T`` (reference
``pulsar_gibbs.py:499`` pulls it through ``pta.get_basis``); its columns are
then analytically marginalized with an (effectively) infinite prior variance.
Because only the *column space* of ``M`` matters for that marginalization,
this module builds an equivalent linear basis directly from the fitted
parameters listed in the par file, using the standard leading-order timing
partials:

- phase offset, spin frequency and derivatives  -> ``1, t, t^2 (, t^3)``
- sky position                                  -> annual sin/cos
- proper motion                                 -> ``t *`` annual sin/cos
- parallax                                      -> semi-annual sin/cos
- DM and derivatives                            -> ``1/nu^2 (, t/nu^2)``
- DMX windows (DMX_/DMXR1_/DMXR2_)              -> windowed ``1/nu^2``
- FD profile-evolution terms                    -> ``log(nu/1 GHz)^k``
- JUMP system offsets (flag/MJD form)           -> indicator columns
- Keplerian binary parameters                   -> orbital-phase harmonics
  (2 harmonics; +2 more when Shapiro-sensitive params M2/SINI are fitted,
  since the Shapiro delay is sharply peaked at conjunction)

The DMX/FD/JUMP rows give a real-format NANOGrav par file the same column
structure ``tools/make_enterprise_snapshot.py`` hand-builds for the
hermetic enterprise-surface snapshot (r4 VERDICT missing #1).

The matrix is full column rank over the shipped ``simulated_data/`` corpus
and is consumed after SVD orthonormalization or column normalization (see
``models/signals.py``, mirroring the reference's ``tm_svd``/``tm_norm``
options at ``model_definition.py:42-46``).
"""

from __future__ import annotations

import re

import numpy as np

from .partim import ParFile, TimFile

DAY = 86400.0
YEAR = 365.25 * DAY

_FD_RE = re.compile(r"^FD(\d+)$")


def design_matrix(par: ParFile, tim: TimFile, return_labels: bool = False):
    """Build the (n_toa, n_col) timing design matrix for the fitted params.

    With ``return_labels=True`` also returns one name per surviving
    column (the enterprise ``fitpars``-style surface: real DMX_/FD/JUMP
    tags where the par file carries them, generic partial names
    elsewhere)."""
    t = (tim.mjds - tim.mjds.mean()) * DAY            # seconds, centered
    tyr = 2.0 * np.pi * t / YEAR                      # annual phase
    cols = [np.ones_like(t)]                          # overall phase offset
    labels = ["Offset"]

    fitted = set(par.fitted)

    # spin frequency and derivatives
    if "F0" in fitted:
        cols.append(t)
        labels.append("F0")
    if "F1" in fitted:
        cols.append(t**2)
        labels.append("F1")
    if "F2" in fitted:
        cols.append(t**3)
        labels.append("F2")

    # astrometry: position -> annual; proper motion -> t * annual;
    # parallax -> semi-annual
    if fitted & {"RAJ", "DECJ", "ELONG", "ELAT", "LAMBDA", "BETA"}:
        cols += [np.sin(tyr), np.cos(tyr)]
        labels += ["POS_SIN", "POS_COS"]
    if fitted & {"PMRA", "PMDEC", "PMELONG", "PMELAT", "PMLAMBDA", "PMBETA"}:
        cols += [t * np.sin(tyr), t * np.cos(tyr)]
        labels += ["PM_SIN", "PM_COS"]
    if "PX" in fitted:
        cols += [np.sin(2 * tyr), np.cos(2 * tyr)]
        labels += ["PX_SIN", "PX_COS"]

    # dispersion measure
    nu2 = (tim.freqs / 1400.0) ** 2
    nu2 = np.where(nu2 > 0, nu2, 1.0)
    if "DM" in fitted and np.ptp(tim.freqs) > 0:
        cols.append(1.0 / nu2)
        labels.append("DM")
    if "DM1" in fitted and np.ptp(tim.freqs) > 0:
        cols.append(t / nu2)
        labels.append("DM1")

    # DMX: piecewise-constant dispersion windows, the NANOGrav convention
    # (fitted DMX_#### with DMXR1_/DMXR2_ window bounds) — the column
    # structure enterprise gets from tempo2 and the reference consumes
    # through pta.get_basis (clean_demo.ipynb cells 3-5); previously only
    # hand-built by tools/make_enterprise_snapshot.py
    if np.ptp(tim.freqs) > 0:
        for key in sorted(fitted):
            if not key.startswith("DMX_"):
                continue
            tag = key[len("DMX_"):]
            r1 = par.get(f"DMXR1_{tag}")
            r2 = par.get(f"DMXR2_{tag}")
            if r1 is None or r2 is None:
                continue          # no window bounds -> no lever arm
            win = (tim.mjds >= r1) & (tim.mjds <= r2)
            if win.any():
                cols.append(win / nu2)
                labels.append(key)

    # FD: frequency-dependent profile-evolution delay,
    # FDk -> log(nu / 1 GHz)^k (tempo2 definition)
    lognu = np.log(np.where(tim.freqs > 0, tim.freqs, 1000.0) / 1000.0)
    for key in sorted(fitted):
        m = _FD_RE.match(key)
        if m and np.ptp(tim.freqs) > 0:
            cols.append(lognu ** int(m.group(1)))
            labels.append(key)

    # JUMP: fitted inter-system offsets.  Flag form selects TOAs by a tim
    # flag value; MJD form by an epoch window.  Only entries carrying the
    # tempo2 fit flag "1" become columns (unfitted jumps are fixed
    # delays, not free parameters).  The fit flag is POSITIONAL — the
    # field after the offset value — because tempo2 writes a trailing
    # uncertainty ("JUMP -fe Rcvr_800 -8.8e-06 1 1.2e-07") that a
    # last-token test would misread.
    # Labels count FITTED jumps (tempo2's JUMP_1..JUMP_n are per fitted
    # parameter), not raw par-file lines — skipped unfitted entries must
    # not leave holes in the numbering.
    n_jump = 0
    for toks in par.jumps:
        if toks and toks[0].upper() == "MJD" and len(toks) >= 5:
            if toks[4] != "1":
                continue
            t1, t2 = float(toks[1]), float(toks[2])
            sel = (tim.mjds >= t1) & (tim.mjds <= t2)
        elif toks and toks[0].startswith("-") and len(toks) >= 4:
            if toks[3] != "1":
                continue
            flag, val = toks[0][1:], toks[1]
            sel = np.array([fl.get(flag) == val for fl in tim.flags])
        else:
            continue
        if sel.any() and not sel.all():
            n_jump += 1
            cols.append(sel.astype(float))
            labels.append(f"JUMP{n_jump}")

    # binary: harmonics of the orbital phase
    kepler = {"PB", "T0", "TASC", "A1", "OM", "ECC", "EPS1", "EPS2",
              "PBDOT", "XDOT", "OMDOT", "M2", "SINI", "KIN", "KOM", "GAMMA"}
    fitted_binary = fitted & kepler
    pb = par.get("PB")
    if fitted_binary and pb:
        t0 = par.get("T0", par.get("TASC", tim.mjds.mean()))
        phase = 2.0 * np.pi * ((tim.mjds - t0) / pb)
        n_harm = 2
        if fitted_binary & {"M2", "SINI", "KIN"}:
            n_harm = 4
        for k in range(1, n_harm + 1):
            cols += [np.sin(k * phase), np.cos(k * phase)]
            labels += [f"ORB_S{k}", f"ORB_C{k}"]

    M = np.column_stack(cols)
    keep = _degenerate_keep(M)
    if return_labels:
        return M[:, keep], [labels[j] for j in keep]
    return M[:, keep]


def _degenerate_keep(M: np.ndarray, rtol: float = 1e-10) -> list:
    """Indices of columns NOT numerically inside the span of earlier ones.

    The rank test runs on unit-normalized columns; raw timing partials span
    ~18 orders of magnitude (t^2 in s^2 vs the ones column) and would
    otherwise defeat a scale-blind singular-value threshold.
    """
    norms = np.linalg.norm(M, axis=0)
    Mn = M / np.where(norms > 0, norms, 1.0)
    keep = []
    for j in range(Mn.shape[1]):
        if norms[j] == 0:
            continue
        s = np.linalg.svd(Mn[:, keep + [j]], compute_uv=False)
        if s[-1] > rtol * s[0]:
            keep.append(j)
    return keep
