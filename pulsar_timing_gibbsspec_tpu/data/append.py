"""Append-TOAs operations for standing models.

A PTA dataset accrues: new TOAs arrive per pulsar over months while
the posterior of the standing model keeps being served.  These helpers
express that growth as a pure dataset-to-dataset operation — extend
the TOA/design rows of a subset of pulsars, keep everything else
byte-identical — so the serving layer can digest the grown dataset,
plan a bucket migration, and fork a checkpoint generation
(:mod:`..runtime.lineage`) without ever mutating the parent's inputs
in place.

Design-matrix handling: appending TOAs changes the timing-model fit
window, so the design matrix is recomputed over the *full* grown TOA
set (the standard refit).  Column scaling is irrelevant downstream —
the model ingests the design through an SVD (``tm_svd``) — only the
column space matters.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from .dataset import Pulsar

__all__ = ["dataset_digest", "append_toas", "append_polynomial_toas"]


def dataset_digest(psrs) -> str:
    """Content digest of a pulsar list: sha256 over each pulsar's name
    and its TOA/error/residual/frequency/design bytes, in submission
    order.  The order is hashed deliberately — the logical pulsar
    order IS the chain identity (per-pulsar key folds, padded slot
    assignment), so a reordered dataset is a *different* dataset.
    """
    h = hashlib.sha256()
    for psr in psrs:
        h.update(str(psr.name).encode())
        for arr in (psr.toas, psr.toaerrs, psr.residuals, psr.freqs,
                    psr.Mmat):
            a = np.ascontiguousarray(np.asarray(arr, np.float64))
            h.update(np.asarray(a.shape, np.int64).tobytes())
            h.update(a.tobytes())
    return h.hexdigest()


def append_toas(psr, toas, toaerrs, residuals, freqs=None,
                backend_flags=None, Mmat=None) -> Pulsar:
    """Append observations to one pulsar and return a new
    :class:`Pulsar` (the input is never mutated).

    ``toas``/``toaerrs``/``residuals`` are the new rows; ``freqs`` and
    ``backend_flags`` default to repeating the pulsar's last entry.
    ``Mmat`` is the recomputed design matrix over the FULL grown TOA
    set — appending changes the fit window, so callers refit; when
    omitted the old columns are re-evaluated only if the caller's
    design convention is unknown, which is an error here: pass the
    refit matrix explicitly or use :func:`append_polynomial_toas` for
    the synthetic family.  The grown arrays are sorted by TOA with a
    stable argsort so equal epochs keep submission order.
    """
    toas = np.asarray(toas, np.float64)
    n = toas.shape[0]
    if n == 0:
        return psr
    toaerrs = np.asarray(toaerrs, np.float64)
    residuals = np.asarray(residuals, np.float64)
    if toaerrs.shape != (n,) or residuals.shape != (n,):
        raise ValueError(
            f"{psr.name}: appended toaerrs/residuals must match the "
            f"{n} new TOAs (got {toaerrs.shape} / {residuals.shape})")
    if freqs is None:
        freqs = np.full(n, float(np.asarray(psr.freqs)[-1]))
    if backend_flags is None:
        backend_flags = np.asarray([psr.backend_flags[-1]] * n,
                                   dtype=object)
    if Mmat is None:
        raise ValueError(
            f"{psr.name}: appending TOAs changes the timing-model fit "
            "window — pass the refit design matrix (Mmat) over the "
            "full grown TOA set")
    all_toas = np.concatenate([psr.toas, toas])
    order = np.argsort(all_toas, kind="stable")
    Mmat = np.asarray(Mmat, np.float64)
    if Mmat.shape[0] != all_toas.shape[0]:
        raise ValueError(
            f"{psr.name}: refit Mmat has {Mmat.shape[0]} rows, grown "
            f"dataset has {all_toas.shape[0]} TOAs")
    return dataclasses.replace(
        psr,
        toas=all_toas[order],
        toaerrs=np.concatenate([psr.toaerrs, toaerrs])[order],
        residuals=np.concatenate([psr.residuals, residuals])[order],
        freqs=np.concatenate([np.asarray(psr.freqs, np.float64),
                              np.asarray(freqs, np.float64)])[order],
        backend_flags=np.concatenate(
            [np.asarray(psr.backend_flags, dtype=object),
             np.asarray(backend_flags, dtype=object)])[order],
        Mmat=Mmat[order],
    )


def append_polynomial_toas(psrs, add, seed=0, frac_span=0.25) -> list:
    """Grow a polynomial-design dataset (the synthetic family of
    ``analysis.jaxprcheck.entries.synthetic_pulsars``) by drawing new
    TOAs *after* each pulsar's current last epoch and refitting the
    polynomial design over the full grown set.

    ``add`` is either an int (append that many TOAs to every pulsar)
    or a ``{name: n}`` mapping (grow a subset; absent pulsars are
    returned unchanged).  Per-pulsar draws use
    ``default_rng([seed, index])`` so growth is reproducible and
    independent of which other pulsars grow.  The parent's TOAs are a
    strict prefix of the grown pulsar's epochs — new observations land
    strictly later in time — which is what makes in-bucket resume
    prefixes meaningful.
    """
    out = []
    for ii, psr in enumerate(psrs):
        n = int(add) if not isinstance(add, dict) \
            else int(add.get(psr.name, 0))
        if n < 0:
            raise ValueError(f"{psr.name}: cannot append {n} TOAs")
        if n == 0:
            out.append(psr)
            continue
        rng = np.random.default_rng([int(seed), ii])
        span = float(psr.tspan) if psr.tspan > 0 else 86400.0
        lo = float(np.asarray(psr.toas).max())
        new_toas = np.sort(rng.uniform(lo, lo + frac_span * span, n))
        scale = float(np.std(psr.residuals)) or 1e-7
        new_res = scale * rng.standard_normal(n)
        new_errs = np.full(n, float(np.asarray(psr.toaerrs)[-1]))
        all_toas = np.concatenate([psr.toas, new_toas])
        tm_cols = int(psr.Mmat.shape[1])
        t = (all_toas - all_toas.mean()) / (all_toas.max()
                                            - all_toas.min())
        M = np.column_stack([t ** k for k in range(tm_cols)])
        out.append(append_toas(psr, new_toas, new_errs, new_res,
                               Mmat=M))
    return out
