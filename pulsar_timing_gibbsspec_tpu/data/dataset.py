"""Pulsar data container and loaders.

``Pulsar`` is the host-side ingestion product this framework's model layer
consumes — the same contract the reference has with ``enterprise.Pulsar``
(residuals, TOA uncertainties, backend flags, design matrix; see reference
``pulsar_gibbs.py:71`` for residuals and ``:123`` for the backend-flag
selection input).  If the optional ``enterprise`` package is importable, its
higher-fidelity loader may be used instead via ``from_enterprise``.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from .design import design_matrix
from .partim import parse_par, parse_tim

DAY = 86400.0


@dataclasses.dataclass
class Pulsar:
    """Host-side per-pulsar data (all times/uncertainties in seconds)."""

    name: str
    toas: np.ndarray            # (n,) TOA epochs [s] (MJD * 86400)
    toaerrs: np.ndarray         # (n,) TOA uncertainties [s]
    residuals: np.ndarray       # (n,) timing residuals [s]
    freqs: np.ndarray           # (n,) observing frequency [MHz]
    backend_flags: np.ndarray   # (n,) backend/receiver label per TOA (str)
    Mmat: np.ndarray            # (n, m) timing design matrix
    fitpars: list               # fitted timing parameter names
    flags: dict = dataclasses.field(default_factory=dict)  # extra flag columns
    #: unit vector to the pulsar (consistent frame; only angular separations
    #: are consumed, by the overlap-reduction functions in models/orf.py)
    pos: np.ndarray = dataclasses.field(default_factory=lambda: np.zeros(3))

    @property
    def ntoa(self) -> int:
        return len(self.toas)

    @property
    def tspan(self) -> float:
        return float(self.toas.max() - self.toas.min())

    def backends(self) -> list:
        return sorted(set(self.backend_flags.tolist()))


def _backend_labels(tim) -> np.ndarray:
    """Backend label per TOA: '-f' flag if present (NANOGrav convention,
    matched by enterprise's ``selections.by_backend`` used at reference
    ``pulsar_gibbs.py:123``), else '-be', else the site code."""
    out = []
    for fl, site in zip(tim.flags, tim.sites):
        out.append(fl.get("f", fl.get("be", site)))
    return np.asarray(out, dtype=object)


def load_pulsar(par_path, tim_path, inject: dict | None = None) -> Pulsar:
    """Load one pulsar from par/tim.

    ``inject`` (optional): kwargs for
    :func:`~pulsar_timing_gibbsspec_tpu.data.simulate.inject_residuals`
    (e.g. ``dict(log10_A=np.log10(2e-15), gamma=13/3, nmodes=30)``); when
    given, residuals are regenerated with a known injection instead of the
    (unavailable without tempo2) observed post-fit residuals.
    """
    par = parse_par(par_path)
    tim = parse_tim(tim_path)
    M = design_matrix(par, tim)

    # sky position -> equatorial unit vector (ecliptic coords rotated by the
    # obliquity so mixed ELONG/ELAT and RAJ/DECJ catalogs share one frame)
    OBLIQUITY = np.deg2rad(23.439281)
    if "ELONG" in par.values or "LAMBDA" in par.values:
        lon = par.get("ELONG", par.get("LAMBDA"))
        lat = par.get("ELAT", par.get("BETA", 0.0))
        x = np.array([np.cos(lat) * np.cos(lon),
                      np.cos(lat) * np.sin(lon),
                      np.sin(lat)])
        ce, se = np.cos(OBLIQUITY), np.sin(OBLIQUITY)
        pos = np.array([x[0], ce * x[1] - se * x[2], se * x[1] + ce * x[2]])
    elif "RAJ" in par.values or "DECJ" in par.values:
        lon, lat = par.get("RAJ", 0.0), par.get("DECJ", 0.0)
        pos = np.array([np.cos(lat) * np.cos(lon),
                        np.cos(lat) * np.sin(lon),
                        np.sin(lat)])
    else:
        pos = np.zeros(3)   # unknown; orf_matrix refuses zero-norm positions

    residuals = np.zeros_like(tim.mjds)
    if inject is not None:
        from .fourier import fourier_basis
        from .simulate import inject_residuals

        kw = dict(inject)
        nmodes = kw.pop("nmodes", 30)
        Tspan = kw.pop("Tspan", float(np.ptp(tim.mjds) * DAY))
        if Tspan <= 0:
            raise ValueError(
                f"{par.name}: cannot inject a red-noise realization with "
                f"Tspan={Tspan} (need >=2 distinct TOA epochs)")
        F, f = fourier_basis(tim.mjds, nmodes, Tspan)
        residuals, _ = inject_residuals(
            par.name, F, f, Tspan, tim.errs, M, **kw)

    return Pulsar(
        name=par.name,
        toas=tim.mjds * DAY,
        toaerrs=tim.errs,
        residuals=residuals,
        freqs=tim.freqs,
        backend_flags=_backend_labels(tim),
        Mmat=M,
        fitpars=list(par.fitted),
        flags={"pta": tim.flags[0].get("pta", "") if tim.flags else ""},
        pos=pos,
    )


def load_directory(dirpath, inject: dict | None = None, names=None) -> list:
    """Load every ``<name>.par``/``<name>.tim`` pair under ``dirpath``."""
    dirpath = Path(dirpath)
    psrs = []
    for parf in sorted(dirpath.glob("*.par")):
        timf = parf.with_suffix(".tim")
        if not timf.exists():
            continue
        if names is not None and parf.stem not in names:
            continue
        psrs.append(load_pulsar(parf, timf, inject=inject))
    return psrs


def get_tspan(psrs) -> float:
    """Common span [s] across pulsars (reference uses
    ``model_utils.get_tspan`` at ``model_definition.py:195`` to set the
    frequency grid ``f_i = i/Tspan``)."""
    tmin = min(p.toas.min() for p in psrs)
    tmax = max(p.toas.max() for p in psrs)
    return float(tmax - tmin)


def from_enterprise(epsr) -> Pulsar:
    """Adapter from an ``enterprise.Pulsar`` to the host-side container.

    Duck-typed on the enterprise Pulsar attribute surface (``name``,
    ``toas`` [s], ``toaerrs`` [s], ``residuals`` [s], ``freqs`` [MHz],
    ``backend_flags``, ``Mmat``, ``fitpars``, ``flags``, ``pos``) rather
    than an import, so it needs no enterprise at definition time and any
    object exposing those attributes converts.  This is the reference's
    real-data path (``clean_demo.ipynb`` cells 3-5: a NANOGrav 9-yr pulsar
    with its full tempo2 timing solution): the enterprise-built design
    matrix and post-fit residuals flow in at full fidelity, replacing this
    package's leading-order ``design_matrix`` for real datasets.
    """
    toas = np.asarray(epsr.toas, dtype=np.float64)
    Mmat = np.asarray(epsr.Mmat, dtype=np.float64)
    if Mmat.ndim != 2 or Mmat.shape[0] != toas.shape[0]:
        raise ValueError(
            f"{epsr.name}: Mmat shape {Mmat.shape} does not match "
            f"{toas.shape[0]} TOAs")
    # enterprise flags are per-TOA arrays keyed by flag name; keep them,
    # but normalize 'pta' to a scalar label (the partim-loader convention
    # consumed by the factory's ECORR gate, reference
    # model_definition.py:221 "'NANOGrav' in p.flags['pta']")
    flags = {}
    for key, val in dict(getattr(epsr, "flags", {}) or {}).items():
        arr = np.asarray(val)
        if key == "pta":
            # always a scalar label, even when the flag array is empty
            flags[key] = str(arr.flat[0]) if arr.size else ""
        else:
            flags[key] = arr
    flags.setdefault("pta", "")
    pos = np.asarray(getattr(epsr, "pos", np.zeros(3)), dtype=np.float64)
    return Pulsar(
        name=str(epsr.name),
        toas=toas,
        toaerrs=np.asarray(epsr.toaerrs, dtype=np.float64),
        residuals=np.asarray(epsr.residuals, dtype=np.float64),
        freqs=np.asarray(epsr.freqs, dtype=np.float64),
        backend_flags=np.asarray(epsr.backend_flags, dtype=object),
        Mmat=Mmat,
        fitpars=list(epsr.fitpars),
        flags=flags,
        pos=pos,
    )


def load_enterprise_snapshot(path) -> Pulsar:
    """Load a recorded ``enterprise.Pulsar`` attribute surface (``.npz``)
    through :func:`from_enterprise`.

    The snapshot format (written by ``tools/make_enterprise_snapshot.py``)
    records exactly the attributes the adapter consumes: ``name``,
    ``toas``/``toaerrs``/``residuals`` [s], ``freqs`` [MHz],
    ``backend_flags``, the full tempo2-structured ``Mmat`` with
    ``fitpars``, per-TOA ``flag_<name>`` arrays and ``pos``.  Loading goes
    through :func:`from_enterprise` itself, so the real-data adapter is
    the code path exercised — hermetically, with no enterprise install
    (reference ``clean_demo.ipynb`` cells 3-5).
    """
    import types

    with np.load(path, allow_pickle=False) as z:
        flags = {k[len("flag_"):]: z[k] for k in z.files
                 if k.startswith("flag_")}
        epsr = types.SimpleNamespace(
            name=str(z["name"]),
            toas=z["toas"],
            toaerrs=z["toaerrs"],
            residuals=z["residuals"],
            freqs=z["freqs"],
            backend_flags=z["backend_flags"].astype(object),
            Mmat=z["Mmat"],
            fitpars=[str(s) for s in z["fitpars"]],
            flags=flags,
            pos=z["pos"],
        )
    return from_enterprise(epsr)
