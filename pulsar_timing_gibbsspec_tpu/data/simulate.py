"""Injection simulator: synthetic post-fit residuals with a known GWB.

The reference's correctness oracle is injection recovery on simulated data
(``singlepulsar_sim_A2e-15_gamma4.333.ipynb``: A=2e-15, gamma=13/3 GWB
injected with libstempo.toasim, posterior violins compared to the injection).
The shipped ``simulated_data/`` corpus contains the *TOAs* of such a
simulation but recovering its residuals requires the tempo2 timing solution.
This module regenerates the equivalent experiment natively: draw Fourier
coefficients from the power-law PSD, add white measurement noise from the
.tim uncertainties, and project out the timing-model column space (the
"post-fit" operation).  Deterministic per-pulsar seeds make the dataset
reproducible across runs and backends.
"""

from __future__ import annotations

import hashlib

import numpy as np

DAY = 86400.0
YEAR = 365.25 * DAY
FYR = 1.0 / YEAR


def powerlaw_psd(f: np.ndarray, log10_A: float, gamma: float, df: float) -> np.ndarray:
    """Per-coefficient prior variance of the Fourier modes [s^2].

    Standard PTA convention (as in enterprise's ``utils.powerlaw``):
    ``phi(f) = A^2/(12 pi^2) fyr^(gamma-3) f^(-gamma) df``.
    """
    A = 10.0 ** log10_A
    return (A**2 / (12.0 * np.pi**2)) * FYR ** (gamma - 3.0) * f ** (-gamma) * df


def _stable_seed(name: str, salt: int) -> int:
    h = hashlib.sha256(f"{name}:{salt}".encode()).digest()
    return int.from_bytes(h[:4], "little")


def inject_residuals(name, F, f, Tspan, toaerrs, Mmat,
                     log10_A=np.log10(2e-15), gamma=13.0 / 3.0,
                     efac=1.0, seed=0):
    """Generate post-fit residuals = P_M^perp (F a + white noise).

    Returns (residuals [s], injected coefficients a).
    """
    rng = np.random.default_rng(_stable_seed(name, seed))
    phi = powerlaw_psd(f, log10_A, gamma, 1.0 / Tspan)
    a = rng.normal(size=F.shape[1]) * np.sqrt(phi)
    noise = rng.normal(size=F.shape[0]) * toaerrs * efac
    r = F @ a + noise
    # post-fit projection: subtract the least-squares timing-model fit.
    # Project with an orthonormalized column basis — raw timing partials
    # span ~18 decades and make a direct lstsq numerically lossy.
    Q, _ = np.linalg.qr(Mmat / np.linalg.norm(Mmat, axis=0))
    return r - Q @ (Q.T @ r), a
