"""Injection simulator: synthetic post-fit residuals with a known GWB.

The reference's correctness oracle is injection recovery on simulated data
(``singlepulsar_sim_A2e-15_gamma4.333.ipynb``: A=2e-15, gamma=13/3 GWB
injected with libstempo.toasim, posterior violins compared to the injection).
The shipped ``simulated_data/`` corpus contains the *TOAs* of such a
simulation but recovering its residuals requires the tempo2 timing solution.
This module regenerates the equivalent experiment natively: draw Fourier
coefficients from the power-law PSD, add white measurement noise from the
.tim uncertainties, and project out the timing-model column space (the
"post-fit" operation).  Deterministic per-pulsar seeds make the dataset
reproducible across runs and backends.
"""

from __future__ import annotations

import hashlib

import numpy as np

DAY = 86400.0
YEAR = 365.25 * DAY
FYR = 1.0 / YEAR


def powerlaw_psd(f: np.ndarray, log10_A: float, gamma: float, df: float) -> np.ndarray:
    """Per-coefficient prior variance of the Fourier modes [s^2].

    Standard PTA convention (as in enterprise's ``utils.powerlaw``):
    ``phi(f) = A^2/(12 pi^2) fyr^(gamma-3) f^(-gamma) df``.
    """
    A = 10.0 ** log10_A
    return (A**2 / (12.0 * np.pi**2)) * FYR ** (gamma - 3.0) * f ** (-gamma) * df


def _stable_seed(name: str, salt: int) -> int:
    h = hashlib.sha256(f"{name}:{salt}".encode()).digest()
    return int.from_bytes(h[:4], "little")


def inject_residuals(name, F, f, Tspan, toaerrs, Mmat,
                     log10_A=np.log10(2e-15), gamma=13.0 / 3.0,
                     efac=1.0, seed=0):
    """Generate post-fit residuals = P_M^perp (F a + white noise).

    Returns (residuals [s], injected coefficients a).
    """
    rng = np.random.default_rng(_stable_seed(name, seed))
    phi = powerlaw_psd(f, log10_A, gamma, 1.0 / Tspan)
    a = rng.normal(size=F.shape[1]) * np.sqrt(phi)
    noise = rng.normal(size=F.shape[0]) * toaerrs * efac
    return _postfit_project(Mmat, F @ a + noise), a


def _postfit_project(Mmat, r):
    """Subtract the least-squares timing-model fit.  Projects with an
    orthonormalized column basis — raw timing partials span ~18 decades
    and make a direct lstsq numerically lossy."""
    Q, _ = np.linalg.qr(Mmat / np.linalg.norm(Mmat, axis=0))
    return r - Q @ (Q.T @ r)


def inject_correlated(psrs, orf="hd", log10_A=np.log10(2e-15),
                      gamma=13.0 / 3.0, nmodes=10, seed=0, efac=1.0):
    """Replace every pulsar's residuals with a *jointly drawn* correlated
    common process plus white noise (post-fit projected).

    The per-pulsar injector above draws independent coefficient sets — it
    can validate spectra but carries no inter-pulsar correlation.  Here
    the Fourier coefficients of all pulsars are drawn jointly on the
    common ``Tspan`` grid with per-frequency covariance
    ``phi_j * G`` (``G`` the named ORF over the pulsar positions), the
    signature the correlated-ORF samplers exist to recover.  The
    reference can only produce such datasets through libstempo/toasim
    (``singlepulsar_sim...ipynb``); this is dependency-free and
    deterministic in ``seed``.

    Returns ``(new_psrs, a)`` — pulsars with replaced residuals (same
    order) and the injected coefficients ``a`` of shape (P, 2*nmodes).
    """
    import dataclasses

    from ..models.orf import orf_matrix
    from .dataset import get_tspan
    from .fourier import fourier_basis

    psrs = list(psrs)
    P = len(psrs)
    Tspan = get_tspan(psrs)
    from ..models.orf import ORFS

    if orf not in ORFS:
        raise NotImplementedError(
            f"inject_correlated supports the fixed two-point ORFs "
            f"{sorted(ORFS)}; got '{orf}'")
    G = orf_matrix(orf, [p.pos for p in psrs])
    # eigh square root, not Cholesky: monopole/dipole are PSD but
    # rank-deficient, and injection from a degenerate G is well-defined
    w, V = np.linalg.eigh(G)
    Lg = V * np.sqrt(np.clip(w, 0.0, None))[None, :]
    rng = np.random.default_rng(_stable_seed("correlated", seed))
    # joint draw: cov over pulsars = phi_j * G per coefficient column
    f = np.repeat(np.arange(1, nmodes + 1) / Tspan, 2)
    phi = powerlaw_psd(f, log10_A, gamma, 1.0 / Tspan)
    a = (Lg @ rng.normal(size=(P, 2 * nmodes))) * np.sqrt(phi)[None, :]

    out = []
    for ii, p in enumerate(psrs):
        F, _ = fourier_basis(p.toas / DAY, nmodes, Tspan)
        noise_rng = np.random.default_rng(_stable_seed(p.name, seed + 1))
        r = F @ a[ii] + noise_rng.normal(size=p.ntoa) * p.toaerrs * efac
        out.append(dataclasses.replace(p, residuals=_postfit_project(
            p.Mmat, r)))
    return out, a
