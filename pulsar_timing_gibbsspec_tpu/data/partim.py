"""tempo2 ``.par`` / ``.tim`` text readers.

The reference relies on ``enterprise.Pulsar`` (libstempo/PINT, i.e. the
tempo2 C++ stack) for ingestion (reference ``pulsar_gibbs.py:55-57`` takes an
enterprise pulsar; the notebooks call ``Pulsar(par, tim)``).  This module is a
dependency-free reader sufficient for the shipped ``simulated_data/`` corpus
(45 pulsars, tempo2 text formats) and for any par/tim pair with standard
columns.  Full tempo2 timing-solution evaluation is intentionally out of
scope — the framework consumes *residuals* plus a linear design matrix (see
``data/design.py``), exactly the contract the reference has with enterprise.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

import numpy as np

#: par-file keys that are switches/strings, never fitted numeric parameters
_NON_NUMERIC_KEYS = {
    "PSRJ", "PSRB", "PSR", "BINARY", "EPHEM", "CLK", "UNITS", "TIMEEPH",
    "T2CMETHOD", "CORRECT_TROPOSPHERE", "PLANET_SHAPIRO", "DILATEFREQ",
    "INFO", "NITS", "NTOA", "TRES", "MODE", "EPHVER", "DCOVFILE", "TZRSITE",
}


@dataclasses.dataclass
class ParFile:
    """Parsed timing model: parameter values and which are fitted."""

    name: str
    values: dict          # key -> float value (numeric entries only)
    fitted: list          # keys flagged for fitting ("1" in the fit column)
    raw: dict             # key -> list of raw string fields
    #: JUMP lines, one token list each (tempo2 allows many JUMP entries;
    #: a dict keyed by "JUMP" would keep only the last) — flag form
    #: ``-flag value offset [fit]`` or MJD form ``MJD t1 t2 offset [fit]``
    jumps: list = dataclasses.field(default_factory=list)

    def __getitem__(self, key):
        return self.values[key]

    def get(self, key, default=None):
        return self.values.get(key, default)


def _to_float(tok: str):
    """Parse a tempo2 numeric token (allows D-exponent Fortran style)."""
    try:
        return float(tok.replace("D", "E").replace("d", "e"))
    except ValueError:
        return None


def parse_par(path) -> ParFile:
    """Read a tempo2 par file.

    Layout per line: ``KEY value [fitflag] [uncertainty]``.  The fit flag is
    the literal field ``1`` in the third column (tempo2 convention).  RAJ/DECJ
    sexagesimal values are converted to radians; ELONG/ELAT degrees to
    radians.
    """
    values, fitted, raw, jumps = {}, [], {}, []
    name = Path(path).stem
    for line in Path(path).read_text().splitlines():
        toks = line.split()
        if not toks or toks[0].startswith("#"):
            continue
        key = toks[0].upper()
        raw[key] = toks[1:]
        if key == "JUMP" and len(toks) > 1:
            # repeated lines, non-numeric second field — collected whole
            # for design_matrix (flag-selected / MJD-windowed offsets)
            jumps.append(toks[1:])
            continue
        if key in ("PSRJ", "PSRB", "PSR") and len(toks) > 1:
            name = toks[1]
            continue
        if key in _NON_NUMERIC_KEYS or len(toks) < 2:
            continue
        if key in ("RAJ", "DECJ"):
            val = _sexagesimal_to_rad(toks[1], hours=(key == "RAJ"))
        else:
            val = _to_float(toks[1])
        if val is None:
            continue
        if key in ("ELONG", "ELAT", "LAMBDA", "BETA"):
            values[key] = np.deg2rad(val)
        else:
            values[key] = val
        # fit flag: a bare "1" in column 3 (not an uncertainty like "1.5e-3")
        if len(toks) >= 3 and toks[2] == "1":
            fitted.append(key)
    return ParFile(name=name, values=values, fitted=fitted, raw=raw,
                   jumps=jumps)


def _sexagesimal_to_rad(tok: str, hours: bool) -> float:
    parts = tok.split(":")
    if len(parts) == 1:
        return float(tok)
    sign = -1.0 if parts[0].strip().startswith("-") else 1.0
    mags = [abs(float(p)) for p in parts] + [0.0, 0.0]
    deg = mags[0] + mags[1] / 60.0 + mags[2] / 3600.0
    if hours:
        deg *= 15.0
    return sign * np.deg2rad(deg)


@dataclasses.dataclass
class TimFile:
    """Parsed TOAs. MJDs kept at float128-free double precision; the sampler
    only ever uses TOA *differences* (span ~15 yr), where f64 is ~µs-exact."""

    mjds: np.ndarray       # (n,) TOA epochs [MJD, f64]
    errs: np.ndarray       # (n,) TOA uncertainties [seconds]
    freqs: np.ndarray      # (n,) observing frequencies [MHz]
    flags: list            # (n,) dict of -flag value pairs per TOA
    sites: list            # (n,) observatory codes


def parse_tim(path) -> TimFile:
    """Read a tempo2 ``FORMAT 1`` tim file.

    Line layout: ``name freq mjd err site [-flag value ...]`` with err in
    microseconds.  ``INCLUDE`` directives are followed; comment/command lines
    are skipped.
    """
    mjds, errs, freqs, flags, sites = [], [], [], [], []
    path = Path(path)
    for line in path.read_text().splitlines():
        s = line.strip()
        if s.upper().startswith("INCLUDE") and len(s.split()) > 1:
            sub = parse_tim(path.parent / s.split()[1])
            mjds += list(sub.mjds); errs += list(sub.errs)
            freqs += list(sub.freqs); flags += sub.flags; sites += sub.sites
            continue
        if not s or s.startswith(("#", "C ", "CODE", "FORMAT", "MODE", "EFAC", "EQUAD", "TIME", "JUMP", "SKIP", "NOSKIP")):
            continue
        toks = s.split()
        if len(toks) < 5:
            continue
        freq, mjd, err = _to_float(toks[1]), _to_float(toks[2]), _to_float(toks[3])
        if freq is None or mjd is None or err is None:
            continue
        fl = {}
        ii = 5
        while ii < len(toks):
            if toks[ii].startswith("-") and not _is_number(toks[ii]) and ii + 1 < len(toks):
                fl[toks[ii][1:]] = toks[ii + 1]
                ii += 2
            else:
                ii += 1
        mjds.append(mjd)
        errs.append(err * 1e-6)          # µs -> s
        freqs.append(freq)
        flags.append(fl)
        sites.append(toks[4])
    order = np.argsort(np.asarray(mjds, dtype=np.float64), kind="stable")
    return TimFile(
        mjds=np.asarray(mjds, dtype=np.float64)[order],
        errs=np.asarray(errs, dtype=np.float64)[order],
        freqs=np.asarray(freqs, dtype=np.float64)[order],
        flags=[flags[i] for i in order],
        sites=[sites[i] for i in order],
    )


_NUM_RE = re.compile(r"^-?(\d+\.?\d*|\.\d+)([eEdD][+-]?\d+)?$")


def _is_number(tok: str) -> bool:
    return bool(_NUM_RE.match(tok))
