"""Build the native shared library: ``python -m
pulsar_timing_gibbsspec_tpu.native.build``.

Compiles ``acor.cpp`` (and any future host-side C++ translation units) into
``libptgibbs_native.so`` next to this file with the system ``g++``.  The
pure-NumPy fallbacks in ``ops/acf.py`` keep everything working when the
library has not been built; building it removes the ACT estimation from the
Python hot path of the first (adaptation) sweep.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).parent
SOURCES = ["acor.cpp"]
OUT = HERE / "libptgibbs_native.so"


def build(verbose: bool = True) -> Path:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           *[str(HERE / s) for s in SOURCES], "-o", str(OUT)]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    try:
        path = build()
    except (subprocess.CalledProcessError, FileNotFoundError) as err:
        print(f"native build failed: {err}", file=sys.stderr)
        sys.exit(1)
    print(f"built {path}")
