from . import acor_native

__all__ = ["acor_native"]
