"""ctypes binding for the C++ ACT estimator (graceful fallback when unbuilt)."""

from __future__ import annotations

import ctypes
from pathlib import Path

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = Path(__file__).parent / "libptgibbs_native.so"
    if so.exists():
        lib = ctypes.CDLL(str(so))
        # a stale .so built before a symbol was added must degrade to the
        # NumPy fallback, not break available()
        if not hasattr(lib, "ptg_integrated_act"):
            return None
        lib.ptg_integrated_act.restype = ctypes.c_double
        lib.ptg_integrated_act.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_double]
        _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def act(x: np.ndarray, c: float = 5.0) -> float:
    lib = _load()
    x = np.ascontiguousarray(x, dtype=np.float64)
    ptr = x.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    return float(lib.ptg_integrated_act(ptr, len(x), c))
