// Native host components for the TPU Gibbs framework.
//
// The reference depends on the third-party C++ `acor` extension for the
// integrated autocorrelation time that sizes its per-sweep MH sub-chains
// (reference pulsar_gibbs.py:7,370-371).  This file provides the in-repo
// equivalent: a Sokal self-consistent-window ACT estimator (the same
// definition as the NumPy fallback in ops/acf.py), exposed through a plain C
// ABI consumed via ctypes (native/acor_native.py) — no pybind11 required.
//
// ptg_integrated_act: tau = 1 + 2 * sum_{t<=W} rho_t with the window W the
// first lag satisfying W >= c * tau(W).  Runs in O(n * W) with incremental
// autocovariances, which beats the FFT path for the ~1000-sample adaptation
// chains this gates (W is typically < 100).  The sampler calls it per
// sub-chain column and sizes the per-sweep MH scans by a percentile of
// the results (jax_backend._act_from_rec).

#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

double ptg_integrated_act(const double* x, long n, double c) {
    if (n < 4) return 1.0;
    double mean = 0.0;
    for (long i = 0; i < n; ++i) mean += x[i];
    mean /= (double)n;

    std::vector<double> d((size_t)n);
    double var = 0.0;
    for (long i = 0; i < n; ++i) {
        d[(size_t)i] = x[i] - mean;
        var += d[(size_t)i] * d[(size_t)i];
    }
    if (var <= 0.0) return 1.0;

    double tau = 1.0;
    for (long t = 1; t < n; ++t) {
        double acf = 0.0;
        for (long i = 0; i + t < n; ++i) acf += d[(size_t)i] * d[(size_t)(i + t)];
        tau += 2.0 * acf / var;
        if ((double)t >= c * tau) {
            return tau > 1.0 ? tau : 1.0;
        }
    }
    return tau > 1.0 ? tau : 1.0;
}

}  // extern "C"
