"""Integrated autocorrelation time (ACT).

The reference depends on the C++ ``acor`` extension to size its per-sweep MH
sub-chains (``aclength_white = max_j ceil(acor(chain_j))``, reference
``pulsar_gibbs.py:370-371``) — the ACT is load-bearing, not just a
diagnostic (SURVEY §2.2).  This module provides a NumPy FFT implementation
of the standard Sokal self-consistent-window estimator, and prefers the
in-repo C++ implementation (``native/acor.cpp``) when its shared library has
been built (``python -m pulsar_timing_gibbsspec_tpu.native.build``).
"""

from __future__ import annotations

import numpy as np

from ..native import acor_native


def _autocorr_fft(x: np.ndarray) -> np.ndarray:
    n = len(x)
    x = x - x.mean()
    nfft = 1 << (2 * n - 1).bit_length()
    f = np.fft.rfft(x, nfft)
    acf = np.fft.irfft(f * np.conj(f), nfft)[:n].real
    if acf[0] <= 0:
        return np.ones(1)
    return acf / acf[0]


def integrated_act(x: np.ndarray, c: float = 5.0) -> float:
    """Sokal windowed integrated ACT: ``tau = 1 + 2 sum_t rho_t`` summed up
    to the first window ``W >= c * tau(W)``.  Returns >= 1.0."""
    x = np.asarray(x, dtype=np.float64)
    if x.ndim != 1:
        raise ValueError("integrated_act expects a 1-d chain")
    if len(x) < 4 or np.ptp(x) == 0:
        return 1.0
    if acor_native.available():
        return acor_native.act(x)
    rho = _autocorr_fft(x)
    tau = 2.0 * np.cumsum(rho) - 1.0
    windows = np.arange(len(tau))
    ok = windows >= c * tau
    if not np.any(ok):
        return float(max(tau[-1], 1.0))
    w = np.argmax(ok)
    return float(max(tau[w], 1.0))
