"""Integrated autocorrelation time (ACT).

The reference depends on the C++ ``acor`` extension to size its per-sweep MH
sub-chains (``aclength_white = max_j ceil(acor(chain_j))``, reference
``pulsar_gibbs.py:370-371``) — the ACT is load-bearing, not just a
diagnostic (SURVEY §2.2).  This module provides a NumPy FFT implementation
of the standard Sokal self-consistent-window estimator, and prefers the
in-repo C++ implementation (``native/acor.cpp``) when its shared library has
been built (``python -m pulsar_timing_gibbsspec_tpu.native.build``).
"""

from __future__ import annotations

import numpy as np

from ..native import acor_native


def _autocorr_fft(x: np.ndarray) -> np.ndarray:
    n = len(x)
    x = x - x.mean()
    nfft = 1 << (2 * n - 1).bit_length()
    f = np.fft.rfft(x, nfft)
    acf = np.fft.irfft(f * np.conj(f), nfft)[:n].real
    if acf[0] <= 0:
        return np.ones(1)
    return acf / acf[0]


def act_from_rho(rho: np.ndarray, c: float = 5.0) -> np.ndarray:
    """Sokal windowed ACT from normalized autocorrelations, batched.

    ``rho`` is ``(..., L)`` with ``rho[..., 0] == 1``; the window is the
    first lag ``W >= c * tau(W)`` per leading index (falling back to the
    full window when none qualifies, as :func:`integrated_act` does).
    This is the shared finalizer of the host estimator below and of the
    on-device lagged-product sketch (``obs/sketch.py``), so the two
    report the same statistic by construction.  Returns ``(...)`` floats
    clipped to >= 1.0.
    """
    rho = np.asarray(rho, dtype=np.float64)
    tau = 2.0 * np.cumsum(rho, axis=-1) - 1.0
    windows = np.arange(rho.shape[-1])
    ok = windows >= c * tau
    # argmax finds the first qualifying window; rows with none get the
    # full-window tau (argmax of all-False is 0 -> masked to L-1)
    w = np.argmax(ok, axis=-1)
    w = np.where(np.any(ok, axis=-1), w, rho.shape[-1] - 1)
    return np.maximum(np.take_along_axis(tau, w[..., None],
                                         axis=-1)[..., 0], 1.0)


def integrated_act(x: np.ndarray, c: float = 5.0) -> float:
    """Sokal windowed integrated ACT: ``tau = 1 + 2 sum_t rho_t`` summed up
    to the first window ``W >= c * tau(W)``.  Returns >= 1.0."""
    x = np.asarray(x, dtype=np.float64)
    if x.ndim != 1:
        raise ValueError("integrated_act expects a 1-d chain")
    if len(x) < 4 or np.ptp(x) == 0:
        return 1.0
    if acor_native.available():
        return acor_native.act(x)
    return float(act_from_rho(_autocorr_fft(x), c))
