from .acf import integrated_act

__all__ = ["integrated_act"]
