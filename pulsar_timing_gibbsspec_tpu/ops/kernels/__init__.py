"""Kernel tier: fused accelerator kernels with XLA reference twins.

The per-sweep device wall is the b-draw's many-small-matrix chain over
the ``(C, P, Bmax, Bmax)`` batch — factor, two solves, sample injection
— plus the segmented Gram.  XLA lowers each stage to its own HBM
round-trip; the Pallas/Mosaic kernels here run the whole chain out of
VMEM in one pass:

- :func:`chol_solve_sample` — the fused Jacobi-preconditioned Cholesky
  -> triangular solves -> N(0, I) sample injection of the b-draw
  (``ops/linalg.jacobi_factor_mean_prop``'s five outputs) as ONE kernel
  over the whole per-chain pulsar batch;
- :func:`gram_accumulate` — the segmented ``tnt_d`` Gram as a
  grid-streamed accumulate (one VMEM-resident accumulator, one HBM
  read per segment, no per-segment partial-Gram round-trip).

Every kernel ships with a pure-XLA reference implementation
(:mod:`.reference`) that the dispatch falls back to, and the Pallas
body is the SAME traced math applied to the same whole-batch shapes —
so ``interpret=True`` parity on the CPU container is bitwise in f64
(tests/test_kernels.py), not merely close.

Dispatch (``Settings.kernel_tier`` / ``PTGIBBS_KERNEL_TIER``):

- ``"xla"`` — always the reference implementations (today's lowering);
- ``"pallas"`` — the fused kernels, in Mosaic on TPU and in interpret
  mode elsewhere (the CPU testing story);
- ``"auto"`` (default) — ``"pallas"`` on a TPU backend when Pallas
  imports, else ``"xla"``.

Mixed-precision island map: only the f32 STEADY bodies route to Mosaic
— Mosaic has no f64, so the periodic exact bodies (the widening-f64
Gram, the two-float ``tf_chol_factor`` refresh) stay on the XLA tier
by design and the dispatch enforces it (``widen``/``factor="tf"``/f64
operands fall back unless interpreting).  The tier is resolved from
static Python at trace time: switching it retraces once, never inside
the steady loop.
"""

from __future__ import annotations

from ...config import settings
from . import reference

_TIERS = ("pallas", "xla", "auto")


def pallas_available() -> bool:
    """Whether the Pallas kernel module imports in this environment."""
    try:
        from . import pallas_tpu  # noqa: F401
    except Exception:  # noqa: BLE001 — any import failure means no tier
        return False
    return True


def _backend() -> str:
    import jax

    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — backend probe must never raise
        return "cpu"


def interpret_mode() -> bool:
    """Pallas kernels run in interpret mode off-TPU (the CPU container's
    parity-test story); Mosaic lowering is TPU-only."""
    return _backend() != "tpu"


def resolve_tier(tier: str | None = None) -> str:
    """The effective tier: explicit argument > ``settings.kernel_tier``;
    ``auto`` means Pallas on TPU (when importable) and XLA elsewhere;
    an explicit ``pallas`` degrades to ``xla`` when Pallas is
    unavailable (fallback, not failure)."""
    if tier is None:
        tier = settings.kernel_tier
    if tier not in _TIERS:
        raise ValueError(
            f"kernel tier {tier!r} must be one of {_TIERS}")
    if tier == "auto":
        return ("pallas" if _backend() == "tpu" and pallas_available()
                else "xla")
    if tier == "pallas" and not pallas_available():
        return "xla"
    return tier


def chol_solve_sample(Sig, d, z, *, ridge=0.0, factor="blocked",
                      tier=None):
    """Fused batched Cholesky -> solves -> sample injection: the five
    outputs of ``jacobi_factor_mean_prop`` — ``(L, Li, dj, mean, bp)``
    with ``bp = mean + dj * Li^T z`` — in one kernel pass over the
    leading (pulsar) batch.

    ``factor="blocked"`` is the f32/f64 blocked recursion with ``ridge``
    added to the preconditioned matrix (the steady b-draw proposal);
    ``factor="tf"`` is the two-float near-f64 factor with ``ridge``
    riding its f32 stage only (the exact_every refresh) — tf carries
    emulated-f64 arithmetic, so it is XLA-tier on hardware by design.
    """
    t = resolve_tier(tier)
    if t == "pallas" and factor == "blocked":
        interp = interpret_mode()
        if interp or Sig.dtype.name == "float32":
            from . import pallas_tpu

            return pallas_tpu.chol_solve_sample_pallas(
                Sig, d, z, ridge=ridge, interpret=interp)
    return reference.chol_solve_sample_ref(Sig, d, z, ridge=ridge,
                                           factor=factor)


def gram_accumulate(TNa, Ta, *, out_dtype=None, widen=False, tier=None):
    """Segment-streamed Gram accumulate: ``sum_s TNa[:, s]^T @ Ta[:, s]``
    over ``(P, nseg, m, B1)`` operands -> ``(P, B1, B1)``.

    ``widen=True`` accumulates each segment's dot directly in
    ``out_dtype`` (the exact ``tnt_d`` path: f32 products exactly
    representable in f64); otherwise segments are f32
    ``precision="highest"`` dots cast to ``out_dtype`` before the
    segment reduce (``out_dtype=f32`` is the new steady body,
    ``f64`` the ``tnt_d_seg`` refresh class).  The segment reduce is
    SEQUENTIAL in both tiers — the grid-accumulator order — so the
    tiers agree bitwise rather than at reassociation level.
    """
    import numpy as np

    if out_dtype is None:
        out_dtype = TNa.dtype
    t = resolve_tier(tier)
    if t == "pallas":
        interp = interpret_mode()
        f32 = (np.dtype(TNa.dtype) == np.float32
               and np.dtype(out_dtype) == np.float32)
        if interp or (not widen and f32):
            from . import pallas_tpu

            return pallas_tpu.gram_accumulate_pallas(
                TNa, Ta, out_dtype=out_dtype, widen=widen,
                interpret=interp)
    return reference.gram_accumulate_ref(TNa, Ta, out_dtype=out_dtype,
                                         widen=widen)
