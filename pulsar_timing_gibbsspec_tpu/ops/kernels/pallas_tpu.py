"""Pallas/Mosaic fused kernels of the tier (see package docstring).

Both kernels keep the whole per-chain batch resident: the b-draw's
``(P, Bmax, Bmax)`` factor batch is ~250 KB in f32 at the bench shape
(45 x 37 x 37) — far under VMEM — so the fused chain runs with ONE HBM
read of (Sig, d, z) and one write of the five outputs, where the XLA
lowering round-trips each stage.  The Gram kernel streams the TOA
segments through a VMEM-resident accumulator: one HBM read per segment
block, no materialized per-segment partial Grams.

The kernel bodies reuse the exact traced math of the XLA reference
(``jacobi_factor_mean_prop`` / the reference's per-segment dot) on the
same whole-batch shapes, which is what makes interpret-mode parity
bitwise in f64 rather than ULP-close.  ``vmap`` over the chain axis
composes through ``pallas_call``'s batching rule (the chain axis
becomes a leading grid dimension).

Off-TPU the kernels run with ``interpret=True`` — correctness is
provable on the CPU container; Mosaic lowering itself is exercised
only on hardware.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..linalg import jacobi_factor_mean_prop
from .reference import _segment_dot


def chol_solve_sample_pallas(Sig, d, z, *, ridge=0.0, interpret=True):
    """Fused ``(L, Li, dj, mean, bp)`` over the whole leading batch in
    one ``pallas_call`` (no grid: the batch is VMEM-resident; ``vmap``
    adds the chain grid axis)."""
    dt = Sig.dtype

    def kern(s_ref, d_ref, z_ref, L_ref, Li_ref, dj_ref, m_ref, bp_ref):
        L, Li, dj, mean, bp = jacobi_factor_mean_prop(
            s_ref[...], d_ref[...], z_ref[...], ridge=ridge)
        L_ref[...] = L
        Li_ref[...] = Li
        dj_ref[...] = dj
        m_ref[...] = mean
        bp_ref[...] = bp

    outs = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(Sig.shape, dt),
                   jax.ShapeDtypeStruct(Sig.shape, dt),
                   jax.ShapeDtypeStruct(d.shape, dt),
                   jax.ShapeDtypeStruct(d.shape, dt),
                   jax.ShapeDtypeStruct(d.shape, dt)],
        interpret=interpret,
        name="chol_solve_sample",
    )(Sig, d, z)
    return tuple(outs)


def gram_accumulate_pallas(TNa, Ta, *, out_dtype, widen=False,
                           interpret=True):
    """Segment-streamed Gram accumulate: grid over the (sequential)
    segment axis, whole-pulsar blocks, one VMEM accumulator."""
    P, nseg, m, B1 = TNa.shape

    def kern(a_ref, b_ref, o_ref):
        s = pl.program_id(0)
        # a_ref/b_ref blocks are (P, 1, m, B1); [:, 0] matches the
        # reference's per-segment (P, m, B1) dot shape exactly
        part = _segment_dot(a_ref[...], b_ref[...], 0, out_dtype, widen)

        @pl.when(s == 0)
        def _init():
            o_ref[...] = part

        @pl.when(s != 0)
        def _accumulate():
            o_ref[...] = o_ref[...] + part

    return pl.pallas_call(
        kern,
        grid=(nseg,),
        in_specs=[pl.BlockSpec((P, 1, m, B1), lambda s: (0, s, 0, 0)),
                  pl.BlockSpec((P, 1, m, B1), lambda s: (0, s, 0, 0))],
        out_specs=pl.BlockSpec((P, B1, B1), lambda s: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((P, B1, B1), jnp.dtype(out_dtype)),
        interpret=interpret,
        name="gram_accumulate",
    )(TNa, Ta)
