"""Pure-XLA reference implementations of the fused kernel tier.

These are the ``kernel_tier="xla"`` production paths AND the parity
oracles the Pallas kernels are tested against.  Two disciplines keep
the tiers bitwise-comparable in f64 interpret mode (tests/
test_kernels.py):

- the Pallas kernel bodies call the SAME traced math on the SAME
  whole-batch shapes (a per-tile kernel would reassociate the batched
  dots at the 1-2 ULP level — measured — so the tier boundary is drawn
  at the batch, not the matrix);
- the segment reduce of :func:`gram_accumulate_ref` is SEQUENTIAL
  (unrolled left-to-right adds), matching the grid-accumulator order of
  the Pallas kernel instead of ``jnp.sum``'s reassociated reduce.  For
  the f64-accumulated paths this is a pure f64 reassociation of the
  previous ``jnp.sum`` order (the class already documented on
  ``tnt_d``), bitwise when ``nseg == 1``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..linalg import jacobi_factor_mean_prop, tf_chol_factor


def chol_solve_sample_ref(Sig, d, z, *, ridge=0.0, factor="blocked"):
    """The unfused lowering of the b-draw factor chain: Jacobi
    preconditioning, blocked (or two-float) factorization, the fused
    mean/sample 2-column solve.  Returns ``(L, Li, dj, mean, bp)``.

    ``factor="blocked"``: ``ridge`` is added to the preconditioned
    matrix (the steady proposal's breakdown guard).  ``factor="tf"``:
    ``ridge`` rides ``tf_chol_factor``'s f32 stage only and is removed
    by its two-float congruence correction (the refresh contract)."""
    if factor == "tf":
        return jacobi_factor_mean_prop(
            Sig, d, z, factor=lambda A: tf_chol_factor(A, ridge=ridge))
    if factor != "blocked":
        raise ValueError(
            f"factor={factor!r} must be 'blocked' or 'tf'")
    return jacobi_factor_mean_prop(Sig, d, z, ridge=ridge)


def _segment_dot(TNa, Ta, s, out_dtype, widen):
    """One segment's partial Gram, in the dtype discipline of the
    calling path (widening-f64 exact dot vs f32 MXU dot cast to the
    reduce dtype)."""
    if widen:
        return jnp.einsum("pnb,pnc->pbc", TNa[:, s], Ta[:, s],
                          preferred_element_type=out_dtype)
    part = jnp.einsum("pnb,pnc->pbc", TNa[:, s], Ta[:, s],
                      precision="highest")
    return part.astype(out_dtype)


def gram_accumulate_ref(TNa, Ta, *, out_dtype=None, widen=False):
    """Sequential-segment Gram accumulate over ``(P, nseg, m, B1)``
    operands -> ``(P, B1, B1)``; the XLA twin of the Pallas grid
    accumulator (same per-segment dot shapes, same left-to-right
    reduce order)."""
    if out_dtype is None:
        out_dtype = TNa.dtype
    nseg = TNa.shape[1]
    acc = _segment_dot(TNa, Ta, 0, out_dtype, widen)
    for s in range(1, nseg):
        acc = acc + _segment_dot(TNa, Ta, s, out_dtype, widen)
    return acc
