"""Device linear algebra for the Gibbs b-draw and marginalized likelihood.

The hot kernel is the per-pulsar factorization of ``Sigma = T^T N^-1 T +
diag(phi^-1)`` (reference ``pulsar_gibbs.py:489-520`` uses LAPACK SVD with a
QR fallback; ``:598-608`` uses Cholesky for the marginalized likelihood).
On TPU the idiomatic form is a *batched* Cholesky over the pulsar axis on
the MXU, in float32 made safe by Jacobi (diagonal) preconditioning:

    A = D Sigma D,   D = diag(1/sqrt(diag(Sigma)))

has unit diagonal and a condition number smaller by the ratio of the extreme
diagonal entries of Sigma (here ~1e20 across timing-model vs red-noise
columns), after which a float32 Cholesky is well-posed.  All functions
broadcast over arbitrary leading batch dimensions and are jit/vmap-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def precond_cholesky(Sigma):
    """Jacobi-preconditioned Cholesky.

    Returns ``(L, dj)`` where ``L`` is the lower Cholesky factor of
    ``D Sigma D`` and ``dj`` the diagonal of ``D = diag(1/sqrt(diag Sigma))``.
    """
    diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sigma * dj[..., :, None] * dj[..., None, :]
    L = jnp.linalg.cholesky(A)
    return L, dj


def precond_solve(L, dj, v):
    """``Sigma^-1 v`` given the preconditioned factor from
    :func:`precond_cholesky`."""
    u = jax.scipy.linalg.solve_triangular(L, dj * v, lower=True)
    w = jax.scipy.linalg.solve_triangular(L, u, lower=True, trans=1)
    return dj * w


def precond_logdet(L, dj):
    """``log det Sigma`` from the preconditioned factor:
    ``logdet(D Sigma D) - 2 sum log dj``."""
    ldiag = jnp.diagonal(L, axis1=-2, axis2=-1)
    return 2.0 * jnp.sum(jnp.log(ldiag), axis=-1) - 2.0 * jnp.sum(
        jnp.log(dj), axis=-1)


def precond_sample(L, dj, mean, z):
    """Draw ``N(mean, Sigma^-1)`` given the factor of Sigma: with
    ``A = D Sigma D = L L^T``, ``x = mean + D L^-T z`` has covariance
    ``D A^-1 D = Sigma^-1`` (the reference samples the same law through an
    SVD square root, ``pulsar_gibbs.py:507-518``)."""
    w = jax.scipy.linalg.solve_triangular(L, z, lower=True, trans=1)
    return mean + dj * w


def mvn_conditional_draw(TNT, phiinv, d, z):
    """The complete b-draw kernel: mean ``Sigma^-1 d`` and a sample
    ``mean + Sigma^-1/2 z`` for ``Sigma = TNT + diag(phiinv)``.

    Batched over leading dims; returns ``(b, mean)``.
    """
    Sigma = TNT + _batched_diag(phiinv)
    L, dj = precond_cholesky(Sigma)
    mean = precond_solve(L, dj, d)
    return precond_sample(L, dj, mean, z), mean


def _batched_diag(v):
    """diag embedding that broadcasts over leading batch dimensions."""
    return v[..., :, None] * jnp.eye(v.shape[-1], dtype=v.dtype)
