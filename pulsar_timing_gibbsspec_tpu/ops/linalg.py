"""Device linear algebra for the Gibbs b-draw and marginalized likelihood.

The hot kernel is the per-pulsar factorization of ``Sigma = T^T N^-1 T +
diag(phi^-1)`` (reference ``pulsar_gibbs.py:489-520`` uses LAPACK SVD with a
QR fallback; ``:598-608`` uses Cholesky for the marginalized likelihood).
On TPU the idiomatic form is a *batched* Cholesky over the pulsar axis on
the MXU, in float32 made safe by Jacobi (diagonal) preconditioning:

    A = D Sigma D,   D = diag(1/sqrt(diag(Sigma)))

has unit diagonal and a condition number smaller by the ratio of the extreme
diagonal entries of Sigma (here ~1e20 across timing-model vs red-noise
columns), after which a float32 Cholesky is well-posed.  All functions
broadcast over arbitrary leading batch dimensions and are jit/vmap-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def precond_cholesky(Sigma, ridge=0.0):
    """Jacobi-preconditioned Cholesky (XLA-native lowering).

    Returns ``(L, dj)`` where ``L`` is the lower Cholesky factor of
    ``D Sigma D [+ ridge I]`` and ``dj`` the diagonal of
    ``D = diag(1/sqrt(diag Sigma))``.  ``ridge`` (on the unit-diagonal
    preconditioned matrix) guards an f32 factorization against entry
    rounding making a near-singular system indefinite.

    The production sweep paths use :func:`blocked_chol_inv` instead —
    XLA's native batched ``cholesky``/``solve_triangular`` lower to
    near-serial small-slice loops on TPU (12.6 ms vs 2.1 ms at the
    (64, 45, 37, 37) bench shape, ``tools/chol_probe.py``).  This
    native-path trio stays as the independent cross-check the tests and
    probes compare the blocked factorization against."""
    diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sigma * dj[..., :, None] * dj[..., None, :]
    if ridge:
        A = A + Sigma.dtype.type(ridge) * jnp.eye(A.shape[-1], dtype=A.dtype)
    L = jnp.linalg.cholesky(A)
    return L, dj


def precond_solve(L, dj, v):
    """``Sigma^-1 v`` given the preconditioned factor from
    :func:`precond_cholesky`."""
    u = jax.scipy.linalg.solve_triangular(L, dj * v, lower=True)
    w = jax.scipy.linalg.solve_triangular(L, u, lower=True, trans=1)
    return dj * w


def precond_logdet(L, dj):
    """``log det Sigma`` from the preconditioned factor:
    ``logdet(D Sigma D) - 2 sum log dj``."""
    ldiag = jnp.diagonal(L, axis1=-2, axis2=-1)
    return 2.0 * jnp.sum(jnp.log(ldiag), axis=-1) - 2.0 * jnp.sum(
        jnp.log(dj), axis=-1)


def jacobi_factor_mean(Sig, d, factor=None, ridge=0.0):
    """Jacobi-preconditioned factorization + conditional mean, the shared
    recipe of every b-draw/marginal-likelihood path: ``dj = 1/sqrt(diag
    Sig)``, ``(L, Li) = factor(D Sig D [+ ridge I])``, ``mean = Sig^-1 d
    = dj * Li^T (Li (dj d))`` as explicit-inverse matvecs.

    ``factor`` defaults to :func:`blocked_chol_inv`; pass
    :func:`tf_chol_factor` for the two-float near-f64 variant.  Matvecs
    run ``precision="highest"`` — required for the f32 instantiation
    (TPU default multiplies f32 in bf16) and a no-op for f64.  Returns
    ``(L, Li, dj, mean)``; batched over leading dims."""
    if factor is None:
        factor = blocked_chol_inv
    diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sig * dj[..., :, None] * dj[..., None, :]
    if ridge:
        A = A + ridge * jnp.eye(A.shape[-1], dtype=A.dtype)
    L, Li = factor(A)
    w = jnp.einsum("...ij,...j->...i", Li, dj * d, precision="highest")
    mean = dj * jnp.einsum("...ji,...j->...i", Li, w, precision="highest")
    return L, Li, dj, mean


def jacobi_factor_mean_prop(Sig, d, z, factor=None, ridge=0.0):
    """:func:`jacobi_factor_mean` fused with the proposal draw: the mean
    matvec ``dj * Li^T (Li (dj d))`` and the sample square-root matvec
    ``dj * Li^T z`` share the transposed factor, so stacking ``(w, z)``
    as a 2-column right-hand side turns two batched matvecs into one
    batched matmul — the Metropolised refresh's accept path then reads
    both results from a single MXU pass instead of several small
    per-pulsar ops.  Returns ``(L, Li, dj, mean, bp)`` with
    ``bp = mean + dj * Li^T z``; batched over leading dims."""
    if factor is None:
        factor = blocked_chol_inv
    diag = jnp.diagonal(Sig, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sig * dj[..., :, None] * dj[..., None, :]
    if ridge:
        A = A + ridge * jnp.eye(A.shape[-1], dtype=A.dtype)
    L, Li = factor(A)
    w = jnp.einsum("...ij,...j->...i", Li, dj * d, precision="highest")
    wz = jnp.stack([w, z.astype(w.dtype)], axis=-1)
    mz = jnp.einsum("...ji,...js->...is", Li, wz, precision="highest")
    mean = dj * mz[..., 0]
    bp = mean + dj * mz[..., 1]
    return L, Li, dj, mean, bp


def precond_sample(L, dj, mean, z):
    """Draw ``N(mean, Sigma^-1)`` given the factor of Sigma: with
    ``A = D Sigma D = L L^T``, ``x = mean + D L^-T z`` has covariance
    ``D A^-1 D = Sigma^-1`` (the reference samples the same law through an
    SVD square root, ``pulsar_gibbs.py:507-518``)."""
    w = jax.scipy.linalg.solve_triangular(L, z, lower=True, trans=1)
    return mean + dj * w


def mvn_conditional_draw(TNT, phiinv, d, z):
    """The complete b-draw kernel: mean ``Sigma^-1 d`` and a sample
    ``mean + Sigma^-1/2 z`` for ``Sigma = TNT + diag(phiinv)``.

    Uses the blocked matmul-scheduled factorization (:func:`
    blocked_chol_inv`) so that on TPU's software f64 every solve is a
    batched matvec: with ``A = D Sigma D = L L^T``,
    ``Sigma^-1 v = D Linv^T Linv D v`` and the sample square root is
    ``D Linv^T`` (same law the reference samples through an SVD square
    root, ``pulsar_gibbs.py:507-518``).

    Batched over leading dims; returns ``(b, mean)``.
    """
    Sigma = TNT + _batched_diag(phiinv)
    _, Li, dj, mean = jacobi_factor_mean(Sigma, d)
    samp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z,
                                  precision="highest")
    return samp, mean


def _batched_diag(v):
    """diag embedding that broadcasts over leading batch dimensions."""
    return v[..., :, None] * jnp.eye(v.shape[-1], dtype=v.dtype)


# ---------------------------------------------------------------------------
# blocked f64 Cholesky + inverse: matmul-rich factorization for TPU
# ---------------------------------------------------------------------------
#
# TPU emulates f64 in software; XLA's native lowering of
# ``jnp.linalg.cholesky``/``solve_triangular`` for f64 runs essentially
# serially (~80 MFLOP/s measured on a (45, 37, 37) batch — 9.4 ms), while
# batched f64 *matmuls* reach ~15 GFLOP/s.  The blocked right-looking
# factorization below keeps the O(B^3) Schur updates in matmuls and unrolls
# only the tiny diagonal panels, then builds the explicit blocked inverse
# L^-1 so every later solve is a batched matvec on the fast path.  ~5x
# faster end-to-end for the Gibbs b-draw at f64 accuracy (no precision
# compromise: the factorization is ordinary f64 arithmetic, just scheduled
# for the hardware).

def _mm(a, b):
    # precision="highest": required for the f32 instantiation of the
    # recursion (tf_chol_factor) — TPU's default einsum precision
    # multiplies f32 operands in bf16, whose ~1e-2 product error makes
    # the Schur complements of a lambda_min ~ 1e-5 system indefinite
    # (NaN factor).  No-op for the f64 instantiation.
    return jnp.einsum("...ik,...kj->...ij", a, b, precision="highest")


def _cholinv_rec(A):
    """Recursive batched (L, L^-1) of SPD ``A``: halve until 1x1/2x2
    closed forms, combine with batched matmuls.

    chol([[A11, .], [A21, A22]]) = [[L11, 0], [A21 L11^-T, chol(S)]] with
    ``S = A22 - L21 L21^T``; the inverse combines as
    ``Linv21 = -L22inv L21 L11inv``.
    """
    n = A.shape[-1]
    if n == 1:
        L = jnp.sqrt(A)
        return L, 1.0 / L
    if n == 2:
        a = jnp.sqrt(A[..., 0, 0])
        b = A[..., 1, 0] / a
        c = jnp.sqrt(A[..., 1, 1] - b * b)
        z = jnp.zeros_like(a)
        L = jnp.stack([jnp.stack([a, z], -1),
                       jnp.stack([b, c], -1)], -2)
        ia = 1.0 / a
        ic = 1.0 / c
        Li = jnp.stack([jnp.stack([ia, z], -1),
                        jnp.stack([-b * ia * ic, ic], -1)], -2)
        return L, Li
    h = n // 2
    L11, I11 = _cholinv_rec(A[..., :h, :h])
    L21 = _mm(A[..., h:, :h], jnp.swapaxes(I11, -1, -2))
    L22, I22 = _cholinv_rec(A[..., h:, h:] - _mm(L21, jnp.swapaxes(L21, -1,
                                                                   -2)))
    I21 = -_mm(I22, _mm(L21, I11))
    top = jnp.concatenate(
        [L11, jnp.zeros(A.shape[:-2] + (h, n - h), A.dtype)], axis=-1)
    bot = jnp.concatenate([L21, L22], axis=-1)
    L = jnp.concatenate([top, bot], axis=-2)
    itop = jnp.concatenate(
        [I11, jnp.zeros(A.shape[:-2] + (h, n - h), A.dtype)], axis=-1)
    ibot = jnp.concatenate([I21, I22], axis=-1)
    Li = jnp.concatenate([itop, ibot], axis=-2)
    return L, Li


def tf_mm(a, b, transpose_b=False):
    """Two-float (hi/lo f32 split) batched matmul of f64-valued operands
    on the MXU.

    ``a @ b`` with each operand split as ``hi = f32(v)``, ``lo =
    f32(v - hi)``; the three significant products (hi*hi, hi*lo, lo*hi)
    run as f32 einsums with ``precision="highest"`` and are recombined in
    f64.  The result carries the operands' full f64 values up to the f32
    accumulation of the hi*hi pass over the contraction axis — relative
    error ~sqrt(k) * eps_f32 (~3e-7 at k=37), vs ~15 GFLOP/s for XLA's
    emulated-f64 matmul on the VPU.  Used where a small, *known* forward
    error is acceptable (Metropolised proposal factors); not a drop-in
    for exact f64 matmuls.
    """
    f32 = jnp.float32
    dt = a.dtype
    ah = a.astype(f32)
    al = (a - ah.astype(dt)).astype(f32)
    bh = b.astype(f32)
    bl = (b - bh.astype(dt)).astype(f32)
    eq = "...ik,...jk->...ij" if transpose_b else "...ik,...kj->...ij"

    def mm32(u, v):
        return jnp.einsum(eq, u, v, precision="highest",
                          preferred_element_type=f32)

    hh = mm32(ah, bh)
    cross = mm32(ah, bl) + mm32(al, bh)
    return hh.astype(dt) + cross.astype(dt)


def tf_chol_factor(A, ridge=4e-6):
    """Near-f64 triangular factor of SPD unit-diagonal ``A`` built from
    f32 MXU primitives: returns ``(L, Li)`` with ``Li A Li^T = I + E``,
    ``||E|| ~ n * eps_f32`` (~5e-6 at n=37) — *independent of cond(A)*.

    Two stages: (1) ``L0 = chol_f32(A32 + ridge I)`` — the ridge keeps
    the f32 factorization of a system with ``lambda_min`` as small as
    ~4.5e-6 from breaking down, at the price of an O(1) distortion of the
    softest directions; (2) the residual congruence ``R = Li0 A Li0^T``
    (two-float matmuls, f64 values) is *well-conditioned* (``lambda_min(R)
    >= lambda_min(A) / (lambda_min(A) + ridge + chol backward error)``,
    measured ~0.3), so its f32 Cholesky ``Lr`` is accurate to f32
    rounding without any conditioning amplification, and
    ``Li = Lr^-1 Li0``, ``L = L0 Lr`` correct the stage-1 distortion
    exactly up to that rounding.  Cost: two f32 cholesky + two f32
    triangular inversions + three two-float matmuls — all MXU — vs the
    ~60 ms (C=32, B=37, P=45) of the f64 blocked factorization.

    A breakdown (A32 + ridge indefinite beyond observed margins) yields
    NaN rows; callers Metropolise and mask, so a NaN only skips that
    pulsar's update for the sweep.

    Both f32 factorizations use the blocked matmul recursion
    (:func:`blocked_chol_inv` in f32) rather than XLA's native batched
    ``cholesky``/``solve_triangular``, whose TPU lowerings are
    loop-scheduled and dominate the factor cost at this batch width.
    """
    f32 = jnp.float32
    dt = A.dtype
    n = A.shape[-1]
    eye32 = jnp.eye(n, dtype=f32)
    A32 = A.astype(f32)
    L0, Li0 = _cholinv_rec(A32 + f32(ridge) * eye32)
    # residual congruence in two-float: R = Li0 A Li0^T ~ I
    R = tf_mm(tf_mm(Li0.astype(dt), A), Li0.astype(dt), transpose_b=True)
    Lr, Lir = _cholinv_rec(R.astype(f32))
    Li = tf_mm(Lir.astype(dt), Li0.astype(dt))
    L = tf_mm(L0.astype(dt), Lr.astype(dt))
    return L, Li


def blocked_chol_inv(A):
    """Batched lower Cholesky ``L`` of SPD ``A`` and its explicit inverse
    ``Linv = L^-1``, scheduled as a recursion of batched matmuls.

    TPU emulates f64 in software; XLA's native f64
    ``cholesky``/``solve_triangular`` lowering runs essentially serially
    (~80 MFLOP/s measured on a (45, 37, 37) batch — 9.4 ms + 5.7 ms for
    the solves), while batched f64 *matmuls* reach ~15 GFLOP/s.  This
    factorization keeps the O(B^3) work in matmuls and reduces every
    later solve to a batched matvec with ``Linv``.  Ordinary f64
    arithmetic — no precision compromise vs ``jnp.linalg.cholesky``.
    """
    return _cholinv_rec(A)


# ---------------------------------------------------------------------------
# block-grid Cholesky: factorization over an m x m grid of P x P blocks
# ---------------------------------------------------------------------------
#
# The correlated-ORF joint b-draw's Schur complement on the GW subspace is
# a (2K, 2K) grid of (P, P) blocks: diagonal-in-pulsar TNT couplings on
# every grid cell plus the dense cross-pulsar HD prior G^-1/rho_k on the
# grid diagonal only.  A dense (2KP, 2KP) recursion works but its program
# size grows with the flattened dimension (the same growth that capped the
# old dense joint draw at HD_DENSE_MAX total coefficients); the grid
# factorization below keeps every operation at the (P, P) block size — m
# unrolled stages, each one diagonal-block recursion plus batched (P, P)
# matmul trailing updates — so the compiled program scales with m, not
# (mP)^2, and the matmuls stay MXU-shaped.  It is the SAME Cholesky (same
# ordering, same arithmetic up to f64 roundoff) as factoring the flattened
# matrix, so the sampled law is identical to the dense reference path.

def _mm_t(a, b, transpose_b=False):
    """f64 batched matmul with the tf_mm calling convention, so the grid
    factorization can swap between exact and two-float instantiations."""
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return _mm(a, b)


def block_grid_cholinv(S, factor=None, mm=None):
    """Blocked right-looking Cholesky of an SPD matrix laid out as an
    ``(..., m, m, P, P)`` grid of blocks (``S[..., i, j]`` is block row
    ``i``, block column ``j``; grid-symmetric: ``S[i, j] == S[j, i]^T``).

    Returns ``(Ld, Ldi, Loff)``:

    - ``Ld``  ``(..., m, P, P)``: the lower-triangular diagonal blocks of
      the factor ``L``;
    - ``Ldi`` ``(..., m, P, P)``: their explicit inverses (every solve
      below is a batched matvec, the :func:`blocked_chol_inv` discipline);
    - ``Loff`` ``(..., m, m, P, P)``: the strictly-lower off-diagonal
      blocks of ``L`` (zeros elsewhere).

    ``factor`` is the per-diagonal-block ``(L, L^-1)`` routine
    (:func:`blocked_chol_inv` for f64, :func:`tf_chol_factor` for the
    two-float mixed-precision mode) and ``mm`` the matching matmul with
    the ``(a, b, transpose_b=False)`` convention (:func:`_mm_t` /
    :func:`tf_mm`).  ``m`` is unrolled at trace time.
    """
    if factor is None:
        factor = _cholinv_rec
    if mm is None:
        mm = _mm_t
    m = S.shape[-4]
    Ld, Ldi = [], []
    Loff = jnp.zeros(S.shape, S.dtype)
    T = S
    for g in range(m):
        Lg, Lgi = factor(T[..., 0, 0, :, :])
        Ld.append(Lg)
        Ldi.append(Lgi)
        if g == m - 1:
            break
        # column panel: L[j, g] = T[j, 0] @ Lg^-T for all trailing j
        Lcol = mm(T[..., 1:, 0, :, :], Lgi[..., None, :, :],
                  transpose_b=True)                     # (..., r, P, P)
        Loff = Loff.at[..., g + 1:, g, :, :].set(Lcol)
        # trailing Schur update, all (j, l) pairs as one batched matmul
        upd = mm(Lcol[..., :, None, :, :], Lcol[..., None, :, :, :],
                 transpose_b=True)                      # (..., r, r, P, P)
        T = T[..., 1:, 1:, :, :] - upd
    return (jnp.stack(Ld, axis=-3), jnp.stack(Ldi, axis=-3), Loff)


def block_grid_solve_lower(Ldi, Loff, r):
    """``L v = r`` with the grid factor from :func:`block_grid_cholinv`;
    ``r`` is ``(..., m, P)`` in block-major order.  Forward substitution
    over the unrolled block stages — every step a (P, P) matvec."""
    m = r.shape[-2]
    vs = []
    for g in range(m):
        acc = r[..., g, :]
        for j in range(g):
            acc = acc - jnp.einsum("...ij,...j->...i",
                                   Loff[..., g, j, :, :], vs[j],
                                   precision="highest")
        vs.append(jnp.einsum("...ij,...j->...i", Ldi[..., g, :, :], acc,
                             precision="highest"))
    return jnp.stack(vs, axis=-2)


def block_grid_solve_upper(Ldi, Loff, r):
    """``L^T w = r`` with the grid factor (backward substitution)."""
    m = r.shape[-2]
    ws = [None] * m
    for g in reversed(range(m)):
        acc = r[..., g, :]
        for j in range(g + 1, m):
            acc = acc - jnp.einsum("...ji,...j->...i",
                                   Loff[..., j, g, :, :], ws[j],
                                   precision="highest")
        ws[g] = jnp.einsum("...ji,...j->...i", Ldi[..., g, :, :], acc,
                           precision="highest")
    return jnp.stack(ws, axis=-2)


def block_grid_to_dense(S):
    """``(..., m, m, P, P)`` grid -> ``(..., mP, mP)`` dense matrix in
    block-major coordinate order (``dense[g P + p, g' P + q] =
    S[g, g', p, q]``) — the small-system fallback path factors this with
    one :func:`blocked_chol_inv` recursion; identical ordering means the
    factor (hence the drawn sample) matches the grid path exactly."""
    m, P = S.shape[-4], S.shape[-1]
    return jnp.moveaxis(S, -2, -3).reshape(S.shape[:-4] + (m * P, m * P))
