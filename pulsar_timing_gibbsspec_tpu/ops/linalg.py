"""Device linear algebra for the Gibbs b-draw and marginalized likelihood.

The hot kernel is the per-pulsar factorization of ``Sigma = T^T N^-1 T +
diag(phi^-1)`` (reference ``pulsar_gibbs.py:489-520`` uses LAPACK SVD with a
QR fallback; ``:598-608`` uses Cholesky for the marginalized likelihood).
On TPU the idiomatic form is a *batched* Cholesky over the pulsar axis on
the MXU, in float32 made safe by Jacobi (diagonal) preconditioning:

    A = D Sigma D,   D = diag(1/sqrt(diag(Sigma)))

has unit diagonal and a condition number smaller by the ratio of the extreme
diagonal entries of Sigma (here ~1e20 across timing-model vs red-noise
columns), after which a float32 Cholesky is well-posed.  All functions
broadcast over arbitrary leading batch dimensions and are jit/vmap-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def precond_cholesky(Sigma, ridge=0.0):
    """Jacobi-preconditioned Cholesky.

    Returns ``(L, dj)`` where ``L`` is the lower Cholesky factor of
    ``D Sigma D [+ ridge I]`` and ``dj`` the diagonal of
    ``D = diag(1/sqrt(diag Sigma))``.  ``ridge`` (on the unit-diagonal
    preconditioned matrix) guards an f32 factorization against entry
    rounding making a near-singular system indefinite."""
    diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sigma * dj[..., :, None] * dj[..., None, :]
    if ridge:
        A = A + Sigma.dtype.type(ridge) * jnp.eye(A.shape[-1], dtype=A.dtype)
    L = jnp.linalg.cholesky(A)
    return L, dj


def precond_solve(L, dj, v):
    """``Sigma^-1 v`` given the preconditioned factor from
    :func:`precond_cholesky`."""
    u = jax.scipy.linalg.solve_triangular(L, dj * v, lower=True)
    w = jax.scipy.linalg.solve_triangular(L, u, lower=True, trans=1)
    return dj * w


def precond_logdet(L, dj):
    """``log det Sigma`` from the preconditioned factor:
    ``logdet(D Sigma D) - 2 sum log dj``."""
    ldiag = jnp.diagonal(L, axis1=-2, axis2=-1)
    return 2.0 * jnp.sum(jnp.log(ldiag), axis=-1) - 2.0 * jnp.sum(
        jnp.log(dj), axis=-1)


def precond_sample(L, dj, mean, z):
    """Draw ``N(mean, Sigma^-1)`` given the factor of Sigma: with
    ``A = D Sigma D = L L^T``, ``x = mean + D L^-T z`` has covariance
    ``D A^-1 D = Sigma^-1`` (the reference samples the same law through an
    SVD square root, ``pulsar_gibbs.py:507-518``)."""
    w = jax.scipy.linalg.solve_triangular(L, z, lower=True, trans=1)
    return mean + dj * w


def mvn_conditional_draw(TNT, phiinv, d, z):
    """The complete b-draw kernel: mean ``Sigma^-1 d`` and a sample
    ``mean + Sigma^-1/2 z`` for ``Sigma = TNT + diag(phiinv)``.

    Uses the blocked matmul-scheduled factorization (:func:`
    blocked_chol_inv`) so that on TPU's software f64 every solve is a
    batched matvec: with ``A = D Sigma D = L L^T``,
    ``Sigma^-1 v = D Linv^T Linv D v`` and the sample square root is
    ``D Linv^T`` (same law the reference samples through an SVD square
    root, ``pulsar_gibbs.py:507-518``).

    Batched over leading dims; returns ``(b, mean)``.
    """
    Sigma = TNT + _batched_diag(phiinv)
    diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sigma * dj[..., :, None] * dj[..., None, :]
    _, Li = blocked_chol_inv(A)
    u = jnp.einsum("...ij,...j->...i", Li, dj * d)
    mean = dj * jnp.einsum("...ji,...j->...i", Li, u)
    samp = mean + dj * jnp.einsum("...ji,...j->...i", Li, z)
    return samp, mean


def _batched_diag(v):
    """diag embedding that broadcasts over leading batch dimensions."""
    return v[..., :, None] * jnp.eye(v.shape[-1], dtype=v.dtype)


# ---------------------------------------------------------------------------
# blocked f64 Cholesky + inverse: matmul-rich factorization for TPU
# ---------------------------------------------------------------------------
#
# TPU emulates f64 in software; XLA's native lowering of
# ``jnp.linalg.cholesky``/``solve_triangular`` for f64 runs essentially
# serially (~80 MFLOP/s measured on a (45, 37, 37) batch — 9.4 ms), while
# batched f64 *matmuls* reach ~15 GFLOP/s.  The blocked right-looking
# factorization below keeps the O(B^3) Schur updates in matmuls and unrolls
# only the tiny diagonal panels, then builds the explicit blocked inverse
# L^-1 so every later solve is a batched matvec on the fast path.  ~5x
# faster end-to-end for the Gibbs b-draw at f64 accuracy (no precision
# compromise: the factorization is ordinary f64 arithmetic, just scheduled
# for the hardware).

def _mm(a, b):
    return jnp.einsum("...ik,...kj->...ij", a, b)


def _cholinv_rec(A):
    """Recursive batched (L, L^-1) of SPD ``A``: halve until 1x1/2x2
    closed forms, combine with batched matmuls.

    chol([[A11, .], [A21, A22]]) = [[L11, 0], [A21 L11^-T, chol(S)]] with
    ``S = A22 - L21 L21^T``; the inverse combines as
    ``Linv21 = -L22inv L21 L11inv``.
    """
    n = A.shape[-1]
    if n == 1:
        L = jnp.sqrt(A)
        return L, 1.0 / L
    if n == 2:
        a = jnp.sqrt(A[..., 0, 0])
        b = A[..., 1, 0] / a
        c = jnp.sqrt(A[..., 1, 1] - b * b)
        z = jnp.zeros_like(a)
        L = jnp.stack([jnp.stack([a, z], -1),
                       jnp.stack([b, c], -1)], -2)
        ia = 1.0 / a
        ic = 1.0 / c
        Li = jnp.stack([jnp.stack([ia, z], -1),
                        jnp.stack([-b * ia * ic, ic], -1)], -2)
        return L, Li
    h = n // 2
    L11, I11 = _cholinv_rec(A[..., :h, :h])
    L21 = _mm(A[..., h:, :h], jnp.swapaxes(I11, -1, -2))
    L22, I22 = _cholinv_rec(A[..., h:, h:] - _mm(L21, jnp.swapaxes(L21, -1,
                                                                   -2)))
    I21 = -_mm(I22, _mm(L21, I11))
    top = jnp.concatenate(
        [L11, jnp.zeros(A.shape[:-2] + (h, n - h), A.dtype)], axis=-1)
    bot = jnp.concatenate([L21, L22], axis=-1)
    L = jnp.concatenate([top, bot], axis=-2)
    itop = jnp.concatenate(
        [I11, jnp.zeros(A.shape[:-2] + (h, n - h), A.dtype)], axis=-1)
    ibot = jnp.concatenate([I21, I22], axis=-1)
    Li = jnp.concatenate([itop, ibot], axis=-2)
    return L, Li


def blocked_chol_inv(A):
    """Batched lower Cholesky ``L`` of SPD ``A`` and its explicit inverse
    ``Linv = L^-1``, scheduled as a recursion of batched matmuls.

    TPU emulates f64 in software; XLA's native f64
    ``cholesky``/``solve_triangular`` lowering runs essentially serially
    (~80 MFLOP/s measured on a (45, 37, 37) batch — 9.4 ms + 5.7 ms for
    the solves), while batched f64 *matmuls* reach ~15 GFLOP/s.  This
    factorization keeps the O(B^3) work in matmuls and reduces every
    later solve to a batched matvec with ``Linv``.  Ordinary f64
    arithmetic — no precision compromise vs ``jnp.linalg.cholesky``.
    """
    return _cholinv_rec(A)
