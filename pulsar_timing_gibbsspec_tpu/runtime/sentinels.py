"""Divergence sentinels: cheap health checks on the sampled chain.

Three layers, cheapest first:

- :func:`chunk_health` — on-device reductions folded into the jax
  backend's compiled chunk (``_make_chunk``): per-chain all-finite
  flags and a moved-fraction (the complement of a fully stuck / MH
  acceptance-collapsed chain), a few scalars per chunk instead of
  shipping judgment to the host.
- :class:`SentinelMonitor` — host-side tracker of those reductions:
  logs acceptance-collapse warnings through ``metrics.jsonl`` and
  raises :class:`ChainDivergence` after ``stuck_chunks`` consecutive
  fully-stuck chunks (a sampler wedged in a rejection loop).
- :func:`check_rows` — backend-agnostic host check on newly recorded
  rows (the facade runs it before rows can reach a checkpoint).

Recovery is the supervisor's job: a divergence rewinds to the last
checkpoint and replays; a divergence that REPEATS at the same point on
a deterministic replay gets :func:`refold_checkpoint_key` — a fresh
PRNG fold at the checkpoint — so the re-draw explores a different
stream instead of deterministically reproducing the blow-up.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from . import telemetry


class ChainDivergence(FloatingPointError):
    """A recorded stretch of chain failed a health check.

    ``row`` is the first offending recorded row (absolute index);
    ``what`` is a short tag (``"nonfinite"``, ``"stuck_chain"``).
    Subclasses FloatingPointError so existing non-finite handling (and
    the supervisor's failure taxonomy) treats both uniformly.
    """

    def __init__(self, msg, row=None, what=None):
        super().__init__(msg)
        self.row = row
        self.what = what


#: slack (in x = 0.5*log10(rho) units) past the prior bounds before a
#: recorded rho value counts as a breach — grid endpoints land exactly
#: on the bound, so the tolerance keeps legal draws out of the flag
RHO_BOUND_TOL = 1e-6


def chunk_health(xs, bs, rho_ix=None, rho_lo=None, rho_hi=None):
    """On-device health reductions over a chunk's recorded stacks.

    ``xs`` is (n, C, nx), ``bs`` (n, C, ...) where C is the chain axis
    (the tenant-row axis in the serving tier — rows are independent
    conditional chains, so each gets its own verdict).  Returns a
    per-row health vector:

    - ``finite`` (C,) bool — every recorded value finite;
    - ``move_frac`` (C,) float32 — fraction of recorded steps where the
      chain state changed at all (a fully stuck chain — MH acceptance
      collapsed to zero AND every conditional frozen — scores 0.0);
    - ``rho_ok`` (C,) bool — every recorded common-rho coordinate
      (``xs[..., rho_ix]``, x units = 0.5*log10(rho)) inside the prior
      bounds ``[rho_lo, rho_hi]`` ± :data:`RHO_BOUND_TOL`.  A breach
      means the conjugate draw escaped its own grid — numerically
      poisoned even while still finite.  All-True when the model
      samples no common rho (``rho_ix`` None/empty).

    Traced inside the jitted chunk, so the host receives a handful of
    scalars per row, not a verdict-sized transfer.
    """
    import jax.numpy as jnp

    fin = (jnp.all(jnp.isfinite(xs), axis=(0, 2))
           & jnp.all(jnp.isfinite(bs),
                     axis=tuple([0] + list(range(2, bs.ndim)))))
    if xs.shape[0] > 1:
        moved = jnp.mean(
            jnp.any(xs[1:] != xs[:-1], axis=-1).astype(jnp.float32), axis=0)
    else:
        # a single recorded row carries no movement information
        moved = jnp.ones((xs.shape[1],), jnp.float32)
    C = xs.shape[1]
    if (rho_ix is None or getattr(rho_ix, "size", 0) == 0
            or rho_lo is None or rho_hi is None):
        rho_ok = jnp.ones((C,), bool)
    elif getattr(rho_ix, "ndim", 1) == 2:
        # serving tier: per-row index columns (C, K) from the stacked
        # CompiledPTA — gather each row's own rho coordinates
        rows = jnp.take_along_axis(
            xs, rho_ix.astype(jnp.int32)[None, :, :], axis=2)
        rho_ok = jnp.all((rows >= rho_lo - RHO_BOUND_TOL)
                         & (rows <= rho_hi + RHO_BOUND_TOL), axis=(0, 2))
    else:
        rows = xs[:, :, jnp.asarray(rho_ix, jnp.int32)]
        rho_ok = jnp.all((rows >= rho_lo - RHO_BOUND_TOL)
                         & (rows <= rho_hi + RHO_BOUND_TOL), axis=(0, 2))
    return {"finite": fin, "move_frac": moved, "rho_ok": rho_ok}


class SentinelMonitor:
    """Tracks per-chunk health across a run.

    ``collapse_frac``: below this moved-fraction a chain is flagged as
    acceptance-collapsed (warning event, run continues).
    ``stuck_chunks``: after this many CONSECUTIVE fully-stuck chunks
    (moved fraction exactly 0) a :class:`ChainDivergence` is raised —
    replaying a wedged sampler forever is not progress.
    """

    def __init__(self, collapse_frac=0.02, stuck_chunks=3):
        self.collapse_frac = float(collapse_frac)
        self.stuck_chunks = int(stuck_chunks)
        self.events = []
        self.last = None
        self._streak = None

    def reset_run(self):
        """Forget streak state at the start of a fresh run()/retry."""
        self._streak = None

    def observe(self, health, it):
        """Fold one chunk's host-side health dict in; returns the new
        warning events (also appended to ``self.events``)."""
        fin = np.atleast_1d(np.asarray(health["finite"]))
        mv = np.atleast_1d(np.asarray(health["move_frac"], np.float64))
        self.last = {"finite_frac": float(fin.mean()),
                     "move_frac_min": round(float(mv.min()), 4),
                     "move_frac_mean": round(float(mv.mean()), 4)}
        if self._streak is None or len(self._streak) != len(mv):
            self._streak = np.zeros(len(mv), dtype=int)
        stuck = mv <= 0.0
        self._streak = np.where(stuck, self._streak + 1, 0)
        events = []
        if "rho_ok" in health:
            rok = np.atleast_1d(np.asarray(health["rho_ok"]))
            self.last["rho_ok_frac"] = float(rok.mean())
            if not rok.all():
                # a rho-bound breach is numerically poisoned state even
                # while finite: warn + count, leave the verdict (rewind
                # vs quarantine) to the supervisor / serving tier
                telemetry.incr("rho_bound_breaches")
                events.append({"event": "rho_bound_breach", "iter": int(it),
                               "chains": np.where(~rok)[0].tolist()})
        low = (mv < self.collapse_frac) & ~stuck
        if low.any():
            events.append({"event": "mh_acceptance_collapse", "iter": int(it),
                           "chains": np.where(low)[0].tolist(),
                           "move_frac": [round(float(v), 4)
                                         for v in mv[low]]})
        if (self._streak >= self.stuck_chunks).any():
            chains = np.where(self._streak >= self.stuck_chunks)[0].tolist()
            telemetry.incr("sentinel_trips")
            raise ChainDivergence(
                f"chains {chains} recorded identical states for "
                f"{self.stuck_chunks} consecutive chunks (iteration "
                f"{it}): the sampler is wedged — rewind and re-draw",
                row=int(it), what="stuck_chain")
        if events:
            telemetry.incr("sentinel_events", len(events))
            self.events += events
        return events


def check_rows(chain, bchain, lo, hi):
    """Backend-agnostic host sentinel on newly recorded rows [lo, hi):
    raises :class:`ChainDivergence` on any non-finite value, naming the
    first bad absolute row, BEFORE the rows can reach a checkpoint."""
    if hi <= lo:
        return
    for nm, arr in (("chain", chain), ("bchain", bchain)):
        seg = np.asarray(arr[lo:hi])
        if seg.size == 0:
            continue
        flat = seg.reshape(len(seg), -1)
        bad = ~np.isfinite(flat).all(axis=1)
        if bad.any():
            row = lo + int(np.argmax(bad))
            telemetry.incr("sentinel_trips")
            raise ChainDivergence(
                f"non-finite {nm} state recorded at row {row}: the sweep "
                "diverged — rows past the last checkpoint are discarded",
                row=row, what="nonfinite")


def refold_checkpoint_key(outdir, salt) -> bool:
    """Perturb the checkpoint's PRNG state with ``salt`` (atomically,
    manifest updated to match).

    Used when a divergence reproduces on deterministic replay: the
    rewound retry then re-draws the diverged stretch under a fresh
    stream.  This intentionally breaks bit-exact resume from the refold
    point on — that is the point.  Works on both backends' checkpoints
    (jax ``jax_key`` via ``fold_in``; numpy ``rng_state`` via a
    ``SeedSequence`` over the old packed state + salt).
    """
    apath = Path(outdir) / "adapt.npz"
    if not apath.exists():
        return False
    with np.load(apath) as z:
        state = {k: z[k] for k in z.files}
    if "jax_key" in state:
        import jax.random as jr

        key = jr.wrap_key_data(np.asarray(state["jax_key"], np.uint32))
        state["jax_key"] = np.asarray(jr.key_data(
            jr.fold_in(key, int(salt))))
    elif "rng_state" in state:
        from ..sampler.blocks import rng_state_pack

        ent = [int(salt)] + [int(v) for v in
                             np.asarray(state["rng_state"], np.uint64)]
        rng = np.random.default_rng(np.random.SeedSequence(ent))
        state["rng_state"] = rng_state_pack(rng)
    else:
        return False
    it = state.pop("iter")
    tmp = apath.with_name("adapt.npz.tmp.npz")
    np.savez(tmp, iter=it, **state)
    os.replace(tmp, apath)
    # the manifest tracks adapt.npz's hash: rewrite it (same row count)
    # or the refolded checkpoint would itself be rejected on resume
    from . import integrity

    man = integrity.read_manifest(outdir)
    if man is not None and not man.get("corrupt"):
        # carry any non-core manifest sections (logical layout, shard
        # map) through the rewrite — dropping them would strand the
        # refolded checkpoint on its original device count
        extra = {k: v for k, v in man.items()
                 if k not in ("schema", "rows", "written_at", "files")}
        integrity.write_manifest(outdir, man.get("rows", int(it)),
                                 extra=extra or None)
    telemetry.incr("refolds")
    return True
