"""Process-wide resilience counters.

One tiny registry shared by the integrity layer, the sentinels and the
supervisor so retry/rollback/degradation activity is visible in one
place: ``bench.py`` embeds :func:`snapshot` in its JSON line and the
supervisor mirrors the same numbers into ``metrics.jsonl`` events.

Counter names in use (others may appear; consumers must not assume a
closed set):

- ``retries``             supervisor attempts beyond the first
- ``rollbacks``           checkpoints restored from the ``.bak`` set
- ``refolds``             checkpoint PRNG keys perturbed after a
                          repeated (deterministic) divergence
- ``degradations``        jax -> numpy backend downgrades
- ``torn_checkpoints``    chain/bchain row-count mismatches on resume
- ``corrupt_checkpoints`` manifest verification failures on resume
- ``sentinel_events``     non-fatal health warnings (acceptance collapse)
- ``sentinel_trips``      sentinel-raised divergences (stuck/non-finite)
- ``preempt_requests``    drain requests (signal or maintenance hook)
- ``preempt_drains``      drains completed to a verified checkpoint
- ``drain_abandoned_chunks``  in-flight chunks dropped at the deadline
- ``watchdog_soft``       dispatch past the soft deadline (logged only)
- ``watchdog_dumps``      stack dumps at the hard deadline
- ``watchdog_stalls``     chunk dispatches aborted as stalled
- ``stall_retries``       supervisor retries under the stall policy

Gauges (:func:`gauge`) carry last-value measurements (floats) next to
the counters — e.g. ``drain_latency_ms``, the request-to-verified-
checkpoint time of the most recent preemption drain.

Serving-layer gauges (``serve.service``, glossary in docs/SERVING.md):

- ``queue_depth``              requests waiting for a batch-row slot
- ``warm_hit_rate``            fraction of admissions that landed on an
                               already-compiled bucket program
- ``compile_stalls``           admissions that had to wait for a bucket
                               program compile (cold bucket)
- ``tenant_evictions``         residents checkpointed + requeued to make
                               room (fair-share churn or injected)
- ``time_to_first_sample_ms``  submit-to-first-recorded-sweep latency of
                               the most recent request
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counts: dict[str, int] = {}
_gauges: dict[str, float] = {}


def incr(name: str, n: int = 1) -> int:
    """Add ``n`` to counter ``name`` (created at 0); returns the new value."""
    with _lock:
        _counts[name] = _counts.get(name, 0) + int(n)
        return _counts[name]


def get(name: str) -> int:
    with _lock:
        return _counts.get(name, 0)


def gauge(name: str, value: float) -> None:
    """Record a last-value measurement (overwrites; e.g. latencies)."""
    with _lock:
        _gauges[name] = float(value)


def get_gauge(name: str, default: float | None = None):
    with _lock:
        return _gauges.get(name, default)


def gauges() -> dict[str, float]:
    """Copy of all gauges, sorted by name."""
    with _lock:
        return dict(sorted(_gauges.items()))


def snapshot() -> dict[str, int]:
    """Copy of all counters, sorted by name (stable for JSON output)."""
    with _lock:
        return dict(sorted(_counts.items()))


def reset() -> None:
    """Zero every counter and gauge (tests; bench run isolation)."""
    with _lock:
        _counts.clear()
        _gauges.clear()
