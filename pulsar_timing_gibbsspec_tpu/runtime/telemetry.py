"""Process-wide resilience counters.

One tiny registry shared by the integrity layer, the sentinels and the
supervisor so retry/rollback/degradation activity is visible in one
place: ``bench.py`` embeds :func:`snapshot` in its JSON line and the
supervisor mirrors the same numbers into ``metrics.jsonl`` events.

Counter names in use (others may appear; consumers must not assume a
closed set):

- ``retries``             supervisor attempts beyond the first
- ``rollbacks``           checkpoints restored from the ``.bak`` set
- ``refolds``             checkpoint PRNG keys perturbed after a
                          repeated (deterministic) divergence
- ``degradations``        jax -> numpy backend downgrades
- ``torn_checkpoints``    chain/bchain row-count mismatches on resume
- ``corrupt_checkpoints`` manifest verification failures on resume
- ``sentinel_events``     non-fatal health warnings (acceptance collapse)
- ``sentinel_trips``      sentinel-raised divergences (stuck/non-finite)
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counts: dict[str, int] = {}


def incr(name: str, n: int = 1) -> int:
    """Add ``n`` to counter ``name`` (created at 0); returns the new value."""
    with _lock:
        _counts[name] = _counts.get(name, 0) + int(n)
        return _counts[name]


def get(name: str) -> int:
    with _lock:
        return _counts.get(name, 0)


def snapshot() -> dict[str, int]:
    """Copy of all counters, sorted by name (stable for JSON output)."""
    with _lock:
        return dict(sorted(_counts.items()))


def reset() -> None:
    """Zero every counter (tests; bench run isolation)."""
    with _lock:
        _counts.clear()
