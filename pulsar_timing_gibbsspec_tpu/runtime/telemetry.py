"""Process-wide resilience counters.

One tiny registry shared by the integrity layer, the sentinels and the
supervisor so retry/rollback/degradation activity is visible in one
place: ``bench.py`` embeds :func:`snapshot` in its JSON line and the
supervisor mirrors the same numbers into ``metrics.jsonl`` events.

Counter names in use (others may appear; consumers must not assume a
closed set):

- ``retries``             supervisor attempts beyond the first
- ``rollbacks``           checkpoints restored from the ``.bak`` set
- ``refolds``             checkpoint PRNG keys perturbed after a
                          repeated (deterministic) divergence
- ``degradations``        jax -> numpy backend downgrades
- ``torn_checkpoints``    chain/bchain row-count mismatches on resume
- ``corrupt_checkpoints`` manifest verification failures on resume
- ``sentinel_events``     non-fatal health warnings (acceptance collapse)
- ``sentinel_trips``      sentinel-raised divergences (stuck/non-finite)
- ``preempt_requests``    drain requests (signal or maintenance hook)
- ``preempt_drains``      drains completed to a verified checkpoint
- ``drain_abandoned_chunks``  in-flight chunks dropped at the deadline
- ``watchdog_soft``       dispatch past the soft deadline (logged only)
- ``watchdog_dumps``      stack dumps at the hard deadline
- ``watchdog_stalls``     chunk dispatches aborted as stalled
- ``stall_retries``       supervisor retries under the stall policy
- ``stage_band_breaches`` stage samples past ``band_k``x their EMA
                          (labeled ``stage=``; obs.perf.StageAggregator)
- ``anomaly_captures``    flight-recorder windows opened (obs.perf)

Gauges (:func:`gauge`) carry last-value measurements (floats) next to
the counters — e.g. ``drain_latency_ms``, the request-to-verified-
checkpoint time of the most recent preemption drain; the streaming
dispatch attribution lives here too: ``dispatch_ms{stage=,stat=}``,
``chunk_wall_ms``/``chunk_wall_ema_ms`` (driver steady loop) and
``watchdog_ema_s``/``watchdog_deadline_s`` (glossary:
docs/OBSERVABILITY.md "Streaming stage gauges").

Serving-layer gauges and their glossary moved to docs/OBSERVABILITY.md
("Metric and label glossary") together with the per-job labeled serve
gauges (``serve_ess_per_sec``/``serve_rhat_max``/``serve_accept_rate``).

**Labels.**  ``incr``/``gauge`` (and their getters) accept keyword
labels: ``gauge("serve_ess_per_sec", v, tenant="3")`` stores the series
under the composite key ``serve_ess_per_sec{tenant="3"}`` (Prometheus
exposition syntax, labels sorted — so per-tenant serve gauges never
collide process-wide).  Plain-name calls are untouched; consumers that
iterate :func:`snapshot`/:func:`gauges` see composite keys as strings,
and ``obs.metrics`` parses them back into real Prometheus labels.

**Scoping.**  :func:`snapshot`/:func:`gauges`/:func:`reset` take an
optional ``prefix`` filtered on the BASE name (label part ignored), so
chaos/serve tests can clear exactly their own namespace
(``reset("serve_")``) without erasing counters another suite asserts on.
"""

from __future__ import annotations

import threading

# RLock, not Lock: ``incr`` is reachable from the preemption signal
# handler (request_drain -> incr), which can land while the main thread
# holds this lock in another telemetry call.  Reentry on an RLock costs
# at worst a racy re-read the owner re-does; a plain Lock costs the
# process (self-deadlock inside the handler).
_lock = threading.RLock()
_counts: dict[str, int] = {}
_gauges: dict[str, float] = {}


def _esc(v) -> str:
    """Prometheus label-value escaping; keeps composite keys parseable
    when a value carries quotes/backslashes (e.g. a path label).  ``\\r``
    is escaped too — the exposition spec only names ``\\n``, but a bare
    carriage return from a hostile network-supplied label value would
    still break line-oriented scrapers."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n").replace("\r", "\\r")


def labeled(name: str, **labels) -> str:
    """The composite registry key of a labeled series (identity for no
    labels).  Matches Prometheus exposition syntax; ``obs.metrics.
    split_key`` is the inverse (including value unescaping)."""
    if not labels:
        return name
    lab = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
    return f"{name}{{{lab}}}"


def _base(key: str) -> str:
    return key.split("{", 1)[0]


def incr(name: str, n: int = 1, **labels) -> int:
    """Add ``n`` to counter ``name`` (created at 0); returns the new value."""
    key = labeled(name, **labels)
    with _lock:
        _counts[key] = _counts.get(key, 0) + int(n)
        return _counts[key]


def get(name: str, **labels) -> int:
    with _lock:
        return _counts.get(labeled(name, **labels), 0)


def gauge(name: str, value: float, **labels) -> None:
    """Record a last-value measurement (overwrites; e.g. latencies)."""
    with _lock:
        _gauges[labeled(name, **labels)] = float(value)


def get_gauge(name: str, default: float | None = None, **labels):
    with _lock:
        return _gauges.get(labeled(name, **labels), default)


def gauges(prefix: str | None = None) -> dict[str, float]:
    """Copy of gauges, sorted by name; ``prefix`` filters on base name."""
    with _lock:
        return dict(sorted((k, v) for k, v in _gauges.items()
                           if prefix is None or _base(k).startswith(prefix)))


def snapshot(prefix: str | None = None) -> dict[str, int]:
    """Copy of counters, sorted by name (stable for JSON output);
    ``prefix`` filters on base name."""
    with _lock:
        return dict(sorted((k, v) for k, v in _counts.items()
                           if prefix is None or _base(k).startswith(prefix)))


def reset(prefix: str | None = None) -> None:
    """Zero counters and gauges (tests; bench run isolation).  With
    ``prefix``, only series whose BASE name starts with it are cleared —
    scoped test isolation instead of process-wide erasure."""
    with _lock:
        if prefix is None:
            _counts.clear()
            _gauges.clear()
            return
        for d in (_counts, _gauges):
            for k in [k for k in d if _base(k).startswith(prefix)]:
                del d[k]
