"""Checkpoint lineage: hash-chained generations for standing models.

A PTA dataset *accrues* — new TOAs arrive per pulsar for years — so a
long-lived analysis is a chain of checkpoint GENERATIONS, each forked
from the verified checkpoint of its parent when the dataset grew.
This module makes that chain a first-class, verifiable object:

- every forked manifest carries a ``lineage`` section::

      {"generation": 2,                  # 0 = root (no parent)
       "parent_dir": ".../g00012",      # the parent checkpoint dir
       "parent_manifest_sha256": "…",   # sha256 of the parent's
                                        #   manifest.json AT FORK TIME
       "dataset_sha256": "…",           # content digest of the grown
                                        #   dataset this fork serves
       "bucket": [2, 48, 24, 3],        # padded shape of the child
       "retained_rows": 128}            # rows copied from the parent

  The parent-manifest hash makes the ancestry a hash chain: a child
  vouches for the exact parent state it was forked from, so a swapped,
  rolled-back-and-diverged, or bit-rotted ancestor is detectable by
  walking the chain — same trick as the journal's checksum sidecar,
  applied across directories.

- :func:`fork_generation` creates a child generation ATOMICALLY: the
  parent is verified first (``integrity.verify`` + ``.bak`` rollback),
  the checkpoint set is staged into ``<child>.fork.tmp`` (optionally
  re-padded for a bigger bucket), the child manifest — inheriting
  every non-file section of the parent's (``layout``/``shard_map``/
  ``serve``) with the lineage overlaid — is written last, and the
  staging dir is promoted with one directory rename.  A kill at ANY
  point leaves either no child (stage dirs are ignorable garbage) or
  a fully verified child — never a half-copied directory that a
  resume path could trust.

- :func:`resolve_verified` walks the chain from the newest generation
  toward the root and returns the NEWEST generation that verifies —
  both its files (against its manifest) and its linkage (its recorded
  parent hash against the parent's actual manifest, ``.bak``
  accepted).  A torn or corrupted generation therefore degrades to
  its newest verified ancestor instead of failing the job; when no
  generation verifies, the typed :class:`LineageError` carries the
  per-generation report.

Verification attempts ``integrity.rollback`` once per generation
before giving up on it, so a torn current set with a good ``.bak``
self-heals exactly like a plain resume would.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from pathlib import Path

from . import faults, telemetry
from .integrity import (CheckpointError, MANIFEST, MANIFEST_BAK,
                        check_not_quarantined, read_manifest, rollback,
                        verify, write_manifest)

#: checkpoint-set members a fork carries over (``.bak`` generations and
#: ``metrics.jsonl`` stay with the parent — the child starts fresh)
FORK_FILES = ("chain.npy", "bchain.npy", "adapt.npz",
              "pars_chain.txt", "pars_bchain.txt")
#: manifest keys owned by :func:`integrity.write_manifest` itself —
#: everything else is an inheritable extra section
_MANIFEST_OWN = ("schema", "rows", "written_at", "files")


class LineageError(CheckpointError):
    """No generation in a checkpoint lineage could be verified.

    ``report`` holds the walk: one ``{"dir", "generation", "ok",
    "why"}`` record per generation visited, newest first.
    """

    def __init__(self, msg, report=None):
        super().__init__(msg)
        self.report = list(report or [])


def lineage_of(outdir) -> dict | None:
    """The manifest's ``lineage`` section, or None (root / unreadable)."""
    man = read_manifest(outdir)
    if not isinstance(man, dict) or man.get("corrupt"):
        return None
    lin = man.get("lineage")
    return dict(lin) if isinstance(lin, dict) else None


def generation_of(outdir) -> int:
    """The directory's generation counter (0 for a root checkpoint)."""
    lin = lineage_of(outdir)
    return int(lin.get("generation", 0)) if lin else 0


def _manifest_hashes(outdir) -> set:
    """sha256 of the directory's manifest.json and manifest.bak.json
    bytes — linkage accepts either, because a legitimate rollback
    swaps the primary for the ``.bak`` generation."""
    out = set()
    for name in (MANIFEST, MANIFEST_BAK):
        p = Path(outdir) / name
        if p.exists():
            out.add(hashlib.sha256(p.read_bytes()).hexdigest())
    return out


def _linkage_ok(outdir, lin) -> tuple:
    """(ok, why) for one generation's parent linkage."""
    parent = lin.get("parent_dir")
    if not parent:
        return True, None
    recorded = lin.get("parent_manifest_sha256")
    if not recorded:
        return False, "lineage records a parent but no parent hash"
    if not Path(parent).exists():
        # a pruned ancestor is not corruption: the chain simply ends
        # here and this generation stands on its own verification
        return True, None
    if recorded not in _manifest_hashes(parent):
        return False, (f"lineage hash chain broken: recorded parent "
                       f"manifest sha256 {recorded[:12]}… matches "
                       f"neither {parent}/manifest.json nor its .bak")
    return True, None


def verify_generation(outdir) -> dict:
    """Verify ONE generation: files against its manifest (with one
    ``.bak`` rollback attempt) AND its lineage linkage.  Returns
    ``{"ok", "why", "rows", "generation"}``."""
    outdir = Path(outdir)
    rep = verify(outdir)
    if not rep["ok"]:
        if not rollback(outdir):
            return {"ok": False, "rows": 0,
                    "generation": generation_of(outdir),
                    "why": f"checkpoint files fail verification "
                           f"({', '.join(rep['bad'])}) and no verified "
                           ".bak exists"}
        rep = verify(outdir)
        if not rep["ok"]:
            return {"ok": False, "rows": 0,
                    "generation": generation_of(outdir),
                    "why": "checkpoint fails verification even after "
                           ".bak rollback"}
    lin = lineage_of(outdir)
    if lin is not None:
        ok, why = _linkage_ok(outdir, lin)
        if not ok and rollback(outdir):
            # the primary manifest may carry a damaged lineage section
            # while the .bak generation is intact — one more chance
            lin = lineage_of(outdir)
            ok, why = _linkage_ok(outdir, lin or {})
        if not ok:
            return {"ok": False, "rows": int(rep["rows"]),
                    "generation": generation_of(outdir), "why": why}
    return {"ok": True, "rows": int(rep["rows"]),
            "generation": generation_of(outdir), "why": None}


def walk(outdir) -> list:
    """The ancestry from ``outdir`` (newest first) to the root: one
    ``{"dir", "generation", "lineage"}`` record per generation.  Stops
    at a missing parent, an unreadable manifest, or a cycle."""
    out, seen = [], set()
    cur = Path(outdir)
    while cur is not None and str(cur) not in seen:
        seen.add(str(cur))
        lin = lineage_of(cur)
        out.append({"dir": str(cur),
                    "generation": int(lin.get("generation", 0))
                    if lin else 0,
                    "lineage": lin})
        parent = (lin or {}).get("parent_dir")
        cur = Path(parent) if parent and Path(parent).exists() else None
    return out


def resolve_verified(outdir) -> tuple:
    """The newest verified generation at or above ``outdir``.

    Walks the lineage chain from ``outdir`` toward the root, verifying
    each generation (files + linkage, with ``.bak`` rollback); returns
    ``(dir, report)`` for the first that verifies — the degrade-to-
    ancestor contract.  Raises :class:`LineageError` (carrying the
    typed per-generation report) when no generation verifies or the
    chain cannot be walked further.
    """
    report, seen = [], set()
    cur = Path(outdir)
    while cur is not None and str(cur) not in seen:
        seen.add(str(cur))
        rep = verify_generation(cur)
        report.append({"dir": str(cur),
                       "generation": int(rep["generation"]),
                       "ok": bool(rep["ok"]), "why": rep["why"]})
        if rep["ok"]:
            if str(cur) != str(outdir):
                telemetry.incr("lineage_degrades")
            return cur, report
        lin = lineage_of(cur)
        parent = (lin or {}).get("parent_dir")
        cur = Path(parent) if parent else None
    detail = "; ".join(f"{r['dir']} (gen {r['generation']}): {r['why']}"
                       for r in report)
    raise LineageError(
        f"{outdir}: no generation in the checkpoint lineage verifies "
        f"— {detail or 'no manifest found to walk from'}", report=report)


def _rewrite_adapt(stage, overrides) -> None:
    """Rewrite ``adapt.npz`` in the staging dir with ``overrides``
    merged over its arrays (``iter`` and every other key preserved)."""
    import numpy as np

    p = Path(stage) / "adapt.npz"
    if not p.exists():
        return
    with np.load(p) as z:
        d = {k: z[k] for k in z.files}
    d.update(overrides)
    tmp = Path(stage) / "adapt.npz.tmp.npz"
    np.savez(tmp, **d)
    os.replace(tmp, p)


def fork_generation(parent_dir, child_dir, *, dataset_sha256=None,
                    bucket=None, serve_extra=None, transform=None,
                    adapt_overrides=None) -> dict:
    """Fork a verified parent checkpoint into a child generation.

    Stages the parent's checkpoint set into ``<child>.fork.tmp``,
    applies ``transform(stage_dir, parent_manifest)`` (the cross-bucket
    re-pad hook; the ``migrate.mid_repad`` chaos seam fires right after
    it), writes the child manifest — the parent's non-file sections
    inherited, ``serve_extra`` overlaid, the ``lineage`` section
    appended — and promotes the stage with one atomic directory rename.
    ``adapt_overrides`` (e.g. the child's generation counter) rewrites
    ``adapt.npz`` in the stage.  Idempotent: an existing child whose
    lineage already points at this parent's manifest hash is returned
    as-is, so a replayed or restarted migration never re-forks.

    A parent that fails verification raises through
    :func:`resolve_verified` semantics at the CALLER's discretion —
    this function verifies only the immediate parent (with one
    ``.bak`` rollback attempt) and refuses a quarantine-marked parent
    (forking one would replay a poisoned trajectory under a new name).
    """
    parent_dir, child_dir = Path(parent_dir), Path(child_dir)
    rep = verify(parent_dir)
    if not rep["ok"]:
        if not rollback(parent_dir):
            raise LineageError(
                f"{parent_dir}: parent checkpoint fails verification "
                f"({', '.join(rep['bad'])}) and has no verified .bak — "
                "cannot fork a generation from unverifiable state")
        rep = verify(parent_dir)
        if not rep["ok"]:
            raise LineageError(
                f"{parent_dir}: parent checkpoint fails verification "
                "even after .bak rollback — cannot fork")
    parent_man = read_manifest(parent_dir)
    check_not_quarantined(parent_dir, manifest=parent_man)
    parent_hash = hashlib.sha256(
        (parent_dir / MANIFEST).read_bytes()).hexdigest()
    parent_lin = parent_man.get("lineage") or {}
    generation = int(parent_lin.get("generation", 0)) + 1
    rows = int(parent_man.get("rows", 0))

    # idempotency: a child already forked from THIS parent state stands
    child_man = read_manifest(child_dir)
    if isinstance(child_man, dict) and not child_man.get("corrupt"):
        lin = child_man.get("lineage") or {}
        if lin.get("parent_manifest_sha256") == parent_hash \
                and verify(child_dir, child_man)["ok"]:
            return child_man

    stage = child_dir.parent / (child_dir.name + ".fork.tmp")
    if stage.exists():
        shutil.rmtree(stage)
    stage.mkdir(parents=True)
    for nm in FORK_FILES:
        src = parent_dir / nm
        if src.exists():
            shutil.copy2(src, stage / nm)
    if adapt_overrides:
        _rewrite_adapt(stage, adapt_overrides)
    if transform is not None:
        transform(stage, parent_man)
    # chaos seam: a kill here leaves only the stage dir — ignorable
    # garbage, the child does not exist yet, recovery is the parent
    faults.fire("migrate.mid_repad", row=rows, outdir=stage)

    extras = {k: v for k, v in parent_man.items()
              if k not in _MANIFEST_OWN}
    if serve_extra:
        extras.update(serve_extra)
    extras["lineage"] = {
        "generation": generation,
        "parent_dir": str(parent_dir),
        "parent_manifest_sha256": parent_hash,
        "dataset_sha256": dataset_sha256,
        "bucket": (list(bucket) if bucket is not None else None),
        "retained_rows": rows,
    }
    man = write_manifest(stage, rows=rows, extra=extras)
    if child_dir.exists():
        shutil.rmtree(child_dir)
    os.replace(stage, child_dir)
    telemetry.incr("lineage_forks")
    return man
