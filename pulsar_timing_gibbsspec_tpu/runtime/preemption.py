"""Preemption-safe drain: SIGTERM/SIGINT → deadline-bounded checkpoint.

Shared accelerator pools kill long runs far more often than math does:
the scheduler sends SIGTERM (or a maintenance notice) and gives the
process a bounded grace window.  This module turns that window into a
clean exit:

1. :func:`install` registers signal handlers (and :func:`request_drain`
   is the pluggable hook for maintenance-event watchers — a cloud
   metadata poller thread calls the same function) that set a
   process-wide drain flag with a deadline.
2. The jax driver's chunk loop stops dispatching new chunks the moment
   the flag is up, and finishes or abandons the in-flight chunk
   depending on the time left (``should_abandon``).
3. The facade's sample loop breaks out, its existing try/finally flush
   persists every verified row, the checkpoint is verified (rolled back
   to ``.bak`` if a concurrent kill tore it), and :class:`Preempted` is
   raised.
4. ``run_supervised`` classifies :class:`Preempted` as the distinct
   ``preempted`` status — resumable by construction, never a failure,
   never retried in-process (the host is going away).

Because chunk/checkpoint grids cannot move the sampled process (per-
sweep keys are pure in the absolute iteration index), the drained
checkpoint resumes bit-identically on the next incarnation — including
on a different device count via ``integrity.reshard_restore``.

All state is process-wide (one drain request serves every facade in the
process) and monotonic-clock based; :func:`reset` restores a clean
slate for tests.
"""

from __future__ import annotations

import signal
import threading
import time

from . import telemetry

#: conventional exit code for a drained (resumable) run — EX_TEMPFAIL,
#: the "transient failure, retry me" code batch schedulers requeue on
EXIT_PREEMPTED = 75

#: default grace window when the requester does not say (seconds);
#: matches the shorter end of common preemption notices
DEFAULT_DEADLINE_S = 30.0


class Preempted(RuntimeError):
    """The run drained to a verified checkpoint after a preemption
    request — a resumable outcome, not a failure.  ``rows`` is the
    recorded-row count persisted; ``verified`` whether the final
    checkpoint set passed integrity verification (after rollback, if
    one was needed)."""

    def __init__(self, msg, rows=0, verified=True, rolled_back=False):
        super().__init__(msg)
        self.rows = int(rows)
        self.verified = bool(verified)
        self.rolled_back = bool(rolled_back)


# RLock, not Lock: request_drain runs in signal context on the main
# thread, which can interrupt the main thread *inside* deadline_remaining
# / drain_info's own ``with _lock`` — a non-reentrant lock would
# self-deadlock the process right when the scheduler wants it gone
_lock = threading.RLock()
_event = threading.Event()
_state = {"reason": None, "requested_at": None, "deadline_s": None}
_prev_handlers: dict[int, object] = {}


def request_drain(reason="maintenance", deadline_s=None) -> None:
    """Ask every running sampler in this process to drain.

    This IS the pluggable maintenance-event hook: signal handlers call
    it, and so can any watcher thread (cloud preemption notice, pool
    rebalance, operator RPC).  Idempotent — the first request wins; a
    later one cannot extend the deadline (the grace window is the
    scheduler's, not ours)."""
    with _lock:
        if _event.is_set():
            return
        _state["reason"] = str(reason)
        _state["requested_at"] = time.monotonic()
        _state["deadline_s"] = (DEFAULT_DEADLINE_S if deadline_s is None
                                else float(deadline_s))
        _event.set()
    telemetry.incr("preempt_requests")


def drain_requested() -> bool:
    """Cheap flag check for hot loops (no lock on the fast path)."""
    return _event.is_set()


def deadline_remaining() -> float:
    """Seconds left in the grace window (+inf when no drain is
    requested; can go negative once the window is blown)."""
    with _lock:
        if not _event.is_set():
            return float("inf")
        return (_state["requested_at"] + _state["deadline_s"]
                - time.monotonic())


def should_abandon(est_s=0.0) -> bool:
    """True when finishing ``est_s`` more seconds of work would blow the
    drain deadline — the in-flight chunk is then dropped (its sweeps are
    replayed bit-exactly on resume) in favor of checkpointing what is
    already verified."""
    return _event.is_set() and deadline_remaining() < float(est_s)


def drain_info() -> dict:
    """Snapshot for logging/metrics (reason, age, remaining)."""
    with _lock:
        if not _event.is_set():
            return {"requested": False}
        now = time.monotonic()
        return {"requested": True, "reason": _state["reason"],
                "age_s": round(now - _state["requested_at"], 3),
                "deadline_s": _state["deadline_s"],
                "remaining_s": round(_state["requested_at"]
                                     + _state["deadline_s"] - now, 3)}


def mark_drained() -> float:
    """Record a completed drain: gauges the request-to-checkpoint
    latency (ms) and counts the drain.  Returns the latency in
    seconds (0.0 when no request was pending — direct Preempted
    construction in tests)."""
    with _lock:
        t0 = _state["requested_at"]
    lat = 0.0 if t0 is None else time.monotonic() - t0
    telemetry.gauge("drain_latency_ms", lat * 1000.0)
    telemetry.incr("preempt_drains")
    return lat


def install(signals=(signal.SIGTERM, signal.SIGINT),
            deadline_s=DEFAULT_DEADLINE_S) -> None:
    """Register drain-on-signal handlers (main thread only — a CPython
    constraint on ``signal.signal``).  Re-entrant delivery escalates:
    the SECOND signal restores the previous handler and re-raises, so
    an operator's double Ctrl-C still kills a wedged drain."""
    def _handler(signum, frame):
        if _event.is_set():
            # second signal: give up on draining, restore + re-raise
            prev = _prev_handlers.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            raise KeyboardInterrupt(
                f"second signal {signum} during drain")
        request_drain(reason=signal.Signals(signum).name,
                      deadline_s=deadline_s)

    for s in signals:
        _prev_handlers[s] = signal.getsignal(s)
        signal.signal(s, _handler)


def uninstall() -> None:
    """Restore the handlers :func:`install` replaced."""
    for s, prev in _prev_handlers.items():
        signal.signal(s, prev)
    _prev_handlers.clear()


def reset() -> None:
    """Clear the drain flag and deadline (tests; between supervised
    incarnations in one process)."""
    with _lock:
        _event.clear()
        _state.update(reason=None, requested_at=None, deadline_s=None)
