"""Deterministic fault injection for the resilience chaos suite.

Production code calls the seam hooks (:func:`fire`, :func:`mutate_rows`)
at well-defined points; with nothing armed they are near-free no-ops
(one list check).  Tests arm faults with :func:`inject` (or the
:func:`injected` context manager) and the hooks then raise or corrupt
deterministically at the requested recorded row, so every failure mode
in ``docs/RESILIENCE.md`` is reproducible bit-for-bit.

Seam points (``fire``):

- ``"chainstore.between_replaces"`` — inside ``ChainStore.save``,
  after ``chain.npy`` was replaced but before ``bchain.npy`` (the torn
  checkpoint window); ``row`` is the checkpoint row count.
- ``"chainstore.post_save"`` — after the full checkpoint set including
  ``manifest.json`` hit disk (file-corruption kinds damage files here).
- ``"sample.loop"`` — in the facade's sweep loop, after the newly
  recorded rows passed the sentinels; ``row`` is the rows done so far.
- ``"dispatch.chunk"`` — inside the jax driver's watchdog-guarded chunk
  dispatch, before the compiled chunk runs; ``row`` is the absolute
  iteration index of the chunk start.
- ``"serve.chunk"`` — in the multi-tenant service's scheduler loop,
  between multiplexed chunks (after the previous chunk's rows were
  checkpoint-eligible, before the next dispatch); ``row`` is the
  service's global chunk counter.  Crash/stall/sigterm kinds work here
  like at any seam; the service additionally polls
  :func:`tenant_evict_request` at the same point.

Fault kinds:

- ``"crash"``          raise :class:`InjectedCrash` at a fire point
  (simulated preemption / SIGKILL — the caller gets no chance to clean
  up past that statement).
- ``"xla_error"``      raise :class:`XlaRuntimeError` at a fire point
  (stand-in for a device/runtime failure; the supervisor classifies it
  by type name, same as the real ``jaxlib`` exception).
- ``"nan_rows"``       overwrite recorded chain/bchain rows with NaN via
  ``mutate_rows`` (simulated diverged chunk output).
- ``"truncate_file"``  cut the target file to half its size at a fire
  point with ``outdir`` (torn write / disk-full artifact).
- ``"corrupt_file"``   overwrite a few bytes mid-file (bit rot).
- ``"sigterm_at_seam"`` request a preemption drain at the fire point —
  deterministic, seam-precise stand-in for SIGTERM delivery (the real
  handler calls the same ``preemption.request_drain``); ``seconds``
  carries the drain deadline (default when 0).
- ``"stall"``          sleep ``seconds`` at the fire point (a hung XLA
  dispatch, as seen from the host) — armed at ``"dispatch.chunk"`` it
  exercises the watchdog's escalate/abort path.
- ``"device_count_change_on_resume"`` make ``device_count_override``
  return ``devices`` — simulates the pool handing the next incarnation
  a different device count than the checkpoint was written under
  (``integrity.reshard_restore`` consults it).
- ``"tenant_evict"`` make :func:`tenant_evict_request` return truthy at
  the ``"serve.chunk"`` seam — forces the serving scheduler to evict a
  resident tenant back to the queue (checkpoint + requeue), the churn
  half of the kill-mid-multiplex chaos drill.  With ``tenant=<id>`` the
  fault names its victim and ``at_row`` counts THAT JOB's resident
  chunks (not the global chunk counter), so a campaign schedule can
  evict "tenant 2 after its 3rd chunk" deterministically regardless of
  when admission placed it.
- ``"poison_rows"`` NaN-poison one tenant's rows of a multiplexed chunk
  via :func:`poison_tenant_rows` (simulated single-tenant divergence —
  the blast-radius drill's trigger).  ``tenant`` selects the victim
  row; ``at_row`` counts the victim's resident chunks.
- ``"device_loss"`` raise :class:`DeviceLost` at a fire point, carrying
  ``devices`` = the surviving device count — the serving tier's
  evacuation drill (drain residents, rebuild on the surviving submesh,
  re-admit).  With ``slice=<id>`` the loss is attributed to one
  placement slice: only that fault domain evacuates and re-places
  (capped re-place budget), co-resident slices keep sampling bitwise.

Migration seams (the standing-model append path — ``serve/gateway.py``
``/v1/append`` and ``SamplerService.append_job`` →
``runtime/lineage.py``):

- ``"migrate.pre_journal"`` — in the gateway's append handler, after
  the grown model was validated and routed but BEFORE the forking
  intent is journaled; a kill here leaves nothing durable (recovery =
  parent untouched, the client retries).
- ``"migrate.post_journal"`` — after the ``"forking"`` journal entry
  is durable but before any checkpoint work; recovery = restart or
  replay re-materializes the child from the journal.
- ``"migrate.mid_repad"`` — inside ``lineage.fork_generation``, after
  the checkpoint set was staged (and re-padded, for a cross-bucket
  migration) into ``<child>.fork.tmp`` but before the child manifest /
  atomic promote; a kill here leaves only ignorable stage garbage.
- ``"migrate.pre_readmit"`` — after the child generation's directory
  was atomically promoted and verified but before the child job is
  submitted to the scheduler; recovery = the fork is idempotent, a
  re-materialization finds the child on disk and just readmits it.

Migration fault kinds:

- ``"kill_mid_migration"`` raise :class:`InjectedCrash` at a migration
  seam (same recovery contract as ``"crash"``, named separately so a
  campaign schedule reads as intent).
- ``"corrupt_lineage"`` mangle the ``lineage.parent_manifest_sha256``
  recorded in the target directory's ``manifest.json`` AND
  ``manifest.bak.json`` at a fire point with ``outdir`` — the broken
  hash chain the rollback-to-ancestor drill detects.
- ``"append_during_drain"`` make :func:`append_during_drain` return
  truthy at the gateway's ``"gateway.append"`` poll — simulates the
  drain beginning before the append was journaled; the gateway must
  refuse typed (DRAINING), binding nothing.

Transport seams (the gateway in ``serve/gateway.py``):

- ``"gateway.step"`` — in the gateway scheduler thread, before each
  supervised service round; ``row`` is the gateway step counter.  A
  ``"gateway_kill"`` armed here simulates SIGKILL on the gateway
  process mid-stream: the scheduler dies with NO goodbye (no final
  journal write, no drain) — the restart drill then asserts the
  journal and checkpoints already on disk are sufficient.
- ``"wire.request"`` / ``"wire.submit"`` / ``"wire.stream"`` — consumed
  via :func:`transport_fault` by the gateway's request, submission and
  stream paths; ``row`` is the request counter (request/submit) or the
  stream cursor (stream).

Transport fault kinds (consumed by :func:`transport_fault`; these
return handles instead of raising — the TRANSPORT misbehaves, the
gateway must stay correct):

- ``"conn_drop"``   the client connection vanishes: at a request seam
  the computed (and, for submissions, already-journaled) response is
  never delivered — the lost-ACK window idempotent submission exists
  for; at a stream seam the stream aborts mid-delivery and the client
  must reattach with its cursor.
- ``"dup_submit"``  the client retries a submission it already sent
  (timeout/lost ACK): the gateway processes the identical submission
  twice and must resolve both to ONE job handle via the dedupe journal.
- ``"slow_client"`` the stream consumer stalls ``seconds`` per event:
  rows keep landing while the stream lags — past the gateway's
  ``shed_lag`` bound the stream must be SHED (never block sampling).
- ``"gateway_kill"`` raise :class:`InjectedCrash` at ``"gateway.step"``
  (see above).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass

import numpy as np


class InjectedCrash(RuntimeError):
    """Simulated hard kill (e.g. preemption between checkpoint replaces)."""


class XlaRuntimeError(RuntimeError):
    """Stand-in for ``jaxlib``'s XlaRuntimeError.

    The supervisor's :func:`~..runtime.supervisor.classify_failure`
    matches device failures by type NAME, so the injected and the real
    exception take exactly the same recovery path without this module
    importing jaxlib.
    """


class DeviceLost(RuntimeError):
    """A device dropped out of the mesh mid-run.

    Unlike a transient :class:`XlaRuntimeError`, the lost capacity does
    not come back on retry: the run must EVACUATE — drain state through
    verified checkpoints, rebuild programs on the surviving submesh
    (``devices``, or None when unknown) and resume there.  The serving
    tier's :meth:`~..serve.service.SamplerService.evacuate` and the
    single-tenant ``integrity.reshard_restore`` are the two consumers.

    On a multi-slice service (``placement=``), ``slice_id`` attributes
    the loss to ONE placement slice: the supervised path then evacuates
    and re-places only that fault domain (capped by its re-place
    budget) while every other slice keeps sampling bitwise.  Without
    attribution (``slice_id=None``) the whole service evacuates, as
    before.
    """

    def __init__(self, msg, devices=None, slice_id=None):
        super().__init__(msg)
        self.devices = devices
        self.slice_id = slice_id


@dataclass
class _Fault:
    kind: str
    point: str | None = None    # required seam, None = any fire point
    at_row: int | None = None   # trigger once row >= at_row
    times: int = 1              # max firings before self-disarm
    backend: str | None = None  # only fire for this backend name
    path: str | None = None     # target file for file-damage kinds
    seconds: float = 0.0        # stall sleep / drain deadline
    devices: int | None = None  # device_count override / survivors
    tenant: int | None = None   # victim tenant for serve-tier kinds
    slice: int | None = None    # victim placement slice (device_loss)
    fired: int = 0


_armed: list[_Fault] = []
_lock = threading.Lock()


def inject(kind, point=None, at_row=None, times=1, backend=None, path=None,
           seconds=0.0, devices=None, tenant=None, slice=None):
    """Arm a fault; returns the handle (remove with :func:`clear`)."""
    f = _Fault(kind=kind, point=point, at_row=at_row, times=times,
               backend=backend, path=path, seconds=seconds, devices=devices,
               tenant=tenant, slice=slice)
    with _lock:
        _armed.append(f)
    return f


def clear() -> None:
    """Disarm every fault (tests call this in teardown)."""
    with _lock:
        _armed.clear()


@contextlib.contextmanager
def injected(kind, **kw):
    """``with injected("crash", point=..., at_row=...):`` scoped arming."""
    f = inject(kind, **kw)
    try:
        yield f
    finally:
        with _lock:
            if f in _armed:
                _armed.remove(f)


def _take(point, row, backend, kinds):
    """Armed faults matching (point, row, backend), consuming one firing
    each; row-triggered faults fire at the first seam whose row reaches
    ``at_row``."""
    hits = []
    with _lock:
        for f in _armed:
            if f.kind not in kinds or f.fired >= f.times:
                continue
            if f.point is not None and f.point != point:
                continue
            if f.at_row is not None and (row is None or row < f.at_row):
                continue
            if (f.backend is not None and backend is not None
                    and f.backend != backend):
                continue
            f.fired += 1
            hits.append(f)
    return hits


def fire(point, row=None, backend=None, outdir=None):
    """Seam hook: raise / damage files per the armed faults.

    A no-op (single truthiness check) when nothing is armed, so the hot
    loop pays nothing for the seam in production.
    """
    if not _armed:
        return
    for f in _take(point, row, backend, ("truncate_file", "corrupt_file")):
        if outdir is not None:
            _damage(os.path.join(str(outdir), f.path or "chain.npy"), f.kind)
    for f in _take(point, row, backend, ("corrupt_lineage",)):
        if outdir is not None:
            _corrupt_lineage(outdir)
    for f in _take(point, row, backend, ("stall",)):
        time.sleep(f.seconds)
    for f in _take(point, row, backend, ("sigterm_at_seam",)):
        from . import preemption

        preemption.request_drain(
            reason=f"sigterm_at_seam:{point}",
            deadline_s=f.seconds or None)
    for f in _take(point, row, backend, ("crash", "xla_error",
                                         "device_loss", "gateway_kill",
                                         "kill_mid_migration")):
        if f.kind in ("crash", "gateway_kill", "kill_mid_migration"):
            raise InjectedCrash(
                f"injected {f.kind} at {point} (row {row})")
        if f.kind == "device_loss":
            where = "" if f.slice is None else f" on slice {f.slice}"
            raise DeviceLost(
                f"injected device loss{where} at {point} (row {row}): "
                f"{f.devices if f.devices is not None else '?'} "
                "device(s) survive", devices=f.devices,
                slice_id=f.slice)
        raise XlaRuntimeError(
            f"INTERNAL: injected device failure at {point} (row {row})")


def transport_fault(point, row=None):
    """Consume armed transport faults at a wire seam (counting a firing
    each) and return the fired handles — ``conn_drop`` / ``dup_submit``
    / ``slow_client``.  Unlike :func:`fire` this never raises: the
    gateway interprets the handles (drop the response, replay the
    submission, stall the stream consumer) because the FAULT is the
    transport's, and the code under test is the gateway's recovery."""
    if not _armed:
        return []
    return _take(point, row, None,
                 ("conn_drop", "dup_submit", "slow_client"))


def device_count_override(default=None):
    """Consume an armed ``device_count_change_on_resume`` fault.

    Returns the fault's ``devices`` (counting a firing), or ``default``
    when none is armed — resume paths call this to learn the device
    count the "pool" hands the next incarnation."""
    if not _armed:
        return default
    hits = _take("resume.device_count", None, None,
                 ("device_count_change_on_resume",))
    return hits[-1].devices if hits else default


def tenant_evict_request(row=None, job_rows=None):
    """Consume armed ``tenant_evict`` faults at the ``serve.chunk``
    seam (counting a firing each).

    ``row`` is the service's global chunk counter; ``job_rows`` maps
    resident ``tenant_id -> chunks that tenant has been resident``
    (the service passes it so ``at_row`` on a tenant-targeted fault
    counts the VICTIM's chunks, not everyone's — a global counter
    cannot say "evict tenant 2 after its 3rd chunk" when admission
    order varies).  Returns the set of victim tenant_ids, or ``True``
    for an untargeted request (evict any one resident — historical
    behavior), or ``False`` when nothing fired.
    """
    if not _armed:
        return False
    victims = set()
    untargeted = False
    with _lock:
        for f in _armed:
            if f.kind != "tenant_evict" or f.fired >= f.times:
                continue
            if f.point is not None and f.point != "serve.chunk":
                continue
            if f.tenant is not None:
                held = None if job_rows is None \
                    else job_rows.get(int(f.tenant))
                if held is None or (f.at_row is not None
                                    and held < f.at_row):
                    continue
                f.fired += 1
                victims.add(int(f.tenant))
            else:
                if f.at_row is not None and (row is None
                                             or row < f.at_row):
                    continue
                f.fired += 1
                untargeted = True
    if victims:
        return victims
    return untargeted


def poison_tenant_rows(np_xs, np_bs, tenant_slots, job_rows):
    """NaN-poison ONE tenant's rows of a multiplexed chunk for armed
    ``poison_rows`` faults (the blast-radius drill: a single tenant's
    chunk output diverges while its co-residents' rows stay exact).

    ``np_xs`` (chunk, T, nx) / ``np_bs`` (chunk, T, ...) are the host
    copies of the recorded stacks; ``tenant_slots`` maps tenant_id ->
    slot index; ``job_rows`` maps tenant_id -> chunks resident (the
    per-job ``at_row`` clock, same as :func:`tenant_evict_request`).
    Returns ``(np_xs, np_bs, poisoned_slots)`` — the arrays are copied
    first when read-only (``np.asarray`` of a device array is an
    immutable view), so callers must rebind them.
    """
    if not _armed:
        return np_xs, np_bs, set()
    poisoned = set()
    with _lock:
        for f in _armed:
            if f.kind != "poison_rows" or f.fired >= f.times:
                continue
            if f.tenant is None:
                continue
            slot = tenant_slots.get(int(f.tenant))
            if slot is None:
                continue
            held = job_rows.get(int(f.tenant), 0)
            if f.at_row is not None and held < f.at_row:
                continue
            f.fired += 1
            poisoned.add(int(slot))
    if poisoned:
        if not np_xs.flags.writeable:
            np_xs = np_xs.copy()
        if not np_bs.flags.writeable:
            np_bs = np_bs.copy()
        for slot in poisoned:
            np_xs[:, slot] = np.nan
            np_bs[:, slot] = np.nan
    return np_xs, np_bs, poisoned


def append_during_drain() -> bool:
    """Consume an armed ``append_during_drain`` fault at the gateway's
    append poll (counting a firing).  True = pretend the drain began
    before this append could be journaled; the gateway refuses typed."""
    if not _armed:
        return False
    return bool(_take("gateway.append", None, None,
                      ("append_during_drain",)))


def _corrupt_lineage(outdir):
    """Mangle the recorded parent-manifest hash in ``manifest.json``
    and ``manifest.bak.json`` — both, so a ``.bak`` rollback cannot
    silently heal the chain and the rollback-to-ancestor path is the
    one exercised."""
    import json

    for name in ("manifest.json", "manifest.bak.json"):
        p = os.path.join(str(outdir), name)
        if not os.path.exists(p):
            continue
        try:
            with open(p) as fh:
                man = json.load(fh)
        except ValueError:
            continue
        lin = man.get("lineage")
        if not isinstance(lin, dict):
            continue
        lin["parent_manifest_sha256"] = "0" * 64
        with open(p, "w") as fh:
            json.dump(man, fh, indent=1, sort_keys=True)


def _damage(path, kind):
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    if kind == "truncate_file":
        with open(path, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
    else:                       # corrupt_file: flip bytes past the header
        with open(path, "r+b") as fh:
            fh.seek(max(size // 2, 0))
            fh.write(b"\xde\xad\xbe\xef")


def mutate_rows(chain, bchain, lo, hi, backend=None):
    """NaN-poison recorded rows in ``[lo, hi)`` for armed ``nan_rows``
    faults (simulates a diverged chunk landing in the host buffers)."""
    if not _armed:
        return
    with _lock:
        hits = [f for f in _armed
                if f.kind == "nan_rows" and f.fired < f.times
                and f.at_row is not None and lo <= f.at_row < hi
                and (f.backend is None or backend is None
                     or f.backend == backend)]
        for f in hits:
            f.fired += 1
    for f in hits:
        chain[f.at_row] = np.nan
        bchain[f.at_row] = np.nan
