"""Resilient sampling runtime: supervised runs, checkpoint integrity,
divergence sentinels, deterministic fault injection.

The sampler facade promises bit-exact resume (sampler/chains.py); this
package defends that promise in production: ``integrity`` makes the
checkpoint set verifiable (manifest + rotating .bak), ``sentinels``
catches diverged/stuck chains before they reach disk, ``supervisor``
retries transient failures with capped backoff and degrades jax ->
numpy after repeated device faults, and ``faults`` injects every one of
those failures deterministically so ``tests/test_chaos.py`` can prove
recovery is bit-identical to an uninterrupted run.  See
docs/RESILIENCE.md.
"""

from . import faults, integrity, sentinels, telemetry
from .integrity import CheckpointError
from .sentinels import ChainDivergence, SentinelMonitor
from .supervisor import (SupervisorReport, backoff_delay, classify_failure,
                         run_supervised)

__all__ = [
    "faults", "integrity", "sentinels", "telemetry",
    "CheckpointError", "ChainDivergence", "SentinelMonitor",
    "SupervisorReport", "backoff_delay", "classify_failure",
    "run_supervised",
]
