"""Resilient sampling runtime: supervised runs, checkpoint integrity,
divergence sentinels, deterministic fault injection.

The sampler facade promises bit-exact resume (sampler/chains.py); this
package defends that promise in production: ``integrity`` makes the
checkpoint set verifiable (manifest + rotating .bak), ``sentinels``
catches diverged/stuck chains before they reach disk, ``supervisor``
retries transient failures with capped backoff and degrades jax ->
numpy after repeated device faults, ``preemption`` turns SIGTERM /
maintenance notices into a deadline-bounded drain to a verified
checkpoint (the distinct resumable ``preempted`` outcome), ``watchdog``
aborts hung chunk dispatches against an EMA deadline (the retryable
``stall`` class), and ``faults`` injects every one of those failures
deterministically so ``tests/test_chaos.py`` can prove recovery is
bit-identical to an uninterrupted run.  See docs/RESILIENCE.md.
"""

from . import faults, integrity, preemption, sentinels, telemetry, watchdog
from .integrity import CheckpointError
from .preemption import EXIT_PREEMPTED, Preempted
from .sentinels import ChainDivergence, SentinelMonitor
from .supervisor import (SupervisorReport, backoff_delay, classify_failure,
                         run_supervised)
from .watchdog import DispatchStall, DispatchWatchdog

__all__ = [
    "faults", "integrity", "preemption", "sentinels", "telemetry",
    "watchdog",
    "CheckpointError", "ChainDivergence", "SentinelMonitor",
    "SupervisorReport", "backoff_delay", "classify_failure",
    "run_supervised",
    "EXIT_PREEMPTED", "Preempted", "DispatchStall", "DispatchWatchdog",
]
