"""Checkpoint integrity: manifest sidecar, verification, rotating .bak.

``ChainStore.save`` is atomic per file (tmp + ``os.replace``) but not
across files: a kill between the two replaces leaves a new ``chain.npy``
next to an old ``bchain.npy`` — a torn checkpoint that the seed code
silently truncated to the common prefix.  This module makes the
checkpoint SET verifiable:

- ``manifest.json`` — written (atomically, last) by every save: schema
  version, row count, and per-file sha256/size/shape/dtype for
  ``chain.npy``/``bchain.npy``/``adapt.npz``.  Any file that does not
  match its manifest entry marks the whole set torn/corrupt.
- ``*.bak`` + ``manifest.bak.json`` — one rotating generation of the
  previous VERIFIED checkpoint, refreshed at the start of each save, so
  a torn current set rolls back to the last good one (bounded loss:
  one checkpoint interval, replayed bit-exactly on resume).

``load_resume`` (sampler/chains.py) verifies before trusting anything
and calls :func:`rollback` on mismatch; :class:`CheckpointError` is
raised only when neither the primary nor the backup set verifies.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from pathlib import Path

import numpy as np

from . import telemetry

SCHEMA_VERSION = 1
MANIFEST = "manifest.json"
MANIFEST_BAK = "manifest.bak.json"
#: checkpoint-set members covered by the manifest (when present on disk)
CHECKPOINT_FILES = ("chain.npy", "bchain.npy", "adapt.npz")


class CheckpointError(RuntimeError):
    """A checkpoint failed verification and could not be recovered."""


class LayoutMismatch(CheckpointError):
    """The checkpoint's recorded pulsar order disagrees with the PTA
    supplied for resume.

    The logical pulsar order IS the chain identity — per-pulsar key
    folds and padded slot assignment are positional — so resuming a
    checkpoint against a reordered or substituted pulsar list would
    silently attribute one pulsar's state to another.  Names the FIRST
    mismatched position (``index``/``expected``/``got``)."""

    def __init__(self, outdir, index, expected, got):
        self.index = int(index)
        self.expected = expected
        self.got = got
        super().__init__(
            f"{outdir}: pulsar order mismatch at index {index}: the "
            f"checkpoint layout records {expected!r} but this PTA "
            f"supplies {got!r} — the logical pulsar order IS the chain "
            "identity (per-pulsar key folds, padded slot assignment) "
            "and cannot change on resume; reorder the PTA to the "
            "recorded layout or start a fresh run")


def check_layout_pulsars(outdir, want, got):
    """Raise :class:`LayoutMismatch` naming the first position where
    the checkpoint's recorded pulsar list ``want`` disagrees with the
    supplied PTA's ``got``.  A checkpoint with no recorded list (``want``
    empty / pre-layout) is not checkable and passes."""
    want = [str(p) for p in (want or [])]
    got = [str(p) for p in (got or [])]
    if not want or want == got:
        return
    n = min(len(want), len(got))
    for i in range(n):
        if want[i] != got[i]:
            raise LayoutMismatch(outdir, i, want[i], got[i])
    # equal prefix, unequal length: the boundary is the first mismatch
    raise LayoutMismatch(outdir, n,
                         want[n] if len(want) > n else "<none>",
                         got[n] if len(got) > n else "<none>")


def file_sha256(path, chunk=1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            buf = fh.read(chunk)
            if not buf:
                break
            h.update(buf)
    return h.hexdigest()


def _npy_meta(path):
    """(shape, dtype) of an .npy without loading the data (mmap header
    read); (None, None) when the header itself is unreadable."""
    try:
        arr = np.load(path, mmap_mode="r")
        return list(arr.shape), str(arr.dtype)
    except Exception:
        return None, None


def write_manifest(outdir, rows, extra=None) -> dict:
    """Describe the current checkpoint set in ``manifest.json`` (tmp +
    replace, so the manifest itself can never be half-written)."""
    outdir = Path(outdir)
    files = {}
    for nm in CHECKPOINT_FILES:
        p = outdir / nm
        if not p.exists():
            continue
        ent = {"sha256": file_sha256(p), "bytes": p.stat().st_size}
        if nm.endswith(".npy"):
            shape, dtype = _npy_meta(p)
            if shape is not None:
                ent["shape"], ent["dtype"] = shape, dtype
        files[nm] = ent
    man = {"schema": SCHEMA_VERSION, "rows": int(rows),
           "written_at": round(time.time(), 3), "files": files}
    if extra:
        man.update(extra)
    tmp = outdir / (MANIFEST + ".tmp")
    tmp.write_text(json.dumps(man, indent=1, sort_keys=True))
    os.replace(tmp, outdir / MANIFEST)
    return man


def read_manifest(outdir, name=MANIFEST):
    """Parsed manifest, ``None`` if absent (pre-manifest checkpoint), or
    a sentinel with ``"corrupt": True`` when present but unparseable —
    an unreadable manifest must fail verification, not resume blind."""
    p = Path(outdir) / name
    if not p.exists():
        return None
    try:
        man = json.loads(p.read_text())
    except (ValueError, OSError):
        man = None
    if not isinstance(man, dict) or "files" not in man:
        return {"schema": -1, "rows": 0, "files": {}, "corrupt": True}
    return man


def verify(outdir, manifest=None, suffix="") -> dict:
    """Check every manifest-listed file (``+ suffix``) against its
    recorded size and sha256.  Returns ``{"ok", "bad": [names],
    "rows"}``; size is checked first so the common torn case skips the
    hash."""
    outdir = Path(outdir)
    if manifest is None:
        manifest = read_manifest(outdir)
    if manifest is None:
        return {"ok": False, "bad": [MANIFEST + suffix], "rows": 0}
    if manifest.get("corrupt") or manifest.get("schema") != SCHEMA_VERSION:
        return {"ok": False, "bad": [MANIFEST + suffix], "rows": 0}
    bad = []
    for nm, ent in manifest["files"].items():
        p = outdir / (nm + suffix)
        if not p.exists() or p.stat().st_size != ent["bytes"]:
            bad.append(nm + suffix)
        elif file_sha256(p) != ent["sha256"]:
            bad.append(nm + suffix)
    return {"ok": not bad, "bad": bad,
            "rows": int(manifest.get("rows", 0))}


def read_layout(outdir):
    """The manifest's layout split, or ``None`` for a pre-layout
    checkpoint: ``{"layout": {...}, "shard_map": {...}|None}``.

    ``layout`` is the LOGICAL identity of the sampled process — facade
    class, chain count, pulsar names in logical order, padded pulsar
    width, record thinning, key-folding policy.  ``shard_map`` is the
    physical placement the run happened to use; it is advisory only.
    """
    man = read_manifest(outdir)
    if man is None or man.get("corrupt") or "layout" not in man:
        return None
    return {"layout": man["layout"], "shard_map": man.get("shard_map")}


def reshard_restore(outdir, pta, devices=None, **gibbs_kwargs):
    """Rebuild a sampler facade that resumes ``outdir``'s checkpoint on
    a (possibly different) device count.

    The checkpoint's LOGICAL layout — chains and pulsars in logical
    order, padded pulsar width, per-chain keys folded from the logical
    chain index — pins the sampled process; the shard map does not.  So
    a run checkpointed under 8 devices resumes under 1, 2 or 4 (or back
    to 8) as long as the new count divides the recorded padded width,
    and the per-chain streams are bit-identical: the padded draw shapes
    (part of the PRNG stream identity under threefry counter pairing)
    and the logical fold indices are unchanged, only the physical
    placement of the same arrays moves.

    ``devices`` is an int — the classic 1-d pulsar mesh — or a 2-tuple
    ``(n_chain_devs, n_pulsar_devs)`` for the 2-d chain-sharded mesh:
    the pulsar size must divide the recorded padded width and the
    chain size the recorded chain count, and any 2-d layout resumes
    bitwise per LOGICAL chain from any other (chains are independent
    processes keyed by logical index; placement never touches a
    stream).  ``devices=None`` resumes unsharded (single default
    device); ``1`` / ``(1, 1)`` likewise skip the mesh.  The
    ``device_count_change_on_resume`` fault, when armed, overrides
    ``devices`` — the chaos suite's stand-in for the pool handing the
    next incarnation a different slice.  Returns the facade; call
    ``.sample(x0, outdir=outdir, resume=True, ...)`` on it.
    """
    from . import faults

    info = read_layout(outdir)
    if info is None:
        raise CheckpointError(
            f"{outdir}: checkpoint manifest has no logical-layout "
            "section (written by a pre-elasticity version); resume it "
            "on the original device count instead")
    lay = info["layout"]
    devices = faults.device_count_override(devices)
    want = lay.get("pulsars", [])
    check_layout_pulsars(outdir, want, getattr(pta, "pulsars", []))
    pad = int(lay.get("pad_pulsars", 0)) or None
    if isinstance(devices, (tuple, list)):
        n_chain, n_psr = (int(s) for s in devices)
    else:
        n_chain, n_psr = 1, (int(devices) if devices is not None else 1)
    mesh = None
    if n_chain * n_psr > 1:
        if n_psr > 1 and (pad is None or pad % n_psr):
            raise CheckpointError(
                f"{outdir}: checkpoint's padded pulsar width ({pad}) "
                f"does not divide over {n_psr} devices; the padded "
                "width is part of the logical layout (PRNG draw shapes) "
                "and cannot be changed on resume — pick a pulsar-axis "
                "size that divides it")
        nch = int(gibbs_kwargs.get("nchains", lay.get("nchains", 1)))
        if n_chain > 1 and nch % n_chain:
            raise CheckpointError(
                f"{outdir}: checkpoint's chain count ({nch}) does not "
                f"divide over a {n_chain}-device chain axis; the chain "
                "count is part of the logical layout (per-chain key "
                "folds) and cannot be changed on resume — pick a chain-"
                "axis size that divides it")
        from ..parallel.sharding import make_mesh

        mesh = make_mesh((n_chain, n_psr) if n_chain > 1 else n_psr)
    from ..sampler.gibbs import PTABlockGibbs, PulsarBlockGibbs

    cls = {"PulsarBlockGibbs": PulsarBlockGibbs,
           "PTABlockGibbs": PTABlockGibbs}.get(
        lay.get("facade"),
        PTABlockGibbs if len(want) > 1 else PulsarBlockGibbs)
    gibbs_kwargs.setdefault("nchains", int(lay.get("nchains", 1)))
    gibbs_kwargs.setdefault("record_every", int(lay.get("record_every", 1)))
    gibbs_kwargs["pad_pulsars"] = pad
    gibbs_kwargs["mesh"] = mesh
    return cls(pta, backend="jax", **gibbs_kwargs)


def rotate_backup(outdir) -> bool:
    """Refresh the ``.bak`` generation from the current checkpoint set.

    Copies (never moves — a kill mid-rotation must not lose the
    primary) each manifest-listed file to ``<name>.bak`` via tmp +
    replace, then the manifest to ``manifest.bak.json``.  Skips —
    leaving any existing backup untouched — when the current set does
    not verify: a torn set must never overwrite the last good backup.
    """
    outdir = Path(outdir)
    man = read_manifest(outdir)
    if man is None or not verify(outdir, man)["ok"]:
        return False
    for nm in man["files"]:
        tmp = outdir / (nm + ".bak.tmp")
        shutil.copy2(outdir / nm, tmp)
        os.replace(tmp, outdir / (nm + ".bak"))
    tmp = outdir / (MANIFEST_BAK + ".tmp")
    shutil.copy2(outdir / MANIFEST, tmp)
    os.replace(tmp, outdir / MANIFEST_BAK)
    return True


def rollback(outdir) -> bool:
    """Restore the ``.bak`` checkpoint over the primary files.

    The backup set is verified against ``manifest.bak.json`` first;
    returns False (primary untouched) when there is no verified backup.
    """
    outdir = Path(outdir)
    bman = read_manifest(outdir, MANIFEST_BAK)
    if bman is None or not verify(outdir, bman, suffix=".bak")["ok"]:
        return False
    for nm in bman["files"]:
        tmp = outdir / (nm + ".restore.tmp")
        shutil.copy2(outdir / (nm + ".bak"), tmp)
        os.replace(tmp, outdir / nm)
    tmp = outdir / (MANIFEST + ".restore.tmp")
    shutil.copy2(outdir / MANIFEST_BAK, tmp)
    os.replace(tmp, outdir / MANIFEST)
    telemetry.incr("rollbacks")
    return True


def check_not_quarantined(outdir, force_requeue=False, manifest=None):
    """Refuse a quarantine-marked checkpoint directory unless the
    operator passed ``force_requeue``.

    A manifest whose ``serve.state`` is ``"quarantined"`` marks a job
    the serving tier PARKED after exhausting its quarantine budget: the
    checkpoint itself is verified (rows up to the last clean save), but
    resuming it blindly would replay the same poisoned trajectory.
    EVERY resume path must route through this one check —
    :func:`load_resume` here and ``ChainStore.load_resume`` (the
    facade / ``reshard_restore`` path) both call it, so there is no
    side door that silently resumes a parked job.  ``manifest`` skips
    the re-read when the caller already holds the (verified) manifest.
    """
    if force_requeue:
        return
    man = read_manifest(Path(outdir)) if manifest is None else manifest
    if (isinstance(man, dict) and not man.get("corrupt")
            and (man.get("serve") or {}).get("state") == "quarantined"):
        raise CheckpointError(
            f"{outdir} holds a QUARANTINED job (its serving tier "
            "parked it after repeated row-health breaches).  The "
            "checkpoint is verified but the job needs an operator "
            "decision: resume with force_requeue=True "
            "(--force-requeue) to requeue it from the verified rows")


def load_resume(outdir, force_requeue=False, pta=None):
    """Standalone verified checkpoint load for a bare directory.

    ``ChainStore.load_resume`` needs a live store instance (the facade
    owns one); the serving scheduler readmits evicted jobs knowing only
    their per-job checkpoint dir.  This helper reconstructs the store
    from the directory's own ``pars_chain.txt``/``pars_bchain.txt`` and
    delegates — same manifest verification, ``.bak`` rollback and
    :class:`CheckpointError` semantics.  Returns
    ``(chain, bchain, start_iter, adapt_state)`` or ``None`` when there
    is nothing to resume from.

    A manifest whose ``serve.state`` is ``"quarantined"`` marks a job
    the serving tier PARKED after exhausting its quarantine budget: the
    checkpoint itself is verified (rows up to the last clean save), but
    resuming it blindly would replay the same poisoned trajectory.
    Such a directory REFUSES to load unless ``force_requeue=True``
    (the ``--force-requeue`` flag on the CLI surfaces) — an operator
    decision, not a scheduler default.

    ``pta``, when supplied, is checked against the manifest's recorded
    pulsar order (``layout.pulsars`` for facade checkpoints,
    ``serve.pulsars`` for serving-tier ones) BEFORE anything loads —
    :class:`LayoutMismatch` names the first disagreeing pulsar.
    """
    from ..sampler.chains import ChainStore

    outdir = Path(outdir)
    if not (outdir / "chain.npy").exists():
        return None
    if pta is not None:
        man = read_manifest(outdir)
        if isinstance(man, dict) and not man.get("corrupt"):
            want = ((man.get("layout") or {}).get("pulsars")
                    or (man.get("serve") or {}).get("pulsars"))
            if want:
                check_layout_pulsars(outdir, want,
                                     getattr(pta, "pulsars", []))

    def _names(fname):
        p = outdir / fname
        if not p.exists():
            return []
        return [ln.strip() for ln in p.read_text().splitlines()
                if ln.strip()]

    store = ChainStore(outdir, _names("pars_chain.txt"),
                       _names("pars_bchain.txt"))
    return store.load_resume(force_requeue=force_requeue)
