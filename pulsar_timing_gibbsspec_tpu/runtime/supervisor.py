"""Supervised sampling: retry loop, failure taxonomy, graceful degrade.

``run_supervised`` drives ``gibbs.sample(resume=True)`` to completion
through transient failures: each attempt resumes from the last verified
checkpoint (the facade's finally-flush bounds the loss per failure to
under ``save_every`` sweeps), retries are spaced by capped exponential
backoff with deterministic jitter, and failures are classified so each
class gets the right response instead of blind retry:

- ``device``      XLA / runtime faults (preempted TPU, OOM): retry; after
                  ``degrade_after`` consecutive ones the run degrades to
                  the float64 numpy oracle and continues from the SAME
                  checkpoint (slow beats dead).
- ``corruption``  checkpoint failed verification beyond repair upstream:
                  roll back to the ``.bak`` generation, then retry.
- ``divergence``  sentinel-detected NaN/stuck chain: rewind (implicit —
                  the poisoned rows never reached the checkpoint) and
                  replay; if the SAME divergence reproduces on the
                  deterministic replay, refold the checkpoint PRNG key
                  so the re-draw takes a fresh stream.
- ``crash``       injected/os-level kill artifacts: plain retry.
- ``stall``       watchdog-aborted hung dispatch: its OWN capped retry
                  budget (``stall_max_retries``) and backoff — a stall
                  is usually environmental (wedged device runtime) and
                  either clears in a couple of retries or never does,
                  so it must not consume the general budget.
- ``preempted``   drain completed after SIGTERM/maintenance notice:
                  NOT a failure — the supervisor logs it, stamps the
                  report ``status="preempted"`` and returns; the next
                  incarnation resumes bit-identically from the drained
                  checkpoint (``preemption.EXIT_PREEMPTED`` is the
                  conventional exit code for schedulers to requeue on).
- ``user``        bugs (shape errors, contract violations, tripped
                  transfer guard): re-raised immediately — retrying a
                  deterministic bug is denial of service on yourself.

Each attempt runs under ``analysis.guards.count_recompiles`` so failure
events in ``metrics.jsonl`` carry the compile count — a retry storm
that also recompiles every time is a cache-miss bug, not flakiness.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict, dataclass, field
from pathlib import Path

import numpy as np

from . import faults, integrity, preemption, sentinels, telemetry
from .watchdog import DispatchStall


def classify_failure(exc) -> str:
    """Map an exception from ``sample()`` to a failure class:
    ``device | device_loss | corruption | divergence | crash | stall |
    preempted | user | unknown``."""
    if isinstance(exc, preemption.Preempted):
        return "preempted"
    if isinstance(exc, DispatchStall):
        return "stall"
    if isinstance(exc, faults.DeviceLost):
        # lost capacity does not come back on retry: the caller must
        # evacuate onto the surviving submesh, not replay blindly
        return "device_loss"
    if isinstance(exc, faults.InjectedCrash):
        return "crash"
    if isinstance(exc, integrity.CheckpointError):
        return "corruption"
    if isinstance(exc, FloatingPointError):    # includes ChainDivergence
        return "divergence"
    name = type(exc).__name__
    low = str(exc).lower()
    # jaxlib's XlaRuntimeError (and the injected stand-in) by NAME —
    # importing jaxlib here would defeat the numpy-only degrade path
    if "xlaruntimeerror" in name.lower() or name == "InternalError":
        return "device"
    if "transfer" in low and ("guard" in low or "disallow" in low):
        # a tripped transfer guard (analysis.guards.no_transfers) is a
        # code-discipline bug — retrying cannot fix it
        return "user"
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError,
                        AttributeError, NotImplementedError,
                        AssertionError)):
        return "user"
    if isinstance(exc, OSError):
        return "crash"
    if isinstance(exc, RuntimeError):
        if any(t in low for t in ("xla", "device", "tpu", "out of memory",
                                  "resource exhausted", "internal error")):
            return "device"
        return "user"        # resume-contract violations et al.
    return "unknown"


def backoff_delay(retry, base=0.5, cap=30.0, jitter=0.25, seed=0) -> float:
    """Capped exponential backoff with deterministic jitter.

    ``retry`` is 1-based; the jitter draw is a pure function of
    (seed, retry) so tests — and post-mortems — can reproduce the exact
    sleep schedule."""
    d = min(float(cap), float(base) * (2.0 ** (retry - 1)))
    u = np.random.default_rng([int(seed), int(retry)]).uniform(-jitter,
                                                               jitter)
    return max(0.0, d * (1.0 + float(u)))


class CircuitOpen(RuntimeError):
    """A circuit breaker rejected the operation: the subject has been
    failing at a rate that makes immediate retry harmful.  Carries the
    breaker so callers can report the cooldown."""

    def __init__(self, msg, breaker=None):
        super().__init__(msg)
        self.breaker = breaker


class CircuitBreaker:
    """Failure-rate circuit breaker (closed → open → half-open).

    CLOSED counts outcomes over a sliding window of the last ``window``
    events; once at least ``min_events`` are in the window and the
    failure fraction reaches ``threshold`` the breaker OPENS — calls
    are rejected for ``cooldown_s``.  After the cooldown it goes
    HALF-OPEN: exactly one probe is allowed through; a recorded success
    closes the breaker (window cleared), a failure re-opens it with a
    fresh cooldown.  ``clock`` is injectable so tests (and the seeded
    chaos campaign) never sleep real time.

    The serving tier keys one breaker per tenant: a tenant whose
    uploads keep diverging stops being re-admitted at full cadence —
    its retries cost the service compile/dispatch wall that healthy
    tenants are paying for.

    Thread-safe: every transition and query runs under one instance
    RLock.  The gateway reaches ``check``/``would_allow`` from N
    concurrent handler threads while the scheduler thread claims
    probes via ``allow`` — without the lock, two ``allow`` callers can
    both observe ``_probing`` False and BOTH claim the single
    half-open probe (check-then-set), so one failing probe re-opens
    the breaker while a duplicate probe is already in flight.
    """

    def __init__(self, window=8, threshold=0.5, min_events=2,
                 cooldown_s=30.0, clock=time.monotonic):
        self.window = int(window)
        self.threshold = float(threshold)
        self.min_events = max(1, int(min_events))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.RLock()
        self._events: list[bool] = []     # True = failure
        self.state = "closed"
        self.opened_at = None
        self.opens = 0
        self._probing = False

    def _failure_rate(self) -> float:
        if not self._events:
            return 0.0
        return sum(self._events) / len(self._events)

    def record_failure(self) -> None:
        with self._lock:
            if self.state == "half_open":
                # the probe failed: straight back to open, fresh cooldown
                self._trip()
                return
            self._events = (self._events + [True])[-self.window:]
            if (self.state == "closed"
                    and len(self._events) >= self.min_events
                    and self._failure_rate() >= self.threshold):
                self._trip()

    def record_success(self) -> None:
        with self._lock:
            if self.state == "half_open":
                # probe succeeded: the fault cleared — close and forget
                self.state = "closed"
                self._events = []
                self._probing = False
                return
            self._events = (self._events + [False])[-self.window:]

    def _trip(self) -> None:
        with self._lock:
            self.state = "open"
            self.opened_at = self.clock()
            self.opens += 1
            self._probing = False
        telemetry.incr("circuit_opens")

    def would_allow(self) -> bool:
        """Non-consuming query: would :meth:`allow` pass right now?
        (Never transitions state or claims the half-open probe slot —
        submit-time gating must not eat the scheduler's probe.)"""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                return self.clock() - self.opened_at >= self.cooldown_s
            return not self._probing

    def allow(self) -> bool:
        """True when a call may proceed: always in CLOSED; in OPEN only
        once the cooldown elapsed (transitioning to HALF-OPEN); in
        HALF-OPEN only for the single in-flight probe.  The
        claim-the-probe decision is atomic under the instance lock:
        exactly one concurrent caller wins the half-open slot."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self.clock() - self.opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self._probing = True
                    return True
                return False
            # half-open: one probe at a time
            if not self._probing:
                self._probing = True
                return True
            return False

    def check(self, subject="operation") -> None:
        """Raise :class:`CircuitOpen` unless :meth:`would_allow` —
        a query, not a claim: the probe slot stays available."""
        with self._lock:
            if self.would_allow():
                return
            wait = 0.0 if self.opened_at is None else max(
                0.0, self.cooldown_s - (self.clock() - self.opened_at))
            raise CircuitOpen(
                f"circuit open for {subject}: failure rate "
                f"{self._failure_rate():.2f} over the last "
                f"{len(self._events)} attempt(s) — retry in "
                f"{wait:.1f}s", breaker=self)

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "opens": int(self.opens),
                    "failure_rate": round(self._failure_rate(), 3),
                    "events": len(self._events)}


class AdmissionController:
    """Service-level admission control / backpressure, driven by the
    gauges the serving tier already publishes (ROADMAP 1d):

    - ``queue_depth`` — past ``max_queue`` the service REJECTS new
      submissions (typed :class:`CircuitOpen`): unbounded queues turn
      overload into latency for everyone instead of an error for the
      marginal request.
    - ``compile_stalls`` — ``note_compile()`` timestamps every cold
      compile; when ``storm_compiles`` of them land within
      ``storm_window_s`` the controller declares a COMPILE STORM and
      ``defer_cold()`` tells the scheduler to hold NEW dataset shapes
      (cold buckets) in the queue while warm jobs keep the device busy
      — a burst of novel shapes would otherwise serialize everyone
      behind back-to-back XLA compiles (``time_to_first_sample_ms``
      blows up service-wide).

    Deferral is never starvation: once the storm window drains (no new
    cold compile for ``storm_window_s``), cold jobs admit again.
    """

    def __init__(self, max_queue=64, storm_compiles=3, storm_window_s=60.0,
                 clock=time.monotonic):
        self.max_queue = int(max_queue)
        self.storm_compiles = int(storm_compiles)
        self.storm_window_s = float(storm_window_s)
        self.clock = clock
        self._compiles: list[float] = []
        self.rejections = 0
        self.deferrals = 0

    def admit_submission(self, queue_depth) -> None:
        """Gate one submission on backpressure; raises
        :class:`CircuitOpen` when the queue is full."""
        if int(queue_depth) >= self.max_queue:
            self.rejections += 1
            telemetry.incr("admission_rejections")
            raise CircuitOpen(
                f"admission rejected: queue depth {int(queue_depth)} "
                f">= {self.max_queue} (backpressure — resubmit after "
                "the queue drains)", breaker=None)

    def note_compile(self) -> None:
        """Record one cold bucket compile (a ``compile_stalls`` tick)."""
        now = self.clock()
        self._compiles = [t for t in self._compiles
                          if now - t < self.storm_window_s] + [now]

    def storming(self) -> bool:
        now = self.clock()
        self._compiles = [t for t in self._compiles
                          if now - t < self.storm_window_s]
        return len(self._compiles) >= self.storm_compiles

    def defer_cold(self, warm) -> bool:
        """True when a job whose program is not yet compiled (``warm``
        False) should wait out the current compile storm."""
        if warm or not self.storming():
            return False
        self.deferrals += 1
        telemetry.incr("admission_deferrals")
        return True

    def snapshot(self) -> dict:
        return {"storming": self.storming(),
                "rejections": int(self.rejections),
                "deferrals": int(self.deferrals)}


@dataclass
class SupervisorReport:
    """Outcome counters for one supervised run (mirrored to
    ``metrics.jsonl`` and, via runtime.telemetry, to bench.py JSON)."""

    attempts: int = 0
    retries: int = 0
    rollbacks: int = 0
    refolds: int = 0
    degradations: int = 0
    #: stall-class retries, budgeted separately from ``retries``
    stall_retries: int = 0
    #: "completed" | "preempted" — preemption is a resumable outcome,
    #: not a failure, and callers branch on this to requeue
    status: str = "completed"
    backend: str = ""
    failures: list = field(default_factory=list)

    def as_dict(self):
        return asdict(self)


def _log_event(outdir, record):
    """Append to the run's ``metrics.jsonl`` stream (same file the
    facade's ChainStore writes) without requiring a store instance."""
    p = Path(outdir)
    p.mkdir(parents=True, exist_ok=True)
    rec = {"ts": round(time.time(), 3), **record}
    with open(p / "metrics.jsonl", "a") as fh:
        fh.write(json.dumps(rec) + "\n")


def _degraded(gibbs):
    """Numpy twin of a jax facade for graceful degradation, or None when
    the run shape cannot transfer (multi-chain or thinned records have
    no numpy equivalent)."""
    be = gibbs._backend
    if getattr(be, "C", 1) != 1 or getattr(be, "record_every", 1) != 1:
        return None
    try:
        return gibbs.with_backend("numpy")
    except Exception:
        return None


def run_supervised(gibbs, x0, outdir, niter, save_every=100, resume=True,
                   max_retries=8, degrade_after=3, backoff_base=0.5,
                   backoff_cap=30.0, jitter=0.25, backoff_seed=0,
                   sleep=time.sleep, allow_degrade=True,
                   stall_max_retries=3, stall_backoff_base=None,
                   **sample_kwargs):
    """Drive ``gibbs.sample`` to ``niter`` under the retry policy above.

    Returns ``(chain, report)``.  ``sleep`` is injectable so tests can
    capture the backoff schedule instead of waiting it out; ``resume``
    applies to the FIRST attempt only (every retry resumes).

    A ``preempted`` outcome returns early with ``report.status ==
    "preempted"`` and the rows drained so far — callers exit with
    ``preemption.EXIT_PREEMPTED`` and let the scheduler requeue.
    Stalls retry under their own ``stall_max_retries`` budget
    (backoff base ``stall_backoff_base``, defaulting to
    ``backoff_base``) without consuming the general budget.
    """
    from ..analysis.guards import count_recompiles

    rep = SupervisorReport(backend=gibbs.backend_name)
    consecutive_device = 0
    last_div_sig = None
    rc = None
    while True:
        rep.attempts += 1
        try:
            with count_recompiles() as rc:
                chain = gibbs.sample(
                    x0, outdir=outdir, niter=niter,
                    resume=resume or rep.attempts > 1,
                    save_every=save_every, **sample_kwargs)
            rep.backend = gibbs.backend_name
            _log_event(outdir, {"event": "supervised_run_complete",
                                **rep.as_dict()})
            return chain, rep
        except KeyboardInterrupt:
            raise                # the facade's finally-flush already ran
        except Exception as exc:
            kind = classify_failure(exc)
            if kind == "preempted":
                # a drained run is a resumable OUTCOME, not a failure:
                # report it as such and hand control back so the caller
                # can exit before the grace window closes
                rep.status = "preempted"
                rep.backend = gibbs.backend_name
                _log_event(outdir, {
                    "event": "supervised_preempted",
                    "rows": getattr(exc, "rows", None),
                    "verified": getattr(exc, "verified", None),
                    "drain": preemption.drain_info(), **rep.as_dict()})
                return getattr(gibbs, "chain", None), rep
            n_comp = int(getattr(rc, "events", 0) or 0)
            fail = {"attempt": rep.attempts, "kind": kind,
                    "error": f"{type(exc).__name__}: {exc}"[:300],
                    "n_compiles": n_comp}
            rep.failures.append(fail)
            _log_event(outdir, {"event": "supervised_failure", **fail})
            if kind == "user":
                raise
            if kind == "stall":
                # stalls have their own capped budget + backoff: they
                # are environmental and must not eat the general budget
                if rep.stall_retries >= stall_max_retries:
                    _log_event(outdir, {"event": "supervised_giving_up",
                                        "reason": "stall budget",
                                        **rep.as_dict()})
                    raise
                rep.stall_retries += 1
                telemetry.incr("stall_retries")
                delay = backoff_delay(
                    rep.stall_retries,
                    backoff_base if stall_backoff_base is None
                    else stall_backoff_base,
                    backoff_cap, jitter, seed=backoff_seed)
                _log_event(outdir, {"event": "supervised_retry",
                                    "next_attempt": rep.attempts + 1,
                                    "kind": kind,
                                    "stall_retry": rep.stall_retries,
                                    "backoff_s": round(delay, 3)})
                sleep(delay)
                continue
            if rep.retries >= max_retries:
                _log_event(outdir, {"event": "supervised_giving_up",
                                    **rep.as_dict()})
                raise
            rep.retries += 1
            telemetry.incr("retries")
            if kind == "corruption":
                # load_resume already tried the .bak; one more explicit
                # attempt here, then give up — retrying an unverifiable
                # checkpoint forever converges to nothing
                if integrity.rollback(outdir):
                    rep.rollbacks += 1
                    _log_event(outdir, {"event": "checkpoint_rollback",
                                        "attempt": rep.attempts})
                else:
                    raise
            if kind == "divergence":
                sig = f"{type(exc).__name__}:{exc}"
                if sig == last_div_sig:
                    # deterministic replay reproduced the same blow-up:
                    # re-draw the stretch under a fresh PRNG fold
                    if sentinels.refold_checkpoint_key(
                            outdir, salt=rep.attempts):
                        rep.refolds += 1
                        _log_event(outdir, {"event": "prng_refold",
                                            "attempt": rep.attempts})
                last_div_sig = sig
            else:
                last_div_sig = None
            consecutive_device = (consecutive_device + 1
                                  if kind in ("device", "device_loss")
                                  else 0)
            if (allow_degrade and gibbs.backend_name == "jax"
                    and consecutive_device >= degrade_after):
                down = _degraded(gibbs)
                if down is not None:
                    gibbs = down
                    rep.degradations += 1
                    rep.backend = gibbs.backend_name
                    telemetry.incr("degradations")
                    consecutive_device = 0
                    _log_event(outdir, {"event": "backend_degraded",
                                        "to": gibbs.backend_name,
                                        "attempt": rep.attempts})
            delay = backoff_delay(rep.retries, backoff_base, backoff_cap,
                                  jitter, seed=backoff_seed)
            _log_event(outdir, {"event": "supervised_retry",
                                "next_attempt": rep.attempts + 1,
                                "kind": kind,
                                "backoff_s": round(delay, 3)})
            sleep(delay)
