"""Dispatch watchdog: EMA-deadline heartbeat around blocking chunk work.

A hung XLA dispatch (wedged device runtime, dead tunnel, livelocked
collective) looks exactly like a very slow chunk — except it never
returns, and an unsupervised run blocks forever without writing the
checkpoint it already has.  The watchdog turns "never returns" into a
classified, retryable failure:

- The deadline tracks an EMA of steady-chunk wall times: ``k`` times
  the smoothed chunk wall, floored at ``floor_s``.  Before any steady
  wall is measured (first dispatch, or a fresh compile of an
  off-residue tail chunk) the much larger ``first_floor_s`` applies —
  a cold XLA compile is slow, not stuck.
- Escalation inside one guarded call: past the SOFT deadline
  (``soft_frac`` of the hard one) it logs a heartbeat warning and
  counts ``watchdog_soft``; at the HARD deadline it dumps every
  thread's stack (the post-mortem a hung run otherwise takes to the
  grave) and counts ``watchdog_dumps``; then it abandons the dispatch
  and raises :class:`DispatchStall` (``watchdog_stalls``).
- The blocking call runs on a reusable single worker thread so the
  waiter can time out; an abandoned worker (still blocked in native
  code — Python cannot interrupt it) is detached and a fresh worker
  serves the next call.  The jitted function, its compile cache and
  the device arrays are all thread-safe to share, and the abandoned
  call's result is discarded, so a late completion has no effect.

``run_supervised`` classifies :class:`DispatchStall` as the ``stall``
failure class with its own capped retry budget: the retry resumes from
the last committed checkpoint bit-identically (the aborted chunk never
reached the chain files).

The guard adds no retraces: it never touches traced values — it only
times the call and runs it on another thread.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback

from . import telemetry
from ..obs import trace as otrace


class DispatchStall(RuntimeError):
    """A guarded dispatch blew its hard deadline and was abandoned."""


def dump_stacks() -> str:
    """Formatted stacks of every live thread (the hang post-mortem)."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(ln.rstrip() for ln in traceback.format_stack(frame))
    return "\n".join(out)


class DispatchWatchdog:
    """Heartbeat guard for the driver's blocking chunk work.

    ``observe(dt)`` feeds steady-chunk wall times; ``call(fn)`` runs
    ``fn`` under the current deadline.  ``on_event`` (optional) receives
    ``(stage, info)`` for ``"soft" | "dump" | "stall"`` so the driver
    can mirror escalations into ``metrics.jsonl``.
    """

    def __init__(self, k=4.0, floor_s=30.0, first_floor_s=1800.0,
                 ema_alpha=0.3, soft_frac=0.5, on_event=None,
                 poll_s=0.05):
        if k <= 1.0:
            raise ValueError("watchdog k must exceed 1 (deadline must "
                             "sit above the steady chunk wall)")
        self.k = float(k)
        self.floor_s = float(floor_s)
        self.first_floor_s = float(first_floor_s)
        self.ema_alpha = float(ema_alpha)
        self.soft_frac = float(soft_frac)
        self.on_event = on_event
        self.poll_s = float(poll_s)
        self.ema = None
        self._n_seen = None
        self._worker = None
        self._inbox = None

    # -- deadline model ------------------------------------------------------

    def _check_geometry(self, n) -> None:
        """Reset the EMA when the sweeps-per-dispatch changes (e.g.
        ``megachunk`` differs across a resume): the per-sweep wall is
        NOT geometry-invariant — a bigger dispatch amortizes its fixed
        overhead over more sweeps — so an EMA seeded under the old
        geometry would misprice the new one and a resumed run could
        trip a spurious soft-warn on its first healthy chunk.  The
        first post-change call falls back to ``first_floor_s``, exactly
        like a fresh run."""
        n = max(int(n), 1)
        if self._n_seen is not None and n != self._n_seen \
                and self.ema is not None:
            self.ema = None
            telemetry.incr("watchdog_ema_resets")
        self._n_seen = n

    def observe(self, dt, n=1) -> None:
        """Feed one steady-chunk wall time (seconds) covering ``n``
        sweeps: the EMA is kept PER SWEEP, so mega-chunk runs (one
        dispatch spanning many sub-chunks) and legacy runs share one
        deadline model.  A change in ``n`` between calls resets the EMA
        (:meth:`_check_geometry`).  ``n=1`` (the default) keeps the
        historical per-dispatch semantics.  Callers must skip walls that
        include a fresh compile — they would poison the EMA the way one
        outlier poisons any small-alpha smoother."""
        self._check_geometry(n)
        per = float(dt) / max(int(n), 1)
        self.ema = per if self.ema is None else (
            self.ema_alpha * per + (1.0 - self.ema_alpha) * self.ema)
        # the live deadline model, scrapeable next to the dispatch_ms
        # stage gauges (perfwatch's stall-margin view)
        telemetry.gauge("watchdog_ema_s", self.ema)
        telemetry.gauge("watchdog_deadline_s", self.deadline(n))

    def deadline(self, n=1) -> float:
        """Current hard deadline (seconds) for one guarded call covering
        ``n`` sweeps (the per-sweep EMA scaled back up)."""
        if self.ema is None:
            return self.first_floor_s
        return max(self.floor_s, self.k * self.ema * max(int(n), 1))

    # -- guarded execution ---------------------------------------------------

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._inbox = {"fn": None, "go": threading.Event(),
                           "done": threading.Event(), "out": None,
                           "exc": None}
            self._worker = threading.Thread(
                target=self._serve, args=(self._inbox,),
                name="dispatch-watchdog-worker", daemon=True)
            self._worker.start()

    @staticmethod
    def _serve(box):
        while True:
            box["go"].wait()
            box["go"].clear()
            fn = box["fn"]
            if fn is None:        # abandoned: a fresh worker took over
                return
            try:
                box["out"] = fn()
            except BaseException as exc:    # noqa: BLE001 — re-raised
                box["exc"] = exc
            box["done"].set()

    def _emit(self, stage, info):
        # stack dumps are huge; the trace keeps the escalation timeline,
        # not the post-mortem payload (that goes through on_event)
        otrace.instant(f"watchdog.{stage}",
                       **{k: v for k, v in info.items() if k != "stacks"})
        if self.on_event is not None:
            try:
                self.on_event(stage, info)
            except Exception:
                pass              # observability must not kill the run

    def call(self, fn, what="dispatch", n=1):
        """Run ``fn()`` under the deadline for ``n`` sweeps of work;
        returns its result or re-raises its exception.  Raises
        :class:`DispatchStall` (and abandons the call) when the hard
        deadline passes."""
        self._check_geometry(n)
        self._ensure_worker()
        box = self._inbox
        box["fn"], box["out"], box["exc"] = fn, None, None
        box["done"].clear()
        box["go"].set()
        hard = self.deadline(n)
        soft = self.soft_frac * hard
        t0 = time.monotonic()
        warned = False
        while True:
            if box["done"].wait(self.poll_s):
                break
            el = time.monotonic() - t0
            if not warned and el >= soft:
                warned = True
                telemetry.incr("watchdog_soft")
                self._emit("soft", {"what": what, "elapsed_s": el,
                                    "deadline_s": hard})
            if el >= hard:
                telemetry.incr("watchdog_dumps")
                self._emit("dump", {"what": what, "elapsed_s": el,
                                    "stacks": dump_stacks()})
                # detach: the worker may be blocked in native code and
                # cannot be interrupted; drop our reference and let a
                # future call start a clean one
                self._worker = None
                self._inbox = None
                telemetry.incr("watchdog_stalls")
                self._emit("stall", {"what": what, "elapsed_s": el,
                                     "deadline_s": hard})
                raise DispatchStall(
                    f"{what} exceeded the watchdog deadline "
                    f"({el:.1f}s > {hard:.1f}s; steady-chunk EMA "
                    f"{'unset' if self.ema is None else f'{self.ema:.2f}s'}"
                    ") — dispatch abandoned; resume from the last "
                    "committed checkpoint")
        if box["exc"] is not None:
            raise box["exc"]
        return box["out"]
