def __getattr__(name):
    if name in ("PulsarBlockGibbs", "PTABlockGibbs"):
        from . import gibbs

        return getattr(gibbs, name)
    if name == "NumpyGibbs":
        from .numpy_backend import NumpyGibbs

        return NumpyGibbs
    raise AttributeError(name)
