"""Ensemble mixing stage: interchain stretch moves, ASIS interweaving,
and parallel tempering on the chain axis.

The driver's 64 vmapped chains are one device array — an *ensemble* the
blocked Gibbs sweep never exploited.  This module is the compiled
per-sweep stage that does, attacking the rho <-> b funnel that pins CRN
rho-ACT at ~45 sweeps against the f64 oracle's ~27 blocking floor
(ROADMAP item 2, the r5 collapse experiment's diagnosis):

- :func:`stretch_rho_move` — Goodman & Weare (2010) affine-invariant
  stretch proposals on the common-spectrum ln-rho block, paired across
  complementary chain half-ensembles.  Given b, rho's conditional is
  the pure prior term ``prod_pk phi^{-n/2} exp(-tau/phi)`` (the white
  likelihood doesn't see rho once b is fixed), so the accept ratio is a
  (P, K) reduction — no residual work.  Proposals slide the whole
  ensemble along the funnel ridge at the ensemble's own scale.
- :func:`asis_rho_redraw` — ancillarity-sufficiency interweaving (Yu &
  Meng 2011) generalizing the shipped ``rho_scale_moves`` random-walk:
  with the prior diagonal, the exact ancillary coordinates are
  ``b~ = b / sqrt(phi)``; holding b~ fixed, rho_k's conditional over
  the log-uniform grid is a per-pulsar two-scalar (A_p, B_p) white
  likelihood profile, drawn exactly by Gumbel-max (the same grid error
  class as ``rho_update``).  The sweep body's ``rho_update`` is the
  sufficient draw; this is the ancillary one — one interweave per
  sweep.
- :func:`pt_swap` — parallel tempering over a temperature sub-axis of
  the chain batch: chain ``c`` runs at inverse temperature
  ``betas[c % n_temps]`` (a geometric ladder adapted toward ~23% swap
  acceptance by stochastic approximation with decaying gain), with
  even/odd deck swaps of the full (x, b, u) state between adjacent
  rungs.  Only the likelihood is tempered (``pi_beta ~ L^beta * prior``)
  so every phi-only grid conditional in the sweep body is untouched;
  beta enters the white/ECORR MH log-likelihoods, the b-draw system
  (N -> N / beta), the scale move's residual delta, and the swap
  energy.  Only beta = 1 chains (``c % n_temps == 0``) are posterior
  samples.

Mesh discipline (contracts/crn_ensemble.json): chain c = w * n_temps + t
keeps each temperature block contiguous, so on a ``(chain, pulsar)``
mesh with ``n_temps`` dividing the per-device chain block, tempering
swaps permute a device-LOCAL axis (zero collectives), and only the
stretch move's small (C, K) ln-rho payload crosses chain blocks — the
explicit chain-axis collective allowlist, never b or design matrices.

Everything here is a pure function of the ``(x, b, u)`` carry, the
small ``ens_state`` pytree, and one folded key — the stage rides the
chunk scan, snapshots per-sweep for bitwise resume, and is Python-gated
(off means the ops never enter the jaxpr).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from ..config import settings


@dataclasses.dataclass(frozen=True)
class EnsembleSpec:
    """Static configuration of the ensemble stage (hashable — part of
    the chunk-function cache key via driver identity)."""

    n_temps: int = 1
    stretch: bool = True
    asis: bool = True
    #: Goodman-Weare stretch scale: z ~ g(z) ~ 1/sqrt(z) on [1/a, a]
    stretch_a: float = 2.0
    #: PT swap-acceptance target of the stochastic-approximation ladder
    #: (the classic ~23% optimal-scaling figure)
    swap_target: float = 0.23
    #: SA gain schedule gain_m = sa_gain / (1 + m / sa_t0)^0.6
    sa_gain: float = 0.5
    sa_t0: float = 50.0
    #: initial geometric ladder ratio beta_{t+1} / beta_t
    beta_ratio: float = 0.55


def ensemble_applies(cm) -> bool:
    """Static predicate: same applicability class as
    ``jax_backend._rho_scale_applies`` — CRN free-spectrum common blocks
    with diagonal N (the stretch/ASIS targets are the shared rho block;
    the cheap likelihood deltas assume diagonal N)."""
    return (cm.orf_name == "crn" and cm.gw_kind == "free_spectrum"
            and bool(cm.K) and len(cm.rho_ix_x) > 0 and not cm.has_ke)


def validate_ensemble(spec: EnsembleSpec, nchains: int, mesh=None):
    """Raise unless the chain batch factors into the (walker, temp)
    layout the stage assumes; actionable by construction."""
    T = int(spec.n_temps)
    if T < 1:
        raise ValueError(f"pt_ladder={T} must be >= 1")
    if nchains % T:
        raise ValueError(
            f"nchains={nchains} is not a multiple of the tempering "
            f"ladder depth {T} — chain c runs at betas[c % {T}], so the "
            "ladder must tile the chain batch exactly")
    W = nchains // T
    if spec.stretch and (W < 2 or W % 2):
        raise ValueError(
            f"stretch moves need an even number >= 2 of walkers per "
            f"temperature (half-ensemble pairing); got {W} "
            f"(nchains={nchains}, pt_ladder={T})")
    if mesh is not None and T > 1:
        from ..parallel.sharding import chain_submesh_size

        nc = chain_submesh_size(mesh)
        if nc > 1 and (nchains // nc) % T:
            raise ValueError(
                f"per-device chain block {nchains // nc} is not a "
                f"multiple of pt_ladder={T}: tempering swaps must stay "
                "within the device-local chain block "
                "(contracts/crn_ensemble.json)")


def init_ens_state(spec: EnsembleSpec, dtype) -> dict:
    """The small per-run ensemble state pytree: the adaptive ladder
    log-spacings plus the per-temperature swap/stretch counters the obs
    summary reports.  Rides the chunk scan carry, the per-writeback
    staging args, and ``adapt_state`` (``ens_*`` keys)."""
    import jax.numpy as jnp

    T = int(spec.n_temps)
    lsp0 = float(np.log(np.log(1.0 / spec.beta_ratio)))
    return {
        "lsp": jnp.full((max(T - 1, 0),), lsp0, dtype),
        "m": jnp.zeros((), dtype),
        "swap_acc": jnp.zeros((max(T - 1, 0),), dtype),
        "swap_try": jnp.zeros((max(T - 1, 0),), dtype),
        "stretch_acc": jnp.zeros((T,), dtype),
        "stretch_try": jnp.zeros((), dtype),
    }


def betas_from_lsp(lsp):
    """Inverse-temperature ladder from log-spacings:
    ``beta_t = exp(-sum_{s<t} exp(lsp_s))`` — beta_0 = 1 always, each
    spacing positive by construction, so adaptation can never reorder
    or collapse the ladder."""
    import jax.numpy as jnp

    return jnp.concatenate([jnp.ones((1,), lsp.dtype),
                            jnp.exp(-jnp.cumsum(jnp.exp(lsp)))])


def chain_betas(spec: EnsembleSpec, es: dict, nchains: int):
    """Per-chain inverse temperatures under the c = w * T + t layout."""
    import jax.numpy as jnp

    return jnp.tile(betas_from_lsp(es["lsp"]), nchains // spec.n_temps)


# ---------------------------------------------------------------------------
# stretch move

def stretch_halves(logpdf, coords, key, a=2.0):
    """One Goodman-Weare stretch sweep over an ensemble: two sequential
    complementary-half updates (each walker's partner drawn from the
    *other* half, so the move is a valid Metropolis kernel conditioned
    on the fixed half).

    ``coords`` is ``(W, G, d)`` — walkers x independent groups (the
    temperature rungs; pairing never crosses groups) x dimension.
    ``logpdf(c, lo)`` maps proposal coords ``(m, G, d)`` plus the
    STATIC walker offset ``lo`` (a Python int: the proposals are for
    walkers ``lo..lo+m``) to log densities ``(m, G)`` — the static
    offset lets per-walker parameters outside the moved block be read
    with static slices, which a 2-d mesh partitions without chain-axis
    gathers (only the partner COORDS, the small ``(m, G, d)`` payload,
    cross chain blocks).  Accepts with the affine-invariance Jacobian
    ``z^(d-1)``.

    Returns ``(coords, n_accept)`` with ``n_accept`` summed per group.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    W, G, d = coords.shape
    h = W // 2

    def half(coords, lo, co, kh):
        kp, kz, ka = jr.split(kh, 3)
        # static half slice (lo is a Python int): partitions as a local
        # slice on a chain-sharded walker axis, no dynamic-start gather
        cs = jax.lax.slice_in_dim(coords, lo, lo + h, axis=0)
        # complementary-half pairing: partner indices are a pure
        # function of the folded stage key (no PRNG reuse with the
        # z / accept draws — three split streams)
        j = co + jr.randint(kp, (h, G), 0, W - h)
        cp = jnp.take_along_axis(coords, j[..., None], axis=0)
        zu = jr.uniform(kz, (h, G), dtype=coords.dtype)
        z = ((a - 1.0) * zu + 1.0) ** 2 / a
        prop = cp + z[..., None] * (cs - cp)
        logr = ((d - 1.0) * jnp.log(z)
                + logpdf(prop, lo) - logpdf(cs, lo))
        acc = jnp.log(jr.uniform(ka, (h, G), dtype=coords.dtype)) < logr
        coords = jax.lax.dynamic_update_slice_in_dim(
            coords, jnp.where(acc[..., None], prop, cs),
            jnp.asarray(lo, jnp.int32), axis=0)
        return coords, jnp.sum(acc, axis=0).astype(coords.dtype)

    k1, k2 = jr.split(key)
    coords, a0 = half(coords, 0, h, k1)
    coords, a1 = half(coords, h, 0, k2)
    return coords, a0 + a1


def _gw_coeff_counts(cm):
    """Static (P, K) count of live GW coefficients per (pulsar, freq) —
    the ``n`` of the rho-conditional ``phi^{-n/2} exp(-tau/phi)``."""
    B = cm.Bmax
    gsin = np.asarray(cm.gw_sin_ix)
    gcos = np.asarray(cm.gw_cos_ix)
    live = np.asarray(cm.psr_mask)[:, None]
    return (((gsin >= 0) & (gsin < B)).astype(np.float64) * live
            + ((gcos >= 0) & (gcos < B)).astype(np.float64) * live)


def stretch_rho_move(cm, spec: EnsembleSpec, x, b, key):
    """Interchain stretch move on the common ln-rho block over the full
    ``(C, nx)`` chain batch.  Target per chain (given that chain's b):
    ``sum_pk -tau/phi - n/2 log phi`` with ``phi = rho + red`` — exact,
    cheap, and beta-independent (the rho | b conditional is untempered
    for every rung, see module docstring), so all temperature groups
    share one logpdf.  Pairing stays within a temperature group.

    Returns ``(x, n_accept_per_temp)``."""
    import jax
    import jax.numpy as jnp

    cdt = cm.cdtype
    C = x.shape[0]
    T, K, P = spec.n_temps, cm.K, cm.P
    Wn = C // T
    rix = jnp.asarray(cm.rho_ix_x, jnp.int32)
    ln10x2 = 2.0 * np.log(10.0)
    lnlo = np.log(cm.rhomin)
    lnhi = np.log(cm.rhomax)
    nv = jnp.asarray(_gw_coeff_counts(cm), cdt)                 # (P, K)
    lvec = (ln10x2 * x[:, rix].astype(cdt)).reshape(Wn, T, K)
    tau = jax.vmap(cm.gw_tau)(b).astype(cdt).reshape(Wn, T, P, K)
    redv = jax.vmap(cm.red_phi)(x).astype(cdt).reshape(Wn, T, P, K)

    def logpdf(c, lo):                     # (m, T, K), static offset lo
        m = c.shape[0]
        rv = jax.lax.slice_in_dim(redv, lo, lo + m, axis=0)
        tv = jax.lax.slice_in_dim(tau, lo, lo + m, axis=0)
        phi = jnp.exp(c)[:, :, None, :] + rv
        val = -tv / phi - 0.5 * nv * jnp.log(phi)
        lp = jnp.sum(jnp.where(nv > 0, val, jnp.zeros((), cdt)),
                     axis=(-2, -1))
        inb = jnp.all((c > lnlo) & (c < lnhi), axis=-1)
        return jnp.where(inb, lp, -jnp.inf)

    lnew, nacc = stretch_halves(logpdf, lvec, key, a=spec.stretch_a)
    x = x.at[:, rix].set(
        (0.5 / np.log(10.0) * lnew.reshape(C, K)).astype(x.dtype))
    return x, nacc


# ---------------------------------------------------------------------------
# ASIS ancillary redraw

def asis_rho_redraw(cm, x, b, u, key, beta=None):
    """Exact ancillary-parameterization redraw of the common rho block
    for ONE chain (the stage vmaps it): per frequency k, substitute
    ``b~ = b / sqrt(phi)`` on the shared GW columns — under the
    diagonal prior this is the exact ASIS ancillary coordinate, its
    density a rho-independent N(0, I) with unit Jacobian — and draw
    ``ln rho_k | b~`` over the same log-uniform grid as ``rho_update``.
    Holding b~ fixed, moving the grid scales the columns by
    ``s_p(rho') = sqrt((rho' + red_p) / (rho + red_p))``, so the only
    rho-dependent term is the white likelihood of the shifted residual:
    ``beta * sum_p [ delta_p A_p - delta_p^2 B_p / 2 ]`` with
    ``delta_p = s_p - 1``, ``A_p = sum r t / N``, ``B_p = sum t^2 / N``
    and ``t`` the per-pulsar two-column matvec — the same structure as
    ``rho_scale_moves`` but profiled over the whole grid and drawn by
    Gumbel-max instead of random-walked.  b, u, and x[rho] are updated
    consistently (u by the rank-1 column shift, no full matvec).

    ``beta`` (tempering) scales the likelihood profile only; None
    traces the exact untempered program.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    cdt = cm.cdtype
    fdt = cm.dtype
    B, P, K = cm.Bmax, cm.P, cm.K
    gsin = jnp.asarray(cm.gw_sin_ix, jnp.int32)
    gcos = jnp.asarray(cm.gw_cos_ix, jnp.int32)
    live = jnp.asarray(cm.psr_mask, cdt)
    redv = cm.red_phi(x)                                  # (P, K) aligned
    N = cm.ndiag_fast(x)
    toam = jnp.asarray(cm.toa_mask, fdt)
    invN = toam / N.astype(fdt)
    y = jnp.asarray(cm.y, cm.dtype)
    grid = 10.0 ** jnp.linspace(math.log10(cm.rhomin),
                                math.log10(cm.rhomax),
                                settings.rho_grid_size, dtype=fdt)
    pr_ar = jnp.arange(P, dtype=jnp.int32)

    def step(carry, args):
        x, b, u = carry
        k, key = args
        kg, = jr.split(key, 1)
        sk = jnp.clip(jnp.take(gsin, k, axis=1), 0, B - 1)    # (P,)
        ck = jnp.clip(jnp.take(gcos, k, axis=1), 0, B - 1)
        vs = ((jnp.take(gsin, k, axis=1) >= 0)
              & (jnp.take(gsin, k, axis=1) < B)).astype(cdt) * live
        vc = ((jnp.take(gcos, k, axis=1) >= 0)
              & (jnp.take(gcos, k, axis=1) < B)).astype(cdt) * live
        bs = b[pr_ar, sk] * vs
        bc = b[pr_ar, ck] * vc
        Ts = jnp.take_along_axis(
            jnp.asarray(cm.T, cm.dtype), sk[:, None, None], axis=2)[:, :, 0]
        Tc = jnp.take_along_axis(
            jnp.asarray(cm.T, cm.dtype), ck[:, None, None], axis=2)[:, :, 0]
        t = (Ts * bs.astype(fdt)[:, None] + Tc * bc.astype(fdt)[:, None])
        r = y - u
        A = jnp.sum(r * t * invN, axis=1)                     # (P,)
        Bq = jnp.sum(t * t * invN, axis=1)                    # (P,)
        rix = jnp.asarray(cm.rho_ix_x, jnp.int32)[k]
        lrho = 2.0 * np.log(10.0) * jnp.asarray(x, cdt)[rix]
        red_k = redv[:, jnp.minimum(k, K - 1)]                # (P,)
        phi0 = jnp.exp(lrho) + red_k
        nv = vs + vc
        s = jnp.sqrt((grid[None, :].astype(cdt) + red_k[:, None])
                     / phi0[:, None])                         # (P, G)
        dl = (s - 1.0).astype(fdt)
        lg = jnp.sum(jnp.where(
            (nv > 0)[:, None],
            dl * A[:, None] - 0.5 * dl * dl * Bq[:, None],
            jnp.zeros((), fdt)), axis=0)                      # (G,)
        if beta is not None:
            lg = lg * beta.astype(fdt)
        gum = jr.gumbel(kg, lg.shape, dtype=fdt)
        rnew = grid[jnp.argmax(lg + gum)]
        snew = jnp.sqrt((rnew.astype(cdt) + red_k) / phi0)    # (P,)
        dnew = (snew - 1.0).astype(fdt)
        b = b.at[pr_ar, sk].set(jnp.where(vs > 0, b[pr_ar, sk] * snew,
                                          b[pr_ar, sk]))
        b = b.at[pr_ar, ck].set(jnp.where(vc > 0, b[pr_ar, ck] * snew,
                                          b[pr_ar, ck]))
        u = u + dnew[:, None] * t
        x = x.at[rix].set((0.5 * jnp.log10(rnew)).astype(x.dtype))
        return (x, b, u), None

    keys = jr.split(key, K)
    (x, b, u), _ = jax.lax.scan(step, (x, b, u),
                                (jnp.arange(K, dtype=jnp.int32), keys))
    return x, b, u


# ---------------------------------------------------------------------------
# parallel tempering

def _partner_table(T, parity):
    """Static adjacent-rung pairing: rung r <-> r+1 for r = parity (mod
    2); unpaired rungs map to themselves."""
    out = np.arange(T)
    for r in range(parity, T - 1, 2):
        out[r], out[r + 1] = r + 1, r
    return out


def pt_swap(cm, spec: EnsembleSpec, x, b, u, es, key, t):
    """Even/odd deck swaps of the full (x, b, u) chain state between
    adjacent temperature rungs, plus the stochastic-approximation
    ladder update.

    Swap energy is the data log-likelihood
    ``-0.5 sum (r^2 / N + log N)`` per chain (everything the swap's
    ``beta``-weight multiplies; priors are untempered and cancel), and
    the accept for pair (r, r+1) is the standard
    ``(beta_r - beta_{r+1})(E_{r+1} - E_r)`` with ONE shared uniform
    per pair.  Chain c = w * T + t keeps the rung axis device-local
    (reshape, not collective) on a 2-D mesh.  The log-spacing SA update
    ``lsp_r += gain_m * (abar_r - target)`` uses the expected accept
    probability of the rungs active this sweep; the decaying gain makes
    it a diminishing-adaptation scheme (vanishing kernel drift — the
    same class PTMCMCSampler ships)."""
    import jax.numpy as jnp
    import jax.random as jr

    import jax

    cdt = cm.cdtype
    fdt = cm.dtype
    T = spec.n_temps
    C = x.shape[0]
    Wn = C // T
    betas = betas_from_lsp(es["lsp"])                         # (T,)
    toam = jnp.asarray(cm.toa_mask, fdt)
    Nf = jnp.where(toam > 0, jax.vmap(cm.ndiag_fast)(x).astype(fdt), 1.0)
    r = jnp.asarray(cm.y, fdt)[None] - u
    ll = (-0.5 * jnp.sum(jnp.where(toam > 0, r * r / Nf + jnp.log(Nf),
                                   jnp.zeros((), fdt)),
                         axis=(1, 2))).astype(cdt)            # (C,)
    lw = ll.reshape(Wn, T)
    ar = jnp.arange(T)
    partner = jnp.where((t % 2) == 0,
                        jnp.asarray(_partner_table(T, 0), jnp.int32),
                        jnp.asarray(_partner_table(T, 1), jnp.int32))
    la = (betas - betas[partner])[None, :] * (lw[:, partner] - lw)
    ku, = jr.split(key, 1)    # draws come from split subkeys (key policy)
    un = jr.uniform(ku, (Wn, T), dtype=cdt)
    ush = un[:, jnp.minimum(ar, partner)]     # one uniform per pair
    acc = (jnp.log(ush) < la) & (partner != ar)[None, :]      # (Wn, T)

    def sw(a):
        aw = a.reshape((Wn, T) + a.shape[1:])
        ap = jnp.take(aw, partner, axis=1)
        m = acc.reshape(acc.shape + (1,) * (aw.ndim - 2))
        return jnp.where(m, ap, aw).reshape(a.shape)

    x, b, u = sw(x), sw(b), sw(u)
    # SA ladder update on the rungs whose pair was active this parity
    active = (partner[:-1] == ar[:-1] + 1)                    # (T-1,)
    pbar = jnp.mean(jnp.minimum(jnp.exp(la[:, :-1]), 1.0), axis=0)
    m = es["m"] + 1.0
    gain = spec.sa_gain / (1.0 + m / spec.sa_t0) ** 0.6
    lsp = es["lsp"] + gain * jnp.where(active,
                                       pbar - spec.swap_target, 0.0)
    # keep spacings in a sane band so a transient can't freeze or
    # explode the ladder (betas stay ordered by construction)
    lsp = jnp.clip(lsp, np.log(0.01), np.log(5.0))
    es = {**es, "lsp": lsp, "m": m,
          "swap_acc": es["swap_acc"] + jnp.sum(acc[:, :-1], axis=0)
          .astype(cdt),
          "swap_try": es["swap_try"] + jnp.where(active, float(Wn), 0.0)
          .astype(cdt)}
    return x, b, u, es


# ---------------------------------------------------------------------------
# the per-sweep stage

def ensemble_stage(cm, spec: EnsembleSpec, carry, es, kt, t):
    """Append the ensemble moves to one steady sweep: ASIS interweave
    (per chain), interchain stretch, then tempering swaps.  ``kt`` is
    the sweep-level ``fold_in(base_key, t)`` key; stage streams use
    tags >= C so they can never collide with the per-chain sweep
    streams ``fold_in(kt, c)``, c < C."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    x, b, u = carry
    C = x.shape[0]
    if spec.asis:
        ka = jr.fold_in(kt, C + 1)
        keys = jax.vmap(lambda c: jr.fold_in(ka, c))(jnp.arange(C))
        if spec.n_temps > 1:
            bet = chain_betas(spec, es, C).astype(cm.cdtype)
            x, b, u = jax.vmap(
                lambda xx, bb, uu, kk, be:
                asis_rho_redraw(cm, xx, bb, uu, kk, beta=be)
            )(x, b, u, keys, bet)
        else:
            x, b, u = jax.vmap(
                lambda xx, bb, uu, kk: asis_rho_redraw(cm, xx, bb, uu, kk)
            )(x, b, u, keys)
    if spec.stretch:
        x, nacc = stretch_rho_move(cm, spec, x, b,
                                   jr.fold_in(kt, C + 2))
        es = {**es,
              "stretch_acc": es["stretch_acc"] + nacc.astype(
                  es["stretch_acc"].dtype),
              "stretch_try": es["stretch_try"] + float(C // spec.n_temps)}
    if spec.n_temps > 1:
        x, b, u, es = pt_swap(cm, spec, x, b, u, es,
                              jr.fold_in(kt, C + 3), t)
    return (x, b, u), es


def ensemble_summary(spec: EnsembleSpec, es) -> dict:
    """Host-side roll-up of the ensemble counters for obs_summary /
    bench: per-rung swap rates, per-temperature stretch acceptance,
    and the current ladder."""
    lsp = np.asarray(es["lsp"], np.float64)
    betas = np.concatenate([[1.0], np.exp(-np.cumsum(np.exp(lsp)))])
    st = float(np.asarray(es["stretch_try"]))
    sacc = np.asarray(es["swap_acc"], np.float64)
    stry = np.asarray(es["swap_try"], np.float64)
    return {
        "n_temps": int(spec.n_temps),
        "stretch": bool(spec.stretch),
        "asis": bool(spec.asis),
        "stretch_a": float(spec.stretch_a),
        "betas": [float(v) for v in betas],
        "swap_rate": [float(a / max(n, 1.0))
                      for a, n in zip(sacc, stry)],
        "stretch_accept": [
            float(a / max(st, 1.0))
            for a in np.asarray(es["stretch_acc"], np.float64)],
        "sa_steps": float(np.asarray(es["m"])),
    }
