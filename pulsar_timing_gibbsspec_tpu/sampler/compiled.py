"""Host PTA model -> static device representation.

The reference pulls residuals, bases, ``Nvec`` and ``phi`` lazily out of
enterprise Python objects on every parameter draw (``pulsar_gibbs.py:
495-499``).  For a jit-compiled sweep everything the conditionals touch must
instead be *compiled once* into padded, stacked arrays plus pure functions
of the flat parameter vector ``x``:

- ragged per-pulsar shapes (71-720 TOAs, differing basis widths and backend
  counts across the 45 ``simulated_data/`` pulsars) are padded to common
  ``(P, Nmax)`` / ``(P, Bmax)`` shapes with masks, so the whole PTA is one
  SPMD batch a TPU mesh can shard over the pulsar axis (SURVEY §2.3)
- every hyperparameter reference becomes an integer gather into the
  "extended" vector ``xe = [x, constants, 0-sentinel]``, so varied vs fixed
  parameters (enterprise ``Constant``) need no control flow on device
- ``phi(x)`` is a scatter-add of per-GP-component contributions into the
  basis columns, mirroring ``SignalModel.get_phi`` with shared Fourier
  columns summing red + GW contributions

Padding conventions (chosen so pads are exact no-ops, not approximations):
TOA pads have ``y=0, T=0, sigma2=1, efac=1, equad=-40`` giving ``Nvec=1``
(zero log-likelihood contribution); basis-column pads have ``phi=1`` so
``Sigma`` gains a detached unit diagonal block whose Cholesky is trivial and
whose sampled ``b`` entries multiply zero basis columns; dropped scatter /
sentinel gather indices make missing components vanish.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import settings
from ..models import psd as psdmod
from ..models.priors import Constant, InvGamma, LinearExp, Normal, Uniform
from .blocks import BlockIndex, rho_bounds

#: prior-variance stand-in for "infinite" (marginalized timing-model
#: columns).  Enterprise uses 1e40 in f64, but TPU emulates f64 as an
#: f32 pair (double-double): full f64 *precision*, f32 *exponent range*
#: (~1e+-38, subnormals flushed to 0).  1e30 stays inside that range on
#: every backend while remaining >=1e12 times any physical phi.
BIG_PHI = {"f32": 1e30, "f64": 1e30}
#: floor used where a red process has fewer modes than the GW grid
#: (reference pads with a negligible value, see numpy_backend
#: ``_red_phi_at_gw_freqs``).  1e-30 rather than 1e-40: the latter is a
#: float32 subnormal, which the TPU flushes to 0 (making 1/phi = inf);
#: 1e-30 is still <=1e-12 of any physical phi (rho in [1e-18, 1e-8]).
PHI_FLOOR = 1e-30

_LN10 = np.log(10.0)
_LN12PI2 = np.log(12.0 * np.pi ** 2)
_LNFYR = np.log(psdmod.FYR)


def _softplus(z):
    import jax.numpy as jnp

    return jnp.logaddexp(0.0, z)


# Log-space PSD evaluation: the host functions in models/psd.py are exact in
# float64, but their intermediates (A**2 ~ 1e-40, f**-gamma ~ 1e50) underflow
# and overflow float32, producing 0 * inf = NaN.  On device every member of
# the powerlaw family is therefore evaluated as exp(log phi), whose log-space
# intermediates span only ~[-100, 100].

def _lnphi_powerlaw(f, df, log10_A, gamma):
    import jax.numpy as jnp

    return (2.0 * _LN10 * log10_A - _LN12PI2 + (gamma - 3.0) * _LNFYR
            - gamma * jnp.log(f) + jnp.log(df))


def _lnphi_turnover(f, df, log10_A, gamma, lf0=-8.5,
                    kappa=10.0 / 3.0, beta=0.5):
    import jax.numpy as jnp

    lnf = jnp.log(f)
    lnhc = (_LN10 * log10_A + 0.5 * (3.0 - gamma) * (lnf - _LNFYR)
            - beta * _softplus(kappa * (_LN10 * lf0 - lnf)))
    return 2.0 * lnhc - _LN12PI2 - 3.0 * lnf + jnp.log(df)


def _lnphi_broken_powerlaw(f, df, log10_A, gamma, delta=0.0,
                           log10_fb=-8.5, kappa=0.1):
    import jax.numpy as jnp

    lnf = jnp.log(f)
    lnhc = (_LN10 * log10_A + 0.5 * (3.0 - gamma) * (lnf - _LNFYR)
            + 0.5 * kappa * (gamma - delta)
            * _softplus((lnf - _LN10 * log10_fb) / kappa))
    return 2.0 * lnhc - _LN12PI2 - 3.0 * lnf + jnp.log(df)


def _lnphi_turnover_knee(f, df, log10_A, gamma, lfb=-8.5, lfk=-8.0,
                         kappa=10.0 / 3.0, delta=0.1):
    import jax.numpy as jnp

    lnf = jnp.log(f)
    lnhc = (_LN10 * log10_A + 0.5 * (3.0 - gamma) * (lnf - _LNFYR)
            + _softplus(delta * (lnf - _LN10 * lfk))
            - 0.5 * _softplus(kappa * (_LN10 * lfb - lnf)))
    return 2.0 * lnhc - _LN12PI2 - 3.0 * lnf + jnp.log(df)


def _lnphi_powerlaw_breakflat(f, df, log10_A, gamma, log10_fb):
    import jax.numpy as jnp

    lnf = jnp.minimum(jnp.log(f), _LN10 * log10_fb)
    return (2.0 * _LN10 * log10_A - _LN12PI2 + (gamma - 3.0) * _LNFYR
            - gamma * lnf + jnp.log(df))


_LNPSD_FNS = {
    "powerlaw": _lnphi_powerlaw,
    "turnover": _lnphi_turnover,
    "turnover_knee": _lnphi_turnover_knee,
    "broken_powerlaw": _lnphi_broken_powerlaw,
    "powerlaw_breakflat": _lnphi_powerlaw_breakflat,
}


@dataclasses.dataclass
class GPComponent:
    """One Fourier-GP / ECORR component, stacked over pulsars.

    ``cols`` are indices into the padded basis axis (pad = Bmax, dropped on
    scatter); ``rho_ix``/``hyp_ix`` are gathers into ``xe`` (pad = sentinel).
    """

    kind: str                  # psd name, or 'ecorr'
    cols: object               # (P, W) int32
    f: object                  # (P, W) per-column frequency (powerlaw family)
    df: object                 # (P, W) per-column bin width
    hyp_ix: object             # (P, H) int32, powerlaw-family hyper refs
    rho_ix: object             # (P, W) int32, free-spectrum/ecorr refs


@dataclasses.dataclass
class CompiledPTA:
    """Static device model.  Arrays are jax on first use; built as NumPy."""

    # -- static shape info ---------------------------------------------------
    P: int                     # padded pulsar count
    P_real: int                # true pulsar count
    Nmax: int
    Bmax: int
    nx: int                    # number of free parameters
    K: int                     # GW frequency count (0 if no gw signal)
    Kr: int                    # red frequency count (0 if none)
    widths: tuple              # true basis width per real pulsar
    param_names: tuple
    dtype: object              # storage dtype of the large arrays
    cdtype: object             # compute dtype (state, reductions, solves)
    # -- data ----------------------------------------------------------------
    y: object                  # (P, Nmax)
    T: object                  # (P, Nmax, Bmax)
    toa_mask: object           # (P, Nmax)
    basis_mask: object         # (P, Bmax)
    psr_mask: object           # (P,)
    sigma2: object             # (P, Nmax)
    efac_ix: object            # (P, Nmax) -> xe
    equad_ix: object           # (P, Nmax) -> xe
    gequad_ix: object          # (P, Nmax) -> xe (global EQUAD; off pad)
    const_pool: object         # (npool,)
    phi_base: object           # (P, Bmax)
    components: list
    # -- priors --------------------------------------------------------------
    pkind: object              # (nx,) 0 uniform / 1 normal / 2 linexp
    pa: object                 # (nx,) pmin or mu
    pb: object                 # (nx,) pmax or sigma
    prop_scale: object         # (nx,) base proposal sd: 0.1x prior width
    # -- Gibbs blocks --------------------------------------------------------
    idx: BlockIndex
    # -- GW / red conditional metadata ---------------------------------------
    gw_sin_ix: object          # (P, K) -> b columns
    gw_cos_ix: object          # (P, K)
    gw_f: object               # (P, K) per-frequency
    gw_df: object              # (P, K)
    gw_kind: str               # 'free_spectrum' | powerlaw family | ''
    gw_hyp_ix: object          # (P, H)
    gw_rho_ix: object          # (P, K) -> xe (spectrum only)
    rho_ix_x: object           # (K,) -> x, common rho write-back
    red_valid: object          # (P,) 1.0 where the pulsar has intrinsic red
    red_kind: str
    red_hyp_ix: object         # (P, H)
    red_rho_ix: object         # (P, Kr) -> xe
    red_rho_ix_x: object       # (P, Kr) -> x, per-pulsar rho write-back
    red_sin_ix: object         # (P, Kr) -> b columns (red signal's own grid)
    red_cos_ix: object         # (P, Kr)
    ec_cols: object            # (P, We) -> b columns (pad Bmax)
    ec_ix: object              # (P, We) -> xe
    #: per-pulsar positions (in x) of that pulsar's white-noise parameters
    #: (pad nx) and their counts — the white conditional factorizes over
    #: pulsars given b, so the device backend runs P independent
    #: single-site MH chains in parallel (one per pulsar)
    white_par_ix: object       # (P, Wp)
    white_nper: object         # (P,)
    ecorr_par_ix: object       # (P, Ep)
    ecorr_nper: object         # (P,)
    rhomin: float
    rhomax: float
    red_rhomin: float
    red_rhomax: float
    #: common-process ORF: 'crn' keeps the per-pulsar block-diagonal path;
    #: any other positive-definite ORF (hd/freq_hd/st/gw_monopole/
    #: gw_dipole) activates the joint cross-pulsar b-draw and the
    #: quadratic-form rho conditional (rank-deficient ORFs are rejected
    #: in orf_ginv_stack)
    orf_name: str = "crn"
    orf_Ginv: object = None    # (K, P, P) per-frequency inverse ORF stack
                               # (identity pads; constant over K for fixed
                               # ORFs, varying for freq_hd)
    #: (P, Bmax) 1.0 on Fourier/chromatic GP columns — the coefficient
    #: set whose N(0, phi(x)) prior is the generic b-conditional
    #: likelihood of the powerlaw-family hyper MH block
    gp_mask: object = None
    red_f: object = None       # (P, Kr) red-grid frequencies (tprocess)
    red_df: object = None      # (P, Kr) red-grid bin widths
    #: parameterized ORF (bin_orf / legendre_orf): linear basis stack
    #: G(theta) = I + sum_j theta_j B_j (identity pads) and the gather of
    #: theta out of x.  None for fixed ORFs (orf_Ginv is static then).
    orf_B: object = None       # (J, P, P)
    orf_par_ix: object = None  # (J,) -> x
    #: True when intrinsic red and the common process share basis columns
    #: (the CRN layout); False for correlated ORFs, whose processes keep
    #: their own columns — then the red conditionals see no gw 'other'
    red_shares_gw: bool = True
    #: kernel-ECORR execution mode (``ecorrsample='kernel'``): the epoch
    #: blocks live inside N (Woodbury) instead of sampled basis columns.
    #: Marginally identical to basis ECORR — ``N = D + U c U^T`` with
    #: disjoint epoch indicators U is what the basis representation
    #: integrates to — so the two modes are KS-cross-validated against
    #: each other.  ``ke_eid[p, i]`` is TOA i's epoch id (Emax = dummy
    #: slot for TOAs outside every epoch and pads), ``ke_par_ix[p, e]``
    #: gathers the owning backend's log10_ecorr out of xe (dummy epochs
    #: point at the -40 constant, whose 10^(2*.) underflows to a zero
    #: correction).  None when the mode is off.
    ke_eid: object = None      # (P, Nmax) int32 -> [0, Emax]
    ke_par_ix: object = None   # (P, Emax) int32 -> xe

    @property
    def has_ke(self) -> bool:
        return self.ke_eid is not None

    # =======================================================================
    # device-side pure functions (jit/vmap-safe; arrays close over as consts)
    # =======================================================================

    @property
    def sentinel(self):
        """Index of the fixed 0.0 slot in ``xe`` (pad gathers land here)."""
        return self.nx

    def xe(self, x):
        import jax.numpy as jnp

        return jnp.concatenate([
            jnp.asarray(x, dtype=self.cdtype),
            jnp.zeros(1, dtype=self.cdtype),
            jnp.asarray(self.const_pool, dtype=self.cdtype)])

    def ndiag(self, x):
        """(P, Nmax) diagonal measurement covariance
        (``WhiteNoiseSignal.get_ndiag`` compiled to three gathers)."""
        xev = self.xe(x)
        efac = xev[self.efac_ix]
        equad = xev[self.equad_ix]
        gequad = xev[self.gequad_ix]
        return (efac * efac * self.sigma2 + 10.0 ** (2.0 * equad)
                + 10.0 ** (2.0 * gequad))

    def ndiag_fast(self, x):
        """(P, Nmax) measurement covariance in the *storage* dtype — the
        whitened b-draw only consumes the O(1) ratio ``sigma^2/N``."""
        xev = self.xe(x).astype(self.dtype)
        efac = xev[self.efac_ix]
        equad = xev[self.equad_ix]
        gequad = xev[self.gequad_ix]
        return (efac * efac * self.sigma2 + 10.0 ** (2.0 * equad)
                + 10.0 ** (2.0 * gequad))

    def _phi_accum(self, x, base, comps, dtype=None):
        """Scatter-add the selected components' variances onto ``base``."""
        import jax.numpy as jnp

        dtype = dtype or self.cdtype
        xev = self.xe(x).astype(dtype)
        phi = jnp.asarray(base, dtype=dtype)
        rows = jnp.arange(self.P)[:, None]
        for c in comps:
            if c.kind in ("free_spectrum", "ecorr"):
                vals = 10.0 ** (2.0 * xev[c.rho_ix])
            elif c.kind == "infinitepower":
                vals = jnp.full(c.cols.shape, BIG_PHI["f32"], dtype)
            elif c.kind == "tprocess":
                # powerlaw scaled by per-frequency InvGamma alphas
                # (rho_ix carries the alpha gathers, one per column)
                args = [xev[c.hyp_ix[:, h]][:, None]
                        for h in range(c.hyp_ix.shape[1])]
                vals = jnp.exp(_lnphi_powerlaw(c.f, c.df, *args)) \
                    * xev[c.rho_ix]
            else:
                fn = _LNPSD_FNS[c.kind]
                args = [xev[c.hyp_ix[:, h]][:, None]
                        for h in range(c.hyp_ix.shape[1])]
                vals = jnp.exp(fn(c.f, c.df, *args))
            # c.f/c.df are stored f64, so powerlaw-family vals promote to
            # f64; cast before the scatter (f64->f32 scatter is a
            # FutureWarning today and a hard error in future JAX)
            phi = phi.at[rows, c.cols].add(vals.astype(dtype), mode="drop")
        return phi

    def phi(self, x, dtype=None):
        """(P, Bmax) per-column prior variance (pads = 1)."""
        import jax.numpy as jnp

        phi = self._phi_accum(x, self.phi_base, self.components, dtype)
        # powerlaw-family phi can underflow to exactly 0 at prior corners
        # (e.g. log10_A = -20: exp(lnphi) ~ 1e-44 flushes to 0 under the
        # TPU's f32-exponent-range f64), which would make 1/phi = inf in
        # the b-draw; the floor is sampling-neutral (see PHI_FLOOR)
        return jnp.maximum(phi, PHI_FLOOR)

    def phi_hyper_split(self, x, dtype=None):
        """``(static, dyn_fn)``: the part of phi that is constant while
        only powerlaw-family hypers move (free-spectrum rho, ECORR — their
        parameters belong to other Gibbs blocks), evaluated once, plus a
        function accumulating the hyper-dependent part.  Lets the MH block
        avoid re-evaluating every component per step."""
        stat_comps = [c for c in self.components
                      if c.kind in ("free_spectrum", "ecorr")]
        dyn_comps = [c for c in self.components
                     if c.kind not in ("free_spectrum", "ecorr")]
        static = self._phi_accum(x, self.phi_base, stat_comps, dtype)

        def dyn(q):
            import jax.numpy as jnp

            return jnp.maximum(
                self._phi_accum(q, static, dyn_comps, dtype), PHI_FLOOR)

        return static, dyn

    def lnprior(self, x):
        import jax.numpy as jnp

        x = jnp.asarray(x, dtype=self.cdtype)
        inside = (x >= self.pa) & (x <= self.pb)
        ninf = jnp.array(-jnp.inf, dtype=self.cdtype)
        lp_u = jnp.where(inside, -jnp.log(self.pb - self.pa), ninf)
        lp_n = (-0.5 * ((x - self.pa) / self.pb) ** 2
                - jnp.log(self.pb * np.sqrt(2.0 * np.pi)))
        dens = (np.log(10.0) * 10.0 ** x
                / (10.0 ** self.pb - 10.0 ** self.pa))
        lp_l = jnp.where(inside, jnp.log(dens), ninf)
        from jax.scipy.special import gammaln

        xp = jnp.maximum(x, 1e-30)
        lp_g = jnp.where(
            x > 0,
            self.pa * jnp.log(self.pb) - gammaln(self.pa)
            - (self.pa + 1.0) * jnp.log(xp) - self.pb / xp, ninf)
        per = jnp.where(self.pkind == 0, lp_u,
                        jnp.where(self.pkind == 1, lp_n,
                                  jnp.where(self.pkind == 2, lp_l, lp_g)))
        return jnp.sum(per)

    def coord_logpdf(self, j, v):
        """Prior log-density of value ``v`` for coordinate ``j`` (both
        arbitrary-shaped arrays) — single-site MH needs only the changed
        coordinate's prior delta, not the full ``lnprior``."""
        import jax.numpy as jnp

        j = jnp.minimum(j, self.nx - 1)
        dt = jnp.asarray(v).dtype
        kind = jnp.asarray(self.pkind)[j]
        a = jnp.asarray(self.pa, dtype=dt)[j]
        b_ = jnp.asarray(self.pb, dtype=dt)[j]
        inside = (v >= a) & (v <= b_)
        ninf = jnp.array(-jnp.inf, dtype=dt)
        lp_u = jnp.where(inside, -jnp.log(b_ - a), ninf)
        lp_n = (-0.5 * ((v - a) / b_) ** 2
                - jnp.log(b_ * np.sqrt(2.0 * np.pi)))
        dens = np.log(10.0) * 10.0 ** v / (10.0 ** b_ - 10.0 ** a)
        lp_l = jnp.where(inside, jnp.log(dens), ninf)
        from jax.scipy.special import gammaln

        vp = jnp.maximum(v, 1e-30)
        lp_g = jnp.where(v > 0, a * jnp.log(b_) - gammaln(a)
                         - (a + 1.0) * jnp.log(vp) - b_ / vp, ninf)
        return jnp.where(kind == 0, lp_u,
                         jnp.where(kind == 1, lp_n,
                                   jnp.where(kind == 2, lp_l, lp_g)))

    def orf_G(self, x):
        """(P, P) ORF correlation matrix at the current state (sampled
        weights); only valid for parameterized ORFs."""
        import jax.numpy as jnp

        th = jnp.asarray(x, self.cdtype)[self.orf_par_ix]
        return (jnp.eye(self.P, dtype=self.cdtype)
                + jnp.einsum("j,jpq->pq", th,
                             jnp.asarray(self.orf_B, self.cdtype)))

    def orf_ginv_k(self, x):
        """(K, P, P) inverse ORF stack at the current state: the stored
        static stack for fixed ORFs, rebuilt from the sampled weights for
        parameterized ones (the sampler keeps theta inside the PD region,
        so the inverse is well-defined at chain states).

        Via the blocked Cholesky inverse, not ``jnp.linalg.inv``: TPU's
        XLA has no f64 LuDecomposition lowering, and G is SPD anyway."""
        import jax.numpy as jnp

        if self.orf_B is None:
            return jnp.asarray(self.orf_Ginv, self.cdtype)
        from ..ops.linalg import blocked_chol_inv

        _, Li = blocked_chol_inv(self.orf_G(x))
        Gi = Li.T @ Li                      # (L L^T)^-1 = L^-T L^-1
        return jnp.broadcast_to(Gi, (max(self.K, 1), self.P, self.P))

    def gw_cols_valid(self):
        """``(cols, valid, ccl)`` for the GW coefficient columns in
        group-major order — the shared gather layout of every
        correlated-ORF b-draw kernel (joint/sequential/freqblock):

        - ``cols``  ``(P, 2K)`` int32: per-pulsar b-column of GW group
          ``t`` (groups ordered ``[sin k=0..K-1 | cos k=0..K-1]``;
          out-of-range entries mark pulsars without that frequency);
        - ``valid`` ``(P, 2K)`` cdtype: in-range indicator;
        - ``ccl``   ``(P, 2K)``: clipped gather-safe indices (gathers
          through ``ccl`` must be masked by ``valid`` — a clipped slot
          can collide with a real column).
        """
        import jax.numpy as jnp

        gsin = jnp.asarray(self.gw_sin_ix, jnp.int32)
        gcos = jnp.asarray(self.gw_cos_ix, jnp.int32)
        cols = jnp.concatenate([gsin, gcos], axis=1)
        valid = ((cols >= 0) & (cols < self.Bmax)).astype(self.cdtype)
        ccl = jnp.clip(cols, 0, self.Bmax - 1)
        return cols, valid, ccl

    def gw_tau(self, b):
        """(P, K) per-frequency ``(b_sin^2 + b_cos^2)/2``
        (reference ``pulsar_gibbs.py:208-209``)."""
        import jax.numpy as jnp

        bs = jnp.take_along_axis(b, self.gw_sin_ix, axis=1)
        bc = jnp.take_along_axis(b, self.gw_cos_ix, axis=1)
        return 0.5 * (bs * bs + bc * bc)

    def gw_phi(self, x):
        """(P, K) GW prior variance per frequency (phi at the sin columns)."""
        import jax.numpy as jnp

        xev = self.xe(x)
        if self.gw_kind == "free_spectrum":
            return 10.0 ** (2.0 * xev[self.gw_rho_ix])
        fn = _LNPSD_FNS[self.gw_kind]
        args = [xev[self.gw_hyp_ix[:, h]][:, None]
                for h in range(self.gw_hyp_ix.shape[1])]
        return jnp.exp(fn(self.gw_f, self.gw_df, *args))

    def red_tau(self, b):
        """(P, Kr) per-frequency coefficient power on the *red* signal's own
        columns — distinct from :meth:`gw_tau` when the red process has more
        modes than the common one."""
        import jax.numpy as jnp

        bs = jnp.take_along_axis(b, self.red_sin_ix, axis=1)
        bc = jnp.take_along_axis(b, self.red_cos_ix, axis=1)
        return 0.5 * (bs * bs + bc * bc)

    def gw_phi_at_red(self, x):
        """(P, Kr) common-process phi aligned to the red frequency grid —
        the 'other' variance on the red signal's columns.  Floored at
        PHI_FLOOR beyond the common mode count (the mirror image of
        :meth:`red_phi`), and floored EVERYWHERE when the common process
        lives on its own columns (correlated ORFs): disjoint columns carry
        no shared variance."""
        import jax.numpy as jnp

        Kr = self.red_rho_ix_x.shape[1]
        out = jnp.full((self.P, Kr), PHI_FLOOR, dtype=self.cdtype)
        if self.K and self.red_shares_gw:
            n = min(self.K, Kr)
            out = out.at[:, :n].set(self.gw_phi(x)[:, :n])
        return out

    def red_phi(self, x):
        """(P, K) intrinsic-red prior variance aligned to the GW grid,
        floored at PHI_FLOOR beyond each pulsar's red mode count / where the
        pulsar has no red process (oracle ``_red_phi_at_gw_freqs``)."""
        import jax.numpy as jnp

        xev = self.xe(x)
        k = jnp.arange(self.K)
        if self.red_kind == "" or not self.red_shares_gw:
            # no red at all, or red on disjoint columns (correlated
            # common process): the gw columns carry no red variance
            return jnp.full((self.P, self.K), PHI_FLOOR, dtype=self.cdtype)
        if self.red_kind == "infinitepower":
            out = jnp.where(jnp.arange(self.K)[None, :] < self.Kr,
                            BIG_PHI["f32"], PHI_FLOOR)
            return jnp.where(self.red_valid[:, None] > 0, out, PHI_FLOOR)
        if self.red_kind == "free_spectrum":
            Kr = self.red_rho_ix.shape[1]
            vals = 10.0 ** (2.0 * xev[self.red_rho_ix])  # (P, Kr)
            out = jnp.full((self.P, self.K), PHI_FLOOR, dtype=self.cdtype)
            n = min(self.K, Kr)
            out = out.at[:, :n].set(vals[:, :n])
        elif self.red_kind == "tprocess":
            args = [xev[self.red_hyp_ix[:, h]][:, None] for h in range(2)]
            vals = (jnp.exp(_lnphi_powerlaw(self.red_f, self.red_df, *args))
                    * xev[self.red_rho_ix])              # (P, Kr)
            out = jnp.full((self.P, self.K), PHI_FLOOR, dtype=self.cdtype)
            n = min(self.K, self.red_rho_ix.shape[1])
            out = out.at[:, :n].set(jnp.maximum(vals[:, :n], PHI_FLOOR))
        else:
            fn = _LNPSD_FNS[self.red_kind]
            args = [xev[self.red_hyp_ix[:, h]][:, None]
                    for h in range(self.red_hyp_ix.shape[1])]
            vals = jnp.exp(fn(self.gw_f, self.gw_df, *args))
            out = jnp.where(k[None, :] < self.Kr, vals, PHI_FLOOR)
        return jnp.where(self.red_valid[:, None] > 0, out, PHI_FLOOR)


# ===========================================================================
# pytree registration: CompiledPTA as a jit ARGUMENT
# ===========================================================================
#
# Closure-captured jax.Arrays are lowered as replicated constants — GSPMD
# drops their shardings entirely (measured: a jit-captured pulsar-sharded
# basis compiles to zero collective ops, i.e. every device computes the
# whole model).  Single-chip drivers may keep the closure style, but any
# multi-device path MUST pass the sharded CompiledPTA *as an argument* so
# the compiled program sees the pulsar-axis shardings and inserts the
# mesh collectives (`__graft_entry__.dryrun_multichip` asserts this on
# the optimized HLO).  Registering the dataclass as a pytree makes that
# an ordinary function argument: array fields are leaves, everything
# else rides an identity-hashed static box (stable per instance, so
# repeated calls with the same model hit the jit cache).

_CM_ARRAY_FIELDS = (
    "y", "T", "toa_mask", "basis_mask", "psr_mask", "sigma2",
    "efac_ix", "equad_ix", "gequad_ix", "const_pool", "phi_base",
    "components", "pkind", "pa", "pb", "prop_scale",
    "gw_sin_ix", "gw_cos_ix", "gw_f", "gw_df", "gw_hyp_ix", "gw_rho_ix",
    "rho_ix_x", "red_valid", "red_hyp_ix", "red_rho_ix", "red_rho_ix_x",
    "red_sin_ix", "red_cos_ix", "ec_cols", "ec_ix",
    "white_par_ix", "white_nper", "ecorr_par_ix", "ecorr_nper",
    "orf_Ginv", "gp_mask", "red_f", "red_df", "orf_B", "orf_par_ix",
    "ke_eid", "ke_par_ix",
)
_CM_STATIC_FIELDS = tuple(
    f.name for f in dataclasses.fields(CompiledPTA)
    if f.name not in _CM_ARRAY_FIELDS)


class _StaticBox:
    """Identity-hashed aux-data carrier: jit cache keys compare by
    instance, and the box is memoized on the CompiledPTA so repeated
    flattens of one model stay cache-stable."""

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


def _cm_flatten(cm):
    children = tuple(getattr(cm, n) for n in _CM_ARRAY_FIELDS)
    box = cm.__dict__.get("_staticbox")
    if box is None:
        box = _StaticBox({n: getattr(cm, n) for n in _CM_STATIC_FIELDS})
        cm.__dict__["_staticbox"] = box
    return children, box


def _cm_unflatten(box, children):
    kw = dict(box.data)
    kw.update(zip(_CM_ARRAY_FIELDS, children))
    cm = CompiledPTA(**kw)
    cm.__dict__["_staticbox"] = box
    return cm


def _gp_flatten(c):
    return (c.cols, c.f, c.df, c.hyp_ix, c.rho_ix), c.kind


def _gp_unflatten(kind, children):
    return GPComponent(kind, *children)


def _register_pytrees():
    from jax import tree_util

    tree_util.register_pytree_node(CompiledPTA, _cm_flatten, _cm_unflatten)
    tree_util.register_pytree_node(GPComponent, _gp_flatten, _gp_unflatten)


_register_pytrees()


def _as_i32(a):
    return np.asarray(a, dtype=np.int32)


def compile_pta(pta, pad_pulsars: int | None = None,
                kernel_ecorr: bool = False,
                pad_toas: int | None = None,
                pad_basis: int | None = None) -> CompiledPTA:
    """Compile a host :class:`~..models.pta.PTA` into a CompiledPTA.

    ``pad_pulsars``: total pulsar-axis length (>= len(pta.pulsars)); extra
    slots are inert dummy pulsars so the axis divides a device-mesh size.

    ``pad_toas`` / ``pad_basis``: force the TOA axis (``Nmax``) and basis
    axis (``Bmax``) to a fixed length at least the data-derived maximum.
    Pad TOA rows carry y=0, T=0, sigma2=1, constant efac=1 and
    equad=-40 (Nvec=1, zero masked log-likelihood) and pad basis columns
    carry phi_base=1 with basis_mask=0, so forcing larger axes is exact —
    the serve/ bucket router uses this to land heterogeneous datasets on
    one compiled program shape.

    ``kernel_ecorr``: execute ECORR epoch blocks inside N (Woodbury, the
    reference's ``ecorrsample='kernel'`` semantics — its own path is dead
    code at ``pulsar_gibbs.py:409-486``) instead of as sampled basis
    columns.  The ECORR basis columns are dropped from T (they are always
    the trailing block, see ``models/pta.py`` layout) and the per-TOA
    epoch structure is compiled into ``ke_eid``/``ke_par_ix``.
    """
    settings.apply()
    np_dtype = np.float64 if settings.precision == "f64" else np.float32
    np_cdtype = (np.float64 if settings.compute_precision == "f64"
                 else np_dtype)
    big_phi = BIG_PHI[settings.precision if settings.precision in BIG_PHI
                      else "f32"]

    names = list(pta.param_names)
    nx = len(names)
    pos = {nm: ii for ii, nm in enumerate(names)}
    pool: list = []

    sentinel = nx  # fixed 0.0 slot in xe = [x, 0, const_pool]

    def const_ref(value):
        pool.append(float(value))
        return nx + 1 + len(pool) - 1

    def ref(p, elem=None):
        """xe index of a scalar parameter (or element of a vector one)."""
        if isinstance(p, Constant):
            return const_ref(p.value)
        nm = p.name if elem is None else f"{p.name}_{elem}"
        return pos[nm]

    models = [pta.model(ii) for ii in range(len(pta.pulsars))]
    P_real = len(models)
    P = pad_pulsars or P_real
    if P < P_real:
        raise ValueError("pad_pulsars smaller than the pulsar count")
    Nmax = max(m.pulsar.ntoa for m in models)
    if pad_toas is not None:
        if pad_toas < Nmax:
            raise ValueError(
                f"pad_toas={pad_toas} smaller than the largest TOA count "
                f"{Nmax}")
        Nmax = int(pad_toas)
    if kernel_ecorr and not any(m._ecorr for m in models):
        raise ValueError(
            "ecorrsample='kernel' requested but the model has no ECORR "
            "signal (build with white_vary=True on NANOGrav-flagged data)")

    def _width(m):
        # kernel mode: ECORR columns (always the trailing basis block) are
        # not sampled — they live inside N via Woodbury
        if kernel_ecorr and m._ecorr:
            return m._slices[m._ecorr[0].name].start
        return m.get_basis().shape[1]

    widths = tuple(_width(m) for m in models)
    Bmax = max(widths)
    if pad_basis is not None:
        if pad_basis < Bmax:
            raise ValueError(
                f"pad_basis={pad_basis} smaller than the widest basis "
                f"{Bmax}")
        Bmax = int(pad_basis)

    efac1 = const_ref(1.0)
    equad_off = const_ref(-40.0)

    y = np.zeros((P, Nmax), np_dtype)
    T = np.zeros((P, Nmax, Bmax), np_dtype)
    toa_mask = np.zeros((P, Nmax), np_dtype)
    basis_mask = np.zeros((P, Bmax), np_dtype)
    psr_mask = np.zeros(P, np_dtype)
    sigma2 = np.ones((P, Nmax), np_dtype)
    efac_ix = np.full((P, Nmax), efac1, np.int32)
    equad_ix = np.full((P, Nmax), equad_off, np.int32)
    gequad_ix = np.full((P, Nmax), equad_off, np.int32)
    phi_base = np.ones((P, Bmax), np_dtype)

    gp_mask = np.zeros((P, Bmax), np_dtype)

    for ii, m in enumerate(models):
        n, w = m.pulsar.ntoa, widths[ii]
        for s in m._fourier + m._chrom:
            sl_ = m._slices[s.name]
            gp_mask[ii, sl_.start:sl_.stop] = 1.0
        y[ii, :n] = m.pulsar.residuals
        T[ii, :n, :w] = m.get_basis()[:, :w]
        toa_mask[ii, :n] = 1.0
        basis_mask[ii, :w] = 1.0
        psr_mask[ii] = 1.0
        sigma2[ii, :n] = m.pulsar.toaerrs ** 2
        if m.white is not None:
            for lab, mask in m.white._masks.items():
                where = np.where(mask)[0]
                efac_ix[ii, where] = ref(m.white._efac[lab])
                if m.white._equad:
                    equad_ix[ii, where] = ref(m.white._equad[lab])
            if m.white._gequad is not None:
                gequad_ix[ii, :n] = ref(m.white._gequad)
        # static marginalized bases: constant prior variance per column —
        # effectively-infinite for timing-model/dm_annual columns, finite
        # Gaussian prior variances for BayesEphem-style physical bases
        # (clipped into the TPU-safe exponent range either way)
        for s in m._timing:
            sl_ = m._slices[s.name]
            phi_base[ii, sl_] = np.clip(s.get_phi({}), PHI_FLOOR, big_phi)
        # GP columns start at 0 and accumulate component contributions
        # (kernel mode: the ECORR columns are dropped, and touching their
        # now-out-of-range slice would zero pad columns whose phi must be 1)
        ecs = [] if kernel_ecorr else m._ecorr
        for s in m._fourier + m._chrom + ecs:
            sl_ = m._slices[s.name]
            phi_base[ii, sl_.start:sl_.stop] = 0.0

    # ---- GP components, grouped by position in the per-model signal lists --
    components: list = []
    n_fourier = {len(m._fourier) for m in models}
    if len(n_fourier) > 1:
        raise ValueError("pulsars disagree on Fourier signal count; the "
                         "compiled batch requires a homogeneous model "
                         "(build with model_general)")

    comp_specs = []  # (kind, per-pulsar (cols, f, df, hyp_refs, rho_refs))
    for c in range(n_fourier.pop() if n_fourier else 0):
        kinds = {m._fourier[c].psd_name for m in models}
        if len(kinds) > 1:
            raise ValueError(f"Fourier signal #{c} has mixed PSDs {kinds}")
        kind = kinds.pop()
        rows = []
        for m in models:
            s = m._fourier[c]
            sl_ = m._slices[s.name]
            cols = np.arange(sl_.start, sl_.stop)
            f, df = s.freqs, s._df
            psd_ps = getattr(s, "psd_params", s.params)
            hyp, rho = [], []
            if kind == "free_spectrum":
                p = psd_ps[0]
                rho = [ref(p, elem=j // 2) for j in range(len(cols))]
            elif kind == "tprocess":
                hyp = [ref(p) for p in psd_ps[:2]]         # log10_A, gamma
                alphas = psd_ps[2]
                rho = [ref(alphas, elem=j // 2) for j in range(len(cols))]
            else:
                hyp = [ref(p) for p in psd_ps]
            rows.append((cols, f, df, hyp, rho))
        comp_specs.append((kind, rows))
    # chromatic GPs (DM, scattering): own columns, same component machinery
    n_chrom = {len(m._chrom) for m in models}
    if len(n_chrom) > 1:
        raise ValueError("pulsars disagree on chromatic signal count; the "
                         "compiled batch requires a homogeneous model "
                         "(build with model_general)")
    for c in range(n_chrom.pop() if n_chrom else 0):
        kinds = {m._chrom[c].psd_name for m in models}
        if len(kinds) > 1:
            raise ValueError(f"chromatic signal #{c} has mixed PSDs {kinds}")
        kind = kinds.pop()
        if kind == "free_spectrum":
            raise NotImplementedError(
                "free-spectrum chromatic GPs have no conditional sampler "
                "block; use a powerlaw-family PSD")
        rows = []
        for m in models:
            s = m._chrom[c]
            sl_ = m._slices[s.name]
            rows.append((np.arange(sl_.start, sl_.stop), s.freqs, s._df,
                         [ref(p) for p in s.params], []))
        comp_specs.append((kind, rows))
    ec_rows = []
    for m in models:
        if m._ecorr and not kernel_ecorr:
            s = m._ecorr[0]
            sl_ = m._slices[s.name]
            cols = np.arange(sl_.start, sl_.stop)
            refs = [ref(s._by_backend[lab]) for lab in s._owners]
            ec_rows.append((cols, refs))
        else:
            ec_rows.append((np.zeros(0, np.int64), []))

    # ---- kernel-ECORR epoch structure --------------------------------------
    ke_eid = ke_par_ix = None
    if kernel_ecorr:
        Emax = max((m._ecorr[0]._U.shape[1] if m._ecorr else 0)
                   for m in models)
        # dummy epoch: id Emax, parameter = the -40 constant, so its
        # c = 10^-80 correction underflows (f32 exponent range) to zero
        ke_eid = np.full((P, Nmax), Emax, np.int32)
        ke_par_ix = np.full((P, max(Emax, 1)), equad_off, np.int32)
        for ii, m in enumerate(models):
            if not m._ecorr:
                continue
            s = m._ecorr[0]
            U = s._U                                    # (ntoa, E)
            n = m.pulsar.ntoa
            in_epoch = U.sum(axis=1) > 0
            ke_eid[ii, :n] = np.where(in_epoch, U.argmax(axis=1), Emax)
            for e, lab in enumerate(s._owners):
                ke_par_ix[ii, e] = ref(s._by_backend[lab])
    if any(len(r[0]) for r in ec_rows):
        comp_specs.append(("ecorr", [
            (cols, np.zeros(len(cols)), np.zeros(len(cols)), [], refs)
            for cols, refs in ec_rows]))

    def pad2(rows, fill, w=None):
        w = w if w is not None else max((len(r) for r in rows), default=0)
        out = np.full((P, w), fill)
        for ii, r in enumerate(rows):
            out[ii, :len(r)] = r
        return out

    for kind, rows in comp_specs:
        W = max(len(r[0]) for r in rows)
        H = max((len(r[3]) for r in rows), default=0)
        components.append(GPComponent(
            kind=kind,
            cols=_as_i32(pad2([r[0] for r in rows], Bmax, W)),
            f=pad2([r[1] for r in rows], 1.0, W).astype(np_dtype),
            df=pad2([r[2] for r in rows], 0.0, W).astype(np_dtype),
            hyp_ix=_as_i32(pad2([r[3] for r in rows], sentinel, H)),
            rho_ix=_as_i32(pad2([r[4] for r in rows], sentinel, W)),
        ))

    # ---- GW / red conditional metadata -------------------------------------
    gw_kind = red_kind = ""
    K = Kr = 0
    gw_sin = gw_cos = gw_f = gw_df = gw_hyp = gw_rho = None
    red_hyp = red_rho = red_rho_x = red_sin = red_cos = None
    red_valid = np.zeros(P, np_dtype)
    rho_ix_x = np.zeros(0, np.int32)

    def fsig(m, frag):
        return next((s for s in m._fourier if frag in s.name), None)

    floor_ref = const_ref(-15.0)  # 10^(2*-15) == PHI_FLOOR

    if any(fsig(m, "gw") for m in models):
        sigs = [fsig(m, "gw") for m in models]
        K = max(len(s.freqs) // 2 for s in sigs if s is not None)
        gw_sin = np.zeros((P, K), np.int32)
        gw_cos = np.zeros((P, K), np.int32)
        gw_f = np.ones((P, K), np_dtype)
        gw_df = np.zeros((P, K), np_dtype)
        gw_kind = next(s.psd_name for s in sigs if s is not None)
        Hg = max((len(getattr(s, "psd_params", s.params)) for s in sigs
                  if s is not None and s.psd_name != "free_spectrum"),
                 default=0)
        gw_hyp = np.full((P, max(Hg, 1)), sentinel, np.int32)
        gw_rho = np.full((P, K), floor_ref, np.int32)
        for ii, (m, s) in enumerate(zip(models, sigs)):
            if s is None:
                continue
            sl_ = m._slices[s.name]
            cols = np.arange(sl_.start, sl_.stop)
            gw_sin[ii, :len(cols) // 2] = cols[::2]
            gw_cos[ii, :len(cols) // 2] = cols[1::2]
            gw_f[ii, :len(cols) // 2] = s.freqs[::2]
            gw_df[ii, :len(cols) // 2] = s._df[::2]
            psd_ps = getattr(s, "psd_params", s.params)
            if gw_kind == "free_spectrum":
                p = psd_ps[0]
                kp = min(K, p.size or 1)
                gw_rho[ii, :kp] = [ref(p, elem=k) for k in range(kp)]
            else:
                gw_hyp[ii, :len(psd_ps)] = [ref(p) for p in psd_ps]
        if gw_kind == "free_spectrum":
            p = next(getattr(s, "psd_params", s.params)[0]
                     for s in sigs if s is not None)
            if not isinstance(p, Constant):
                rho_ix_x = _as_i32([pos[f"{p.name}_{k}"] for k in range(K)])

    red_f = red_df = None
    if any(fsig(m, "red") for m in models):
        sigs = [fsig(m, "red") for m in models]
        red_kind = next(s.psd_name for s in sigs if s is not None)
        Kr = max(len(s.freqs) // 2 for s in sigs if s is not None)
        Hr = max((2 if s.psd_name == "tprocess" else len(s.params)
                  for s in sigs
                  if s is not None and s.psd_name != "free_spectrum"),
                 default=0)
        red_hyp = np.full((P, max(Hr, 1)), sentinel, np.int32)
        red_rho = np.full((P, Kr), floor_ref, np.int32)
        red_rho_x = np.full((P, Kr), nx, np.int32)  # pad -> dropped scatter
        red_sin = np.zeros((P, Kr), np.int32)
        red_cos = np.zeros((P, Kr), np.int32)
        red_f = np.ones((P, Kr), np_dtype)
        red_df = np.zeros((P, Kr), np_dtype)
        for ii, (m, s) in enumerate(zip(models, sigs)):
            if s is None:
                continue
            red_valid[ii] = 1.0
            sl_ = m._slices[s.name]
            cols = np.arange(sl_.start, sl_.stop)
            red_sin[ii, :len(cols) // 2] = cols[::2]
            red_cos[ii, :len(cols) // 2] = cols[1::2]
            red_f[ii, :len(cols) // 2] = s.freqs[::2]
            red_df[ii, :len(cols) // 2] = s._df[::2]
            if red_kind == "free_spectrum":
                p = s.params[0]
                kp = min(Kr, p.size or 1)
                red_rho[ii, :kp] = [ref(p, elem=k) for k in range(kp)]
                if not isinstance(p, Constant):
                    red_rho_x[ii, :kp] = [pos[f"{p.name}_{k}"]
                                          for k in range(kp)]
            elif red_kind == "tprocess":
                # hypers = (log10_A, gamma); alpha gathers ride red_rho
                # and the conjugate draw writes back through red_rho_ix_x
                red_hyp[ii, :2] = [ref(p) for p in s.params[:2]]
                alphas = s.params[2]
                kp = min(Kr, alphas.size or 1)
                red_rho[ii, :kp] = [ref(alphas, elem=k) for k in range(kp)]
                if not isinstance(alphas, Constant):
                    red_rho_x[ii, :kp] = [pos[f"{alphas.name}_{k}"]
                                          for k in range(kp)]
            else:
                red_hyp[ii, :len(s.params)] = [ref(p) for p in s.params]

    # do red and gw share basis columns?  (True in the CRN layout; False
    # when the factory gives a correlated common process its own group)
    red_shares_gw = True
    if red_kind:
        overlaps = []
        for m in models:
            rs, gs = fsig(m, "red"), fsig(m, "gw")
            if rs is None or gs is None:
                continue
            a_sl, g_sl = m._slices[rs.name], m._slices[gs.name]
            overlaps.append(a_sl.start < g_sl.stop
                            and g_sl.start < a_sl.stop)
        red_shares_gw = any(overlaps) if overlaps else True

    # ---- ECORR b-columns (for the ECORR conditional likelihood) ------------
    We = max((len(r[0]) for r in ec_rows), default=0)
    ec_cols = _as_i32(pad2([r[0] for r in ec_rows], Bmax, We)
                      if We else np.zeros((P, 0)))
    ec_ix = _as_i32(pad2([r[1] for r in ec_rows], sentinel, We)
                    if We else np.zeros((P, 0)))

    # ---- per-pulsar white/ecorr parameter tables ---------------------------
    wrows, erows = [], []
    for m in models:
        wp = []
        if m.white is not None:
            for pp in m.white.params:
                if not isinstance(pp, Constant):
                    wp.append(pos[pp.name])
        wrows.append(sorted(set(wp)))
        ep = []
        for sig in m._ecorr:
            for pp in sig.params:
                if not isinstance(pp, Constant):
                    ep.append(pos[pp.name])
        erows.append(sorted(set(ep)))
    Wp = max((len(r) for r in wrows), default=0)
    Ep = max((len(r) for r in erows), default=0)
    white_par_ix = _as_i32(pad2(wrows, nx, max(Wp, 1)))
    white_nper = _as_i32([len(r) for r in wrows] + [0] * (P - P_real))
    ecorr_par_ix = _as_i32(pad2(erows, nx, max(Ep, 1)))
    ecorr_nper = _as_i32([len(r) for r in erows] + [0] * (P - P_real))

    # ---- priors ------------------------------------------------------------
    pkind = np.zeros(nx, np.int32)
    pa = np.zeros(nx, np_dtype)
    pb = np.ones(nx, np_dtype)
    ct = 0
    for p in pta.params:
        nsc = p.size if p.size else 1
        if isinstance(p, Uniform):
            kind, a, b_ = 0, p.pmin, p.pmax
        elif isinstance(p, Normal):
            kind, a, b_ = 1, p.mu, p.sigma
        elif isinstance(p, LinearExp):
            kind, a, b_ = 2, p.pmin, p.pmax
        elif isinstance(p, InvGamma):
            kind, a, b_ = 3, p.shape, p.rate
        else:
            raise NotImplementedError(
                f"prior {type(p).__name__} not supported on device")
        pkind[ct:ct + nsc] = kind
        pa[ct:ct + nsc] = a
        pb[ct:ct + nsc] = b_
        ct += nsc

    # single-site proposal scale tied to each coordinate's prior width:
    # scale-free (an efac spanning [0.01, 10] and an equad spanning 3.5
    # decades both get jumps that traverse the support in ~10 moves at the
    # scale-mixture's upper end), unlike the reference's dimension-scaled
    # sigma = 0.05 * blockdim (pulsar_gibbs.py:346) which under-steps small
    # per-pulsar blocks started far from the mode
    # (kind 3 = InvGamma alphas: never MH-proposed — conjugate draws —
    # but give them a nonzero scale anyway so no block can freeze)
    prop_scale = np.where((pkind == 1) | (pkind == 3), pb,
                          0.1 * np.abs(pb - pa))

    try:
        rhomin, rhomax = rho_bounds(pta, "gw")
    except ValueError:
        rhomin, rhomax = 1e-20, 1e-8
    try:
        red_rhomin, red_rhomax = rho_bounds(pta, "red")
    except ValueError:
        red_rhomin, red_rhomax = rhomin, rhomax

    # ---- correlated common-process ORF -------------------------------------
    orf_name = "crn"
    orf_Ginv = None
    orf_B = None
    orf_par_ix = None
    gw_orfs = {s.orf_name for m in models for s in m._fourier
               if "gw" in s.name}
    if gw_orfs - {"crn"}:
        if len(gw_orfs) > 1:
            raise NotImplementedError(f"mixed common-process ORFs {gw_orfs}")
        orf_name = gw_orfs.pop()
        if orf_name.startswith("zero_diag_"):
            # builds (reference model_definition.py:202-205: fixed common
            # amplitude, detection-statistic cross-correlation models) but
            # G(theta) has zero diagonal -> the coefficient prior is not
            # positive definite and cannot anchor a Gibbs draw
            raise NotImplementedError(
                f"orf='{orf_name}' builds (fixed-amplitude detection-"
                "statistic model) but cannot be *sampled*: the zero-"
                "diagonal correlation is not a positive-definite "
                "coefficient prior.  Evaluate it with your own "
                "likelihood machinery, or sample the full-diagonal "
                f"'{orf_name[len('zero_diag_'):]}' instead")
        # intrinsic red is supported alongside a correlated common
        # process only on DISJOINT columns (the factory gives correlated
        # processes their own share_group): the joint cross-pulsar prior
        # on the gw columns is then purely rho_k G while red keeps its
        # per-pulsar diagonal
        if red_kind and red_shares_gw:
            raise NotImplementedError(
                "correlated ORF with intrinsic red noise sharing the "
                "common process's basis columns is not implemented (build "
                "with model_general, which gives correlated processes "
                "their own columns)")
        if any(fsig(m, "gw") is None for m in models):
            raise NotImplementedError(
                "correlated ORF requires every pulsar to carry the common "
                "process")
        if gw_kind != "free_spectrum" or not len(rho_ix_x):
            raise NotImplementedError(
                "correlated ORF is implemented for a varied common free "
                "spectrum (common_psd='spectrum'); the powerlaw-family "
                "HD marginalized-likelihood MH block is not implemented")
        ksets = {len(fsig(m, "gw").freqs) // 2 for m in models}
        if len(ksets) > 1:
            raise NotImplementedError(
                "correlated ORF requires a homogeneous common mode count "
                f"across pulsars (got {sorted(ksets)})")
        sig0 = next(s for s in (fsig(m, "gw") for m in models)
                    if s is not None)
        if orf_name in ("bin_orf", "legendre_orf"):
            # sampled correlation weights: precompute the linear basis
            # stack G(theta) = I + sum_j theta_j B_j (zero-padded rows
            # and columns keep pad pulsars at identity) and the gather
            # of theta out of x; G is rebuilt on device per use
            from ..models.orf import orf_param_basis

            B_real, labels = orf_param_basis(
                orf_name, [m.pulsar.pos for m in models],
                leg_lmax=getattr(sig0, "leg_lmax", 5))
            orf_B = np.zeros((len(labels), P, P))
            orf_B[:, :P_real, :P_real] = B_real
            op = getattr(sig0, "orf_params", [])
            if len(op) != len(labels):
                raise ValueError(
                    f"orf='{orf_name}' needs {len(labels)} sampled "
                    f"weights, model carries {len(op)} (build with "
                    "model_general)")
            orf_par_ix = _as_i32([pos[p.name] for p in op])
        else:
            # fixed ORFs: static per-frequency inverse stack.  No size
            # gate: up to HD_DENSE_MAX total coefficients the sweep uses
            # the dense joint draw; larger arrays the sequential
            # pulsar-wise sweep (O(Bmax^2) program).  (K, P, P) so
            # freq_hd rides the same machinery.
            from ..models.orf import orf_ginv_stack

            ginv_real = orf_ginv_stack(
                orf_name, [m.pulsar.pos for m in models], K,
                orf_ifreq=getattr(sig0, "orf_ifreq", 0))  # (K, Pr, Pr)
            orf_Ginv = np.tile(np.eye(P), (K, 1, 1))
            orf_Ginv[:, :P_real, :P_real] = ginv_real

    zeros_pk = np.zeros((P, max(K, 1)), np_dtype)
    return CompiledPTA(
        P=P, P_real=P_real, Nmax=Nmax, Bmax=Bmax, nx=nx, K=K, Kr=Kr,
        widths=widths, param_names=tuple(names), dtype=np_dtype,
        cdtype=np_cdtype,
        y=y, T=T, toa_mask=toa_mask, basis_mask=basis_mask, psr_mask=psr_mask,
        sigma2=sigma2, efac_ix=efac_ix, equad_ix=equad_ix,
        gequad_ix=gequad_ix,
        const_pool=np.asarray(pool, np_dtype), phi_base=phi_base,
        components=components,
        pkind=pkind, pa=pa, pb=pb,
        prop_scale=prop_scale.astype(np_dtype),
        idx=BlockIndex.build(names),
        gw_sin_ix=_as_i32(gw_sin if gw_sin is not None else zeros_pk),
        gw_cos_ix=_as_i32(gw_cos if gw_cos is not None else zeros_pk),
        gw_f=(gw_f if gw_f is not None else np.ones((P, max(K, 1)), np_dtype)),
        gw_df=(gw_df if gw_df is not None else zeros_pk),
        gw_kind=gw_kind,
        gw_hyp_ix=(gw_hyp if gw_hyp is not None
                   else np.full((P, 1), sentinel, np.int32)),
        gw_rho_ix=(gw_rho if gw_rho is not None
                   else np.full((P, max(K, 1)), sentinel, np.int32)),
        rho_ix_x=rho_ix_x,
        red_valid=red_valid, red_kind=red_kind,
        red_hyp_ix=(red_hyp if red_hyp is not None
                    else np.full((P, 1), sentinel, np.int32)),
        red_rho_ix=(red_rho if red_rho is not None
                    else np.full((P, max(Kr, 1)), sentinel, np.int32)),
        red_rho_ix_x=(red_rho_x if red_rho_x is not None
                      else np.full((P, max(Kr, 1)), nx, np.int32)),
        red_sin_ix=_as_i32(red_sin if red_sin is not None
                           else np.zeros((P, max(Kr, 1)))),
        red_cos_ix=_as_i32(red_cos if red_cos is not None
                           else np.zeros((P, max(Kr, 1)))),
        red_f=(red_f if red_f is not None
               else np.ones((P, max(Kr, 1)), np_dtype)),
        red_df=(red_df if red_df is not None
                else np.zeros((P, max(Kr, 1)), np_dtype)),
        ec_cols=ec_cols, ec_ix=ec_ix,
        white_par_ix=white_par_ix, white_nper=white_nper,
        ecorr_par_ix=ecorr_par_ix, ecorr_nper=ecorr_nper,
        rhomin=float(rhomin), rhomax=float(rhomax),
        red_rhomin=float(red_rhomin), red_rhomax=float(red_rhomax),
        orf_name=orf_name, orf_Ginv=orf_Ginv, gp_mask=gp_mask,
        red_shares_gw=red_shares_gw,
        orf_B=orf_B, orf_par_ix=orf_par_ix,
        ke_eid=ke_eid, ke_par_ix=ke_par_ix,
    )
