"""JAX device backend: the jit-compiled blocked Gibbs sweep.

Everything inside the sweep runs on device as compiled XLA: the white-noise
Metropolis sub-chain and the power-law red block are fixed-length
``lax.scan``s, the free-spectrum draw is a Gumbel-max over a log-uniform
grid, and the b-draw is a batched Jacobi-preconditioned Cholesky over the
pulsar axis (``ops/linalg.py``).  Sweeps are themselves composed in a
``lax.scan`` of ``chunk_size`` iterations per device dispatch, so the host
only sees one round-trip per checkpoint interval — the reference pays a full
Python/enterprise round-trip per conditional per iteration
(``pulsar_gibbs.py:656-698``).

Reference semantics mapped here:

- ``update_white``  (``pulsar_gibbs.py:332-406``): 1000-step adaptation MH
  once, then ACT-sized sub-chains.  The ACT becomes a *static* scan length,
  measured on host after the adaptation scan (the one place the reference's
  data-dependent loop bound turns into a compile-time constant).
- ``update_red``    (``:271-329``): PTMCMCSampler is replaced by an in-repo
  adaptive MH — covariance adapted on the marginalized likelihood during the
  first sweep, then 20 SCAM/single-site steps per sweep on the cheap
  b-conditional likelihood.
- ``update_gwrho_params`` (``:199-268``): exact inverse-CDF when there is no
  intrinsic red noise, else grid + Gumbel-max.  The multi-pulsar common
  spectrum (``pta_gibbs.py:181-214``) is the same grid with per-pulsar log
  PDFs *summed* over the pulsar axis — a single ``jnp.sum`` that XLA lowers
  to an ICI all-reduce when the pulsar axis is sharded over a mesh.
- ``update_b``      (``:489-520``): N(Sigma^-1 d, Sigma^-1) via batched
  preconditioned Cholesky.

The multi-chain axis (``nchains=C``) vmaps whole sweeps over a leading
chains axis — an additional throughput axis the reference does not have
(SURVEY §7 hard part (a)).  Every chain is an independent Gibbs process
(per-chain PRNG streams ``fold_in(fold_in(key, iteration), chain)``, per-
chain adaptation state), so C chains multiply posterior samples/sec by
~C while the per-sweep kernels are far below the chip's roofline.
"""

from __future__ import annotations

import contextlib
import os
import time
import warnings
from typing import NamedTuple

import numpy as np

from .. import config as config_mod
from ..config import settings
from ..obs import trace as otrace
from ..ops.acf import integrated_act
from ..runtime import faults, preemption, telemetry
from ..runtime.sentinels import SentinelMonitor, chunk_health
from ..runtime.watchdog import DispatchWatchdog
from .compiled import CompiledPTA, compile_pta

_SCALES = np.array([0.1, 0.5, 1.0, 3.0, 10.0])
_SCALE_P = np.array([0.1, 0.15, 0.5, 0.15, 0.1])
#: rows in the per-chain DE history buffer (past red-block states)
DE_HIST_LEN = 64
#: DE history refresh period and chain-row delay, in *absolute iteration*
#: units.  The buffer for iterations [m*DE_Q, (m+1)*DE_Q) is always the
#: chain rows [m*DE_Q - DE_DELAY - H, m*DE_Q - DE_DELAY): a pure function
#: of the iteration index, never of the chunk/dispatch grid — resume
#: restarts chunks at checkpoint rows that are off the original grid, so
#: any grid-dependent refresh would break bitwise resume.  DE_DELAY >=
#: DE_Q + chunk_size guarantees the rows are already written (or
#: preloaded) at dispatch time under the double-buffered chunk loop.
DE_Q = 128
DE_DELAY = 256


# ===========================================================================
# pure kernels (module-level so __graft_entry__ / parallel can reuse them)
# ===========================================================================

def _gram_operands(cm: CompiledPTA, Nvec, seg_len):
    """Segment operands of the fused augmented Gram: ``Ta = [T | y]``
    and ``TNa = Ta / N`` split into ``nseg`` equal TOA segments, both
    ``(P, nseg, m, B1)``.  Pads: extra zero TOA rows with unit noise
    contribute exactly zero to every segment."""
    import jax.numpy as jnp

    Ta = jnp.concatenate([jnp.asarray(cm.T, cm.dtype),
                          jnp.asarray(cm.y, cm.dtype)[:, :, None]], axis=2)
    TNa = Ta / Nvec.astype(cm.dtype)[:, :, None]
    P, N, B1 = Ta.shape
    nseg = max(1, -(-N // seg_len))
    m = -(-N // nseg)
    if nseg * m != N:
        pad = nseg * m - N
        Ta = jnp.pad(Ta, ((0, 0), (0, pad), (0, 0)))
        TNa = jnp.pad(TNa, ((0, 0), (0, pad), (0, 0)))
    return TNa.reshape(P, nseg, m, B1), Ta.reshape(P, nseg, m, B1)


def tnt_d(cm: CompiledPTA, Nvec, seg_len=None):
    """``TNT = T^T N^-1 T`` and ``d = T^T N^-1 y`` batched over pulsars
    (the per-sweep cache of reference ``pulsar_gibbs.py:500-502``),
    EXACT accumulation.

    Computed as one fused einsum over the augmented basis ``[T | y]``:
    the Gram matrix's last row/column delivers ``d`` (and ``y^T N^-1 y``)
    for free — on TPU's software-emulated f64 a separate matvec einsum
    for ``d`` costs nearly as much as the whole Gram update, so fusing is
    ~2x on this kernel.  Storage-dtype (f32) inputs with compute-dtype
    (f64) accumulation: every f32*f32 product is exactly representable
    in f64, so the only error is the benign f32 rounding of the stored
    basis (backward error) plus f64 summation rounding.

    SEGMENTED exact path (``settings.gram_seg_len_exact``, env
    ``PTGIBBS_GRAM_SEG_EXACT``): the TOA axis is split into ``nseg``
    equal segments carried as an operand batch dimension (``psbc``
    output order — the ``spbc`` form was THE out-of-memory term of
    wide-chain compiles), each segment accumulated in f64 by the dot
    itself, then the per-segment partial Grams are reduced over the
    segment axis in f64.  This bounds the widening dot_general's
    contraction length at seg_len, which collapses XLA's segmented
    operand-copy scratch (ceil(N/seg) tile-padded copies, 15.8 GiB at
    C=128 — analysis/jaxprcheck/hbm.py) to a single segment and is what
    breaks the C=128 HBM wall.

    Summation order (documented because it defines the exact oracle's
    bitstream): TOAs accumulate inside each segment's f64 dot
    accumulator, then the per-segment partial Grams reduce over the
    segment axis in f64, SEQUENTIALLY left-to-right — the kernel tier's
    grid-accumulator order (ops/kernels), shared by both tiers so the
    fused Pallas kernel and this XLA path agree bitwise rather than at
    reassociation level.  Relative to the monolithic single-dot
    accumulation this is a pure f64 REASSOCIATION — same exact products,
    different partial-sum grouping — so the two agree at the f64
    rounding class: within a few ULP at the Jacobi scale
    ``sqrt(G_bb G_cc)`` (measured 3e-16 on the bench-geometry state;
    elements with heavy cancellation differ more in their OWN relative
    terms, exactly as any reassociated f64 sum does), and bitwise when
    nseg == 1 (N <= seg_len).  The ``exact`` oracle and the
    ``exact_every`` Metropolised refresh keep their posteriors
    (tests/test_jax_backend.py::test_tnt_d_segmented_parity).  Pads:
    extra zero TOA rows with unit noise contribute exactly zero to
    every segment."""
    from ..ops import kernels

    if seg_len is None:
        seg_len = settings.gram_seg_len_exact
    TNa, Ta = _gram_operands(cm, Nvec, seg_len)
    G = kernels.gram_accumulate(TNa, Ta, out_dtype=cm.cdtype, widen=True)
    return G[:, :cm.Bmax, :cm.Bmax], G[:, :cm.Bmax, cm.Bmax]


def tnt_d_seg(cm: CompiledPTA, Nvec, seg_len=None):
    """Segmented-f32 MXU Gram: same quantities as :func:`tnt_d`, computed
    as per-segment f32 einsums (MXU, ``precision="highest"``) reduced
    over segments in f64.

    The f64 of :func:`tnt_d` buys only exact *accumulation* — the inputs
    are f32 entries either way — and runs on the VPU's emulated f64 at
    ~60x the cost.  Chunking the TOA axis bounds the f32 accumulation
    error at ~sqrt(seg_len)*eps_f32 of the Jacobi scale (Cauchy-Schwarz
    bounds each segment's |sum of products| by sqrt(G_bb G_cc)), which
    measured 2.5e-7 on the 45-pulsar bench — an order below the
    preconditioned system's smallest eigenvalue (~4.5e-6), so factors of
    the resulting Sigma stay safely positive definite.  Two consumer
    classes: the CRN refresh (:func:`draw_b_refresh`) Metropolizes the
    resulting draw, so there the Gram error only prices acceptance and
    stationarity stays exact; the correlated-ORF Gibbs draws
    (:func:`draw_b_hd_sequential`, :func:`draw_b_joint`) consume it
    directly, accepting a conditional perturbed at the same backward-
    error class as the already-accepted f32 basis storage (~4x the entry
    rounding) — not exact, documented.  Pads: extra zero TOA rows with
    unit noise contribute exactly zero to every segment.

    Segment length: ``settings.gram_seg_len`` (env ``PTGIBBS_GRAM_SEG``),
    with the error-model constants documented on the setting."""
    from ..ops import kernels

    if seg_len is None:
        seg_len = settings.gram_seg_len
    TNa, Ta = _gram_operands(cm, Nvec, seg_len)
    # per-segment f32 MXU dots reduced sequentially in f64 through the
    # kernel tier: segments ride the operand batch axis (the spbc form
    # made XLA materialize a transposed operand copy scratch of
    # (nseg, C, P, Nmax, B1) — tiling-padded 3.4x, 15.8 GB at C=128,
    # THE out-of-memory term of wide-chain compiles) and the bounded
    # per-segment dot keeps that scratch collapsed to one segment
    G = kernels.gram_accumulate(TNa, Ta, out_dtype=cm.cdtype, widen=False)
    return G[:, :cm.Bmax, :cm.Bmax], G[:, :cm.Bmax, cm.Bmax]


def tnt_d_seg32(cm: CompiledPTA, Nvec, seg_len=None):
    """All-f32 steady Gram: the same segmented quantities as
    :func:`tnt_d_seg` with the segment reduce ALSO in f32 — the
    PR 3 mixed-precision pattern extended to the CRN steady body.

    Error model: the f32 segment reduce adds ~sqrt(nseg)*eps_f32 of the
    Jacobi scale on top of :func:`tnt_d_seg`'s in-segment
    ~sqrt(seg_len)*eps_f32 — the same class as the monolithic f32 Gram
    this replaces in :func:`draw_b_mh` (and usually smaller: the
    monolithic dot accumulated all N TOAs in one f32 chain).  The
    consumer is a Metropolised PROPOSAL, so this error only prices
    acceptance; stationarity stays exact (the N4 steady/exact pair,
    contracts/numerics_crn.json).  Routed through the kernel tier
    (ops/kernels): under ``kernel_tier="pallas"`` the whole accumulate
    is one segment-streamed Mosaic kernel — f32 end to end, the tier's
    steady-body island."""
    from ..ops import kernels

    if seg_len is None:
        seg_len = settings.gram_seg_len
    TNa, Ta = _gram_operands(cm, Nvec, seg_len)
    G = kernels.gram_accumulate(TNa, Ta, out_dtype=cm.dtype, widen=False)
    return G[:, :cm.Bmax, :cm.Bmax], G[:, :cm.Bmax, cm.Bmax]


def ke_segsum(cm: CompiledPTA, vals):
    """Sum ``vals`` (P, Nmax[, ...]) per ECORR epoch -> (P, Emax+1[, ...]);
    the trailing slot collects dummy/pad TOAs and is dropped by callers."""
    import jax.numpy as jnp

    E = cm.ke_par_ix.shape[1]
    shape = (cm.P, E + 1) + vals.shape[2:]
    out = jnp.zeros(shape, vals.dtype)
    return out.at[jnp.arange(cm.P, dtype=jnp.int32)[:, None],
                  jnp.asarray(cm.ke_eid, jnp.int32)].add(vals)


def ke_weights(cm: CompiledPTA, x, Nvec):
    """Per-epoch Woodbury pieces of ``N = D + U c U^T`` with disjoint epoch
    indicators U (kernel ECORR): ``c_e = 10^(2 log10_ecorr)``, ``s_e =
    sum_(i in e) 1/D_i``, ``w_e = c_e / (1 + c_e s_e)`` — so
    ``N^-1 = D^-1 - w_e (D^-1 1_e)(D^-1 1_e)^T`` per block and
    ``logdet N = sum log D + sum log1p(c_e s_e)``.  Exponent-safe on the
    TPU's f32-range f64: c ~ 1e-14, 1/D ~ 1e12, and every product is
    O(1e-2..1e2).  Returns ``(c, s, w)``, each (P, Emax) in the compute
    dtype; dummy epochs have c = 10^-80 -> 0 underflow -> w = 0."""
    import jax.numpy as jnp

    cdt = cm.cdtype
    c = (10.0 ** (2.0 * cm.xe(x)[cm.ke_par_ix])).astype(cdt)     # (P, E)
    invN = (jnp.asarray(cm.toa_mask, cdt) / Nvec.astype(cdt))
    s = ke_segsum(cm, invN)[:, :-1]
    w = c / (1.0 + c * s)
    return c, s, w


def tnt_d_ke(cm: CompiledPTA, Nvec, w):
    """Kernel-ECORR :func:`tnt_d`: ``T^T N^-1 T`` and ``T^T N^-1 y`` with
    the block N, via the Woodbury correction ``- V^T diag(w) V`` where
    ``V_e = sum_(i in e) [T|y]_i / D_i`` — the same fused augmented-Gram
    trick as the diagonal path, so ``d``'s correction rides the last
    column for free."""
    import jax.numpy as jnp

    TNT, d = tnt_d(cm, Nvec)
    Ta = jnp.concatenate([jnp.asarray(cm.T, cm.dtype),
                          jnp.asarray(cm.y, cm.dtype)[:, :, None]], axis=2)
    TNa = (Ta / Nvec.astype(cm.dtype)[:, :, None]).astype(cm.cdtype)
    V = ke_segsum(cm, TNa)[:, :-1]                   # (P, E, B+1)
    corr = jnp.einsum("peb,pe,pec->pbc", V, w.astype(cm.cdtype), V,
                      preferred_element_type=cm.cdtype)
    return (TNT - corr[:, :cm.Bmax, :cm.Bmax],
            d - corr[:, :cm.Bmax, cm.Bmax])


def tnt_d_x(cm: CompiledPTA, x, Nvec):
    """``(TNT, d)`` for the current state: diagonal N, or the kernel-ECORR
    block N when the model compiles in that mode."""
    if not cm.has_ke:
        return tnt_d(cm, Nvec)
    _, _, w = ke_weights(cm, x, Nvec)
    return tnt_d_ke(cm, Nvec, w)


def ke_ll_corr(cm: CompiledPTA, x, Nvec, z):
    """(P,) Woodbury correction to a diagonal Gaussian log-density:
    ``-0.5 [sum_e log1p(c_e s_e) - sum_e w_e z_e^2]`` with ``z_e =
    sum_(i in e) r_i / D_i`` passed in.  Every term is O(1)-O(E), so the
    correction carries MH acceptance differences exactly even in f32."""
    import jax.numpy as jnp

    c, s, w = ke_weights(cm, x, Nvec)
    return -0.5 * (jnp.sum(jnp.log1p(c * s), axis=1)
                   - jnp.sum(w * z * z, axis=1))


def ke_rz(cm: CompiledPTA, Nvec, r):
    """(P, Emax) per-epoch ``z_e = sum r_i / D_i`` in the compute dtype."""
    import jax.numpy as jnp

    invN = (jnp.asarray(cm.toa_mask, cm.cdtype) / Nvec.astype(cm.cdtype))
    return ke_segsum(cm, r.astype(cm.cdtype) * invN)[:, :-1]


def lnlike_white_fn(cm: CompiledPTA, x, r2):
    """Diagonal white-noise likelihood conditional on b, with the residual
    square ``r2 = (y - T b)^2`` precomputed for the block (reference
    ``get_lnlikelihood_white``, ``pulsar_gibbs.py:523-546``)."""
    import jax.numpy as jnp

    return jnp.sum(lnlike_white_per(cm, x, r2))


def lnlike_white_per(cm: CompiledPTA, x, r2):
    """Per-pulsar white-noise likelihood (P,) — the conditional factorizes
    over pulsars given b, which is what lets the device backend run the
    white MH as P independent parallel chains.

    Evaluated in sigma^2-scaled form ``N = sigma^2 M`` with
    ``M = efac^2 + 10^(2 equad)/sigma^2``: with raw seconds units
    (sigma^2 ~ 1e-15) the Hessian of the raw form has intermediates like
    ``N^3 ~ 1e-42`` that underflow the TPU's f32-exponent-range f64
    emulation; in scaled form every intermediate of the value, gradient
    and Hessian is O(1)-O(1e4).  Algebraically identical to
    ``-0.5 sum(log N + r2/N)`` (reference ``pulsar_gibbs.py:523-546``).
    """
    import jax.numpy as jnp

    cdt = cm.cdtype
    xev = cm.xe(x)
    efac = xev[cm.efac_ix]
    equad = xev[cm.equad_ix]
    gequad = xev[cm.gequad_ix]
    s2 = jnp.asarray(cm.sigma2, cdt)
    ln_s2 = jnp.log(s2)
    ln10_2 = 2.0 * np.log(10.0)
    M = (efac * efac + jnp.exp(ln10_2 * equad - ln_s2)
         + jnp.exp(ln10_2 * gequad - ln_s2))
    w = r2.astype(cdt) / s2
    return -0.5 * jnp.sum(cm.toa_mask * (ln_s2 + jnp.log(M) + w / M), axis=1)


def lnlike_hyper_fn(cm: CompiledPTA, x, b, phi_fn=None):
    """Generic b-conditional likelihood of every GP-prior hyperparameter:
    ``sum over GP columns of -0.5 (log phi_c(x) + b_c^2 / phi_c(x))``.

    Equal (up to hyper-independent constants) to the reference's
    conditional red likelihood (``pulsar_gibbs.py:549-566``:
    ``logratio - exp(logratio)`` per shared frequency), and additionally
    covers GPs on their own columns (the chromatic DM block), which the
    per-frequency tau fold cannot see.  This is the target of the
    powerlaw-family MH block.  ``phi_fn`` (from
    ``cm.phi_hyper_split``) lets a scan evaluate only the
    hyper-dependent components per step."""
    import jax.numpy as jnp

    phi = cm.phi(x) if phi_fn is None else phi_fn(x)
    mask = jnp.asarray(cm.gp_mask, cm.cdtype)
    b2 = (b * b).astype(cm.cdtype)
    return -0.5 * jnp.sum(mask * (jnp.log(phi) + b2 / phi))


def lnlike_fullmarg_fn(cm: CompiledPTA, x, TNT, d):
    """b-marginalized likelihood (reference ``:569-610``), batched Cholesky
    over pulsars; pads contribute exactly zero."""
    import jax.numpy as jnp

    from ..ops.linalg import (_batched_diag, jacobi_factor_mean,
                              precond_logdet)

    N = cm.ndiag(x)
    phi = cm.phi(x)
    out = -0.5 * jnp.sum(cm.toa_mask * (jnp.log(N) + cm.y ** 2 / N))
    if cm.has_ke:
        # kernel-ECORR: N is the Woodbury block matrix (TNT/d passed in
        # must come from tnt_d_x); correct logdet N and y^T N^-1 y
        out = out + jnp.sum(ke_ll_corr(
            cm, x, N, ke_rz(cm, N, jnp.asarray(cm.y, cm.dtype))))
    logdet_phi = jnp.sum(jnp.log(phi), axis=-1)
    Sigma = TNT + _batched_diag(1.0 / phi)
    # matmul-scheduled factorization (same arithmetic as the native f64
    # cholesky, which XLA lowers near-serially on TPU — see
    # blocked_chol_inv); solves become matvecs with the explicit inverse
    L, _, dj, expval = jacobi_factor_mean(Sigma, d)
    logdet_sigma = precond_logdet(L, dj)
    return out + 0.5 * jnp.sum(
        jnp.sum(d * expval, axis=-1) - logdet_sigma - logdet_phi)


def _joint_kernel_active(cm: CompiledPTA):
    """True when the correlated-ORF b-draw routes to the structured joint
    kernel (:func:`draw_b_joint_structured`) — the production default —
    rather than one of the sequential/frequency-block alternatives kept
    selectable through ``PTGIBBS_HD_KERNEL`` past ``HD_DENSE_MAX``."""
    return (HD_SCALABLE_KERNEL == "joint"
            or cm.P * cm.Bmax <= HD_DENSE_MAX)


def draw_b_fn(cm: CompiledPTA, x, key, b=None, exact=False, factors=None):
    """b | everything: batched preconditioned-Cholesky Gaussian draw
    (reference ``update_b``, ``pulsar_gibbs.py:489-520``).

    Computed from ``Sigma = T^T N^-1 T + diag(phi^-1)`` with f64
    accumulation (see :func:`tnt_d`).  A whitened-basis f32 variant was
    benchmarked ~9 ms/sweep faster but cannot resolve the near-degenerate
    Fourier/timing directions (preconditioned lambda_min ~ 1e-7 is below
    f32 entry rounding), producing O(0.1 sigma) conditional-mean errors —
    correctness keeps the f64-accumulated path.

    With a correlated ORF the per-pulsar draws are replaced by one joint
    cross-pulsar Gaussian drawn through the structure-exploiting
    two-stage factorization (:func:`draw_b_joint_structured` — the
    production kernel at every size; ``factors`` passes a per-sweep
    :func:`joint_factor_cache`).  ``PTGIBBS_HD_KERNEL=pulsar|freq``
    selects the sequential / frequency-block alternatives past
    ``HD_DENSE_MAX`` total coefficients, starting from ``b`` (zeros if
    not given).
    """
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops.linalg import mvn_conditional_draw

    if cm.orf_name != "crn":
        # exact=True selects the f64 blocked factorization: the warmup /
        # initial draws run there — warmup states (prior-drawn rho, b
        # interpolating the data) push the conditional systems past the
        # two-float factor's breakdown margins (observed as seed-dependent
        # NaN warmup chains on TPU), while the ~10x cost only ever applies
        # to the few dozen warmup sweeps
        if _joint_kernel_active(cm):
            return draw_b_joint_structured(cm, x, key, b=b, exact=exact,
                                           factors=factors)
        if b is None:
            b = jnp.zeros((cm.P, cm.Bmax), cm.cdtype)
        if HD_SCALABLE_KERNEL == "pulsar":
            return draw_b_hd_sequential(cm, x, b, key, exact=exact)
        return draw_b_hd_freqblock(cm, x, b, key, exact=exact)
    N = cm.ndiag_fast(x)
    TNT, d = tnt_d_x(cm, x, N)
    phi = cm.phi(x)
    z = jr.normal(key, (cm.P, cm.Bmax), dtype=cm.cdtype)
    b, _ = mvn_conditional_draw(TNT, 1.0 / phi, d, z)
    return b


def draw_b_hd_sequential(cm: CompiledPTA, x, b, key, exact=False):
    """Correlated-ORF b-draw as a sequential pulsar-wise Gibbs sweep —
    the scalable alternative to :func:`draw_b_joint` (whose dense
    ``(P Bmax)^2`` program is capped at ``HD_DENSE_MAX`` coefficients).

    The joint prior of the GW coefficients per (frequency, phase) group
    is ``N(0, rho_k G)`` over pulsars; pulsar ``p``'s conditional given
    the others is Gaussian with precision ``(G^-1)_pp / rho_k`` and mean
    ``-(1/(G^-1)_pp) sum_{q != p} (G^-1)_pq a_qk``, so each pulsar's full
    coefficient draw is the *standard per-pulsar system* with a modified
    GW prior and a linear offset — one ``lax.scan`` over pulsars, each
    step an exact conditional (a valid Gibbs sweep; it mixes the
    cross-pulsar correlations over sweeps instead of within one).

    Scheduling: each pulsar's conditional precision ``Sigma_p = TNT_p +
    diag(pinv_p with (G^-1)_pp/rho on the gw columns)`` depends only on
    ``x`` — never on the other pulsars' coefficients, which enter only
    the linear term.  So all P factorizations run as ONE batched
    two-float MXU factorization before the scan (``tf_chol_factor``, the
    CRN refresh's proposal kernel; see the inline note on its accepted
    O(1e-5) congruence error).

    The scan itself carries *no* (Bmax, Bmax) work (r5; the r4
    chain-width knee).  The step-p draw is ``dj (Li^T (Li (dj (d_p -
    scatter(cross_p))) + z_p))``; only the scatter term depends on the
    other pulsars, so it splits into a per-sweep constant ``base_p``
    (batched matvecs before the scan) minus ``Corr_p @ cross_p`` where
    ``Corr_p = dj ⊙ (Li^T Li)[:, gw cols] ⊙ dj[gw cols]`` is a
    (Bmax, 2K) slice of the conditional covariance — one batched
    (B, B) @ (B, 2K) matmul before the scan.  Each scan step is then a
    (K, P) einsum for ``cross`` plus one (Bmax, 2K) matvec: the r4 trace
    (119 -> 529 ms per b-draw from C=32 to C=64, the per-step (C, B, B)
    f64 working set crossing VMEM tiling) collapses to a latency-bound
    scan, and the chain axis keeps scaling past 32.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops.linalg import blocked_chol_inv, tf_chol_factor, tf_mm

    cdt = cm.cdtype
    B, P, K = cm.Bmax, cm.P, cm.K
    N = cm.ndiag_fast(x)
    # segmented MXU Gram for diagonal-N models: its ~2.5e-7 Jacobi-scale
    # accumulation error is the same backward-error class as the f32
    # basis storage (an order below lambda_min of the preconditioned
    # systems), while cutting ~35 ms/sweep at C=16; KE models keep the
    # f64-accumulated Gram under their Woodbury corrections
    TNT, d = (tnt_d_seg(cm, N) if not cm.has_ke
              else tnt_d_x(cm, x, N))                   # (P, B, B), (P, B)
    phi = cm.phi(x)
    pinv = 1.0 / phi                               # (P, B)
    rows_p = jnp.arange(P, dtype=jnp.int32)[:, None]
    rho = 10.0 ** (2.0 * jnp.asarray(x, cdt)[cm.rho_ix_x])       # (K,)
    Ginv = cm.orf_ginv_k(x).astype(cdt)            # (K, P, P)
    gsin = jnp.asarray(cm.gw_sin_ix, jnp.int32)
    gcos = jnp.asarray(cm.gw_cos_ix, jnp.int32)
    live_mask = jnp.asarray(cm.psr_mask, cdt)

    # batched factorization of every pulsar's conditional precision:
    # gw columns carry the conditional prior precision (G^-1)_pp / rho
    prior_prec = jnp.diagonal(Ginv, axis1=1, axis2=2).T / rho    # (P, K)
    pin = pinv.at[rows_p, gsin].set(prior_prec, mode="drop")
    pin = pin.at[rows_p, gcos].set(prior_prec, mode="drop")
    Sigma = TNT + pin[:, :, None] * jnp.eye(B, dtype=cdt)
    diag = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)                      # (P, B)
    A = Sigma * dj[:, :, None] * dj[:, None, :]
    # two-float MXU factorization (r5): the f64 blocked factor is the
    # sweep floor at these widths (the CRN exact draw's same-shape
    # factorization measures ~400 ms at C=64), while tf_chol_factor's
    # congruence error ||Li A Li^T - I|| ~ B*eps_f32 ~ 8e-6 is
    # condition-INDEPENDENT — the same kernel the CRN refresh uses as a
    # Metropolised proposal with measured acceptance 0.9999, i.e. the
    # draw it produces is statistically indistinguishable from the exact
    # conditional at the 1e-4 level per draw.  Unlike CRN there is no
    # Hastings correction here, so the stationary law carries that
    # O(1e-5)-relative covariance perturbation; the same accepted-error
    # class as the un-Metropolised segmented Gram above (KS-validated
    # against the f64 oracle in tests/test_jax_backend.py).  exact=True
    # (warmup/init, see draw_b_fn) takes the f64 blocked factor instead.
    _, Li = (blocked_chol_inv(A) if exact
             else tf_chol_factor(A))               # (P, B, B)
    kz, kp = jr.split(key)
    z = jr.normal(kz, (P, B), cdt)

    # hoist ALL (B, B) work out of the scan (see docstring): the step-p
    # draw is base_p - Corr_p @ cross_p with
    #   base_p = dj (Li^T (Li (dj d_p) + z_p))          (per-sweep const)
    #   Corr_p = dj ⊙ (Li^T Li)[:, cols_p] ⊙ dj[cols_p]  (B, 2K)
    # cols = [sin cols, cos cols]; out-of-range pad indices (the scatter's
    # old mode="drop") become zeroed Corr columns instead of clamped reads
    w = jnp.einsum("pij,pj->pi", Li, dj * d, precision="highest")
    base = dj * jnp.einsum("pji,pj->pi", Li, w + z, precision="highest")
    cols = jnp.concatenate([gsin, gcos], axis=1)   # (P, 2K)
    valid = ((cols >= 0) & (cols < B)).astype(cdt)  # (P, 2K)
    ccl = jnp.clip(cols, 0, B - 1)
    djc = jnp.take_along_axis(dj, ccl, axis=1) * valid
    Lic = jnp.take_along_axis(
        Li, ccl[:, None, :], axis=2) * djc[:, None, :]          # (P, B, 2K)
    LiT = jnp.swapaxes(Li, -1, -2)
    Corr = dj[:, :, None] * (
        jnp.einsum("pij,pjm->pim", LiT, Lic, precision="highest")
        if exact else tf_mm(LiT, Lic))                          # (P, B, 2K)

    def gather_a(brow, p):
        """(K, 2) GW coefficients of one pulsar row of the padded b."""
        return jnp.stack([brow[gsin[p]], brow[gcos[p]]], axis=-1)

    a0_s = jnp.take_along_axis(b, gsin, axis=1)
    a0_c = jnp.take_along_axis(b, gcos, axis=1)
    a0 = jnp.stack([a0_s, a0_c], axis=-1) * live_mask[:, None, None]

    def step(carry, p):
        b, a = carry                               # (P, B), (P, K, 2)
        g_row = Ginv[:, p, :]                      # (K, P)
        gpp = Ginv[:, p, p]                        # (K,)
        cross = (jnp.einsum("kq,qkf->kf", g_row, a)
                 - gpp[:, None] * a[p]) / rho[:, None]   # (K, 2)
        cvec = jnp.concatenate([cross[:, 0], cross[:, 1]])       # (2K,)
        bp = base[p] - Corr[p] @ cvec
        # pad pulsars keep their inert coords; real rows update.  The
        # finite guard (tf path) skips a pulsar whose two-float factor
        # broke down instead of poisoning the chain (draw_b_mh's ok-mask
        # contract; warmup/init run exact=True so this only backstops
        # rare steady-state excursions)
        ok = jnp.all(jnp.isfinite(bp))
        bnew = jnp.where((live_mask[p] > 0) & ok, bp, b[p])
        b = b.at[p].set(bnew)
        a = a.at[p].set(gather_a(bnew, p) * live_mask[p])
        return (b, a), None

    # random update order per sweep: a fixed scan order makes the "last"
    # pulsars condition on fresher neighbors every sweep while the first
    # pulsars always move against stale state — permuting symmetrizes the
    # information flow across sweeps (random-scan Gibbs, still exact) and
    # measurably improves rho_k mixing (docs/HD_MIXING.md)
    (b, _), _ = jax.lax.scan(step, (b, a0), jr.permutation(kp, P))
    return b


def draw_b_hd_freqblock(cm: CompiledPTA, x, b, key, exact=False):
    """Correlated-ORF b-draw as TWO-BLOCK Gibbs: per-pulsar non-GW
    coordinates given the GW coefficients (one batched draw), then a
    ``lax.scan`` over the K frequencies drawing each frequency's GW
    coefficients JOINTLY across all pulsars (a (2P, 2P) system per
    step).  The ALTERNATIVE scalable kernel (``PTGIBBS_HD_KERNEL=freq``)
    — built expecting per-frequency joint draws to recover dense mixing,
    then measured ~2x WORSE-mixing than the pulsar-wise sweep (toy
    freq/dense ACT ratio 2.71 vs pulsar/dense 1.38, docs/HD_MIXING.md:
    the dominant coupling is gw <-> own timing model, which only the
    per-pulsar joint draw resolves within one conditional).  Kept
    selectable for the P >> K regime its scan shape is right for.

    The shape argument for TPU (why it was worth building and keeping)
    vs the pulsar-wise sweep (:func:`draw_b_hd_sequential`):

    - the sequential axis is K (10 on the bench model), not P (45):
      scan latency shrinks 4.5x and STAYS constant as the array grows —
      more pulsars widen the batched/jointly-drawn dimensions instead
      of lengthening the scan (the scaling direction a PTA framework
      actually faces);
    - the cross-pulsar correlations of frequency k — the quadratic form
      ``taut_k`` the rho_k conditional consumes (:func:`rho_update`) —
      are sampled jointly within one sweep instead of relaxed
      pulsar-by-pulsar.  (Measured, this does NOT dominate: the gw <->
      timing-model coupling left to two-block alternation costs more
      ACT than the joint cross-pulsar draw saves — see the kernel
      decision above.)

    Both blocks are exact conditionals of the same joint law the dense
    draw samples (prior per (frequency, phase): ``N(0, rho_k G)`` across
    pulsars — reference ``pta_gibbs.py:533`` assumes phi block-diagonal
    and never finished this), so the sweep is a valid Gibbs kernel; the
    factorizations use the two-float MXU factor with the same accepted
    O(1e-5) congruence-error class as :func:`draw_b_hd_sequential`
    (KS-validated against the f64 oracle and the dense draw).

    Block 1 (non-GW | GW) runs the full-size per-pulsar system with the
    GW rows/columns projected out (identity rows in their place) so one
    batched (P, B, B) factorization serves every pulsar; the drawn
    values on GW slots are discarded.  Block 2 assembles, per frequency
    k, the joint system over m groups of P coordinates — GW sin/cos
    across pulsars, PLUS (for models with intrinsic red noise) each
    pulsar's red sin/cos at the paired frequency index: the red Fourier
    columns are near-collinear with the same-frequency GW columns
    (almost the same sinusoids on the same TOAs), and a block split that
    separates the two mixes catastrophically along the (red_k - gw_k)
    ridge — measured z ~ 14 bias-level disagreement with the f64 oracle
    at test lengths before red was folded in.  Gibbs blocks may overlap
    (the red coords are also in block 1): every draw is an exact
    conditional, so invariance is preserved and the double update only
    helps mixing.  ``Q_k``'s m x m block structure: per-pulsar TNT
    sub-blocks ``diag(T_ij)`` everywhere, plus ``Ginv_k / rho_k`` on
    the two GW diagonal blocks (cross-pulsar coupling) and the diagonal
    red prior ``1/phi`` on the red diagonal blocks.  Pad pulsars carry
    zero TNT and the decoupled identity rows of ``Ginv``, so they draw
    inert values that the masked ``taut`` reduction never sees.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops.linalg import blocked_chol_inv, tf_chol_factor

    factor = blocked_chol_inv if exact else tf_chol_factor
    cdt = cm.cdtype
    B, P, K = cm.Bmax, cm.P, cm.K
    N = cm.ndiag_fast(x)
    TNT, d = (tnt_d_seg(cm, N) if not cm.has_ke
              else tnt_d_x(cm, x, N))                   # (P, B, B), (P, B)
    phi = cm.phi(x)
    pinv = 1.0 / phi                                    # (P, B)
    rows_p = jnp.arange(P, dtype=jnp.int32)[:, None]
    rho = 10.0 ** (2.0 * jnp.asarray(x, cdt)[cm.rho_ix_x])        # (K,)
    Ginv = cm.orf_ginv_k(x).astype(cdt)                 # (K, P, P)
    gsin = jnp.asarray(cm.gw_sin_ix, jnp.int32)
    gcos = jnp.asarray(cm.gw_cos_ix, jnp.int32)
    cols = jnp.concatenate([gsin, gcos], axis=1)        # (P, 2K)
    valid = ((cols >= 0) & (cols < B)).astype(cdt)
    ccl = jnp.clip(cols, 0, B - 1)
    # GW-slot indicator (P, B): .max absorbs clipped duplicates
    gwm = jnp.zeros((P, B), cdt).at[rows_p, ccl].max(valid)
    nm = 1.0 - gwm                                      # non-GW indicator

    kz1, kz2, kp = jr.split(key, 3)

    # ---- block 1: non-GW | GW --------------------------------------------
    # full-size system with GW rows/cols replaced by identity: one batched
    # factorization, GW slots of the draw discarded afterwards
    Sigma = TNT + (pinv * nm)[:, :, None] * jnp.eye(B, dtype=cdt)
    Sn = Sigma * nm[:, :, None] * nm[:, None, :] \
        + gwm[:, :, None] * jnp.eye(B, dtype=cdt)
    ge = b * gwm                                        # embedded GW coords
    rhs = nm * (d - jnp.einsum("pij,pj->pi", TNT, ge, precision="highest"))
    diag = jnp.diagonal(Sn, axis1=-2, axis2=-1)
    dj = 1.0 / jnp.sqrt(diag)
    A = Sn * dj[:, :, None] * dj[:, None, :]
    _, Li = factor(A)
    z = jr.normal(kz1, (P, B), cdt)
    w = jnp.einsum("pij,pj->pi", Li, dj * rhs, precision="highest")
    bn = dj * jnp.einsum("pji,pj->pi", Li, w + z, precision="highest")
    # two-float breakdown guard (same contract as draw_b_mh's ok-mask):
    # a NaN factor row skips that pulsar's update for the sweep instead
    # of poisoning the chain.  Pad pulsars (psr_mask == 0) also keep
    # their incoming b: their decoupled identity system draws pure
    # noise, and letting it churn would make pad-row contents depend on
    # the kernel choice instead of staying inert (the invariant the
    # sequential kernel's live_mask already keeps).
    live = (jnp.asarray(cm.psr_mask, cdt) > 0)[:, None]
    ok1 = jnp.all(jnp.isfinite(bn), axis=1, keepdims=True)
    b = jnp.where((gwm > 0) | ~ok1 | ~live, b, bn)

    # ---- block 2: per-frequency joint draw across pulsars -----------------
    # m coordinate groups of P: gw sin, gw cos (+ red sin, red cos at the
    # paired frequency index when the model has intrinsic red columns)
    rsin = (jnp.asarray(cm.red_sin_ix, jnp.int32) if cm.red_sin_ix is not None
            else jnp.zeros((P, 0), jnp.int32))
    rcos = (jnp.asarray(cm.red_cos_ix, jnp.int32) if cm.red_cos_ix is not None
            else jnp.zeros((P, 0), jnp.int32))
    Kr = int(rsin.shape[1])
    # shared-column models have no separate red columns to fold in (and
    # folding them would double-count the duplicate index)
    m = 4 if (Kr > 0 and not cm.red_shares_gw) else 2
    zs = jr.normal(kz2, (K, m * P), cdt)
    eyeP = jnp.eye(P, dtype=cdt)
    pr_arange = jnp.arange(P, dtype=jnp.int32)

    def step(b, k):
        gcols = [jnp.take(gsin, k, axis=1), jnp.take(gcos, k, axis=1)]
        vals = [((c >= 0) & (c < B)).astype(cdt) for c in gcols]
        if m == 4:
            kr = jnp.minimum(k, Kr - 1)
            in_r = (k < Kr).astype(cdt)
            for rarr in (rsin, rcos):
                c = jnp.take(rarr, kr, axis=1)
                gcols.append(c)
                vals.append(((c >= 0) & (c < B)).astype(cdt) * in_r)
        c4 = jnp.clip(jnp.stack(gcols, axis=1), 0, B - 1)     # (P, m)
        v4 = jnp.stack(vals, axis=1)                          # (P, m)
        # TNT rows/sub-blocks at the block's columns
        Tr = jnp.take_along_axis(TNT, c4[:, :, None], axis=1) \
            * v4[:, :, None]                                  # (P, m, B)
        T4 = jnp.take_along_axis(Tr, c4[:, None, :].repeat(m, 1), axis=2) \
            * v4[:, None, :]                                  # (P, m, m)
        Dg = Ginv[k] / rho[k]                                 # (P, P)
        blocks = []
        for i in range(m):
            row = []
            for j in range(m):
                blk = jnp.zeros((P, P), cdt).at[pr_arange, pr_arange].set(
                    T4[:, i, j])
                if i == j:
                    if i < 2:
                        # cross-pulsar GW prior; decoupled unit rows for
                        # pulsars without this frequency
                        vi = v4[:, i]
                        blk = blk + Dg * vi[:, None] * vi[None, :] \
                            + (1.0 - vi) * eyeP
                    else:
                        # diagonal red prior (or unit row when invalid)
                        pri = jnp.take_along_axis(
                            pinv, c4[:, i][:, None], 1)[:, 0]
                        blk = blk + jnp.diag(
                            jnp.where(v4[:, i] > 0, pri, 1.0))
                row.append(blk)
            blocks.append(jnp.concatenate(row, axis=1))
        Q = jnp.concatenate(blocks, axis=0)                   # (mP, mP)
        # rhs: data projection minus coupling to every OTHER coordinate
        a4 = jnp.take_along_axis(b, c4, axis=1) * v4          # (P, m)
        coup = jnp.einsum("pib,pb->pi", Tr, b, precision="highest")
        self_c = jnp.einsum("pij,pj->pi", T4, a4, precision="highest")
        dk = jnp.take_along_axis(d, c4, axis=1) * v4
        r = (dk - coup + self_c).T.reshape(m * P)             # group-major
        qdiag = jnp.diagonal(Q)
        qj = 1.0 / jnp.sqrt(qdiag)
        Aq = Q * qj[:, None] * qj[None, :]
        _, Lq = factor(Aq)
        wq = Lq @ (qj * r)
        anew = (qj * (Lq.T @ (wq + zs[k]))).reshape(m, P)     # (m, P)
        # breakdown guard: a non-finite joint draw (two-float factor
        # breakdown at an extreme warmup state) skips this frequency's
        # update for the sweep instead of poisoning the chain
        okk = jnp.all(jnp.isfinite(anew))
        for i in range(m):
            vi = v4[:, i]
            ci = c4[:, i]
            old = b[pr_arange, ci]
            # live[:, 0] keeps pad rows out of the scatter: their Ginv
            # identity rows draw valid-looking but meaningless values
            b = b.at[pr_arange, ci].set(
                jnp.where((vi > 0) & okk & live[:, 0], anew[i], old))
        return b, None

    b, _ = jax.lax.scan(step, b, jr.permutation(kp, K))
    return b


#: correlated-ORF b-draw kernel: "joint" (production — the structured
#: two-stage joint draw, :func:`draw_b_joint_structured`: one batched
#: per-pulsar factorization + a block-grid Schur factorization on the GW
#: subspace; samples the EXACT joint conditional, so it inherits the
#: dense draw's mixing — toy ACT ratio 1.0 by construction — at program
#: size and flop cost that scale with the 2K·P Schur subspace instead of
#: (P·Bmax)^2), "pulsar" (sequential pulsar-wise sweep, the pre-r06
#: production kernel: ACT ratio 1.38 vs dense, docs/HD_MIXING.md) or
#: "freq" (two-block frequency-joint: ACT ratio 2.71, kept for the
#: P >> K regime its K-length scan shape is right for).  "pulsar"/"freq"
#: apply past HD_DENSE_MAX total coefficients; below it the joint draw
#: always runs (it is both exact and the cheapest at toy size).
HD_SCALABLE_KERNEL = os.environ.get("PTGIBBS_HD_KERNEL", "joint")
if HD_SCALABLE_KERNEL not in ("joint", "pulsar", "freq"):
    raise ValueError(
        f"PTGIBBS_HD_KERNEL={HD_SCALABLE_KERNEL!r}: the correlated-ORF "
        "kernel must be 'joint' (production), 'pulsar' or 'freq'")

#: flatten-threshold of the structured draw's GW Schur factorization: at
#: or below this many GW-subspace coordinates (2K·P) the (2K, 2K) grid of
#: (P, P) blocks is flattened and factored by ONE blocked_chol_inv
#: recursion (fewer ops for toy systems); above it the per-(frequency,
#: phase) block-grid factorization keeps every operation at the (P, P)
#: block size so the compiled program scales with 2K, not (2KP)^2 — the
#: same program-size wall that capped the old dense joint draw (the dense
#: (P·Bmax)^2 compile measured 242 s at dim 108; transport broke at dim
#: 1665).  Both paths compute the same Cholesky in the same coordinate
#: order, so the drawn sample is identical up to f64 roundoff.
SCHUR_DENSE_MAX = 128


def _joint_perm_parts(cm: CompiledPTA, x):
    """Shared assembly pieces of the permuted joint system — the ONE
    coordinate ordering both the dense reference draw
    (:func:`draw_b_joint`) and the structured two-stage draw
    (:func:`draw_b_joint_structured`) factor, so Cholesky uniqueness
    makes their same-key samples agree to f64 roundoff:

    ``[P·Bmax "local" slots, pulsar-major (GW slots replaced by inert
    identity coordinates) | 2K·P GW slots, group-major (sin k=0..K-1,
    cos k=0..K-1; pulsar index inner)]``

    Identity rows embedded in an SPD matrix stay exactly decoupled under
    Cholesky (L[i,i]=1, zeros elsewhere in the row/column — the same
    trick draw_b_hd_freqblock's block 1 uses), so the inert slots keep
    every shape static without perturbing the real coordinates' factor;
    their drawn values are masked out at scatter-back.  Invalid GW slots
    (pulsars without that frequency; pad pulsars) are inert identity
    rows in the GW section the same way.

    Returns ``(TNT, d, cols, valid, ccl, gwm, nm, Snn, Tg, Agg)`` where
    ``Snn`` is the per-pulsar local block (GW rows/cols -> identity),
    ``Tg (P, B, 2K)`` the local-GW coupling strips (GW rows zeroed) and
    ``Agg (P, 2K, 2K)`` the per-pulsar GW-GW TNT blocks.
    """
    import jax.numpy as jnp

    cdt = cm.cdtype
    B, P = cm.Bmax, cm.P
    N = cm.ndiag_fast(x)
    TNT, d = (tnt_d_seg(cm, N) if not cm.has_ke
              else tnt_d_x(cm, x, N))   # see draw_b_hd_sequential note
    phi = cm.phi(x)
    pinv = 1.0 / phi                                     # (P, B)
    rows_p = jnp.arange(P, dtype=jnp.int32)[:, None]
    cols, valid, ccl = cm.gw_cols_valid()                # (P, 2K) each
    gwm = jnp.zeros((P, B), cdt).at[rows_p, ccl].max(valid)
    nm = 1.0 - gwm                                       # non-GW indicator
    eyeB = jnp.eye(B, dtype=cdt)
    # local block: per-pulsar Sigma with the GW prior rows zeroed and the
    # GW rows/cols replaced by identity (diag(pinv) restricted to non-GW
    # slots — the GW slots' prior lives in the Schur section instead)
    Snn = (TNT + (pinv * nm)[:, :, None] * eyeB) \
        * nm[:, :, None] * nm[:, None, :] + gwm[:, :, None] * eyeB
    # GW strips: TNT columns at the group cols (valid-masked gathers —
    # a clipped invalid index can collide with a real column)
    Tcols = jnp.take_along_axis(TNT, ccl[:, None, :], axis=2) \
        * valid[:, None, :]                              # (P, B, 2K)
    Tg = Tcols * nm[:, :, None]                          # GW rows zeroed
    Agg = jnp.take_along_axis(Tcols, ccl[:, :, None], axis=1) \
        * valid[:, :, None]                              # (P, 2K, 2K)
    return TNT, d, cols, valid, ccl, gwm, nm, Snn, Tg, Agg


def _joint_gw_prior(cm: CompiledPTA, x, valid):
    """(2K, P, P) group-major GW prior blocks ``G^-1/rho_k`` with inert
    identity rows on invalid slots, plus the duplicated ``rho``/``G_pp``
    vectors the Schur diagonal needs: ``(Dg, rho2, Gpp)``."""
    import jax.numpy as jnp

    cdt = cm.cdtype
    P = cm.P
    rho = 10.0 ** (2.0 * jnp.asarray(x, cdt)[cm.rho_ix_x])         # (K,)
    Ginv = cm.orf_ginv_k(x).astype(cdt)                            # (K,P,P)
    Gfull = jnp.concatenate([Ginv, Ginv], axis=0)                  # (2K,P,P)
    rho2 = jnp.concatenate([rho, rho])                             # (2K,)
    vg = valid.T                                                   # (2K, P)
    eyeP = jnp.eye(P, dtype=cdt)
    Dg = Gfull / rho2[:, None, None] * vg[:, :, None] * vg[:, None, :] \
        + (1.0 - vg)[:, :, None] * eyeP
    Gpp = jnp.diagonal(Gfull, axis1=1, axis2=2)                    # (2K, P)
    return Dg, rho2, Gpp


def draw_b_joint(cm: CompiledPTA, x, key):
    """Correlated-ORF joint b-draw over all pulsars at once — the DENSE
    reference path (one flat factorization of the full permuted system).

    The inter-pulsar coupling lives only in the GW columns: the joint
    prior per (frequency, phase) group over pulsars is ``rho_k G`` (the
    extension the reference never finished — ``pta_gibbs.py:533`` assumes
    phi block-diagonal, SURVEY §3.6), so the joint ``Phi^-1`` carries
    ``G^-1 / rho_k`` on those groups and stays diagonal elsewhere.

    The system is assembled in the permuted ``[local | GW group-major]``
    ordering of :func:`_joint_perm_parts` — the production kernel
    (:func:`draw_b_joint_structured`) factors the SAME matrix blockwise,
    so for the same key the two draws agree to f64 roundoff (the
    same-key acceptance test); this dense path is the oracle/reference,
    not a sweep kernel.
    """
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops.linalg import blocked_chol_inv

    cdt = cm.cdtype
    B, P, K = cm.Bmax, cm.P, cm.K
    PB = P * B
    G = 2 * K
    n = PB + G * P
    (TNT, d, cols, valid, ccl, gwm, nm, Snn, Tg,
     Agg) = _joint_perm_parts(cm, x)
    rows_p = jnp.arange(P, dtype=jnp.int32)[:, None]
    Dg, _, _ = _joint_gw_prior(cm, x, valid)
    # dense assembly in the permuted layout
    lrows = jnp.arange(P)[:, None] * B + jnp.arange(B)[None, :]    # (P, B)
    garr = PB + jnp.arange(G)[:, None] * P + jnp.arange(P)[None, :]
    gidx = garr.T                                                  # (P, 2K)
    Lam = jnp.zeros((n, n), cdt)
    Lam = Lam.at[lrows[:, :, None], lrows[:, None, :]].set(Snn)
    Lam = Lam.at[lrows[:, :, None], gidx[:, None, :]].set(Tg)
    Lam = Lam.at[gidx[:, :, None], lrows[:, None, :]].set(
        jnp.swapaxes(Tg, 1, 2))
    Lam = Lam.at[gidx[:, :, None], gidx[:, None, :]].set(Agg)
    Lam = Lam.at[garr[:, :, None], garr[:, None, :]].add(Dg)
    dn = (d * nm).reshape(PB)
    dgw = (jnp.take_along_axis(d, ccl, axis=1) * valid).T.reshape(G * P)
    dvec = jnp.concatenate([dn, dgw])
    dj = 1.0 / jnp.sqrt(jnp.diagonal(Lam))
    A = Lam * dj[:, None] * dj[None, :]
    _, Li = blocked_chol_inv(A)
    u = Li @ (dj * dvec)
    z = jr.normal(key, (n,), dtype=cdt)
    samp = dj * (Li.T @ (u + z))
    bloc = samp[:PB].reshape(P, B) * nm
    bgw = samp[PB:].reshape(G, P).T                                # (P, 2K)
    return bloc.at[rows_p, cols].set(bgw, mode="drop")


class JointFactors(NamedTuple):
    """Per-pulsar stage-1 products of the structured joint draw — pure
    functions of (Nvec, non-GW phi) only, i.e. of the white/ECORR and red
    blocks' coordinates.  The rho draw, the rho<->b scale moves and the
    ORF MH touch ONLY the GW prior (stage 2), so the sweep body computes
    this cache once after the red blocks and every joint-draw sub-step of
    the sweep reuses it (see _sweep_body)."""

    d: object        # (P, B) projected data
    cols: object     # (P, 2K) GW group columns
    valid: object    # (P, 2K) in-range mask
    ccl: object      # (P, 2K) clipped gather indices
    nm: object       # (P, B) non-GW indicator
    dj_n: object     # (P, B) local Jacobi scales
    Li1: object      # (P, B, B) inverse stage-1 factor (preconditioned)
    Tg: object       # (P, B, 2K) local-GW coupling strips
    Agg: object      # (P, 2K, 2K) per-pulsar GW-GW TNT blocks
    mixed: bool      # static: two-float stage kernels selected


def joint_factor_cache(cm: CompiledPTA, x, exact=False, mixed=None):
    """Stage 1 of the structured joint draw: the batched factorization of
    the P per-pulsar local blocks (TNT + diagonal prior, GW rows/cols ->
    identity) — :func:`ops.linalg.blocked_chol_inv` over the (P, B, B)
    batch, or its two-float instantiation in the mixed-precision mode
    (``settings.joint_mixed``; ``exact=True`` always takes the f64
    factor, the warmup breakdown-margin contract).

    Split out of the draw so the compiled sweep can hoist it: the cache
    depends only on coordinates the white/ECORR/red blocks own, never on
    (rho, ORF, b), so it is computed once per sweep and shared by every
    joint-draw sub-step (b-draw and any Metropolised variants)."""
    import jax.numpy as jnp

    from ..ops.linalg import blocked_chol_inv, tf_chol_factor

    if mixed is None:
        mixed = settings.joint_mixed
    use_tf = bool(mixed) and not exact
    (TNT, d, cols, valid, ccl, gwm, nm, Snn, Tg,
     Agg) = _joint_perm_parts(cm, x)
    dj_n = 1.0 / jnp.sqrt(jnp.diagonal(Snn, axis1=-2, axis2=-1))
    An = Snn * dj_n[:, :, None] * dj_n[:, None, :]
    _, Li1 = (tf_chol_factor(An) if use_tf else blocked_chol_inv(An))
    return JointFactors(d=d, cols=cols, valid=valid, ccl=ccl, nm=nm,
                        dj_n=dj_n, Li1=Li1, Tg=Tg, Agg=Agg, mixed=use_tf)


def draw_b_joint_structured(cm: CompiledPTA, x, key, b=None, exact=False,
                            factors=None, mixed=None):
    """Structure-exploiting joint correlated-ORF b-draw: the production
    kernel.  Samples the SAME exact joint conditional as
    :func:`draw_b_joint` — for the same key the two samples agree to f64
    roundoff — through a two-stage factorization that never materializes
    the (P·Bmax)^2 system:

    1. **per-pulsar stage** (:func:`joint_factor_cache`): one batched
       (P, B, B) factorization of the local blocks with GW rows/cols
       embedded as inert identity coordinates (fixed shapes, exact
       decoupling under Cholesky);
    2. **GW Schur stage**: the Schur complement on the 2K·P GW subspace —
       the only part ``G^-1/rho_k`` touches — assembled as a (2K, 2K)
       grid of (P, P) blocks: ``S[g, g'] = diag_p(Agg_p[g, g'] - (C_p
       C_p^T)[g, g']) + delta_gg' G^-1/rho_g`` with ``C_p = Bhat_p
       Li1_p^T`` the Cholesky B-panel.  The HD coupling therefore stays
       in (P, P) blocks (``ops.linalg.block_grid_cholinv``, unrolled
       over the 2K per-(frequency, phase) stages); at or below
       ``SCHUR_DENSE_MAX`` GW coordinates the grid is flattened and
       factored by one dense recursion instead (same ordering -> same
       factor, fewer ops at toy size);
    3. the Gaussian sample composes the two stages: ``samp = D L^-T
       (L^-1 d + z)`` with one ``z = normal(key, (P·Bmax + 2K·P,))`` —
       the same key discipline, shape and coordinate order as the dense
       draw (jaxlint R1-clean: the key is consumed exactly once).

    Mixed precision (``settings.joint_mixed``, ``exact=False``): both
    stages factor with :func:`ops.linalg.tf_chol_factor` — an f32 MXU
    factorization plus one iterative-refinement step (the residual
    congruence correction), mirroring the segmented-Gram f32 pattern —
    and the grid matmuls run :func:`ops.linalg.tf_mm`; the accepted
    condition-independent O(n·eps_f32) error class the sequential kernel
    KS-validated.  A non-finite result (two-float breakdown at an
    extreme state) keeps the previous ``b`` wholesale for the sweep
    instead of poisoning the chain (draw_b_mh's ok-mask contract);
    ``exact=True`` (warmup/refresh) always factors in f64 and never
    touches the two-float kernels.
    """
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops.linalg import (_mm_t, block_grid_cholinv,
                              block_grid_solve_lower,
                              block_grid_solve_upper, block_grid_to_dense,
                              blocked_chol_inv, tf_chol_factor, tf_mm)

    cdt = cm.cdtype
    B, P, K = cm.Bmax, cm.P, cm.K
    PB = P * B
    G = 2 * K
    f = (joint_factor_cache(cm, x, exact=exact, mixed=mixed)
         if factors is None else factors)
    mm = tf_mm if f.mixed else _mm_t
    factor = tf_chol_factor if f.mixed else blocked_chol_inv
    rows_p = jnp.arange(P, dtype=jnp.int32)[:, None]

    # ---- stage 2: Schur complement on the GW subspace ---------------------
    Dg, rho2, Gpp = _joint_gw_prior(cm, x, f.valid)
    # GW Jacobi scales: diag of the permuted system's GW section
    diag_g = jnp.diagonal(f.Agg, axis1=-2, axis2=-1) \
        + jnp.where(f.valid > 0, Gpp.T / rho2[None, :], 1.0)       # (P, 2K)
    dj_g = 1.0 / jnp.sqrt(diag_g)
    # Cholesky B-panel: per pulsar, Bhat_p = dj_g ⊙ Tg_p^T ⊙ dj_n
    Bhat = jnp.swapaxes(f.Tg, 1, 2) * dj_g[:, :, None] \
        * f.dj_n[:, None, :]                                       # (P,2K,B)
    C = mm(Bhat, f.Li1, transpose_b=True)                          # (P,2K,B)
    CCt = mm(C, C, transpose_b=True)                               # (P,2K,2K)
    Agg_hat = f.Agg * dj_g[:, :, None] * dj_g[:, None, :]
    dj_gT = dj_g.T                                                 # (2K, P)
    Dg_hat = Dg * dj_gT[:, :, None] * dj_gT[:, None, :]
    M = Agg_hat - CCt                                              # (P,2K,2K)
    pr = jnp.arange(P, dtype=jnp.int32)
    gr = jnp.arange(G, dtype=jnp.int32)
    S = jnp.zeros((G, G, P, P), cdt).at[
        :, :, pr, pr].set(jnp.moveaxis(M, 0, -1))
    S = S.at[gr, gr].add(Dg_hat)

    # ---- solves + sample --------------------------------------------------
    dn_hat = f.dj_n * (f.d * f.nm)                                 # (P, B)
    dg_hat = dj_g * (jnp.take_along_axis(f.d, f.ccl, axis=1)
                     * f.valid)                                    # (P, 2K)
    v_n = jnp.einsum("pij,pj->pi", f.Li1, dn_hat, precision="highest")
    r_g = dg_hat - jnp.einsum("pgb,pb->pg", C, v_n,
                              precision="highest")                 # (P, 2K)
    # one normal draw in the permuted layout: same shape/order as the
    # dense reference, so same-key samples coincide
    z = jr.normal(key, (PB + G * P,), dtype=cdt)
    z_n = z[:PB].reshape(P, B)
    z_g = z[PB:].reshape(G, P)
    # inner Jacobi on the Schur matrix (its diagonal drifts below 1 as
    # the local columns explain the GW columns); chol(D S D) = D chol(S)
    # for diagonal D, so preconditioning here leaves the sample map of
    # the overall factorization unchanged in exact arithmetic
    sdiag = jnp.diagonal(S[gr, gr], axis1=-2, axis2=-1)            # (G, P)
    sj = 1.0 / jnp.sqrt(sdiag)
    rg = r_g.T                                                     # (G, P)
    if G * P <= SCHUR_DENSE_MAX:
        Sd = block_grid_to_dense(S)                                # (GP, GP)
        sjf = sj.reshape(G * P)
        As = Sd * sjf[:, None] * sjf[None, :]
        _, Lsi = factor(As)
        v_g = (Lsi @ (sjf * rg.reshape(G * P))).reshape(G, P)
        w_g = sj * (Lsi.T @ (v_g + z_g).reshape(G * P)).reshape(G, P)
    else:
        Ssc = S * sj[:, None, :, None] * sj[None, :, None, :]
        _, Ldi, Loff = block_grid_cholinv(Ssc, factor=factor, mm=mm)
        v_g = block_grid_solve_lower(Ldi, Loff, sj * rg)
        w_g = sj * block_grid_solve_upper(Ldi, Loff, v_g + z_g)
    # back-substitute the local section through the B-panel
    w_gT = w_g.T                                                   # (P, 2K)
    t_n = v_n + z_n - jnp.einsum("pgb,pg->pb", C, w_gT,
                                 precision="highest")
    w_n = jnp.einsum("pji,pj->pi", f.Li1, t_n, precision="highest")
    bnew = (f.dj_n * w_n * f.nm).at[rows_p, f.cols].set(
        dj_g * w_gT, mode="drop")
    # two-float breakdown guard (draw_b_mh's ok-mask contract): skip the
    # whole update rather than poison the chain; exact=True never takes
    # the two-float kernels so this is inert there
    if b is None:
        b = jnp.zeros((P, B), cdt)
    ok = jnp.all(jnp.isfinite(bnew))
    return jnp.where(ok, bnew, b)


def _mh_step(cm: CompiledPTA, lnlike, ind):
    """One single-site Metropolis step with the reference's scale-mixture
    proposal (``pulsar_gibbs.py:344-351``), jump sd tied to the chosen
    coordinate's prior width; returns a scan body."""
    import jax.numpy as jnp
    import jax.random as jr

    scales = jnp.asarray(_SCALES, dtype=cm.cdtype)
    probs = jnp.asarray(_SCALE_P, dtype=cm.cdtype)
    prop = jnp.asarray(cm.prop_scale, dtype=cm.cdtype)
    ind = jnp.asarray(ind, jnp.int32)

    def step(carry, key):
        x, ll0, lp0 = carry
        k1, k2, k3, k4 = jr.split(key, 4)
        scale = jr.choice(k1, scales, p=probs)
        j = ind[jr.randint(k2, (), 0, len(ind))]
        q = x.at[j].add(jr.normal(k3, dtype=cm.cdtype) * prop[j] * scale)
        lp1 = cm.lnprior(q)
        ll1 = lnlike(q)
        ok = jnp.isfinite(lp1) & jnp.isfinite(ll1)
        logr = jnp.where(ok, (ll1 + lp1) - (ll0 + lp0), -jnp.inf)
        acc = logr > jnp.log(jr.uniform(k4, dtype=cm.cdtype))
        x = jnp.where(acc, q, x)
        ll0 = jnp.where(acc, ll1, ll0)
        lp0 = jnp.where(acc, lp1, lp0)
        return (x, ll0, lp0), x[ind]

    return step


def mh_scan(cm: CompiledPTA, x, key, lnlike, ind, nsteps):
    """Fixed-length single-site MH sub-chain; returns (x', recorded block
    coordinates (nsteps, len(ind)))."""
    import jax
    import jax.random as jr

    step = _mh_step(cm, lnlike, ind)
    carry = (x, lnlike(x), cm.lnprior(x))
    (x, _, _), rec = jax.lax.scan(step, carry, jr.split(key, nsteps))
    return x, rec


def parallel_cov_mh_scan(cm: CompiledPTA, x, key, ll_per_fn, par_ix, nper,
                         chol, nsteps, record=True, mode=None, asqrt=None,
                         p_indep=0.5, inflate=1.3):
    """Per-pulsar *full-block* MH with adapted proposals.

    Two proposal kernels, mixed per step and per pulsar:

    - **random walk**: ``q_p = x_p + scale * (2.38/sqrt(W_p)) L_p z`` (the
      standard AM scaling; the reference gets the same effect from
      PTMCMCSampler's AM/SCAM jumps, ``pulsar_gibbs.py:288-296``);
    - **independence** (when ``mode`` is given): ``q_p = mode_p +
      inflate * L_p z``, a draw from the inflated Laplace approximation of
      the conditional, accepted with the Hastings ratio
      ``pi(q) g(x) / (pi(x) g(q))``.  The proposal center/shape are fixed
      per run (adaptation-time state), never functions of the current
      ``x``, so the correction is the simple two-density ratio.  Because
      the white/ECORR conditionals are near-Gaussian, accepted states are
      nearly independent — the measured ACT (which sizes every later
      sub-chain) drops from O(block ACT of a random walk) to O(1), which
      is worth ~10x on the per-sweep device budget.

    ``chol`` is (P, W, W): any per-pulsar square roots of the proposal
    covariances (in practice the Laplace eigen square roots from
    :func:`laplace_newton_chol` — not triangular), rows/cols beyond
    ``nper[p]`` zeroed.  ``asqrt`` is the matching square root of the
    *precision* (``A = asqrt asqrt^T``), needed for the independence
    log-density; ``mode`` is (P, W).
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    fdt = cm.dtype
    scales = jnp.asarray(_SCALES, dtype=fdt)
    probs = jnp.asarray(_SCALE_P, dtype=fdt)
    nper = jnp.asarray(nper, jnp.int32)
    par_ix = jnp.asarray(par_ix, jnp.int32)
    W = par_ix.shape[1]
    wmask = (jnp.arange(W, dtype=jnp.int32)[None, :]
             < nper[:, None]).astype(fdt)
    live = nper > 0
    amp = 2.38 / jnp.sqrt(jnp.maximum(nper, 1).astype(fdt))
    safe_ix = jnp.minimum(par_ix, cm.nx - 1)
    chol = jnp.asarray(chol, dtype=fdt)

    k1, k3, k4, k5 = jr.split(key, 4)
    scale = jr.choice(k1, scales, (nsteps, cm.P), p=probs)
    z = jr.normal(k3, (nsteps, cm.P, W), dtype=fdt)
    # precision="highest": proposal shaping feeds the accept ratio
    # through logg; a tf32 lowering on GPU would perturb the proposal
    # density away from the density actually sampled (numcheck N3)
    Lz = jnp.einsum("pwv,spv->spw", chol, z,
                    precision="highest") * wmask[None]
    noise = Lz * (amp[None, :, None] * scale[:, :, None])
    logu = jnp.log(jr.uniform(k4, (nsteps, cm.P), dtype=fdt))
    if mode is not None:
        coin = jr.uniform(k5, (nsteps, cm.P), dtype=fdt) < p_indep
        mode = jnp.asarray(mode, fdt)
        asq = jnp.asarray(asqrt, fdt) / fdt(inflate)

        def logg(w):
            # the independence-proposal log-density enters the Hastings
            # correction; w derives from the f64 state, so a default-
            # precision (tf32-on-GPU) product here would bias the
            # accept ratio (numcheck N3)
            u = jnp.einsum("pwv,pw->pv", asq, (w - mode) * wmask,
                           precision="highest")
            return -0.5 * jnp.sum(u * u, axis=-1)
    else:
        coin = jnp.zeros((nsteps, cm.P), bool)

    def step(carry, inp):
        x, ll0 = carry
        nz_rw, lz, cn, lu = inp
        xw = x[safe_ix].astype(fdt)               # (P, W)
        if mode is not None:
            nz_ind = (mode + fdt(inflate) * lz - xw) * wmask
            nz = jnp.where(cn[:, None], nz_ind, nz_rw)
        else:
            nz = nz_rw
        qw = xw + nz
        dlp = jnp.sum(wmask * (cm.coord_logpdf(par_ix, qw)
                               - cm.coord_logpdf(par_ix, xw)), axis=1)
        if mode is not None:
            dlp = dlp + jnp.where(cn, logg(xw) - logg(qw), 0.0)
        q = x.at[par_ix].add(nz.astype(x.dtype), mode="drop")
        ll1 = ll_per_fn(q)
        ok = jnp.isfinite(dlp) & jnp.isfinite(ll1)
        logr = jnp.where(ok, (ll1 - ll0) + dlp, -jnp.inf)
        acc = (logr > lu) & live
        # where(acc, nz, 0) rather than nz * acc: a non-finite proposal
        # (NaN * 0 = NaN) must never poison an unaccepted state
        x = x.at[par_ix].add(
            jnp.where(acc[:, None], nz, 0.0).astype(x.dtype), mode="drop")
        ll0 = jnp.where(acc, ll1, ll0)
        out = x[safe_ix] if record else None
        return (x, ll0), out

    (x, _), rec = jax.lax.scan(step, (x, ll_per_fn(x)),
                               (noise, Lz, coin, logu))
    return x, rec


def _prior_halfwidth2(cm: CompiledPTA):
    """(nx,) squared prior half-widths (normal: (1 sd)^2 scaled to 2 sd)."""
    w = np.where(np.asarray(cm.pkind) == 1, 2.0 * np.asarray(cm.pb),
                 np.abs(np.asarray(cm.pb) - np.asarray(cm.pa)))
    return (0.5 * w) ** 2


def laplace_newton_chol(cm: CompiledPTA, x, ll_per_fn, par_ix, nper,
                        newton_iters=8):
    """Per-pulsar Laplace proposal square roots for a factorized MH block.

    The white/ECORR conditionals given ``b`` are near-Gaussian (hundreds of
    TOAs per pulsar), so instead of the reference's empirical random-walk
    adaptation (``pulsar_gibbs.py:332-371`` — which collapses when the
    initial single-site walk never moves a tightly-constrained EFAC, the
    round-1 white-mixing failure), the proposal covariance comes from the
    *analytic* local curvature:

    1. a few damped, per-pulsar-vectorized Newton steps move each block to
       its conditional mode (pure initialization — does not affect the
       stationary distribution);
    2. the negative block Hessian ``A = -H`` is eigendecomposed and the
       proposal square root is ``L = V diag(1/sqrt(clip(e)))``, eigenvalues
       floored so no proposal sd exceeds half the prior width
       (likelihood-unconstrained directions walk the prior at O(1)
       acceptance instead of freezing).

    The block Hessian is computed with ``W`` Hessian-vector products shared
    across all pulsars at once — cross-pulsar blocks vanish because the
    conditional factorizes, so a tangent of ``e_w`` broadcast over pulsars
    returns every pulsar's ``H[:, :, w]`` column in one pass.

    Returns ``(x_at_mode, L, asqrt)`` with ``L = V diag(1/sqrt(e))``
    (covariance square root) and ``asqrt = V diag(sqrt(e))`` (precision
    square root, for the independence-proposal log-density), both
    (P, W, W) with pad rows zeroed.
    """
    import jax
    import jax.numpy as jnp

    P, W = par_ix.shape
    cdt = cm.cdtype
    par_ix = jnp.asarray(par_ix, jnp.int32)
    nper = jnp.asarray(nper, jnp.int32)
    safe_ix = jnp.minimum(par_ix, cm.nx - 1)
    wmask = (jnp.arange(W, dtype=jnp.int32)[None, :]
             < nper[:, None])                               # (P, W) bool
    live = nper > 0

    hw2 = jnp.asarray(_prior_halfwidth2(cm), cdt)[safe_ix]  # (P, W)
    vmax = jnp.max(jnp.where(wmask, hw2, 1e-30), axis=1)    # (P,)
    pk = jnp.asarray(cm.pkind, jnp.int32)[safe_ix]
    a = jnp.asarray(cm.pa, cdt)[safe_ix]
    b_ = jnp.asarray(cm.pb, cdt)[safe_ix]
    lo = jnp.where(pk == 1, a - 8.0 * b_, a)
    hi = jnp.where(pk == 1, a + 8.0 * b_, b_)
    margin = 1e-6 * (hi - lo)
    lo, hi = lo + margin, hi - margin

    x = jnp.asarray(x, cdt)
    theta0 = x[safe_ix]
    eyeW = jnp.eye(W, dtype=cdt)

    def q_of(theta):
        return x.at[par_ix].set(jnp.where(wmask, theta, theta0), mode="drop")

    def f_sum(theta):
        return jnp.sum(ll_per_fn(q_of(theta)).astype(cdt))

    grad_f = jax.grad(f_sum)

    def decomp(theta):
        cols = [jax.jvp(grad_f, (theta,),
                        (jnp.broadcast_to(eyeW[w], (P, W)),))[1]
                for w in range(W)]
        H = jnp.stack(cols, axis=-1)                        # (P, W, W)
        A = -0.5 * (H + jnp.swapaxes(H, 1, 2))
        mo = wmask[:, :, None] & wmask[:, None, :]
        A = (jnp.where(mo, A, 0.0)
             + jnp.where(wmask, 0.0, 1.0)[:, :, None] * eyeW[None])
        return jnp.linalg.eigh(A)

    def newton_body(theta, _):
        g = grad_f(theta)
        e, V = decomp(theta)
        # saddle-free Newton: |e| handles the log-convex far tail (e.g.
        # lnL ~ -n log(efac) at efac >> mode has e < 0); the floor keeps
        # steps <= O(prior width); per-pulsar keep-if-better damps the rest
        e = jnp.maximum(jnp.abs(e), 1.0 / vmax[:, None])
        step = jnp.einsum("pwk,pk->pw", V,
                          jnp.einsum("pwk,pw->pk", V, g) / e)
        best = ll_per_fn(q_of(theta))
        out = theta
        for alpha in (1.0, 0.25):
            cand = jnp.clip(theta + alpha * step, lo, hi)
            llc = ll_per_fn(q_of(cand))
            better = (llc > best) & live
            out = jnp.where(better[:, None], cand, out)
            best = jnp.where(better, llc, best)
        return out, None

    theta = theta0
    if newton_iters:
        theta, _ = jax.lax.scan(newton_body, theta0, None,
                                length=newton_iters)
    e, V = decomp(theta)
    e = jnp.clip(e, 1.0 / vmax[:, None], None)              # sd <= halfwidth
    mo = (wmask[:, :, None] & wmask[:, None, :]).astype(cdt)
    L = (V * (1.0 / jnp.sqrt(e))[:, None, :]) * mo
    asqrt = (V * jnp.sqrt(e)[:, None, :]) * mo
    return q_of(theta), L, asqrt


def white_ll_rel(cm: CompiledPTA, x0, r2):
    """Block-relative per-pulsar white likelihood in the storage dtype.

    ``ll(q) - ll(x0)`` with the cancellation done per element *before* the
    sum: with ``z = N0/Nq``, ``delta_i = 0.5 (log z_i - w_i (z_i - 1))``,
    ``w_i = r2_i / N0_i`` (from ``ll = -0.5 (log N + r2/N)`` per element:
    ``r2 (1/Nq - 1/N0) = w (z - 1)`` enters with a minus).  Every
    intermediate is O(1), so float32 carries the MH acceptance differences
    exactly where the absolute likelihood (~1e6) would quantize them at
    ~0.06.
    """
    import jax.numpy as jnp

    fdt = cm.dtype
    N0f = cm.ndiag_fast(x0)
    w = (r2.astype(fdt) / N0f)
    mask = jnp.asarray(cm.toa_mask, dtype=fdt)

    def ll_rel(q):
        xev = cm.xe(q).astype(fdt)
        efac = xev[cm.efac_ix]
        equad = xev[cm.equad_ix]
        gequad = xev[cm.gequad_ix]
        Nq = (efac * efac * jnp.asarray(cm.sigma2, fdt)
              + 10.0 ** (2.0 * equad) + 10.0 ** (2.0 * gequad))
        z = N0f / Nq
        return 0.5 * jnp.sum(mask * (jnp.log(z) - w * (z - 1.0)), axis=1)

    return ll_rel


def lnlike_ecorr_per(cm: CompiledPTA, x, b):
    """Per-pulsar ECORR conditional ll (P,) in the compute dtype: the basis
    coefficients at the ECORR columns are iid N(0, 10^(2 ecorr)).  Used for
    Laplace curvature, where the f32 relative form is too noisy."""
    import jax.numpy as jnp

    cdt = cm.cdtype
    mask = (cm.ec_cols < cm.Bmax).astype(cdt)
    bj = jnp.take_along_axis(
        b, jnp.minimum(cm.ec_cols, cm.Bmax - 1), axis=1).astype(cdt)
    e = cm.xe(x)[cm.ec_ix]
    return jnp.sum(mask * (-np.log(10.0) * e
                           - 0.5 * bj * bj * 10.0 ** (-2.0 * e)), axis=1)


def ecorr_ll_rel(cm: CompiledPTA, x0, b):
    """Block-relative per-pulsar ECORR likelihood in the storage dtype:
    ``delta_j = -ln10 (e_q - e_0) + 0.5 u_j (1 - 10^(2(e_0 - e_q)))`` with
    ``u_j = b_j^2 / phi_0``."""
    import jax.numpy as jnp

    fdt = cm.dtype
    xev0 = cm.xe(x0)
    e0 = xev0[cm.ec_ix].astype(fdt)
    mask = (cm.ec_cols < cm.Bmax).astype(fdt)
    bj = jnp.take_along_axis(b, jnp.minimum(cm.ec_cols, cm.Bmax - 1), axis=1)
    u = (bj * bj * 10.0 ** (-2.0 * xev0[cm.ec_ix])).astype(fdt)

    def ll_rel(q):
        eq = cm.xe(q).astype(fdt)[cm.ec_ix]
        ratio = 10.0 ** (2.0 * (e0 - eq))
        return jnp.sum(mask * (-np.log(10.0) * (eq - e0)
                               + 0.5 * u * (1.0 - ratio)), axis=1)

    return ll_rel


def white_block_ll(cm: CompiledPTA, x, r, r2):
    """The white MH block's target: diagonal relative form, or the
    Woodbury form when the model compiled with kernel ECORR."""
    if cm.has_ke:
        return white_ll_ke(cm, x, r, r2)
    return white_ll_rel(cm, x, r2)


def ecorr_block_ll(cm: CompiledPTA, x, b, r):
    """The ECORR MH block's target: basis-coefficient conditional, or the
    kernel (in-N Woodbury) conditional on the residual."""
    if cm.has_ke:
        return ecorr_ll_ke(cm, x, r)
    return ecorr_ll_rel(cm, x, b)


def white_ll_ke(cm: CompiledPTA, x0, r, r2):
    """Kernel-ECORR white-block likelihood closure: the f32-exact relative
    diagonal form plus the O(1) Woodbury correction (whose x0 constant
    cancels in MH differences).  ``r`` is the block-fixed residual.

    ``ndiag_fast`` throughout — the same N variant the relative diagonal
    base and the exact b-draw's KE weights use (``draw_b_fn`` ->
    ``tnt_d_x``), so the white-block target and the b-draw conditional
    see one consistent N even where the fast and f64 diagonals differ by
    f32 storage rounding."""
    base = white_ll_rel(cm, x0, r2)

    def ll(q):
        Nq = cm.ndiag_fast(q)
        return base(q) + ke_ll_corr(cm, q, Nq, ke_rz(cm, Nq, r))

    return ll


def ecorr_ll_ke(cm: CompiledPTA, x0, r):
    """Kernel-ECORR block likelihood closure (ECORR amplitudes only): with
    the diagonal D fixed, only ``c_e(q)`` moves, so the per-epoch
    aggregates ``s_e`` and ``z_e^2`` are precomputed once per block and
    each MH step costs O(Emax).  Differentiable — the same closure feeds
    the Laplace proposal curvature.  ``ndiag_fast`` for consistency with
    the b-draw's KE weights (see :func:`white_ll_ke`)."""
    import jax.numpy as jnp

    N0 = cm.ndiag_fast(x0)
    cdt = cm.cdtype
    invN = (jnp.asarray(cm.toa_mask, cdt) / N0.astype(cdt))
    s = ke_segsum(cm, invN)[:, :-1]
    z = ke_segsum(cm, r.astype(cdt) * invN)[:, :-1]
    z2 = z * z

    def ll(q):
        c = (10.0 ** (2.0 * cm.xe(q)[cm.ke_par_ix])).astype(cdt)
        w = c / (1.0 + c * s)
        return -0.5 * (jnp.sum(jnp.log1p(c * s), axis=1)
                       - jnp.sum(w * z2, axis=1))

    return ll


def red_mh_block(cm: CompiledPTA, x, b, key, U, S, nsteps, hist=None):
    """Per-sweep power-law hyper block (intrinsic red, varied common
    process, chromatic DM): `nsteps` MH steps mixing differential-
    evolution (pair differences from a past-sample history buffer, the
    reference PTMCMC's highest-weighted jump: DE=50 vs SCAM=30/AM=15 at
    ``pulsar_gibbs.py:294``), adapted-eigendirection (SCAM), full
    adapted-covariance (AM) and the single-site scale-mixture proposal,
    on the cheap b-conditional likelihood (reference
    ``pulsar_gibbs.py:300-327``).  Mix: DE .5, SCAM .15, AM .15,
    single-site .2 — the reference's DE/(SCAM+AM)/other proportions with
    the covariance-family weight split evenly.

    ``hist`` is a frozen (H, d) buffer of past red-block states
    (ter Braak & Vrugt 2008 "DE-MC with sampling from the past": a
    periodically-refreshed history keeps the chain ergodic while every
    proposal stays symmetric, so the plain Metropolis accept is exact);
    ``None`` compiles the SCAM/single-site-only variant.  The caller
    selects the buffer for the current DE period (see ``DE_Q``) before
    passing it in."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    rind = jnp.asarray(cm.idx.red)
    d = len(cm.idx.red)
    sigma = 0.05 * d
    _, phi_dyn = cm.phi_hyper_split(x)      # static comps evaluated once
    lnlike = lambda q: lnlike_hyper_fn(cm, q, b, phi_fn=phi_dyn)
    scales = jnp.asarray(_SCALES, dtype=cm.cdtype)
    probs = jnp.asarray(_SCALE_P, dtype=cm.cdtype)
    use_de = hist is not None
    if use_de:
        H = hist.shape[0]
        gamma0 = jnp.asarray(2.38 / np.sqrt(2.0 * d), cm.cdtype)

    am_scale = jnp.asarray(2.38 / np.sqrt(d), cm.cdtype)
    # covariance square root for the AM jump: cov = U diag(S) U^T
    am_sqrt = U * jnp.sqrt(S)[None, :]

    def step(carry, key):
        x, ll0, lp0 = carry
        k0, k1, k2, k3, k4, k5, k6, k7, k8 = jr.split(key, 9)
        # SCAM branch: jump along one adapted covariance eigendirection
        j = jr.randint(k1, (), 0, d)
        stepsz = 2.38 * jnp.sqrt(S[j]) * jr.normal(k2, dtype=cm.cdtype)
        q_scam = x.at[rind].add(stepsz * U[:, j])
        # AM branch: full adapted-covariance jump (reference weight 15/95,
        # pulsar_gibbs.py:294)
        z_am = jr.normal(k6, (d,), dtype=cm.cdtype)
        q_am = x.at[rind].add(am_scale * (am_sqrt @ z_am))
        # single-site branch
        scale = jr.choice(k7, scales, p=probs)
        jj = rind[jr.randint(k8, (), 0, d)]
        q_ss = x.at[jj].add(jr.normal(k3, dtype=cm.cdtype) * sigma * scale)
        r = jr.uniform(k0)
        if use_de:
            # DE branch: gamma (h_a - h_b) over two distinct history rows;
            # 10% of jumps use gamma=1 for mode hopping (standard DE-MC)
            ka, kb, kg = jr.split(k5, 3)
            a_ix = jr.randint(ka, (), 0, H)
            b_ix = (a_ix + 1 + jr.randint(kb, (), 0, H - 1)) % H
            gamma = jnp.where(jr.uniform(kg) < 0.1, 1.0, gamma0)
            q_de = x.at[rind].add(gamma * (hist[a_ix] - hist[b_ix]))
            # weights mirror the reference ratios: DE .5 / SCAM .15 /
            # AM .15 / single-site .2
            q = jnp.where(r < 0.5, q_de,
                          jnp.where(r < 0.65, q_scam,
                                    jnp.where(r < 0.8, q_am, q_ss)))
        else:
            q = jnp.where(r < 0.25, q_scam,
                          jnp.where(r < 0.5, q_am, q_ss))
        lp1 = cm.lnprior(q)
        ll1 = lnlike(q)
        ok = jnp.isfinite(lp1) & jnp.isfinite(ll1)
        logr = jnp.where(ok, (ll1 + lp1) - (ll0 + lp0), -jnp.inf)
        acc = logr > jnp.log(jr.uniform(k4, dtype=cm.cdtype))
        return (jnp.where(acc, q, x), jnp.where(acc, ll1, ll0),
                jnp.where(acc, lp1, lp0)), None

    carry = (x, lnlike(x), cm.lnprior(x))
    (x, _, _), _ = jax.lax.scan(step, carry, jr.split(key, nsteps))
    return x


def _rho_grid(cm: CompiledPTA, lo, hi):
    # grid math runs in the storage dtype: log-density values are O(+-100),
    # so f32 carries the Gumbel-max draw exactly where it matters while
    # avoiding ~20 ms/sweep of emulated-f64 transcendentals on TPU
    import jax.numpy as jnp

    return 10.0 ** jnp.linspace(np.log10(lo), np.log10(hi),
                                settings.rho_grid_size, dtype=cm.dtype)


#: red-marginalization grid size for the partially-collapsed common-rho
#: draw (log-spaced over [red_rhomin, red_rhomax]; ~0.1 dex spacing over
#: the 6-decade prior — the integrand varies on O(1)-dex scales)
RHO_COLLAPSE_J = 64
#: opt-in switch for the partially-collapsed draw — measured
#: net-negative at the bench scale, see _rho_collapsed_applies
RHO_COLLAPSE = os.environ.get("PTGIBBS_RHO_COLLAPSE", "") == "1"


def _rho_collapsed_applies(cm: CompiledPTA) -> bool:
    """Static predicate: the partially-collapsed common-rho draw applies
    to CRN models whose per-pulsar free-spectrum red shares the common
    Fourier columns.

    OPT-IN (``PTGIBBS_RHO_COLLAPSE=1``), measured net-negative on the
    45-pulsar bench and therefore off by default: collapsing red out of
    the rho draw cut the common-rho ACT only 49 -> 38 sweeps while its
    quadrature cost took the sweep from 63.5 to 45.3/s — ess_per_sec
    75.7 vs 83.2 uncollapsed.  The experiment's real yield is the
    diagnosis: with red marginalized the ACT barely moved, and the f64
    oracle (reference blocking) measures ~27 on a chain long enough to
    resolve it — the funnel is rho <-> b (the coefficients' total power
    re-drawn against the prior variance they inform, relative step
    ~1/sqrt(2P) per sweep), intrinsic to the vHV Gibbs blocking on BOTH
    backends, not the red/common degeneracy this move targets."""
    # sampled red slots only (red_rho_ix_x < nx): Constant-red models
    # must keep the conditional draw — marginalizing a FIXED amplitude
    # over its prior (with no compensating redraw) would target the
    # wrong posterior
    return (RHO_COLLAPSE and cm.orf_name == "crn"
            and cm.red_kind == "free_spectrum" and cm.red_shares_gw
            and bool(np.any(np.asarray(cm.red_rho_ix_x) < cm.nx)))


#: step scale (natural log of the variance ratio) for the interweaving
#: rho <-> b rescale move; ~0.28 dex proposals against a posterior whose
#: per-bin log-rho width is O(0.5-1) dex
RHO_SCALE_SIGMA = 0.65


def _rho_scale_applies(cm: CompiledPTA) -> bool:
    """Static predicate for :func:`rho_scale_moves`: CRN free-spectrum
    common blocks with diagonal N (the cheap residual delta assumes
    it), shared by both sweep bodies so the gate cannot drift."""
    return (cm.orf_name == "crn" and cm.gw_kind == "free_spectrum"
            and bool(cm.K) and len(cm.rho_ix_x) > 0 and not cm.has_ke)


def rho_scale_moves(cm: CompiledPTA, x, b, u, key, beta=None):
    """Interweaving (ancillary) scale moves along the rho <-> b funnel:
    per frequency k, jointly propose ``rho_k -> e^z rho_k`` and
    ``b_{pk} -> e^{z/2} b_{pk}`` on the shared GW columns, Metropolis-
    accepted with the exact joint density ratio plus the transform's
    Jacobian ``e^{z n_coeff / 2}``.

    This targets the slow direction the r5 collapse experiment isolated
    (:func:`_rho_collapsed_applies`): the conditional scan re-draws
    rho_k | tau_k and b | rho alternately, a ~1/sqrt(2P)-relative
    random walk along the (coefficient power, prior variance) ridge on
    which BOTH backends measure ACT ~27-50 sweeps.  The scale move
    slides ALONG the ridge: the prior term is nearly invariant (exactly
    invariant where red-free: ``N(e^{z/2} b; 0, e^z rho)`` matches the
    Jacobian), so the accept ratio is dominated by the white-residual
    likelihood change — one per-frequency two-column matvec.

    Exactness: a standard Metropolis-within-Gibbs kernel on (rho_k, b)
    — the deterministic scaling ``T_z`` with symmetric lognormal ``z``
    and the |det T_z'| correction, rejected outside the rho prior
    bounds.  Cost: ~0.3 ms/sweep TOTAL for all K moves (bench
    throughput unchanged, 63.5 vs 63.7 sweeps/s with the move on);
    applied where :func:`_rho_scale_applies` (the reference's sampler
    has no such move — its funnel random-walks, ``pta_gibbs.py:205``).

    Returns ``(x, b, u)`` with the cached matvec updated in place.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    cdt = cm.cdtype
    fdt = cm.dtype
    B, P, K = cm.Bmax, cm.P, cm.K
    gsin = jnp.asarray(cm.gw_sin_ix, jnp.int32)
    gcos = jnp.asarray(cm.gw_cos_ix, jnp.int32)
    live = jnp.asarray(cm.psr_mask, cdt)
    redv = cm.red_phi(x)                                  # (P, K) aligned
    N = cm.ndiag_fast(x)
    toam = jnp.asarray(cm.toa_mask, fdt)
    invN = toam / N.astype(fdt)
    y = jnp.asarray(cm.y, cm.dtype)
    lo = np.log(cm.rhomin)
    hi = np.log(cm.rhomax)
    pr_ar = jnp.arange(P)

    def step(carry, args):
        x, b, u = carry
        k, key = args
        kz, ka = jr.split(key)
        z = RHO_SCALE_SIGMA * jr.normal(kz, dtype=cdt)
        sk = jnp.clip(jnp.take(gsin, k, axis=1), 0, B - 1)    # (P,)
        ck = jnp.clip(jnp.take(gcos, k, axis=1), 0, B - 1)
        vs = ((jnp.take(gsin, k, axis=1) >= 0)
              & (jnp.take(gsin, k, axis=1) < B)).astype(cdt) * live
        vc = ((jnp.take(gcos, k, axis=1) >= 0)
              & (jnp.take(gcos, k, axis=1) < B)).astype(cdt) * live
        bs = b[pr_ar, sk] * vs
        bc = b[pr_ar, ck] * vc
        # two-column matvec: this frequency's contribution to u = T b
        Ts = jnp.take_along_axis(
            jnp.asarray(cm.T, cm.dtype), sk[:, None, None], axis=2)[:, :, 0]
        Tc = jnp.take_along_axis(
            jnp.asarray(cm.T, cm.dtype), ck[:, None, None], axis=2)[:, :, 0]
        t = (Ts * bs.astype(fdt)[:, None] + Tc * bc.astype(fdt)[:, None])
        # white-likelihood delta for u -> u + delta * t
        delta = (jnp.exp(0.5 * z) - 1.0).astype(fdt)
        r = y - u
        dll = (delta * jnp.sum(r * t * invN)
               - 0.5 * delta * delta * jnp.sum(t * t * invN))
        if beta is not None:
            # tempered likelihood delta; the prior/Jacobian terms below
            # are untempered (pi_beta ~ L^beta * prior)
            dll = dll * beta.astype(dll.dtype)
        # prior delta: tau' = e^z tau against phi' = e^z rho + red
        rix = jnp.asarray(cm.rho_ix_x, jnp.int32)[k]
        lrho = 2.0 * np.log(10.0) * jnp.asarray(x, cdt)[rix]  # ln rho
        rho = jnp.exp(lrho)
        tau = 0.5 * (bs * bs + bc * bc)                       # (P,)
        ez = jnp.exp(z)
        phi0 = rho + redv[:, jnp.minimum(k, K - 1)]
        phi1 = ez * rho + redv[:, jnp.minimum(k, K - 1)]
        nv = vs + vc                                          # coeff count
        dlp = jnp.sum(jnp.where(
            nv > 0,
            -(ez * tau / phi1 - tau / phi0)
            - 0.5 * nv * (jnp.log(phi1) - jnp.log(phi0)),
            jnp.zeros((), cdt)))
        njac = 0.5 * jnp.sum(nv) * z                          # log |det|
        inb = (lrho + z > lo) & (lrho + z < hi)
        logr = jnp.where(inb, dll.astype(cdt) + dlp + njac, -jnp.inf)
        acc = logr > jnp.log(jr.uniform(ka, dtype=cdt))
        scale = jnp.where(acc, jnp.exp(0.5 * z), 1.0)
        b = b.at[pr_ar, sk].set(jnp.where(vs > 0, b[pr_ar, sk] * scale,
                                          b[pr_ar, sk]))
        b = b.at[pr_ar, ck].set(jnp.where(vc > 0, b[pr_ar, ck] * scale,
                                          b[pr_ar, ck]))
        u = jnp.where(acc, u + delta * t, u)
        x = jnp.where(acc, x.at[rix].add(
            (0.5 / np.log(10.0) * z).astype(x.dtype)), x)
        return (x, b, u), None

    keys = jr.split(key, K)
    (x, b, u), _ = jax.lax.scan(step, (x, b, u),
                                (jnp.arange(K), keys))
    return x, b, u


def rho_update(cm: CompiledPTA, x, b, key):
    """Free-spectrum conditional draw of the common (GW) log10_rho block.

    Single pulsar without intrinsic red noise: exact truncated inverse-CDF
    (vHV2014, reference ``pulsar_gibbs.py:215-216``).  Otherwise: per-pulsar
    log-PDF grids summed over the pulsar axis (== the PDF product of
    ``pta_gibbs.py:205``; the sum turns into a ``psum`` over ICI when the
    pulsar axis is sharded) then Gumbel-max sampled (``:233-234``).

    ``PTGIBBS_RHO_COLLAPSE=1`` (opt-in) replaces the shared-column
    free-spectrum draw with a PARTIALLY-COLLAPSED one: rho_k drawn with
    the per-pulsar red amplitudes INTEGRATED OUT over their log-uniform
    prior (a log-spaced ``RHO_COLLAPSE_J``-point quadrature — the same
    grid-resolution error class as the grid draws themselves), the
    sweep body redrawing red | rho immediately after
    (:func:`red_conditional_update`): together an exact blocked draw of
    (rho, red) | b.  Off by default — measured net-negative; see
    :func:`_rho_collapsed_applies` for the numbers and for what the
    experiment actually established (the funnel is rho <-> b, shared
    with the reference's identical blocking)."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    if cm.K == 0 or len(cm.rho_ix_x) == 0:
        return x
    tau = cm.gw_tau(b)  # (P, K)
    if cm.orf_name != "crn":
        # correlated ORF: p(rho_k | a) ~ rho^-P exp(-taut_k/rho) with the
        # quadratic form taut_k = 0.5 sum_phase a_k^T G^-1 a_k (reduces to
        # sum_p tau_pk at G = I)
        fdt = cm.dtype
        Ginv = cm.orf_ginv_k(x)                         # (K, P, P)
        live = jnp.asarray(cm.psr_mask, cm.cdtype)
        taut = jnp.zeros((cm.K,), cm.cdtype)
        for ix in (cm.gw_sin_ix, cm.gw_cos_ix):
            a = jnp.take_along_axis(b, ix, axis=1) * live[:, None]  # (P, K)
            taut = taut + 0.5 * jnp.einsum("pk,kpq,qk->k", a, Ginv, a)
        grid = _rho_grid(cm, cm.rhomin, cm.rhomax)
        logpdf = (-cm.P_real * jnp.log(grid)[None, :]
                  - (taut[:, None] / grid[None, :]).astype(fdt))
        gum = jr.gumbel(key, logpdf.shape, dtype=fdt)
        rhonew = grid[jnp.argmax(logpdf + gum, axis=-1)]
        return x.at[cm.rho_ix_x].set(
            (0.5 * jnp.log10(rhonew)).astype(x.dtype))
    if cm.P_real == 1 and cm.red_kind == "":
        # clamp tau away from zero: at tau=0 the inverse-CDF below is 0/0
        # (the NaN source of round 1 — b starts at zeros), and the clamped
        # draw converges to the correct tau->0 limit p(rho) ~ 1/rho^2 with
        # relative density error exp(-tau_min/rho) - 1 ~ 1e-6
        t = jnp.maximum(tau[0], cm.rhomin * 1e-6)
        k1, = jr.split(key, 1)
        hi = -jnp.expm1(t / cm.rhomax - t / cm.rhomin)
        eta = hi * jr.uniform(k1, t.shape, dtype=cm.cdtype)
        rhonew = t / (t / cm.rhomax - jnp.log1p(-eta))
    elif _rho_collapsed_applies(cm):
        grid = _rho_grid(cm, cm.rhomin, cm.rhomax)
        fdt = cm.dtype
        grid32 = grid.astype(fdt)
        lgrid = jnp.log(grid32)
        ltau = jnp.log(tau).astype(fdt)                 # (P, K)
        redg = 10.0 ** jnp.linspace(
            np.log10(cm.red_rhomin), np.log10(cm.red_rhomax),
            RHO_COLLAPSE_J, dtype=fdt)
        # (p, k) slots where a SAMPLED red amplitude shares the column
        # (per-slot: heterogeneous mode counts leave high-k slots of
        # short-red pulsars red-free, and Constant red params must not
        # be marginalized — both carry the nx sentinel in red_rho_ix_x)
        Kr = cm.red_rho_ix_x.shape[1]
        n = min(cm.K, Kr)
        samp = jnp.asarray(cm.red_rho_ix_x, jnp.int32) < cm.nx      # (P, Kr)
        ap = jnp.zeros((cm.P, cm.K), bool).at[:, :n].set(samp[:, :n])
        pmask = jnp.asarray(cm.psr_mask, fdt) > 0

        def per_k(args):
            ltk, apk = args                             # (P,), (P,)
            # marginal factor: logsumexp over the red quadrature
            lr = ltk[:, None, None] - jnp.log(
                grid32[None, :, None] + redg[None, None, :])
            lm = jax.nn.logsumexp(lr - jnp.exp(lr), axis=-1) \
                - jnp.log(jnp.asarray(RHO_COLLAPSE_J, fdt))  # (P, R)
            # no-red slots keep the plain conditional factor
            lp = ltk[:, None] - lgrid[None, :]
            plain = lp - jnp.exp(lp)
            lm = jnp.where(apk[:, None], lm, plain)
            return jnp.sum(jnp.where(pmask[:, None], lm,
                                     jnp.zeros((), fdt)), axis=0)

        # lax.map over K bounds the (P, R, J) transient to one frequency
        logpdf = jax.lax.map(per_k, (ltau.T, ap.T))     # (K, R)
        gum = jr.gumbel(key, logpdf.shape, dtype=fdt)
        rhonew = grid[jnp.argmax(logpdf + gum, axis=-1)]
    else:
        grid = _rho_grid(cm, cm.rhomin, cm.rhomax)
        fdt = cm.dtype
        ltau = jnp.log(tau).astype(fdt)
        lother = jnp.log(cm.red_phi(x)).astype(fdt)
        logratio = (ltau[:, :, None]
                    - jnp.logaddexp(lother[:, :, None],
                                    jnp.log(grid)[None, None, :]))
        logpdf = logratio - jnp.exp(logratio)
        # mask by WHERE, not multiply: a pad pulsar with an exactly-zero
        # coefficient pair has log tau = -inf, and 0 * -inf = NaN would
        # silently send every rho_k to the grid floor (argmax of a NaN
        # row is index 0) — a finite chain no _check_finite can flag
        logpdf = jnp.sum(jnp.where(
            jnp.asarray(cm.psr_mask, fdt)[:, None, None] > 0,
            logpdf, jnp.zeros((), fdt)), axis=0)
        gum = jr.gumbel(key, logpdf.shape, dtype=fdt)
        rhonew = grid[jnp.argmax(logpdf + gum, axis=-1)]
    return x.at[cm.rho_ix_x].set(
        (0.5 * jnp.log10(rhonew)).astype(x.dtype))


def red_conditional_update(cm: CompiledPTA, x, b, key):
    """Per-pulsar intrinsic red free-spectrum conditional draw with the
    common GW process as the 'other' phi component (reference
    ``pta_gibbs.py:252-276``)."""
    import jax.numpy as jnp
    import jax.random as jr

    tau = cm.red_tau(b)
    grid = _rho_grid(cm, cm.red_rhomin, cm.red_rhomax)
    fdt = cm.dtype
    ltau = jnp.log(tau).astype(fdt)
    lother = jnp.log(cm.gw_phi_at_red(x)).astype(fdt)
    logratio = (ltau[:, :, None]
                - jnp.logaddexp(lother[:, :, None],
                                jnp.log(grid)[None, None, :]))
    logpdf = logratio - jnp.exp(logratio)
    gum = jr.gumbel(key, logpdf.shape, dtype=fdt)
    rhonew = grid[jnp.argmax(logpdf + gum, axis=-1)]  # (P, Kr)
    return x.at[cm.red_rho_ix_x].set(
        (0.5 * jnp.log10(rhonew)).astype(x.dtype), mode="drop")


#: log10 bounds and size of the alpha grid for the t-process conditional;
#: the InvGamma(1, 1) prior holds ~all its mass in [1e-4, 1e4] and the
#: likelihood tail decays as alpha^-2 past tau/plaw, so the grid brackets
#: every non-negligible posterior
TP_ALPHA_LOG10_MIN = -4.0
TP_ALPHA_LOG10_MAX = 10.0
TP_ALPHA_GRID = 1000


def tprocess_alpha_update(cm: CompiledPTA, x, b, key):
    """Per-frequency draw of the t-process scale factors.

    The shared Fourier columns carry ``phi_j = rho_gw,j + alpha_j
    plaw_j`` (common + intrinsic contributions are additive there), so
    the alpha conditional under the ``InvGamma(1, 1)`` prior
    (enterprise_extensions ``t_process``, df=2) is

        p(alpha | b) ~ alpha^-2 e^(-1/alpha)
                       (o_j + alpha plaw_j)^-1 exp(-tau_j/(o_j + alpha plaw_j))

    with ``o_j`` the common-process variance aligned to the red grid and
    ``tau_j = (b_sin^2 + b_cos^2)/2``.  Sampled by Gumbel-max on a
    log-uniform grid — the same mechanism as the rho conditionals (it
    reduces to the exact conjugate InvGamma(2, 1 + tau/plaw) draw as
    ``o -> 0``).  A Gibbs block the reference never had (its t-process
    models could only be sampled by generic MH through enterprise)."""
    import jax.numpy as jnp
    import jax.random as jr

    from .compiled import _lnphi_powerlaw

    fdt = cm.dtype
    xev = cm.xe(x)
    tau = cm.red_tau(b)                                   # (P, Kr)
    args = [xev[cm.red_hyp_ix[:, h]][:, None] for h in range(2)]
    lnplaw = _lnphi_powerlaw(cm.red_f, cm.red_df, *args)  # (P, Kr)
    other = cm.gw_phi_at_red(x)                           # (P, Kr)
    grid = 10.0 ** jnp.linspace(TP_ALPHA_LOG10_MIN, TP_ALPHA_LOG10_MAX,
                                TP_ALPHA_GRID, dtype=cm.cdtype)
    # log phi on the grid, computed in log space to stay range-safe
    lnvar = jnp.logaddexp(jnp.log(other)[:, :, None],
                          lnplaw[:, :, None] + jnp.log(grid)[None, None, :])
    # point mass on the log-spaced grid = density(alpha) * alpha (Jacobian):
    # prior alpha^-2 e^(-1/alpha) contributes -2 ln a + ln a = -ln a
    logpdf = (-jnp.log(grid)[None, None, :] - 1.0 / grid[None, None, :]
              - lnvar - tau[:, :, None] * jnp.exp(-lnvar)).astype(fdt)
    gum = jr.gumbel(key, logpdf.shape, dtype=fdt)
    alpha = grid[jnp.argmax(logpdf + gum, axis=-1)]       # (P, Kr)
    return x.at[cm.red_rho_ix_x].set(alpha.astype(x.dtype), mode="drop")


def lnlike_orf_fn(cm: CompiledPTA, b):
    """b-conditional likelihood of the sampled ORF weights (bin_orf /
    legendre_orf): for each (frequency, phase) group the gw coefficients
    are jointly ``N(0, rho_k G(theta))``, so up to theta-independent
    constants

        ln L(theta) = -K ln det G - 0.5 sum_{k,phase} a_k^T G^-1 a_k / rho_k

    (two phases give the K, not K/2, logdet factor).  Non-PD proposals
    produce a NaN Cholesky and are rejected by the MH accept's finite
    guard — the chain never leaves the PD region it starts in."""
    import jax
    import jax.numpy as jnp

    live = jnp.asarray(cm.psr_mask, cm.cdtype)
    a_s = jnp.take_along_axis(b, jnp.asarray(cm.gw_sin_ix), axis=1)
    a_c = jnp.take_along_axis(b, jnp.asarray(cm.gw_cos_ix), axis=1)
    A = jnp.stack([a_s, a_c], axis=-1) * live[:, None, None]   # (P, K, 2)

    def lnlike(q):
        G = cm.orf_G(q)
        L = jnp.linalg.cholesky(G)                # NaN if theta not PD
        logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(L)))
        rho = 10.0 ** (2.0 * jnp.asarray(q, cm.cdtype)[cm.rho_ix_x])
        w = jax.scipy.linalg.solve_triangular(
            L, A.reshape(cm.P, -1), lower=True)   # (P, K*2)
        quad = jnp.sum(w.reshape(cm.P, -1, 2) ** 2
                       / rho[None, :, None])
        return -cm.K * logdet - 0.5 * quad

    return lnlike


#: default period of the near-exact Metropolised refresh
#: (:func:`draw_b_refresh`) interleaved with the cheap f32-proposal draw,
#: bounding how long an occasional ill-conditioned f32 proposal can leave
#: a pulsar's coefficients unmoved (driver kwarg ``exact_every``;
#: stationarity is exact at ANY period — the Hastings accept corrects
#: both proposals — so the period trades only worst-case stickiness
#: against the refresh cost, ~45 ms at C=64 vs the ~10 ms every-sweep
#: body; the pure-f64 draw this slot used to run cost 148.7 ms).  The
#: period was MEASURED, not argued: per-coordinate chain ACT over every
#: hyperparameter channel and every recorded b coefficient is flat
#: across exact_every in {4, 8, 16, 32} on the 45-pulsar bench model
#: (docs/EXACT_EVERY.md, tools/exact_every_probe.py); the default takes
#: 16 — half the refresh cost of 8, with the 32 row showing a further
#: halving still costs nothing at typical states (16 keeps margin for
#: the rare ill-conditioned states the refresh exists to bound)
EXACT_EVERY = 16
#: correlated-ORF arrays up to this many total coefficients use the
#: dense joint b-draw (best mixing: one exact draw of everything);
#: larger arrays use the sequential pulsar-wise conditional sweep —
#: the dense recursive factor's XLA program grows ~O((P Bmax)^2):
#: measured scanned-sweep compile 242 s at dim 108 vs 47 s sequential
#: (CPU, 4 real pulsars), and the remote-compile transport breaks
#: outright by dim 1665.  64 keeps the dense draw for toy systems where
#: its compile is cheap and routes real-size arrays to the sequential
#: sweep, whose program size is O(Bmax^2) regardless of pulsar count
HD_DENSE_MAX = 64
#: diagonal ridge on the f32-preconditioned proposal system: larger than
#: the f32 entry rounding of the unit-diagonal matrix so its Cholesky
#: cannot break down, small enough to barely touch the proposal shape
_PROP_RIDGE = 4e-6


def b_matvec(cm: CompiledPTA, b):
    """``u = T b`` in the storage dtype — the sufficient statistic for the
    white-noise part of the exact b log-density; cached across sweeps
    because it depends only on ``b``.  ``precision="highest"`` matters:
    TPU's default matmul precision multiplies in bf16 (~1e-3 relative),
    which would perturb the MH target by O(0.1) in log density; full-f32
    multiplies keep the documented ~1e-5 accuracy."""
    import jax.numpy as jnp

    return jnp.einsum("pnb,pb->pn", cm.T, b.astype(cm.dtype),
                      precision="highest")


def _logpi_b_per(cm: CompiledPTA, x, b, u, beta=None):
    """Per-pulsar log pi(b | x) up to b-independent constants, from the
    cached matvec ``u = T b``: ``-0.5 u^2/N + (y/N) u - 0.5 b^2/phi``.
    f32 elementwise with f64 accumulation: the absolute error is ~1e-5 on
    an O(100) log-ratio — far below what an accept/reject step can see.

    ``beta`` (parallel tempering, sampler/ensemble.py) scales the
    LIKELIHOOD term only — the b-prior stays untempered, matching
    ``pi_beta ~ L^beta * prior``.  None (the default) traces the exact
    pre-tempering program."""
    import jax.numpy as jnp

    fdt = cm.dtype
    N = cm.ndiag_fast(x)
    t1 = ((-0.5 * u + jnp.asarray(cm.y, cm.dtype)) * (u / N)
          * jnp.asarray(cm.toa_mask, fdt))
    if beta is not None:
        t1 = t1 * beta.astype(fdt)
    phi32 = cm.phi(x, dtype=fdt)
    bb = b.astype(fdt)
    t2 = -0.5 * bb * bb / phi32
    return (jnp.sum(t1.astype(cm.cdtype), axis=1)
            + jnp.sum(t2.astype(cm.cdtype), axis=1))


def _logpi_b_pair(cm: CompiledPTA, x, b_old, b_new, u_old, u_new,
                  beta=None):
    """Both sides of the MH log-density ratio in one fused pass: stacks
    (old, new) on a leading axis so ``N``, ``phi`` and the masked
    reductions are computed once and the elementwise work runs as one
    batched kernel instead of two :func:`_logpi_b_per` calls.  Same
    error class (f32 elementwise, f64 accumulation).  Returns
    ``(lpi_old, lpi_new)``, each ``(P,)`` in the compute dtype."""
    import jax.numpy as jnp

    fdt = cm.dtype
    N = cm.ndiag_fast(x)
    uu = jnp.stack([u_old, u_new])
    t1 = ((-0.5 * uu + jnp.asarray(cm.y, cm.dtype)) * (uu / N)
          * jnp.asarray(cm.toa_mask, fdt))
    if beta is not None:
        t1 = t1 * beta.astype(fdt)
    phi32 = cm.phi(x, dtype=fdt)
    bb = jnp.stack([b_old, b_new]).astype(fdt)
    t2 = -0.5 * bb * bb / phi32
    lp = (jnp.sum(t1.astype(cm.cdtype), axis=2)
          + jnp.sum(t2.astype(cm.cdtype), axis=2))
    return lp[0], lp[1]


def draw_b_mh(cm: CompiledPTA, x, b, u, key, beta=None):
    """Metropolised b-draw: propose from the f32-factored conditional,
    accept per pulsar with the exact Hastings ratio.

    The exact f64 draw (:func:`draw_b_fn`) costs ~15 ms/sweep in TPU's
    software f64; the f32 proposal pipeline (MXU einsum, native batched
    Cholesky + triangular solves) is essentially free, and the exact
    log-density ratio needs only one ``T b'`` matvec thanks to the cached
    ``u = T b``.  The f32 factor is a *proposal* — any error only lowers
    acceptance (measured ~98% mean across states; per-pulsar accepts keep
    one hard pulsar from stalling the rest, and the periodic exact draw
    in the sweep body bounds worst-case stickiness).  The chain's
    stationary distribution stays the exact conditional.

    Returns ``(b', u', accepted_mask)``.
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import kernels

    fdt = cm.dtype
    k1, k2 = jr.split(key)
    # ---- f32 proposal: N(mean32, Sigma32^-1) ------------------------------
    # full-f32 multiplies here too: bf16 default precision would blur the
    # proposal mean/covariance and only lower acceptance, but the 3-pass
    # f32 MXU path is still essentially free next to the f64 work
    N = cm.ndiag_fast(x)
    if beta is not None:
        # tempered conditional: L^beta is Gaussian with N -> N / beta,
        # which scales TNT and d below in one place (prior untempered)
        N = N / beta.astype(N.dtype)
    # all-f32 segmented augmented Gram through the kernel tier: TNT and
    # d from one fused accumulate (tnt_d_seg32) instead of the old
    # monolithic pair of einsums — same f32 proposal error class,
    # bounded per-segment dots, and one Mosaic kernel under
    # kernel_tier="pallas"
    TNT, d = tnt_d_seg32(cm, N)
    phi32 = cm.phi(x, dtype=fdt)
    eye = jnp.eye(cm.Bmax, dtype=fdt)
    Sig = TNT + (1.0 / phi32)[:, :, None] * eye
    # matmul-scheduled factorization with the explicit inverse: XLA's
    # native batched cholesky + triangular solves lower to sequential
    # small-slice loops on TPU and cost 12.6 ms at the (64, 45, 37, 37)
    # bench shape vs 2.1 ms for blocked_chol_inv + matvecs
    # (tools/chol_probe.py) — 75% of the whole steady sweep was this
    # lowering (tools/sweep_probe.py: b_mh 13.5 ms of full_sweep 17.9).
    # The fused chol->solve->sample kernel (ops/kernels) runs the
    # factor, both triangular solves, and the N(0, I) injection in one
    # VMEM-resident pass — one HBM round-trip instead of four; the XLA
    # tier is the identical jacobi_factor_mean_prop lowering as before
    z = jr.normal(k1, (cm.P, cm.Bmax), fdt)
    L, Li, dj, mean, bp32 = kernels.chol_solve_sample(Sig, d, z,
                                                      ridge=_PROP_RIDGE)
    bp = bp32.astype(cm.cdtype)
    up = b_matvec(cm, bp)
    # ---- exact log-density ratio + proposal correction --------------------
    lpi_old, lpi_new = _logpi_b_pair(cm, x, b, bp, u, up, beta=beta)
    # logq(v) = -0.5 || L^T ((v - mean)/dj) ||^2 (+ const that cancels);
    # for the fresh proposal that quadratic form is exactly ||z||^2 —
    # which is why w_old needs full-f32 precision: it enters the ratio
    # against that exactly-known value
    w_old = jnp.einsum("pji,pj->pi", L, (b.astype(fdt) - mean) / dj,
                       precision="highest")
    logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1).astype(cm.cdtype)
    logq_new = -0.5 * jnp.sum(z * z, axis=1).astype(cm.cdtype)
    logr = (lpi_new - lpi_old) + (logq_old - logq_new)
    ok = (jnp.all(jnp.isfinite(bp32), axis=1) & jnp.isfinite(logr))
    logu = jnp.log(jr.uniform(k2, (cm.P,), cm.cdtype))
    acc = ok & (logr > logu)
    b_new = jnp.where(acc[:, None], bp, b)
    u_new = jnp.where(acc[:, None], up, u)
    return b_new, u_new, acc


def draw_b_refresh(cm: CompiledPTA, x, b, u, key, beta=None):
    """Near-exact Metropolised b-refresh: propose from the segmented-Gram
    conditional factored in f64, accept with the exact Hastings ratio.

    This replaces the pure-f64 exact draw in the periodic refresh slot of
    the sweep (``exact_every``): the proposal differs from the true
    conditional only by the segmented Gram's ~2.5e-7 accumulation error
    (:func:`tnt_d_seg`) and the two-float factor's ~1e-5 congruence
    residual (``tf_chol_factor``: ridge-corrected, so no O(1) distortion
    of the softest directions) — acceptance measured ~0.9999 mean /
    ~0.97 worst-pulsar on the warmed 45-pulsar bench state, and the
    Hastings accept keeps the stationary law the *exact* conditional
    regardless.  Cost ~tens of ms vs the f64 draw's 148.7 ms at C=32.

    Against the per-sweep f32-proposal draw (:func:`draw_b_mh`, ridge
    ``_PROP_RIDGE`` distorting the softest directions by O(1) when
    ``lambda_min ~ ridge``), this proposal's factor is ridge-corrected:
    soft-direction stickiness that survives the f32 draw is cleared
    here, preserving the exact draw's role at a fraction of its price.
    Returns ``(b', u', accepted)``.
    """
    import jax.numpy as jnp
    import jax.random as jr

    from ..ops import kernels
    from ..ops.linalg import _batched_diag

    cdt = cm.cdtype
    k1, k2 = jr.split(key)
    N = cm.ndiag_fast(x)
    if beta is not None:
        # tempered conditional (see draw_b_mh): N -> N / beta
        N = N / beta.astype(N.dtype)
    TNT, d = tnt_d_seg(cm, N)
    phi = cm.phi(x)
    Sig = TNT + _batched_diag(1.0 / phi)
    # factor="tf": tf_chol_factor applies _PROP_RIDGE to its f32 stage
    # only and removes the distortion in the two-float correction — so
    # the ridge rides the factor, not the helper.  The exact body stays
    # on the kernel tier's XLA path by design (Mosaic has no f64; the
    # mixed-precision island map puts only f32 steady bodies in Pallas)
    z = jr.normal(k1, (cm.P, cm.Bmax), cdt)
    L, Li, dj, mean, bp = kernels.chol_solve_sample(
        Sig, d, z, ridge=_PROP_RIDGE, factor="tf")
    up = b_matvec(cm, bp)
    lpi_old, lpi_new = _logpi_b_pair(cm, x, b, bp, u, up, beta=beta)
    w_old = jnp.einsum("pji,pj->pi", L, (b - mean) / dj)
    logq_old = -0.5 * jnp.sum(w_old * w_old, axis=1)
    logq_new = -0.5 * jnp.sum(z * z, axis=1)
    logr = (lpi_new - lpi_old) + (logq_old - logq_new)
    ok = jnp.all(jnp.isfinite(bp), axis=1) & jnp.isfinite(logr)
    logu = jnp.log(jr.uniform(k2, (cm.P,), cdt))
    acc = ok & (logr > logu)
    b_new = jnp.where(acc[:, None], bp, b)
    u_new = jnp.where(acc[:, None], up, u)
    return b_new, u_new, acc


def residual_sq(cm: CompiledPTA, b):
    """(y - T b)^2 in the storage dtype: |T_i . b| ~ |y| so the f32 matvec
    error is ~1e-5 relative to the residual — far below what the white MH
    deltas can resolve anyway."""
    import jax.numpy as jnp

    r = jnp.asarray(cm.y, cm.dtype) - jnp.einsum("pnb,pb->pn", cm.T,
                                       b.astype(cm.dtype),
                                       precision="highest")
    return r * r


# ===========================================================================
# driver
# ===========================================================================

class JaxGibbsDriver:
    """Backend implementing the facade's run/adapt-state protocol on device.

    ``hypersample``/``redsample`` are the reference's block-kernel
    selectors (``pulsar_gibbs.py:42``): ``None`` means auto (block
    activation follows the compiled model — free-spectrum intrinsic red
    gets the per-pulsar grid draw, powerlaw-family hypers the adaptive MH
    block); explicit values are validated against the structure and raise
    when they ask for an unimplemented kernel.
    """

    def __init__(self, pta, hypersample=None, redsample=None,
                 ecorrsample=None,
                 seed=None, common_rho=False, white_adapt_iters=1000,
                 red_adapt_iters=2000, red_steps=20, chunk_size=None,
                 pad_pulsars=None, mesh=None, warmup_sweeps=50,
                 warmup_white_steps=16, white_steps_max=64, nchains=1,
                 exact_every=EXACT_EVERY, record_precision=None,
                 record_every=1, transfer_guard=False, sentinels=True,
                 joint_mixed=None, watchdog=None, obs=None,
                 ensemble=None, pt_ladder=None, megachunk=None):
        settings.apply()
        import jax
        import jax.random as jr

        from .blocks import validate_sampling_flags

        validate_sampling_flags(pta, hypersample, ecorrsample, redsample)
        self._jax, self._jr = jax, jr
        self.cm = compile_pta(pta, pad_pulsars=pad_pulsars,
                              kernel_ecorr=(ecorrsample == "kernel"))
        #: the mesh (or None) is remembered for the checkpoint manifest's
        #: shard_map section — physical placement, recorded separately
        #: from the logical layout precisely so resume can change it
        self._mesh = mesh
        if mesh is not None:
            from ..parallel.sharding import shard_compiled

            self.cm = shard_compiled(self.cm, mesh)
        #: dispatch watchdog (runtime.watchdog): ``True`` builds the
        #: default EMA-deadline guard, an instance is used as-is, and
        #: None/False runs unguarded.  The guard never touches traced
        #: values (zero retraces) — it only times the blocking chunk
        #: work on a worker thread so a hung dispatch becomes the
        #: retryable ``stall`` failure class instead of a silent hang
        if watchdog is True:
            self.watchdog = DispatchWatchdog()
        elif isinstance(watchdog, DispatchWatchdog):
            self.watchdog = watchdog
        elif watchdog in (None, False):
            self.watchdog = None
        else:
            raise ValueError(
                "watchdog must be True/False/None or a DispatchWatchdog "
                f"instance, got {watchdog!r}")
        self.nb_total = int(sum(self.cm.widths))
        self.white_adapt_iters = white_adapt_iters
        self.red_adapt_iters = red_adapt_iters
        self.red_steps = red_steps
        #: pinned autotune defaults (tools/autotune.py -> AUTOTUNE.json):
        #: consulted only under PTGIBBS_AUTOTUNE, and only for dispatch
        #: geometry the caller left unset — never overriding an explicit
        #: chunk_size/megachunk argument, and never touching the sampled
        #: process (every geometry is bitwise-identical by the key-fold
        #: policy; the table tunes amortization only)
        tuned = (config_mod.autotune_defaults()
                 if os.environ.get("PTGIBBS_AUTOTUNE") else None) or {}
        self.chunk_size = (chunk_size or tuned.get("chunk")
                           or settings.chunk_size)
        #: mega-chunk factor: sub-chunks scanned back to back inside ONE
        #: device dispatch (the device-resident steady loop).  The outer
        #: scan re-selects the DE history buffers per sub-chunk, so each
        #: sub sees exactly the history the legacy chunk grid would —
        #: the sampled process is bitwise-identical for every value.
        #: 1 (default) is the legacy one-chunk-per-dispatch loop.
        if megachunk is None and tuned.get("megachunk"):
            megachunk = tuned["megachunk"]
        self.megachunk = int(settings.megachunk if megachunk is None
                             else megachunk)
        if self.megachunk < 1:
            raise ValueError("megachunk must be >= 1")
        #: dtype of the recorded per-sweep states shipped device->host.
        #: "f32" (default) records in the storage dtype; "bf16" halves the
        #: dominant device-to-host payload again for bandwidth-starved
        #: links (e.g. a tunneled device) at ~0.4% relative quantization
        #: of the RECORD only — the sweep carry and checkpoints are exact,
        #: resume stays bitwise within a run, and the sampled process is
        #: identical to the f32-record run except that DE-jump history
        #: (refreshed from recorded chain rows past DE_DELAY) sees the
        #: rounded rows: the difference proposal stays symmetric, so
        #: stationarity is untouched while the realized proposal stream
        #: differs at rounding level.  Tested in
        #: tests/test_jax_backend.py::test_record_precision_bf16.
        rp = record_precision or settings.record_precision
        if rp not in ("f32", "bf16"):
            raise ValueError(f"record_precision must be 'f32' or 'bf16', "
                             f"got {rp!r}")
        import jax.numpy as _jnp
        # "f32" means float32 storage — also under settings.precision=
        # "f64" validation runs (it previously aliased cm.dtype and
        # silently recorded f64 there).  Both record dtypes share f32's
        # exponent range, so an f64 state beyond ~3.4e38 would record as
        # inf and trip _check_finite — chain states never approach that
        # (priors bound the hypers; b coefficients are O(residual))
        self.rdtype = _jnp.bfloat16 if rp == "bf16" else _jnp.float32
        #: on-device record thinning: ship every k-th sweep's state to the
        #: host (reference records every iteration, pulsar_gibbs.py:658-659;
        #: k=1 default keeps that).  The SAMPLED PROCESS is identical for
        #: every k — per-sweep keys are pure in the iteration index and the
        #: full-precision carry never passes through the record — only the
        #: recorded rows (and so chain.npy's length) change.  The binding
        #: constraint it relieves is the device->host record transfer
        #: (~52 MB/chunk f32 at C=64 over the bench's ~18 MB/s tunnel —
        #: tools/chunk_probe.py); with measured b-ACT medians ~2 sweeps
        #: (docs/EXACT_EVERY.md), k up to ~ACT keeps the chain's ESS while
        #: cutting the dominant payload by k.
        self.record_every = int(record_every)
        if self.record_every < 1:
            raise ValueError("record_every must be >= 1")
        if self.chunk_size % self.record_every:
            # thinning offsets are static in the compiled chunk; a stride
            # that does not divide the chunk would cycle through
            # record_every distinct offsets — record_every fresh ~30 s
            # compiles — instead of reusing one
            raise ValueError(
                f"record_every={self.record_every} must divide "
                f"chunk_size={self.chunk_size}")
        #: when True, every steady-loop chunk dispatch runs under
        #: ``jax.transfer_guard("disallow")`` (analysis.guards.no_transfers)
        #: so an implicit host<->device round-trip sneaking into the hot
        #: path raises instead of silently serializing the sweep
        self.transfer_guard = bool(transfer_guard)
        #: per-chunk health sentinel (runtime.sentinels): the compiled
        #: chunk already computes per-chain finite/moved reductions on
        #: device; the monitor turns them into metrics.jsonl warnings
        #: and raises ChainDivergence on persistently stuck chains
        self.sentinel = SentinelMonitor() if sentinels else None
        self.health_last = None
        self.warmup_sweeps = warmup_sweeps
        self.warmup_white_steps = warmup_white_steps
        self.exact_every = int(exact_every)
        #: mixed-precision mode of the structured correlated-ORF joint
        #: b-draw (draw_b_joint_structured): steady sweeps factor both
        #: stages with the two-float MXU kernel + one refinement step;
        #: every exact_every-th sweep refreshes in f64.  None defers to
        #: settings.joint_mixed; False forces f64 everywhere (validation)
        self.joint_mixed = (settings.joint_mixed if joint_mixed is None
                            else bool(joint_mixed))
        #: cap on the ACT-sized white/ECORR sub-chain length: with Laplace
        #: proposals the measured ACT is O(few); a larger measurement means
        #: a near-unidentified parameter whose exactness does not justify
        #: hundreds of device steps per sweep
        self.white_steps_max = white_steps_max
        #: number of independent chains vmapped over a leading axis
        self.C = int(nchains)
        if self.C < 1:
            raise ValueError("nchains must be >= 1")
        if mesh is not None:
            # a 2-d (chain, pulsar) mesh splits the vmapped chain axis:
            # C must divide the chain submesh or every (C, ...) carry
            # would need a ragged shard (actionable error, satellite 5)
            from ..parallel.sharding import validate_chains

            validate_chains(mesh, self.C)
        self.key = jr.key(np.random.SeedSequence(seed).generate_state(1)[0])
        #: common_rho asserts the model really has a shared free-spectrum
        #: block (PTABlockGibbs passes True); it is not a switch — the
        #: compiled structure decides, and a mismatch is a usage error
        if common_rho and not (self.cm.K and len(self.cm.rho_ix_x)):
            raise ValueError(
                "common_rho=True but the model has no shared free-spectrum "
                "gw block (build with common_psd='spectrum')")
        self.common_rho = common_rho

        cm = self.cm
        # block activation follows the compiled model structure (mirrors the
        # oracle sweeps): a red free-spectrum block gets the per-pulsar grid
        # draw, any powerlaw-family hypers (per-pulsar red and/or a varied
        # common process) get the adaptive MH block, t-process alphas get
        # their exact conjugate draw — independently
        self.do_tprocess = (cm.red_kind == "tprocess"
                            and bool(np.any(np.asarray(cm.red_rho_ix_x)
                                            < cm.nx)))
        self.do_red_conditional = (not self.do_tprocess
                                   and bool(np.any(np.asarray(cm.red_rho_ix_x)
                                                   < cm.nx)))
        self.do_red_mh = len(cm.idx.red) > 0
        if self.do_red_mh and self.record_every > 1:
            # the DE jump history is refreshed from recorded chain rows
            # addressed BY ITERATION INDEX (_de_hist_for); a thinned chain
            # no longer carries those rows, and silently decimating the
            # history would change the realized proposal stream with the
            # thinning setting — loud-reject instead
            raise ValueError(
                "record_every > 1 is unavailable for models with a "
                "red-hyper MH block: the DE jump history reads recorded "
                "chain rows by iteration index; run with record_every=1")
        if self.do_red_mh and self.chunk_size > DE_DELAY - DE_Q:
            # a larger chunk could outrun the DE history delay (rows not
            # yet written at dispatch), and a silent seed-freeze fallback
            # would make the sampled process depend on chunk_size —
            # breaking the chunk-grid-independence that bitwise resume
            # rests on
            raise ValueError(
                f"chunk_size={self.chunk_size} exceeds the DE history "
                f"delay margin ({DE_DELAY - DE_Q}); use chunk_size <= "
                f"{DE_DELAY - DE_Q} for models with a red hyper MH block")
        if (self.do_red_mh and self.megachunk > 1
                and (2 * self.megachunk - 1) * self.chunk_size
                > DE_DELAY - DE_Q):
            # the mega dispatch stages every sub-chunk's DE buffers up
            # front, while the PREVIOUS mega's rows are still in flight
            # (double buffering) — so the last sub-chunk's history must
            # predate the previous dispatch's first iteration too:
            # (2*n_sub - 1)*chunk_size <= DE_DELAY - DE_Q.  A silent
            # seed-freeze fallback would make the sampled process depend
            # on the mega grid, breaking bitwise grid-independence
            raise ValueError(
                f"megachunk={self.megachunk} x chunk_size="
                f"{self.chunk_size} outruns the DE history delay margin: "
                f"(2*megachunk - 1)*chunk_size must be <= "
                f"{DE_DELAY - DE_Q} for models with a red hyper MH block")
        # sampled ORF weights (bin_orf / legendre_orf): MH block on the
        # coefficient-conditional correlated likelihood
        self.do_orf_mh = cm.orf_B is not None and len(cm.idx.orf) > 0

        # flat (pulsar, col) gather that turns padded (P, Bmax) b arrays
        # into the reference's concatenated per-pulsar layout
        pi, ci = [], []
        for ii, w in enumerate(cm.widths):
            pi += [ii] * w
            ci += list(range(w))
        self._b_pi, self._b_ci = np.asarray(pi), np.asarray(ci)

        # adaptation state (every array carries a leading chains axis)
        self.aclength_white = None
        self.chol_white = None
        self.mode_white = None
        self.asqrt_white = None
        self.chol_ecorr = None
        self.mode_ecorr = None
        self.asqrt_ecorr = None
        self.cov_red = None
        self.red_U = None
        self.red_S = None
        #: (C, H, d) frozen DE history (ter Braak-style sampling from the
        #: past), seeded from the adaptation record and refreshed from
        #: already-written chain rows at chunk dispatch (always a full
        #: chunk behind, so the refresh is a pure function of the row
        #: index and resume stays bitwise)
        self.red_hist = None
        self._de_dev_cache = {}
        self.aclength_ecorr = None
        self.b = np.zeros((self.C, cm.P, cm.Bmax), dtype=cm.cdtype)
        self._sweep_fns = {}

        #: on-device streaming diagnostics (obs/sketch.py): ``True``
        #: enables the default sketch, a dict passes SketchSpec options
        #: (channels/cross/lags), None/False runs uninstrumented —
        #: OPT-IN so the default chunk keeps the dtype/donation census
        #: pinned by contracts/crn_quick.json byte-identical.  The
        #: sketch reads only the chunk's state stack (no keys, no carry
        #: writes), so sampling outputs are bitwise-unchanged either
        #: way; the instrumented program has its own static contract
        #: (contracts/obs_quick.json: zero new collectives, donation
        #: intact, summary-slab output bytes bounded).
        self.obs = None
        self._obs_state = None
        #: per-writeback cumulative (n, mean, m2) host snapshots — the
        #: ~kB trail moment_split_rhat() reconstructs half-stream
        #: moments from by Chan subtraction (obs/summary.py)
        self._obs_snaps = []
        if obs:
            from ..obs.sketch import init_state, make_sketch_spec

            self.obs = make_sketch_spec(
                cm, **(obs if isinstance(obs, dict) else {}))
            self._obs_state = init_state(self.obs, self.C)

        #: ensemble mixing stage (sampler/ensemble.py): interchain
        #: stretch moves on the common rho block, an ASIS ancillary grid
        #: redraw, and (pt_ladder > 1) likelihood-tempered chains with
        #: even/odd deck swaps.  None defers to settings.ensemble /
        #: settings.pt_ladder (PTGIBBS_ENSEMBLE / PTGIBBS_PT_LADDER).
        #: Off is Python-gated: the steady chunk traces exactly the
        #: pre-ensemble program, so default behavior is bitwise HEAD
        #: (tests/test_ensemble.py::test_ensemble_off_bitwise_identical).
        from . import ensemble as _ens_mod

        ens_on = settings.ensemble if ensemble is None else bool(ensemble)
        n_temps = int(settings.pt_ladder if pt_ladder is None
                      else pt_ladder)
        self._ens = None
        self._ens_state = None
        if ens_on:
            if not _ens_mod.ensemble_applies(cm):
                raise ValueError(
                    "ensemble=True requires a CRN free-spectrum model "
                    "with a shared rho block and diagonal N (no kernel "
                    "ECORR); build with common_psd='spectrum'")
            spec = _ens_mod.EnsembleSpec(n_temps=n_temps)
            _ens_mod.validate_ensemble(spec, self.C, mesh)
            self._ens = spec
            self._ens_state = _ens_mod.init_ens_state(spec, cm.cdtype)
        elif n_temps > 1:
            raise ValueError(
                "pt_ladder > 1 requires ensemble=True (tempered chains "
                "only exist inside the ensemble stage)")

        # b passed through so large correlated-ORF models can take the
        # sequential conditional path (a no-op for the others)
        self._jit_draw_b_b = jax.jit(
            jax.vmap(lambda x, k, b: draw_b_fn(cm, x, k, b, exact=True)))
        self._jit_draw_b = lambda x, keys: self._jit_draw_b_b(
            x, keys, jax.numpy.asarray(self.b))

    # ---- adaptation (first sweep) ------------------------------------------

    def _chain_keys(self, k):
        """(C,) independent keys, one per chain."""
        return self._jr.split(k, self.C)

    def _moment_proposal(self, rec, nper):
        """Moment-matched independence proposal from an adaptation record.

        The Laplace factors seed the record scan, but a curvature Gaussian
        is a poor independence proposal for soft-edged conditionals (a
        below-threshold-flat log10-equad yields ~35% acceptance); the
        Gaussian matched to the *empirical* mean/covariance of the
        recorded sub-chain accepts far more.  Returns per-chain
        ``(mode (C,P,W), chol, asqrt)`` as float64 host arrays; frozen or
        pad rows fall back to unit factors (their live mask keeps them
        out of every proposal anyway).
        """
        rec = np.asarray(rec, np.float64)            # (C, steps, P, W)
        C, S, P, W = rec.shape
        burn = rec[:, min(100, S // 2):]
        mode = burn.mean(axis=1)                     # (C, P, W)
        dev = burn - mode[:, None]
        cov = np.einsum("cspw,cspv->cpwv", dev, dev) / max(
            burn.shape[1] - 1, 1)
        nper = np.asarray(nper)
        wmask = (np.arange(W)[None] < nper[:, None])  # (P, W)
        mo = wmask[:, :, None] & wmask[:, None, :]
        cov = np.where(mo[None], cov, 0.0) + np.where(
            wmask, 0.0, 1.0)[None, :, :, None] * np.eye(W)
        e, V = np.linalg.eigh(cov)
        e = np.maximum(e, 1e-12)
        chol = (V * np.sqrt(e)[..., None, :]) * mo[None]
        asqrt = (V / np.sqrt(e)[..., None, :]) * mo[None]
        return mode, chol, asqrt

    def _first_sweep(self, x):
        """Mirror of the oracle's ``sweep(first=True)``: adaptation runs for
        each MH block (vmapped over the chains axis — each chain adapts its
        own proposal state), measured ACT/covariances become the static
        shape of every later sweep."""
        import jax

        cm = self.cm
        jr = self._jr
        x = jax.numpy.asarray(x, dtype=cm.cdtype)   # (C, nx)

        self.key, k = jr.split(self.key)
        b = self._jit_draw_b(x, self._chain_keys(k))
        # keep self.b current: the sequential HD path conditions each
        # pulsar on the others' coefficients via self.b, and the final
        # draw below must not see the stale warmup-end state
        self.b = b

        if len(cm.idx.white):
            # Laplace proposals at the conditional mode (replaces the
            # collapse-prone empirical two-phase adaptation), then one
            # record scan with the production mixed independence/RW kernel
            # to measure the ACT that sizes later sub-chains
            def lap_white(x, b):
                r2 = residual_sq(cm, b)
                xm, L, asq = laplace_newton_chol(
                    cm, x, lambda q: lnlike_white_per(cm, q, r2),
                    cm.white_par_ix, cm.white_nper)
                safe = np.minimum(np.asarray(cm.white_par_ix), cm.nx - 1)
                return xm, L, asq, xm[safe]

            x, chol, asq, mode = jax.jit(jax.vmap(lap_white))(x, b)
            self.chol_white = np.asarray(chol, np.float64)
            self.asqrt_white = np.asarray(asq, np.float64)
            self.mode_white = np.asarray(mode, np.float64)
            self.key, k = jr.split(self.key)

            def rec_white(x, b, k, chol, mode, asq):
                r = jax.numpy.asarray(cm.y, cm.dtype) - b_matvec(cm, b)
                return parallel_cov_mh_scan(
                    cm, x, k, white_block_ll(cm, x, r, r * r),
                    cm.white_par_ix,
                    cm.white_nper, chol, self.white_adapt_iters,
                    mode=mode, asqrt=asq)

            rw_jit = jax.jit(jax.vmap(rec_white))
            x, rec2 = rw_jit(
                x, b, self._chain_keys(k),
                jax.numpy.asarray(self.chol_white, cm.dtype),
                jax.numpy.asarray(self.mode_white, cm.dtype),
                jax.numpy.asarray(self.asqrt_white, cm.dtype))
            # refine: moment-matched proposal from the record, then
            # re-record with the production kernel so the measured ACT
            # (the static per-sweep scan length) describes what runs
            (self.mode_white, self.chol_white,
             self.asqrt_white) = self._moment_proposal(rec2, cm.white_nper)
            self.key, k = jr.split(self.key)
            x, rec3 = rw_jit(
                x, b, self._chain_keys(k),
                jax.numpy.asarray(self.chol_white, cm.dtype),
                jax.numpy.asarray(self.mode_white, cm.dtype),
                jax.numpy.asarray(self.asqrt_white, cm.dtype))
            self.aclength_white = min(self._act_from_rec(rec3, cm.white_nper),
                                      self.white_steps_max)

        if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
            def lap_ec(x, b):
                if cm.has_ke:
                    r = jax.numpy.asarray(cm.y, cm.dtype) - b_matvec(cm, b)
                    curv = ecorr_ll_ke(cm, x, r)
                else:
                    curv = lambda q: lnlike_ecorr_per(cm, q, b)
                xm, L, asq = laplace_newton_chol(
                    cm, x, curv, cm.ecorr_par_ix, cm.ecorr_nper)
                safe = np.minimum(np.asarray(cm.ecorr_par_ix), cm.nx - 1)
                return xm, L, asq, xm[safe]

            x, chol, asq, mode = jax.jit(jax.vmap(lap_ec))(x, b)
            self.chol_ecorr = np.asarray(chol, np.float64)
            self.asqrt_ecorr = np.asarray(asq, np.float64)
            self.mode_ecorr = np.asarray(mode, np.float64)
            self.key, k = jr.split(self.key)

            def rec_ec(x, b, k, chol, mode, asq):
                r = jax.numpy.asarray(cm.y, cm.dtype) - b_matvec(cm, b)
                return parallel_cov_mh_scan(
                    cm, x, k, ecorr_block_ll(cm, x, b, r), cm.ecorr_par_ix,
                    cm.ecorr_nper, chol, self.white_adapt_iters,
                    mode=mode, asqrt=asq)

            re_jit = jax.jit(jax.vmap(rec_ec))
            x, rec2 = re_jit(
                x, b, self._chain_keys(k),
                jax.numpy.asarray(self.chol_ecorr, cm.dtype),
                jax.numpy.asarray(self.mode_ecorr, cm.dtype),
                jax.numpy.asarray(self.asqrt_ecorr, cm.dtype))
            (self.mode_ecorr, self.chol_ecorr,
             self.asqrt_ecorr) = self._moment_proposal(rec2, cm.ecorr_nper)
            self.key, k = jr.split(self.key)
            x, rec3 = re_jit(
                x, b, self._chain_keys(k),
                jax.numpy.asarray(self.chol_ecorr, cm.dtype),
                jax.numpy.asarray(self.mode_ecorr, cm.dtype),
                jax.numpy.asarray(self.asqrt_ecorr, cm.dtype))
            self.aclength_ecorr = min(self._act_from_rec(rec3, cm.ecorr_nper),
                                      self.white_steps_max)

        if self.do_red_conditional:
            self.key, k = jr.split(self.key)
            x = jax.jit(jax.vmap(
                lambda x, b, k: red_conditional_update(cm, x, b, k)))(
                    x, b, self._chain_keys(k))
        if self.do_tprocess:
            self.key, k = jr.split(self.key)
            x = jax.jit(jax.vmap(
                lambda x, b, k: tprocess_alpha_update(cm, x, b, k)))(
                    x, b, self._chain_keys(k))
        if self.do_red_mh:
            # covariance adaptation on the marginalized likelihood
            # (replaces the reference's scratch PTMCMCSampler,
            # pulsar_gibbs.py:288-315)
            self.key, k = jr.split(self.key)

            def adapt(x, k):
                N = cm.ndiag(x)
                TNT, d = tnt_d_x(cm, x, N)
                return mh_scan(cm, x, k,
                               lambda q: lnlike_fullmarg_fn(cm, q, TNT, d),
                               cm.idx.red, self.red_adapt_iters)

            x, rec = jax.jit(jax.vmap(adapt))(x, self._chain_keys(k))
            rec = np.asarray(rec, dtype=np.float64)   # (C, steps, d)
            d = len(cm.idx.red)
            covs = []
            for c in range(self.C):
                burn = rec[c, min(100, rec.shape[1] // 2):]
                covs.append(np.atleast_2d(np.cov(burn, rowvar=False))
                            + 1e-12 * np.eye(d))
            self.cov_red = np.stack(covs)             # (C, d, d)
            self._set_red_eigs()
            # seed the DE history from the post-burn adaptation record
            # (thinned to H rows); chunk dispatches refresh it from chain
            # rows once enough are written
            burn0 = min(100, rec.shape[1] // 2)
            take = np.linspace(burn0, rec.shape[1] - 1,
                               DE_HIST_LEN).astype(int)
            self.red_hist = rec[:, take, :]           # (C, H, d)

        if cm.K and len(cm.rho_ix_x):
            self.key, k = jr.split(self.key)
            x = jax.jit(jax.vmap(
                lambda x, b, k: rho_update(cm, x, b, k)))(
                    x, b, self._chain_keys(k))

        self.key, k = jr.split(self.key)
        self.b = self._jit_draw_b(x, self._chain_keys(k))
        return x

    def _act_from_rec(self, rec, nper, pct=95.0):
        """Static per-sweep scan length from an adaptation record
        (C, steps, P, W): the ``pct``-th percentile (ceil) of the
        per-(chain, pulsar, parameter) integrated ACTs.

        The reference sizes its sub-chain by the max over ONE pulsar's
        parameters (``aclength_white``, ``pulsar_gibbs.py:367-371``).
        Here the record spans C chains x P pulsars, and the max becomes
        an extreme order statistic over hundreds of sub-chains, dominated
        by likelihood-unconstrained coordinates (posterior ~ prior, e.g.
        an EQUAD far below the measurement noise) whose mixing is
        posterior-irrelevant — measured on the 45-pulsar bench model:
        median ACT 4.9, 90th pct 12.9, max ~69, pinning every pulsar at
        the 64-step cap.  Any fixed length is a valid MH kernel; the
        percentile sizes it for the identified bulk.

        Measured tradeoff (docs/ACT_TAIL.md, 4000-sweep run): pct=95
        chooses a 10-step sub-chain vs 71 for the max rule; the slow-tail
        coordinates' worst chain-level ACT is 29 sweeps (>= 345 effective
        samples per 10k sweeps), statistically indistinguishable from the
        bulk's worst (23.7) — the tail is prior-dominated, not
        under-served."""
        rec = np.asarray(rec, dtype=np.float64)
        nper = np.asarray(nper)
        cols = []
        for c in range(rec.shape[0]):
            burn = rec[c, min(100, rec.shape[1] // 2):]
            cols += [burn[:, p, w] for p in range(self.cm.P_real)
                     for w in range(int(nper[p]))]
        if not cols:
            return 1
        # integrated_act dispatches to the native C estimator when built
        acts = [integrated_act(col) for col in cols]
        return max(1, int(np.ceil(np.percentile(acts, pct))))

    def _set_red_eigs(self):
        import jax.numpy as jnp

        U, S, _ = np.linalg.svd(self.cov_red)         # batched over chains
        self.red_U = jnp.asarray(U, dtype=self.cm.cdtype)
        self.red_S = jnp.asarray(S, dtype=self.cm.cdtype)

    # ---- per-sweep kernel ---------------------------------------------------

    def _dispatch_guard(self):
        """Transfer guard for a compiled-chunk dispatch: active only when
        the driver was built with ``transfer_guard=True``.  Arguments are
        staged with explicit ``jnp.asarray`` (allowed under "disallow"),
        so anything the guard trips on is a genuine implicit transfer."""
        import contextlib

        from ..analysis.guards import no_transfers

        return no_transfers() if self.transfer_guard \
            else contextlib.nullcontext()

    def _aux(self, chain=None, ii=None):
        """Per-chain adaptation state passed to the sweep body as explicit
        jit arguments (never closure constants: a cached chunk function
        must not bake in stale proposal state).  Entries for inactive
        blocks are None, which vanishes from the pytree so vmap/jit only
        see the live arrays.

        When ``(chain, ii)`` is given (steady-chunk dispatch), the DE
        history entries are the buffers for the DE periods the chunk can
        touch, plus the per-iteration switch index — the compiled body
        selects between them by the absolute iteration, so the history a
        sweep sees is a pure function of the iteration index and resume
        stays bitwise no matter where checkpoints land."""
        import jax.numpy as jnp

        dt = self.cm.dtype

        def cast(a):
            return None if a is None else jnp.asarray(a, dt)

        if self.red_hist is None:
            de = (None, None, None)
        else:
            if chain is None or ii is None:
                hp = hn = jnp.asarray(self.red_hist, self.cm.cdtype)
                sw = np.iinfo(np.int32).max
            else:
                m0 = ii // DE_Q
                hp, hn = self._de_bufs(chain, m0)
                sw = (m0 + 1) * DE_Q
            de = (hp, hn, jnp.full((self.C,), sw, jnp.int32))
        return (
            cast(self.chol_white), cast(self.mode_white),
            cast(self.asqrt_white),
            cast(self.chol_ecorr), cast(self.mode_ecorr),
            cast(self.asqrt_ecorr),
            None if self.red_U is None else jnp.asarray(self.red_U),
            None if self.red_S is None else jnp.asarray(self.red_S),
            *de,
        )

    def _aux_mega(self, chain, ii, n_sub):
        """:meth:`_aux` for a mega dispatch: the shared adaptation
        entries plus the DE history triples of EVERY sub-chunk stacked
        on a leading ``n_sub`` axis — the outer scan selects sub ``j``'s
        triple by index, so each sub sees exactly the buffers its legacy
        dispatch would have staged.  The stacked buffers reuse the
        memoized per-period device arrays (:meth:`_de_bufs`); the ctor's
        mega DE guard guarantees every period's chain rows are already
        written when this stages."""
        import jax.numpy as jnp

        base = self._aux()[:8] if self.red_hist is None \
            else self._aux(chain, ii)[:8]
        if self.red_hist is None:
            return base + (None, None, None)
        has, hbs, sws = [], [], []
        for j in range(n_sub):
            m0 = (ii + j * self.chunk_size) // DE_Q
            hp, hn = self._de_bufs(chain, m0)
            has.append(hp)
            hbs.append(hn)
            sws.append(jnp.full((self.C,), (m0 + 1) * DE_Q, jnp.int32))
        return base + (jnp.stack(has), jnp.stack(hbs), jnp.stack(sws))

    def _sweep_body(self, bdraw="mh"):
        """One post-adaptation Gibbs sweep (reference order,
        ``pulsar_gibbs.py:656-698``) as a single-chain body
        ``body(carry, key, aux, t)`` over carry ``(x, b, u)`` with
        ``u = T b`` cached; the chunk functions vmap it over the chains
        axis.

        ``bdraw`` selects the b-draw kernel: "mh" (f32 proposal + exact
        Hastings accept) or "exact" (f64).  The periodic exact refresh is
        selected per *iteration* by the chunk step's ``lax.cond`` between
        the two compiled bodies — the predicate is chain-independent, and
        a cond inside the vmapped body would lower to ``select`` and
        execute both draws every sweep."""
        import jax.numpy as jnp
        import jax.random as jr

        cm = self.cm
        nw = self.aclength_white or 0
        ne = self.aclength_ecorr or 0

        def body(carry, key, aux, t, beta=None):
            x, b, u = carry
            (chol_w, mode_w, asq_w, chol_e, mode_e, asq_e,
             red_U, red_S, hist_a, hist_b, de_sw) = aux
            # per-iteration DE-period select: pure in the absolute
            # iteration index, so chunk/checkpoint grids cannot shift it
            red_hist = (None if hist_a is None
                        else jnp.where(t < de_sw, hist_a, hist_b))
            out = (x, b)
            k = jr.split(key, 9)

            # per-chain inverse temperature (parallel tempering,
            # sampler/ensemble.py): ONLY likelihood-touching blocks see
            # beta — the rho/red/tprocess grid conditionals depend on b
            # solely through the untempered prior and stay exact at
            # every rung.  beta=None (the default) leaves every call
            # identical to the pre-ensemble program.
            def _tll(ll):
                if beta is None:
                    return ll
                return lambda q: ll(q) * beta

            # the cached u = T b makes the white residual free
            r = jnp.asarray(cm.y, cm.dtype) - u
            if len(cm.idx.white) and nw:
                x, _ = parallel_cov_mh_scan(
                    cm, x, k[0], _tll(white_block_ll(cm, x, r, r * r)),
                    cm.white_par_ix,
                    cm.white_nper, chol_w, nw, record=False,
                    mode=mode_w, asqrt=asq_w)
            if len(cm.idx.ecorr) and ne and (cm.ec_cols.shape[1]
                                             or cm.has_ke):
                x, _ = parallel_cov_mh_scan(
                    cm, x, k[1], _tll(ecorr_block_ll(cm, x, b, r)),
                    cm.ecorr_par_ix,
                    cm.ecorr_nper, chol_e, ne, record=False,
                    mode=mode_e, asqrt=asq_e)
            # partially-collapsed rho (shared-column free-spectrum red):
            # rho is drawn with red marginalized, so the red conditional
            # must follow IMMEDIATELY — together they form one exact
            # blocked draw of (rho, red) | b (see rho_update).  All other
            # models keep the reference's red-then-rho scan order.
            collapsed = _rho_collapsed_applies(cm)
            if collapsed and cm.K and len(cm.rho_ix_x):
                x = rho_update(cm, x, b, k[3])
            if self.do_red_conditional:
                x = red_conditional_update(cm, x, b, k[2])
            if self.do_tprocess:
                x = tprocess_alpha_update(cm, x, b, k[6])
            if self.do_red_mh:
                x = red_mh_block(cm, x, b, k[5], red_U, red_S,
                                 self.red_steps, hist=red_hist)
            # stage-1 factor cache of the structured joint draw, hoisted
            # here because its inputs (Nvec, non-GW phi) are final once
            # the white/ECORR/red blocks above have run: every remaining
            # block (rho, the rho <-> b scale interweaving, ORF MH) only
            # moves the GW prior, which lives entirely in the Schur
            # stage — so the batched per-pulsar factorization is shared
            # across the sweep's joint-draw sub-steps instead of being
            # recomputed inside each one
            factors = None
            if cm.orf_name != "crn" and _joint_kernel_active(cm):
                factors = joint_factor_cache(
                    cm, x, exact=(bdraw == "exact"),
                    mixed=self.joint_mixed)
            if not collapsed and cm.K and len(cm.rho_ix_x):
                x = rho_update(cm, x, b, k[3])
            if _rho_scale_applies(cm):
                # interweaving scale moves along the rho <-> b funnel
                x, b, u = rho_scale_moves(cm, x, b, u, k[8], beta=beta)
            if self.do_orf_mh:
                x, _ = mh_scan(cm, x, k[7], lnlike_orf_fn(cm, b),
                               cm.idx.orf, self.red_steps)
            if cm.orf_name != "crn":
                # joint (structured two-stage) or sequential HD draw;
                # steady sweeps take the mixed two-float kernels and the
                # chunk's periodic exact body refreshes in f64
                b = draw_b_fn(cm, x, k[4], b, exact=(bdraw == "exact"),
                              factors=factors)
                u = b_matvec(cm, b)
            elif bdraw == "mh":
                b, u, _ = draw_b_mh(cm, x, b, u, k[4], beta=beta)
            elif cm.has_ke:
                # kernel ECORR: the Metropolised refresh's accept density
                # assumes diagonal N; only the f64 exact draw runs
                b = draw_b_fn(cm, x, k[4])
                u = b_matvec(cm, b)
            else:
                b, u, _ = draw_b_refresh(cm, x, b, u, k[4], beta=beta)
            return (x, b, u), out

        return body

    def _warmup_body(self):
        """Pre-adaptation sweep: fixed-length single-site white/ECORR
        sub-chains and prior-scaled joint red MH.  The reference adapts at
        the initial state (``pulsar_gibbs.py:332-406`` runs its 1000-step
        adaptation on sweep 0), where the conditional posterior can sit in
        a transient corner (huge prior-drawn rho -> b interpolates the data
        -> white noise pinned at the prior floor); warming up first makes
        the measured covariances and ACT describe the stationary region."""
        import jax
        import jax.random as jr

        cm = self.cm
        nw = self.warmup_white_steps

        def body(carry, key, aux, t):
            x, b, u = carry
            out = (x, b)
            k = jr.split(key, 9)
            r = jax.numpy.asarray(cm.y, cm.dtype) - u
            if len(cm.idx.white):
                # Laplace proposal square roots recomputed at the current
                # state each warmup sweep (W HVPs + a batched WxW eigh,
                # small next to the b-draw for the W<=2 blocks) so the white
                # block actually travels toward the typical set instead of
                # freezing under prior-width single-site jumps
                r2 = r * r
                _, chol, _ = laplace_newton_chol(
                    cm, x, lambda q: lnlike_white_per(cm, q, r2),
                    cm.white_par_ix, cm.white_nper, newton_iters=0)
                x, _ = parallel_cov_mh_scan(
                    cm, x, k[0], white_block_ll(cm, x, r, r * r),
                    cm.white_par_ix,
                    cm.white_nper, chol, nw, record=False)
            if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
                if cm.has_ke:
                    curv = ecorr_ll_ke(cm, x, r)
                else:
                    curv = lambda q: lnlike_ecorr_per(cm, q, b)
                _, chol, _ = laplace_newton_chol(
                    cm, x, curv,
                    cm.ecorr_par_ix, cm.ecorr_nper, newton_iters=0)
                x, _ = parallel_cov_mh_scan(
                    cm, x, k[1], ecorr_block_ll(cm, x, b, r),
                    cm.ecorr_par_ix,
                    cm.ecorr_nper, chol, nw, record=False)
            # rho-first under the partially-collapsed draw (see the main
            # sweep body): the red conditional must follow it immediately
            collapsed = _rho_collapsed_applies(cm)
            if collapsed and cm.K and len(cm.rho_ix_x):
                x = rho_update(cm, x, b, k[3])
            if self.do_red_conditional:
                x = red_conditional_update(cm, x, b, k[2])
            if self.do_tprocess:
                x = tprocess_alpha_update(cm, x, b, k[6])
            if self.do_red_mh:
                _, phi_dyn = cm.phi_hyper_split(x)
                x, _ = mh_scan(cm, x, k[5],
                               lambda q: lnlike_hyper_fn(cm, q, b,
                                                         phi_fn=phi_dyn),
                               cm.idx.red, self.red_steps)
            if not collapsed and cm.K and len(cm.rho_ix_x):
                x = rho_update(cm, x, b, k[3])
            if _rho_scale_applies(cm):
                x, b, u = rho_scale_moves(cm, x, b, u, k[8])
            if self.do_orf_mh:
                x, _ = mh_scan(cm, x, k[7], lnlike_orf_fn(cm, b),
                               cm.idx.orf, self.red_steps)
            # pass the carried b: the sequential HD path conditions each
            # pulsar on the others' CURRENT coefficients (restarting from
            # zeros would sample a shrunken, decorrelated conditional).
            # CRN diagonal-N models warm up on the Metropolised refresh —
            # its proposal tracks the conditional independently of the
            # current state, so acceptance stays ~1 even far from
            # stationarity, at a fraction of the f64 draw's cost
            if cm.orf_name != "crn" or cm.has_ke:
                # exact=True: warmup states sit past the two-float
                # factor's breakdown margins (see draw_b_fn)
                b = draw_b_fn(cm, x, k[4], b, exact=True)
                u = b_matvec(cm, b)
            else:
                b, u, _ = draw_b_refresh(cm, x, b, u, k[4])
            return (x, b, u), out

        return body

    def _rho_health_args(self):
        """``(rho_ix, lo, hi)`` for the chunk-health rho-bound flag: the
        sampled common-rho coordinates and the prior bounds in x units
        (``rho = 10**(2x)`` ⇒ ``x = 0.5·log10(rho)``), or all-None when
        the model samples no common rho."""
        cm = self.cm
        ix = np.asarray(cm.rho_ix_x)
        if ix.size == 0:
            return None, None, None
        return (ix, 0.5 * float(np.log10(cm.rhomin)),
                0.5 * float(np.log10(cm.rhomax)))

    def _sub_core(self, body, n, rec_off=0, ensemble=False):
        """Un-jitted core of one ``n``-sweep scan, shared by the legacy
        chunk program (:meth:`_make_chunk`) and the mega-chunk outer scan
        (:meth:`_make_megachunk`).

        Per-sweep, per-chain keys are
        ``fold_in(fold_in(base_key, iteration), chain)`` so the random
        stream is a pure function of the (iteration, chain) index — chunk
        boundaries and checkpoint cadence cannot change the sampled
        process, which makes resume bitwise-exact (fixing the reference's
        lost-adaptation resume bug class, SURVEY §5).  ``aux`` (per-chain
        proposal state) is an explicit argument so cached chunk functions
        never bake in stale adaptation.  The cached matvec ``u = T b`` is
        a pure function of ``b``, recomputed at chunk entry and carried
        within the scan — chunk boundaries cannot change it either.

        The recorded per-sweep b states are cast to the f32 storage dtype
        ON DEVICE before the host transfer: the (chunk, C, P, Bmax)
        b-record is the dominant device-to-host payload (42.6 MB/chunk in
        f64 at C=32 on the bench model, ~2.4 s over the ~18 MB/s tunnel
        ≈ half the steady wall time, tools/chunk_probe.py), and the
        recorded samples carry f32-storage statistical content anyway.
        The sweep *carry* stays full precision: ``n_keep`` dynamically
        indexes the f64 pre-cast stack so resume/tail states never see
        the rounding.

        Returns ``_core(x, b, base_key, it0, aux, n_keep[, ens_state])``
        whose trailing outputs — the full pre-thinning f64 stack and the
        FINAL scan carry — exist only for the obs/mega wrappers; the
        plain chunk drops them and jit DCE restores the exact legacy
        program (contracts/crn_quick.json stays byte-identical)."""
        import jax
        import jax.numpy as jnp
        import jax.random as jr

        cm = self.cm
        chains = jnp.arange(self.C)
        if isinstance(body, tuple):
            body_main, body_exact = body
        else:
            body_main, body_exact = body, None
        vbody = jax.vmap(body_main, in_axes=(0, 0, 0, None))
        vexact = (None if body_exact is None
                  else jax.vmap(body_exact, in_axes=(0, 0, 0, None)))
        # ensemble stage (Python-gated: off means these ops never enter
        # the jaxpr, and the plain chunk program is byte-identical to
        # the pre-ensemble one — contracts/crn_quick.json pins it)
        ens = self._ens if ensemble else None
        temper = ens is not None and ens.n_temps > 1
        if temper:
            # tempered bodies take a per-chain beta as a 5th argument;
            # beta is derived each sweep from the CARRIED ladder state,
            # so resume from any chunk grid replays identical sweeps
            vbody_t = jax.vmap(body_main, in_axes=(0, 0, 0, None, 0))
            vexact_t = (None if body_exact is None
                        else jax.vmap(body_exact,
                                      in_axes=(0, 0, 0, None, 0)))

        def _core(x, b, base_key, it0, aux, n_keep, ens_state=None):
            u = jax.vmap(lambda b1: b_matvec(cm, b1))(b)

            def step(carry, t):
                kt = jr.fold_in(base_key, t)
                keys = jax.vmap(lambda c: jr.fold_in(kt, c))(chains)
                if vexact is None:
                    return vbody(carry, keys, aux, t)
                # iteration-level branch: the predicate is uniform across
                # chains, so cond picks ONE compiled body per sweep (a
                # cond inside the vmapped body would become select and
                # run both b-draws every sweep)
                return jax.lax.cond(
                    t % self.exact_every == 0,
                    lambda c: vexact(c, keys, aux, t),
                    lambda c: vbody(c, keys, aux, t),
                    carry)

            def ens_step(carry, t):
                from . import ensemble as ens_mod

                xbu, es = carry
                kt = jr.fold_in(base_key, t)
                keys = jax.vmap(lambda c: jr.fold_in(kt, c))(chains)
                if temper:
                    bchain = ens_mod.chain_betas(ens, es, self.C).astype(
                        cm.cdtype)
                    run_m = lambda c: vbody_t(c, keys, aux, t, bchain)
                    run_e = (None if vexact_t is None else
                             (lambda c: vexact_t(c, keys, aux, t, bchain)))
                else:
                    run_m = lambda c: vbody(c, keys, aux, t)
                    run_e = (None if vexact is None else
                             (lambda c: vexact(c, keys, aux, t)))
                if run_e is None:
                    xbu, out = run_m(xbu)
                else:
                    xbu, out = jax.lax.cond(
                        t % self.exact_every == 0, run_e, run_m, xbu)
                xbu, es_new = ens_mod.ensemble_stage(cm, ens, xbu, es,
                                                     kt, t)
                # ys carry the PRE-sweep ensemble state next to the
                # pre-sweep (x, b) rows, so the n_keep carry selection
                # below restores the exact mid-chunk resume point
                return (xbu, es_new), out + (es,)

            if ens is not None:
                (((x, b, u), es_end),
                 (xs, bs, ess)) = jax.lax.scan(
                    ens_step, ((x, b, u), ens_state),
                    it0 + jnp.arange(n, dtype=jnp.int32))
            else:
                (x, b, u), (xs, bs) = jax.lax.scan(
                    step, (x, b, u), it0 + jnp.arange(n, dtype=jnp.int32))
            # full-precision carry at row n_keep (rows record PRE-sweep
            # states; n_keep == n means the final carry).  Branch instead
            # of concatenating a carry row onto the stacks: the b record
            # is ~170 MB f64 at C=64 and a concat would clone it on
            # device every chunk just to select one row
            def row(stack):
                return jax.lax.dynamic_index_in_dim(
                    stack, jnp.minimum(n_keep, n - 1), keepdims=False)

            x_end, b_end = jax.lax.cond(
                n_keep >= n,
                lambda: (x, b),
                lambda: (row(xs), row(bs)))
            if ens is not None:
                # ladder/counter state at the SAME resume point: the
                # pre-sweep snapshot of sweep n_keep (== the final carry
                # when the whole chunk is kept)
                es_sel = jax.lax.cond(
                    n_keep >= n,
                    lambda: es_end,
                    lambda: jax.tree_util.tree_map(row, ess))
            # on-device record thinning: the transfer ships rows for
            # iterations it0 + rec_off + j*record_every only.  run() picks
            # rec_off so the recorded iterations satisfy it ≡ it_base
            # (mod record_every) in ABSOLUTE iteration index — the set is
            # then independent of the chunk grid, so checkpoints, resumes
            # and chain extensions record the same iterations a single
            # uninterrupted run would.  The full per-sweep stack still
            # exists on device for the n_keep carry selection above, so
            # thinning cannot touch the resumed process.
            xs_rec = xs[rec_off::self.record_every]
            bs_rec = bs[rec_off::self.record_every]
            # the recorded b goes to host already in the reference's flat
            # (nb_total) layout: the pad-column drop happens on device, so
            # the dominant transfer ships only real columns, and the host
            # writeback is a dtype cast instead of a 40 MB fancy gather
            bs_flat = bs_rec.astype(self.rdtype)[
                :, :, jnp.asarray(self._b_pi, jnp.int32),
                jnp.asarray(self._b_ci, jnp.int32)]
            # the x record ships in the record dtype too: at C=64 the f64
            # (chunk, C, nx) stack is 28.2 MB/chunk — 43% of the b payload
            # — over the ~18 MB/s tunnel (tools/chunk_probe.py), and the
            # recorded hyperparameters carry f32 statistical content for
            # the same reason the b record does.  The carry/resume path
            # reads x_end (selected from the pre-cast stack above), so
            # checkpoints and trailing chunks never see the rounding.
            # Health reductions ride the same dispatch: a handful of
            # per-chain scalars (all-finite, moved fraction, rho-bound
            # breach) computed on device, so divergence/stuck-chain
            # detection costs no extra transfer (runtime.sentinels,
            # docs/RESILIENCE.md)
            health = chunk_health(xs_rec, bs_rec,
                                  *self._rho_health_args())
            if ens is not None:
                return (x_end, b_end, xs_rec.astype(self.rdtype), bs_flat,
                        health, es_sel, xs, x, b, es_end)
            return (x_end, b_end, xs_rec.astype(self.rdtype), bs_flat,
                    health, xs, x, b)

        return _core

    def _make_chunk(self, body, n, rec_off=0, obs=False, ensemble=False):
        """Jitted scan of ``n`` sweeps, the single-chain ``body`` vmapped
        over the chains axis (:meth:`_sub_core` holds the core program
        and the PRNG/record/thinning contracts)."""
        import jax

        _core = self._sub_core(body, n, rec_off, ensemble=ensemble)
        ens = self._ens if ensemble else None
        # the full f64 stack ``xs`` is an extra _core output only so the
        # instrumented variant can fold it into the sketch; the plain
        # variant drops it, and jit DCE restores the exact pre-obs
        # program (contracts/crn_quick.json stays byte-identical)
        if ens is not None:
            def run_chunk(x, b, base_key, it0, aux, n_keep, ens_state):
                return _core(x, b, base_key, it0, aux, n_keep, ens_state)[:6]
        else:
            def run_chunk(x, b, base_key, it0, aux, n_keep):
                return _core(x, b, base_key, it0, aux, n_keep)[:5]

        if not obs:
            return jax.jit(run_chunk)

        from ..obs import sketch as obs_sketch
        spec = self.obs

        if ens is not None:
            def run_chunk_obs(x, b, base_key, it0, aux, n_keep, ens_state,
                              sk):
                out = _core(x, b, base_key, it0, aux, n_keep, ens_state)
                sk = obs_sketch.update(spec, sk, x, out[6])
                return out[:6] + (sk,)
        else:
            def run_chunk_obs(x, b, base_key, it0, aux, n_keep, sk):
                out = _core(x, b, base_key, it0, aux, n_keep)
                # sketch the FULL pre-thinning stack: diagnostics see
                # every sweep in f64 (ACT in sweep units) no matter how
                # hard the record transfer is thinned — the point of the
                # device half.  No keys consumed, no carry touched:
                # sampling outputs are bitwise those of run_chunk.
                sk = obs_sketch.update(spec, sk, x, out[5])
                return out[:5] + (sk,)

        return jax.jit(run_chunk_obs)

    def _make_megachunk(self, body, n, n_sub, rec_off=0, obs=False,
                        ensemble=False):
        """The device-resident steady loop: ONE jitted dispatch scanning
        ``n_sub`` sub-chunks of ``n`` sweeps back to back, with the chunk
        carry donated end-to-end.

        Equivalence to the legacy chunk grid is exact and bitwise: the
        outer scan body calls the same :meth:`_sub_core` program per
        sub-chunk (per-sweep keys are pure in the absolute iteration, the
        matvec ``u = T b`` is recomputed at each sub entry, and the obs
        sketch folds each sub's entry state + full stack exactly as a
        dispatch-per-chunk run would).  The per-sub DE history buffers
        ride the aux pytree with a leading ``n_sub`` axis and are
        re-selected inside the scan, so every sub sees the history its
        legacy twin would (the ctor bounds ``(2*n_sub - 1)*chunk_size``
        by the DE delay margin).

        The record stacks come back as the legacy concatenation —
        ``record_every | chunk_size`` makes every sub ship exactly
        ``n // record_every`` rows on the shared residue, so the
        ``(n_sub, r, ...)`` scan stack reshapes to the ``(n_sub*r, ...)``
        slab a legacy grid would emit row for row.  Health reductions
        combine across subs (finite AND, move_frac mean).

        ``n_keep`` is the mega-wide keep point: each sub selects with
        ``clip(n_keep - j*n, 0, n)`` and the kept carry is where-updated
        for subs whose start precedes the keep point — identical values
        to the legacy trailing-chunk selection.

        Donation: the carries (x, b[, ens_state][, sketch]) alias their
        outputs, so a resident steady phase holds one generation of
        carry instead of two; ``run()`` host-snapshots the pending
        writeback's carry leaves before the next dispatch
        (contracts/crn_megachunk.json pins the aliasing surface)."""
        import jax
        import jax.numpy as jnp

        core = self._sub_core(body, n, rec_off, ensemble=ensemble)
        ens = self._ens if ensemble else None
        obs_on = bool(obs)
        if obs_on:
            from ..obs import sketch as obs_sketch
            spec = self.obs

        def mega(x, b, base_key, it0, aux, n_keep, ens_state=None,
                 sk=None):
            shared, de = aux[:8], aux[8:]
            has_de = de[0] is not None

            def outer(carry, j):
                if ens is not None:
                    x, b, es, keep, sk_c = carry
                else:
                    x, b, keep, sk_c = carry
                    es = None
                if has_de:
                    aux_j = shared + tuple(
                        jax.lax.dynamic_index_in_dim(a, j, keepdims=False)
                        for a in de)
                else:
                    aux_j = shared + (None, None, None)
                sub_keep = jnp.clip(n_keep - j * n, 0, n)
                out = core(x, b, base_key, it0 + j * n, aux_j, sub_keep,
                           es)
                if ens is not None:
                    (x_sel, b_sel, xs_rec, bs_flat, health, es_sel,
                     xs_full, x_fin, b_fin, es_fin) = out
                    sel = (x_sel, b_sel, es_sel)
                else:
                    (x_sel, b_sel, xs_rec, bs_flat, health, xs_full,
                     x_fin, b_fin) = out
                    sel = (x_sel, b_sel)
                if obs_on:
                    # per-sub sketch fold off the SUB entry state — the
                    # same update stream a dispatch-per-chunk run feeds
                    sk_c = obs_sketch.update(spec, sk_c, x, xs_full)
                # keep-carry update: live for every sub whose start is at
                # or before the keep point; j=0 always overwrites the
                # placeholder init, and at an exact sub boundary both the
                # previous sub's final carry and this sub's row-0 select
                # hold the identical value
                live = j * n <= n_keep
                keep = jax.tree_util.tree_map(
                    lambda a, kb: jnp.where(live, a, kb), sel, keep)
                ys = (xs_rec, bs_flat, health)
                if ens is not None:
                    return (x_fin, b_fin, es_fin, keep, sk_c), ys
                return (x_fin, b_fin, keep, sk_c), ys

            keep0 = ((x, b, ens_state) if ens is not None else (x, b))
            carry0 = ((x, b, ens_state, keep0, sk) if ens is not None
                      else (x, b, keep0, sk))
            carry_end, (xs_s, bs_s, health_s) = jax.lax.scan(
                outer, carry0, jnp.arange(n_sub, dtype=jnp.int32))
            if ens is not None:
                _, _, _, keep, sk_end = carry_end
                x_keep, b_keep, es_keep = keep
            else:
                _, _, keep, sk_end = carry_end
                x_keep, b_keep = keep
            xs_all = xs_s.reshape((-1,) + xs_s.shape[2:])
            bs_all = bs_s.reshape((-1,) + bs_s.shape[2:])
            health = {"finite": jnp.all(health_s["finite"], axis=0),
                      "move_frac": jnp.mean(health_s["move_frac"],
                                            axis=0),
                      "rho_ok": jnp.all(health_s["rho_ok"], axis=0)}
            outs = (x_keep, b_keep, xs_all, bs_all, health)
            if ens is not None:
                outs = outs + (es_keep,)
            if obs_on:
                outs = outs + (sk_end,)
            return outs

        # positional wrappers matching the legacy chunk signatures run()
        # stages, with the carries donated (the legacy jits donate
        # nothing — their outputs stay live in the pending writeback)
        if ens is not None and obs_on:
            def run_mega(x, b, base_key, it0, aux, n_keep, ens_state, sk):
                return mega(x, b, base_key, it0, aux, n_keep, ens_state,
                            sk)
            donate = (0, 1, 6, 7)
        elif ens is not None:
            def run_mega(x, b, base_key, it0, aux, n_keep, ens_state):
                return mega(x, b, base_key, it0, aux, n_keep, ens_state)
            donate = (0, 1, 6)
        elif obs_on:
            def run_mega(x, b, base_key, it0, aux, n_keep, sk):
                return mega(x, b, base_key, it0, aux, n_keep, None, sk)
            donate = (0, 1, 6)
        else:
            def run_mega(x, b, base_key, it0, aux, n_keep):
                return mega(x, b, base_key, it0, aux, n_keep)
            donate = (0, 1)
        return jax.jit(run_mega, donate_argnums=donate)

    def _warmup_chunk_fn(self, n):
        if ("warmup", n) not in self._sweep_fns:
            self._sweep_fns[("warmup", n)] = self._make_chunk(
                self._warmup_body(), n)
        return self._sweep_fns[("warmup", n)]

    def _chunk_fn(self, n, rec_off=0):
        if (n, rec_off) not in self._sweep_fns:
            if self.cm.has_ke:
                # kernel ECORR: the Metropolised b-draw's exact accept
                # density assumes diagonal N, so only the exact draw runs
                bodies = self._sweep_body("exact")
            else:
                # both CRN and correlated-ORF models run a body pair:
                # steady sweeps take the mixed/two-float b-draw kernels
                # and every exact_every-th sweep the f64 body refreshes
                # the factorization error (the same cadence contract as
                # the CRN refresh; docs/EXACT_EVERY.md)
                bodies = (self._sweep_body("mh"), self._sweep_body("exact"))
            self._sweep_fns[(n, rec_off)] = self._make_chunk(
                bodies, n, rec_off, obs=self.obs is not None,
                ensemble=self._ens is not None)
        return self._sweep_fns[(n, rec_off)]

    def _mega_fn(self, n, n_sub, rec_off=0):
        key = ("mega", n, n_sub, rec_off)
        if key not in self._sweep_fns:
            if self.cm.has_ke:
                bodies = self._sweep_body("exact")
            else:
                bodies = (self._sweep_body("mh"),
                          self._sweep_body("exact"))
            self._sweep_fns[key] = self._make_megachunk(
                bodies, n, n_sub, rec_off, obs=self.obs is not None,
                ensemble=self._ens is not None)
        return self._sweep_fns[key]

    # ---- facade protocol ----------------------------------------------------

    def _b_flat(self, b_arr):
        """(..., P, Bmax) -> (..., nb_total) reference layout."""
        return np.asarray(b_arr, dtype=np.float64)[..., self._b_pi, self._b_ci]

    def _rows_of(self, n):
        """Recorded rows an offset-0 chunk of ``n`` sweeps ships."""
        k = self.record_every
        return (n + k - 1) // k

    def _it_base(self, niter):
        """First steady-loop iteration — the residue anchor of the thinned
        record: steady rows hold iterations ≡ it_base (mod record_every),
        independent of the chunk grid."""
        W = min(self.warmup_sweeps, max(0, niter - 1))
        if W > 0:
            return W + 1
        return 1 if niter <= 1 else 2

    def _row_layout(self, niter):
        """Total recorded rows of an ``niter``-sweep run: thinned warmup
        rows + the post-warmup carry row + one row per recorded steady
        iteration; equals ``niter`` at record_every=1."""
        W = min(self.warmup_sweeps, max(0, niter - 1))
        base = self._rows_of(W) + 1 if W > 0 else (1 if niter <= 1 else 2)
        it0 = self._it_base(niter)
        return base + max(0, -(-(niter - it0) // self.record_every))

    def chain_shapes(self, niter):
        """(chain_shape, bchain_shape) the run() writeback expects — the
        chains axis appears only for nchains > 1 so single-chain files keep
        the reference's 2-d layout.  The facade and bench allocate through
        this so the layout lives in one place.  With ``record_every=k > 1``
        the row count is the thinned record length, not ``niter``."""
        rows = self._row_layout(niter)
        if self.C == 1:
            return (rows, self.cm.nx), (rows, self.nb_total)
        return (rows, self.C, self.cm.nx), (rows, self.C, self.nb_total)

    def _squeeze(self, arr):
        """Drop the chains axis for nchains=1 so chain files keep the
        reference's 2-d layout."""
        return arr[:, 0] if self.C == 1 else arr

    def _x_in(self, x):
        """Accept a single start point (tiled to all chains — per-chain PRNG
        streams decorrelate them within a few sweeps) or per-chain (C, nx)
        starts."""
        x = np.asarray(x, dtype=np.float64)
        if x.ndim == 1:
            x = np.tile(x, (self.C, 1))
        if x.shape != (self.C, self.cm.nx):
            raise ValueError(f"x0 has shape {x.shape}; expected "
                             f"({self.cm.nx},) or ({self.C}, {self.cm.nx})")
        return x

    @staticmethod
    def _check_finite(arr, it0, what):
        """Host-side numerical-fault detector on every chunk writeback.

        The reference degrades gracefully on numerical failure (QR fallback
        ``pulsar_gibbs.py:511-516``, -inf likelihood ``:603-604``); the
        compiled sweep instead guarantees that a non-finite state never
        reaches the chain files silently (round-1 regression class: an NaN
        propagated through 2000 sweeps unnoticed)."""
        bad = ~np.isfinite(arr)
        if bad.any():
            first = int(np.argwhere(bad.any(axis=tuple(range(1, arr.ndim))))[0])
            raise FloatingPointError(
                f"non-finite {what} written at iteration {it0 + first}: "
                "the device sweep produced NaN/inf — check priors/initial "
                "state; chain files up to the previous checkpoint are valid")

    def _place_carry(self, tree):
        """Commit every ``(C, ...)`` leaf of a carry pytree to the
        mesh's chain axis (``parallel.sharding.shard_carry``).  A None
        mesh or a 1-d pulsar mesh returns the tree untouched, so every
        staging site calls this unconditionally.  Chains are
        independent Gibbs processes, so placement alone makes the
        chain axis collective-free — the contracts/crn_2d_mesh.json
        census pins that."""
        from ..parallel.sharding import shard_carry

        return shard_carry(self._mesh, tree, self.C)

    def _host_carry(self, pending):
        """The pending-writeback tuple with its carry leaves converted to
        host arrays (mega-chunk mode): the next dispatch DONATES the
        device buffers these leaves alias, so they must be read out
        before it is enqueued.  The record slabs (xs, bs) are outputs
        only — never donated — and stay on device for the overlapped
        d2h/writeback path."""
        (row, m, xs, bs, x_end, b_end, it_end, health, sk, es) = pending
        tm = self._jax.tree_util.tree_map
        return (row, m, xs, bs,
                np.asarray(x_end), np.asarray(b_end), it_end,
                tm(np.asarray, health),
                None if sk is None else tm(np.asarray, sk),
                None if es is None else tm(np.asarray, es))

    def run(self, x, chain, bchain, start, niter):
        import jax.numpy as jnp

        cm = self.cm
        x = self._place_carry(
            jnp.asarray(self._x_in(x), dtype=cm.cdtype))   # (C, nx)
        if cm.orf_B is not None:
            # sampled-ORF start state must be positive definite: the MH
            # block rejects non-PD proposals but cannot escape a non-PD
            # start (a prior draw of the weights usually is one)
            th = np.asarray(x)[:, np.asarray(cm.orf_par_ix)]
            G = (np.eye(cm.P)[None]
                 + np.einsum("cj,jpq->cpq", th, np.asarray(cm.orf_B)))
            wmin = np.linalg.eigvalsh(G).min(axis=(-2, -1))
            if (wmin <= 1e-10).any():
                raise ValueError(
                    "initial ORF weights give a non-positive-definite "
                    f"correlation matrix (min eigenvalue {wmin.min():.2e}); "
                    "start the *_orfw_* parameters at 0 (G = identity) — "
                    "x0[idx.orf] = 0")
        # a fresh run invalidates DE buffers derived from a previous
        # run's chain rows (the facade reuses one backend across
        # sample() calls); the seed entry (-1) is still valid but cheap
        # to rebuild once per run
        self._de_dev_cache = {}
        if self.obs is not None and start == 0:
            # diagnostic sketches are per-run: a fresh run must not
            # inherit a previous sample() call's moments (resume keeps
            # accumulating within the process; a fresh process simply
            # restarts the sketch — diagnostics, not sampled state)
            from ..obs.sketch import init_state

            self._obs_state = init_state(self.obs, self.C)
            self._obs_snaps = []
        if self.sentinel is not None:
            # streak state is per-run: a supervised retry must not
            # inherit the failed attempt's stuck count
            self.sentinel.reset_run()
        ii = start
        if ii == 0:
            # draw b from the initial state before any conditional touches
            # it (oracle order, numpy_backend.py:319-321): the first warmup
            # sweep's rho draw then sees real tau, not the b=0 singularity
            self.key, k0 = self._jr.split(self.key)
            self.b = self._jit_draw_b(x, self._chain_keys(k0))
            W = min(self.warmup_sweeps, max(0, niter - 1))
            if W > 0:
                self.key, sub = self._jr.split(self.key)
                fn = self._warmup_chunk_fn(W)
                with otrace.span("warmup.chunk", sweeps=W):
                    x, b, xs, bs, health = fn(
                        x, self._place_carry(jnp.asarray(self.b)), sub,
                        jnp.asarray(0, jnp.int32),
                        self._place_carry(self._aux()),
                        jnp.asarray(W, jnp.int32))
                self.b = b
                xs_h = self._squeeze(np.asarray(xs, dtype=np.float64))
                self._check_finite(xs_h, 0, "warmup state")
                bs_h = self._squeeze(np.asarray(bs, np.float64))
                self._check_finite(bs_h, 0, "warmup b coefficients")
                self._observe_health(health, W)
                wr = self._rows_of(W)          # thinned warmup row count
                chain[0:wr] = xs_h
                bchain[0:wr] = bs_h
            else:
                chain[0] = self._squeeze(np.asarray(
                    x, dtype=np.float64)[None])[0]
                bchain[0] = self._squeeze(self._b_flat(self.b)[None])[0]
                W = 0 if niter <= 1 else 1
                wr = W
            row = max(wr, 0)
            x_h = self._squeeze(np.asarray(x, dtype=np.float64)[None])
            b_h = self._squeeze(self._b_flat(self.b)[None])
            # the final warmup carry is not in xs (the scan records
            # pre-sweep states), so guard this row separately
            self._check_finite(x_h, row, "post-warmup state")
            self._check_finite(b_h, row, "post-warmup b coefficients")
            chain[row if W else 0] = x_h[0]
            bchain[row if W else 0] = b_h[0]
            x = self._first_sweep(x)
            ii = W + 1 if W else 1             # iterations consumed
            rowc = row + 1 if W else 1         # host rows written
            self.x_cur = np.asarray(x, dtype=np.float64)
            self._it_cur = ii
            yield rowc
        else:
            # resuming mid-run: ``start`` counts recorded ROWS; under
            # thinning the iteration counter diverges from it and must be
            # restored from the checkpoint (written as ``it_cur``)
            rowc = start
            if self.record_every > 1:
                it = getattr(self, "_resume_it", None)
                if it is None:
                    raise RuntimeError(
                        "resume with record_every > 1 needs the checkpoint "
                        "iteration counter (adapt.npz 'it_cur'); this "
                        "checkpoint predates it — resume with "
                        "record_every=1 or start fresh")
                ii = int(it)
        # double-buffered steady loop: dispatch chunk i+1 (async on device)
        # BEFORE converting chunk i's outputs, so host-side writeback and
        # the device-to-host transfer overlap device compute (on the
        # tunneled TPU the per-chunk transfer+conversion otherwise
        # serializes with the sweep and costs ~40% of wall time).
        # Checkpoint consistency: the state yielded with chunk i's rows is
        # chunk i's own carry (x_end, b_end) — never the in-flight chunk's.
        b_dev = self._place_carry(jnp.asarray(self.b))
        obs_on = self.obs is not None
        ens_on = self._ens is not None
        # device-resident ensemble (ladder/counter) carry: advanced at
        # dispatch like x/b; self._ens_state is only updated at WRITEBACK
        # so the checkpointed adapt state stays consistent with the rows
        # it is yielded with (same contract as x_cur below)
        es_dev = (self._place_carry(self._jax.tree_util.tree_map(
            jnp.asarray, self._ens_state)) if ens_on else None)
        pending = None    # (row, m, xs, bs, x_end, b_end, it_end, health,
                          #  sk, es)

        def _writeback(row, m, xs, bs, x_end, b_end, it_end, health,
                       sk=None, es=None):
            # a trailing short chunk records extra rows (the compiled
            # chunk always runs full length); truncate HOST-side — an
            # eager device xs[:m] would dispatch with a host scalar
            # operand, an implicit transfer the transfer_guard mode
            # (rightly) rejects
            with otrace.span("chunk.d2h", row=row, rows=m):
                # these conversions block on the chunk's device results
                # AND run the device->host record copy — the span is
                # honestly device-wait + transfer, not separable here
                xs_h = self._squeeze(np.asarray(xs, dtype=np.float64))[:m]
                bs_h = self._squeeze(np.asarray(bs, np.float64))[:m]
            with otrace.span("chunk.writeback", row=row, rows=m):
                self._check_finite(xs_h, row, "chain state")
                self._check_finite(bs_h, row, "b coefficients")
                # sentinel BEFORE the state advances: a stuck-chain raise
                # leaves x_cur/_it_cur at the previous writeback, so the
                # facade's checkpoint stays consistent for the rewind
                self._observe_health(health, it_end)
                chain[row:row + m] = xs_h
                bchain[row:row + m] = bs_h
                self.x_cur = np.asarray(x_end, dtype=np.float64)
                self.b = b_end
                self._it_cur = it_end
                if es is not None:
                    self._ens_state = es
                if sk is not None:
                    # cumulative moment snapshot off THIS chunk's sketch
                    # state (already computed — no wait on the in-flight
                    # chunk): the split-R-hat half-stream trail
                    self._obs_snaps.append(
                        (float(np.asarray(sk["n"])),
                         np.asarray(sk["mean"], np.float64),
                         np.asarray(sk["m2"], np.float64)))
            return row + m

        it_base = self._it_base(niter)
        wd = self.watchdog
        # mega-chunk mode: one dispatch covers n_sub sub-chunks (M
        # sweeps); the watchdog deadline and EMA normalize per sweep so
        # the guard tolerates the longer dispatch without going blind
        n_sub = max(1, int(getattr(self, "megachunk", 1)))
        M = self.chunk_size * n_sub
        mega_on = n_sub > 1
        # steady-chunk wall EMA, kept even without a watchdog: it is the
        # drain path's estimate of what landing the in-flight chunk costs
        wall_ema = None
        while ii < niter:
            if preemption.drain_requested():
                # stop dispatching new chunks the moment the drain flag
                # is up; the fate of the chunk already in flight is
                # decided below against the deadline
                break
            n = min(M, niter - ii)
            # always run the full compiled chunk length: a trailing
            # odd-length chunk would trigger a fresh ~30 s XLA compile for
            # one tail.  Because per-sweep keys are fold_in(base, iteration)
            # — pure in the iteration index — running extra sweeps and
            # discarding them is bitwise-identical to an exact-length run,
            # including on resume: the final state is read from the
            # recorded pre-sweep states at position n.
            # Thinning offset: record iterations ≡ it_base (mod k) in
            # absolute index.  Chunk starts stay on that residue (ctor
            # enforces k | chunk_size), except when an old run's partial
            # tail is extended — that resume pays one fresh compile for
            # its off-residue chunk function.
            off = (it_base - ii) % self.record_every
            # a _chunk_fn cache miss means THIS chunk pays a fresh XLA
            # compile at first execution — its wall must not feed the
            # watchdog EMA (first_floor_s covers cold compiles)
            n_fns = len(self._sweep_fns)
            fn = (self._mega_fn(self.chunk_size, n_sub, off) if mega_on
                  else self._chunk_fn(self.chunk_size, off))
            fresh_compile = len(self._sweep_fns) != n_fns
            if mega_on and pending is not None:
                # the mega program donates its carry: enqueueing the
                # next dispatch invalidates the in-flight outputs the
                # pending writeback still needs.  Snapshot the SMALL
                # carry leaves to host first — this blocks on the
                # previous mega's device compute (an explicit sync point
                # the legacy loop pays at writeback anyway), while the
                # big record slabs still convert after the dispatch, so
                # D2H + ChainStore writeback keep overlapping compute
                with otrace.span("chunk.carry_sync", it0=ii):
                    pending = self._host_carry(pending)
            # stage every argument BEFORE the dispatch with explicit
            # device_put (jnp.asarray of a Python scalar is an IMPLICIT
            # transfer and would trip the guard); the dispatch itself is
            # then transfer-free under transfer_guard("disallow")
            with otrace.span("chunk.host_prep", it0=ii):
                dput = self._jax.device_put
                aux_dev = (self._aux_mega(chain, ii, n_sub) if mega_on
                           else self._aux(chain, ii))
                args = (x, b_dev, self.key, dput(np.int32(ii)),
                        self._place_carry(aux_dev),
                        dput(np.int32(n)))
                if ens_on:
                    args = args + (es_dev,)
                if obs_on:
                    args = args + (self._place_carry(self._obs_state),)

            def _go(fn=fn, args=args, it0=ii):
                # the fault seam and the (thread-local!) transfer guard
                # both live INSIDE this callable: an injected stall runs
                # on the watchdog's clock, and the guard covers the
                # dispatch on whichever thread executes it
                faults.fire("dispatch.chunk", row=it0, backend="jax")
                with self._dispatch_guard():
                    return fn(*args)

            # a cache-miss chunk legitimately compiles at this dispatch:
            # bracket it so phase-scoped retrace counters don't charge
            # it against the steady-state zero-retrace contract
            from ..analysis.guards import planned_compile
            pc = planned_compile() if fresh_compile \
                else contextlib.nullcontext()
            t0 = time.monotonic()
            with pc, otrace.span(
                    "chunk.compile_dispatch" if fresh_compile
                    else "chunk.dispatch", it0=ii, n=n):
                if wd is not None:
                    outs = wd.call(_go, what=f"chunk@{ii}", n=M)
                else:
                    outs = _go()
            x, b_dev, xs, bs, health = outs[:5]
            k_out = 5
            if ens_on:
                es_dev = outs[5]
                k_out = 6
            if obs_on:
                self._obs_state = outs[k_out]
            m = max(0, -(-(n - off) // self.record_every))
            if pending is not None:
                # start both host copies in flight together before the
                # blocking conversions (the b-record is the big payload).
                # Measured A/B (r4): issuing copy_to_host_async EARLIER —
                # right at dispatch, on the not-yet-computed arrays — cut
                # throughput 52 -> 34 sweeps/s under an identical tunnel:
                # on this backend an async copy enqueued behind in-flight
                # compute serializes the next chunk's execution against
                # the previous transfer.  Keep the copies here, one
                # iteration after dispatch, where the arrays are ready.
                for arr in (pending[2], pending[3]):
                    try:
                        arr.copy_to_host_async()
                    except (AttributeError, RuntimeError):
                        pass
                # the writeback blocks on chunk i's device results — on
                # a hung device THIS is where the run would freeze, so
                # it runs under the same watchdog deadline
                if wd is not None:
                    yield wd.call(lambda p=pending: _writeback(*p),
                                  what=f"writeback@{pending[0]}", n=M)
                else:
                    yield _writeback(*pending)
            dt = time.monotonic() - t0
            if not fresh_compile:
                wall_ema = dt if wall_ema is None else (
                    0.3 * dt + 0.7 * wall_ema)
                # host-dict writes only — nothing traced, so the
                # bitwise-inert proof in tests/test_obs.py covers this
                telemetry.gauge("chunk_wall_ms", dt * 1e3)
                telemetry.gauge("chunk_wall_ema_ms", wall_ema * 1e3)
                if wd is not None:
                    wd.observe(dt, n=M)
            pending = (rowc, m, xs, bs, x, b_dev, ii + n, health,
                       self._obs_state if obs_on else None,
                       es_dev if ens_on else None)
            ii += n
            rowc += m
        if pending is not None:
            if preemption.should_abandon(wall_ema or 0.0):
                # landing the in-flight chunk would blow the grace
                # window: drop it — its sweeps replay bit-exactly on
                # resume (per-sweep keys are pure in the absolute
                # iteration index, so nothing is lost but wall time)
                telemetry.incr("drain_abandoned_chunks")
                otrace.instant("drain.abandon_chunk", row=pending[0])
            else:
                yield _writeback(*pending)

    def obs_summary(self):
        """Finalize the on-device diagnostic sketches (obs/summary.py).

        One bounded device->host transfer of the summary slab
        (``obs.sketch.state_bytes``), then pure NumPy: per-chain/channel
        mean/var, Sokal ACT/ESS in SWEEP units (the sketch streams every
        sweep, before record thinning), cross-covariance, per-block move
        rates, and the moment-based split-R-hat over the per-writeback
        snapshot trail.  Raises if the driver was built without
        ``obs=``."""
        if self.obs is None:
            raise RuntimeError(
                "driver built without obs=; pass obs=True (or a dict of "
                "sketch options) to JaxGibbsDriver to enable the "
                "on-device diagnostics")
        from ..obs.summary import finalize, moment_split_rhat

        state_h = {k: np.asarray(v) for k, v in self._obs_state.items()}
        out = finalize(self.obs, state_h)
        rhat = moment_split_rhat(self._obs_snaps, state_h)
        out["split_rhat_moment"] = rhat
        out["rhat_max"] = float(np.max(rhat)) if rhat is not None else None
        if self._ens is not None:
            # per-rung swap rates / stretch acceptance off the carried
            # ensemble counters (the sketch slab itself is untouched —
            # contracts/obs_quick.json stays byte-identical)
            out["ensemble"] = self.ensemble_summary()
        return out

    def ensemble_summary(self):
        """Host roll-up of the ensemble stage's carried counters (swap
        rates per rung, stretch acceptance per temperature, the current
        ladder); None when the stage is off."""
        if self._ens is None:
            return None
        from .ensemble import ensemble_summary

        return ensemble_summary(
            self._ens,
            {k: np.asarray(v) for k, v in self._ens_state.items()})

    def _observe_health(self, health, it_end):
        """Fold a chunk's on-device health reductions into the monitor
        (host conversion of a handful of per-chain scalars)."""
        if self.sentinel is None:
            return
        h = {k: np.asarray(v) for k, v in health.items()}
        self.sentinel.observe(h, it_end)
        self.health_last = self.sentinel.last

    def _de_hist_for(self, chain, m):
        """(C, H, d) DE history for DE period ``m`` (iterations
        [m*DE_Q, (m+1)*DE_Q)): chain rows [m*DE_Q - DE_DELAY - H,
        m*DE_Q - DE_DELAY).  DE_DELAY >= DE_Q + chunk guarantees those
        rows were written back (or preloaded, on resume) before any chunk
        touching period ``m`` is dispatched (chunk_size is capped at
        DE_DELAY - DE_Q in the constructor); until the window exists the
        adaptation-record seed, checkpointed in adapt_state, is used."""
        lo = m * DE_Q - DE_DELAY - DE_HIST_LEN
        hi = m * DE_Q - DE_DELAY
        if lo < 0:
            return self.red_hist
        rows = np.asarray(chain[lo:hi], dtype=np.float64)
        if rows.ndim == 2:          # squeezed single-chain layout
            rows = rows[:, None, :]
        return np.ascontiguousarray(
            rows[:, :, np.asarray(self.cm.idx.red)].transpose(1, 0, 2))

    def _de_bufs(self, chain, m0):
        """Device-resident DE buffers for periods ``(m0, m0+1)``,
        memoized: a period spans DE_Q/chunk dispatches, so rebuilding +
        re-uploading the (C, H, d) buffers every chunk would ship
        identical bytes down the (tunneled) device link most dispatches.
        The seed buffer is cached once under key -1 — every pre-window
        period shares the same device array."""
        import jax.numpy as jnp

        self._de_dev_cache = {k: v for k, v in self._de_dev_cache.items()
                              if k < 0 or k >= m0}
        out = []
        for m in (m0, m0 + 1):
            key = -1 if m * DE_Q - DE_DELAY - DE_HIST_LEN < 0 else m
            buf = self._de_dev_cache.get(key)
            if buf is None:
                buf = jnp.asarray(self._de_hist_for(chain, m),
                                  self.cm.cdtype)
                self._de_dev_cache[key] = buf
            out.append(buf)
        return out

    # ---- checkpointable state ----------------------------------------------

    def adapt_state(self):
        import jax.random as jr

        out = {"jax_key": np.asarray(jr.key_data(self.key)),
               "nchains": np.int64(self.C),
               "b_pad": np.asarray(self.b, dtype=np.float64),
               # iteration counter at the last writeback: equals the row
               # count at record_every=1, diverges under thinning — resume
               # restores the sweep index (and so the PRNG stream) from it
               "it_cur": np.int64(getattr(self, "_it_cur", 0)),
               "record_every": np.int64(self.record_every),
               "x_cur": np.asarray(getattr(
                   self, "x_cur", np.zeros((self.C, self.cm.nx))))}
        for key in ("aclength_white", "cov_red", "red_hist",
                    "aclength_ecorr",
                    "chol_white", "mode_white", "asqrt_white",
                    "chol_ecorr", "mode_ecorr", "asqrt_ecorr"):
            val = getattr(self, key)
            if val is not None:
                out[key] = np.asarray(val)
        if self._ens is not None:
            # ensemble carry (adaptive ladder + counters): part of the
            # sampled process when tempering is on, so resume must
            # restore it exactly for the bitwise contract
            out["ens_pt_ladder"] = np.int64(self._ens.n_temps)
            for k, v in self._ens_state.items():
                out["ens_" + k] = np.asarray(v)
        return out

    def load_adapt_state(self, state):
        import jax.random as jr

        state = dict(state)
        got_c = int(state.pop("nchains", 1))
        if got_c != self.C:
            raise RuntimeError(
                f"resume checkpoint was written with nchains={got_c} but "
                f"this sampler has nchains={self.C}; they must match")
        got_k = int(state.pop("record_every", 1))
        if got_k != self.record_every:
            # a mismatch would silently misread the row cursor as an
            # iteration counter (or vice versa), corrupting the chain and
            # the PRNG alignment
            raise RuntimeError(
                f"resume checkpoint was written with record_every={got_k} "
                f"but this sampler has record_every={self.record_every}; "
                "they must match")
        self.key = jr.wrap_key_data(
            np.asarray(state["jax_key"], dtype=np.uint32))
        b_pad = np.asarray(state["b_pad"], dtype=self.cm.cdtype)
        want = (self.C, self.cm.P, self.cm.Bmax)
        if b_pad.shape != want:
            # the padded pulsar width is part of the LOGICAL layout —
            # PRNG draw shapes pair threefry counters across the whole
            # padded block, so changing it re-keys every draw.  Resuming
            # across a width change is still a valid continuation of the
            # same posterior (pad rows are exact no-ops), just no longer
            # a bitwise one; reshard_restore preserves the width exactly
            # to keep the bitwise contract, so only a hand-built resume
            # lands here.
            warnings.warn(
                f"resume checkpoint's b coefficients have shape "
                f"{b_pad.shape} but this sampler is compiled for {want} "
                "(padded pulsar width changed); re-padding — the resumed "
                "chain is a valid continuation but NOT a bitwise replay. "
                "Use runtime.integrity.reshard_restore to preserve the "
                "checkpoint's layout exactly.", RuntimeWarning,
                stacklevel=2)
            nb = np.zeros(want, dtype=self.cm.cdtype)
            p = min(b_pad.shape[1], want[1])
            w = min(b_pad.shape[2], want[2])
            nb[:, :p, :w] = b_pad[:, :p, :w]
            b_pad = nb
        self.b = b_pad
        if "it_cur" in state:
            self._resume_it = int(state.pop("it_cur"))
        if "x_cur" in state:
            self.x_resume = np.asarray(state["x_cur"], dtype=np.float64)
        for key in ("aclength_white", "cov_red", "red_hist",
                    "aclength_ecorr",
                    "chol_white", "mode_white", "asqrt_white",
                    "chol_ecorr", "mode_ecorr", "asqrt_ecorr"):
            if key in state:
                val = np.asarray(state[key])
                setattr(self, key, int(val) if val.ndim == 0 else val)
        got_t = state.pop("ens_pt_ladder", None)
        if self._ens is not None:
            if got_t is None:
                raise RuntimeError(
                    "resume checkpoint was written with the ensemble "
                    "stage off but this sampler has ensemble=True; they "
                    "must match (the stage changes the sampled process)")
            if int(got_t) != self._ens.n_temps:
                raise RuntimeError(
                    f"resume checkpoint was written with pt_ladder="
                    f"{int(got_t)} but this sampler has pt_ladder="
                    f"{self._ens.n_temps}; they must match")
            es = {}
            for k, v in self._ens_state.items():
                ck = "ens_" + k
                if ck not in state:
                    raise RuntimeError(
                        f"resume checkpoint lacks ensemble state {ck!r}; "
                        "it was written by an incompatible version")
                ref = np.asarray(v)
                es[k] = np.asarray(state[ck]).astype(
                    ref.dtype).reshape(ref.shape)
            self._ens_state = es
        elif got_t is not None:
            raise RuntimeError(
                "resume checkpoint was written with the ensemble stage "
                "on (pt_ladder={}) but this sampler has ensemble=False; "
                "they must match".format(int(got_t)))
        if self.cov_red is not None:
            self._set_red_eigs()
        if self.do_red_mh and self.cov_red is not None \
                and self.red_hist is None:
            raise RuntimeError(
                "resume checkpoint lacks the red-block DE history "
                "(red_hist) — it was written by an incompatible version; "
                "delete the chain directory to start fresh")
        if len(self.cm.idx.white) and (self.aclength_white is None
                                       or self.chol_white is None
                                       or self.mode_white is None):
            raise RuntimeError(
                "resume checkpoint lacks white-noise adaptation state "
                "(chol/mode_white) — it was written by an incompatible "
                "version; delete the chain directory to start fresh")
        if (len(self.cm.idx.ecorr) and self.cm.ec_cols.shape[1]
                and (self.aclength_ecorr is None or self.chol_ecorr is None
                     or self.mode_ecorr is None)):
            raise RuntimeError(
                "resume checkpoint lacks ECORR adaptation state "
                "(chol/mode_ecorr); delete the chain directory to start "
                "fresh")


# ===========================================================================
# stable trace entry points (static auditing — analysis/jaxprcheck)
# ===========================================================================
# Each returns a jittable ``fn`` plus example arguments whose abstract
# trace / AOT lowering is a faithful stand-in for the production program
# at the given configuration, with no device execution beyond staging
# tiny host constants.  analysis/jaxprcheck walks these jaxprs/HLO
# against the contracts committed in contracts/*.json; the entries live
# here, next to the kernels they trace, so a kernel refactor updates its
# audit surface in the same diff (docs/LINTING.md, "jaxprcheck").


def gram_trace_entry(cm: CompiledPTA, nchains: int):
    """The exact (f64-accumulated) b-draw vmapped over ``nchains`` — the
    program whose Gram accumulation scratch is THE out-of-memory term of
    wide-chain compiles (ROADMAP item 1, README r4 notes: a
    ``(nseg, C, P, Nmax, B1)`` operand copy the TPU tiler pads ~3.4x
    past 15.75 GB at C=128).

    Returns ``(fn, example_args)`` with every argument an abstract
    ``jax.ShapeDtypeStruct``: ``jax.jit(fn).trace(*example_args)``
    yields the jaxpr the C1 HBM contract sizes without touching a
    device."""
    import jax
    import jax.random as jr

    def draw(x, key):
        return draw_b_fn(cm, x, key, exact=True)

    x = jax.ShapeDtypeStruct((int(nchains), cm.nx), cm.cdtype)
    keys = jax.ShapeDtypeStruct((int(nchains),), jr.key(0).dtype)
    return jax.vmap(draw), (x, keys)


def sweep_chunk_entry(pta, nchains, *, chunk=2, pad_pulsars=None, seed=0):
    """A steady-state compiled-chunk function plus abstract example
    arguments, built WITHOUT running warmup: the driver gets placeholder
    adaptation state (identity white-proposal factors, zero modes, an
    ACT of 2, no DE history) whose shapes and dataflow are identical to
    the adapted production chunk — values are irrelevant to a static
    audit.

    Returns ``(fn, example_args, drv)``; ``fn`` is the driver's cached
    jitted chunk (key ``(chunk, 0)``) and the example arguments mirror
    ``run()``'s staging ``(x, b, key, it0, aux, n_keep)``.  The aux
    pytree holds tiny concrete arrays (abstracted by ``.trace``); the
    carries and key are ``ShapeDtypeStruct``."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    drv = JaxGibbsDriver(pta, nchains=int(nchains), seed=seed,
                         pad_pulsars=pad_pulsars, chunk_size=int(chunk))
    cm = drv.cm
    C = drv.C
    if len(cm.idx.white):
        W = int(np.asarray(cm.white_par_ix).shape[1])
        eye = np.tile(np.eye(W, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_white = 2
        drv.chol_white = eye
        drv.asqrt_white = eye.copy()
        drv.mode_white = np.zeros((C, cm.P, W), np.float64)
    if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
        E = int(np.asarray(cm.ecorr_par_ix).shape[1])
        eye = np.tile(np.eye(E, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_ecorr = 2
        drv.chol_ecorr = eye
        drv.asqrt_ecorr = eye.copy()
        drv.mode_ecorr = np.zeros((C, cm.P, E), np.float64)
    fn = drv._chunk_fn(int(chunk), 0)
    args = (
        jax.ShapeDtypeStruct((C, cm.nx), cm.cdtype),
        jax.ShapeDtypeStruct((C, cm.P, cm.Bmax), cm.cdtype),
        jax.ShapeDtypeStruct((), jr.key(0).dtype),
        jnp.asarray(0, jnp.int32),
        drv._aux(),
        jnp.asarray(chunk, jnp.int32),
    )
    return fn, args, drv


def megachunk_sweep_chunk_entry(pta, nchains, *, chunk=2, megachunk=3,
                                pad_pulsars=None, seed=0):
    """The device-resident mega-chunk steady dispatch —
    :func:`sweep_chunk_entry`'s program scanned ``megachunk`` sub-chunks
    deep in ONE jitted function (``contracts/crn_megachunk.json``).

    The contract pins what makes the mega dispatch safe to amortize
    over: the (x, b) carries donated end-to-end through the outer scan,
    the per-sweep key-fold policy unchanged from the legacy chunk (keys
    are pure in the absolute iteration — the bitwise grid-independence
    proof's static half), and the output surface bounded by the thinned
    record slab (``megachunk`` times the legacy chunk's rows, nothing
    else grows)."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    n_sub = int(megachunk)
    drv = JaxGibbsDriver(pta, nchains=int(nchains), seed=seed,
                         pad_pulsars=pad_pulsars, chunk_size=int(chunk),
                         megachunk=n_sub)
    cm = drv.cm
    C = drv.C
    if len(cm.idx.white):
        W = int(np.asarray(cm.white_par_ix).shape[1])
        eye = np.tile(np.eye(W, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_white = 2
        drv.chol_white = eye
        drv.asqrt_white = eye.copy()
        drv.mode_white = np.zeros((C, cm.P, W), np.float64)
    if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
        E = int(np.asarray(cm.ecorr_par_ix).shape[1])
        eye = np.tile(np.eye(E, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_ecorr = 2
        drv.chol_ecorr = eye
        drv.asqrt_ecorr = eye.copy()
        drv.mode_ecorr = np.zeros((C, cm.P, E), np.float64)
    fn = drv._mega_fn(int(chunk), n_sub, 0)
    args = (
        jax.ShapeDtypeStruct((C, cm.nx), cm.cdtype),
        jax.ShapeDtypeStruct((C, cm.P, cm.Bmax), cm.cdtype),
        jax.ShapeDtypeStruct((), jr.key(0).dtype),
        jnp.asarray(0, jnp.int32),
        drv._aux_mega(None, None, n_sub),
        jnp.asarray(chunk * n_sub, jnp.int32),
    )
    return fn, args, drv


def obs_sweep_chunk_entry(pta, nchains, *, chunk=2, pad_pulsars=None,
                          seed=0, obs=True):
    """The INSTRUMENTED steady chunk — :func:`sweep_chunk_entry` with
    the obs sketch threaded through (``contracts/obs_quick.json``).

    The extra argument/output pair is the sketch state pytree; the
    contract pins that instrumenting the chunk adds zero collectives,
    keeps the donation surface (carries + sketch state all aliased),
    and bounds the total output bytes — i.e. the summary slab is the
    ONLY new device output and there is no hidden host transfer."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    drv = JaxGibbsDriver(pta, nchains=int(nchains), seed=seed,
                         pad_pulsars=pad_pulsars, chunk_size=int(chunk),
                         obs=obs)
    cm = drv.cm
    C = drv.C
    if len(cm.idx.white):
        W = int(np.asarray(cm.white_par_ix).shape[1])
        eye = np.tile(np.eye(W, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_white = 2
        drv.chol_white = eye
        drv.asqrt_white = eye.copy()
        drv.mode_white = np.zeros((C, cm.P, W), np.float64)
    if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
        E = int(np.asarray(cm.ecorr_par_ix).shape[1])
        eye = np.tile(np.eye(E, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_ecorr = 2
        drv.chol_ecorr = eye
        drv.asqrt_ecorr = eye.copy()
        drv.mode_ecorr = np.zeros((C, cm.P, E), np.float64)
    fn = drv._chunk_fn(int(chunk), 0)
    args = (
        jax.ShapeDtypeStruct((C, cm.nx), cm.cdtype),
        jax.ShapeDtypeStruct((C, cm.P, cm.Bmax), cm.cdtype),
        jax.ShapeDtypeStruct((), jr.key(0).dtype),
        jnp.asarray(0, jnp.int32),
        drv._aux(),
        jnp.asarray(chunk, jnp.int32),
        drv._obs_state,
    )
    return fn, args, drv


def ensemble_sweep_chunk_entry(pta, nchains, *, chunk=2, pad_pulsars=None,
                               seed=0, pt_ladder=1, mesh=None):
    """The ENSEMBLE steady chunk — :func:`sweep_chunk_entry` with the
    mixing stage on (``contracts/crn_ensemble.json``): ASIS interweave +
    interchain stretch (+ tempering swaps at ``pt_ladder > 1``), the
    small ``ens_state`` pytree threaded as an extra argument/output.

    With ``mesh=(chains, pulsars)`` the entry stages the carries with
    the production 2-d placement (concrete, device_put — argument
    shardings are what the partitioner sees), so the contract's
    ``isolate_axis`` check audits the REAL lowering: tempering swaps
    stay device-local on the chain axis, and only the stretch move's
    small ln-rho payload may cross chain blocks (the explicit
    allowlist — never b or design matrices)."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    mesh_obj = None
    if mesh is not None:
        from ..parallel.sharding import make_mesh

        mesh_obj = make_mesh(tuple(int(s) for s in mesh))
    drv = JaxGibbsDriver(pta, nchains=int(nchains), seed=seed,
                         pad_pulsars=pad_pulsars, chunk_size=int(chunk),
                         mesh=mesh_obj, ensemble=True,
                         pt_ladder=int(pt_ladder))
    cm = drv.cm
    C = drv.C
    if len(cm.idx.white):
        W = int(np.asarray(cm.white_par_ix).shape[1])
        eye = np.tile(np.eye(W, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_white = 2
        drv.chol_white = eye
        drv.asqrt_white = eye.copy()
        drv.mode_white = np.zeros((C, cm.P, W), np.float64)
    if len(cm.idx.ecorr) and (cm.ec_cols.shape[1] or cm.has_ke):
        E = int(np.asarray(cm.ecorr_par_ix).shape[1])
        eye = np.tile(np.eye(E, dtype=np.float64), (C, cm.P, 1, 1))
        drv.aclength_ecorr = 2
        drv.chol_ecorr = eye
        drv.asqrt_ecorr = eye.copy()
        drv.mode_ecorr = np.zeros((C, cm.P, E), np.float64)
    fn = drv._chunk_fn(int(chunk), 0)
    x0 = drv._place_carry(jnp.zeros((C, cm.nx), cm.cdtype))
    b0 = drv._place_carry(jnp.zeros((C, cm.P, cm.Bmax), cm.cdtype))
    args = (
        x0, b0,
        jr.key(seed),
        jnp.asarray(0, jnp.int32),
        drv._place_carry(drv._aux()),
        jnp.asarray(chunk, jnp.int32),
        drv._place_carry(drv._ens_state),
    )
    return fn, args, drv


def sharded_sweep_step(cm: CompiledPTA, x, b, key):
    """One CRN sweep with the :class:`CompiledPTA` passed as a jit
    ARGUMENT — the canonical surface of the C2 collective-census
    contract, mirroring ``__graft_entry__._dryrun_multichip_inner``
    (closure-captured jax.Arrays lower as replicated constants and GSPMD
    silently drops their shardings, so only argument shardings reach the
    partitioner).  The committed budget {'all-reduce': 5, 'all-gather':
    3} (MULTICHIP_r*.json) is measured on exactly this step."""
    import jax.random as jr

    k = jr.split(key, 5)
    r2 = residual_sq(cm, b)
    x, _ = mh_scan(cm, x, k[0], lambda q: lnlike_white_fn(cm, q, r2),
                   cm.idx.white, 3)
    x = red_conditional_update(cm, x, b, k[1])
    x = rho_update(cm, x, b, k[2])
    b = draw_b_fn(cm, x, k[3])
    return x, b
