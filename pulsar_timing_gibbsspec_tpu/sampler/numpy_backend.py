"""NumPy oracle backend: reference-faithful blocked Gibbs for one pulsar.

Implements the same mathematics as the reference ``PulsarBlockGibbs``
(``pulsar_gibbs.py``) — the van Haasteren & Vallisneri (2014) conditional
draws — in float64 NumPy.  This backend is the statistical oracle the JAX
device backend is KS-tested against (SURVEY §4: "the reference's own oracle,
PTMCMC-vs-Gibbs, becomes NumPy-vs-JAX").

Blocks per sweep (reference sweep order, ``pulsar_gibbs.py:656-698``):

1. white-noise EFAC/EQUAD: single-site MH on the b-conditional diagonal
   likelihood; first sweep runs 1000 adaptation steps and sizes later
   sub-chains by the measured autocorrelation time (``:332-406``)
2. power-law red hypers (A, gamma): adaptive MH on the b-conditional
   red likelihood, proposal covariance adapted on the first sweep from a
   marginalized-likelihood run (``:271-329``; PTMCMCSampler is replaced by
   an in-repo adaptive MH — SCAM/AM-style jumps from the adapted covariance)
3. free-spectrum rho_k: exact inverse-CDF draw when there is no intrinsic
   red noise, else Gumbel-max on a 1000-point log-uniform grid (``:199-268``)
4. Fourier coefficients b: Gaussian draw with covariance
   ``Sigma^-1 = (T^T N^-1 T + diag(phi^-1))^-1`` (``:489-520``)
"""

from __future__ import annotations

import numpy as np
import scipy.linalg as sl

from ..ops.acf import integrated_act
from .blocks import (BlockIndex, align_phi, gumbel_grid_draw,
                     proposal_step, rho_bounds, rho_grid,
                     rho_log_pdf_grid, tprocess_alpha_log_pdf_grid,
                     validate_sampling_flags)


class NumpyGibbs:
    """Single-pulsar oracle sampler over a host PTA model."""

    def __init__(self, pta, hypersample=None, redsample=None,
                 ecorrsample=None,
                 white_adapt_iters=1000, red_adapt_iters=2000, red_steps=20,
                 seed=None):
        self.pta = pta
        if len(pta.pulsars) != 1:
            raise ValueError("NumpyGibbs is single-pulsar; use the PTA facade")
        validate_sampling_flags(pta, hypersample, ecorrsample, redsample)
        self.hypersample = hypersample
        self.redsample = redsample
        self.white_adapt_iters = white_adapt_iters
        self.red_adapt_iters = red_adapt_iters
        self.red_steps = red_steps
        self.rng = np.random.default_rng(seed)

        self.idx = BlockIndex.build(pta.param_names)
        self._y = pta.get_residuals()[0]
        self._T = pta.get_basis()[0]
        self._model = pta.model(0)

        gw_slice = self._model.basis_slice("gw")
        self.gwid = np.arange(gw_slice.start, gw_slice.stop)
        try:
            self.rhomin, self.rhomax = rho_bounds(pta, "gw")
        except ValueError:   # powerlaw-family common process: no rho block
            self.rhomin, self.rhomax = 1e-20, 1e-8
        try:
            self.red_rhomin, self.red_rhomax = rho_bounds(pta, "red")
        except ValueError:
            self.red_rhomin, self.red_rhomax = self.rhomin, self.rhomax

        # only shared-column Fourier red signals: red_select band/backend
        # splits live on their own masked columns and are sampled by the
        # generic hyper-MH block, not the red conditional machinery
        self.red_sig = next((s for s in self._model._fourier
                             if "red" in s.name), None)
        self._alpha_idx = None
        if self.red_sig is not None:
            rsl = self._model._slices[self.red_sig.name]
            self.redid = np.arange(rsl.start, rsl.stop)
            if self.red_sig.psd_name == "tprocess":
                alphas = self.red_sig.params[2]
                names = pta.param_names
                self._alpha_idx = np.array(
                    [names.index(f"{alphas.name}_{k}")
                     for k in range(alphas.size)])
        self.gw_sig = next((s for s in self._model.signals if "gw" in s.name), None)
        # do red and gw share basis columns?  (CRN layout: yes; a
        # correlated own-column common process: no)
        self._red_shares_gw = (
            self.red_sig is not None and self.gw_sig is not None
            and len(np.intersect1d(self.redid, self.gwid)) > 0)
        if len(self.idx.rho) and len(self.idx.rho) != len(self.gwid) // 2:
            raise ValueError(
                f"found {len(self.idx.rho)} free-spectrum rho parameters but "
                f"{len(self.gwid) // 2} GW frequencies — the conditional rho "
                "draw requires exactly one 'spectrum' common process (use "
                "a single orf entry with common_psd='spectrum')")
        self.ecorr_sig = next((s for s in self._model.signals if "ecorr" in s.name), None)
        if self.ecorr_sig is not None:
            ec_slice = self._model.basis_slice("ecorr")
            self.ecid = np.arange(ec_slice.start, ec_slice.stop)

        # kernel-ECORR mode: the epoch blocks live inside N (Woodbury),
        # marginally identical to the basis representation; the (trailing)
        # ECORR columns are dropped from T and never sampled
        self.kernel_ecorr = ecorrsample == "kernel"
        if self.kernel_ecorr:
            if self.ecorr_sig is None:
                raise ValueError(
                    "ecorrsample='kernel' but the model has no ECORR signal")
            self._T = self._T[:, :self.ecid[0]]
            U = self.ecorr_sig._U                       # (ntoa, E)
            self._ke_E = U.shape[1]
            self._ke_eid = np.where(U.sum(axis=1) > 0, U.argmax(axis=1),
                                    self._ke_E)
            from ..models.priors import Constant

            self._ke_params = []
            for lab in self.ecorr_sig._owners:
                p = self.ecorr_sig._by_backend[lab]
                self._ke_params.append(
                    (p.name, p.value if isinstance(p, Constant) else None))

        self.nb_total = self._T.shape[1]
        self.b = np.zeros(self._T.shape[1])
        # per-sweep caches (invalidated when white params move,
        # reference pulsar_gibbs.py:664-665)
        self._TNT = None
        self._d = None

        # adaptation state (checkpointable)
        self.aclength_white = None
        self.cov_white = None
        self.cov_red = None
        self.red_hist = None
        self._red_pend = None
        self._red_count = 0
        self.aclength_ecorr = None

    # ---- parameter helpers -------------------------------------------------

    def map_params(self, xs):
        return self.pta.map_params(xs)

    def get_lnprior(self, xs):
        return self.pta.get_lnprior(xs)

    # ---- likelihoods -------------------------------------------------------

    def _ndiag(self, xs):
        return self.pta.get_ndiag(self.map_params(xs))[0]

    def _ensure_cache(self, Nvec):
        if self._TNT is None or self._d is None:
            self._TNT = self._T.T @ (self._T / Nvec[:, None])
            self._d = self._T.T @ (self._y / Nvec)

    def invalidate_cache(self):
        self._TNT = None
        self._d = None

    def _ke_wood(self, params, Nvec):
        from .blocks import ke_woodbury

        return ke_woodbury(params, Nvec, self._ke_eid, self._ke_E,
                           self._ke_params)

    def _ke_corr(self, params, Nvec, r):
        from .blocks import ke_corr

        return ke_corr(params, Nvec, r, self._ke_eid, self._ke_E,
                       self._ke_params)

    def _tnt_d(self, params, Nvec):
        """Per-sweep ``(T^T N^-1 T, T^T N^-1 y)``; the kernel-ECORR
        correction is applied at use time (it moves with the ECORR
        parameters, unlike the cached diagonal part)."""
        from .blocks import ke_tnt_corr

        self._ensure_cache(Nvec)
        if not self.kernel_ecorr:
            return self._TNT, self._d
        _, _, w = self._ke_wood(params, Nvec)
        corr = ke_tnt_corr(self._T, self._y, Nvec, w, self._ke_eid,
                           self._ke_E)
        return self._TNT - corr[:-1, :-1], self._d - corr[:-1, -1]

    def lnlike_white(self, xs):
        """Gaussian likelihood of ``y - T b`` (reference :523-546):
        diagonal N, plus the per-epoch Woodbury terms in kernel-ECORR
        mode."""
        Nvec = self._ndiag(xs)
        r = self._y - self._T @ self.b
        out = -0.5 * (np.sum(np.log(Nvec)) + np.sum(r * r / Nvec))
        if self.kernel_ecorr:
            out += self._ke_corr(self.map_params(xs), Nvec, r)
        return out

    def _gw_tau(self):
        """Per-frequency (sin^2 + cos^2)/2 of the GW coefficients
        (reference :208-209)."""
        bb = self.b[self.gwid] ** 2
        return 0.5 * (bb[::2] + bb[1::2])

    def _red_phi_at_gw_freqs(self, params):
        """Intrinsic-red phi aligned to the GW frequency grid: truncated when
        the red process has more modes, padded with a negligible floor when
        it has fewer (red and GW share leading Fourier columns)."""
        kgw = len(self.gwid) // 2
        if self.red_sig is None:
            return np.full(kgw, 1e-30)
        return align_phi(np.asarray(self.red_sig.get_phi(params))[::2], kgw)

    def lnlike_red(self, xs):
        """b-conditional likelihood of every GP hyper: the N(0, phi(x))
        terms of all Fourier + chromatic columns (reference :549-566 is
        the same sum on the shared columns up to hyper-independent
        constants).  Per-column form over the *whole* shared block — not
        truncated to the GW grid — so red-only tail frequencies (when
        red_components > common_components) are included, matching the
        device backend's generic target exactly."""
        params = self.map_params(xs)
        out = 0.0
        m = self._model
        for kind in (m._fourier, m._chrom):
            if not kind:
                continue
            if kind is m._fourier:
                # shared block: per-column phi sums every Fourier signal
                start = min(m._slices[s.name].start for s in kind)
                stop = max(m._slices[s.name].stop for s in kind)
                phi = np.zeros(stop - start)
                for s in kind:
                    sl_ = m._slices[s.name]
                    phi[sl_.start - start:sl_.stop - start] += \
                        np.asarray(s.get_phi(params))
                bb = self.b[start:stop]
                out += float(np.sum(-0.5 * np.log(phi)
                                    - 0.5 * bb * bb / phi))
            else:
                for s in kind:
                    sl_ = m._slices[s.name]
                    phi = np.asarray(s.get_phi(params))
                    bb = self.b[sl_]
                    out += float(np.sum(-0.5 * np.log(phi)
                                        - 0.5 * bb * bb / phi))
        return out

    def lnlike_ecorr(self, xs):
        """b-conditional likelihood of ECORR variances: the ECORR basis
        coefficients are iid N(0, phi_j)."""
        params = self.map_params(xs)
        phi = np.asarray(self.ecorr_sig.get_phi(params))
        bj = self.b[self.ecid]
        return float(np.sum(-0.5 * np.log(phi) - 0.5 * bj * bj / phi))

    def lnlike_fullmarg(self, xs):
        """b-marginalized likelihood (reference :569-610)."""
        params = self.map_params(xs)
        Nvec = self.pta.get_ndiag(params)[0]
        W = self._T.shape[1]
        phi = self.pta.get_phi(params)[0][:W]   # kernel mode: ecorr cols cut
        phiinv, logdet_phi = 1.0 / phi, float(np.sum(np.log(phi)))
        TNT, d = self._tnt_d(params, Nvec)
        out = -0.5 * (np.sum(np.log(Nvec)) + np.sum(self._y**2 / Nvec))
        if self.kernel_ecorr:
            out += self._ke_corr(params, Nvec, self._y)
        Sigma = TNT + np.diag(phiinv)
        try:
            cf = sl.cho_factor(Sigma)
        except np.linalg.LinAlgError:
            return -np.inf
        expval = sl.cho_solve(cf, d)
        logdet_sigma = 2.0 * np.sum(np.log(np.diag(cf[0])))
        return float(out + 0.5 * (d @ expval - logdet_sigma - logdet_phi))

    # ---- conditional draws -------------------------------------------------

    def draw_b(self, xs):
        """b | everything: N(Sigma^-1 d, Sigma^-1) via SVD factor
        (reference :489-520, including the QR fallback)."""
        params = self.map_params(xs)
        Nvec = self.pta.get_ndiag(params)[0]
        W = self._T.shape[1]
        phiinv = 1.0 / self.pta.get_phi(params)[0][:W]
        TNT, d = self._tnt_d(params, Nvec)
        Sigma = TNT + np.diag(phiinv)
        try:
            u, s, _ = sl.svd(Sigma)
            mn = u @ ((u.T @ d) / s)
            Li = u * np.sqrt(1.0 / s)
        except np.linalg.LinAlgError:
            Q, R = sl.qr(Sigma)
            Sigi = sl.solve(R, Q.T)
            mn = Sigi @ d
            u, s, _ = sl.svd(Sigi)
            Li = u * np.sqrt(s)
        self.b = mn + Li @ self.rng.standard_normal(len(mn))
        return self.b

    def update_rho(self, xs):
        """Free-spectrum conditional draw (reference :199-268)."""
        xnew = xs.copy()
        tau = self._gw_tau()
        if self.red_sig is None:
            # exact truncated inverse-CDF (vHV2014; reference :215-216).
            # tau = 0 (a zeroed coefficient pair) is a legal input whose
            # 0/0 limit is the prior draw; clamp like the device path
            # (jax_backend.rho_update) instead of warning through
            tau = np.maximum(tau, self.rhomin * 1e-6)
            hi = 1.0 - np.exp(tau / self.rhomax - tau / self.rhomin)
            eta = self.rng.uniform(0.0, hi)
            rhonew = tau / (tau / self.rhomax - np.log1p(-eta))
        else:
            # the red 'other' applies only on shared columns; a
            # correlated (own-column) common process carries none
            irn = (self._red_phi_at_gw_freqs(self.map_params(xnew))
                   if self._red_shares_gw
                   else np.full(len(tau), 1e-30))
            grid = rho_grid(self.rhomin, self.rhomax)
            rhonew = gumbel_grid_draw(self.rng,
                                      rho_log_pdf_grid(tau, irn, grid), grid)
        xnew[self.idx.rho] = 0.5 * np.log10(rhonew)
        return xnew

    def _mh_loop(self, xs, idx, lnlike, nsteps, sigma, record=None):
        """Single-site Metropolis loop with the reference proposal mixture."""
        x = xs.copy()
        ll0 = lnlike(x)
        lp0 = self.get_lnprior(x)
        for ii in range(nsteps):
            q = proposal_step(self.rng, x, idx, sigma)
            lp1 = self.get_lnprior(q)
            ll1 = lnlike(q) if np.isfinite(lp1) else -np.inf
            if (ll1 + lp1) - (ll0 + lp0) > np.log(self.rng.uniform()):
                x, ll0, lp0 = q, ll1, lp1
            if record is not None:
                record[ii] = x[idx]
        return x

    def update_white(self, xs, adapt=False):
        """EFAC/EQUAD block (reference :332-406): 1000-step adaptation sweep
        once, then ACT-sized sub-chains."""
        wind = self.idx.white
        sigma = 0.05 * len(wind)
        if adapt:
            rec = np.zeros((self.white_adapt_iters, len(wind)))
            xnew = self._mh_loop(xs, wind, self.lnlike_white,
                                 self.white_adapt_iters, sigma, record=rec)
            burn = rec[min(100, len(rec) // 2):]
            self.cov_white = np.atleast_2d(np.cov(burn, rowvar=False))
            self.aclength_white = int(max(
                1, max(int(integrated_act(burn[:, j])) for j in range(len(wind)))))
            return xnew
        return self._mh_loop(xs, wind, self.lnlike_white,
                             self.aclength_white, sigma)

    def update_red(self, xs, adapt=False):
        """Power-law (A, gamma) block (reference :271-329).  The reference
        drives this with PTMCMCSampler (SCAM/AM/DE); here the adaptation run
        estimates the red-block covariance on the marginalized likelihood,
        and per-sweep steps mix differential-evolution (past-history pair
        differences, the reference's top-weighted jump), covariance
        (SCAM-style eigendirection) and single-site jumps on the cheap
        b-conditional likelihood."""
        from .blocks import de_hist_push, de_step, seed_red_hist

        rind = self.idx.red
        if adapt:
            rec = np.zeros((self.red_adapt_iters, len(rind)))
            xnew = self._mh_loop(xs, rind, self.lnlike_fullmarg,
                                 self.red_adapt_iters, 0.05 * len(rind),
                                 record=rec)
            burn = rec[min(100, len(rec) // 2):]
            self.cov_red = np.atleast_2d(np.cov(burn, rowvar=False))
            self.cov_red += 1e-12 * np.eye(len(rind))
            self._red_eigs = np.linalg.svd(self.cov_red)
            self.red_hist = seed_red_hist(burn)
            self._red_pend = self.red_hist.copy()
            self._red_count = 0
            return xnew

        x = xs.copy()
        ll0 = self.lnlike_red(x)
        lp0 = self.get_lnprior(x)
        U, S, _ = self._red_eigs
        am_sqrt = U * np.sqrt(S)[None, :]
        for _ in range(self.red_steps):
            r = self.rng.uniform()
            if r < 0.5:
                # DE: reference ratio weights it highest (DE=50/SCAM=30/AM=15)
                q = de_step(self.rng, x, rind, self.red_hist)
            elif r < 0.65:
                # SCAM: jump along one adapted eigendirection
                q = x.copy()
                j = self.rng.integers(len(rind))
                step = 2.38 * np.sqrt(S[j]) * self.rng.standard_normal()
                q[rind] += step * U[:, j]
            elif r < 0.8:
                # AM: full adapted-covariance jump
                q = x.copy()
                z = self.rng.standard_normal(len(rind))
                q[rind] += (2.38 / np.sqrt(len(rind))) * (am_sqrt @ z)
            else:
                q = proposal_step(self.rng, x, rind, 0.05 * len(rind))
            lp1 = self.get_lnprior(q)
            ll1 = self.lnlike_red(q) if np.isfinite(lp1) else -np.inf
            if (ll1 + lp1) - (ll0 + lp0) > np.log(self.rng.uniform()):
                x, ll0, lp0 = q, ll1, lp1
        # push the state into the frozen-window history (proposals keep
        # reading a snapshot that refreshes every de_hist_push period)
        self.red_hist, self._red_pend, self._red_count = de_hist_push(
            self.red_hist, self._red_pend, self._red_count, x[rind])
        return x

    def update_red_rho(self, xs):
        """Per-frequency free-spectrum draw of an intrinsic red 'spectrum'
        process, with the common GW phi as the 'other' component (the
        per-pulsar analogue of reference ``pta_gibbs.py:252-276``; the
        reference's single-pulsar sampler never supported this)."""
        xnew = xs.copy()
        params = self.map_params(xnew)
        bb = self.b[self.redid] ** 2
        tau = 0.5 * (bb[::2] + bb[1::2])
        K = len(self.idx.red_rho)
        tau = tau[:K]
        gw = (align_phi(np.asarray(self.gw_sig.get_phi(params))[::2], K)
              if self._red_shares_gw else np.full(K, 1e-30))
        grid = rho_grid(self.red_rhomin, self.red_rhomax)
        xnew[self.idx.red_rho] = 0.5 * np.log10(gumbel_grid_draw(
            self.rng, rho_log_pdf_grid(tau, gw, grid), grid))
        return xnew

    def update_tprocess_alpha(self, xs):
        """Grid draw of the t-process scale factors from their conditional
        including the shared common-process variance: ``p(alpha | b) ~
        alpha^-2 e^(-1/alpha) (o + alpha plaw)^-1 e^(-tau/(o + alpha
        plaw))`` (see ``jax_backend.tprocess_alpha_update``; reduces to
        the conjugate ``InvGamma(2, 1 + tau/plaw)`` as ``o -> 0``)."""
        from ..models import psd as psdmod
        from .jax_backend import (TP_ALPHA_GRID, TP_ALPHA_LOG10_MAX,
                                  TP_ALPHA_LOG10_MIN)

        xnew = xs.copy()
        params = self.map_params(xnew)
        bb = self.b[self.redid] ** 2
        tau = 0.5 * (bb[::2] + bb[1::2])
        A = params[self.red_sig.params[0].name]
        gam = params[self.red_sig.params[1].name]
        plaw = psdmod.powerlaw(self.red_sig.freqs[::2],
                               self.red_sig._df[::2], A, gam)
        other = (align_phi(np.asarray(self.gw_sig.get_phi(params))[::2],
                           len(tau))
                 if self.gw_sig is not None and self._red_shares_gw
                 else np.full(len(tau), 1e-30))
        grid = 10.0 ** np.linspace(TP_ALPHA_LOG10_MIN, TP_ALPHA_LOG10_MAX,
                                   TP_ALPHA_GRID)
        logpdf = tprocess_alpha_log_pdf_grid(tau, plaw, other, grid)
        xnew[self._alpha_idx] = gumbel_grid_draw(self.rng, logpdf, grid)
        return xnew

    def update_ecorr(self, xs, adapt=False):
        """ECORR block via MH — the update the reference disables as
        broken (``pulsar_gibbs.py:409-486,676-683``), implemented against
        the basis-ECORR coefficients, or (kernel mode) against the
        in-N Woodbury white conditional given b."""
        eind = self.idx.ecorr
        sigma = 0.05 * len(eind)
        target = self.lnlike_white if self.kernel_ecorr else self.lnlike_ecorr
        if adapt:
            rec = np.zeros((self.white_adapt_iters, len(eind)))
            xnew = self._mh_loop(xs, eind, target,
                                 self.white_adapt_iters, sigma, record=rec)
            burn = rec[min(100, len(rec) // 2):]
            self.aclength_ecorr = int(max(
                1, max(int(integrated_act(burn[:, j])) for j in range(len(eind)))))
            return xnew
        return self._mh_loop(xs, eind, target,
                             self.aclength_ecorr, sigma)

    # ---- sweep -------------------------------------------------------------

    def sweep(self, xs, first=False):
        """One full Gibbs sweep, reference order (``pulsar_gibbs.py:656-698``)."""
        x = np.asarray(xs, dtype=np.float64).copy()
        if first:
            self.draw_b(x)
        self.invalidate_cache()
        if len(self.idx.white):
            x = self.update_white(x, adapt=first)
        if len(self.idx.ecorr) and self.ecorr_sig is not None:
            x = self.update_ecorr(x, adapt=first)
        if len(self.idx.red_rho):
            x = self.update_red_rho(x)
        if self._alpha_idx is not None:
            x = self.update_tprocess_alpha(x)
        if len(self.idx.red):
            x = self.update_red(x, adapt=first)
        if len(self.idx.rho):
            x = self.update_rho(x)
        self.draw_b(x)
        return x

    # ---- adaptation-state (de)serialization for resume --------------------

    def adapt_state(self) -> dict:
        from .blocks import rng_state_pack

        out = {"rng_state": rng_state_pack(self.rng), "b": self.b}
        for key in ("aclength_white", "cov_white", "cov_red", "red_hist",
                    "aclength_ecorr", "_red_pend", "_red_count"):
            val = getattr(self, key, None)
            if val is not None:
                out[key] = np.asarray(val)
        return out

    def load_adapt_state(self, state: dict):
        from .blocks import rng_state_unpack

        rng_state_unpack(self.rng, state["rng_state"])
        self.b = np.asarray(state["b"])
        for key in ("aclength_white", "cov_white", "cov_red", "red_hist",
                    "aclength_ecorr", "_red_pend", "_red_count"):
            if key in state:
                val = state[key]
                setattr(self, key, int(val) if val.ndim == 0 else np.asarray(val))
        if self.cov_red is not None:
            self._red_eigs = np.linalg.svd(self.cov_red)
            if self.red_hist is None:
                raise RuntimeError(
                    "resume checkpoint lacks the red-block DE history "
                    "(red_hist) — it was written by an incompatible "
                    "version; delete the chain directory to start fresh")
            if getattr(self, "_red_pend", None) is None:
                self._red_pend = np.asarray(self.red_hist).copy()
                self._red_count = 0
