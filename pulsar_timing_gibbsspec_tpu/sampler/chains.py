"""Chain persistence: periodic dumps, resume, adaptation-state checkpoints.

The reference saves ``chain.npy``/``bchain.npy`` every 100 iterations
(``pulsar_gibbs.py:701-710``) but its resume path reads ``chain.txt``
(``:638``) — a mismatch SURVEY §5 flags — and never persists MH-adaptation
state, so a resumed run would hit undefined ``aclength_white`` (latent bug,
SURVEY §5).  Here both are fixed: resume reads what was written, and an
``adapt.npz`` sidecar carries adaptation state (covariances, ACT lengths,
RNG/PRNG state) so a resumed chain continues the same stochastic process.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np


class ChainStore:
    """Directory of: chain.npy, bchain.npy, pars_chain.txt, pars_bchain.txt,
    adapt.npz."""

    def __init__(self, outdir, param_names, b_param_names):
        self.outdir = Path(outdir)
        self.outdir.mkdir(parents=True, exist_ok=True)
        self.param_names = list(param_names)
        self.b_param_names = list(b_param_names)
        np.savetxt(self.outdir / "pars_chain.txt", self.param_names, fmt="%s")
        np.savetxt(self.outdir / "pars_bchain.txt", self.b_param_names, fmt="%s")

    def save(self, chain, bchain, upto, adapt_state=None):
        """Persist rows [0, upto) plus adaptation state, atomically enough
        for a crash between files not to corrupt resume (write tmp, rename)."""
        for nm, arr in (("chain.npy", chain), ("bchain.npy", bchain)):
            tmp = self.outdir / (nm + ".tmp.npy")
            np.save(tmp, arr[:upto])
            os.replace(tmp, self.outdir / nm)
        if adapt_state is not None:
            tmp = self.outdir / "adapt.npz.tmp.npz"
            np.savez(tmp, iter=np.int64(upto), **adapt_state)
            os.replace(tmp, self.outdir / "adapt.npz")

    def log_metrics(self, record: dict):
        """Append one JSON line to ``metrics.jsonl`` — the structured
        observability stream (iteration progress, rates, adaptation
        state); the reference only ever prints a percent line
        (``pta_gibbs.py:707-711``)."""
        import json
        import time as _time

        record = {"ts": round(_time.time(), 3),
                  **{k: v for k, v in record.items() if v is not None}}
        with open(self.outdir / "metrics.jsonl", "a") as fh:
            fh.write(json.dumps(record) + "\n")

    def load_resume(self):
        """Return (chain, bchain, start_iter, adapt_state) or None if there
        is nothing to resume from."""
        cpath = self.outdir / "chain.npy"
        bpath = self.outdir / "bchain.npy"
        if not (cpath.exists() and bpath.exists()):
            return None
        chain = np.load(cpath)
        bchain = np.load(bpath)
        upto = min(len(chain), len(bchain))
        adapt = None
        apath = self.outdir / "adapt.npz"
        if apath.exists():
            with np.load(apath) as z:
                adapt = {k: z[k] for k in z.files}
            upto = min(upto, int(adapt.pop("iter")))
        return chain[:upto], bchain[:upto], upto, adapt
