"""Chain persistence: periodic dumps, resume, adaptation-state checkpoints.

The reference saves ``chain.npy``/``bchain.npy`` every 100 iterations
(``pulsar_gibbs.py:701-710``) but its resume path reads ``chain.txt``
(``:638``) — a mismatch SURVEY §5 flags — and never persists MH-adaptation
state, so a resumed run would hit undefined ``aclength_white`` (latent bug,
SURVEY §5).  Here both are fixed: resume reads what was written, and an
``adapt.npz`` sidecar carries adaptation state (covariances, ACT lengths,
RNG/PRNG state) so a resumed chain continues the same stochastic process.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np


class ChainStore:
    """Directory of: chain.npy, bchain.npy, pars_chain.txt, pars_bchain.txt,
    adapt.npz."""

    def __init__(self, outdir, param_names, b_param_names):
        self.outdir = Path(outdir)
        self.outdir.mkdir(parents=True, exist_ok=True)
        self.param_names = list(param_names)
        self.b_param_names = list(b_param_names)
        np.savetxt(self.outdir / "pars_chain.txt", self.param_names, fmt="%s")
        np.savetxt(self.outdir / "pars_bchain.txt", self.b_param_names, fmt="%s")

    def save(self, chain, bchain, upto, adapt_state=None):
        """Persist rows [0, upto) plus adaptation state, atomically enough
        for a crash between files not to corrupt resume (write tmp, rename)."""
        for nm, arr in (("chain.npy", chain), ("bchain.npy", bchain)):
            tmp = self.outdir / (nm + ".tmp.npy")
            np.save(tmp, arr[:upto])
            os.replace(tmp, self.outdir / nm)
        if adapt_state is not None:
            tmp = self.outdir / "adapt.npz.tmp.npz"
            np.savez(tmp, iter=np.int64(upto), **adapt_state)
            os.replace(tmp, self.outdir / "adapt.npz")

    def log_metrics(self, record: dict):
        """Append one JSON line to ``metrics.jsonl`` — the structured
        observability stream (iteration progress, rates, adaptation
        state); the reference only ever prints a percent line
        (``pta_gibbs.py:707-711``)."""
        import json
        import time as _time

        record = {"ts": round(_time.time(), 3),
                  **{k: v for k, v in record.items() if v is not None}}
        with open(self.outdir / "metrics.jsonl", "a") as fh:
            fh.write(json.dumps(record) + "\n")

    def export_hdf5(self, chain, bchain, upto, extra_attrs=None):
        """Write ``chain.h5`` — the HDF5 chain container the reference
        leaves as a TODO ("definitely need to make hdf5 files... and
        la_forge core readers", ``pulsar_gibbs.py:707-708``).  Layout is
        la-forge-Core friendly: a ``chain`` dataset with the parameter
        names in ``params`` (plus the coefficient chain and its names),
        attributes carrying the row count.  Requires ``h5py``; raises a
        clear error when it is missing."""
        try:
            import h5py
        except ImportError as exc:       # pragma: no cover
            raise RuntimeError(
                "HDF5 export requires h5py (chain.npy/bchain.npy remain "
                "the canonical outputs)") from exc

        tmp = self.outdir / "chain.h5.tmp"
        with h5py.File(tmp, "w") as fh:
            fh.create_dataset("chain", data=np.asarray(chain[:upto]))
            fh.create_dataset("bchain", data=np.asarray(bchain[:upto]))
            st = h5py.string_dtype()
            fh.create_dataset("params", data=np.asarray(self.param_names,
                                                        dtype=st))
            fh.create_dataset("b_params", data=np.asarray(self.b_param_names,
                                                          dtype=st))
            fh.attrs["niter"] = int(upto)
            for k, v in (extra_attrs or {}).items():
                fh.attrs[k] = v
        os.replace(tmp, self.outdir / "chain.h5")

    def load_resume(self):
        """Return (chain, bchain, start_iter, adapt_state) or None if there
        is nothing to resume from."""
        cpath = self.outdir / "chain.npy"
        bpath = self.outdir / "bchain.npy"
        if not (cpath.exists() and bpath.exists()):
            return None
        chain = np.load(cpath)
        bchain = np.load(bpath)
        upto = min(len(chain), len(bchain))
        adapt = None
        apath = self.outdir / "adapt.npz"
        if apath.exists():
            with np.load(apath) as z:
                adapt = {k: z[k] for k in z.files}
            upto = min(upto, int(adapt.pop("iter")))
        return chain[:upto], bchain[:upto], upto, adapt
