"""Chain persistence: periodic dumps, resume, adaptation-state checkpoints.

The reference saves ``chain.npy``/``bchain.npy`` every 100 iterations
(``pulsar_gibbs.py:701-710``) but its resume path reads ``chain.txt``
(``:638``) — a mismatch SURVEY §5 flags — and never persists MH-adaptation
state, so a resumed run would hit undefined ``aclength_white`` (latent bug,
SURVEY §5).  Here both are fixed: resume reads what was written, and an
``adapt.npz`` sidecar carries adaptation state (covariances, ACT lengths,
RNG/PRNG state) so a resumed chain continues the same stochastic process.

Integrity (docs/RESILIENCE.md): each save rotates the previous verified
checkpoint to a ``.bak`` generation and writes a ``manifest.json``
sidecar (sha256/size/shape per file, row count) LAST — resume verifies
the set against it, rolls back to ``.bak`` on mismatch, and only then
trusts the files.  The ``runtime.faults`` seams inside ``save`` let the
chaos suite kill the process between the two ``os.replace`` calls and
prove recovery is bit-exact.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path

import numpy as np


class ChainStore:
    """Directory of: chain.npy, bchain.npy, pars_chain.txt, pars_bchain.txt,
    adapt.npz (+ manifest.json and one rotating .bak generation)."""

    def __init__(self, outdir, param_names, b_param_names, backup=True):
        self.outdir = Path(outdir)
        self.outdir.mkdir(parents=True, exist_ok=True)
        self.param_names = list(param_names)
        self.b_param_names = list(b_param_names)
        #: keep a rotating .bak of the previous verified checkpoint set
        self.backup = bool(backup)
        np.savetxt(self.outdir / "pars_chain.txt", self.param_names, fmt="%s")
        np.savetxt(self.outdir / "pars_bchain.txt", self.b_param_names, fmt="%s")

    def save(self, chain, bchain, upto, adapt_state=None, extra=None):
        """Persist rows [0, upto) plus adaptation state, atomically enough
        for a crash between files not to corrupt resume (write tmp, rename;
        the manifest written last makes any torn combination detectable).

        ``extra`` is merged into ``manifest.json`` — the facade passes the
        logical-layout / shard-map sections that make the checkpoint
        resumable on a different device count (docs/RESILIENCE.md)."""
        from ..runtime import faults, integrity

        if self.backup:
            # rotate BEFORE touching the primaries: a kill anywhere in
            # this save leaves the .bak holding the previous checkpoint
            integrity.rotate_backup(self.outdir)
        for nm, arr in (("chain.npy", chain), ("bchain.npy", bchain)):
            tmp = self.outdir / (nm + ".tmp.npy")
            np.save(tmp, arr[:upto])
            os.replace(tmp, self.outdir / nm)
            if nm == "chain.npy":
                faults.fire("chainstore.between_replaces", row=upto,
                            outdir=self.outdir)
        if adapt_state is not None:
            tmp = self.outdir / "adapt.npz.tmp.npz"
            np.savez(tmp, iter=np.int64(upto), **adapt_state)
            os.replace(tmp, self.outdir / "adapt.npz")
        integrity.write_manifest(self.outdir, rows=upto, extra=extra)
        faults.fire("chainstore.post_save", row=upto, outdir=self.outdir)

    def log_metrics(self, record: dict):
        """Append one JSON line to ``metrics.jsonl`` — the structured
        observability stream (iteration progress, rates, adaptation
        state); the reference only ever prints a percent line
        (``pta_gibbs.py:707-711``)."""
        import json
        import time as _time

        record = {"ts": round(_time.time(), 3),
                  **{k: v for k, v in record.items() if v is not None}}
        with open(self.outdir / "metrics.jsonl", "a") as fh:
            fh.write(json.dumps(record) + "\n")

    def export_hdf5(self, chain, bchain, upto, extra_attrs=None):
        """Write ``chain.h5`` — the HDF5 chain container the reference
        leaves as a TODO ("definitely need to make hdf5 files... and
        la_forge core readers", ``pulsar_gibbs.py:707-708``).  Layout is
        la-forge-Core friendly: a ``chain`` dataset with the parameter
        names in ``params`` (plus the coefficient chain and its names),
        attributes carrying the row count.  Requires ``h5py``; raises a
        clear error when it is missing."""
        try:
            import h5py
        except ImportError as exc:       # pragma: no cover
            raise RuntimeError(
                "HDF5 export requires h5py (chain.npy/bchain.npy remain "
                "the canonical outputs)") from exc

        tmp = self.outdir / "chain.h5.tmp"
        try:
            with h5py.File(tmp, "w") as fh:
                fh.create_dataset("chain", data=np.asarray(chain[:upto]))
                fh.create_dataset("bchain", data=np.asarray(bchain[:upto]))
                st = h5py.string_dtype()
                fh.create_dataset("params", data=np.asarray(self.param_names,
                                                            dtype=st))
                fh.create_dataset("b_params",
                                  data=np.asarray(self.b_param_names,
                                                  dtype=st))
                fh.attrs["niter"] = int(upto)
                for k, v in (extra_attrs or {}).items():
                    fh.attrs[k] = v
            os.replace(tmp, self.outdir / "chain.h5")
        finally:
            # a failed export must not leave a stale tmp that a later
            # retry's os.replace would silently promote
            tmp.unlink(missing_ok=True)

    def load_resume(self, force_requeue=False):
        """Return (chain, bchain, start_iter, adapt_state) or None if there
        is nothing to resume from.

        When a ``manifest.json`` exists the set is verified against it
        first; a mismatch (torn write, truncation, bit rot) rolls back
        to the ``.bak`` generation, and :class:`runtime.CheckpointError`
        is raised when neither set verifies — never a silent resume
        from corrupt files.  Pre-manifest directories skip verification
        (legacy path) but a chain/bchain row-count mismatch is still
        reported loudly instead of silently truncated.

        A quarantine-marked manifest (the serving tier parked this job
        after exhausting its row-health budget) refuses to load unless
        ``force_requeue=True`` — ``integrity.check_not_quarantined``,
        shared with ``integrity.load_resume`` so the facade /
        ``reshard_restore`` path cannot silently resume what the
        scheduler refused."""
        from ..runtime import integrity, telemetry

        man = integrity.read_manifest(self.outdir)
        if man is not None:
            rep = integrity.verify(self.outdir, man)
            if not rep["ok"]:
                bad = ", ".join(rep["bad"])
                telemetry.incr("corrupt_checkpoints")
                self.log_metrics({"event": "checkpoint_corrupt",
                                  "files": rep["bad"]})
                if not integrity.rollback(self.outdir):
                    raise integrity.CheckpointError(
                        f"{self.outdir}: checkpoint failed integrity "
                        f"verification ({bad}) and no verified .bak "
                        "backup exists; delete the directory to start "
                        "fresh")
                warnings.warn(
                    f"{self.outdir}: checkpoint failed integrity "
                    f"verification ({bad}); rolled back to the previous "
                    ".bak checkpoint", RuntimeWarning, stacklevel=2)
                self.log_metrics({"event": "checkpoint_rollback"})
                man = integrity.read_manifest(self.outdir)
        integrity.check_not_quarantined(self.outdir, force_requeue,
                                        manifest=man)
        cpath = self.outdir / "chain.npy"
        bpath = self.outdir / "bchain.npy"
        if not (cpath.exists() and bpath.exists()):
            return None
        chain = np.load(cpath)
        bchain = np.load(bpath)
        if len(chain) != len(bchain):
            # verified sets can't get here; a legacy (pre-manifest) torn
            # checkpoint can — recoverable, but never silently
            torn = ("bchain.npy" if len(bchain) < len(chain)
                    else "chain.npy")
            warnings.warn(
                f"{self.outdir}: torn checkpoint — chain.npy has "
                f"{len(chain)} rows, bchain.npy has {len(bchain)} "
                f"({torn} is short); resuming from the common prefix",
                RuntimeWarning, stacklevel=2)
            self.log_metrics({"event": "torn_checkpoint", "file": torn,
                              "chain_rows": int(len(chain)),
                              "bchain_rows": int(len(bchain))})
            telemetry.incr("torn_checkpoints")
        upto = min(len(chain), len(bchain))
        if man is not None and not man.get("corrupt"):
            upto = min(upto, int(man.get("rows", upto)))
        adapt = None
        apath = self.outdir / "adapt.npz"
        if apath.exists():
            try:
                with np.load(apath) as z:
                    adapt = {k: z[k] for k in z.files}
            except Exception as exc:
                raise integrity.CheckpointError(
                    f"{self.outdir}/adapt.npz is unreadable ({exc}); the "
                    "adaptation state cannot be restored — delete the "
                    "directory to start fresh") from exc
            upto = min(upto, int(adapt.pop("iter")))
        return chain[:upto], bchain[:upto], upto, adapt
