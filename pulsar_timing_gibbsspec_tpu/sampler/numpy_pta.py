"""NumPy oracle backend: multi-pulsar blocked Gibbs with a common spectrum.

Reference semantics: ``pta_gibbs.py`` (experimental per the reference
README).  The single cross-pulsar coupling is the common free-spectrum
conditional — per-pulsar grid PDFs multiplied across pulsars before the
inverse-CDF draw (``pta_gibbs.py:181-214``, product at ``:205``); everything
else (white noise, intrinsic red, b-draws) is per-pulsar block-diagonal
(CRN-only: reference ``:533`` assumes phi block-diagonal, SURVEY §3.6).

Note on conventions: the reference's two files disagree cosmetically —
``pta_gibbs.py:195`` uses ``tau = b_sin^2 + b_cos^2`` with
``pdf ~ r exp(-r/2)`` while ``pulsar_gibbs.py:208-209`` uses
``tau = (b_sin^2+b_cos^2)/2`` with ``r exp(-r)``; the two parameterizations
define the same density, and this implementation uses the latter throughout.

The sum-of-log-PDFs formulation here (product of per-pulsar PDFs == sum of
logs) is exactly what the distributed backend turns into a ``psum`` over the
pulsar-sharded mesh axis (SURVEY §2.3).
"""

from __future__ import annotations

import numpy as np
import scipy.linalg as sl

from ..ops.acf import integrated_act
from .blocks import (BlockIndex, align_phi, gumbel_grid_draw,
                     proposal_step, rho_bounds, rho_grid,
                     rho_log_pdf_grid, tprocess_alpha_log_pdf_grid,
                     validate_sampling_flags)


class NumpyPTAGibbs:
    """Multi-pulsar oracle sampler: common GW free spectrum + per-pulsar
    noise blocks."""

    def __init__(self, pta, hypersample=None, redsample=None,
                 ecorrsample=None,
                 white_adapt_iters=1000, red_adapt_iters=2000, red_steps=20,
                 seed=None):
        self.pta = pta
        self.P = len(pta.pulsars)
        validate_sampling_flags(pta, hypersample, ecorrsample, redsample)
        self.kernel_ecorr = ecorrsample == "kernel"
        self.hypersample = hypersample
        self.redsample = redsample
        self.white_adapt_iters = white_adapt_iters
        self.red_adapt_iters = red_adapt_iters
        self.red_steps = red_steps
        self.rng = np.random.default_rng(seed)

        self.idx = BlockIndex.build(pta.param_names)
        self._y = pta.get_residuals()
        self._T = pta.get_basis()
        try:
            self.rhomin, self.rhomax = rho_bounds(pta, "gw")
        except ValueError:   # powerlaw-family common process: no rho block
            self.rhomin, self.rhomax = 1e-20, 1e-8

        self.gwid, self.red_sigs, self.gw_sigs, self.ecorr_sigs = [], [], [], []
        self.redid = []
        self.ecid = []
        #: per-pulsar positions (chain columns) of that pulsar's red
        #: free-spectrum parameters — located by NAME, not model order, since
        #: pta.param_names is name-sorted while pulsars keep insertion order
        self.red_rho_idx = []
        self.alpha_idx = []          # t-process per-frequency scale factors
        names = pta.param_names
        for pname in pta.pulsars:
            m = pta.model(pname)
            sl_gw = m.basis_slice("gw")
            self.gwid.append(np.arange(sl_gw.start, sl_gw.stop))
            # shared-column Fourier red only (red_select splits are
            # own-column signals handled by the generic hyper-MH block)
            red_sig = next((s for s in m._fourier if "red" in s.name), None)
            self.red_sigs.append(red_sig)
            if red_sig is not None:
                sl_red = m._slices[red_sig.name]
                self.redid.append(np.arange(sl_red.start, sl_red.stop))
            else:
                self.redid.append(None)
            self.gw_sigs.append(next(s for s in m.signals if "gw" in s.name))
            ec = next((s for s in m.signals if "ecorr" in s.name), None)
            self.ecorr_sigs.append(ec)
            if ec is not None:
                sl_ec = m.basis_slice("ecorr")
                self.ecid.append(np.arange(sl_ec.start, sl_ec.stop))
            else:
                self.ecid.append(None)
            self.red_rho_idx.append(np.array(
                [ii for ii, nm in enumerate(names)
                 if nm.startswith(f"{pname}_red_noise_log10_rho")], dtype=np.int64))
            self.alpha_idx.append(np.array(
                [ii for ii, nm in enumerate(names)
                 if nm.startswith(f"{pname}_red_noise_alphas")],
                dtype=np.int64))
        if len(self.idx.rho) and len(self.idx.rho) != len(self.gwid[0]) // 2:
            raise ValueError(
                "the common conditional rho draw requires exactly one "
                "'spectrum' common process matching the GW mode count")

        #: per-pulsar: do red and gw share basis columns?  (CRN layout:
        #: yes; correlated own-column common process: no) — static, so
        #: computed once here rather than per sweep
        self._red_shares_gw = [
            self.redid[ii] is not None
            and len(np.intersect1d(self.redid[ii], self.gwid[ii])) > 0
            for ii in range(self.P)]

        # ---- correlated common process (Hellings-Downs etc.) --------------
        # The reference's experimental PTA sampler only ever handles the
        # block-diagonal CRN case (pta_gibbs.py:533, SURVEY §3.6) though its
        # model factory can build HD models (model_definition.py:198-216);
        # here a correlated ORF activates the joint cross-pulsar b-draw and
        # the quadratic-form rho conditional.
        orf_names = {s.orf_name for s in self.gw_sigs}
        if len(orf_names) > 1:
            raise NotImplementedError(f"mixed common-process ORFs {orf_names}")
        self.orf_name = orf_names.pop() if orf_names else "crn"
        self.G = None
        self.orf_B = None
        if self.orf_name != "crn":
            from ..models.orf import orf_ginv_stack, orf_matrix

            for ii in range(self.P):
                if self.redid[ii] is None:
                    continue
                if len(np.intersect1d(self.redid[ii], self.gwid[ii])):
                    raise NotImplementedError(
                        "a correlated common process sharing basis columns "
                        "with intrinsic red noise is not implemented; "
                        "model_general gives correlated processes their "
                        "own columns")
            kset = {len(g) for g in self.gwid}
            if len(kset) > 1:
                raise NotImplementedError(
                    "correlated ORF requires a homogeneous common mode "
                    "count across pulsars")
            pos = [pta.model(ii).pulsar.pos for ii in range(self.P)]
            K = len(self.gwid[0]) // 2
            sig0 = next(s for s in self.gw_sigs if s is not None)
            self._K = K
            if self.orf_name in ("bin_orf", "legendre_orf"):
                # sampled correlation weights: G(theta) = I + sum theta B
                if not len(self.idx.rho):
                    raise NotImplementedError(
                        "parameterized ORFs are implemented for a varied "
                        "common free spectrum (common_psd='spectrum'); "
                        "the update_orf likelihood needs the rho block")
                from ..models.orf import orf_param_basis

                self.orf_B, _ = orf_param_basis(
                    self.orf_name, pos,
                    leg_lmax=getattr(sig0, "leg_lmax", 5))
                self.orf_idx = np.array(
                    [names.index(p.name)
                     for p in getattr(sig0, "orf_params", [])],
                    dtype=np.int64)
                self.G = np.eye(self.P)   # non-None: correlated paths on
                self.Ginv = None          # rebuilt per state
            else:
                # per-frequency (K, P, P) stack: constant for fixed ORFs,
                # varying for freq_hd (CRN below bin orf_ifreq, HD above)
                self.orf_B = None
                self.G = orf_matrix(
                    self.orf_name if not self.orf_name.startswith("freq_")
                    else "hd", pos)
                self.Ginv = orf_ginv_stack(
                    self.orf_name, pos, K,
                    orf_ifreq=getattr(sig0, "orf_ifreq", 0))

        # kernel-ECORR mode: drop the (trailing) ECORR columns per pulsar
        # and carry the epoch structure for in-N Woodbury corrections
        self._ke = None
        if self.kernel_ecorr:
            if not any(s is not None for s in self.ecorr_sigs):
                raise ValueError(
                    "ecorrsample='kernel' but no pulsar has an ECORR signal")
            from ..models.priors import Constant

            self._ke, T2 = [], []
            for ii, (T, ec) in enumerate(zip(self._T, self.ecorr_sigs)):
                if ec is None:
                    self._ke.append(None)
                    T2.append(T)
                    continue
                T2.append(T[:, :self.ecid[ii][0]])
                U = ec._U
                E = U.shape[1]
                eid = np.where(U.sum(axis=1) > 0, U.argmax(axis=1), E)
                prm = [(p.name, p.value if isinstance(p, Constant) else None)
                       for p in (ec._by_backend[lab] for lab in ec._owners)]
                self._ke.append((eid, E, prm))
            self._T = T2

        self.nb_total = sum(T.shape[1] for T in self._T)
        self.b = [np.zeros(T.shape[1]) for T in self._T]
        self._TNT = None
        self._d = None
        self._tnt_ke_cache = {}

        self.aclength_white = None
        self.cov_white = None
        self.cov_red = None
        self.red_hist = None
        self._red_pend = None
        self._red_count = 0
        self.aclength_ecorr = None

    # ---- helpers -----------------------------------------------------------

    def map_params(self, xs):
        return self.pta.map_params(xs)

    def get_lnprior(self, xs):
        return self.pta.get_lnprior(xs)

    def invalidate_cache(self):
        self._TNT = None
        self._d = None
        self._tnt_ke_cache = {}

    def _ensure_cache(self, Nvecs):
        if self._TNT is None:
            self._TNT = [T.T @ (T / N[:, None]) for T, N in zip(self._T, Nvecs)]
            self._d = [T.T @ (y / N) for T, y, N in zip(self._T, self._y, Nvecs)]

    def _gw_tau(self, ii):
        bb = self.b[ii][self.gwid[ii]] ** 2
        return 0.5 * (bb[::2] + bb[1::2])

    def _red_tau(self, ii):
        """Coefficient power on the red signal's own columns — distinct
        from the GW fold when the red process has more modes."""
        bb = self.b[ii][self.redid[ii]] ** 2
        return 0.5 * (bb[::2] + bb[1::2])

    # ---- likelihoods -------------------------------------------------------

    def _ke_corr_ii(self, params, Nvec, r, ii):
        """Woodbury correction to pulsar ``ii``'s diagonal log-density."""
        from .blocks import ke_corr

        eid, E, prm = self._ke[ii]
        return ke_corr(params, Nvec, r, eid, E, prm)

    def _tnt_d_ii(self, params, Nvecs, ii):
        """Pulsar ``ii``'s ``(T^T N^-1 T, T^T N^-1 y)`` with the kernel-
        ECORR correction applied at use time (it moves with the ECORR
        parameters, unlike the cached diagonal part).  Memoized on the
        ECORR parameter values: the red MH block evaluates the
        marginalized likelihood thousands of times per adaptation with
        the white/ECORR state frozen, and the correction is loop-
        invariant there.  ``invalidate_cache`` clears the memo alongside
        the diagonal Gram cache."""
        from .blocks import ke_tnt_corr, ke_woodbury

        self._ensure_cache(Nvecs)
        if self._ke is None or self._ke[ii] is None:
            return self._TNT[ii], self._d[ii]
        eid, E, prm = self._ke[ii]
        ckey = (ii,) + tuple(v if v is not None else params[nm]
                             for nm, v in prm)
        hit = self._tnt_ke_cache.get(ckey)
        if hit is not None:
            return hit
        _, _, w = ke_woodbury(params, Nvecs[ii], eid, E, prm)
        corr = ke_tnt_corr(self._T[ii], self._y[ii], Nvecs[ii], w, eid, E)
        out = (self._TNT[ii] - corr[:-1, :-1], self._d[ii] - corr[:-1, -1])
        self._tnt_ke_cache[ckey] = out
        return out

    def lnlike_white(self, xs):
        params = self.map_params(xs)
        Nvecs = self.pta.get_ndiag(params)
        out = 0.0
        for ii in range(self.P):
            r = self._y[ii] - self._T[ii] @ self.b[ii]
            out += -0.5 * (np.sum(np.log(Nvecs[ii]))
                           + np.sum(r * r / Nvecs[ii]))
            if self._ke is not None and self._ke[ii] is not None:
                out += self._ke_corr_ii(params, Nvecs[ii], r, ii)
        return out

    def lnlike_red(self, xs):
        """b-conditional likelihood of all per-pulsar GP hypers: per-column
        N(0, phi(x)) terms over the whole shared Fourier block (not
        truncated to the GW grid) plus chromatic own-column GPs — the same
        generic target as the device backend."""
        params = self.map_params(xs)
        out = 0.0
        for ii in range(self.P):
            m = self.pta.model(ii)
            if m._fourier:
                start = min(m._slices[s.name].start for s in m._fourier)
                stop = max(m._slices[s.name].stop for s in m._fourier)
                phi = np.zeros(stop - start)
                for s in m._fourier:
                    sl_ = m._slices[s.name]
                    phi[sl_.start - start:sl_.stop - start] += \
                        np.asarray(s.get_phi(params))
                bb = self.b[ii][start:stop]
                out += float(np.sum(-0.5 * np.log(phi)
                                    - 0.5 * bb * bb / phi))
            for s in m._chrom:
                sl_ = m._slices[s.name]
                phi = np.asarray(s.get_phi(params))
                bb = self.b[ii][sl_]
                out += float(np.sum(-0.5 * np.log(phi)
                                    - 0.5 * bb * bb / phi))
        return out

    def lnlike_ecorr(self, xs):
        """b-conditional likelihood of all per-pulsar ECORR variances."""
        params = self.map_params(xs)
        out = 0.0
        for ii in range(self.P):
            if self.ecorr_sigs[ii] is None:
                continue
            phi = np.asarray(self.ecorr_sigs[ii].get_phi(params))
            bj = self.b[ii][self.ecid[ii]]
            out += float(np.sum(-0.5 * np.log(phi) - 0.5 * bj * bj / phi))
        return out

    def lnlike_fullmarg(self, xs):
        """Marginalized likelihood summed over pulsars (reference
        ``pta_gibbs.py:577-621``)."""
        params = self.map_params(xs)
        Nvecs = self.pta.get_ndiag(params)
        phis = self.pta.get_phi(params)
        out = 0.0
        for ii in range(self.P):
            out += -0.5 * (np.sum(np.log(Nvecs[ii]))
                           + np.sum(self._y[ii] ** 2 / Nvecs[ii]))
            if self._ke is not None and self._ke[ii] is not None:
                out += self._ke_corr_ii(params, Nvecs[ii], self._y[ii], ii)
            phi_ii = phis[ii][:self._T[ii].shape[1]]
            phiinv_ii, logdet_phi = 1.0 / phi_ii, np.sum(np.log(phi_ii))
            TNT, d = self._tnt_d_ii(params, Nvecs, ii)
            Sigma = TNT + np.diag(phiinv_ii)
            try:
                cf = sl.cho_factor(Sigma)
            except np.linalg.LinAlgError:
                return -np.inf
            expval = sl.cho_solve(cf, d)
            logdet_sigma = 2.0 * np.sum(np.log(np.diag(cf[0])))
            out += 0.5 * (d @ expval - logdet_sigma - logdet_phi)
        return float(out)

    # ---- conditional draws -------------------------------------------------

    def draw_b(self, xs):
        if self.G is not None:
            return self._draw_b_joint(xs)
        params = self.map_params(xs)
        Nvecs = self.pta.get_ndiag(params)
        phis = self.pta.get_phi(params)
        for ii in range(self.P):
            TNT, d = self._tnt_d_ii(params, Nvecs, ii)
            Sigma = TNT + np.diag(1.0 / phis[ii][:self._T[ii].shape[1]])
            u, s, _ = sl.svd(Sigma)
            mn = u @ ((u.T @ d) / s)
            Li = u * np.sqrt(1.0 / s)
            self.b[ii] = mn + Li @ self.rng.standard_normal(len(mn))
        return self.b

    def _draw_b_joint(self, xs):
        """Correlated-ORF joint b-draw: one dense Gaussian over all
        pulsars' coefficients.  The inter-pulsar coupling lives only in the
        GW columns, whose joint prior per (frequency, phase) group is
        ``rho_k G`` over pulsars, so ``Phi^-1`` is diagonal everywhere
        except those groups, which carry ``G^-1 / rho_k``."""
        params = self.map_params(xs)
        Nvecs = self.pta.get_ndiag(params)
        phis = self.pta.get_phi(params)
        offs = np.cumsum([0] + [T.shape[1] for T in self._T])
        nb = offs[-1]
        Sigma = np.zeros((nb, nb))
        phiinv_diag = np.zeros(nb)
        ds = []
        for ii in range(self.P):
            sl_ = slice(offs[ii], offs[ii + 1])
            TNT, d_ii = self._tnt_d_ii(params, Nvecs, ii)
            Sigma[sl_, sl_] = TNT
            ds.append(d_ii)
            pin = 1.0 / phis[ii][:self._T[ii].shape[1]]
            pin[self.gwid[ii]] = 0.0         # replaced by the group blocks
            phiinv_diag[sl_] = pin
        Sigma[np.diag_indices(nb)] += phiinv_diag
        rho = np.asarray(self.gw_sigs[0].get_phi(params))[::2]
        K = len(rho)
        Ginv = self._ginv(xs)
        for k in range(K):
            for phase in (0, 1):
                rows = np.array([offs[ii] + self.gwid[ii][2 * k + phase]
                                 for ii in range(self.P)])
                Sigma[np.ix_(rows, rows)] += Ginv[k] / rho[k]
        d = np.concatenate(ds)
        cf = sl.cho_factor(Sigma, lower=True)
        mn = sl.cho_solve(cf, d)
        z = self.rng.standard_normal(nb)
        samp = mn + sl.solve_triangular(cf[0], z, lower=True, trans=1)
        for ii in range(self.P):
            self.b[ii] = samp[offs[ii]:offs[ii + 1]]
        return self.b

    def _rho_log_pdf_grid(self, tau, other, grid):
        return rho_log_pdf_grid(tau, other, grid)

    def update_rho(self, xs):
        """Common free-spectrum draw: per-pulsar log-PDF grids summed across
        pulsars (== reference's PDF product, ``pta_gibbs.py:205``), then
        inverse-CDF sampled.

        With a correlated ORF the conditional generalizes to
        ``p(rho_k | a) ~ rho^-P exp(-taut_k / rho)`` with the quadratic
        form ``taut_k = 0.5 sum_phase a_k^T G^-1 a_k`` (which reduces to
        ``sum_p tau_pk`` at ``G = I``)."""
        xnew = xs.copy()
        params = self.map_params(xnew)
        K = len(self.idx.rho)
        grid = rho_grid(self.rhomin, self.rhomax)
        if self.G is not None:
            a = np.stack([self.b[ii][self.gwid[ii]] for ii in range(self.P)])
            taut = np.zeros(K)
            Ginv = self._ginv(xnew)
            for phase in (0, 1):
                ap = a[:, phase::2][:, :K]              # (P, K)
                taut += 0.5 * np.einsum("pk,kpq,qk->k", ap, Ginv, ap)
            logpdf = (-self.P * np.log(grid)[None, :]
                      - taut[:, None] / grid[None, :])
        else:
            logpdf = np.zeros((K, len(grid)))
            for ii in range(self.P):
                tau = self._gw_tau(ii)[:K]
                if self.red_sigs[ii] is not None and self._red_shares_gw[ii]:
                    other = align_phi(np.asarray(
                        self.red_sigs[ii].get_phi(params))[::2], K)
                else:
                    other = np.full(K, 1e-30)
                logpdf += self._rho_log_pdf_grid(tau, other, grid)
        # Gumbel-max across the grid == inverse-CDF on the discrete pdf
        xnew[self.idx.rho] = 0.5 * np.log10(
            gumbel_grid_draw(self.rng, logpdf, grid))
        return xnew

    def update_red(self, xs, adapt=False):
        """Per-pulsar intrinsic red *free-spectrum* block (reference
        ``pta_gibbs.py:252-276``): grid draw per pulsar with the common GW as
        the 'other' phi component.  No-op when there is no red rho block."""
        if len(self.idx.red_rho):
            xnew = xs.copy()
            params = self.map_params(xnew)
            grid = rho_grid(self.rhomin_red, self.rhomax_red)
            for ii in range(self.P):
                if self.red_sigs[ii] is None or not len(self.red_rho_idx[ii]):
                    continue
                K = len(self.red_rho_idx[ii])
                tau = self._red_tau(ii)[:K]
                # the gw 'other' variance applies only on SHARED columns
                # (CRN layout); a correlated common process lives on its
                # own columns, which carry no common variance
                if self._red_shares_gw[ii]:
                    gw = align_phi(
                        np.asarray(self.gw_sigs[ii].get_phi(params))[::2], K)
                else:
                    gw = np.full(K, 1e-30)
                logpdf = rho_log_pdf_grid(tau, gw, grid)
                # assignment keyed by this pulsar's own chain columns
                xnew[self.red_rho_idx[ii]] = 0.5 * np.log10(
                    gumbel_grid_draw(self.rng, logpdf, grid))
            return xnew
        return xs.copy()

    def _orf_G(self, xs):
        """(P, P) correlation matrix at the current sampled weights."""
        return np.eye(self.P) + np.einsum("j,jpq->pq", xs[self.orf_idx],
                                          self.orf_B)

    def _ginv(self, xs):
        """(K, P, P) inverse ORF stack at the current state."""
        if self.orf_B is None:
            return self.Ginv
        Gi = np.linalg.inv(self._orf_G(xs))
        return np.broadcast_to(Gi, (self._K, self.P, self.P))

    def update_orf(self, xs):
        """MH block for the sampled ORF weights (bin_orf / legendre_orf):
        single-site scale-mixture proposals on the coefficient-conditional
        correlated likelihood ``-K ln det G - 0.5 sum a^T G^-1 a / rho``;
        non-PD proposals are rejected (Cholesky failure -> -inf)."""
        if self.orf_B is None or not len(self.idx.orf):
            return xs.copy()

        a = np.stack([self.b[ii][self.gwid[ii]] for ii in range(self.P)])
        K = self._K

        def lnlike(q):
            G = self._orf_G(q)
            try:
                cf = sl.cho_factor(G, lower=True)
            except np.linalg.LinAlgError:
                return -np.inf
            except ValueError:
                return -np.inf
            logdet = 2.0 * np.sum(np.log(np.diag(cf[0])))
            rho = 10.0 ** (2.0 * q[self.idx.rho])
            quad = 0.0
            for phase in (0, 1):
                ap = a[:, phase::2][:, :K]              # (P, K)
                w = sl.cho_solve(cf, ap)
                quad += np.sum(ap * w / rho[None, :])
            return -K * logdet - 0.5 * quad

        return self._mh_loop(xs, self.idx.orf, lnlike, self.red_steps,
                             0.05 * len(self.idx.orf))

    def update_tprocess_alpha(self, xs):
        """Per-pulsar grid draw of t-process scale factors from the
        conditional including the shared common-process variance
        (see ``numpy_backend.NumpyGibbs.update_tprocess_alpha``)."""
        from ..models import psd as psdmod
        from .jax_backend import (TP_ALPHA_GRID, TP_ALPHA_LOG10_MAX,
                                  TP_ALPHA_LOG10_MIN)

        xnew = xs.copy()
        params = self.map_params(xnew)
        grid = 10.0 ** np.linspace(TP_ALPHA_LOG10_MIN, TP_ALPHA_LOG10_MAX,
                                   TP_ALPHA_GRID)
        for ii in range(self.P):
            sig = self.red_sigs[ii]
            if sig is None or not len(self.alpha_idx[ii]):
                continue
            bb = self.b[ii][self.redid[ii]] ** 2
            tau = 0.5 * (bb[::2] + bb[1::2])
            A = params[sig.params[0].name]
            gam = params[sig.params[1].name]
            plaw = psdmod.powerlaw(sig.freqs[::2], sig._df[::2], A, gam)
            if self._red_shares_gw[ii]:
                other = align_phi(
                    np.asarray(self.gw_sigs[ii].get_phi(params))[::2],
                    len(tau))
            else:
                other = np.full(len(tau), 1e-30)
            logpdf = tprocess_alpha_log_pdf_grid(tau, plaw, other, grid)
            xnew[self.alpha_idx[ii]] = gumbel_grid_draw(self.rng, logpdf,
                                                        grid)
        return xnew

    def update_red_mh(self, xs, adapt=False):
        """Powerlaw-family hyper block (per-pulsar red and/or a varied
        common process): adaptive MH as in the single-pulsar sampler."""
        rind = self.idx.red
        if not len(rind):
            return xs.copy()
        from .blocks import de_hist_push, de_step, seed_red_hist

        if adapt:
            rec = np.zeros((self.red_adapt_iters, len(rind)))
            xnew = self._mh_loop(xs, rind, self.lnlike_fullmarg,
                                 self.red_adapt_iters, 0.05 * len(rind), rec)
            burn = rec[min(100, len(rec) // 2):]
            self.cov_red = np.atleast_2d(np.cov(burn, rowvar=False))
            self.cov_red += 1e-12 * np.eye(len(rind))
            self._red_eigs = np.linalg.svd(self.cov_red)
            self.red_hist = seed_red_hist(burn)
            self._red_pend = self.red_hist.copy()
            self._red_count = 0
            return xnew
        x = xs.copy()
        ll0, lp0 = self.lnlike_red(x), self.get_lnprior(x)
        U, S, _ = self._red_eigs
        am_sqrt = U * np.sqrt(S)[None, :]
        for _ in range(self.red_steps):
            r = self.rng.uniform()
            if r < 0.5:
                q = de_step(self.rng, x, rind, self.red_hist)
            elif r < 0.65:
                q = x.copy()
                j = self.rng.integers(len(rind))
                q[rind] += 2.38 * np.sqrt(S[j]) * self.rng.standard_normal() * U[:, j]
            elif r < 0.8:
                # AM: full adapted-covariance jump (reference weight 15/95)
                q = x.copy()
                z = self.rng.standard_normal(len(rind))
                q[rind] += (2.38 / np.sqrt(len(rind))) * (am_sqrt @ z)
            else:
                q = proposal_step(self.rng, x, rind, 0.05 * len(rind))
            lp1 = self.get_lnprior(q)
            ll1 = self.lnlike_red(q) if np.isfinite(lp1) else -np.inf
            if (ll1 + lp1) - (ll0 + lp0) > np.log(self.rng.uniform()):
                x, ll0, lp0 = q, ll1, lp1
        self.red_hist, self._red_pend, self._red_count = de_hist_push(
            self.red_hist, self._red_pend, self._red_count, x[rind])
        return x

    @property
    def rhomin_red(self):
        return rho_bounds(self.pta, "red")[0]

    @property
    def rhomax_red(self):
        return rho_bounds(self.pta, "red")[1]

    def _mh_loop(self, xs, idx, lnlike, nsteps, sigma, record=None):
        x = xs.copy()
        ll0, lp0 = lnlike(x), self.get_lnprior(x)
        for ii in range(nsteps):
            q = proposal_step(self.rng, x, idx, sigma)
            lp1 = self.get_lnprior(q)
            ll1 = lnlike(q) if np.isfinite(lp1) else -np.inf
            if (ll1 + lp1) - (ll0 + lp0) > np.log(self.rng.uniform()):
                x, ll0, lp0 = q, ll1, lp1
            if record is not None:
                record[ii] = x[idx]
        return x

    def update_white(self, xs, adapt=False):
        wind = self.idx.white
        sigma = 0.05 * len(wind)
        if adapt:
            rec = np.zeros((self.white_adapt_iters, len(wind)))
            xnew = self._mh_loop(xs, wind, self.lnlike_white,
                                 self.white_adapt_iters, sigma, rec)
            burn = rec[min(100, len(rec) // 2):]
            self.cov_white = np.atleast_2d(np.cov(burn, rowvar=False))
            self.aclength_white = int(max(
                1, max(int(integrated_act(burn[:, j])) for j in range(len(wind)))))
            return xnew
        return self._mh_loop(xs, wind, self.lnlike_white,
                             self.aclength_white, sigma)

    def update_ecorr(self, xs, adapt=False):
        eind = self.idx.ecorr
        sigma = 0.05 * len(eind)
        target = self.lnlike_white if self.kernel_ecorr else self.lnlike_ecorr
        if adapt:
            rec = np.zeros((self.white_adapt_iters, len(eind)))
            xnew = self._mh_loop(xs, eind, target,
                                 self.white_adapt_iters, sigma, rec)
            burn = rec[min(100, len(rec) // 2):]
            self.aclength_ecorr = int(max(
                1, max(int(integrated_act(burn[:, j])) for j in range(len(eind)))))
            return xnew
        return self._mh_loop(xs, eind, target,
                             self.aclength_ecorr, sigma)

    # ---- sweep -------------------------------------------------------------

    def sweep(self, xs, first=False):
        """Reference sweep order (``pta_gibbs.py:664-704``)."""
        x = np.asarray(xs, dtype=np.float64).copy()
        if first and self.orf_B is not None:
            wmin = float(np.linalg.eigvalsh(self._orf_G(x)).min())
            if wmin <= 1e-10:
                raise ValueError(
                    "initial ORF weights give a non-positive-definite "
                    f"correlation matrix (min eigenvalue {wmin:.2e}); "
                    "start the *_orfw_* parameters at 0 (G = identity)")
        if first:
            self.draw_b(x)
        self.invalidate_cache()
        if len(self.idx.white):
            x = self.update_white(x, adapt=first)
        if len(self.idx.ecorr) and any(s is not None for s in self.ecorr_sigs):
            x = self.update_ecorr(x, adapt=first)
        if len(self.idx.red_rho):
            x = self.update_red(x, adapt=first)
        if any(len(a) for a in self.alpha_idx):
            x = self.update_tprocess_alpha(x)
        if len(self.idx.red):
            x = self.update_red_mh(x, adapt=first)
        if len(self.idx.rho):
            x = self.update_rho(x)
        if self.orf_B is not None and len(self.idx.orf):
            x = self.update_orf(x)
        self.draw_b(x)
        return x

    # ---- resume state ------------------------------------------------------

    def adapt_state(self):
        from .blocks import rng_state_pack

        out = {"rng_state": rng_state_pack(self.rng)}
        for ii, b in enumerate(self.b):
            out[f"b{ii}"] = b
        for key in ("aclength_white", "cov_white", "cov_red", "red_hist",
                    "aclength_ecorr", "_red_pend", "_red_count"):
            val = getattr(self, key, None)
            if val is not None:
                out[key] = np.asarray(val)
        return out

    def load_adapt_state(self, state):
        from .blocks import rng_state_unpack

        rng_state_unpack(self.rng, state["rng_state"])
        self.b = [np.asarray(state[f"b{ii}"]) for ii in range(self.P)]
        for key in ("aclength_white", "cov_white", "cov_red", "red_hist",
                    "aclength_ecorr", "_red_pend", "_red_count"):
            if key in state:
                val = state[key]
                setattr(self, key, int(val) if val.ndim == 0 else np.asarray(val))
        if self.cov_red is not None:
            self._red_eigs = np.linalg.svd(self.cov_red)
            if self.red_hist is None:
                raise RuntimeError(
                    "resume checkpoint lacks the red-block DE history "
                    "(red_hist) — it was written by an incompatible "
                    "version; delete the chain directory to start fresh")
            if getattr(self, "_red_pend", None) is None:
                self._red_pend = np.asarray(self.red_hist).copy()
                self._red_count = 0
