"""Shared Gibbs-block bookkeeping: parameter index groups, prior bounds.

Index groups are located by name fragment, matching the reference's
conventions (``pulsar_gibbs.py:167-196``): rho <- 'rho', red <- 'log10_A' or
'gamma', white <- 'efac' or 'equad', ecorr <- 'ecorr'.  Bounds come off the
parameter objects directly instead of the reference's repr-string parsing
(``pulsar_gibbs.py:82-87``).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class BlockIndex:
    """Positions of each Gibbs block inside the flat chain vector."""

    names: list
    rho: np.ndarray          # common free-spectrum log10_rho entries
    red: np.ndarray          # per-pulsar power-law hypers (log10_A, gamma)
    red_rho: np.ndarray      # per-pulsar free-spectrum entries ('red' + 'rho')
    white: np.ndarray        # efac / equad entries
    ecorr: np.ndarray        # ecorr entries
    orf: np.ndarray          # sampled ORF weights ("_orfw_" fragment)

    @classmethod
    def build(cls, param_names: list) -> "BlockIndex":
        rho, red, red_rho, white, ecorr, orf = [], [], [], [], [], []
        for ii, nm in enumerate(param_names):
            if "rho" in nm and "gw" in nm:
                rho.append(ii)
            # all powerlaw-family hypers, including a varied powerlaw
            # *common* process — the reference sweeps those into the same
            # MH block (get_red_param_indices, pulsar_gibbs.py:175-180)
            if "log10_A" in nm or "gamma" in nm:
                red.append(ii)
            if "rho" in nm and "red" in nm:
                red_rho.append(ii)
            if "efac" in nm or "equad" in nm:
                white.append(ii)
            if "ecorr" in nm:
                ecorr.append(ii)
            if "_orfw_" in nm:
                orf.append(ii)
        arr = lambda v: np.asarray(v, dtype=np.int64)
        return cls(list(param_names), arr(rho), arr(red), arr(red_rho),
                   arr(white), arr(ecorr), arr(orf))


def validate_sampling_flags(pta, hypersample=None, ecorrsample=None,
                            redsample=None):
    """Reference-API block-kernel selectors (``pulsar_gibbs.py:42-43``),
    honored honestly: ``None`` means "auto" (the kernel follows the model
    structure — exact conditionals for free-spectrum blocks, adaptive MH
    for powerlaw-family hypers and white/ECORR).  An explicit value is
    checked against what the structure provides and raises
    ``NotImplementedError`` when it asks for a kernel this framework does
    not implement — never silently ignored (round-1 review finding).
    """
    names = list(pta.param_names)
    has_red_rho = any("rho" in n and "red" in n for n in names)
    # intrinsic red only: common-process powerlaw hypers (gw_*) must not
    # make redsample='conditional' raise on models with no red process
    has_red_pl = any(("log10_A" in n or "gamma" in n) and "red" in n
                     for n in names)
    if hypersample not in (None, "conditional"):
        raise NotImplementedError(
            f"hypersample={hypersample!r}: the common free-spectrum block "
            "is sampled by its exact conditional (inverse-CDF / Gumbel-max "
            "grid); an MH alternative is not implemented")
    if ecorrsample == "kernel":
        # working kernel semantics (the reference's own kernel path is
        # dead code, pulsar_gibbs.py:409-486): epoch blocks live inside N
        # via Woodbury, marginally identical to basis ECORR
        if not any("ecorr" in n for n in names):
            raise ValueError(
                "ecorrsample='kernel' but the model has no ECORR "
                "parameters (need white_vary=True on NANOGrav-flagged "
                "data with a backend selection)")
    elif ecorrsample not in (None, "mh"):
        raise NotImplementedError(
            f"ecorrsample={ecorrsample!r}: ECORR amplitudes are sampled by "
            "adapted-proposal MH on the basis representation, or by the "
            "in-N Woodbury kernel with ecorrsample='kernel'; other "
            "kernels are not implemented")
    if redsample == "conditional" and has_red_pl and not has_red_rho:
        raise NotImplementedError(
            "redsample='conditional' but the intrinsic red process has "
            "powerlaw-family hypers, which only the adaptive-MH block "
            "samples; build the model with red_psd='spectrum' for "
            "conditional red draws")
    if redsample == "mh" and has_red_rho:
        raise NotImplementedError(
            "redsample='mh' but the intrinsic red process is a free "
            "spectrum, which is sampled by its exact per-pulsar "
            "conditional; an MH alternative is not implemented")
    if redsample not in (None, "mh", "conditional"):
        raise NotImplementedError(f"redsample={redsample!r} is not known")


def rho_bounds(pta, frag: str = "gw") -> tuple:
    """(rho_min, rho_max) variance bounds: 10^(2 * log10_rho prior bounds)
    for the free-spectrum parameter whose name contains ``frag`` — the
    quantity the reference extracts at ``pulsar_gibbs.py:86-87``."""
    for p in pta.params:
        if "rho" in p.name and frag in p.name:
            return 10.0 ** (2.0 * p.pmin), 10.0 ** (2.0 * p.pmax)
    raise ValueError(f"no free-spectrum parameter matching '{frag}'")


_U64 = (1 << 64) - 1


def rng_state_pack(rng: np.random.Generator) -> np.ndarray:
    """Serialize a PCG64 Generator state into uint64s (the 128-bit state and
    increment split into halves) for the adapt.npz resume checkpoint."""
    st = rng.bit_generator.state
    s, inc = st["state"]["state"], st["state"]["inc"]
    return np.array([s & _U64, s >> 64, inc & _U64, inc >> 64,
                     int(st["has_uint32"]), st["uinteger"]], dtype=np.uint64)


def rng_state_unpack(rng: np.random.Generator, packed: np.ndarray):
    st = rng.bit_generator.state
    p = [int(v) for v in packed]
    st["state"]["state"] = p[0] | (p[1] << 64)
    st["state"]["inc"] = p[2] | (p[3] << 64)
    st["has_uint32"] = p[4]
    st["uinteger"] = p[5]
    rng.bit_generator.state = st


def rho_grid(lo, hi, npts=None):
    """Log-uniform variance grid for the numerical rho conditionals
    (reference uses 1000 points, ``pulsar_gibbs.py:228``)."""
    from ..config import settings

    return 10.0 ** np.linspace(np.log10(lo), np.log10(hi),
                               npts or settings.rho_grid_size)


def rho_log_pdf_grid(tau, other, grid):
    """log conditional density of one pulsar's free-spectrum contribution on
    the rho grid: ``r - e^r`` with ``r = log tau - log(other + rho)``
    (reference ``pulsar_gibbs.py:229-230``)."""
    # tau = 0 (a zeroed coefficient pair) is a legal input whose density
    # limit is exp(-inf) = 0: take log(0) = -inf silently rather than
    # warning through every oracle grid draw
    with np.errstate(divide="ignore"):
        logratio = (np.log(tau)[:, None]
                    - np.logaddexp(np.log(other)[:, None],
                                   np.log(grid)[None, :]))
    return logratio - np.exp(logratio)


def tprocess_alpha_log_pdf_grid(tau, plaw, other, grid):
    """log point-mass of the t-process scale factors on a log-spaced alpha
    grid: InvGamma(1,1) prior times the 2-coefficient Gaussian likelihood
    with variance ``other + alpha * plaw``, including the log-grid
    Jacobian (point mass = density * alpha: -2 ln a + ln a = -ln a).
    Shared by both NumPy oracles and mirrored by
    ``jax_backend.tprocess_alpha_update``."""
    var = other[:, None] + plaw[:, None] * grid[None, :]
    return (-np.log(grid)[None, :] - 1.0 / grid[None, :]
            - np.log(var) - tau[:, None] / var)


def gumbel_grid_draw(rng, logpdf, grid):
    """Sample one grid point per row via the Gumbel-max trick (== inverse
    CDF on the discrete pdf, reference ``pulsar_gibbs.py:233-234``)."""
    gum = rng.gumbel(size=logpdf.shape)
    return grid[np.argmax(logpdf + gum, axis=-1)]


def align_phi(raw, k):
    """Truncate/floor-pad a per-frequency phi array to ``k`` entries."""
    out = np.full(k, 1e-30)
    n = min(k, len(raw))
    out[:n] = raw[:n]
    return out


def proposal_step(rng, x, idx, sigma):
    """The reference's single-site scale-mixture proposal
    (``pulsar_gibbs.py:344-351``): pick one coordinate of ``idx``, jump by
    N(0,1) * sigma * scale with scale drawn from {0.1,0.5,1,3,10} at probs
    {.1,.15,.5,.15,.1}."""
    q = x.copy()
    scale = rng.choice([0.1, 0.5, 1.0, 3.0, 10.0],
                       p=[0.1, 0.15, 0.5, 0.15, 0.1])
    par = rng.choice(idx)
    q[par] += rng.standard_normal() * sigma * scale
    return q


def ke_woodbury(params, Nvec, eid, E, prm):
    """Per-epoch Woodbury pieces of a kernel-ECORR block
    ``N = D + U c U^T`` (disjoint epoch indicators U): ``c_e =
    10^(2 log10_ecorr)``, ``s_e = sum_(i in e) 1/D_i``, ``w_e =
    c_e / (1 + c_e s_e)`` — shared by both f64 oracles so the formula
    cannot drift between them.  ``prm`` is [(param_name, const_or_None)]
    per epoch owner; ``eid`` maps TOAs to epochs with ``E`` = dummy."""
    c = np.array([10.0 ** (2.0 * (v if v is not None else params[nm]))
                  for nm, v in prm])
    s = np.bincount(eid, weights=1.0 / Nvec, minlength=E + 1)[:E]
    return c, s, c / (1.0 + c * s)


def ke_corr(params, Nvec, r, eid, E, prm):
    """Woodbury correction to the diagonal Gaussian log-density of ``r``:
    ``-0.5 [sum log1p(c s) - sum w z^2]`` with ``z_e = sum r/D``."""
    c, s, w = ke_woodbury(params, Nvec, eid, E, prm)
    z = np.bincount(eid, weights=r / Nvec, minlength=E + 1)[:E]
    return -0.5 * (np.sum(np.log1p(c * s)) - np.sum(w * z * z))


def ke_tnt_corr(T, y, Nvec, w, eid, E):
    """Woodbury correction to the augmented Gram ``([T|y]^T N^-1 [T|y])``
    of a kernel-ECORR block: ``V^T diag(w) V`` with ``V_e = sum_(i in e)
    [T|y]_i / D_i``.  Shared by both f64 oracles; the last row/column
    carries the ``d = T^T N^-1 y`` correction."""
    A = np.column_stack([T, y]) / Nvec[:, None]
    V = np.zeros((E + 1, A.shape[1]))
    np.add.at(V, eid, A)
    V = V[:E]
    return (V * w[:, None]).T @ V


def de_step(rng, x, idx, hist):
    """Differential-evolution proposal from a past-sample history buffer —
    the reference PTMCMC's top-weighted jump (DE=50 vs SCAM=30/AM=15,
    ``pulsar_gibbs.py:294``): ``q = x + gamma (h_a - h_b)`` over two
    distinct history rows, with ``gamma = 2.38/sqrt(2 d)`` and 10% of
    jumps at ``gamma = 1`` for mode hopping.  Symmetric given the frozen
    history, so the plain Metropolis accept is exact (ter Braak & Vrugt
    2008, sampling from the past)."""
    H = len(hist)
    a = rng.integers(H)
    b = (a + 1 + rng.integers(H - 1)) % H
    gamma = 1.0 if rng.uniform() < 0.1 else 2.38 / np.sqrt(2.0 * len(idx))
    q = x.copy()
    q[idx] += gamma * (np.asarray(hist[a]) - np.asarray(hist[b]))
    return q


def de_hist_push(hist, pend, count, row, period=128):
    """Frozen-window DE history update (the NumPy-oracle analogue of the
    JAX path's ``DE_Q``/``DE_DELAY`` rule): new states accumulate in the
    rolling ``pend`` buffer while :func:`de_step` proposals keep reading
    the *frozen* ``hist`` snapshot, which refreshes from ``pend`` only
    every ``period`` pushes.  Between refreshes the proposal distribution
    is fixed, so the DE jump is exactly symmetric conditional on the
    snapshot (ter Braak & Vrugt 2008 sampling-from-the-past) rather than
    continuously adapting.  Returns ``(hist, pend, count)``."""
    pend = np.roll(pend, -1, axis=0)
    pend[-1] = row
    count = int(count) + 1
    if count % period == 0:
        hist = pend.copy()
    return hist, pend, count


def seed_red_hist(rec, hist_len=64):
    """Thin a post-burn adaptation record (steps, d) into a (hist_len, d)
    DE history seed."""
    rec = np.asarray(rec, dtype=np.float64)
    take = np.linspace(0, len(rec) - 1, hist_len).astype(int)
    return rec[take]
