"""User-facing Gibbs sampler facade with selectable execution backend.

``PulsarBlockGibbs(pta, backend='jax'|'numpy')`` is the BASELINE.json
north-star API: same constructor role and ``.sample(x0, outdir, niter,
resume)`` surface as the reference class (``pulsar_gibbs.py:42,620``), with
the execution path chosen by flag.  ``backend='numpy'`` runs the float64
oracle on host; ``backend='jax'`` runs the jit-compiled device path.
``PTABlockGibbs`` is the multi-pulsar variant (reference ``pta_gibbs.py``)
sharing the same machinery with a common free-spectrum block.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from ..runtime import faults, integrity, preemption, sentinels
from .chains import ChainStore
from .numpy_backend import NumpyGibbs


class _GibbsBase:
    def __init__(self, pta, hypersample=None, ecorrsample=None,
                 redsample=None, psr=None, backend="jax", seed=None,
                 progress=True, **backend_opts):
        from .blocks import validate_sampling_flags

        validate_sampling_flags(pta, hypersample, ecorrsample, redsample)
        self.pta = pta
        self.backend_name = backend
        self.ecorrsample = ecorrsample
        self.progress = progress
        # constructor record for with_backend (supervised degradation)
        self._ctor = {"hypersample": hypersample, "ecorrsample": ecorrsample,
                      "redsample": redsample, "psr": psr, "seed": seed,
                      "opts": dict(backend_opts)}
        if backend == "numpy":
            self._backend = self._make_numpy(hypersample, ecorrsample,
                                             redsample, seed, backend_opts)
        elif backend == "jax":
            self._backend = self._make_jax(hypersample, ecorrsample,
                                           redsample, seed, backend_opts)
        else:
            raise ValueError(f"unknown backend '{backend}'")

    # -- reference-compatible accessors -------------------------------------

    @property
    def params(self):
        return self.pta.params

    @property
    def param_names(self):
        return self.pta.param_names

    def map_params(self, xs):
        return self.pta.map_params(xs)

    def initial_sample(self, rng=None):
        return self.pta.initial_sample(rng)

    @property
    def b_param_names(self):
        out = []
        kernel = self.ecorrsample == "kernel"
        for pname in self.pta.pulsars:
            m = self.pta.model(pname)
            named = {}
            for s in m.signals:
                if kernel and s in m._ecorr:
                    # kernel mode drops the (trailing) ECORR basis columns
                    # from bchain — their names must not outnumber them
                    continue
                sl = m._slices[s.name]
                for jj in range(sl.start, sl.stop):
                    # shared Fourier columns: first (widest) signal wins,
                    # matching the reference's one-name-per-column files
                    named.setdefault(jj, f"{pname}_{s.name}_{jj - sl.start}")
            out += [named[jj] for jj in sorted(named)]
        return out

    def with_backend(self, backend):
        """A twin facade on the same PTA with a different execution
        backend — the supervisor's jax->numpy graceful-degradation hook.
        Jax-only options (record/chunk/mesh controls) are dropped when
        degrading to the numpy oracle, which has no equivalents."""
        c = self._ctor
        opts = dict(c["opts"])
        if backend == "numpy":
            for k in ("record_precision", "record_every", "nchains",
                      "chunk_size", "pad_pulsars", "mesh", "warmup_sweeps",
                      "warmup_white_steps", "white_steps_max",
                      "exact_every", "transfer_guard", "joint_mixed",
                      "watchdog", "ensemble", "pt_ladder", "megachunk"):
                opts.pop(k, None)
        return type(self)(self.pta, hypersample=c["hypersample"],
                          ecorrsample=c["ecorrsample"],
                          redsample=c["redsample"], psr=c["psr"],
                          backend=backend, seed=c["seed"],
                          progress=self.progress, **opts)

    def _checkpoint_extra(self):
        """Manifest sections that make the checkpoint layout-free
        (docs/RESILIENCE.md): ``layout`` pins the LOGICAL identity of the
        sampled process — facade class, chain count, pulsar names in
        logical order, the padded pulsar width (part of the PRNG draw
        shapes, hence of the stream), record thinning, and the key-fold
        policy — while ``shard_map`` records the physical placement the
        run happened to use, advisory only: ``integrity.reshard_restore``
        rebuilds the mesh for any device count dividing the padded
        width, bit-identically per logical chain."""
        be = self._backend
        layout = {"facade": type(self).__name__,
                  "backend": self.backend_name,
                  "nchains": int(getattr(be, "C", 1)),
                  "record_every": int(getattr(be, "record_every", 1)),
                  "pulsars": [str(p) for p in self.pta.pulsars],
                  "rng": "fold_in(fold_in(base_key, iteration), chain)"}
        cm = getattr(be, "cm", None)
        if cm is not None:
            layout["pad_pulsars"] = int(cm.P)
        shard = None
        mesh = getattr(be, "_mesh", None)
        if mesh is not None:
            from ..parallel.sharding import mesh_layout

            shard = mesh_layout(mesh)
        return {"layout": layout, "shard_map": shard}

    # -- main loop -----------------------------------------------------------

    def sample(self, xs, outdir="./chains", niter=10000, resume=False,
               save_every=100, hdf5=False):
        """Run ``niter`` Gibbs sweeps, persisting chains to ``outdir``
        (reference ``sample`` at ``pulsar_gibbs.py:620-710``, with resume
        reading what was saved and adaptation state checkpointed).

        With ``nchains=C > 1`` (jax backend) the chain files gain a chains
        axis — ``chain.npy`` is (niter, C, npar) — and ``xs`` may be either
        one start point (tiled) or per-chain (C, npar) starts.

        ``hdf5=True`` additionally writes ``chain.h5`` at the end (the
        la-forge-friendly container the reference leaves as a TODO at
        ``pulsar_gibbs.py:707-708``)."""
        xs = np.atleast_1d(np.asarray(xs, dtype=np.float64))
        npar = len(self.param_names)
        C = getattr(self._backend, "C", 1)
        ok_shapes = [(npar,)] + ([(C, npar)] if C > 1 else [])
        if xs.shape not in ok_shapes:
            raise ValueError(
                f"x0 has shape {xs.shape}; this model has {npar} parameters "
                f"(see .param_names)" + (f" and {C} chains" if C > 1 else ""))
        store = ChainStore(outdir, self.param_names, self.b_param_names)

        if hasattr(self._backend, "chain_shapes"):
            cshape, bshape = self._backend.chain_shapes(niter)
        else:
            cshape = (niter, npar)
            bshape = (niter, self._backend.nb_total)
        # with record_every=k > 1 (jax backend) the chain files hold the
        # thinned record — fewer rows than niter sweeps
        total_rows = cshape[0]
        rec_k = int(getattr(self._backend, "record_every", 1))
        chain = np.zeros(cshape)
        bchain = np.zeros(bshape)
        start = 0
        x = xs
        if resume:
            got = store.load_resume()
            if got is not None:
                prev_c, prev_b, upto, adapt = got
                upto = min(upto, total_rows)
                if prev_c.shape[1:] != chain.shape[1:]:
                    raise RuntimeError(
                        f"{outdir}: cannot resume — saved chain rows have "
                        f"shape {prev_c.shape[1:]} but this sampler "
                        f"(nchains={C}) produces {chain.shape[1:]}; resume "
                        "with the original nchains or start fresh")
                chain[:upto] = prev_c[:upto]
                bchain[:upto] = prev_b[:upto]
                start = upto
                if upto > 0:
                    x = chain[upto - 1].copy()
                if adapt is not None:
                    self._backend.load_adapt_state(adapt)
                    # the post-sweep state (never a chain row yet): resuming
                    # from it reproduces the uninterrupted process exactly
                    x = getattr(self._backend, "x_resume", x)
                elif upto > 0:
                    raise RuntimeError(
                        f"{outdir}: chain files exist but adapt.npz is "
                        "missing; cannot resume the adapted sampler state "
                        "(delete the directory to start fresh)")

        t0 = time.time()
        iterator = self._backend.run(x, chain, bchain, start, niter)
        last_saved = start
        upto_done = start
        # when rows past the last checkpoint are known-bad (sentinel
        # trip) or a save itself failed midway, the finally-flush must
        # NOT persist them — a poisoned/inconsistent checkpoint is worse
        # than the bounded loss it would avoid
        no_flush = False
        # \r progress is for humans at a terminal; under nohup/CI the
        # same stream must be periodic plain lines, not one giant
        # carriage-returned line
        is_tty = bool(getattr(sys.stdout, "isatty", lambda: False)())
        # save_every is in SWEEPS (the reference's unit); yields count
        # recorded rows, so the row-space interval shrinks by k — the
        # crash-loss window must not silently stretch with thinning
        save_rows = max(1, save_every // rec_k)
        ck_extra = self._checkpoint_extra()
        # a drain request (SIGTERM / maintenance hook) breaks the loop;
        # the finally-flush then persists every verified row and the
        # post-loop block verifies + raises Preempted (resumable)
        drained = False
        try:
            for upto in iterator:
                faults.mutate_rows(chain, bchain, upto_done, upto,
                                   backend=self.backend_name)
                try:
                    sentinels.check_rows(chain, bchain, upto_done, upto)
                except sentinels.ChainDivergence as exc:
                    # the backend already advanced past the poisoned
                    # rows: nothing after the last checkpoint may flush
                    no_flush = True
                    store.log_metrics({"event": "divergence",
                                       "row": exc.row, "what": exc.what,
                                       "backend": self.backend_name})
                    raise
                upto_done = upto
                faults.fire("sample.loop", row=upto,
                            backend=self.backend_name)
                # a drain request on the FINAL row falls through: the run
                # is complete, the normal save below commits it
                if preemption.drain_requested() and upto < total_rows:
                    drained = True
                    store.log_metrics({"event": "drain_requested",
                                       "row": int(upto),
                                       **preemption.drain_info()})
                    break
                if upto - last_saved >= save_rows or upto >= total_rows:
                    no_flush = True   # a crash inside save: don't re-save
                    store.save(chain, bchain, upto,
                               adapt_state=self._backend.adapt_state(),
                               extra=ck_extra)
                    no_flush = False
                    el = time.time() - t0
                    done = upto - start
                    # yields count recorded ROWS; each row is record_every
                    # sweeps, so the sweep rate scales back up by k
                    rate = done * rec_k / el if el > 0 else float("nan")
                    # "iter" stays in sweep units (comparable to niter);
                    # the jax backend tracks the exact counter under
                    # thinning
                    it_s = int(getattr(self._backend, "_it_cur", upto))
                    store.log_metrics({
                        "iter": it_s, "niter": int(niter),
                        "rows": int(upto) if rec_k > 1 else None,
                        "elapsed_s": round(el, 3),
                        "sweeps_per_s": round(rate, 3),
                        "record_every": rec_k if rec_k > 1 else None,
                        "backend": self.backend_name,
                        "nchains": int(getattr(self._backend, "C", 1)),
                        "sentinel": getattr(
                            self._backend, "health_last", None),
                        "aclength_white": getattr(
                            self._backend, "aclength_white", None),
                        "aclength_ecorr": getattr(
                            self._backend, "aclength_ecorr", None),
                    })
                    last_saved = upto
                    if self.progress:
                        msg = (f"[{self.backend_name}] {upto}/"
                               f"{total_rows} rows ({rate:.1f} sweeps/s)")
                        if is_tty:
                            print("\r" + msg, end="", flush=True)
                        else:
                            print(msg, flush=True)
        finally:
            if upto_done > last_saved and not no_flush:
                # bounded-loss flush: KeyboardInterrupt or a backend
                # failure between checkpoints still persists every
                # verified row (< save_every sweeps lost), resumable
                try:
                    store.save(chain, bchain, upto_done,
                               adapt_state=self._backend.adapt_state(),
                               extra=ck_extra)
                    store.log_metrics({"event": "final_flush",
                                       "rows": int(upto_done),
                                       "backend": self.backend_name})
                except Exception:
                    # never mask the original exception with a failed
                    # best-effort flush
                    pass
        # the backend's own chunk loop also stops dispatching on a drain
        # request — the iterator then just ends, so an incomplete run
        # with the flag up IS a drain, not a completion
        drained = drained or (preemption.drain_requested()
                              and upto_done < total_rows)
        if drained:
            self.chain = chain
            self.bchain = bchain
            # the flush above is best-effort (it swallows exceptions so
            # a failed save cannot mask a real error); a drain must
            # hand the supervisor a VERIFIED checkpoint or say so —
            # rolling back to the .bak generation if a concurrent kill
            # tore the final save
            rep = integrity.verify(outdir)
            rolled = False
            if not rep["ok"]:
                rolled = integrity.rollback(outdir)
                rep = integrity.verify(outdir)
            lat = preemption.mark_drained()
            store.log_metrics({"event": "preempted_drain",
                               "rows": int(rep["rows"]),
                               "verified": bool(rep["ok"]),
                               "rolled_back": rolled,
                               "latency_s": round(lat, 3),
                               **preemption.drain_info()})
            raise preemption.Preempted(
                f"{outdir}: drained to a "
                f"{'verified' if rep['ok'] else 'UNVERIFIED'} checkpoint "
                f"({rep['rows']} rows) after "
                f"{preemption.drain_info().get('reason', 'preemption')}",
                rows=rep["rows"], verified=rep["ok"], rolled_back=rolled)
        if self.progress and is_tty:
            print()
        if hdf5:
            store.export_hdf5(chain, bchain, total_rows,
                              extra_attrs={"backend": self.backend_name})
        self.chain = chain
        self.bchain = bchain
        return chain


class PulsarBlockGibbs(_GibbsBase):
    """Single-pulsar blocked Gibbs (reference ``pulsar_gibbs.py``)."""

    def _make_numpy(self, hypersample, ecorrsample, redsample, seed, opts):
        return _NumpySingleDriver(self.pta, hypersample, ecorrsample,
                                  redsample, seed, opts)

    def _make_jax(self, hypersample, ecorrsample, redsample, seed, opts):
        from .jax_backend import JaxGibbsDriver

        return JaxGibbsDriver(self.pta, hypersample=hypersample,
                              ecorrsample=ecorrsample,
                              redsample=redsample, seed=seed, **opts)


class PTABlockGibbs(_GibbsBase):
    """Multi-pulsar blocked Gibbs with a common free spectrum (reference
    ``pta_gibbs.py``)."""

    def _make_numpy(self, hypersample, ecorrsample, redsample, seed, opts):
        from .numpy_pta import NumpyPTAGibbs

        return _NumpyPTADriver(self.pta, hypersample, ecorrsample,
                               redsample, seed, opts)

    def _make_jax(self, hypersample, ecorrsample, redsample, seed, opts):
        from .jax_backend import JaxGibbsDriver

        return JaxGibbsDriver(self.pta, hypersample=hypersample,
                              ecorrsample=ecorrsample,
                              redsample=redsample, seed=seed, common_rho=True,
                              **opts)


def _adopt_jax_checkpoint(drv, state):
    """Adopt a jax-backend checkpoint into a numpy driver (supervised
    jax->numpy degradation): resume from its ``x_cur``, seed a fresh
    deterministic RNG from the checkpoint's PRNG key data, and flag the
    first resumed sweep to re-draw b and re-run the one-shot adaptation
    (the device adaptation state has no numpy equivalent).  The
    continuation is a valid Gibbs chain from the same state — not a
    bitwise replay; the oracle cannot reproduce the device stream."""
    xc = np.asarray(state["x_cur"], dtype=np.float64)
    if xc.ndim == 2:
        if xc.shape[0] != 1:
            raise RuntimeError(
                f"cannot degrade a multi-chain (nchains={xc.shape[0]}) "
                "jax checkpoint to the single-chain numpy backend")
        xc = xc[0]
    drv.x_resume = xc
    ent = [0x6DE6] + [int(v) for v in
                      np.asarray(state["jax_key"], np.uint32).ravel()]
    drv.g.rng = np.random.default_rng(np.random.SeedSequence(ent))
    drv._readapt = True


def _reject_jax_only_opts(opts):
    """Targeted error for device-record options reaching the f64 oracle:
    the numpy backends record every sweep at full precision by design, so
    a silent accept would misrepresent what was run and a bare TypeError
    would not name the option."""
    for opt in ("record_precision", "record_every"):
        if opt in opts:
            raise ValueError(
                f"{opt!r} is a jax-backend option (it controls the "
                "device->host record transfer); the numpy oracle backend "
                "records every sweep in float64 — drop the option or use "
                "backend='jax'")


class _NumpySingleDriver:
    """Adapter: NumpyGibbs sweeps -> the facade's run/adapt-state protocol."""

    def __init__(self, pta, hypersample, ecorrsample, redsample, seed, opts):
        _reject_jax_only_opts(opts)
        self.g = NumpyGibbs(pta, hypersample=hypersample,
                            ecorrsample=ecorrsample, redsample=redsample,
                            seed=seed, **opts)
        self.nb_total = self.g.nb_total

    def run(self, x, chain, bchain, start, niter):
        first = start == 0
        readapt = getattr(self, "_readapt", False)
        self._readapt = False
        self.x_cur = x
        for ii in range(start, niter):
            if readapt and ii == start:
                # adopted foreign (jax) checkpoint: b was never restored
                # — draw it from the resumed state before it is recorded
                self.g.draw_b(np.asarray(self.x_cur, dtype=np.float64))
            chain[ii] = self.x_cur
            bchain[ii] = self.g.b
            self.x_cur = self.g.sweep(
                self.x_cur,
                first=(first and ii == 0) or (readapt and ii == start))
            yield ii + 1

    def adapt_state(self):
        out = self.g.adapt_state()
        out["x_cur"] = np.asarray(self.x_cur)
        return out

    def load_adapt_state(self, state):
        state = dict(state)
        if "jax_key" in state and "rng_state" not in state:
            _adopt_jax_checkpoint(self, state)
            return
        if "x_cur" in state:
            self.x_resume = np.asarray(state.pop("x_cur"))
        self.g.load_adapt_state(state)


class _NumpyPTADriver:
    def __init__(self, pta, hypersample, ecorrsample, redsample, seed, opts):
        from .numpy_pta import NumpyPTAGibbs

        _reject_jax_only_opts(opts)
        self.g = NumpyPTAGibbs(pta, hypersample=hypersample,
                               ecorrsample=ecorrsample,
                               redsample=redsample, seed=seed, **opts)
        self.nb_total = self.g.nb_total

    def run(self, x, chain, bchain, start, niter):
        first = start == 0
        readapt = getattr(self, "_readapt", False)
        self._readapt = False
        self.x_cur = x
        for ii in range(start, niter):
            if readapt and ii == start:
                self.g.draw_b(np.asarray(self.x_cur, dtype=np.float64))
            chain[ii] = self.x_cur
            bchain[ii] = np.concatenate(self.g.b)
            self.x_cur = self.g.sweep(
                self.x_cur,
                first=(first and ii == 0) or (readapt and ii == start))
            yield ii + 1

    def adapt_state(self):
        out = self.g.adapt_state()
        out["x_cur"] = np.asarray(self.x_cur)
        return out

    def load_adapt_state(self, state):
        state = dict(state)
        if "jax_key" in state and "rng_state" not in state:
            _adopt_jax_checkpoint(self, state)
            return
        if "x_cur" in state:
            self.x_resume = np.asarray(state.pop("x_cur"))
        self.g.load_adapt_state(state)
