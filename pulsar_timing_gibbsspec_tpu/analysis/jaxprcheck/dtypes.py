"""C3 — dtype-island audit over the traced jaxpr.

The sampler's precision policy (README, docs/PERFORMANCE.md) is a
*placement* policy: f64 belongs to declared exact-islands (the
f64-accumulated Gram, the oracle/exact bodies, the factorizations they
feed) while the steady mixed path stays f32, and the f32 MXU einsums
that replace f64 accumulation must carry ``precision="highest"``.

The audit focuses on matmul-class equations (``dot_general``) — the
ops where a dtype regression costs 60x (VPU-emulated f64) or silently
drops accuracy (default-precision MXU f32).  Each dot is attributed to
the user function that emitted it (``source_of``); the island
declaration is a list of function names per class:

- ``exact_fns``: functions allowed to emit f64-accumulating dots; an
  f64 dot sourced anywhere else is a violation (f64 leaked into the
  steady path).
- ``highest_fns``: functions whose f32 dots must carry
  ``precision=HIGHEST`` on both operands (e.g. the segmented Gram);
  a default-precision dot there is a violation.

A per-program census ``{(out_dtype): count}`` of dots is also returned
so contracts can ratchet the dtype mix byte-identically.
"""

from __future__ import annotations

import os

from .walk import iter_eqns, source_of


def _dots(closed_jaxpr):
    for eqn, _depth in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name == "dot_general":
            yield eqn


def _out_dtype(eqn):
    return str(eqn.outvars[0].aval.dtype)


def _is_highest(precision) -> bool:
    if precision is None:
        return False
    if isinstance(precision, (tuple, list)):
        return all(_is_highest(p) for p in precision)
    return "HIGHEST" in str(precision).upper()


def dot_census(closed_jaxpr) -> dict:
    """``{out_dtype: count}`` over every dot_general in the program."""
    out: dict = {}
    for eqn in _dots(closed_jaxpr):
        k = _out_dtype(eqn)
        out[k] = out.get(k, 0) + 1
    return dict(sorted(out.items()))


def _in_island(fn, fname, islands) -> bool:
    """An island entry matches a function name (``tnt_d``), a file
    basename (``linalg.py`` — whole-module island, e.g. the repo's f64
    exact-solve library), or ``basename:function``."""
    base = os.path.basename(fname)
    return fn in islands or base in islands or f"{base}:{fn}" in islands


def audit_dtypes(closed_jaxpr, exact_fns=(), highest_fns=()):
    """Return ``(violations, census)``; each violation is a string
    carrying the op, its dtypes, and the source location."""
    exact_fns = set(exact_fns)
    highest_fns = set(highest_fns)
    violations = []
    for eqn in _dots(closed_jaxpr):
        f, ln, fn = source_of(eqn)
        loc = f"{fn} at {os.path.basename(f)}:{ln}"
        odt = _out_dtype(eqn)
        if odt == "float64" and not _in_island(fn, f, exact_fns):
            violations.append(
                f"f64-accumulating dot_general outside every declared "
                f"exact-island: {loc} (islands: {sorted(exact_fns)})")
        if _in_island(fn, f, highest_fns) and odt != "float64" \
                and not _is_highest(eqn.params.get("precision")):
            violations.append(
                f"dot_general in {loc} must carry precision=HIGHEST "
                f"(got {eqn.params.get('precision')!r}) — the f32 MXU "
                "einsum policy for exact-accumulation replacements")
    return violations, dot_census(closed_jaxpr)
