"""C5 — donation/aliasing verification from lowered StableHLO.

The driver donates the chunk carries (x, b) so the steady loop runs
in-place on device; if a refactor breaks donation XLA silently doubles
the carry footprint and only a profile would notice.  Donation that
*takes* is visible statically: every argument XLA accepted for
donation carries a ``tf.aliasing_output`` attribute on the lowered
module's entry function.  This check lowers with the declared
``donate_argnums`` and verifies the aliases actually materialized.
"""

from __future__ import annotations

import re


def aliased_outputs(hlo_text: str) -> list:
    """Indices of donation markers XLA accepted, parsed from the lowered
    module's argument attributes.  Two spellings exist: single-device
    lowerings alias each donated input to an output statically
    (``tf.aliasing_output = N : i32`` — N is the output index); SPMD
    lowerings defer the pairing to buffer assignment and mark the
    donated INPUT ``jax.buffer_donor = true`` instead (the meshed
    ensemble-chunk entry, ``contracts/crn_ensemble.json``).  Both count
    as donation-that-took; a lowering uses one spelling or the other,
    so the union is unambiguous for :func:`check_aliasing`'s floor."""
    out = {int(m.group(1))
           for m in re.finditer(r"tf\.aliasing_output\s*=\s*(\d+)",
                                hlo_text)}
    args = [(m.start(), int(m.group(1)))
            for m in re.finditer(r"%arg(\d+)", hlo_text)]
    for m in re.finditer(r"jax\.buffer_donor\s*=\s*true", hlo_text):
        prev = [a for a in args if a[0] < m.start()]
        if prev:                        # nearest preceding %argN
            out.add(prev[-1][1])
    return sorted(out)


def audit_donation(fn, example_args, donate_argnums):
    """Lower ``fn`` with ``donate_argnums`` (host-side only, nothing
    executes) and return ``(aliased_output_indices, hlo_text)``."""
    import jax

    low = jax.jit(fn, donate_argnums=tuple(donate_argnums)).lower(
        *example_args)
    text = low.as_text()
    return aliased_outputs(text), text


def check_aliasing(aliased: list, min_aliased: int):
    """None when enough donated inputs aliased; otherwise the violation
    message."""
    if len(aliased) >= int(min_aliased):
        return None
    return (f"only {len(aliased)} donated argument(s) aliased to outputs "
            f"(contract requires >= {min_aliased}) — a donated carry is "
            "being copied instead of reused; check for dtype/layout "
            "mismatches between the donated input and its output")
