"""C1 — peak-HBM estimate from the jaxpr, with the calibrated
accumulation-scratch model.

The estimate is a *compile-time sizing heuristic*, not a liveness
simulation: resident bytes are modeled as

    base (tile-padded consts + arguments, always live)
  + max over equations of (inputs + outputs + scratch)

which tracks XLA's behavior on this program class because the sweep's
intermediates are dominated by one huge term — the exact-Gram
``dot_general`` whose wider-than-operand accumulation
(``preferred_element_type=f64`` over f32 operands) makes XLA
materialize a segmented operand copy.  The scratch model is calibrated
against the r4 measurement (README / ROADMAP item 1): an
``(nseg, C, P, Nmax, B1)`` copy with ``nseg = ceil(N_contract /
gram_seg_len_exact)`` segments, tile-padded — which reproduces the measured
3.4x pad ratio and 15.8 GiB at C=128 to <1%.  Because it is a
calibrated heuristic, contracts that assert "passes" carry an expected
estimate plus a relative tolerance, so silent drift of the *model* is
caught the same way drift of the *program* is.
"""

from __future__ import annotations

import dataclasses
import math
import os

from .walk import aval_bytes, iter_eqns, source_of, tile_padded_bytes

#: segment length of the scratch model — must track
#: ``config.Settings.gram_seg_len_exact`` (the exact-Gram segment
#: length; kept as a plain constant so this module stays jax-free and
#: import-light until audit time).  The model and the program meet in
#: the middle: a widening dot whose contraction is <= this length
#: models as nseg=1 — exactly the segmented exact ``tnt_d`` path that
#: killed the C=128 wall — while a monolithic contraction models the
#: multi-segment operand-copy scratch the r4 measurement calibrated.
DEFAULT_SEG_LEN = 96

GiB = float(1 << 30)


@dataclasses.dataclass
class Scratch:
    """One modeled accumulation scratch (the C=128 wall's shape)."""

    shape: tuple          # (nseg,) + operand shape
    bytes: int            # tile-padded
    raw_bytes: int        # unpadded element bytes (pad ratio denominator)
    source: tuple         # (file, line, function)

    @property
    def pad_ratio(self) -> float:
        return self.bytes / max(1, self.raw_bytes)

    def describe(self) -> str:
        f, ln, fn = self.source
        return (f"accumulation scratch {self.shape} "
                f"({self.bytes / GiB:.2f} GiB tile-padded, "
                f"{self.pad_ratio:.2f}x pad) from {fn} "
                f"at {os.path.basename(f)}:{ln}")


@dataclasses.dataclass
class HbmReport:
    base_bytes: int
    peak_eqn_bytes: int
    peak_eqn: tuple | None       # (primitive name, source triple)
    scratches: list

    @property
    def estimate_bytes(self) -> int:
        return self.base_bytes + self.peak_eqn_bytes

    @property
    def largest_scratch(self):
        return max(self.scratches, key=lambda s: s.bytes, default=None)


def _npdtype_size(dtype) -> int:
    import numpy as np

    return int(np.dtype(dtype).itemsize)


def _scratch_for(eqn, seg_len):
    """The calibrated scratch rule: a ``dot_general`` accumulating into
    a type wider than its operands forces a segmented operand copy."""
    if eqn.primitive.name != "dot_general":
        return None
    pet = eqn.params.get("preferred_element_type")
    if pet is None:
        return None
    avals = [v.aval for v in eqn.invars if hasattr(v, "aval")]
    if len(avals) < 2:
        return None
    la, ra = avals[0], avals[1]
    op_size = max(_npdtype_size(la.dtype), _npdtype_size(ra.dtype))
    if _npdtype_size(pet) <= op_size:
        return None
    (lc, _rc), _ = eqn.params["dimension_numbers"]
    n_contract = 1
    for d in lc:
        n_contract *= int(la.shape[d])
    nseg = max(1, math.ceil(n_contract / int(seg_len)))
    big = la if math.prod(la.shape) >= math.prod(ra.shape) else ra
    padded = tile_padded_bytes(big.shape, big.dtype)
    raw = math.prod(big.shape) * _npdtype_size(big.dtype)
    return Scratch(shape=(nseg,) + tuple(int(s) for s in big.shape),
                   bytes=nseg * padded, raw_bytes=nseg * raw,
                   source=source_of(eqn))


def audit_hbm(closed_jaxpr, seg_len=DEFAULT_SEG_LEN) -> HbmReport:
    """Size every equation of ``closed_jaxpr`` (recursing through call
    primitives) and return the peak-HBM report."""
    jaxpr = closed_jaxpr.jaxpr
    base = sum(aval_bytes(getattr(v, "aval", None))
               for v in (*jaxpr.constvars, *jaxpr.invars))
    base += sum(tile_padded_bytes(getattr(c, "shape", ()),
                                  getattr(c, "dtype", "float32"))
                for c in closed_jaxpr.consts
                if hasattr(c, "shape") and hasattr(c, "dtype"))
    peak, peak_eqn, scratches = 0, None, []
    for eqn, _depth in iter_eqns(jaxpr):
        foot = 0
        for v in (*eqn.invars, *eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                foot += aval_bytes(aval)
        sc = _scratch_for(eqn, seg_len)
        if sc is not None:
            scratches.append(sc)
            foot += sc.bytes
        if foot > peak:
            peak = foot
            peak_eqn = (eqn.primitive.name, source_of(eqn))
    return HbmReport(base_bytes=int(base), peak_eqn_bytes=int(peak),
                     peak_eqn=peak_eqn, scratches=scratches)


def check_budget(report: HbmReport, budget_bytes: int):
    """None when the estimate fits; otherwise the violation message —
    always naming the dominant accumulation scratch, because that is
    the actionable term (segment it, shrink C, or shard chains)."""
    est = report.estimate_bytes
    if est <= int(budget_bytes):
        return None
    msg = (f"peak-HBM estimate {est / GiB:.2f} GiB exceeds the "
           f"{budget_bytes / GiB:.2f} GiB per-device budget")
    sc = report.largest_scratch
    if sc is not None:
        msg += f": {sc.describe()}"
    elif report.peak_eqn is not None:
        prim, (f, ln, fn) = report.peak_eqn
        msg += (f": dominant equation {prim} in {fn} "
                f"at {os.path.basename(f)}:{ln}")
    return msg
