"""jaxprcheck — jaxpr/HLO-level contract auditor (docs/LINTING.md).

Where :mod:`..jaxlint` enforces JAX discipline at the Python-AST level,
this package proves the contracts that live *below* the AST, by tracing
the compiled sweep under abstract inputs (``jax.jit(fn).trace`` /
``.lower()`` — zero device execution) and walking the ClosedJaxpr and
lowered HLO against machine-readable contracts committed in
``contracts/*.json``:

- **C1** (:mod:`.hbm`) — peak-HBM estimate per device, sizing every
  intermediate with the TPU tiling-pad heuristic calibrated against the
  r4 measurement of the exact-Gram accumulation scratch, so the C=128
  wall is rejected at lint time with the offending equation's source
  location.
- **C2** (:mod:`.collectives`) — collective census (count / kind /
  payload elements of all-reduce / all-gather per sweep), ratcheted
  byte-identical against the committed budget; absorbs the counting core
  of ``parallel/sharding.collective_report``.
- **C3** (:mod:`.dtypes`) — dtype-island audit: f64-accumulating
  matmuls must lie inside declared exact-islands, the mixed steady path
  must stay f32, and ``precision="highest"`` einsums are verified.
- **C4** (:mod:`.keys`) — PRNG key lineage: dataflow over ``random_*``
  primitives proving each key is consumed at most once and fold_in
  chains match the checkpoint key-fold policy
  ``fold_in(fold_in(base_key, iteration), chain)``.
- **C5** (:mod:`.donation`) — chunk carry buffers declared donated are
  verified actually aliased in the lowering.

CLI: ``python -m pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck`` (also
``tools/jaxprcheck.py`` and the ``jaxprcheck`` console script), with a
``jaxprcheck_baseline.json`` ratchet in the jaxlint style.  The traced
programs come from the stable entry points exported by
``sampler/jax_backend.py`` (``gram_trace_entry``, ``sweep_chunk_entry``,
``sharded_sweep_step``) so kernel refactors update their audit surface
in the same diff.
"""

from .collectives import census_from_hlo  # noqa: F401  (import-light)

__all__ = ["census_from_hlo"]
