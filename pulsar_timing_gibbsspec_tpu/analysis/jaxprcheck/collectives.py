"""C2 — collective census from optimized HLO text.

This module is the counting core absorbed from
``parallel/sharding.collective_report`` (which now delegates here): the
regexes are kept verbatim so the census stays byte-comparable with the
MULTICHIP_r*.json trajectory.  Import-light on purpose — pure ``re`` +
``numpy`` — so the census can run over committed HLO snapshots without
touching jax.

Known environment sensitivity (and the reason the census is a
*committed contract*, not a constant): the r05 artifact measured
``{'all-reduce': 5, 'all-gather': 3}`` under the bench container's XLA
build; the current container's XLA partitions the red-conditional
gumbel draw's u32 random bits with one extra partial-bits all-reduce,
measuring ``{'all-reduce': 6, 'all-gather': 3}`` on byte-identical
source.  The contract pins what the current toolchain emits; any drift
— program OR toolchain — fails the gate and forces a deliberate
re-commit.
"""

from __future__ import annotations

import re

import numpy as np


def census_from_hlo(hlo: str) -> dict:
    """Count all-reduce / all-gather ops and list each gather's operand
    element count — verbatim the counting rules of the pre-absorption
    ``collective_report``."""
    counts = {"all-reduce": len(re.findall(r"\ball-reduce(?:-start)?\(",
                                           hlo)),
              "all-gather": len(re.findall(r"\ball-gather(?:-start)?\(",
                                           hlo))}
    elems = []
    for m in re.finditer(r"all-gather(?:-start)?\(", hlo):
        # operand shape precedes the op name on the defining line:
        #   %x = f32[6,17]{...} all-gather(...)
        line = hlo[hlo.rfind("\n", 0, m.start()) + 1:m.start()]
        sm = re.search(r"\[([0-9,]*)\]", line)
        if sm:
            dims = [int(v) for v in sm.group(1).split(",") if v]
            elems.append(int(np.prod(dims)) if dims else 1)
    counts["gather_elems"] = sorted(elems)
    return counts


def check_gather_budget(counts: dict, max_gather_elems):
    """None, or the over-budget message ``collective_report`` raises —
    the guard that keeps "shard the pulsar axis, replicate x" honest."""
    if max_gather_elems is None:
        return None
    too_big = [e for e in counts.get("gather_elems", [])
               if e > max_gather_elems]
    if not too_big:
        return None
    return (f"all-gather operand(s) of {too_big} elements exceed the "
            f"{max_gather_elems}-element budget — a basis-sized array "
            "is crossing the mesh")


def optimized_hlo(fn, *example_args) -> str:
    """Lower + compile ``fn`` (host-side AOT only — nothing executes on
    a device) and return the optimized HLO text."""
    import jax

    return jax.jit(fn).lower(*example_args).compile().as_text()


def census(fn, *example_args, max_gather_elems=None) -> dict:
    """Census the optimized HLO of ``fn``."""
    counts = census_from_hlo(optimized_hlo(fn, *example_args))
    msg = check_gather_budget(counts, max_gather_elems)
    if msg is not None:
        raise RuntimeError(msg)
    return counts


_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute",
                   "collective-broadcast")


def _collective_lines(hlo: str) -> list:
    """``[(op, groups, line)]`` for every collective defining line —
    the decoding core of :func:`collective_groups`, with the raw HLO
    line kept so callers can size the result operand."""
    out = []
    op_re = "|".join(re.escape(o) for o in _COLLECTIVE_OPS)
    for m in re.finditer(r"\b(%s)(?:-start)?\(" % op_re, hlo):
        start = hlo.rfind("\n", 0, m.start()) + 1
        end = hlo.find("\n", m.start())
        line = hlo[start:end if end >= 0 else len(hlo)]
        op, groups = m.group(1), None
        gm = re.search(r"replica_groups=(\{\{[0-9, ]*\}"
                       r"(?:,\{[0-9, ]*\})*\})", line)
        if gm:
            groups = [[int(x) for x in g.split(",") if x.strip()]
                      for g in re.findall(r"\{([0-9, ]*)\}", gm.group(1))]
        else:
            im = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
                           r"(?:T\(([0-9,]+)\))?", line)
            if im:
                g, s = int(im.group(1)), int(im.group(2))
                dims = [int(v) for v in im.group(3).split(",")]
                ids = np.arange(int(np.prod(dims))).reshape(dims)
                if im.group(4):
                    perm = [int(v) for v in im.group(4).split(",")]
                    ids = ids.transpose(perm)
                groups = ids.reshape(g, s).tolist()
        if groups is None and op == "collective-permute":
            pm = re.search(r"source_target_pairs=(\{\{[0-9, ]*\}"
                           r"(?:,\{[0-9, ]*\})*\})", line)
            if pm:
                groups = [[int(x) for x in g.split(",") if x.strip()]
                          for g in re.findall(r"\{([0-9, ]*)\}",
                                              pm.group(1))]
        out.append((op, groups, line))
    return out


def collective_groups(hlo: str) -> list:
    """``[(op, groups)]`` for every collective defining line, with
    ``groups`` as lists of global device ids.

    Three HLO spellings are decoded: explicit
    ``replica_groups={{0,1},{2,3}}`` lists, the iota form
    ``replica_groups=[G,S]<=[dims](T(perm))?`` (arange over ``dims``,
    optionally transposed, reshaped to G groups of S), and
    ``collective-permute``'s ``source_target_pairs`` (each pair is a
    2-device group).  A collective whose groups cannot be decoded —
    including the bare ``replica_groups={}`` meaning *all devices* —
    yields one group spanning every mentioned partition id, so an
    unrecognized spelling fails an isolation check loudly instead of
    slipping past it.
    """
    return [(op, groups) for op, groups, _ in _collective_lines(hlo)]


def _result_elems(line: str):
    """Element count of the defining line's (first) result shape —
    ``%x = f32[6,17]{...} all-gather(...)`` -> 102; None when no shape
    is found (e.g. tuple-result spellings this parser doesn't size)."""
    sm = re.search(r"\[([0-9,]*)\]", line)
    if not sm:
        return None
    dims = [int(v) for v in sm.group(1).split(",") if v]
    return int(np.prod(dims)) if dims else 1


def check_axis_isolation(hlo: str, mesh_shape, axis=0, allow=None) -> list:
    """Messages for collectives whose replica groups cross ``axis`` of
    a row-major device mesh of ``mesh_shape`` — the static proof that
    an "embarrassingly parallel" mesh axis really carries zero
    collective traffic.

    With devices laid out row-major over ``mesh_shape`` (exactly what
    ``parallel.sharding.make_mesh`` does), device ``d``'s coordinate
    along ``axis`` is ``unravel_index(d, mesh_shape)[axis]``; a replica
    group containing two distinct coordinates means bytes move across
    that axis.  Undecodable group spellings are treated as
    all-device groups (see :func:`collective_groups`) and therefore
    fail here rather than pass silently.

    ``allow`` (the ensemble-stage escape hatch,
    ``contracts/crn_ensemble.json``) is a list of
    ``{"op": name, "max_elems": n}`` entries: a crossing collective is
    tolerated only when its op matches an entry, its result operand
    sizes to at most ``max_elems`` elements, AND its replica groups
    were positively decoded — an undecodable spelling or an oversized
    payload (a b-slab or design matrix crossing chain blocks) still
    fails.  The allowlist is for small (rho, hyper) payloads only.
    """
    shape = tuple(int(s) for s in mesh_shape)
    n_dev = int(np.prod(shape))
    allow = allow or []
    msgs = []
    for op, groups, line in _collective_lines(hlo):
        decoded = bool(groups)
        if not groups:
            groups = [list(range(n_dev))]
        for g in groups:
            coords = {int(np.unravel_index(int(d), shape)[axis])
                      for d in g}
            if len(coords) > 1:
                elems = _result_elems(line)
                ok = decoded and elems is not None and any(
                    a.get("op") == op
                    and elems <= int(a.get("max_elems", 0))
                    for a in allow)
                if ok:
                    break
                msgs.append(
                    f"{op} replica group {g} spans coordinates "
                    f"{sorted(coords)} of mesh axis {axis} (shape "
                    f"{shape}) — this axis is contracted to carry "
                    "zero collective traffic"
                    + (f" (result {elems} elems, not allowlisted)"
                       if allow else ""))
                break
    return msgs
