"""C2 — collective census from optimized HLO text.

This module is the counting core absorbed from
``parallel/sharding.collective_report`` (which now delegates here): the
regexes are kept verbatim so the census stays byte-comparable with the
MULTICHIP_r*.json trajectory.  Import-light on purpose — pure ``re`` +
``numpy`` — so the census can run over committed HLO snapshots without
touching jax.

Known environment sensitivity (and the reason the census is a
*committed contract*, not a constant): the r05 artifact measured
``{'all-reduce': 5, 'all-gather': 3}`` under the bench container's XLA
build; the current container's XLA partitions the red-conditional
gumbel draw's u32 random bits with one extra partial-bits all-reduce,
measuring ``{'all-reduce': 6, 'all-gather': 3}`` on byte-identical
source.  The contract pins what the current toolchain emits; any drift
— program OR toolchain — fails the gate and forces a deliberate
re-commit.
"""

from __future__ import annotations

import re

import numpy as np


def census_from_hlo(hlo: str) -> dict:
    """Count all-reduce / all-gather ops and list each gather's operand
    element count — verbatim the counting rules of the pre-absorption
    ``collective_report``."""
    counts = {"all-reduce": len(re.findall(r"\ball-reduce(?:-start)?\(",
                                           hlo)),
              "all-gather": len(re.findall(r"\ball-gather(?:-start)?\(",
                                           hlo))}
    elems = []
    for m in re.finditer(r"all-gather(?:-start)?\(", hlo):
        # operand shape precedes the op name on the defining line:
        #   %x = f32[6,17]{...} all-gather(...)
        line = hlo[hlo.rfind("\n", 0, m.start()) + 1:m.start()]
        sm = re.search(r"\[([0-9,]*)\]", line)
        if sm:
            dims = [int(v) for v in sm.group(1).split(",") if v]
            elems.append(int(np.prod(dims)) if dims else 1)
    counts["gather_elems"] = sorted(elems)
    return counts


def check_gather_budget(counts: dict, max_gather_elems):
    """None, or the over-budget message ``collective_report`` raises —
    the guard that keeps "shard the pulsar axis, replicate x" honest."""
    if max_gather_elems is None:
        return None
    too_big = [e for e in counts.get("gather_elems", [])
               if e > max_gather_elems]
    if not too_big:
        return None
    return (f"all-gather operand(s) of {too_big} elements exceed the "
            f"{max_gather_elems}-element budget — a basis-sized array "
            "is crossing the mesh")


def census(fn, *example_args, max_gather_elems=None) -> dict:
    """Lower + compile ``fn`` (host-side AOT only — nothing executes on
    a device) and census the optimized HLO."""
    import jax

    hlo = jax.jit(fn).lower(*example_args).compile().as_text()
    counts = census_from_hlo(hlo)
    msg = check_gather_budget(counts, max_gather_elems)
    if msg is not None:
        raise RuntimeError(msg)
    return counts
