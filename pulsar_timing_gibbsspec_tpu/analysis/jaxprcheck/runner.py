"""Contract loading and check dispatch.

A contract is a committed JSON file under ``<repo>/contracts/``:

.. code-block:: json

    {
      "name": "crn_bench_c128",
      "fast": false,
      "note": "why this contract exists / provenance of its numbers",
      "entry": {"entry": "gram", "n_psr": 45, "...": "..."},
      "checks": [
        {"kind": "hbm", "budget_bytes": 16911433728,
         "expect": "violation", "expect_source_fn": "tnt_d",
         "expect_scratch_bytes": 16986931200, "tolerance_rel": 0.02},
        {"kind": "collectives", "census": {"all-reduce": 6},
         "isolate_axis": {"mesh": [2, 4], "axis": 0}, "...": 0},
        {"kind": "dtypes", "exact_fns": ["linalg.py"], "census": {}},
        {"kind": "keys", "policy": {"fold_depths_at_split": [2]}},
        {"kind": "donation", "donate_argnums": [0, 1], "min_aliased": 2}
      ]
    }

``entry`` resolves through :mod:`.entries`; each check walks the
traced jaxpr or the lowered HLO of that entry.  Check failures are
:class:`Violation` objects carrying ``path`` (the contract file) and
``rule`` (the check kind) — the same surface jaxlint violations
expose, so the :mod:`..baseline` ratchet applies unchanged.

The ``hbm`` check supports ``expect: "violation"``: the contract
*requires* the auditor to reject the configuration (the C=128 gate),
naming ``expect_source_fn`` — an HBM estimate that silently stops
rejecting an over-budget config is itself a contract failure.  When a
calibration pin (``expect_scratch_bytes`` ± ``tolerance_rel``) is
present, drift of the size model fails the gate the same way drift of
the program does.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from pathlib import Path

from .collectives import (census_from_hlo, check_axis_isolation,
                          check_gather_budget, optimized_hlo)
from .donation import audit_donation, check_aliasing
from .dtypes import audit_dtypes, dot_census
from .entries import resolve_entry
from .hbm import GiB, audit_hbm, check_budget
from .keys import audit_keys, check_policy
from .walk import trace_jaxpr

_REPO_ROOT = Path(__file__).resolve().parents[3]
CONTRACT_DIR = _REPO_ROOT / "contracts"


@dataclasses.dataclass(frozen=True)
class Violation:
    """One contract-check failure; ``path``/``rule`` match the jaxlint
    violation surface so ``analysis.baseline`` ratchets these too."""

    path: str
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}: [{self.rule}] {self.message}"


def load_contract(path) -> dict:
    with open(path, encoding="utf-8") as fh:
        c = json.load(fh)
    c["_path"] = str(path)
    return c


def discover_contracts(root=None, fast_only=False) -> list:
    root = Path(root) if root is not None else CONTRACT_DIR
    out = []
    for p in sorted(root.glob("*.json")):
        c = load_contract(p)
        if "entry" not in c:
            # contracts/ also holds non-jaxprcheck configs (racecheck's
            # allowlists live there); only entry-bearing files are
            # traceable contracts
            continue
        if c.get("tool") not in (None, "jaxprcheck"):
            # entry-bearing contracts of sibling auditors (numcheck)
            # run under their own CLI; coverage still counts them
            continue
        if fast_only and not c.get("fast", False):
            continue
        out.append(c)
    return out


def contract_hashes(root=None) -> dict:
    """``{name: sha256-of-canonical-json}`` over committed contracts —
    the audited-contract fingerprint bench.py embeds in the resilience
    block, so a bench artifact records exactly which budgets it was
    proven against."""
    out = {}
    root = Path(root) if root is not None else CONTRACT_DIR
    for p in sorted(root.glob("*.json")):
        with open(p, encoding="utf-8") as fh:
            c = json.load(fh)
        canon = json.dumps(c, sort_keys=True, separators=(",", ":"))
        out[c.get("name", p.stem)] = hashlib.sha256(
            canon.encode()).hexdigest()
    return out


def _relpath(path) -> str:
    try:
        return os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        return str(path)


# ---------------------------------------------------------------------------
# per-kind check implementations: each returns (messages, facts)

def _check_hbm(chk, closed, fn, args):
    rep = audit_hbm(closed, seg_len=chk.get("seg_len", 96))
    msg = check_budget(rep, chk["budget_bytes"])
    facts = {"estimate_bytes": rep.estimate_bytes,
             "estimate_gib": round(rep.estimate_bytes / GiB, 3)}
    sc = rep.largest_scratch
    if sc is not None:
        facts["scratch"] = {"shape": list(sc.shape), "bytes": sc.bytes,
                            "pad_ratio": round(sc.pad_ratio, 3),
                            "source_fn": sc.source[2]}
    out = []
    expect = chk.get("expect", "pass")
    if expect == "pass":
        if msg is not None:
            out.append(msg)
    else:                                   # expect == "violation"
        want_fn = chk.get("expect_source_fn")
        if msg is None:
            out.append(
                f"expected an HBM-budget violation (the "
                f"{chk['budget_bytes'] / GiB:.2f} GiB gate) but the "
                f"estimate passed at {rep.estimate_bytes / GiB:.2f} GiB "
                "— the auditor stopped rejecting this configuration")
        elif want_fn and want_fn not in msg:
            out.append(
                f"HBM violation fired but does not name {want_fn!r}: "
                f"{msg}")
    pin = chk.get("expect_scratch_bytes")
    if pin is not None:
        got = sc.bytes if sc is not None else 0
        tol = float(chk.get("tolerance_rel", 0.02))
        if abs(got - pin) > tol * pin:
            out.append(
                f"scratch calibration drift: modeled {got} bytes, "
                f"contract pins {pin} (±{tol:.0%}) — re-calibrate "
                "against a fresh HBM measurement before re-committing")
    return out, facts


def _check_collectives(chk, closed, fn, args):
    hlo = optimized_hlo(fn, *args)
    got = census_from_hlo(hlo)
    facts = {"census": got}
    out = []
    want = chk.get("census")
    if want is not None:
        a = json.dumps(got, sort_keys=True)
        b = json.dumps(want, sort_keys=True)
        if a != b:                          # byte-identical ratchet
            out.append(f"collective census drift: measured {a}, "
                       f"contract pins {b}")
    msg = check_gather_budget(got, chk.get("max_gather_elems"))
    if msg is not None:
        out.append(msg)
    iso = chk.get("isolate_axis")
    if iso is not None:
        allow = iso.get("allow")
        msgs = check_axis_isolation(hlo, iso["mesh"], iso.get("axis", 0),
                                    allow=allow)
        facts["isolate_axis"] = {"mesh": [int(s) for s in iso["mesh"]],
                                 "axis": int(iso.get("axis", 0)),
                                 "clean": not msgs}
        if allow is not None:
            # the allowlist is part of the committed facts so a widened
            # escape hatch shows up in review, not just in the lowering
            facts["isolate_axis"]["allow"] = allow
        out.extend(msgs)
    return out, facts


def _check_dtypes(chk, closed, fn, args):
    v, got = audit_dtypes(closed,
                          exact_fns=chk.get("exact_fns", ()),
                          highest_fns=chk.get("highest_fns", ()))
    out = list(v)
    want = chk.get("census")
    if want is not None and json.dumps(got, sort_keys=True) != \
            json.dumps(want, sort_keys=True):
        out.append(f"dot dtype census drift: measured {got}, "
                   f"contract pins {want}")
    return out, {"census": got}


def _check_keys(chk, closed, fn, args):
    rep = audit_keys(closed)
    out = check_policy(rep, chk.get("policy", {}))
    return out, {"n_roots": rep.n_roots, "n_splits": rep.n_splits,
                 "n_bits": rep.n_bits, "n_folds": rep.n_folds,
                 "fold_depths_at_split": list(rep.fold_depths_at_split),
                 "pre_split_consumes": rep.pre_split_consumes}


def _check_donation(chk, closed, fn, args):
    aliased, _text = audit_donation(fn, args,
                                    chk.get("donate_argnums", ()))
    out = []
    msg = check_aliasing(aliased, chk.get("min_aliased", 1))
    if msg is not None:
        out.append(msg)
    return out, {"aliased_outputs": aliased}


def _check_outputs(chk, closed, fn, args):
    """Bound the entry's total output bytes — the static form of "no
    transfer beyond the bounded summary slab": every device result an
    instrumented chunk can ship is an outvar of this jaxpr, so pinning
    their aggregate size (and count) here means instrumentation cannot
    quietly grow the device->host surface (contracts/obs_quick.json)."""
    import numpy as np

    outs = closed.jaxpr.outvars
    per = [int(np.prod(v.aval.shape, dtype=np.int64))
           * np.dtype(v.aval.dtype).itemsize for v in outs]
    total = int(sum(per))
    facts = {"count": len(outs), "total_bytes": total,
             "largest_bytes": max(per) if per else 0}
    out = []
    max_bytes = chk.get("max_bytes")
    if max_bytes is not None and total > int(max_bytes):
        out.append(
            f"output surface grew: {total} bytes across {len(outs)} "
            f"outputs exceeds the contract's {int(max_bytes)}-byte "
            "summary-slab bound")
    max_count = chk.get("max_count")
    if max_count is not None and len(outs) > int(max_count):
        out.append(f"output count {len(outs)} exceeds the contract's "
                   f"{int(max_count)}")
    return out, facts


def _check_cost(chk, closed, fn, args):
    """C6: the static FLOP/HBM-byte cost model.  Pins
    ``pin_flops``/``pin_dot_flops``/``pin_hbm_bytes`` within
    ``tolerance_rel`` so the entry's arithmetic cannot silently grow
    (or the model silently drift); ``max_flops`` caps growth without a
    pin."""
    from .cost import jaxpr_cost

    rep = jaxpr_cost(closed)
    facts = rep.as_dict()
    out = []
    for field in ("flops", "dot_flops", "hbm_bytes"):
        pin = chk.get(f"pin_{field}")
        if pin is None:
            continue
        got = getattr(rep, field)
        tol = float(chk.get("tolerance_rel", 0.05))
        if abs(got - pin) > tol * pin:
            out.append(
                f"cost-model drift on {field}: modeled {got:.6g}, "
                f"contract pins {pin:.6g} (±{tol:.0%}) — either the "
                "entry's arithmetic changed (re-pin deliberately) or a "
                "cost rule regressed")
    max_flops = chk.get("max_flops")
    if max_flops is not None and rep.flops > float(max_flops):
        out.append(f"entry FLOPs grew: {rep.flops:.6g} exceeds the "
                   f"contract's {float(max_flops):.6g} cap")
    return out, facts


_CHECKS = {"hbm": _check_hbm, "collectives": _check_collectives,
           "dtypes": _check_dtypes, "keys": _check_keys,
           "donation": _check_donation, "outputs": _check_outputs,
           "cost": _check_cost}


def run_contract(contract: dict):
    """``(violations, facts)`` for one loaded contract.  The entry is
    traced once; every check shares the ClosedJaxpr."""
    path = _relpath(contract.get("_path", contract.get("name", "?")))
    fn, args, _extras = resolve_entry(contract["entry"])
    closed = trace_jaxpr(fn, args)
    violations, facts = [], {"name": contract.get("name"),
                             "n_eqns": len(closed.jaxpr.eqns)}
    for chk in contract.get("checks", []):
        kind = chk["kind"]
        impl = _CHECKS.get(kind)
        if impl is None:
            violations.append(Violation(path, kind,
                                        f"unknown check kind {kind!r}"))
            continue
        msgs, chk_facts = impl(chk, closed, fn, args)
        facts[kind] = chk_facts
        violations.extend(Violation(path, kind, m) for m in msgs)
    return violations, facts


def check_contract_coverage(root=None) -> list:
    """One ``coverage`` violation per jit entry builder in
    :mod:`.entries` that no committed contract pins — a new compiled
    program cannot land unaudited.  Enumerates ALL entry-bearing
    contracts (not just the fast subset, and including sibling-tool
    contracts like numcheck's): a slow or foreign-tool contract still
    covers its entry."""
    from .entries import _ENTRIES

    rootdir = Path(root) if root is not None else CONTRACT_DIR
    covered = {load_contract(p).get("entry", {}).get("entry")
               for p in sorted(rootdir.glob("*.json"))}
    out = []
    for kind in sorted(set(_ENTRIES) - covered):
        out.append(Violation(
            os.path.join("contracts", kind), "coverage",
            f"jit entry builder {kind!r} (jaxprcheck/entries.py) has no "
            "pinned contracts/*.json — add a contract before the "
            "compiled program ships"))
    return out


def run_contracts(contracts):
    """``(all_violations, {name: facts})`` over a contract list; a
    contract that errors out (entry fails to build/trace) becomes an
    ``error`` violation rather than an exception, so one broken
    contract cannot mask the others."""
    all_v, all_f = [], {}
    for c in contracts:
        path = _relpath(c.get("_path", c.get("name", "?")))
        try:
            v, f = run_contract(c)
        except Exception as e:              # noqa: BLE001 - report, don't die
            all_v.append(Violation(path, "error",
                                   f"{type(e).__name__}: {e}"))
            continue
        all_v.extend(v)
        all_f[c.get("name", path)] = f
    return all_v, all_f
