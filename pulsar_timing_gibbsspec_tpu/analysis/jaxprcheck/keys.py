"""C4 — PRNG key lineage over the traced jaxpr.

A dataflow machine over the typed-key primitives (``random_seed`` /
``random_wrap`` roots, ``random_fold_in`` derivation, ``random_split``
fan-out, ``random_bits`` consumption) proving the two properties the
sampler's reproducibility story rests on:

- **single consumption** — no key variable is split or drawn from more
  than once (the classic key-reuse bug jaxlint's R1 can only catch at
  the AST level; here it is proved on the actual dataflow, through
  vmap batching, pjit, scan and cond);
- **fold policy** — every ``random_split`` happens at the declared
  fold depth, so the chunk's per-(iteration, chain) streams really are
  ``fold_in(fold_in(base_key, iteration), chain)`` (the checkpoint
  key-fold policy recorded in the layout manifest — PR 4).

Per-variable lineage state is ``("pre", n_folds)`` for keys on the
fold chain (root keys enter at depth 0) and ``("post",)`` for keys
produced by a split.  Consumption counts flow through call primitives:
a key consumed inside a pjit/scan body charges the outer variable.
Loop bodies are modeled as running once per iteration: a key entering
a scan/while body as a loop *constant* and consumed inside is consumed
every iteration (flagged — only fold_in derivation is legal there),
and a carry key passed through unchanged after being consumed inside
is cross-iteration reuse (flagged).  Cond branches are mutually
exclusive, so cross-branch consumption charges the max, not the sum.
"""

from __future__ import annotations

import dataclasses
import os

from .walk import source_of, subjaxprs

#: primitives that merely reshape/route key arrays — lineage passes
#: through unchanged
_PASSTHROUGH = {
    "broadcast_in_dim", "reshape", "squeeze", "slice", "dynamic_slice",
    "gather", "concatenate", "transpose", "select_n", "rev", "copy",
    "convert_element_type", "expand_dims", "device_put",
}


def _is_key_aval(aval) -> bool:
    import jax

    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        return jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def _is_var(v) -> bool:
    """True for trackable jaxpr Vars (Literals are unhashable and carry
    no lineage)."""
    import jax

    return isinstance(v, jax.core.Var)


@dataclasses.dataclass
class KeyReport:
    violations: list
    n_roots: int = 0
    n_in_trace_roots: int = 0       # random_seed/random_wrap inside trace
    n_splits: int = 0
    n_bits: int = 0
    n_folds: int = 0
    fold_depths_at_split: list = dataclasses.field(default_factory=list)
    pre_split_consumes: int = 0     # random_bits straight off a fold chain


class _Walker:
    def __init__(self, report: KeyReport):
        self.r = report

    def walk(self, jaxpr, state, consumed):
        """``state``: var -> lineage tuple; ``consumed``: var -> count.
        Mutates both; returns the state of the jaxpr's outvars."""
        for eqn in jaxpr.eqns:
            self._eqn(eqn, state, consumed)
        return [state.get(v) for v in jaxpr.outvars]

    # -- helpers ----------------------------------------------------------
    def _in_state(self, eqn, state):
        for v in eqn.invars:
            if _is_var(v) and v in state:
                return state[v]
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and _is_key_aval(aval):
                return ("pre", 0)       # untracked key: treat as root
        return None

    def _consume(self, eqn, state, consumed, what):
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if not _is_var(v) or aval is None or not _is_key_aval(aval):
                continue
            consumed[v] = consumed.get(v, 0) + 1
            if consumed[v] > 1:
                f, ln, fn = source_of(eqn)
                self.r.violations.append(
                    f"key consumed more than once: {what} in {fn} at "
                    f"{os.path.basename(f)}:{ln} re-uses a key variable "
                    f"already split/drawn from ({consumed[v]} uses)")

    # -- the machine ------------------------------------------------------
    def _eqn(self, eqn, state, consumed):
        name = eqn.primitive.name
        if name in ("random_seed", "random_wrap"):
            self.r.n_in_trace_roots += 1
            for o in eqn.outvars:
                state[o] = ("pre", 0)
            return
        if name == "random_fold_in":
            self.r.n_folds += 1
            st = self._in_state(eqn, state) or ("pre", 0)
            depth = st[1] + 1 if st[0] == "pre" else 1
            for o in eqn.outvars:
                state[o] = ("pre", depth)
            return
        if name == "random_split":
            self.r.n_splits += 1
            st = self._in_state(eqn, state) or ("pre", 0)
            if st[0] == "pre":
                self.r.fold_depths_at_split.append(st[1])
            self._consume(eqn, state, consumed, "random_split")
            for o in eqn.outvars:
                state[o] = ("post",)
            return
        if name == "random_bits":
            self.r.n_bits += 1
            st = self._in_state(eqn, state)
            if st is not None and st[0] == "pre":
                self.r.pre_split_consumes += 1
            self._consume(eqn, state, consumed, "random_bits")
            return
        if name in _PASSTHROUGH:
            st = self._in_state(eqn, state)
            if st is not None:
                for o in eqn.outvars:
                    if _is_key_aval(getattr(o, "aval", None)):
                        state[o] = st
            return
        subs = subjaxprs(eqn)
        if subs:
            self._call(eqn, subs, state, consumed)
            return
        # any other primitive taking a key input: opaque sink — count a
        # consumption so a stray key use can't hide
        if any(_is_key_aval(getattr(v, "aval", None)) for v in eqn.invars):
            self._consume(eqn, state, consumed, name)

    def _call(self, eqn, subs, state, consumed):
        name = eqn.primitive.name
        out_states = None
        # cond branches are mutually exclusive — only one executes, so
        # an outer key consumed in several branches is still consumed
        # once; charge the max across branches, not the sum
        exclusive = name == "cond"
        branch_charges: dict = {}
        for sub in subs:
            sub_state, sub_consumed = {}, {}
            outer_of = {}
            # map outer args onto the body's trailing invars: every call
            # convention here aligns 1:1 from the tail (pjit is exactly
            # 1:1; scan's eqn.invars = consts + carry + xs match body
            # invars = consts + carry + x-slices; cond prepends only the
            # predicate; while prepends cond-consts the body never sees)
            inv = sub.invars
            args = list(eqn.invars)
            for bv, ov in zip(reversed(inv), reversed(args)):
                if not _is_var(ov):
                    continue
                if ov in state:
                    sub_state[bv] = state[ov]
                outer_of[bv] = ov
            outs = self.walk(sub, sub_state, sub_consumed)
            # charge body consumption back to the outer variables, so a
            # key used here AND elsewhere outside still trips the
            # single-consumption rule
            for bv, n in sub_consumed.items():
                ov = outer_of.get(bv)
                if ov is None or n <= 0:
                    continue
                if exclusive:
                    branch_charges[ov] = max(branch_charges.get(ov, 0), n)
                    continue
                consumed[ov] = consumed.get(ov, 0) + n
                if consumed[ov] > 1:
                    f, ln, fn = source_of(eqn)
                    self.r.violations.append(
                        f"key consumed more than once across a "
                        f"{name} boundary in {fn} at "
                        f"{os.path.basename(f)}:{ln}")
            # loop bodies run once per iteration: a key that enters as a
            # loop CONSTANT and is consumed inside is consumed every
            # iteration (only fold_in-then-split derivation is legal
            # there), and a carry key returned unchanged after being
            # consumed is cross-iteration reuse
            if name == "scan":
                nc = int(eqn.params.get("num_consts", 0))
                ncar = int(eqn.params.get("num_carry", 0))
                self._loop_reuse(eqn, name, inv[:nc], sub_consumed)
                self._carry_reuse(eqn, name, inv[nc:nc + ncar],
                                  sub.outvars[:ncar], sub_consumed)
            elif name == "while" and len(sub.outvars) == len(inv):
                self._carry_reuse(eqn, name, inv, sub.outvars,
                                  sub_consumed)
            if out_states is None:
                out_states = outs
            else:
                # cond branches: "post" dominates, else deeper fold
                merged = []
                for a, b in zip(out_states, outs):
                    if a == b:
                        merged.append(a)
                    elif a is None:
                        merged.append(b)
                    elif b is None:
                        merged.append(a)
                    elif a[0] == "post" or b[0] == "post":
                        merged.append(("post",))
                    else:
                        merged.append(("pre", max(a[1], b[1])))
                out_states = merged
        for ov, n in branch_charges.items():
            consumed[ov] = consumed.get(ov, 0) + n
            if consumed[ov] > 1:
                f, ln, fn = source_of(eqn)
                self.r.violations.append(
                    f"key consumed more than once across a {name} "
                    f"boundary in {fn} at {os.path.basename(f)}:{ln}")
        for o, st in zip(eqn.outvars, out_states or []):
            if st is not None and _is_key_aval(getattr(o, "aval", None)):
                state[o] = st

    def _loop_reuse(self, eqn, name, const_slots, sub_consumed):
        for bv in const_slots:
            if sub_consumed.get(bv):
                f, ln, fn = source_of(eqn)
                self.r.violations.append(
                    f"key entering a {name} body as a loop constant is "
                    f"split/drawn from inside the body — consumed every "
                    f"iteration ({fn} at {os.path.basename(f)}:{ln}); "
                    "derive per-iteration keys with fold_in instead")

    def _carry_reuse(self, eqn, name, carry_in, carry_out, sub_consumed):
        for cin, cout in zip(carry_in, carry_out):
            if cout is cin and sub_consumed.get(cin):
                f, ln, fn = source_of(eqn)
                self.r.violations.append(
                    f"{name} carry key consumed inside the body but "
                    f"passed through unchanged — reused next iteration "
                    f"({fn} at {os.path.basename(f)}:{ln})")


def audit_keys(closed_jaxpr) -> KeyReport:
    """Run the lineage machine over the whole program."""
    report = KeyReport(violations=[])
    jaxpr = closed_jaxpr.jaxpr
    state, consumed = {}, {}
    for v in jaxpr.invars:
        if _is_key_aval(getattr(v, "aval", None)):
            state[v] = ("pre", 0)
            report.n_roots += 1
    _Walker(report).walk(jaxpr, state, consumed)
    report.fold_depths_at_split = sorted(set(
        report.fold_depths_at_split))
    return report


def check_policy(report: KeyReport, policy: dict):
    """Contract assertions over a :class:`KeyReport`; returns a list of
    violation strings.  Recognized policy keys:

    - ``fold_depths_at_split``: exact sorted list of distinct fold
      depths observed at split sites (the chunk contract pins ``[2]``:
      iteration then chain).
    - ``max_in_trace_roots``: cap on keys seeded/wrapped inside the
      trace (0 = all randomness flows from the caller's key).
    - ``allow_pre_split_consume``: when false, ``random_bits`` straight
      off a fold chain (no split) is a violation.
    """
    out = list(report.violations)
    want = policy.get("fold_depths_at_split")
    if want is not None and list(report.fold_depths_at_split) != list(want):
        out.append(
            f"fold-depth policy mismatch: splits observed at depths "
            f"{report.fold_depths_at_split}, contract requires {want} "
            "(fold_in(fold_in(base_key, iteration), chain))")
    cap = policy.get("max_in_trace_roots")
    if cap is not None and report.n_in_trace_roots > cap:
        out.append(
            f"{report.n_in_trace_roots} key(s) seeded inside the trace "
            f"(contract allows {cap}) — in-trace random_seed/random_wrap "
            "breaks the resume key-fold policy")
    if not policy.get("allow_pre_split_consume", True) \
            and report.pre_split_consumes:
        out.append(
            f"{report.pre_split_consumes} random_bits draw(s) straight "
            "off the fold chain without a split — draws must come from "
            "split subkeys")
    return out
