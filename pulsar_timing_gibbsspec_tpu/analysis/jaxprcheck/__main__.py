"""CLI: audit the committed contracts, ratchet against the baseline.

Usage::

    python -m pulsar_timing_gibbsspec_tpu.analysis.jaxprcheck [opts]

    --fast             only contracts marked "fast": true (the CI/lint
                       subset — traces small entries on CPU in seconds)
    --contracts DIR    contract directory (default <repo>/contracts)
    --json             machine-readable facts + violations on stdout
    --baseline PATH    ratchet file (default <repo>/jaxprcheck_baseline.json)
    --no-baseline      report every violation, ignore the ratchet
    --write-baseline   accept current violations as the new baseline

Exit status 1 when violations beyond the baseline exist (or any at all
with ``--no-baseline``).  Everything here is host-side tracing and AOT
lowering on the CPU backend — nothing executes on a device, so the
audit is safe in CI and on login nodes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[3]


def _bootstrap_cpu():
    """Force the CPU backend with enough host devices for the sharded
    entries, before any backend initializes."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxprcheck",
        description="jaxpr/HLO-level contract auditor (HBM, collectives, "
                    "dtypes, key lineage, donation) — static, no device")
    ap.add_argument("--fast", action="store_true",
                    help="only contracts marked fast")
    ap.add_argument("--contracts", default=None, metavar="DIR")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--baseline",
                    default=str(_REPO_ROOT / "jaxprcheck_baseline.json"))
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    args = ap.parse_args(argv)

    _bootstrap_cpu()

    from ..baseline import (compare_to_baseline, load_baseline,
                            write_baseline)
    from .runner import (check_contract_coverage, discover_contracts,
                         run_contracts)

    contracts = discover_contracts(args.contracts, fast_only=args.fast)
    if not contracts:
        print("jaxprcheck: no contracts found", file=sys.stderr)
        return 2
    violations, facts = run_contracts(contracts)
    if args.contracts is None:
        # committed contract dir only (a test pointing --contracts at a
        # fixture subset is not claiming repo-wide coverage); runs under
        # --fast too — coverage enumerates all contracts either way
        violations.extend(check_contract_coverage())

    if args.write_baseline:
        write_baseline(args.baseline, violations, _REPO_ROOT)
        print(f"jaxprcheck: baseline written to {args.baseline} "
              f"({len(violations)} violation(s))")
        return 0

    if args.no_baseline:
        new, stale = list(violations), []
    else:
        new, stale = compare_to_baseline(
            violations, load_baseline(args.baseline), _REPO_ROOT)

    if args.as_json:
        print(json.dumps(
            {"contracts": [c.get("name") for c in contracts],
             "facts": facts,
             "violations": [
                 {"path": v.path, "rule": v.rule, "message": v.message}
                 for v in violations],
             "new": len(new)}, indent=2, sort_keys=True))
    else:
        for v in new:
            print(str(v))
        for f, rule, base, cur in stale:
            print(f"stale baseline entry: {f} [{rule}] baseline {base} "
                  f"> current {cur}; ratchet the baseline down")
        ok = "OK" if not new else "FAIL"
        print(f"jaxprcheck: {len(contracts)} contract(s), "
              f"{len(violations)} violation(s), {len(new)} new — {ok}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
