"""Declared trace entry points for contract audits.

A contract names an *entry* — a function + abstract example arguments
— and jaxprcheck traces it on the CPU backend, no device execution.
Entries are built from synthetic pulsars (no file IO) so the audit is
reproducible anywhere; the bench-scale gram entry (45 pulsars, 720
TOAs, 17 timing-model columns) reproduces the r4 exact-Gram geometry
whose accumulation scratch is the C=128 HBM wall.

Entry kinds (the ``entry`` field of a contract):

- ``gram`` — the vmapped exact b-draw alone
  (:func:`..sampler.jax_backend.gram_trace_entry`): the C1 calibration
  target.
- ``chunk`` — a full compiled sweep chunk through the driver
  (:func:`..sampler.jax_backend.sweep_chunk_entry`): key lineage,
  dtype islands, donation.
- ``kernel_chunk`` — the ``chunk`` entry traced with the fused Pallas
  kernel tier forced on (``settings.kernel_tier="pallas"``): pins that
  the fused lowering preserves donation, dtype census and key policy,
  plus the grid-scaled kernel cost (``crn_kernels``).
- ``hd_chunk`` — the same chunk under a Hellings-Downs ORF: the
  structured joint b-draw, its two-float kernels and the
  ``joint_mixed`` path (numcheck's ``numerics_hd_joint`` pin).
- ``megachunk`` — the device-resident mega-chunk steady dispatch
  (:func:`..sampler.jax_backend.megachunk_sweep_chunk_entry`): the
  ``chunk`` program scanned ``megachunk`` sub-chunks deep, carries
  donated end-to-end, key-fold policy and dtype census pinned
  identical to the legacy chunk (``crn_megachunk``).
- ``sharded_step`` — one CRN sweep step under pulsar-axis sharding on
  a host-device mesh (mirrors the MULTICHIP dry-run): the C2 census
  target.
- ``sharded_2d`` — the same step vmapped over chains on a 2-d
  ``(chain, pulsar)`` mesh, carries chain-sharded: its census pinned
  byte-identical to ``sharded_step``'s proves the chain axis is
  collective-free (``crn_2d_mesh``).
- ``serve_mux`` — the routed multiplexed steady chunk of the serving
  layer: >= 3 heterogeneous synthetic datasets snapped into ONE bucket,
  grafted onto one static box, stacked, and traced as one program.  The
  entry *raises* (-> an ``error`` violation) when routing diverges, the
  cache fails to warm-hit, or the stacked pytree's treedef/avals drift
  — the static zero-retrace contract (``serve_buckets``).
- ``serve_placement`` — the multiplexed steady chunk of ONE placement
  slice, carries committed on the slice's carved chain submesh of a
  2-d mesh (``serve_placement``).  Host assertions pin the carving
  invariants (disjoint device sets, per-slice slots divisibility,
  both groups route); ``isolate_axis`` on the traced program proves
  tenant rows never talk across the chain axis — a device loss on the
  neighboring slice cannot perturb this slice's streams at the SPMD
  level.
"""

from __future__ import annotations

import numpy as np


def synthetic_pulsars(n_psr, ntoa, tm_cols=3, seed=0):
    """Self-contained synthetic pulsars; ``tm_cols`` polynomial
    timing-model columns (the bench dataset has 17-wide design
    matrices, the quick entries keep 3)."""
    from ...data.dataset import Pulsar

    rng = np.random.default_rng(seed)
    out = []
    for ii in range(int(n_psr)):
        span = 10.0 * 365.25 * 86400.0
        toas = np.sort(rng.uniform(0.0, span, ntoa)) + 53000.0 * 86400.0
        t = (toas - toas.mean()) / span
        M = np.column_stack([t ** k for k in range(int(tm_cols))])
        th = rng.uniform(0, np.pi)
        ph = rng.uniform(0, 2 * np.pi)
        out.append(Pulsar(
            name=f"FAKE{ii:02d}",
            toas=toas, toaerrs=np.full(ntoa, 1e-6),
            residuals=1e-7 * rng.standard_normal(ntoa),
            freqs=np.full(ntoa, 1400.0),
            backend_flags=np.asarray(["sim"] * ntoa, dtype=object),
            Mmat=M, fitpars=[f"TM{k}" for k in range(int(tm_cols))],
            pos=np.array([np.sin(th) * np.cos(ph),
                          np.sin(th) * np.sin(ph), np.cos(th)]),
        ))
    return out


def build_model(psrs, nmodes, red=True, orf=None):
    """The CRN free-spectrum model the MULTICHIP/bench entries audit;
    ``orf`` switches the common block to a correlated ORF (``"hd"``
    exercises the structured joint b-draw and its two-float kernels)."""
    from ...models.factory import model_general

    return model_general(
        psrs, tm_svd=True, white_vary=True,
        common_psd="spectrum", common_components=int(nmodes),
        red_var=red, red_psd="spectrum", red_components=int(nmodes),
        orf=orf or "crn")


def _gram_entry(spec):
    from ...sampler import jax_backend as jb
    from ...sampler.compiled import compile_pta

    psrs = synthetic_pulsars(spec.get("n_psr", 45), spec.get("ntoa", 720),
                             tm_cols=spec.get("tm_cols", 17),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 10))
    cm = compile_pta(pta)
    fn, args = jb.gram_trace_entry(cm, spec.get("nchains", 64))
    return fn, args, {}


def _chunk_entry(spec):
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    fn, args, drv = jb.sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0))
    return fn, args, {"driver": drv}


def _kernel_chunk_entry(spec):
    """The ``chunk`` entry traced with ``settings.kernel_tier`` forced
    to ``"pallas"``: the fused-kernel lowering of the steady sweep
    (``ops/kernels``), with the b-draw factor chain and the segmented
    Gram inside ``pallas_call`` bodies.  The contract
    (``crn_kernels``) pins that fusing changes NOTHING the other
    audits guard — donation, dtype census (the walkers descend into
    kernel jaxprs), key-fold policy — and pins the grid-scaled cost.
    The tier is a trace-time static, so the override wraps the traced
    function itself (jaxprcheck traces lazily, after this builder
    returns)."""
    from ...config import settings
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    fn, args, drv = jb.sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0))

    def forced(*a):
        prev = settings.kernel_tier
        settings.kernel_tier = "pallas"
        try:
            return fn(*a)
        finally:
            settings.kernel_tier = prev

    return forced, args, {"driver": drv}


def _hd_chunk_entry(spec):
    """The correlated-ORF (Hellings-Downs) steady chunk: the same
    driver path as ``chunk`` but through the structured joint b-draw —
    two-float Cholesky/matmul kernels, Schur block grid, the
    ``joint_mixed`` guard.  The numcheck contract
    (``numerics_hd_joint``) pins this program's precision topology."""
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3),
                      orf=spec.get("orf", "hd"))
    fn, args, drv = jb.sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0))
    return fn, args, {"driver": drv}


def _megachunk_entry(spec):
    """The mega-chunk steady dispatch: the ``chunk`` entry's program
    scanned ``megachunk`` sub-chunks deep in one jitted function.  The
    contract (``crn_megachunk``) pins the end-to-end carry donation, the
    unchanged per-sweep key-fold policy (the static half of the bitwise
    grid-independence proof) and the slab-bounded output surface."""
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    fn, args, drv = jb.megachunk_sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        megachunk=spec.get("megachunk", 3),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0))
    return fn, args, {"driver": drv}


def _obs_chunk_entry(spec):
    """The obs-instrumented steady chunk: same synthetic model as
    ``chunk``, driver built with ``obs=True`` so the streaming
    diagnostic sketch (obs/sketch.py) rides the scan.  The contract
    (``obs_quick``) pins that instrumentation adds zero collectives,
    keeps key lineage and donation intact, and bounds the total output
    bytes to the summary slab."""
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    fn, args, drv = jb.obs_sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0))
    return fn, args, {"driver": drv}


def _sharded_step_entry(spec):
    """Mirror of the MULTICHIP dry-run step: pad + shard the compiled
    model over a 1-d host-device mesh, trace one CRN sweep step."""
    import jax.numpy as jnp
    import jax.random as jr

    from ...parallel.sharding import make_mesh, shard_compiled
    from ...sampler import jax_backend as jb
    from ...sampler.compiled import compile_pta

    n_dev = int(spec.get("devices", 8))
    psrs = synthetic_pulsars(spec.get("n_psr", 15), spec.get("ntoa", 24),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    pad = spec.get("pad_pulsars", -(-len(psrs) // n_dev) * n_dev)
    cm = compile_pta(pta, pad_pulsars=pad)
    cm = shard_compiled(cm, make_mesh(n_dev))

    # CompiledPTA rides as a jit ARGUMENT: closure-captured jax.Arrays
    # lower as replicated constants and GSPMD drops their shardings
    # (zero collectives — the dry-run measured it); only argument
    # shardings reach the partitioner
    def step(cm_, x, b, key):
        return jb.sharded_sweep_step(cm_, x, b, key)

    x0 = jnp.asarray(pta.initial_sample(np.random.default_rng(0)),
                     cm.cdtype)
    b0 = jnp.zeros((cm.P, cm.Bmax), cm.cdtype)
    return step, (cm, x0, b0, jr.key(0)), {}


def _sharded_2d_entry(spec):
    """2-d ``(chain, pulsar)`` mesh mirror of the MULTICHIP dry-run:
    the compiled model pulsar-sharded over the LAST mesh axis, the
    vmapped chain carry (x, b, per-chain keys) chain-sharded over the
    first, one CRN sweep step per chain.

    The chain axis must add ZERO collectives — chains are independent
    Gibbs processes (per-chain ``fold_in`` streams, no cross-chain
    term anywhere in the sweep) — so the census of this entry is
    pinned byte-identical to the 1-d ``sharded_step`` census
    (``crn_multichip``): equality of the two censuses IS the
    zero-chain-collectives check, measured, not asserted."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ...parallel.sharding import (chain_sharding, make_mesh,
                                      shard_compiled)
    from ...sampler import jax_backend as jb
    from ...sampler.compiled import compile_pta

    shape = tuple(int(s) for s in spec.get("mesh", (2, 4)))
    C = int(spec.get("nchains", 4))
    psrs = synthetic_pulsars(spec.get("n_psr", 15), spec.get("ntoa", 24),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    n_psr_dev = shape[1]
    pad = spec.get("pad_pulsars",
                   -(-len(psrs) // n_psr_dev) * n_psr_dev)
    cm = compile_pta(pta, pad_pulsars=pad)
    mesh = make_mesh(shape)
    cm = shard_compiled(cm, mesh)

    # cm rides as a jit ARGUMENT (closure constants lose shardings);
    # the chain carries are committed with chain_sharding so the
    # partitioner sees the 2-d placement the production driver stages
    def step(cm_, x, b, keys):
        return jax.vmap(
            lambda xx, bb, kk: jb.sharded_sweep_step(cm_, xx, bb, kk)
        )(x, b, keys)

    x0 = jnp.tile(jnp.asarray(
        pta.initial_sample(np.random.default_rng(0)), cm.cdtype), (C, 1))
    b0 = jnp.zeros((C, cm.P, cm.Bmax), cm.cdtype)
    keys = jr.split(jr.key(spec.get("seed", 0)), C)
    x0 = jax.device_put(x0, chain_sharding(mesh, x0.ndim))
    b0 = jax.device_put(b0, chain_sharding(mesh, b0.ndim))
    keys = jax.device_put(keys, chain_sharding(mesh, keys.ndim))
    return step, (cm, x0, b0, keys), {}


def _serve_mux_entry(spec):
    """Routed multiplexed chunk over heterogeneous datasets sharing one
    bucket.  Every condition the serving layer's zero-retrace guarantee
    rests on is asserted host-side before the trace: same routed
    bucket, warm cache hits after the first admission, one treedef and
    identical leaf avals across the stack."""
    import jax.numpy as jnp
    import jax.random as jr

    from ...serve.buckets import BucketSpec, BucketTable
    from ...serve.engine import (ProgramCache, compile_bucket, mux_body,
                                 stack_cms)

    ntoas = [int(n) for n in spec.get("ntoas", (24, 30, 36))]
    if len(ntoas) < 3:
        raise ValueError("serve_mux needs >= 3 heterogeneous datasets")
    bucket = BucketSpec(*spec.get("bucket", (2, 40, 24, 3)))
    table = BucketTable([bucket])
    cache = ProgramCache()
    cms = []
    for i, ntoa in enumerate(ntoas):
        pta = build_model(
            synthetic_pulsars(spec.get("n_psr", 2), ntoa,
                              tm_cols=spec.get("tm_cols", 3), seed=i),
            spec.get("nmodes", 3))
        routed = table.route_pta(pta)
        if routed != bucket:
            raise AssertionError(
                f"dataset {i} (ntoa={ntoa}) routed to {routed}, "
                f"not the shared bucket {bucket}")
        cm, warm = cache.adopt(routed, compile_bucket(pta, routed))
        if warm != (i > 0):
            raise AssertionError(
                f"program cache {'missed' if i else 'hit'} on dataset "
                f"{i} — the box graft no longer deduplicates")
        cms.append(cm)
    stack = stack_cms(cms)      # raises SignatureMismatch on aval drift
    T, cm0 = len(cms), cms[0]
    x = jnp.zeros((T, cm0.nx), cm0.cdtype)
    b = jnp.zeros((T, cm0.P, cm0.Bmax), cm0.cdtype)
    tkeys = jr.split(jr.key(spec.get("seed", 0)), T)
    it0 = jnp.ones((T,), jnp.int32)
    return mux_body(spec.get("chunk", 2)), (stack, x, b, tkeys, it0), {}


def _serve_placement_entry(spec):
    """One placement slice's multiplexed steady chunk on its carved
    chain submesh.  Builds the full placement geometry host-side — a
    2-d parent mesh carved into two disjoint chain-span slices hosting
    two DIFFERENT buckets with different slot counts — and asserts the
    carving invariants (disjoint device sets, per-slice divisibility,
    distinct routed buckets, warm cache behavior) before tracing the
    second slice's program with carries committed on ITS submesh.  The
    contract's ``isolate_axis`` then proves the traced program moves
    nothing across the chain (tenant) axis: slices share no devices
    AND no slice's program could use a cross-row collective even if
    they did."""
    import jax.numpy as jnp
    import jax.random as jr

    from ...parallel.sharding import (carve_chain_slices,
                                      chain_submesh_size, make_mesh,
                                      shard_carry)
    from ...serve.buckets import BucketSpec, BucketTable
    from ...serve.engine import (ProgramCache, compile_bucket, mux_body,
                                 stack_cms)

    shape = tuple(int(s) for s in spec.get("mesh", (4, 2)))
    mesh = make_mesh(shape)
    spans = [int(s) for s in spec.get("spans", (2, 2))]
    slots = [int(s) for s in spec.get("slots", (2, 4))]
    subs = carve_chain_slices(mesh, spans)
    devsets = [set(d.id for d in sub.devices.flat) for sub in subs]
    for i in range(len(subs)):
        for j in range(i + 1, len(subs)):
            if devsets[i] & devsets[j]:
                raise AssertionError(
                    f"slices {i} and {j} share devices "
                    f"{sorted(devsets[i] & devsets[j])} — fault "
                    "domains must be disjoint")
    for i, sub in enumerate(subs):
        nc = chain_submesh_size(sub)
        if slots[i] % nc:
            raise AssertionError(
                f"slice {i}: slots={slots[i]} does not divide over "
                f"its {nc} chain rows")
    bspecs = [BucketSpec(*b) for b in
              spec.get("buckets", ((2, 40, 24, 3), (2, 48, 24, 3)))]
    table = BucketTable(bspecs)
    cache = ProgramCache()
    # group A occupies slice 0 (compiled + adopted, never traced here);
    # group B's stack is the traced program, on slice 1's submesh
    groups = []
    for g, (bucket, T) in enumerate(zip(bspecs, slots)):
        cms = []
        for i in range(T):
            # shapes sit strictly inside this bucket but past the next
            # smaller one, so route_pta (smallest cover wins) keeps the
            # groups on their own buckets
            ntoa = bucket.toas - 2 - 4 * (i % 2)
            pta = build_model(
                synthetic_pulsars(spec.get("n_psr", 2), ntoa,
                                  tm_cols=spec.get("tm_cols", 3),
                                  seed=10 * g + i),
                spec.get("nmodes", 3))
            routed = table.route_pta(pta)
            if routed != bucket:
                raise AssertionError(
                    f"group {g} dataset {i} routed to {routed}, not "
                    f"its own bucket {bucket} — groups must stay "
                    "disjoint")
            cm, warm = cache.adopt(routed, compile_bucket(pta, routed))
            if warm != (i > 0):
                raise AssertionError(
                    f"group {g} dataset {i}: cache "
                    f"{'missed' if i else 'hit'} — per-group grafting "
                    "broke")
            cms.append(cm)
        groups.append(cms)
    cms = groups[1]
    stack = stack_cms(cms)
    T, cm0 = len(cms), cms[0]
    x = jnp.zeros((T, cm0.nx), cm0.cdtype)
    b = jnp.zeros((T, cm0.P, cm0.Bmax), cm0.cdtype)
    tkeys = jr.split(jr.key(spec.get("seed", 0)), T)
    it0 = jnp.ones((T,), jnp.int32)
    x, b, tkeys = shard_carry(subs[1], (x, b, tkeys), T)
    return mux_body(spec.get("chunk", 2)), (stack, x, b, tkeys, it0), {}


def _ensemble_chunk_entry(spec):
    """The ensemble-mixing steady chunk (``crn_ensemble``): same
    synthetic CRN model as ``chunk``, driver built with ``ensemble=True``
    (+ a tempering ladder), optionally staged on a 2-d (chain, pulsar)
    mesh so the chain-axis collective allowlist is audited against the
    production placement."""
    from ...sampler import jax_backend as jb

    psrs = synthetic_pulsars(spec.get("n_psr", 3), spec.get("ntoa", 40),
                             tm_cols=spec.get("tm_cols", 3),
                             seed=spec.get("seed", 0))
    pta = build_model(psrs, spec.get("nmodes", 3))
    fn, args, drv = jb.ensemble_sweep_chunk_entry(
        pta, spec.get("nchains", 4), chunk=spec.get("chunk", 2),
        pad_pulsars=spec.get("pad_pulsars"), seed=spec.get("seed", 0),
        pt_ladder=spec.get("pt_ladder", 1), mesh=spec.get("mesh"))
    return fn, args, {"driver": drv}


_ENTRIES = {"gram": _gram_entry, "chunk": _chunk_entry,
            "kernel_chunk": _kernel_chunk_entry,
            "hd_chunk": _hd_chunk_entry,
            "megachunk": _megachunk_entry,
            "obs_chunk": _obs_chunk_entry,
            "sharded_step": _sharded_step_entry,
            "sharded_2d": _sharded_2d_entry,
            "ensemble_chunk": _ensemble_chunk_entry,
            "serve_mux": _serve_mux_entry,
            "serve_placement": _serve_placement_entry}


def resolve_entry(spec: dict):
    """``(fn, example_args, extras)`` for a contract's entry spec.
    ``extras`` may carry the live driver (``chunk``) for donation
    checks."""
    kind = spec.get("entry")
    if kind not in _ENTRIES:
        raise KeyError(
            f"unknown entry {kind!r}; known: {sorted(_ENTRIES)}")
    return _ENTRIES[kind](spec)
