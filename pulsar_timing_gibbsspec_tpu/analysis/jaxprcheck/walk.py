"""Jaxpr traversal and the TPU tile-padding size model.

The traversal is generic over call-like primitives: any equation
parameter holding a (Closed)Jaxpr — ``pjit``'s ``jaxpr``, ``scan``'s
``jaxpr``, ``cond``'s ``branches``, ``while``'s ``body_jaxpr`` /
``cond_jaxpr``, custom-derivative wrappers — is recursed into, so
auditors see every equation of the whole program.

The size model is the (sublane, lane) tile padding of TPU vector
memory: the minor-most dimension pads to 128 lanes and the
second-minor to the dtype's sublane count (8 for 4/8-byte, 16 for
2-byte, 32 for 1-byte elements); rank-1 arrays pad the single axis to
128.  Calibrated against the r4 HBM measurement of the exact-Gram
accumulation scratch: the model reproduces the README's 3.4x pad ratio
and 15.8 GiB at C=128 to <1% (tests/test_jaxprcheck.py pins both).
"""

from __future__ import annotations

import math

import numpy as np

#: lane (minor-most) tile width — fixed across dtypes
LANE = 128

#: sublane (second-minor) tile height by element size in bytes
_SUBLANE = {1: 32, 2: 16}          # default 8 for 4- and 8-byte elements


def _itemsize(dtype) -> int:
    """Element size in bytes; typed PRNG keys count their data words
    (threefry: 2 x uint32 = 8 bytes)."""
    try:
        import jax

        if jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key):
            return 8
    except Exception:
        pass
    return int(np.dtype(dtype).itemsize)


def tile_padded_bytes(shape, dtype) -> int:
    """Bytes the TPU tiler allocates for an array of ``shape``/``dtype``
    once minor dims are padded to the (sublane, LANE) tile."""
    item = _itemsize(dtype)
    shape = tuple(int(s) for s in shape)
    if not shape:
        return item
    sub = _SUBLANE.get(item, 8)
    minor = math.ceil(shape[-1] / LANE) * LANE
    if len(shape) == 1:
        return minor * item
    sublane = math.ceil(shape[-2] / sub) * sub
    lead = 1
    for s in shape[:-2]:
        lead *= s
    return lead * sublane * minor * item


def aval_bytes(aval) -> int:
    """Tile-padded bytes of an abstract value (0 for non-array avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return tile_padded_bytes(shape, dtype)


def _as_jaxpr(v):
    import jax

    if isinstance(v, jax.core.ClosedJaxpr):
        return v.jaxpr
    if isinstance(v, jax.core.Jaxpr):
        return v
    return None


def subjaxprs(eqn):
    """Every (open) sub-jaxpr held in ``eqn.params`` — call-like
    primitives (pjit, scan, cond, while, custom_*) all store their
    bodies there."""
    out = []
    for val in eqn.params.values():
        if isinstance(val, (tuple, list)):
            for v in val:
                j = _as_jaxpr(v)
                if j is not None:
                    out.append(j)
        else:
            j = _as_jaxpr(val)
            if j is not None:
                out.append(j)
    return out


def iter_eqns(jaxpr, depth=0):
    """Yield ``(eqn, depth)`` over ``jaxpr`` and every nested sub-jaxpr
    (pre-order; depth counts call-primitive nesting)."""
    for eqn in jaxpr.eqns:
        yield eqn, depth
        for sub in subjaxprs(eqn):
            yield from iter_eqns(sub, depth + 1)


#: path fragment marking frames that belong to this repository — dots
#: emitted from inside jax library helpers (cho_solve's ``_mm`` etc.)
#: attribute to the repo call site, not the library internals
_REPO_FRAGMENT = "pulsar_timing_gibbsspec_tpu"

#: ...but never to the auditor itself (its trace wrapper is a repo
#: frame on every equation's stack)
_SELF_FRAGMENT = "analysis" + "/" + "jaxprcheck"


def source_of(eqn):
    """``(file_name, line, function_name)`` of the frame that emitted
    ``eqn`` — the location a violation report points at.  Prefers the
    innermost frame inside this repository (excluding jaxprcheck's own
    tracing machinery); falls back to jax's notion of the user frame
    (so library-internal helpers attribute to the repo function that
    called them, and code outside the repo attributes to itself)."""
    try:
        from jax._src import source_info_util

        for frame in source_info_util.user_frames(eqn.source_info):
            f = frame.file_name.replace("\\", "/")
            if _REPO_FRAGMENT in f and _SELF_FRAGMENT not in f:
                return (frame.file_name, int(frame.start_line),
                        frame.function_name)
        frame = source_info_util.user_frame(eqn.source_info)
    except Exception:
        frame = None
    if frame is None:
        return ("<unknown>", 0, "<unknown>")
    return (frame.file_name, int(frame.start_line), frame.function_name)


def trace_jaxpr(fn, example_args):
    """Abstractly trace ``fn`` (jitted or plain) to a ClosedJaxpr —
    never executes: ``ShapeDtypeStruct`` arguments stay abstract and
    concrete example arrays are only read for shape/dtype."""
    import jax

    traced = jax.jit(fn).trace(*example_args)
    return traced.jaxpr
