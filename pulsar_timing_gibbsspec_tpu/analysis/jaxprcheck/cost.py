"""C6: static per-program FLOP + HBM-byte cost model.

Walks a jaxpr with per-primitive arithmetic rules (``dot_general`` from
its dimension numbers, ``cholesky`` n^3/3, solves n^2 m, elementwise
and reductions by element count) and charges HBM traffic as the
tile-padded bytes of every leaf equation's operands and results —
the same size model the C1 HBM check calibrated against the measured
exact-Gram scratch.  ``scan`` bodies multiply by ``length``; ``cond``
takes the widest branch; ``while`` counts one trip and records a note
(static analysis cannot bound the trip count).

The outputs feed the roofline attribution layer
(``profiling.block_cost_model``): FLOPs / time = achieved compute,
FLOPs / bytes = arithmetic intensity, compared against the device
ridge point to classify each Gibbs block compute- vs bandwidth-bound.
Byte counts are an upper bound — a fused program re-reads nothing,
while this model charges every equation's operands — so intensities
are conservative (a block the model already calls compute-bound truly
is).

Everything here is host-side tracing; nothing executes on a device.
"""

from __future__ import annotations

import dataclasses
import math

from .walk import aval_bytes, subjaxprs, trace_jaxpr

#: primitives costing ~1 flop per output element
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs",
    "sign", "floor", "ceil", "round", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "gt", "lt", "ge", "le", "eq", "ne", "select_n", "clamp",
    "gt_to", "lt_to", "ge_to", "le_to",     # total-order comparisons
    "add_any", "nextafter", "square",
    # transcendentals lower to short polynomial kernels; charging one
    # flop/element keeps the model dot-dominated and predictable
    "exp", "exp2", "log", "log1p", "expm1", "tanh", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "erf",
    "erfc", "erf_inv", "logistic", "sqrt", "rsqrt", "cbrt", "pow",
    "integer_pow", "digamma", "lgamma", "is_finite",
}

#: reductions cost ~1 flop per *input* element
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "cumsum", "cumprod", "cummax", "cummin", "reduce_precision",
}

#: counter-based PRNG kernels: ~this many integer ops per output word
RNG_FLOPS_PER_ELEM = 16
_RNG = {"threefry2x32", "random_bits", "random_seed", "random_fold_in",
        "random_split", "random_wrap", "random_unwrap", "random_gamma"}

#: data movement — zero flops, bytes only
_MOVEMENT = {
    "reshape", "transpose", "broadcast_in_dim", "convert_element_type",
    "slice", "dynamic_slice", "dynamic_update_slice", "gather",
    "scatter", "scatter-add", "concatenate", "pad", "iota", "copy",
    "squeeze", "rev", "device_put", "stop_gradient", "split",
    "bitcast_convert_type",
    "sharding_constraint", "all_gather", "all_to_all", "ppermute",
    "psum", "pbroadcast",
    # pallas kernel-body primitives: Ref reads/writes and the grid
    # index are movement/bookkeeping, not arithmetic
    "get", "swap", "addupdate", "program_id",
}


@dataclasses.dataclass
class CostReport:
    """Static cost facts for one (sub)program."""

    flops: float = 0.0        # all arithmetic, dot + non-dot
    dot_flops: float = 0.0    # dot_general multiply-adds only (2mnk)
    hbm_bytes: float = 0.0    # tile-padded operand+result traffic
    by_prim: dict = dataclasses.field(default_factory=dict)
    notes: list = dataclasses.field(default_factory=list)

    def _add(self, prim: str, flops: float, by: float, scale: float,
             is_dot: bool = False) -> None:
        self.flops += flops * scale
        self.hbm_bytes += by * scale
        if is_dot:
            self.dot_flops += flops * scale
        if flops:
            self.by_prim[prim] = self.by_prim.get(prim, 0.0) + flops * scale

    def _merge(self, sub: "CostReport", scale: float) -> None:
        self.flops += sub.flops * scale
        self.dot_flops += sub.dot_flops * scale
        self.hbm_bytes += sub.hbm_bytes * scale
        for k, v in sub.by_prim.items():
            self.by_prim[k] = self.by_prim.get(k, 0.0) + v * scale
        for n in sub.notes:
            if n not in self.notes:
                self.notes.append(n)

    def as_dict(self) -> dict:
        d = {"flops": self.flops, "dot_flops": self.dot_flops,
             "hbm_bytes": self.hbm_bytes,
             "intensity": (self.flops / self.hbm_bytes
                           if self.hbm_bytes else 0.0)}
        if self.notes:
            d["notes"] = list(self.notes)
        return d


def _shape(var):
    aval = getattr(var, "aval", None)
    return tuple(getattr(aval, "shape", ()) or ())


def _nelems(var) -> int:
    n = 1
    for s in _shape(var):
        n *= int(s)
    return n


def _dot_general_flops(eqn) -> float:
    """2 * batch * M * N * K from the dimension numbers."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    ls, rs = _shape(eqn.invars[0]), _shape(eqn.invars[1])
    k = math.prod(ls[i] for i in lc) or 1
    b = math.prod(ls[i] for i in lb) or 1
    m = math.prod(s for i, s in enumerate(ls) if i not in (*lc, *lb)) or 1
    n = math.prod(s for i, s in enumerate(rs) if i not in (*rc, *rb)) or 1
    return 2.0 * b * m * n * k


def _linalg_flops(name: str, eqn) -> float:
    a = _shape(eqn.invars[0])
    if len(a) < 2:
        return float(_nelems(eqn.invars[0]))
    n = int(a[-1])
    batch = math.prod(a[:-2]) or 1
    if name == "cholesky":
        return batch * n ** 3 / 3.0
    if name == "triangular_solve":
        # b is (..., n, m) (or transposed): n flops per rhs element
        bv = eqn.invars[1]
        return float(_nelems(bv)) * n
    if name in ("lu", "qr", "eigh", "svd", "getrf"):
        return batch * 2.0 * n ** 3
    return float(_nelems(eqn.invars[0]))


def _leaf_bytes(eqn) -> float:
    by = 0.0
    for v in (*eqn.invars, *eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            by += aval_bytes(aval)
    return by


_LINALG = {"cholesky", "triangular_solve", "lu", "qr", "eigh", "svd",
           "getrf"}


def jaxpr_cost(jaxpr, _scale: float = 1.0) -> CostReport:
    """Cost a (Closed)Jaxpr.  Control flow: ``scan`` multiplies its body
    by ``length``; ``cond`` takes the most expensive branch; ``while``
    counts one body trip and notes the unbounded count."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)   # ClosedJaxpr -> Jaxpr
    rep = CostReport()
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if name == "scan":
            length = float(eqn.params.get("length", 1))
            sub = jaxpr_cost(eqn.params["jaxpr"])
            rep._merge(sub, length)
        elif name == "while":
            rep._merge(jaxpr_cost(eqn.params["body_jaxpr"]), 1.0)
            rep._merge(jaxpr_cost(eqn.params["cond_jaxpr"]), 1.0)
            if "while:trip_count_unknown" not in rep.notes:
                rep.notes.append("while:trip_count_unknown")
        elif name == "cond":
            branches = [jaxpr_cost(b) for b in eqn.params["branches"]]
            if branches:
                rep._merge(max(branches, key=lambda r: r.flops), 1.0)
        elif name == "pallas_call":
            # The kernel body runs once per grid step, so its cost
            # scales by the grid product (the fused chol kernel has an
            # empty grid -> x1; the Gram accumulator's grid is the
            # segment axis -> x nseg; vmap adds the chain axis to the
            # grid, scaling both).  The body's per-block operand bytes
            # times the grid steps IS the streamed HBM traffic, so no
            # separate outer-operand charge (which would double-count
            # the fused kernel's single round-trip).
            grid = tuple(getattr(eqn.params.get("grid_mapping"), "grid",
                                 ()) or ())
            scale = 1.0
            for g in grid:
                scale *= float(g)
            rep._merge(jaxpr_cost(eqn.params["jaxpr"]), scale)
        else:
            subs = subjaxprs(eqn)
            if subs:                      # pjit / custom_* / remat …
                for sub in subs:
                    rep._merge(jaxpr_cost(sub), 1.0)
                continue
            by = _leaf_bytes(eqn)
            if name == "dot_general":
                rep._add(name, _dot_general_flops(eqn), by, 1.0,
                         is_dot=True)
            elif name in _LINALG:
                rep._add(name, _linalg_flops(name, eqn), by, 1.0)
            elif name in _ELEMENTWISE:
                out_elems = sum(_nelems(v) for v in eqn.outvars)
                rep._add(name, float(out_elems), by, 1.0)
            elif name in _REDUCTIONS:
                in_elems = sum(_nelems(v) for v in eqn.invars)
                rep._add(name, float(in_elems), by, 1.0)
            elif name in _RNG:
                out_elems = sum(_nelems(v) for v in eqn.outvars)
                rep._add(name, float(out_elems) * RNG_FLOPS_PER_ELEM,
                         by, 1.0)
            elif name in _MOVEMENT:
                rep._add(name, 0.0, by, 1.0)
            else:
                # unknown primitive: bytes only, flagged once
                rep._add(name, 0.0, by, 1.0)
                note = f"unmodeled:{name}"
                if note not in rep.notes:
                    rep.notes.append(note)
    if _scale != 1.0:
        scaled = CostReport()
        scaled._merge(rep, _scale)
        return scaled
    return rep


def cost_of(fn, example_args) -> CostReport:
    """Trace ``fn`` on example args (abstract — nothing runs) and cost
    the resulting program."""
    return jaxpr_cost(trace_jaxpr(fn, example_args))
