"""``jaxlint``: AST-based JAX/TPU-discipline analyzer.

The compiled Gibbs sweep rests on convention-only invariants that nothing
in the Python language enforces: PRNG keys are single-use, host NumPy must
not leak into traced code, the TWO_FLOAT f64-emulation contract
(``sampler/compiled.py``) forbids implicit dtypes in device allocations,
and jit boundaries must not retrace per sweep.  A silent violation of any
of these corrupts posteriors rather than crashing (the van Haasteren &
Vallisneri 2014 conditional draws must be exact), so the rules are
machine-checked here instead of reviewed by eye.

Rules
-----

- **R1 prng-key-reuse** — the same key variable is consumed by two
  ``jax.random.*`` draws with no intervening ``split``/``fold_in``/
  reassignment.  Tracked per function scope with linear statement flow
  (branches merge consumed-ness as a union; loop bodies are walked twice
  so cross-iteration reuse is caught).
- **R2 host-numpy-in-traced-code** — ``np.*`` calls (on non-constant
  arguments), ``.item()``/``.tolist()``, or ``float()`` applied to values
  inside *traced* functions: functions that are jit/vmap/pmap-decorated,
  wrapped at a call site (``jax.jit(jax.vmap(f))``), passed to
  ``lax.scan``/``cond``/``while_loop``/``fori_loop``/``switch``/``map``
  bodies, or (transitively) called by name from such a function in the
  same module.
- **R3 implicit-dtype-in-device-code** — ``jnp.zeros/ones/full/empty/
  asarray/array/eye/linspace/arange`` in traced code without an
  explicit dtype (keyword or positional) and without an immediate
  ``.astype(...)``: the TWO_FLOAT contract requires every device
  allocation to state its precision (``arange`` is the classic
  offender — its dtype flips int/float with the argument types).
- **R4 retrace-hazard** — (a) a ``jax.jit``-wrapped callable created and
  invoked in one expression (fresh jit cache entry — and so a fresh
  trace/compile — per call); (b) a Python scalar / dict literal passed
  positionally to a callable assigned from ``jax.jit(...)`` that declares
  no ``static_argnums``/``static_argnames`` (weak-type flips and literal
  retraces).
- **R5 tracer-leak-self-assign** — ``self.<attr> = ...`` inside a traced
  function body: the attribute captures a tracer that outlives the trace.
- **R6 debug-leftover** — ``jax.debug.print``/``jax.debug.breakpoint``/
  ``breakpoint()`` anywhere in library code.
- **R7 host-sync-leak** — operations inside traced code that force the
  tracer to a concrete host value, blocking dispatch (or raising a
  ``TracerBoolConversionError``): ``bool(...)``/``int(...)`` on a
  non-constant value, and ``if``/``while``/``assert``/``not`` applied
  directly to a ``jnp.*`` call result (implicit ``__bool__`` — use
  ``lax.cond``/``jnp.where`` instead).  Complements R2: R2 catches
  host *NumPy* leaking in, R7 catches traced values leaking *out*.

Suppression: a trailing ``# jaxlint: disable=R1`` (comma-separated rules,
or ``all``) on the violation's first source line suppresses it.
Pre-existing violations live in ``jaxlint_baseline.json`` (see
:mod:`.baseline`): new violations fail, the baseline only ratchets down.

The analyzer is purely syntactic — it never imports the code it checks —
so it is safe on modules with import-time side effects and needs no JAX
installation.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

RULES = {
    "R1": "prng-key-reuse",
    "R2": "host-numpy-in-traced-code",
    "R3": "implicit-dtype-in-device-code",
    "R4": "retrace-hazard",
    "R5": "tracer-leak-self-assign",
    "R6": "debug-leftover",
    "R7": "host-sync-leak",
}

_PRAGMA_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9,\s]+)")

#: jax transforms whose function argument becomes traced code
_TRACING_WRAPPERS = {
    "jax.jit", "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.jacfwd", "jax.jacrev", "jax.hessian",
    # bare names for un-importable contexts (fixtures, `from jax import *`)
    "jit", "vmap", "pmap",
}
#: control-flow primitives -> positions of their traced body arguments
_BODY_TAKERS = {
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.cond": (1, 2),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.switch": (1,),          # a list of branches
    "jax.lax.associative_scan": (0,),
    "jax.lax.custom_linear_solve": (0, 1),
}
#: jax.random functions that do NOT consume a key's single use
_KEY_NONCONSUMING = {"split", "fold_in", "key", "PRNGKey", "wrap_key_data",
                     "key_data", "clone", "key_impl"}
#: module basenames treated as jax.random when alias resolution fails
#: (e.g. ``self._jr.split`` in the driver)
_RANDOMISH_BASES = {"jr", "random", "jrandom"}

#: jnp constructors R3 checks, mapped to the positional index that counts
#: as an explicit dtype (None = keyword-only in practice)
_DTYPE_CTORS = {
    "zeros": 1, "ones": 1, "empty": 1, "full": 2,
    "asarray": 1, "array": 1, "eye": None, "linspace": None,
    # arange(start, stop, step, dtype): dtype is positional index 3;
    # without it the result dtype flips int/float with the arguments
    "arange": 3,
}
#: np attributes that are compile-time constants, not host-array leaks
_NP_CONST_ATTRS = {"pi", "e", "inf", "nan", "euler_gamma", "newaxis",
                   "float32", "float64", "int32", "int64", "uint32",
                   "bool_", "complex64", "complex128"}


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{RULES[self.rule]}] {self.msg}")


def _pragma_rules(line: str):
    m = _PRAGMA_RE.search(line)
    if not m:
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


class _Module:
    """One parsed module: alias map, parent links, traced-function set."""

    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.aliases = self._collect_aliases(tree)
        self.parents: dict = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.defs_by_name: dict[str, list] = {}
        self.all_defs: list = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
                self.all_defs.append(node)
            elif isinstance(node, ast.Lambda):
                self.all_defs.append(node)
        self.traced: set = set()
        self._mark_traced()

    # -- alias resolution ---------------------------------------------------

    @staticmethod
    def _collect_aliases(tree):
        """name -> dotted module path, from every import in the module
        (function-local imports included: this repo imports jax lazily)."""
        out = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        # canonical shorthand: numpy/jax.numpy/jax.random keep their
        # conventional spellings even if imported under other names
        canon = {}
        for name, target in out.items():
            canon[name] = target
        return canon

    def qualname(self, node) -> str | None:
        """Dotted name of an expression, alias-expanded ('jnp.zeros' ->
        'jax.numpy.zeros'); None for non-name expressions."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))

    # -- traced-function discovery ------------------------------------------

    def _mark_fn_arg(self, arg):
        """Mark a function-valued argument (Name / Lambda / nested wrap /
        list of branches) as traced."""
        if isinstance(arg, ast.Lambda):
            self.traced.add(arg)
        elif isinstance(arg, ast.Name):
            for d in self.defs_by_name.get(arg.id, []):
                self.traced.add(d)
        elif isinstance(arg, (ast.List, ast.Tuple)):
            for el in arg.elts:
                self._mark_fn_arg(el)
        elif isinstance(arg, ast.Call):
            q = self.qualname(arg.func)
            if q in _TRACING_WRAPPERS or q == "functools.partial" \
                    or q == "partial":
                for a in arg.args:
                    self._mark_fn_arg(a)

    def _mark_traced(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    q = self.qualname(target)
                    if q in _TRACING_WRAPPERS:
                        self.traced.add(node)
                    elif q in ("functools.partial", "partial") and \
                            isinstance(dec, ast.Call) and dec.args:
                        if self.qualname(dec.args[0]) in _TRACING_WRAPPERS:
                            self.traced.add(node)
            elif isinstance(node, ast.Call):
                q = self.qualname(node.func)
                if q in _TRACING_WRAPPERS:
                    for a in node.args:
                        self._mark_fn_arg(a)
                elif q in _BODY_TAKERS:
                    for pos in _BODY_TAKERS[q]:
                        if pos < len(node.args):
                            self._mark_fn_arg(node.args[pos])
        # transitive closure: a function called by name from traced code
        # runs under the same trace (the module-level kernels in
        # sampler/jax_backend.py are all reached this way)
        changed = True
        while changed:
            changed = False
            for fn in list(self.traced):
                body = fn.body if isinstance(body := fn.body, list) else [body]
                for stmt in body:
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Call) and \
                                isinstance(node.func, ast.Name):
                            for d in self.defs_by_name.get(node.func.id, []):
                                if d not in self.traced:
                                    self.traced.add(d)
                                    changed = True

    def traced_roots(self):
        """Traced defs whose enclosing function is not itself traced (so
        each traced subtree is visited exactly once)."""
        out = []
        for fn in self.traced:
            p = self.parents.get(fn)
            enclosed = False
            while p is not None:
                if p in self.traced:
                    enclosed = True
                    break
                p = self.parents.get(p)
            if not enclosed:
                out.append(fn)
        return out


# ===========================================================================
# rule implementations
# ===========================================================================

def _is_const_expr(node, mod: _Module) -> bool:
    """Compile-time-constant expression: safe as a host computation even
    inside traced code (XLA constant-folds it)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_const_expr(node.operand, mod)
    if isinstance(node, ast.BinOp):
        return (_is_const_expr(node.left, mod)
                and _is_const_expr(node.right, mod))
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_const_expr(e, mod) for e in node.elts)
    q = mod.qualname(node)
    if q and q.startswith("numpy."):
        return q.split(".", 1)[1] in _NP_CONST_ATTRS
    return False


def _np_call_name(node: ast.Call, mod: _Module) -> str | None:
    q = mod.qualname(node.func)
    if q and q.startswith("numpy.") and not q.startswith("numpy.random."):
        return q
    return None


def _jnp_call_name(node: ast.Call, mod: _Module) -> str | None:
    q = mod.qualname(node.func)
    if q and q.startswith("jax.numpy."):
        return q[len("jax.numpy."):]
    return None


def _rand_call(node: ast.Call, mod: _Module) -> str | None:
    """jax.random function name if this call is (or plausibly is) one."""
    q = mod.qualname(node.func)
    if q is None:
        return None
    if q.startswith("jax.random."):
        return q[len("jax.random."):]
    head, _, fn = q.rpartition(".")
    if head and head.split(".")[-1] in _RANDOMISH_BASES:
        return fn
    return None


class _Rule1KeyScan:
    """Linear-flow key-consumption tracking within one function scope."""

    def __init__(self, mod: _Module, report):
        self.mod = mod
        self.report = report
        self.state: dict[str, bool] = {}

    @staticmethod
    def _terminates(stmts) -> bool:
        """Whether a branch body unconditionally leaves the join point."""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    @staticmethod
    def _token(node) -> str | None:
        if isinstance(node, (ast.Name, ast.Subscript, ast.Attribute)):
            try:
                return ast.unparse(node)
            except Exception:
                return None
        return None

    def _clear(self, token):
        self.state.pop(token, None)
        for t in [t for t in self.state
                  if t.startswith(token + "[") or t.startswith(token + ".")]:
            self.state.pop(t)

    def _clear_target(self, target):
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._clear_target(el)
        elif isinstance(target, ast.Starred):
            self._clear_target(target.value)
        else:
            tok = self._token(target)
            if tok:
                self._clear(tok)

    def _scan_expr(self, node):
        if node is None:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = _rand_call(sub, self.mod)
            if fn is None or not sub.args:
                continue
            tok = self._token(sub.args[0])
            if tok is None:
                continue
            if fn in _KEY_NONCONSUMING:
                if fn in ("split", "fold_in"):
                    self.state[tok] = False
                continue
            if self.state.get(tok):
                self.report(sub, "R1",
                            f"key '{tok}' consumed again by jax.random.{fn} "
                            "with no intervening split/reassignment")
            self.state[tok] = True

    def _walk(self, stmts):
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return                       # own scope, scanned separately
        if isinstance(s, ast.If):
            self._scan_expr(s.test)
            before = dict(self.state)
            self._walk(s.body)
            after_body = dict(self.state)
            body_exits = self._terminates(s.body)
            self.state = dict(before)
            self._walk(s.orelse)
            if body_exits:
                return          # only the else path reaches the join
            if self._terminates(s.orelse):
                self.state = after_body
                return
            # a key consumed on either branch may be consumed at the join
            for tok in set(after_body) | set(self.state):
                self.state[tok] = (after_body.get(tok, False)
                                   or self.state.get(tok, False))
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self._scan_expr(s.iter)
            self._clear_target(s.target)
            # two passes: a draw consuming a loop-invariant key is reuse on
            # the second iteration
            self._walk(s.body)
            self._clear_target(s.target)
            self._walk(s.body)
            self._walk(s.orelse)
        elif isinstance(s, ast.While):
            self._scan_expr(s.test)
            self._walk(s.body)
            self._walk(s.body)
            self._walk(s.orelse)
        elif isinstance(s, ast.Try):
            self._walk(s.body)
            for h in s.handlers:
                self._walk(h.body)
            self._walk(s.orelse)
            self._walk(s.finalbody)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._scan_expr(item.context_expr)
            self._walk(s.body)
        elif isinstance(s, ast.Assign):
            self._scan_expr(s.value)
            for t in s.targets:
                self._clear_target(t)
        elif isinstance(s, ast.AnnAssign):
            self._scan_expr(s.value)
            self._clear_target(s.target)
        elif isinstance(s, ast.AugAssign):
            self._scan_expr(s.value)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, (ast.expr,)):
                    self._scan_expr(child)

    def run(self, body):
        self.state = {}
        self._walk(body)


def _scan_traced_subtree(root, mod: _Module, report):
    """R2/R3/R5 over one traced function's subtree (nested defs included —
    they execute under the same trace)."""
    body = root.body if isinstance(root.body, list) else [root.body]
    for stmt in body:
        for node in ast.walk(stmt):
            # R5: stateful writes capture tracers beyond the trace
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        report(node, "R5",
                               f"assignment to self.{t.attr} inside traced "
                               "code leaks a tracer into host state")
            if not isinstance(node, ast.Call):
                continue
            # R2: host NumPy / host conversions on traced values
            npq = _np_call_name(node, mod)
            if npq is not None and not all(
                    _is_const_expr(a, mod) for a in node.args):
                report(node, "R2",
                       f"host call {npq}(...) on non-constant arguments "
                       "inside traced code")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("item", "tolist") and not node.args:
                report(node, "R2",
                       f".{node.func.attr}() forces a host transfer inside "
                       "traced code")
            if isinstance(node.func, ast.Name) and node.func.id == "float" \
                    and node.args and not _is_const_expr(node.args[0], mod):
                report(node, "R2",
                       "float(...) on a non-constant value inside traced "
                       "code")
            # R3: device allocations must state their dtype
            jname = _jnp_call_name(node, mod)
            if jname in _DTYPE_CTORS:
                has_kw = any(k.arg == "dtype" for k in node.keywords)
                pos = _DTYPE_CTORS[jname]
                has_pos = pos is not None and len(node.args) > pos
                parent = mod.parents.get(node)
                cast_away = (isinstance(parent, ast.Attribute)
                             and parent.attr == "astype")
                if not (has_kw or has_pos or cast_away):
                    report(node, "R3",
                           f"jnp.{jname}(...) without an explicit dtype in "
                           "device code (TWO_FLOAT contract: state the "
                           "precision)")


def _contains_jnp_call(node, mod: _Module) -> bool:
    """Whether an expression's value comes (at least partly) straight
    from a ``jnp.*`` call — the cheap syntactic proxy for "this is a
    traced array, not host state"."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _jnp_call_name(sub, mod):
            return True
    return False


def _scan_r7(root, mod: _Module, report):
    """Host-sync leaks in one traced subtree: explicit bool()/int()
    coercions and implicit truthiness tests of jnp expressions."""
    body = root.body if isinstance(root.body, list) else [root.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in ("bool", "int") and node.args and \
                    not _is_const_expr(node.args[0], mod):
                report(node, "R7",
                       f"{node.func.id}(...) on a non-constant value "
                       "inside traced code forces a host sync (or a "
                       "TracerBoolConversionError under jit)")
                continue
            test = None
            where = None
            if isinstance(node, (ast.If, ast.While)):
                test, where = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.Assert):
                test, where = node.test, "assert"
            elif isinstance(node, ast.UnaryOp) and \
                    isinstance(node.op, ast.Not):
                test, where = node.operand, "not"
            if test is not None and _contains_jnp_call(test, mod):
                report(node, "R7",
                       f"implicit bool() of a jnp expression in "
                       f"'{where}' inside traced code — a host sync "
                       "point; branch with lax.cond/jnp.where instead")


def _scan_r4(mod: _Module, report):
    """Retrace hazards, module-wide."""
    jitted: dict[str, bool] = {}   # call token -> has static argnums
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            q = mod.qualname(node.value.func)
            if q == "jax.jit" or q == "jit":
                static = any(k.arg in ("static_argnums", "static_argnames")
                             for k in node.value.keywords)
                for t in node.targets:
                    try:
                        jitted[ast.unparse(t)] = static
                    except Exception:
                        pass
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        # (a) immediately-invoked jit wrapper: a fresh cache entry per call
        if isinstance(node.func, ast.Call):
            q = mod.qualname(node.func.func)
            if q in ("jax.jit", "jit"):
                report(node, "R4",
                       "jax.jit(...) created and invoked in one "
                       "expression: a fresh trace/compile on every call")
        # (b) literal scalars/dicts into a jitted callable
        try:
            tok = ast.unparse(node.func)
        except Exception:
            continue
        if tok in jitted and not jitted[tok]:
            for a in node.args:
                bad = (isinstance(a, ast.Constant)
                       and a.value is not None
                       and not isinstance(a.value, bytes)) or \
                      isinstance(a, ast.Dict)
                if bad:
                    kind = "dict" if isinstance(a, ast.Dict) else "scalar"
                    report(a, "R4",
                           f"Python {kind} literal passed positionally to "
                           f"jitted callable '{tok}' without "
                           "static_argnums (weak-type/retrace hazard)")


def _scan_r6(mod: _Module, report):
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        q = mod.qualname(node.func)
        if q and q.startswith("jax.debug."):
            report(node, "R6", f"{q}(...) left in library code")
        elif isinstance(node.func, ast.Name) and \
                node.func.id == "breakpoint":
            report(node, "R6", "breakpoint() left in library code")


# ===========================================================================
# per-file / per-tree analysis
# ===========================================================================

def analyze_source(src: str, path: str = "<string>") -> list[Violation]:
    """All violations in one source string (pragmas applied)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [Violation(path, exc.lineno or 0, "R6",
                          f"file does not parse: {exc.msg}")]
    mod = _Module(tree, path)
    lines = src.splitlines()
    raw: list[Violation] = []
    seen = set()

    def report(node, rule, msg):
        key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
               rule)
        if key in seen:
            return
        seen.add(key)
        raw.append(Violation(path, getattr(node, "lineno", 0), rule, msg))

    # R1 over every function scope plus the module scope
    scopes = [(mod.tree.body,)] + [
        (d.body if isinstance(d.body, list) else [ast.Expr(d.body)],)
        for d in mod.all_defs]
    for (body,) in scopes:
        _Rule1KeyScan(mod, report).run(body)
    # R2/R3/R5/R7 over traced subtrees
    for root in mod.traced_roots():
        _scan_traced_subtree(root, mod, report)
        _scan_r7(root, mod, report)
    _scan_r4(mod, report)
    _scan_r6(mod, report)

    out = []
    for v in raw:
        line = lines[v.line - 1] if 0 < v.line <= len(lines) else ""
        disabled = _pragma_rules(line)
        if v.rule in disabled or "ALL" in disabled:
            continue
        out.append(v)
    out.sort(key=lambda v: (v.line, v.rule))
    return out


def analyze_file(path) -> list[Violation]:
    p = Path(path)
    return analyze_source(p.read_text(), str(p))


def iter_py_files(paths):
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def analyze_paths(paths) -> list[Violation]:
    out = []
    for f in iter_py_files(paths):
        out.extend(analyze_file(f))
    return out
