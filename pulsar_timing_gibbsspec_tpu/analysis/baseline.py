"""Baseline ratchet shared by every analysis layer.

``jaxlint_baseline.json`` (repo root) records the accepted pre-existing
violations as per-file, per-rule counts::

    {"violations": {"pulsar_timing_gibbsspec_tpu/sampler/jax_backend.py":
                        {"R4": 7}}}

The CLI fails when any (file, rule) count *exceeds* its baselined value —
new debt is rejected.  ``tests/test_jaxlint.py`` asserts *equality*, so
fixing a baselined violation forces the baseline file down with it: the
count can only shrink.  Regenerate after fixes with
``python -m pulsar_timing_gibbsspec_tpu.analysis --write-baseline``.

The *justified* variant (racecheck, numcheck) adds one obligation:
every baselined ``(file, rule)`` pair must carry a one-line
justification under ``justifications`` (key ``"<file> [<rule>]"``);
missing/empty/TODO text fails the gate even when the ratchet itself is
satisfied — accepted debt must say *why* it is acceptable, not just
that it is old.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

BASELINE_NAME = "jaxlint_baseline.json"


def _rel(path: str, root: Path) -> str:
    p = Path(path)
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def baseline_counts(violations, root: Path) -> dict:
    """(file -> rule -> count) mapping for a violation list."""
    counts: Counter = Counter(
        (_rel(v.path, root), v.rule) for v in violations)
    out: dict = {}
    for (f, rule), n in sorted(counts.items()):
        out.setdefault(f, {})[rule] = n
    return out


def load_baseline(path) -> dict:
    p = Path(path)
    if not p.exists():
        return {}
    return json.loads(p.read_text()).get("violations", {})


def write_baseline(path, violations, root: Path) -> dict:
    data = {"violations": baseline_counts(violations, root)}
    Path(path).write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return data["violations"]


def compare_to_baseline(violations, baseline: dict, root: Path,
                        analyzed_files=None):
    """(new_violations, stale_entries).

    ``new_violations``: violations beyond each (file, rule) baseline
    count — these fail the build.  ``stale_entries``: baselined
    (file, rule) pairs whose current count dropped below the baseline —
    reported so the baseline gets ratcheted down.  ``analyzed_files``
    (repo-relative posix paths) limits staleness reporting to files that
    were actually analyzed, so linting a subset does not mistake
    out-of-scope baseline entries for fixed ones.
    """
    current = baseline_counts(violations, root)
    new = []
    for v in violations:
        f = _rel(v.path, root)
        if current.get(f, {}).get(v.rule, 0) > \
                baseline.get(f, {}).get(v.rule, 0):
            new.append(v)
    stale = []
    for f, rules in baseline.items():
        if analyzed_files is not None and f not in analyzed_files:
            continue
        for rule, n in rules.items():
            cur = current.get(f, {}).get(rule, 0)
            if cur < n:
                stale.append((f, rule, n, cur))
    return new, stale


# -- the justified baseline (racecheck, numcheck) -----------------------------

def justification_key(file: str, rule: str) -> str:
    return f"{file} [{rule}]"


def load_justified_baseline(path) -> dict:
    p = Path(path)
    if not p.exists():
        return {"violations": {}, "justifications": {}}
    data = json.loads(p.read_text())
    data.setdefault("violations", {})
    data.setdefault("justifications", {})
    return data


def check_justifications(data: dict) -> list:
    """Baselined (file, rule) pairs whose justification is missing,
    empty, or a TODO stub — each fails the gate."""
    bad = []
    just = data.get("justifications", {})
    for f, rules in sorted(data.get("violations", {}).items()):
        for rule in sorted(rules):
            text = str(just.get(justification_key(f, rule), "")).strip()
            if not text or text.upper().startswith("TODO"):
                bad.append((f, rule))
    return bad


def write_justified_baseline(path, findings, root: Path) -> dict:
    """Write counts; keep existing justifications, stub new pairs with
    a TODO the justification gate will reject until a human fills it."""
    old = load_justified_baseline(path)
    counts = baseline_counts(findings, root)
    just = {}
    for f, rules in counts.items():
        for rule in rules:
            key = justification_key(f, rule)
            just[key] = old["justifications"].get(
                key, "TODO: one-line justification for accepting this")
    data = {"violations": counts, "justifications": just}
    Path(path).write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return data
