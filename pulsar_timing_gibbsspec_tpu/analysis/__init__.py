"""Static analysis (:mod:`.jaxlint`) and runtime guards (:mod:`.guards`)
for JAX/TPU discipline.

``python -m pulsar_timing_gibbsspec_tpu.analysis <paths>`` runs the
linter; see :mod:`.jaxlint` for the rule catalogue and
``docs/LINTING.md`` for the workflow.

:mod:`.guards` is imported lazily (it needs jax); the linter itself is
pure-stdlib so it works in environments without jax installed.
"""

from .jaxlint import (RULES, Violation, analyze_file, analyze_paths,
                      analyze_source)
from .baseline import (baseline_counts, compare_to_baseline, load_baseline,
                       write_baseline)

__all__ = [
    "RULES", "Violation", "analyze_file", "analyze_paths", "analyze_source",
    "baseline_counts", "compare_to_baseline", "load_baseline",
    "write_baseline",
]
