"""CLI: ``python -m pulsar_timing_gibbsspec_tpu.analysis [paths...]``.

Exit status 0 when no violations beyond ``jaxlint_baseline.json``;
1 otherwise.  ``--write-baseline`` accepts the current state as the new
ratchet.  ``--ruff`` additionally runs the generic-Python linter (ruff,
configured in ``pyproject.toml``) over the same paths when it is
installed, so one command covers both layers.
"""

from __future__ import annotations

import argparse
import shutil
import subprocess
import sys
from pathlib import Path

from .baseline import (BASELINE_NAME, _rel, compare_to_baseline,
                       load_baseline, write_baseline)
from .jaxlint import analyze_paths, iter_py_files

_PKG_ROOT = Path(__file__).resolve().parents[1]   # the package dir
_REPO_ROOT = _PKG_ROOT.parent                      # holds the baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="AST-based JAX/TPU-discipline linter (rules R1-R7).")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the package)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON (default: <repo>/{BASELINE_NAME})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current violations as the new baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring the baseline")
    ap.add_argument("--ruff", action="store_true",
                    help="also run ruff (generic lint) when installed")
    args = ap.parse_args(argv)

    paths = [Path(p) for p in args.paths] or [_PKG_ROOT]
    root = _REPO_ROOT
    bl_path = Path(args.baseline) if args.baseline else root / BASELINE_NAME

    violations = analyze_paths(paths)

    if args.write_baseline:
        data = write_baseline(bl_path, violations, root)
        n = sum(sum(r.values()) for r in data.values())
        print(f"jaxlint: wrote baseline with {n} violation(s) "
              f"across {len(data)} file(s) -> {bl_path}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(bl_path)
    analyzed = {_rel(str(f), root) for f in iter_py_files(paths)}
    new, stale = compare_to_baseline(violations, baseline, root, analyzed)

    rc = 0
    if new:
        rc = 1
        print(f"jaxlint: {len(new)} non-baselined violation(s):",
              file=sys.stderr)
        for v in new:
            print(f"  {v}", file=sys.stderr)
    for f, rule, was, now in stale:
        print(f"jaxlint: baseline for {f} {rule} is stale "
              f"({was} -> {now}); run --write-baseline to ratchet down")
    if rc == 0:
        n_base = len(violations) - len(new)
        print(f"jaxlint: OK ({len(violations)} violation(s), "
              f"{n_base} baselined, 0 new)")

    if args.ruff:
        exe = shutil.which("ruff")
        if exe is None:
            print("jaxlint: ruff not installed; skipping generic lint",
                  file=sys.stderr)
        else:
            r = subprocess.run([exe, "check", *map(str, paths)], check=False)
            rc = rc or r.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
