"""N4 — the f32-steady / f64-exact body pairing, checked.

PR 3 established the convention: every mixed-precision steady sweep
body (``_sweep_body("mh")``) is paired with an f64 exact body
(``_sweep_body("exact")``) of identical shape signature, and the
chunk's iteration-level ``lax.cond`` refreshes through the exact body
every ``exact_every`` sweeps.  Until now nothing checked it — deleting
the pairing (or letting the signatures drift so the cond could no
longer select between them) would only surface as a distant KS
failure.

``check_pair`` proves, for a live driver:

1. a paired f64 exact body exists (building it must not raise),
2. both bodies trace to the *same* abstract output signature under
   identical abstract inputs (``jax.eval_shape`` — nothing executes),
3. the refresh cadence is declared in-contract and matches the
   driver's ``exact_every``.

``body_signature`` / ``compare_signatures`` are the unit surface the
mutation self-test drives with seeded defects.
"""

from __future__ import annotations


def body_signature(drv, bdraw: str):
    """Flat ``[(shape, dtype), ...]`` abstract output signature of one
    sweep body, traced with the driver's own carry/aux avals."""
    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    body = drv._sweep_body(bdraw)
    cm = drv.cm
    x = jax.ShapeDtypeStruct((cm.nx,), cm.dtype)
    b = jax.ShapeDtypeStruct((cm.P, cm.Bmax), cm.cdtype)
    u = jax.ShapeDtypeStruct(np.shape(cm.y), cm.dtype)
    # the chunk vmaps the body over chains with every aux leaf mapped
    # at axis 0 (_make_chunk: in_axes=(0, 0, 0, None)) — the
    # single-chain body sees aux with the chain axis stripped
    aux = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a)[1:], a.dtype),
        drv._aux())
    out = jax.eval_shape(lambda c, k, a, t: body(c, k, a, t),
                         (x, b, u), jr.key(0), aux, jnp.int32(0))
    leaves = jax.tree_util.tree_leaves(out)
    return [(tuple(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", "?"))) for leaf in leaves]


def compare_signatures(sig_mh, sig_exact) -> list:
    """Human-readable mismatches between two body signatures."""
    out = []
    if len(sig_mh) != len(sig_exact):
        out.append(
            f"body pair output arity differs: steady has {len(sig_mh)} "
            f"leaves, exact has {len(sig_exact)}")
        return out
    for i, (a, b) in enumerate(zip(sig_mh, sig_exact)):
        if a != b:
            out.append(
                f"body pair signature mismatch at leaf {i}: steady "
                f"{a[0]}/{a[1]} vs exact {b[0]}/{b[1]}")
    return out


def check_pair(drv, contract: dict) -> list:
    """``[(rule, message, file, line)]`` N4 findings for one driver."""
    out = []
    cadence = contract.get("exact_every")
    if drv is None:
        return out
    if cadence is None:
        out.append((
            "N4",
            "the contract declares no exact_every cadence — the f64 "
            "refresh cadence must be pinned in-contract, not implied "
            "by the driver default", None, None))
    elif int(cadence) != int(drv.exact_every):
        out.append((
            "N4",
            f"declared cadence exact_every={int(cadence)} does not "
            f"match the driver's exact_every={int(drv.exact_every)} — "
            "re-pin the contract or fix the driver", None, None))
    if getattr(drv.cm, "has_ke", False):
        # kernel ECORR runs the exact body only — no pair to check
        return out
    try:
        sig_exact = body_signature(drv, "exact")
    except Exception as e:      # noqa: BLE001 - the finding IS the report
        out.append((
            "N4",
            f"no registered f64 exact body pairs the f32 steady body "
            f"(building/tracing it failed: {type(e).__name__}: {e})",
            None, None))
        return out
    sig_mh = body_signature(drv, "mh")
    for msg in compare_signatures(sig_mh, sig_exact):
        out.append((
            "N4",
            msg + " — the chunk's lax.cond cannot alternate bodies "
            "whose signatures differ; the pairing contract is broken",
            None, None))
    return out
