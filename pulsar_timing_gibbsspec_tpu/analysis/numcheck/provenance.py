"""The precision-provenance dataflow machine.

A forward taint walk over the traced jaxpr (the same traversal scheme
as jaxprcheck's key-lineage machine): every ``convert_element_type``
f64→f32 creates a :class:`Narrow` taint that propagates through all
floating-point dataflow — across pjit/scan/cond/while boundaries via
the tail-aligned invar mapping, loop bodies iterated to a fixed point
(taint sets only grow, so the iteration is monotone and converges) —
and is recorded as a :class:`SinkHit` when it reaches an accumulation
sink (reduce_sum-class over enough elements, a Cholesky/solve, or a
dot_general contraction).

The walk also collects the raw material of the N2/N3 rules: every
reassociation-sensitive reduction (including scan-carried fp
accumulations, found structurally: an fp carry whose output is an
add-chain over its input) and every dot_general with its precision
parameter and input-taint status.
"""

from __future__ import annotations

import dataclasses
import os

from ..jaxprcheck.walk import source_of, subjaxprs

#: reduce-class primitives that are reassociation-sensitive over fp
_REDUCE_SINKS = {"reduce_sum", "reduce_prod", "cumsum", "cumprod"}

#: factorization / solve sinks — error there multiplies through the
#: whole conditional draw, so any tainted input counts regardless of size
_FACTOR_SINKS = {"cholesky", "triangular_solve"}

#: additive primitives an accumulation chain is made of
_ADDITIVE = {"add", "add_any"}

#: movement primitives an accumulation chain may pass through unchanged
_CHAIN_PASS = {
    "convert_element_type", "reshape", "broadcast_in_dim", "transpose",
    "squeeze", "slice", "dynamic_slice", "dynamic_update_slice",
    "select_n", "copy", "expand_dims", "concatenate", "rev",
}

_FP = {"float16", "bfloat16", "float32", "float64"}


def _is_var(v) -> bool:
    import jax

    return isinstance(v, jax.core.Var)


def _dtype(v):
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


def _is_fp(v) -> bool:
    return _dtype(v) in _FP


@dataclasses.dataclass(frozen=True)
class Site:
    """A source location a finding anchors to."""

    file: str
    line: int
    fn: str

    @property
    def block(self) -> str:
        return f"{os.path.basename(self.file)}:{self.fn}"

    def __str__(self):
        return f"{self.fn} at {os.path.basename(self.file)}:{self.line}"


@dataclasses.dataclass(frozen=True)
class Narrow:
    """One f64→f32 ``convert_element_type`` site (a taint source)."""

    site: Site
    islanded: bool          # inside a declared mixed-precision island


@dataclasses.dataclass(frozen=True)
class Reduction:
    """A reassociation-sensitive fp reduction."""

    site: Site
    kind: str               # reduce_sum / cumsum / ... / scan_carry
    dtype: str
    length: int             # elements folded into one result


@dataclasses.dataclass(frozen=True)
class Dot:
    """A dot_general with its precision and input-taint status."""

    site: Site
    out_dtype: str
    highest: bool
    k: int                  # contraction size
    tainted: bool           # any input was ever f64 (islanded or not)


@dataclasses.dataclass(frozen=True)
class SinkHit:
    """A narrowed value reaching an accumulation/factorization sink."""

    narrow: Narrow
    sink_kind: str
    sink: Site


@dataclasses.dataclass
class ProvReport:
    """Everything the rules need, plus the census that pins topology."""

    narrows: list = dataclasses.field(default_factory=list)
    reductions: list = dataclasses.field(default_factory=list)
    dots: list = dataclasses.field(default_factory=list)
    sink_hits: list = dataclasses.field(default_factory=list)

    def narrow_census(self) -> dict:
        """``{"file.py:fn": count}`` over every f64→f32 narrow — the
        committed fingerprint of the program's precision topology."""
        out: dict = {}
        for n in self.narrows:
            out[n.site.block] = out.get(n.site.block, 0) + 1
        return dict(sorted(out.items()))


def _in_island(site: Site, islands) -> bool:
    from ..jaxprcheck.dtypes import _in_island as impl

    return impl(site.fn, site.file, islands)


def _is_highest(precision) -> bool:
    from ..jaxprcheck.dtypes import _is_highest as impl

    return impl(precision)


def _reduce_length(eqn) -> int:
    axes = eqn.params.get("axes", ())
    shape = getattr(eqn.invars[0].aval, "shape", ())
    n = 1
    for a in axes:
        n *= int(shape[a])
    if eqn.primitive.name in ("cumsum", "cumprod"):
        ax = eqn.params.get("axis", 0)
        n = int(shape[ax]) if shape else 1
    return n


def _dot_k(eqn) -> int:
    (lc, _rc), _b = eqn.params["dimension_numbers"]
    ls = getattr(eqn.invars[0].aval, "shape", ())
    k = 1
    for i in lc:
        k *= int(ls[i])
    return k


class _Walker:
    """Forward taint propagation; ``state``: var -> frozenset[Narrow]."""

    def __init__(self, report: ProvReport, islands, min_reduce: int):
        self.r = report
        self.islands = set(islands)
        self.min_reduce = int(min_reduce)
        self._mute = 0          # >0 during loop fixed-point pre-passes
        self._seen_hits = set()

    # -- recording (suppressed during fixed-point pre-passes) -------------
    def _rec_narrow(self, n: Narrow):
        if not self._mute:
            self.r.narrows.append(n)

    def _rec_reduce(self, red: Reduction):
        if not self._mute:
            self.r.reductions.append(red)

    def _rec_dot(self, d: Dot):
        if not self._mute:
            self.r.dots.append(d)

    def _rec_hits(self, taint, kind, sink: Site):
        if self._mute:
            return
        for nv in taint:
            key = (nv.site, kind, sink.block)
            if key not in self._seen_hits:
                self._seen_hits.add(key)
                self.r.sink_hits.append(SinkHit(nv, kind, sink))

    # -- the machine -------------------------------------------------------
    def walk(self, jaxpr, state):
        for eqn in jaxpr.eqns:
            self._eqn(eqn, state)
        return [state.get(v, frozenset()) if _is_var(v) else frozenset()
                for v in jaxpr.outvars]

    def _taint_in(self, eqn, state):
        t = frozenset()
        for v in eqn.invars:
            if _is_var(v) and v in state:
                t = t | state[v]
        return t

    def _propagate(self, eqn, state, taint):
        if not taint:
            return
        for o in eqn.outvars:
            if _is_fp(o):
                state[o] = state.get(o, frozenset()) | taint

    def _eqn(self, eqn, state):
        name = eqn.primitive.name
        subs = subjaxprs(eqn)
        if subs:
            self._call(eqn, subs, state)
            return
        taint = self._taint_in(eqn, state)
        if name == "convert_element_type":
            if _dtype(eqn.invars[0]) == "float64" and \
                    _dtype(eqn.outvars[0]) == "float32":
                site = Site(*source_of(eqn))
                nv = Narrow(site, _in_island(site, self.islands))
                self._rec_narrow(nv)
                taint = taint | {nv}
        elif name in _REDUCE_SINKS:
            if _is_fp(eqn.invars[0]):
                n = _reduce_length(eqn)
                if n >= self.min_reduce:
                    site = Site(*source_of(eqn))
                    self._rec_reduce(Reduction(
                        site, name, _dtype(eqn.invars[0]), n))
                    self._rec_hits(taint, name, site)
        elif name == "dot_general":
            site = Site(*source_of(eqn))
            k = _dot_k(eqn)
            self._rec_dot(Dot(site, _dtype(eqn.outvars[0]),
                              _is_highest(eqn.params.get("precision")),
                              k, bool(taint)))
            if k >= self.min_reduce:
                self._rec_hits(taint, name, site)
        elif name in _FACTOR_SINKS:
            site = Site(*source_of(eqn))
            self._rec_hits(taint, name, site)
        self._propagate(eqn, state, taint)

    # -- call boundaries ---------------------------------------------------
    def _map_in(self, eqn, sub, state):
        """Outer args onto the body's trailing invars: pjit is exactly
        1:1; scan's invars = consts + carry + xs match the body; cond
        prepends only the predicate; while prepends consts the body
        never sees — every convention here tail-aligns."""
        sub_state = {}
        args = list(eqn.invars)
        for bv, ov in zip(reversed(sub.invars), reversed(args)):
            if _is_var(ov) and ov in state:
                sub_state[bv] = state[ov]
        return sub_state

    def _map_out(self, eqn, state, out_states):
        """Body out-states back onto the outer outvars, 1:1 from the
        front; also conservatively forward outer input taint through
        the call result (a tainted operand feeding any body path may
        surface in any output)."""
        for o, st in zip(eqn.outvars, out_states or []):
            if st and _is_fp(o):
                state[o] = state.get(o, frozenset()) | st
        self._propagate(eqn, state, self._taint_in(eqn, state))

    def _call(self, eqn, subs, state):
        name = eqn.primitive.name
        if name == "scan":
            self._scan(eqn, state)
            return
        if name == "while":
            self._while(eqn, state)
            return
        # pjit / cond / custom_* — walk each body once; cond branches
        # are alternatives, so out-states union per position
        out_states = None
        for sub in subs:
            outs = self.walk(sub, self._map_in(eqn, sub, state))
            if out_states is None:
                out_states = outs
            else:
                out_states = [a | b for a, b in zip(out_states, outs)]
        self._map_out(eqn, state, out_states)

    def _scan(self, eqn, state):
        closed = eqn.params["jaxpr"]
        sub = getattr(closed, "jaxpr", closed)
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        sub_state = self._map_in(eqn, sub, state)
        outs = self._fixpoint(sub, sub_state,
                              sub.invars[nc:nc + ncar], range(ncar))
        self._scan_carry_accums(eqn)
        self._map_out(eqn, state, outs)

    def _while(self, eqn, state):
        body = eqn.params["body_jaxpr"]
        body = getattr(body, "jaxpr", body)
        cond = eqn.params["cond_jaxpr"]
        cond = getattr(cond, "jaxpr", cond)
        ncar = len(body.outvars)
        sub_state = self._map_in(eqn, body, state)
        outs = self._fixpoint(body, sub_state,
                              body.invars[len(body.invars) - ncar:],
                              range(ncar))
        # the predicate jaxpr only decides the trip count — walk it for
        # event recording, discard its out-state
        self.walk(cond, self._map_in(eqn, cond, state))
        self._map_out(eqn, state, outs)

    def _fixpoint(self, sub, sub_state, carry_in, carry_out_ix):
        """Iterate a loop body to a taint fixed point (recording muted),
        then one final recorded pass.  Taint sets only grow, so the
        iteration is monotone; the cap is a safety net."""
        self._mute += 1
        try:
            for _ in range(4):
                outs = self.walk(sub, dict(sub_state))
                grew = False
                for bv, oi in zip(carry_in, carry_out_ix):
                    cur = sub_state.get(bv, frozenset())
                    new = cur | outs[oi]
                    if new != cur:
                        sub_state[bv] = new
                        grew = True
                if not grew:
                    break
        finally:
            self._mute -= 1
        return self.walk(sub, dict(sub_state))

    def _scan_carry_accums(self, eqn):
        """Structural N2 source: an fp scan carry whose output is an
        add-chain over its own input is a carried accumulation — its
        effective summation length is the scan trip count."""
        if self._mute:
            return
        closed = eqn.params.get("jaxpr")
        body = getattr(closed, "jaxpr", closed)
        if body is None:
            return
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        length = int(eqn.params.get("length", 0) or 0)
        if length < self.min_reduce:
            return
        eqns, resolve = _flatten_pjit(body)
        producer = {}
        for e in eqns:
            for o in e.outvars:
                producer[resolve(o)] = e
        for ci, co in zip(body.invars[nc:nc + ncar], body.outvars[:ncar]):
            if not _is_fp(ci):
                continue
            site = self._find_add_chain(resolve(co), resolve(ci),
                                        producer, resolve)
            if site is not None:
                self._rec_reduce(Reduction(site, "scan_carry",
                                           _dtype(ci), length))

    def _find_add_chain(self, start, target, producer, resolve):
        """Backward reachability ``start -> target`` through additive and
        movement primitives; returns the Site of an add on the path."""
        stack = [(start, None)]
        seen = set()
        while stack:
            v, add_site = stack.pop()
            if v is target and add_site is not None:
                return add_site
            if id(v) in seen:
                continue
            seen.add(id(v))
            e = producer.get(v)
            if e is None:
                continue
            name = e.primitive.name
            if name in _ADDITIVE:
                site = Site(*source_of(e))
                for iv in e.invars:
                    if _is_var(iv):
                        stack.append((resolve(iv), site))
            elif name in _CHAIN_PASS:
                for iv in e.invars:
                    if _is_var(iv):
                        stack.append((resolve(iv), add_site))
        return None


def _flatten_pjit(jaxpr):
    """``(leaf_eqns, resolve)`` with pjit bodies inlined: traversals see
    through nested jit boundaries by resolving a pjit outvar to the
    body outvar that produced it and a body invar back to the outer
    argument feeding it."""
    eqns, alias = [], {}

    def go(j):
        for e in j.eqns:
            if e.primitive.name == "pjit":
                sub = e.params["jaxpr"]
                sub = getattr(sub, "jaxpr", sub)
                for bv, ov in zip(reversed(sub.invars),
                                  reversed(list(e.invars))):
                    if _is_var(bv) and _is_var(ov):
                        alias[bv] = ov
                for o, so in zip(e.outvars, sub.outvars):
                    if _is_var(o) and _is_var(so):
                        alias[o] = so
                go(sub)
            else:
                eqns.append(e)

    go(jaxpr)

    def resolve(v):
        while v in alias:
            v = alias[v]
        return v

    return eqns, resolve


def analyze_provenance(closed_jaxpr, islands=(), min_reduce=8) -> ProvReport:
    """Run the taint machine over a whole traced program."""
    report = ProvReport()
    walker = _Walker(report, islands, min_reduce)
    walker.walk(closed_jaxpr.jaxpr, {})
    return report
