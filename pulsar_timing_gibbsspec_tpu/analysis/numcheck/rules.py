"""N1–N3 rule evaluation over a :class:`.provenance.ProvReport`.

Each rule reads the contract's declarations:

- ``islands``: mixed-precision islands (jaxprcheck island syntax — a
  function name, a file basename, or ``file.py:fn``) where f64→f32
  narrowing is *by design* (the steady mixed path, the two-float
  kernels).  N1 only fires for narrows outside every island.
- ``declared_orders``: ``[{"fn": <island-spec>, "order": <text>}]`` —
  the pinned summation-order notes N2 matches reductions against.  An
  entry with an empty ``order`` does not count: the point is the
  committed prose, not the key.
- ``narrow_census``: exact ``{"file.py:fn": count}`` pin of every
  narrow site — any new ``.astype`` anywhere moves the census and
  fails the ``census`` rule even when N1's dataflow cannot see a sink.

Findings are ``(rule, message, file, line)`` tuples; the runner
attaches contract paths and applies source pragmas.
"""

from __future__ import annotations

import json

from ..jaxprcheck.dtypes import _in_island
from .provenance import ProvReport

RULES = {
    "N1": "silent-downcast-into-accumulation",
    "N2": "unpinned-reassociation",
    "N3": "tf32-hazard",
    "N4": "missing-exact-body",
    "N5": "error-ledger-drift",
}


def check_rules(rep: ProvReport, contract: dict) -> list:
    """``[(rule, message, src_file, src_line)]`` for N1/N2/N3 plus the
    narrow-census topology pin."""
    out = []
    islands = set(contract.get("islands", ()))
    declared = [d for d in contract.get("declared_orders", ())
                if str(d.get("order", "")).strip()]

    # N1 — a non-islanded narrow reaching an accumulation sink
    for hit in rep.sink_hits:
        if hit.narrow.islanded:
            continue
        out.append((
            "N1",
            f"silent f64→f32 downcast at {hit.narrow.site} flows into "
            f"a {hit.sink_kind} sink at {hit.sink} outside every "
            f"declared mixed-precision island (islands: "
            f"{sorted(islands)}) — widen, or declare the island and "
            "justify it",
            hit.narrow.site.file, hit.narrow.site.line))

    # N2 — reassociation-sensitive reductions without a pinned order
    for red in rep.reductions:
        if any(_in_island(red.site.fn, red.site.file, {d["fn"]})
               for d in declared):
            continue
        out.append((
            "N2",
            f"reassociation-sensitive {red.kind} over {red.length} "
            f"{red.dtype} element(s) at {red.site} has no pinned "
            "summation order — add a declared_orders entry stating the "
            "order (the PR 8 segmented-Gram note, as contract)",
            red.site.file, red.site.line))

    # N3 — default-precision f32 dots consuming once-f64 data
    for d in rep.dots:
        if d.out_dtype != "float32" or d.highest or not d.tainted:
            continue
        out.append((
            "N3",
            f"f32 dot_general (k={d.k}) at {d.site} runs at default "
            "precision on data that was f64 upstream — on GPU the MXU "
            "lowers this to tf32 (10-bit mantissa) silently; pass "
            'precision="highest" or justify',
            d.site.file, d.site.line))

    # census — the committed precision-topology fingerprint
    want = contract.get("narrow_census")
    if want is not None:
        got = rep.narrow_census()
        if json.dumps(got, sort_keys=True) != \
                json.dumps(dict(want), sort_keys=True):
            out.append((
                "census",
                f"narrow-site census drift: measured {got}, contract "
                f"pins {dict(sorted(want.items()))} — every new/removed "
                "f64→f32 cast must re-pin the topology",
                None, None))
    return out
