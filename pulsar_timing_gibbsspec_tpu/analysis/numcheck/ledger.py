"""N5 — the first-order error ledger.

Per source block (``file.py:fn``, the attribution ``source_of`` gives
every leaf equation), accumulate scan-scaled op counts by fp dtype, the
longest accumulation chain (dot contraction size, reduce length, or
Cholesky order — the ``n`` of the classic ``n·eps`` forward-error
bound), and the cost model's FLOP attribution.  The block's
``ulp_bound_rel`` is ``max_chain · eps(dtype)`` — the standard
first-order relative rounding bound for a length-``n`` recursive
sum/contraction (Higham, *Accuracy and Stability of Numerical
Algorithms*, §4.2, dropping the O(eps²) terms).

The ledger is machine-readable JSON: a mixed-precision PR that moves a
block's chain length or dtype *must* re-pin the contract's
``ledger.max_ulp_rel`` instead of asserting safety in prose — that is
the whole point of N5.
"""

from __future__ import annotations

import os

import numpy as np

from ..jaxprcheck.cost import (_ELEMENTWISE, _REDUCTIONS,
                               _dot_general_flops, _linalg_flops)
from ..jaxprcheck.walk import source_of, subjaxprs
from .provenance import _FP, _dot_k, _reduce_length

_EPS = {"float16": float(np.finfo(np.float16).eps),
        "bfloat16": 2.0 ** -7,
        "float32": float(np.finfo(np.float32).eps),
        "float64": float(np.finfo(np.float64).eps)}

_FACTOR = {"cholesky", "triangular_solve"}


def _dtype(v):
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


def _nelems(v) -> int:
    n = 1
    for s in getattr(getattr(v, "aval", None), "shape", ()) or ():
        n *= int(s)
    return n


class _Block:
    __slots__ = ("flops", "dot_flops", "ops", "max_chain")

    def __init__(self):
        self.flops = 0.0
        self.dot_flops = 0.0
        self.ops = {}
        self.max_chain = {}

    def charge(self, dtype, elems, flops, chain, scale, is_dot=False):
        self.flops += flops * scale
        if is_dot:
            self.dot_flops += flops * scale
        if dtype in _FP:
            self.ops[dtype] = self.ops.get(dtype, 0.0) + elems * scale
            if chain > self.max_chain.get(dtype, 0):
                self.max_chain[dtype] = int(chain)


def _walk(jaxpr, blocks, scale):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            length = float(eqn.params.get("length", 1) or 1)
            sub = eqn.params["jaxpr"]
            _walk(getattr(sub, "jaxpr", sub), blocks, scale * length)
            continue
        subs = subjaxprs(eqn)
        if subs:
            for sub in subs:
                _walk(sub, blocks, scale)
            continue
        dt = _dtype(eqn.outvars[0]) if eqn.outvars else None
        f, ln, fn = source_of(eqn)
        key = f"{os.path.basename(f)}:{fn}"
        blk = blocks.get(key)
        if blk is None:
            blk = blocks[key] = _Block()
        if name == "dot_general":
            blk.charge(dt, sum(_nelems(o) for o in eqn.outvars),
                       _dot_general_flops(eqn), _dot_k(eqn), scale,
                       is_dot=True)
        elif name in _FACTOR:
            shape = getattr(eqn.invars[0].aval, "shape", ())
            n = int(shape[-1]) if shape else 1
            blk.charge(dt, sum(_nelems(o) for o in eqn.outvars),
                       _linalg_flops(name, eqn), n, scale)
        elif name in _REDUCTIONS:
            dt_in = _dtype(eqn.invars[0])
            blk.charge(dt_in, sum(_nelems(v) for v in eqn.invars),
                       float(sum(_nelems(v) for v in eqn.invars)),
                       _reduce_length(eqn), scale)
        elif name in _ELEMENTWISE:
            n = sum(_nelems(o) for o in eqn.outvars)
            blk.charge(dt, n, float(n), 1, scale)


def error_ledger(closed_jaxpr) -> dict:
    """The full machine-readable ledger for one traced program."""
    blocks: dict = {}
    _walk(closed_jaxpr.jaxpr, blocks, 1.0)
    out_blocks = []
    max_ulp: dict = {}
    for key in sorted(blocks):
        blk = blocks[key]
        if not blk.ops:
            continue
        ulp = {d: blk.max_chain.get(d, 1) * _EPS[d] for d in blk.ops}
        for d, v in ulp.items():
            if v > max_ulp.get(d, 0.0):
                max_ulp[d] = v
        out_blocks.append({
            "block": key,
            "flops": blk.flops,
            "dot_flops": blk.dot_flops,
            "ops": {d: blk.ops[d] for d in sorted(blk.ops)},
            "max_chain": {d: blk.max_chain.get(d, 1)
                          for d in sorted(blk.ops)},
            "ulp_bound_rel": {d: ulp[d] for d in sorted(ulp)},
        })
    return {"blocks": out_blocks,
            "max_ulp_rel": {d: max_ulp[d] for d in sorted(max_ulp)}}


def check_ledger(ledger: dict, contract: dict) -> list:
    """``[(rule, message, file, line)]`` — N5 drift of the program-wide
    per-dtype ULP bound beyond the contract pin."""
    spec = contract.get("ledger")
    if not spec:
        return []
    out = []
    tol = float(spec.get("tolerance_rel", 0.25))
    want = spec.get("max_ulp_rel", {})
    got = ledger.get("max_ulp_rel", {})
    for d in sorted(set(want) | set(got)):
        w, g = want.get(d), got.get(d)
        if w is None:
            out.append((
                "N5",
                f"error ledger grew a {d} accumulation chain "
                f"(ulp_bound_rel={g:.3g}) the contract does not pin — "
                "re-pin ledger.max_ulp_rel", None, None))
        elif g is None:
            out.append((
                "N5",
                f"contract pins a {d} ulp bound ({w:.3g}) but the "
                f"program no longer has {d} accumulations — ratchet "
                "the pin out", None, None))
        elif abs(g - w) > tol * w:
            out.append((
                "N5",
                f"error-ledger drift on {d}: measured max ulp_bound_rel "
                f"{g:.6g}, contract pins {w:.6g} (±{tol:.0%}) — a chain "
                "length or dtype moved; re-pin the ledger deliberately",
                None, None))
    return out
