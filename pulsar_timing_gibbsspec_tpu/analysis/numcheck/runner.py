"""Contract discovery, per-contract dispatch, pragma suppression.

A numcheck contract is a committed JSON file under
``<repo>/contracts/`` tagged ``"tool": "numcheck"``:

.. code-block:: json

    {
      "name": "numerics_crn",
      "tool": "numcheck",
      "fast": true,
      "entry": {"entry": "chunk", "n_psr": 3, "ntoa": 40},
      "exact_every": 16,
      "islands": ["jax_backend.py:parallel_cov_mh_scan", "linalg.py"],
      "declared_orders": [{"fn": "jax_backend.py:ll_rel",
                           "order": "single fused reduce, fixed layout"}],
      "narrow_census": {"jax_backend.py:ll_rel": 4},
      "ledger": {"max_ulp_rel": {"float32": 1.4e-5}},
      "min_reduce_elems": 8
    }

The ``tool`` tag keeps jaxprcheck's discovery from picking these up
(it skips foreign-tool files) while its entry-coverage check still
counts them — a numcheck contract pinning an entry builder covers it.

Findings carry the contract path (the jaxprcheck Violation surface, so
the shared ratchet applies) plus, where known, the *source* location
of the offending equation — a trailing ``# numcheck: disable=N3``
comment on that source line suppresses the finding, same pragma
semantics as racecheck.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path

from ..jaxprcheck.entries import resolve_entry
from ..jaxprcheck.runner import Violation, load_contract
from ..jaxprcheck.walk import trace_jaxpr
from .ledger import check_ledger, error_ledger
from .pairs import check_pair
from .provenance import analyze_provenance
from .rules import check_rules

_REPO_ROOT = Path(__file__).resolve().parents[3]
CONTRACT_DIR = _REPO_ROOT / "contracts"
BASELINE_NAME = "numcheck_baseline.json"

_PRAGMA_RE = re.compile(r"#\s*numcheck:\s*disable=([A-Za-z0-9,\s]+)")


def pragma_rules(line: str) -> set:
    """Rules a trailing ``# numcheck: disable=...`` comment suppresses."""
    m = _PRAGMA_RE.search(line)
    if not m:
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def _suppressed(rule, src_file, src_line) -> bool:
    if not src_file or not src_line:
        return False
    try:
        with open(src_file, encoding="utf-8") as fh:
            for i, text in enumerate(fh, 1):
                if i == int(src_line):
                    disabled = pragma_rules(text)
                    return rule.upper() in disabled or "ALL" in disabled
    except OSError:
        return False
    return False


def discover_contracts(root=None, fast_only=False) -> list:
    root = Path(root) if root is not None else CONTRACT_DIR
    out = []
    for p in sorted(root.glob("*.json")):
        c = load_contract(p)
        if c.get("tool") != "numcheck":
            continue
        if fast_only and not c.get("fast", False):
            continue
        out.append(c)
    return out


def _relpath(path) -> str:
    try:
        return os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        return str(path)


def run_contract(contract: dict):
    """``(violations, facts)`` for one loaded contract: trace the
    entry once, run provenance + rules, the N4 pairing proof, and the
    N5 ledger pin."""
    path = _relpath(contract.get("_path", contract.get("name", "?")))
    fn, args, extras = resolve_entry(contract["entry"])
    closed = trace_jaxpr(fn, args)
    rep = analyze_provenance(
        closed, islands=contract.get("islands", ()),
        min_reduce=contract.get("min_reduce_elems", 8))
    led = error_ledger(closed)
    findings = check_rules(rep, contract)
    findings += check_pair(extras.get("driver"), contract)
    findings += check_ledger(led, contract)
    violations = [
        Violation(path, rule, msg)
        for rule, msg, src_file, src_line in findings
        if not _suppressed(rule, src_file, src_line)]
    facts = {"name": contract.get("name"),
             "n_eqns": len(closed.jaxpr.eqns),
             "narrow_census": rep.narrow_census(),
             "n_reductions": len(rep.reductions),
             "n_dots": len(rep.dots),
             "n_sink_hits": len(rep.sink_hits),
             "ledger": led}
    return violations, facts


def run_contracts(contracts):
    """``(all_violations, {name: facts})``; a contract that errors out
    becomes an ``error`` violation rather than an exception, so one
    broken contract cannot mask the others."""
    all_v, all_f = [], {}
    for c in contracts:
        path = _relpath(c.get("_path", c.get("name", "?")))
        try:
            v, f = run_contract(c)
        except Exception as e:          # noqa: BLE001 - report, don't die
            all_v.append(Violation(path, "error",
                                   f"{type(e).__name__}: {e}"))
            continue
        all_v.extend(v)
        all_f[c.get("name", path)] = f
    return all_v, all_f


def analyze_traced(closed_jaxpr, contract: dict | None = None):
    """Unit surface for tests: provenance + rules over an already
    traced program, contract declarations optional."""
    contract = dict(contract or {})
    rep = analyze_provenance(
        closed_jaxpr, islands=contract.get("islands", ()),
        min_reduce=contract.get("min_reduce_elems", 8))
    return check_rules(rep, contract), rep


def load_json(path) -> dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)
