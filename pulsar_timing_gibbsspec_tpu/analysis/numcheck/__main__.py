"""CLI: audit the numcheck contracts, ratchet against the baseline.

Usage::

    python -m pulsar_timing_gibbsspec_tpu.analysis.numcheck [opts]

    --fast             only contracts marked "fast": true (the ci_lint
                       subset)
    --contracts DIR    contract directory (default <repo>/contracts)
    --json             machine-readable facts (incl. the N5 error
                       ledger) + violations on stdout
    --ledger PATH      also write the per-contract error ledgers to a
                       JSON file
    --baseline PATH    ratchet file (default <repo>/numcheck_baseline.json)
    --no-baseline      report every finding, ignore the ratchet
    --write-baseline   accept current findings as the new baseline
                       (existing justifications kept; new pairs get a
                       TODO stub the gate rejects until filled in)

Exit status 1 when findings beyond the baseline exist or any baselined
pair lacks a one-line justification.  Everything is host-side tracing
on the CPU backend — nothing executes on a device.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[3]


def _bootstrap_cpu():
    """Force the CPU backend with enough host devices for the sharded
    entries, before any backend initializes."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="numcheck",
        description="static precision-flow / reassociation / exact-body "
                    "auditor over the traced entry builders (CPU "
                    "tracing only, no device execution)")
    ap.add_argument("--fast", action="store_true",
                    help="only contracts marked fast")
    ap.add_argument("--contracts", default=None, metavar="DIR")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="write the per-contract error ledgers here")
    ap.add_argument("--baseline",
                    default=str(_REPO_ROOT / "numcheck_baseline.json"))
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    args = ap.parse_args(argv)

    _bootstrap_cpu()

    from ..baseline import (check_justifications, compare_to_baseline,
                            load_justified_baseline,
                            write_justified_baseline)
    from .runner import discover_contracts, run_contracts

    contracts = discover_contracts(args.contracts, fast_only=args.fast)
    if not contracts:
        print("numcheck: no contracts found", file=sys.stderr)
        return 2
    violations, facts = run_contracts(contracts)

    if args.ledger:
        ledgers = {name: f.get("ledger") for name, f in facts.items()}
        out = Path(args.ledger)
        if out.is_dir():
            out = out / "numcheck_ledger.json"
        out.write_text(
            json.dumps(ledgers, indent=2, sort_keys=True) + "\n")

    if args.write_baseline:
        data = write_justified_baseline(args.baseline, violations,
                                        _REPO_ROOT)
        todo = check_justifications(data)
        print(f"numcheck: baseline written to {args.baseline} "
              f"({len(violations)} finding(s), {len(todo)} "
              "justification(s) to fill in)")
        return 0

    if args.no_baseline:
        new, stale, missing = list(violations), [], []
    else:
        data = load_justified_baseline(args.baseline)
        new, stale = compare_to_baseline(violations, data["violations"],
                                         _REPO_ROOT)
        missing = check_justifications(data)

    if args.as_json:
        print(json.dumps(
            {"contracts": [c.get("name") for c in contracts],
             "facts": facts,
             "violations": [
                 {"path": v.path, "rule": v.rule, "message": v.message}
                 for v in violations],
             "new": len(new),
             "missing_justifications": [list(m) for m in missing]},
            indent=2, sort_keys=True))
    else:
        for v in new:
            print(str(v))
        for f, rule, base, cur in stale:
            print(f"stale baseline entry: {f} [{rule}] baseline {base} "
                  f"> current {cur}; ratchet the baseline down")
        for f, rule in missing:
            print(f"baselined without justification: {f} [{rule}] — add "
                  f"a one-line reason under justifications in "
                  f"{Path(args.baseline).name}")
        ok = "OK" if not new and not missing else "FAIL"
        print(f"numcheck: {len(contracts)} contract(s), "
              f"{len(violations)} finding(s), {len(new)} new — {ok}")
    return 1 if (new or missing) else 0


if __name__ == "__main__":
    sys.exit(main())
